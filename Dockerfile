# Build-once runtime image — the reference's L2 contract rebuilt for TPU.
#
# The reference bakes its whole driven stack into a Singularity image and
# gates the build on a sanity run
# (/root/reference/install-scripts/tf-hvd-gcc-ompi-ucx-mlnx.def:18-55,
# build-container.sh:23-30: build once, `singularity run` sanity-check,
# `exec` everywhere).  This Dockerfile is the same contract on the TPU-VM
# container runtime: the pinned JAX stack + this framework + the compiled
# native data plane baked in, with the sanity report as both build gate
# and default entrypoint.
#
#   build:   docker build -t tpu-hc-bench .
#   sanity:  docker run --rm tpu-hc-bench            (the `singularity run` analog)
#   bench:   docker run --rm --privileged tpu-hc-bench \
#              python -m tpu_hc_bench 1 0 128 ib --model=resnet50
#
# On a TPU-VM, pass the TPU through with `--privileged` (vfio/libtpu device
# nodes) exactly as the reference's hybrid-MPI model shares the host's IB
# devices into the container (SURVEY.md §2b #26).
FROM python:3.12-slim

# native toolchain for the C++ data plane (TFRecord scanner + libjpeg
# decoder, tpu_hc_bench/native) — g++ plays the reference's GCC-8.2 role,
# from the distro instead of an 80-minute source build
RUN apt-get update && apt-get install -y --no-install-recommends \
        g++ make libjpeg-dev \
    && rm -rf /var/lib/apt/lists/*

WORKDIR /opt/tpu-hc-bench

# the pinned stack — scripts/setup/stack-pins.txt is the ONE source of
# truth shared with install_jax_stack.sh (host) and build-venv-image.sh,
# so the image can never drift from the host stack (the reference's
# %post-reruns-setup.sh double-build serves exactly this purpose);
# [tpu] extras pull libtpu for real hardware — harmless on CPU-only hosts
COPY pyproject.toml scripts/setup/stack-pins.txt ./
RUN PIN_JAX="$(grep -oP '^jax==\K.*' stack-pins.txt)" \
    && pip install --no-cache-dir "jax[tpu]==${PIN_JAX}" -r stack-pins.txt \
        -f https://storage.googleapis.com/jax-releases/libtpu_releases.html

COPY tpu_hc_bench/ tpu_hc_bench/
COPY scripts/ scripts/
COPY bench.py .
RUN pip install --no-cache-dir --no-deps .

# pre-build the native libraries so every container start is identical
# (the host-container ABI-symmetry lesson of the reference's dual MPI
# install, without the dual install)
RUN make -C tpu_hc_bench/native

# build-time sanity gate: a broken stack fails the image build, exactly as
# build-container.sh:29-30 runs the image before declaring success
RUN JAX_PLATFORMS=cpu python -m tpu_hc_bench.utils.sanity

# the `singularity run` analog: default command prints the stack report
CMD ["python", "-m", "tpu_hc_bench.utils.sanity"]
