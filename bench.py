"""Headline benchmark: ResNet-50 synthetic images/sec/chip on real TPU.

Runs the reference measurement protocol (50 warmup + 100 timed batches,
``run-tf-sing-ucx-openmpi.sh:32-35``) on ResNet-50 with synthetic data —
the exact experiment of BASELINE.json config 1 — on every available chip,
and prints ONE JSON line.

``vs_baseline``: the reference publishes no numbers (BASELINE.md), so the
comparison point is the widely reported tf_cnn_benchmarks ResNet-50 fp32
MKL throughput of a 2-socket Xeon-Platinum HC-class node, ~85 images/sec
per node — i.e. vs_baseline is images/sec-per-chip over images/sec-per-
reference-node, worker-unit vs worker-unit.
"""

from __future__ import annotations

import json
import os
import sys

REFERENCE_NODE_IMAGES_PER_SEC = 85.0


def _maybe_regress(payload: dict) -> int:
    """``BENCH_REGRESS=1``: gate the exit code on the noise-aware
    regression check (``obs.regress``) after the JSON line is printed —
    the fresh record vs the median/MAD of the matching-fingerprint
    history (``BENCH_HISTORY`` sources, default ``BENCH_*.json`` +
    ``artifacts/`` in the cwd).  Opt-in: a plain bench run never reads
    history."""
    if os.environ.get("BENCH_REGRESS") != "1":
        return 0
    from tpu_hc_bench.obs import regress as regress_mod

    specs = None
    hist = os.environ.get("BENCH_HISTORY")
    if hist:
        specs = [s for s in hist.split(os.pathsep) if s]
    return regress_mod.run_regress(payload, specs, out=sys.stderr)


def _serve_main() -> int:
    """``BENCH_WORKLOAD=serve``: the serving-lane headline — one
    continuous-batching run of the round-16 engine at a fixed Poisson
    arrival rate, ONE JSON line (tokens/s + the p99/goodput SLO
    extras).  The continuous-vs-static A/B harness is
    ``scripts/bench_serve.py``; this entry keeps the serve headline in
    the same BENCH_*.json trajectory as the training one.  Shares the
    env grammar: BENCH_MODEL (a decoder/classify member),
    BENCH_ARRIVAL, BENCH_ARRIVAL_RATE, BENCH_REQUESTS, BENCH_SERVE_BUCKETS,
    BENCH_BATCHING, BENCH_DECODE_ATTENTION (gather|paged), BENCH_QUANT
    (off|int8_w|int8_kv), BENCH_DECODE_BLOCK_PAGES, BENCH_COMPILE_CACHE,
    BENCH_METRICS_DIR, BENCH_CONFIG=auto (resolves the <model>@serve
    registry row).  The extras carry decode_attention/quant and the
    worst decode bucket's AOT temp bytes so `obs regress`/`obs diff`
    track the decode-kernel win.
    """
    from tpu_hc_bench import flags
    from tpu_hc_bench.obs import metrics as obs_metrics
    from tpu_hc_bench.serve import cli as serve_cli

    cfg = flags.BenchmarkConfig(
        model=os.environ.get("BENCH_MODEL", "moe_tiny"),
        workload="serve",
        config=os.environ.get("BENCH_CONFIG", "manual"),
        arrival=os.environ.get("BENCH_ARRIVAL", "poisson"),
        arrival_rate=float(os.environ.get("BENCH_ARRIVAL_RATE", "16")),
        num_requests=int(os.environ.get("BENCH_REQUESTS", "48")),
        serve_buckets=os.environ.get("BENCH_SERVE_BUCKETS", "auto"),
        batching=os.environ.get("BENCH_BATCHING", "continuous"),
        decode_attention=os.environ.get("BENCH_DECODE_ATTENTION",
                                        "gather"),
        quant=os.environ.get("BENCH_QUANT", "off"),
        decode_block_pages=int(
            os.environ.get("BENCH_DECODE_BLOCK_PAGES", "0")),
        compile_cache=os.environ.get("BENCH_COMPILE_CACHE") or None,
        metrics_dir=os.environ.get("BENCH_METRICS_DIR") or None,
    ).resolve()
    log = lambda m: print(m, file=sys.stderr, flush=True)  # noqa: E731
    engine, requests = serve_cli.build_engine_and_requests(cfg, log)
    summary = serve_cli.run_serve(
        engine, requests, serve_cli.serve_writer(cfg, cfg.metrics_dir))
    manifest = obs_metrics.run_manifest(cfg=cfg)
    payload = {
        "metric": f"{cfg.model}_serve_tokens_per_s",
        "value": summary["tokens_per_s"],
        "unit": "tokens/sec",
        "vs_baseline": None,    # scripts/bench_serve.py carries the A/B
        "extra": {
            "workload": "serve",
            "batching": summary["batching"],
            "arrival": cfg.arrival,
            "arrival_rate": cfg.arrival_rate,
            "requests": summary["requests"],
            "completed": summary["completed"],
            "p99_ms": summary["p99_e2e_ms"],
            "p99_ttft_ms": summary["p99_ttft_ms"],
            "goodput": summary["goodput"],
            "tokens_per_s": summary["tokens_per_s"],
            "queue_depth_max": summary["queue_depth_max"],
            "buckets": summary["buckets"],
            "max_in_flight": summary["max_in_flight"],
            "kv_pages": summary["kv_pages"],
            "kv_page_size": summary["kv_page_size"],
            "decode_attention": summary.get("decode_attention"),
            "quant": summary.get("quant"),
            "aot_decode_temp_bytes": summary.get("aot_decode_temp_bytes"),
            "post_warmup_compiles": summary["post_warmup_compiles"],
            # round 20: the attribution-shift metrics obs regress gates
            # on (absent on pre-r20 history; the checks skip there)
            "tail_queue_wait_frac": summary.get("tail_queue_wait_frac"),
            "tail_decode_stall_frac": summary.get(
                "tail_decode_stall_frac"),
            # round 22: the allocation-honesty metrics obs regress
            # gates on (absent on pre-r22 history; the checks skip)
            "kv_pool_util": summary.get("kv_pool_util"),
            "kv_req_gap_frac": summary.get("kv_req_gap_frac"),
            # round 25: the lazy-reservation/prefix-sharing arms (part
            # of the regress fingerprint) and their gated metrics
            # (absent on pre-r25 history; the checks skip)
            "kv_reserve": summary.get("kv_reserve"),
            "prefix_cache": summary.get("prefix_cache"),
            "prefix_hit_frac": summary.get("prefix_hit_frac"),
            "pages_grown_total": summary.get("pages_grown_total"),
            # round 24: the merged-sketch tail + fired health signals
            # obs regress gates on (absent on pre-r24 history; skips)
            "p99_merged_ms": summary.get("p99_merged_ms"),
            "latency_source": summary.get("latency_source"),
            "signals_fired": summary.get("signals_fired"),
            "signals_fired_total": summary.get("signals_fired_total"),
            "config_source": cfg.config_source,
            "tuned_config": cfg.tuned_config,
        },
        "manifest": obs_metrics.manifest_subset(manifest),
    }
    print(json.dumps(payload))
    if summary["completed"] == 0:
        return 1
    return _maybe_regress(payload)


def main() -> int:
    # debug/CI escape hatch: BENCH_FORCE_CPU=1 runs the identical protocol
    # on a virtual 8-device CPU mesh (numbers meaningless, plumbing real)
    if os.environ.get("BENCH_FORCE_CPU") == "1":
        import tpu_hc_bench  # noqa: F401  (JAX version shims before config)
        import jax

        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", 8)

    # round 16: the serving lane's headline rides the same entry point
    # (after the FORCE_CPU escape hatch so both lanes share it)
    if os.environ.get("BENCH_WORKLOAD", "train") == "serve":
        return _serve_main()

    from tpu_hc_bench import flags
    from tpu_hc_bench.obs import metrics as obs_metrics
    from tpu_hc_bench.train import driver

    # round 14: BENCH_CONFIG=auto resolves the tuned registry row for
    # (BENCH_MODEL, live hardware) — tpu_hc_bench.tune.  The tuned
    # batch only wins when no explicit BENCH_BATCH_SIZE is set (auto
    # leaves the field at its dataclass default so resolve_auto's
    # explicit-flag-wins rule lets the row through); manual keeps the
    # headline protocol's batch 128.
    config_mode = os.environ.get("BENCH_CONFIG", "manual")
    batch_env = os.environ.get("BENCH_BATCH_SIZE")
    if batch_env is not None:
        batch_size = int(batch_env)
    elif config_mode == "auto":
        batch_size = flags.BenchmarkConfig.batch_size
    else:
        batch_size = 128

    cfg_kwargs = dict(
        # full obs artifact (metrics.jsonl + manifest.json) when asked;
        # the manifest fields below ride in the JSON line regardless
        metrics_dir=os.environ.get("BENCH_METRICS_DIR") or None,
        batch_size=batch_size,
        config=config_mode,
        model=os.environ.get("BENCH_MODEL", "resnet50"),
        use_fp16=True,          # bf16 compute: the TPU-native fast path
        num_warmup_batches=int(os.environ.get("BENCH_WARMUP", "50")),
        num_batches=int(os.environ.get("BENCH_BATCHES", "100")),
        display_every=10,
        # packed 4x4/s1 stem — same math as the 7x7/s2 conv (proven by
        # tests/test_models.py::test_space_to_depth_stem_equivalence).
        # Default OFF: the round-2 A/B measured s2d slower (BASELINE.md
        # "space_to_depth re-measured").  Models without an s2d stem are
        # rejected loudly by create_model.
        use_space_to_depth=os.environ.get("BENCH_S2D", "0") == "1",
        # round 3: Pallas fused bottleneck segment (BENCH_FUSED_CONV=1 to
        # enable; only the v1 bottleneck resnets accept it, so default off
        # keeps every BENCH_MODEL working)
        fused_conv=os.environ.get("BENCH_FUSED_CONV", "0") == "1",
        # round 6: gradient-arm A/B knobs — psum (default) | replicated |
        # zero1, the Horovod 128 MiB fusion threshold, and the
        # overlapped-vs-serialized collective schedule
        variable_update=os.environ.get("BENCH_VARIABLE_UPDATE", "psum"),
        fusion_threshold_bytes=int(os.environ.get(
            "BENCH_FUSION_THRESHOLD", "134217728")),
        overlap_grad_comm=os.environ.get("BENCH_OVERLAP", "on"),
        # round 12: elastic-resume knobs — BENCH_TRAIN_DIR checkpoints
        # the bench run (topology sidecar included), BENCH_RESUME=elastic
        # continues a prior bench run on a different world size; the
        # resume identity rides the JSON `extra` either way
        train_dir=os.environ.get("BENCH_TRAIN_DIR") or None,
        resume=os.environ.get("BENCH_RESUME", "auto"),
        # round 13: host-level shared input service A/B on real-data
        # bench runs (BENCH_DATA_DIR + BENCH_INPUT_SERVICE=on|off|auto);
        # synthetic runs resolve the flag to off with a translation note
        data_dir=os.environ.get("BENCH_DATA_DIR") or None,
        input_service=os.environ.get("BENCH_INPUT_SERVICE", "auto"),
        # round 15: pre-run AOT memory check (obs.memory) —
        # BENCH_HBM_BUDGET=16GB|auto warns loudly BEFORE the run pays
        # for the full compile when the step program cannot fit
        hbm_budget=os.environ.get("BENCH_HBM_BUDGET") or None,
    )
    cfg = flags.BenchmarkConfig(**cfg_kwargs).resolve()
    if (config_mode == "auto" and cfg.config_source == "baseline"
            and batch_env is None):
        # no tuned row for this hardware: fall back to the HEADLINE
        # protocol's batch 128, not the dataclass default 64 — a fresh
        # machine's BENCH history must stay comparable with the manual
        # runs.  Provenance stays 'baseline' and the loud note rides
        # the translation banner either way.
        note = cfg.translations.get("config")
        cfg_kwargs.update(batch_size=128, config="manual")
        cfg = flags.BenchmarkConfig(**cfg_kwargs).resolve()
        cfg.config_source = "baseline"
        if note:
            cfg.translations["config"] = note

    # human-readable progress to stderr; stdout carries only the JSON line
    result = driver.run_benchmark(
        cfg, fabric_name="ici",
        print_fn=lambda m: print(m, file=sys.stderr, flush=True),
    )
    # run-identity manifest (obs.metrics): the answer to "what exactly
    # produced this BENCH_*.json" — versions, git sha, device, world.
    # With BENCH_METRICS_DIR set the driver already wrote the manifest;
    # reuse it so the artifact and the JSON line agree on one record
    if cfg.metrics_dir:
        with open(os.path.join(cfg.metrics_dir,
                               obs_metrics.MANIFEST_NAME)) as f:
            manifest = json.load(f)
    else:
        manifest = obs_metrics.run_manifest(cfg=cfg)
    payload = {
        "metric": f"{cfg.model}_synthetic_images_per_sec_per_chip",
        "value": round(result.images_per_sec_per_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(
            result.images_per_sec_per_chip / REFERENCE_NODE_IMAGES_PER_SEC, 3
        ),
        "extra": {
            "total_images_per_sec": round(result.total_images_per_sec, 2),
            "mfu": round(result.mfu, 4),
            "chips": result.total_workers,
            "global_batch": result.global_batch,
            "mean_step_ms": round(result.mean_step_ms, 3),
            "p50_step_ms": round(result.p50_step_ms, 3),
            "p50_step_granularity": result.p50_step_granularity,
            "dtype": cfg.compute_dtype,
            # gradient-arm identity: A/B runs over these knobs must
            # render as config drift, not as unexplained perf deltas
            # (obs diff reads the same fields from the manifest config)
            "variable_update": cfg.variable_update,
            "fusion_threshold_bytes": cfg.fusion_threshold_bytes,
            "overlap_grad_comm": cfg.overlap_grad_comm,
            # goodput ledger: the perf trajectory captures overlap wins
            # (compile/checkpoint blocking shrinking), not just the
            # images/sec headline (NaN-goodput runs carry null)
            "goodput": (round(result.goodput, 4)
                        if result.goodput == result.goodput else None),
            "goodput_phases": result.goodput_phases,
            # input plane: which arm ACTUALLY fed the run (the driver
            # resolves --input_service=auto, so the flag string alone
            # can't distinguish arms; true/false/null-resolved) + the
            # ledger's data_wait fraction — the input-service success
            # metric (~0 as workers-per-host scale)
            "input_service": result.input_service,
            "input_service_flag": cfg.input_service,
            "data_wait_frac": (round(result.data_wait_frac, 4)
                               if result.data_wait_frac
                               == result.data_wait_frac else None),
            # resume topology (saved world -> live world, arm): a
            # post-resume throughput shift with a world-size change is
            # a different experiment — obs diff and the BENCH history
            # must both see it as config drift, not a regression
            "resume": result.resume,
            # measured device memory (round 15, obs.memory): the run's
            # HBM high water (mem_source says allocator peak vs the
            # live-arrays fallback) and the step program's AOT
            # argument/temp/output byte account — the BENCH history
            # shows a lever change moving memory BEFORE it OOMs
            "peak_hbm_bytes": result.peak_hbm_bytes,
            "hbm_bytes_limit": result.hbm_bytes_limit,
            "mem_source": result.mem_source,
            "memory_analysis": result.memory_analysis,
            # config provenance (round 14): manual = hand-set flags,
            # auto = a tuned registry row was applied (the row rides
            # along), baseline = --config=auto found no row and fell
            # back to BASELINE defaults — the perf trajectory must
            # distinguish tuned from hand-set runs
            "config_source": cfg.config_source,
            "tuned_config": cfg.tuned_config,
        },
        "manifest": obs_metrics.manifest_subset(manifest),
    }
    print(json.dumps(payload))
    return _maybe_regress(payload)


if __name__ == "__main__":
    raise SystemExit(main())
