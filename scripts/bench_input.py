"""Host input-pipeline throughput benchmark (no TPU involved).

Measures the real-data decode path alone — TFRecord scan -> JPEG decode ->
random-resized-crop -> resize — as a function of decode-pool width, to
prove the pipeline can feed a chip (VERDICT r1 weak #2: the single-thread
pipeline capped at ~644 img/s vs the ~2700 img/s synthetic compute
ceiling).

Writes representative shards (400x400 JPEGs, ImageNet-typical size) to a
temp dir unless --data_dir points at real shards.

Usage: python scripts/bench_input.py [--data_dir DIR] [--workers 1,2,4,8]
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, ".")

from tpu_hc_bench.data import imagenet


def make_shards(tmp: str, n_images: int = 1024, size: int = 400):
    import io

    from PIL import Image

    from tpu_hc_bench.data import tfrecord

    rng = np.random.default_rng(0)
    per_shard = n_images // 4
    paths = []
    for s in range(4):
        records = []
        for _ in range(per_shard):
            # photographic-ish content: smooth gradients + noise compresses
            # like a real photo (pure noise JPEGs decode unrealistically slow)
            base = np.linspace(0, 255, size, dtype=np.float32)
            img = (base[None, :, None] * 0.5 + base[:, None, None] * 0.5
                   + rng.normal(0, 20, (size, size, 3)))
            arr = np.clip(img, 0, 255).astype(np.uint8)
            buf = io.BytesIO()
            Image.fromarray(arr).save(buf, format="JPEG", quality=90)
            records.append(tfrecord.build_example({
                "image/encoded": [buf.getvalue()],
                "image/class/label": [int(rng.integers(1, 1001))],
            }))
        path = os.path.join(tmp, f"train-{s:05d}-of-00004")
        tfrecord.write_records(path, records)
        paths.append(path)
    return tmp


def bench(data_dir: str, workers: int, batch: int = 128,
          n_batches: int = 8) -> float:
    ds = imagenet.ImageNetDataset(
        data_dir, global_batch=batch, image_size=224, train=True,
        wire_dtype="uint8", decode_workers=workers,
    )
    it = iter(ds)
    next(it)                      # warm: open shards, spin pool
    t0 = time.perf_counter()
    for _ in range(n_batches):
        next(it)
    dt = time.perf_counter() - t0
    return batch * n_batches / dt


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--data_dir", default=None)
    ap.add_argument("--workers", default="1,2,4,8,0")
    ap.add_argument("--batch", type=int, default=128)
    args = ap.parse_args()

    ncpu = os.cpu_count()
    print(f"host vCPUs: {ncpu}")
    tmp = None
    data_dir = args.data_dir
    if data_dir is None:
        tmp = tempfile.TemporaryDirectory()
        print("writing synthetic 400x400 JPEG shards...", flush=True)
        data_dir = make_shards(tmp.name)
    for w in (int(x) for x in args.workers.split(",")):
        label = w if w else f"auto"
        rate = bench(data_dir, w or None, batch=args.batch)
        print(f"decode_workers={label:>4}  {rate:7.1f} img/s", flush=True)
    if tmp:
        tmp.cleanup()


if __name__ == "__main__":
    main()
