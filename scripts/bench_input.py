"""Host input-plane benchmark: decode-width sweep + service-vs-private A/B.

Two modes, no TPU involved:

- ``--mode sweep`` (the round-2 original): measures the real-data decode
  path alone — TFRecord scan -> JPEG decode -> random-resized-crop ->
  resize — as a function of decode-pool width, to prove the pipeline can
  feed a chip (VERDICT r1 weak #2).

- ``--mode ab`` (default, round 13): the INPUT SERVICE A/B.  Runs
  1/2/4 simulated workers-per-host through both input arms —

  * ``per_process``: each worker process owns a private
    ``ImageNetDataset`` decode pool (the seed pipeline, the
    ``--input_service=off`` control arm).  The worker's simulated step
    holds the GIL for ``--churn_ms`` (the host-side Python of a real
    step loop: batch shard/dispatch/metrics), which is exactly what
    starves a private in-process pool.
  * ``service``: ONE ``data.service.InputService`` decode pool in the
    parent process feeds every worker over shared-memory rings
    (``--input_service=on``); consumer GILs never touch decode.

  Each simulated worker times ``next(batch)`` (its data_wait), then
  burns ``--churn_ms`` of GIL-held Python and sleeps ``--step_ms`` (the
  accelerator part of the step, which costs no host CPU).  Emits a JSON
  comparison per (workers, arm): aggregate img/s/host, data_wait
  fraction, and host CPU utilization — the acceptance record for
  "data_wait ~0 as workers-per-host scale".

Usage:
  python scripts/bench_input.py [--workers 1,2,4] [--json OUT.json]
  python scripts/bench_input.py --mode sweep [--workers 1,2,4,8,0]
"""

from __future__ import annotations

import argparse
import json
import multiprocessing as mp
import os
import resource
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, ".")

from tpu_hc_bench.data import imagenet


def make_shards(tmp: str, n_images: int = 1024, size: int = 400,
                n_shards: int = 4):
    import io

    from PIL import Image

    from tpu_hc_bench.data import tfrecord

    rng = np.random.default_rng(0)
    per_shard = n_images // n_shards
    for s in range(n_shards):
        records = []
        for _ in range(per_shard):
            # photographic-ish content: smooth gradients + noise compresses
            # like a real photo (pure noise JPEGs decode unrealistically slow)
            base = np.linspace(0, 255, size, dtype=np.float32)
            img = (base[None, :, None] * 0.5 + base[:, None, None] * 0.5
                   + rng.normal(0, 20, (size, size, 3)))
            arr = np.clip(img, 0, 255).astype(np.uint8)
            buf = io.BytesIO()
            Image.fromarray(arr).save(buf, format="JPEG", quality=90)
            records.append(tfrecord.build_example({
                "image/encoded": [buf.getvalue()],
                "image/class/label": [int(rng.integers(1, 1001))],
            }))
        path = os.path.join(tmp, f"train-{s:05d}-of-{n_shards:05d}")
        tfrecord.write_records(path, records)
    return tmp


# ---------------------------------------------------------------------
# mode sweep (round 2)


def bench(data_dir: str, workers: int, batch: int = 128,
          n_batches: int = 8) -> float:
    ds = imagenet.ImageNetDataset(
        data_dir, global_batch=batch, image_size=224, train=True,
        wire_dtype="uint8", decode_workers=workers,
    )
    it = iter(ds)
    next(it)                      # warm: open shards, spin pool
    t0 = time.perf_counter()
    for _ in range(n_batches):
        next(it)
    dt = time.perf_counter() - t0
    return batch * n_batches / dt


def run_sweep(args, data_dir: str) -> None:
    for w in (int(x) for x in args.workers.split(",")):
        label = w if w else "auto"
        rate = bench(data_dir, w or None, batch=args.batch)
        print(f"decode_workers={label:>4}  {rate:7.1f} img/s", flush=True)


# ---------------------------------------------------------------------
# mode ab (round 13): input service vs per-process pools


def _churn(ms: float) -> None:
    """GIL-held Python for ~ms — the step loop's host-side work."""
    deadline = time.perf_counter() + ms / 1e3
    x = 0
    while time.perf_counter() < deadline:
        x += 1
    return None


def _consumer(arm: str, k: int, num_workers: int, data_dir: str,
              batch: int, image_size: int, n_batches: int, step_ms: float,
              churn_ms: float, svc_name: str, depth: int, q) -> None:
    """One simulated worker, modeled on the real driver's input plane.

    ``batch`` is the worker's CONSUMED images per step (its slice of
    the data mesh).  The ``per_process`` arm does what the driver's
    off-arm does at workers-per-host > 1: decode the FULL host batch
    (``num_workers * batch`` images) of its own shard stream, of which
    its devices consume one slice — W-fold redundant host decode.  The
    ``service`` arm reads its ring, which carries exactly the consumed
    slice (decoded once, service-side).  Both arms then burn
    ``churn_ms`` of GIL-held Python (the step loop's host-side work)
    and sleep ``step_ms`` (the accelerator part).
    """
    try:
        host_batch = batch * num_workers
        if arm == "service":
            from tpu_hc_bench.data import service as service_mod

            client = service_mod.ServiceClient(
                svc_name,
                service_mod.image_batch_layout(batch, image_size, "uint8"),
                worker=k, depth=depth, timeout=120.0)
            it = iter(client)
        else:
            # local_workers mirrors the SHIPPED --input_service=off arm
            # (the driver divides each private pool's auto width by the
            # local worker count) — the control is the current product,
            # not the pre-round-13 undivided-pool strawman
            ds = imagenet.ImageNetDataset(
                data_dir, global_batch=host_batch, image_size=image_size,
                train=True, wire_dtype="uint8", worker=k,
                num_workers=num_workers, local_workers=num_workers)
            it = iter(ds)
        next(it)                        # warm: shards open / ring filled
        wait_s = 0.0
        t_start = time.perf_counter()
        for _ in range(n_batches):
            t0 = time.perf_counter()
            b = next(it)
            wait_s += time.perf_counter() - t0
            # the consumed slice (per-process: rows [k*b, (k+1)*b) of
            # this worker's full host batch; service: the whole ring
            # batch IS the slice)
            if arm == "per_process":
                b = (b[0][k * batch:(k + 1) * batch],
                     b[1][k * batch:(k + 1) * batch])
            _churn(churn_ms)
            time.sleep(step_ms / 1e3)
        wall = time.perf_counter() - t_start
        q.put({"worker": k, "images": batch * n_batches,
               "wait_s": round(wait_s, 4), "wall_s": round(wall, 4)})
    except Exception as e:              # surface, don't hang the parent
        q.put({"worker": k, "error": f"{type(e).__name__}: {e}"})


def run_arm(arm: str, num_workers: int, data_dir: str, args) -> dict:
    from tpu_hc_bench.data import service as service_mod

    depth = args.depth
    svc = None
    svc_name = ""
    if arm == "service":
        # pool width 0 -> the SHIPPED service default
        # (imagenet.host_decode_budget, same figure the per-process
        # arm divides) — the A/B compares products, not a widened
        # bench-only pool
        svc = service_mod.make_image_service(
            [data_dir], num_workers=num_workers,
            global_batch=args.batch * num_workers,
            image_size=args.image_size, wire_dtype="uint8",
            decode_workers=args.service_decode_workers,
            depth=depth, slice_per_worker=True,
        ).start()
        svc_name = svc.name
    cpu0 = resource.getrusage(resource.RUSAGE_SELF)
    cpu0c = resource.getrusage(resource.RUSAGE_CHILDREN)
    t0 = time.perf_counter()
    ctx = mp.get_context("fork")
    q = ctx.Queue()
    procs = [
        ctx.Process(target=_consumer, args=(
            arm, k, num_workers, data_dir, args.batch, args.image_size,
            args.n_batches, args.step_ms, args.churn_ms, svc_name, depth, q))
        for k in range(num_workers)
    ]
    for p in procs:
        p.start()
    reports = [q.get(timeout=600) for _ in procs]
    for p in procs:
        p.join()
    wall = time.perf_counter() - t0
    cpu1 = resource.getrusage(resource.RUSAGE_SELF)
    cpu1c = resource.getrusage(resource.RUSAGE_CHILDREN)
    svc_stats = None
    if svc is not None:
        svc_stats = svc.stats()
        svc.stop()
    errors = [r["error"] for r in reports if "error" in r]
    if errors:
        raise RuntimeError(f"{arm} arm consumer(s) failed: {errors}")
    images = sum(r["images"] for r in reports)
    timed_wall = max(r["wall_s"] for r in reports)
    cpu_s = ((cpu1.ru_utime + cpu1.ru_stime
              - cpu0.ru_utime - cpu0.ru_stime)
             + (cpu1c.ru_utime + cpu1c.ru_stime
                - cpu0c.ru_utime - cpu0c.ru_stime))
    rec = {
        "arm": arm,
        "workers": num_workers,
        "img_per_s_host": round(images / timed_wall, 1),
        "data_wait_frac": round(
            sum(r["wait_s"] for r in reports)
            / sum(r["wall_s"] for r in reports), 4),
        "cpu_util": round(cpu_s / (wall * (os.cpu_count() or 1)), 3),
        "per_worker": reports,
    }
    if svc_stats is not None:
        rec["service"] = svc_stats
    return rec


def run_ab(args, data_dir: str) -> dict:
    worker_counts = [int(x) for x in args.workers.split(",")]
    arms = []
    for k in worker_counts:
        for arm in ("per_process", "service"):
            rec = run_arm(arm, k, data_dir, args)
            arms.append(rec)
            print(f"workers={k} {arm:>12}: "
                  f"{rec['img_per_s_host']:7.1f} img/s/host  "
                  f"data_wait {100 * rec['data_wait_frac']:5.1f}%  "
                  f"cpu {100 * rec['cpu_util']:5.1f}%", flush=True)
    by = {(r["workers"], r["arm"]): r for r in arms}
    verdict = {}
    for k in worker_counts:
        pp, sv = by[(k, "per_process")], by[(k, "service")]
        verdict[f"workers{k}"] = {
            "service_img_per_s": sv["img_per_s_host"],
            "per_process_img_per_s": pp["img_per_s_host"],
            "service_data_wait_frac": sv["data_wait_frac"],
            "per_process_data_wait_frac": pp["data_wait_frac"],
            "service_wins": (sv["img_per_s_host"] > pp["img_per_s_host"]
                             and sv["data_wait_frac"]
                             < pp["data_wait_frac"]),
        }
    return {
        "host_cpus": os.cpu_count(),
        "batch": args.batch,
        "n_batches": args.n_batches,
        "image_size": args.image_size,
        "source_px": args.source_px,
        "step_ms": args.step_ms,
        "churn_ms": args.churn_ms,
        "ring_depth": args.depth,
        "arms": arms,
        "verdict": verdict,
    }


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--mode", choices=["ab", "sweep"], default="ab")
    ap.add_argument("--data_dir", default=None)
    ap.add_argument("--workers", default=None,
                    help="ab: simulated workers/host (default 1,2,4); "
                         "sweep: decode pool widths (default 1,2,4,8,0)")
    ap.add_argument("--batch", type=int, default=None,
                    help="CONSUMED images per worker per step "
                         "(default: ab 16, sweep 128)")
    ap.add_argument("--n_batches", type=int, default=12)
    ap.add_argument("--image_size", type=int, default=224)
    ap.add_argument("--source_px", type=int, default=None,
                    help="synthetic source JPEG edge px (no --data_dir; "
                         "default: ab 280, sweep 400)")
    ap.add_argument("--n_images", type=int, default=384)
    ap.add_argument("--step_ms", type=float, default=180.0,
                    help="simulated accelerator step (sleep; no host CPU)")
    ap.add_argument("--churn_ms", type=float, default=20.0,
                    help="simulated host-side Python per step (GIL-held)")
    ap.add_argument("--depth", type=int, default=3,
                    help="service ring depth (slots/worker; default 3 "
                         "~ the per-process arm's prefetch buffering, "
                         "so neither arm gets a deeper warm buffer)")
    ap.add_argument("--service_decode_workers", type=int, default=0,
                    help="service host pool width (0 = the shipped "
                         "default, imagenet.host_decode_budget)")
    ap.add_argument("--json", default=None, metavar="OUT",
                    help="ab: also write the comparison JSON here")
    args = ap.parse_args()
    if args.workers is None:
        args.workers = "1,2,4" if args.mode == "ab" else "1,2,4,8,0"
    if args.batch is None:
        args.batch = 16 if args.mode == "ab" else 128
    if args.source_px is None:
        args.source_px = 280 if args.mode == "ab" else 400

    print(f"host vCPUs: {os.cpu_count()}")
    tmp = None
    data_dir = args.data_dir
    if data_dir is None:
        tmp = tempfile.TemporaryDirectory()
        print(f"writing synthetic {args.source_px}x{args.source_px} JPEG "
              "shards...", flush=True)
        data_dir = make_shards(tmp.name, n_images=args.n_images,
                               size=args.source_px)
    try:
        if args.mode == "sweep":
            run_sweep(args, data_dir)
            return
        result = run_ab(args, data_dir)
        print(json.dumps(result, indent=1))
        if args.json:
            with open(args.json, "w") as f:
                json.dump(result, f, indent=1)
            print(f"wrote {args.json}", file=sys.stderr)
    finally:
        if tmp:
            tmp.cleanup()


if __name__ == "__main__":
    main()
