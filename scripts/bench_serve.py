"""Serving-lane A/B benchmarks: batching arms, and decode-kernel arms.

``--mode batching`` (default) is the round-16 acceptance experiment:
ONE warmed engine (every (batch, seqlen) bucket AOT-compiled once,
through ``--compile_cache`` when given), ONE identical seeded request
trace, TWO scheduler arms —

- ``static``: the classic control — collect a full batch, run it to
  completion, only then admit again; arrivals queue while stragglers
  finish.
- ``continuous``: Orca-style — admission and retirement per decode
  step; a retired request's slot is refilled at the very next step.

``--mode decode`` (round 18) is the decode-kernel/quantization A/B:
one engine PER arm (the arms compile different decode programs), same
trace, continuous batching —

- ``gather/off``: the dense-gather ``_softmax_attend`` reference;
- ``paged/off``: the Pallas flash-decode kernel reading K/V through
  the page tables (``ops.paged_attention``);
- ``paged/int8_kv``: + int8 KV pool with per-page scales consumed
  inside the kernel;
- ``paged/int8_w``: + per-channel int8 weights dequantized at the
  matmul.

The verdict checks the worst decode bucket's AOT ``memory_analysis``
temp bytes (the dense-gather temporaries the kernel eliminates), the
int8 pool's argument-byte shrink, ZERO post-warmup compiles on every
arm, and token-for-token parity of the f32 arms (read back from the
per-arm request records).

``--mode kv`` (round 25, supersedes the round-22 honesty A/B) is the
allocation A/B: ONE warmed engine, one fixed constrained pool, one
trace with an imposed shared prompt prefix, THREE ``(kv_reserve,
prefix_cache)`` arms — worst-case reservation (the round-22 control),
lazy on-demand growth, and lazy + the COW shared-prefix cache.  The
headline is the lazy+prefix arm's ``kv_pool_util``; the verdict
requires strictly more admitted req/s than the control at the SAME
pool bytes, util above the round-22 waste line, and token-for-token
parity on every arm.

``--mode faults`` (round 23) is the overload-survival A/B: one warmed
engine, one overload trace, one fixed fault schedule (NaN-poisoned
requests + a sticky KV-pool squeeze), shedding+preemption+quarantine
vs the no-degradation control.  Headline: served-within-SLO goodput —
the degrading arm must answer MORE of the trace correctly within
``--deadline_ms`` than the arm that heroically serves everything late.

``--mode signals`` (round 24) is the sensing A/B: one warmed engine,
policy knobs pinned OFF, a clean control trace vs an injected
overload + sticky pool squeeze.  The health-signal engine must fire
``SUSTAINED_OVERLOAD`` and ``KV_PRESSURE`` on the overload arm (the
KV onset at/after the injection instant) and NOTHING on the control
arm, and both arms' merged-sketch p99 must land inside the exact
stored-sample bracket widened by the sketch's relative-error bound.

Every mode folds the per-arm KV-pool ledger (``kv_pool`` /
``kv_pool_util`` / ``kv_req_gap_frac``) into its arms.

Both modes emit a BENCH-style JSON record with
``decode_attention``/``quant``/``aot_decode_temp_bytes`` in ``extra``
(the fields ``obs regress``/``obs diff`` track) plus ``obs
diff``-renderable per-arm metrics dirs under ``--metrics_root``.

Env knobs (CI parity with bench.py):

- ``BENCH_MODEL`` (default moe_tiny), ``BENCH_ARRIVAL_RATE``,
  ``BENCH_SERVE_BUCKETS``, ``BENCH_REQUESTS``, ``BENCH_MAX_IN_FLIGHT``,
  ``BENCH_DECODE_ATTENTION``, ``BENCH_QUANT``, ``BENCH_MODE``,
  ``BENCH_COMPILE_CACHE`` (a dir makes the zero-recompile assertion
  measured, not vacuous).

Usage:
  JAX_PLATFORMS=cpu python scripts/bench_serve.py \
      [--mode batching|decode] [--json OUT.json] [--metrics_root DIR]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, ".")


def _build_cfg(args, **overrides):
    from tpu_hc_bench import flags as flags_mod

    kw = dict(
        model=args.model,
        workload="serve",
        arrival=args.arrival,
        arrival_rate=args.arrival_rate,
        num_requests=args.num_requests,
        serve_buckets=args.serve_buckets,
        max_in_flight=args.max_in_flight,
        kv_page_size=args.kv_page_size,
        max_prompt_len=args.max_prompt_len,
        max_output_len=args.max_output_len,
        decode_attention=args.decode_attention,
        quant=args.quant,
        decode_block_pages=args.decode_block_pages,
        compile_cache=args.compile_cache,
        seed=args.seed,
    )
    kw.update(overrides)
    return flags_mod.BenchmarkConfig(**kw).resolve()


def run_ab(args) -> dict:
    from tpu_hc_bench import flags as flags_mod
    from tpu_hc_bench.obs import metrics as obs_metrics
    from tpu_hc_bench.serve import cli as serve_cli

    cfg = _build_cfg(args)

    log = lambda m: print(m, file=sys.stderr, flush=True)  # noqa: E731
    engine, requests = serve_cli.build_engine_and_requests(cfg, log)

    arms: dict[str, dict] = {}
    for arm in ("static", "continuous"):
        mdir = None
        arm_cfg = cfg
        if args.metrics_root:
            mdir = os.path.join(args.metrics_root, arm)
            # per-arm manifest: obs diff renders the batching flip as
            # config drift next to the serve-metric delta rows
            arm_cfg = flags_mod.BenchmarkConfig(
                **{**cfg.__dict__,
                   "translations": {}, "batching": arm,
                   "explicit_flags": None, "tuned_config": None})
        log(f"--- arm: {arm} ---")
        summary = serve_cli.run_serve(
            engine, requests, serve_cli.serve_writer(arm_cfg, mdir),
            batching=arm)
        arms[arm] = {
            "tokens_per_s": summary["tokens_per_s"],
            "p99_e2e_ms": summary["p99_e2e_ms"],
            "p99_ttft_ms": summary["p99_ttft_ms"],
            "p50_e2e_ms": summary["p50_e2e_ms"],
            "p99_queue_ms": summary.get("p99_queue_ms"),
            "goodput": summary["goodput"],
            "queue_depth_max": summary["queue_depth_max"],
            "wall_s": summary["wall_s"],
            "completed": summary["completed"],
            "post_warmup_compiles": summary["post_warmup_compiles"],
            # round 20: the tail-attribution fold (obs.requests) — the
            # A/B's WHY column: static's p99 lives in queue_wait/
            # decode_stall, continuous moves it back to decode_active
            "attribution": summary.get("attribution"),
            # round 22 (obs.kv): the pool ledger per arm — static's
            # fill-then-drain pattern and continuous' refill-per-step
            # produce different written/reserved integrals on the SAME
            # reservation policy
            "kv_pool": summary.get("kv_pool"),
            "kv_pool_util": summary.get("kv_pool_util"),
            "kv_req_gap_frac": summary.get("kv_req_gap_frac"),
            # round 24: the merged-sketch tail + any fired health
            # signals per arm
            "p99_merged_ms": summary.get("p99_merged_ms"),
            "signals_fired": summary.get("signals_fired"),
            "signals_fired_total": summary.get("signals_fired_total"),
            "metrics_dir": mdir,
        }

    from tpu_hc_bench.obs import requests as requests_mod

    st, ct = arms["static"], arms["continuous"]
    st_attr, ct_attr = st["attribution"], ct["attribution"]
    verdict = {
        # the two acceptance properties: continuous beats static on the
        # p99 tail AND on goodput-under-load, at the same offered load
        "continuous_beats_static_p99": ct["p99_e2e_ms"] < st["p99_e2e_ms"],
        "continuous_beats_static_goodput": ct["goodput"] > st["goodput"],
        "p99_e2e_delta_pct": round(
            100.0 * (ct["p99_e2e_ms"] - st["p99_e2e_ms"])
            / max(st["p99_e2e_ms"], 1e-9), 1),
        "goodput_delta_pct": round(
            100.0 * (ct["goodput"] - st["goodput"])
            / max(st["goodput"], 1e-9), 1),
        "zero_post_warmup_compiles": (
            ct["post_warmup_compiles"] == 0
            and st["post_warmup_compiles"] == 0),
        # the attribution story: continuous batching's tail spends a
        # smaller share of its e2e waiting (queue + resident-starved)
        # than static's, at the same offered load
        "continuous_tail_waits_less": (
            (ct_attr["tail_frac"]["queue_wait"]
             + ct_attr["tail_frac"]["decode_stall"])
            < (st_attr["tail_frac"]["queue_wait"]
               + st_attr["tail_frac"]["decode_stall"])
            if st_attr and ct_attr else None),
        "compile_cache": engine.cache_dir,
        "compile_record": engine.compile_record,
    }
    manifest = obs_metrics.manifest_subset(
        obs_metrics.run_manifest(cfg=cfg))
    return {
        "metric": f"{cfg.model}_serve_tokens_per_s",
        "value": ct["tokens_per_s"],
        "unit": "tokens/sec",
        # continuous over the classic static arm at the same load — the
        # serving analog of bench.py's vs-reference ratio
        "vs_baseline": round(
            ct["tokens_per_s"] / max(st["tokens_per_s"], 1e-9), 3),
        "extra": {
            "workload": "serve",
            "model": cfg.model,
            "arrival": cfg.arrival,
            "arrival_rate": cfg.arrival_rate,
            "num_requests": cfg.num_requests,
            "max_prompt_len": cfg.max_prompt_len,
            "max_output_len": cfg.max_output_len,
            "buckets": list(engine.batch_buckets),
            "max_in_flight": engine.cap,
            "kv_page_size": engine.page_size,
            "kv_pages": engine.num_pages,
            "decode_attention": cfg.decode_attention,
            "quant": cfg.quant,
            "aot_decode_temp_bytes": engine.compile_record.get(
                "aot_decode_temp_bytes"),
            "p99_ms": ct["p99_e2e_ms"],
            "goodput": ct["goodput"],
            "tokens_per_s": ct["tokens_per_s"],
            # the regress gate's attribution-shift metrics (headline =
            # continuous arm, matching the other extras)
            **requests_mod.flatten_attribution(ct_attr),
            # round 22: the regress gate's allocation-honesty metric
            "kv_pool_util": ct.get("kv_pool_util"),
            "kv_req_gap_frac": ct.get("kv_req_gap_frac"),
            # round 24: the regress gate's merged tail + fire count
            # (headline = continuous arm, matching the other extras)
            "p99_merged_ms": ct.get("p99_merged_ms"),
            "signals_fired_total": ct.get("signals_fired_total"),
            # the static-vs-continuous attribution delta as `obs diff`
            # renders it (also viewable live: obs diff <root>/static
            # <root>/continuous)
            "attribution_diff": requests_mod.attribution_diff_lines(
                st_attr, ct_attr),
            "arms": arms,
            "verdict": verdict,
        },
        "manifest": manifest,
    }


DECODE_ARMS = (("gather", "off"), ("paged", "off"),
               ("paged", "int8_kv"), ("paged", "int8_w"))


def run_decode_ab(args) -> dict:
    """The round-18 decode-kernel/quant A/B: one engine per arm (the
    arms compile different decode programs), same seeded trace,
    continuous batching, zero post-warmup compiles everywhere."""
    import tempfile

    from tpu_hc_bench.obs import metrics as obs_metrics
    from tpu_hc_bench.serve import cli as serve_cli

    log = lambda m: print(m, file=sys.stderr, flush=True)  # noqa: E731
    root = args.metrics_root or tempfile.mkdtemp(prefix="bench_decode_")
    arms: dict[str, dict] = {}
    tokens: dict[str, dict] = {}
    base_cfg = None
    for da, q in DECODE_ARMS:
        arm = f"{da}+{q}"
        cfg = _build_cfg(args, decode_attention=da, quant=q,
                         decode_block_pages=(args.decode_block_pages
                                             if da == "paged" else 0))
        base_cfg = base_cfg or cfg
        log(f"--- decode arm: {arm} ---")
        engine, requests = serve_cli.build_engine_and_requests(cfg, log)
        mdir = os.path.join(root, arm.replace("+", "_"))
        summary = serve_cli.run_serve(
            engine, requests, serve_cli.serve_writer(cfg, mdir),
            batching="continuous")
        toks = {}
        with open(os.path.join(mdir, "metrics.jsonl")) as f:
            for line in f:
                rec = json.loads(line)
                if rec.get("kind") == "request":
                    toks[rec["id"]] = rec.get("generated")
        tokens[arm] = toks
        arms[arm] = {
            "decode_attention": da,
            "quant": q,
            "tokens_per_s": summary["tokens_per_s"],
            "p99_e2e_ms": summary["p99_e2e_ms"],
            "p99_ttft_ms": summary["p99_ttft_ms"],
            "goodput": summary["goodput"],
            "completed": summary["completed"],
            "aot_decode_temp_bytes": summary["aot_decode_temp_bytes"],
            "post_warmup_compiles": summary["post_warmup_compiles"],
            "attribution": summary.get("attribution"),
            # round 22 (obs.kv): the pool ledger per arm
            "kv_pool": summary.get("kv_pool"),
            "kv_pool_util": summary.get("kv_pool_util"),
            "kv_req_gap_frac": summary.get("kv_req_gap_frac"),
            "kv_pool_bytes": summary.get("kv_pool_bytes"),
            "metrics_dir": mdir,
        }
        wk, wma = engine.aot_memory_worst(kinds=("decode",))
        if wma:
            arms[arm]["aot_decode_args_bytes"] = wma.get("argument_bytes")

    ga, pa = arms["gather+off"], arms["paged+off"]
    kv = arms["paged+int8_kv"]
    tmp_g, tmp_p = ga["aot_decode_temp_bytes"], pa["aot_decode_temp_bytes"]
    int8_match = sum(
        1 for rid, t in tokens["gather+off"].items()
        if tokens["paged+int8_kv"].get(rid) == t)
    verdict = {
        # the kernel eliminates the dense-gather temporaries: worst
        # decode bucket's AOT temp bytes must drop vs the reference
        "paged_temp_lt_gather": (
            tmp_g is not None and tmp_p is not None and tmp_p < tmp_g),
        "temp_bytes_delta_pct": (
            round(100.0 * (tmp_p - tmp_g) / max(tmp_g, 1), 1)
            if tmp_g and tmp_p is not None else None),
        # the int8 pool quarters the KV argument bytes
        "int8_kv_args_lt_gather": (
            kv.get("aot_decode_args_bytes") or 0)
            < (ga.get("aot_decode_args_bytes") or 0),
        # pinned parity: f32 paged decode is token-for-token identical
        # to the gather reference; int8 arms are tolerance arms, their
        # match count is reported, not asserted
        "paged_token_parity": tokens["gather+off"] == tokens["paged+off"],
        "int8_kv_token_matches": f"{int8_match}/"
                                 f"{len(tokens['gather+off'])}",
        "zero_post_warmup_compiles": all(
            a["post_warmup_compiles"] == 0 for a in arms.values()),
        "all_completed": all(a["completed"] == args.num_requests
                             for a in arms.values()),
    }
    manifest = obs_metrics.manifest_subset(
        obs_metrics.run_manifest(cfg=base_cfg))
    return {
        "metric": f"{args.model}_decode_kernel_ab",
        "value": pa["tokens_per_s"],
        "unit": "tokens/sec",
        # the paged kernel over the dense-gather reference at the same
        # load — the decode-kernel analog of the batching A/B ratio
        "vs_baseline": round(
            pa["tokens_per_s"] / max(ga["tokens_per_s"], 1e-9), 3),
        "extra": {
            "workload": "serve",
            "mode": "decode",
            "model": args.model,
            "arrival_rate": args.arrival_rate,
            "num_requests": args.num_requests,
            "max_prompt_len": args.max_prompt_len,
            "max_output_len": args.max_output_len,
            "kv_page_size": args.kv_page_size,
            "decode_attention": "paged",
            "quant": "off",
            "aot_decode_temp_bytes": tmp_p,
            "p99_ms": pa["p99_e2e_ms"],
            "goodput": pa["goodput"],
            "tokens_per_s": pa["tokens_per_s"],
            "kv_pool_util": pa.get("kv_pool_util"),
            "kv_req_gap_frac": pa.get("kv_req_gap_frac"),
            "arms": arms,
            "verdict": verdict,
        },
        "manifest": manifest,
    }


#: round 25: (kv_reserve, prefix_cache) policy arms over ONE warmed
#: engine at one FIXED constrained pool — worst-case reservation is
#: the round-22 control whose measured waste this A/B must reclaim
KV_ARMS = (("worst", "off"), ("lazy", "off"), ("lazy", "on"))

#: virtual per-step costs (seconds) — page_copy included so the COW
#: device copy is charged deterministically like any other program
KV_VCLOCK = {"prefill": 0.004, "decode": 0.003, "classify": 0.002,
             "page_copy": 0.001}


def run_kv_ab(args) -> dict:
    """The round-25 allocation A/B: ONE warmed engine (gather/off —
    the arms differ ONLY in allocation policy, never in kernels), ONE
    seeded trace with an imposed shared prompt prefix, ONE fixed
    constrained pool sized well below ``max_in_flight`` worst-case
    tables, THREE ``(kv_reserve, prefix_cache)`` arms —

    - ``worst+off``: the round-22 control — admission reserves the
      full table width up front; the pool admits few residents and
      ~45% of reserved page-seconds are never written.
    - ``lazy+off``: admission reserves ``ceil(prompt/page)`` + headroom
      and decode grows pages on demand (``--kv_preempt=on`` absorbs
      growth failure); same pool now holds more residents.
    - ``lazy+on``: + the COW shared-prefix cache — requests repeating
      a page-aligned prefix map those slots to shared physical pages
      and skip the prefill page writes for them.

    The headline is the lazy+prefix arm's ``kv_pool_util``; the
    verdict requires it to admit strictly more req/s than the control
    AT THE SAME POOL BYTES, util above the round-22 waste line, a
    shrunken honesty gap, and token-for-token parity of every arm
    (sharing and growth are allocation tricks — they must never change
    what a request decodes).  VirtualClock (with an explicit
    ``page_copy`` cost) keeps the artifact deterministic."""
    import dataclasses
    import tempfile

    import numpy as np

    from tpu_hc_bench.obs import metrics as obs_metrics
    from tpu_hc_bench.serve import cli as serve_cli
    from tpu_hc_bench.serve import engine as engine_mod

    log = lambda m: print(m, file=sys.stderr, flush=True)  # noqa: E731
    root = args.metrics_root or tempfile.mkdtemp(prefix="bench_kv_")

    # the FIXED constrained pool: far below max_in_flight worst-case
    # tables (the worst arm can only hold a few residents), page 0
    # reserved as trash — identical bytes for every arm by design
    table_width = -(-(args.max_prompt_len + args.max_output_len)
                    // args.kv_page_size)
    kv_pages = 1 + max(2, args.max_in_flight // 2) * table_width
    # offered at overload so the POOL, not the arrival process, is the
    # bottleneck — admitted req/s then measures what each reservation
    # policy fits into the same bytes; headroom 0 makes decode growth
    # real (every page past the prompt's is allocated on demand)
    cfg = _build_cfg(args, decode_attention="gather", quant="off",
                     decode_block_pages=0, kv_pages=kv_pages,
                     kv_growth_headroom=0,
                     arrival_rate=max(args.arrival_rate,
                                      args.overload_rate))
    engine, requests = serve_cli.build_engine_and_requests(cfg, log)

    # impose the shared prefix the cache exists for: every prompt's
    # first page worth of tokens becomes one fixed seeded block (kept
    # inside each prompt's own length — arrival times and lengths are
    # untouched, so the trace's offered load is identical)
    vocab = engine.spec.vocab_size
    block = np.random.default_rng((args.seed, 25)).integers(
        0, vocab, size=args.kv_page_size, dtype=np.int32)
    requests = [
        dataclasses.replace(
            r, prompt=np.concatenate(
                [block[:min(len(r.prompt), args.kv_page_size)],
                 r.prompt[min(len(r.prompt), args.kv_page_size):]]))
        if r.prompt is not None and len(r.prompt) else r
        for r in requests]

    arms: dict[str, dict] = {}
    tokens: dict[str, dict] = {}
    for kr, pc in KV_ARMS:
        arm = f"{kr}+{pc}"
        mdir = os.path.join(root, arm.replace("+", "_"))
        log(f"--- kv arm: kv_reserve={kr} prefix_cache={pc} ---")
        writer = serve_cli.serve_writer(cfg, mdir)
        try:
            summary = engine.run(
                requests, batching="continuous", writer=writer,
                clock=engine_mod.VirtualClock(KV_VCLOCK),
                kv_reserve=kr, prefix_cache=pc,
                # lazy admission can over-admit; growth failure must
                # preempt-and-requeue instead of stalling
                kv_preempt=("on" if kr == "lazy" else "off"))
        finally:
            writer.close()
        toks = {}
        with open(os.path.join(mdir, "metrics.jsonl")) as f:
            for line in f:
                rec = json.loads(line)
                if rec.get("kind") == "request":
                    toks[rec["id"]] = rec.get("generated")
        tokens[arm] = toks
        kvf = summary.get("kv_pool") or {}
        arms[arm] = {
            "kv_reserve": kr,
            "prefix_cache": pc,
            "kv_pool": summary.get("kv_pool"),
            "kv_pool_util": summary.get("kv_pool_util"),
            "kv_req_gap_frac": summary.get("kv_req_gap_frac"),
            "kv_pool_bytes": summary.get("kv_pool_bytes"),
            "kv_pages": engine.num_pages,
            "kv_page_size": engine.page_size,
            "prefix_hit_frac": summary.get("prefix_hit_frac"),
            "pages_grown_total": summary.get("pages_grown_total"),
            "cow_copies": kvf.get("cow_copies"),
            "prefix_pages_shared": kvf.get("prefix_pages_shared"),
            # the fraction of reserved page-seconds never written,
            # restated in pool bytes at this (shared) page cost
            "wasted_pool_bytes": (
                round((1.0 - kvf["util"]) * summary["kv_pool_bytes"])
                if isinstance(kvf.get("util"), (int, float))
                and summary.get("kv_pool_bytes") else None),
            # the admission headline at the FIXED pool: how fast the
            # constrained pool drains the same offered trace
            "req_per_s": round(
                summary["completed"] / max(summary["wall_s"], 1e-9), 3),
            "wall_s": summary["wall_s"],
            "tokens_per_s": summary["tokens_per_s"],
            "p99_e2e_ms": summary["p99_e2e_ms"],
            "goodput": summary["goodput"],
            "completed": summary["completed"],
            "preempts": (summary.get("degrade") or {}).get("preempts"),
            "post_warmup_compiles": summary["post_warmup_compiles"],
            "metrics_dir": mdir,
        }

    ctl = arms["worst+off"]
    lzy = arms["lazy+off"]
    shr = arms["lazy+on"]
    util = shr.get("kv_pool_util")
    gap = shr.get("kv_req_gap_frac")
    verdict = {
        # round-22 carryover: the control still measures a real gap
        "gap_measured": (
            isinstance(ctl.get("kv_pool_util"), (int, float))
            and ctl["kv_pool_util"] < 1.0
            and isinstance(ctl.get("kv_req_gap_frac"), (int, float))
            and ctl["kv_req_gap_frac"] > 0.0),
        "control_kv_pool_util": ctl.get("kv_pool_util"),
        "control_req_gap_frac": ctl.get("kv_req_gap_frac"),
        # the round-25 acceptance: same pool bytes, more admitted req/s
        "lazy_prefix_beats_control_req_per_s": (
            shr["req_per_s"] > ctl["req_per_s"]),
        "same_pool_bytes_across_arms": (
            len({a["kv_pool_bytes"] for a in arms.values()}) == 1),
        "lazy_prefix_kv_pool_util": util,
        "lazy_prefix_req_gap_frac": gap,
        "util_above_waste_line": (
            isinstance(util, (int, float)) and util > 0.55),
        "gap_below_r22_waste": (
            isinstance(gap, (int, float)) and gap < 0.382),
        "prefix_hit_frac": shr.get("prefix_hit_frac"),
        "pages_grown_total": lzy.get("pages_grown_total"),
        "cow_copies": shr.get("cow_copies"),
        # allocation tricks never change tokens: both lazy arms decode
        # the exact streams of the worst-case control
        "lazy_token_parity": tokens["lazy+off"] == tokens["worst+off"],
        "prefix_token_parity": tokens["lazy+on"] == tokens["worst+off"],
        "zero_post_warmup_compiles": all(
            a["post_warmup_compiles"] == 0 for a in arms.values()),
        "all_completed": all(a["completed"] == args.num_requests
                             for a in arms.values()),
    }
    manifest = obs_metrics.manifest_subset(
        obs_metrics.run_manifest(cfg=cfg))
    return {
        "metric": f"{args.model}_kv_pool_util",
        "value": util,
        "unit": "written_page_s/reserved_page_s",
        "vs_baseline": (
            round(util / max(ctl.get("kv_pool_util") or 1e-9, 1e-9), 3)
            if isinstance(util, (int, float)) else None),
        "extra": {
            "workload": "serve",
            "mode": "kv",
            "model": args.model,
            "arrival_rate": args.arrival_rate,
            "num_requests": args.num_requests,
            "max_prompt_len": args.max_prompt_len,
            "max_output_len": args.max_output_len,
            "kv_page_size": args.kv_page_size,
            "kv_pages": kv_pages,
            "decode_attention": "gather",
            "quant": "off",
            # headline arm = lazy+prefix (what the regress gate tracks)
            "kv_reserve": "lazy",
            "prefix_cache": "on",
            "kv_pool_util": util,
            "kv_req_gap_frac": gap,
            "prefix_hit_frac": shr.get("prefix_hit_frac"),
            "pages_grown_total": shr.get("pages_grown_total"),
            "goodput": shr["goodput"],
            "tokens_per_s": shr["tokens_per_s"],
            "arms": arms,
            "verdict": verdict,
        },
        "manifest": manifest,
    }


#: the round-23 fixed fault schedule: three poisoned requests spread
#: through the trace; the pool squeeze lands just after traffic starts
#: and is sized at run time so the squeezed pool still fits two
#: residents (a deeper squeeze would stall the no-degradation control
#: outright and the A/B would measure a crash, not a policy)
FAULT_NAN_RIDS = (5, 11, 23)
FAULT_SQUEEZE_T = 0.05


def run_faults_ab(args) -> dict:
    """The round-23 overload-survival A/B: ONE warmed engine, one
    seeded overload trace (arrival rate far above service capacity),
    one fixed fault schedule (NaN-poisoned requests + a sticky KV-pool
    squeeze), TWO policy arms —

    - ``control``: no degradation (``--shed=off``, ``--kv_preempt=off``)
      — the pre-round-23 engine: poisoned requests serve garbage,
      squeezed admission head-of-line blocks, every request is served
      arbitrarily late.
    - ``degrade``: ``--shed=deadline`` + ``--kv_preempt=on`` — expired
      and hopeless requests are shed with a cause, poisoned requests
      are quarantined, pool pressure preempts/requeues instead of
      blocking.

    The headline is served-within-SLO goodput: the fraction of the
    offered trace answered CORRECTLY (known-poisoned rids never count —
    the control serves them, but serves NaN garbage) within
    ``--deadline_ms``.  Runs under VirtualClock so the artifact is a
    deterministic property of the policies, not of host load."""
    from tpu_hc_bench.obs import metrics as obs_metrics
    from tpu_hc_bench.serve import cli as serve_cli
    from tpu_hc_bench.serve import engine as engine_mod
    from tpu_hc_bench.serve import faults as faults_mod

    log = lambda m: print(m, file=sys.stderr, flush=True)  # noqa: E731
    import tempfile

    root = args.metrics_root or tempfile.mkdtemp(prefix="bench_faults_")
    cfg = _build_cfg(args, slo_e2e_ms=args.deadline_ms)
    engine, requests = serve_cli.build_engine_and_requests(cfg, log)
    squeeze = max(0, engine.num_pages - 2 * engine.table_width)
    spec = ",".join(
        [f"nan_logits@{r}" for r in FAULT_NAN_RIDS
         if r < args.num_requests]
        + ([f"pool_squeeze@{FAULT_SQUEEZE_T}:{squeeze}"]
           if squeeze else []))
    vclock = {"prefill": 0.004, "decode": 0.003, "classify": 0.002}

    arm_policies = {
        "control": dict(shed="off", kv_preempt="off"),
        "degrade": dict(shed="deadline", kv_preempt="on"),
    }
    arms: dict[str, dict] = {}
    for arm, policy in arm_policies.items():
        mdir = os.path.join(root, arm)
        log(f"--- faults arm: {arm} ({spec}) ---")
        writer = serve_cli.serve_writer(cfg, mdir)
        fleet = None
        try:
            summary = engine.run(
                requests, batching="continuous", writer=writer,
                clock=engine_mod.VirtualClock(vclock),
                faults=faults_mod.parse_serve_plan(spec),
                deadline_ms=args.deadline_ms, **policy)
        finally:
            writer.close()
        served_ok = 0
        counts = {"request": 0, "shed": 0, "quarantine": 0}
        with open(os.path.join(mdir, "metrics.jsonl")) as f:
            for line in f:
                rec = json.loads(line)
                kind = rec.get("kind")
                if kind in counts:
                    counts[kind] += 1
                if (kind == "request"
                        and rec["id"] not in FAULT_NAN_RIDS
                        and rec["e2e_ms"] <= args.deadline_ms):
                    served_ok += 1
        arms[arm] = {
            **policy,
            "served_within_slo": round(
                served_ok / max(1, args.num_requests), 4),
            "completed": summary["completed"],
            "shed": counts["shed"],
            "quarantined": counts["quarantine"],
            "degrade": summary.get("degrade"),
            "shed_frac": summary.get("shed_frac"),
            "p99_e2e_ms": summary.get("p99_e2e_ms"),
            "goodput": summary["goodput"],
            "slo": summary.get("slo"),
            "post_warmup_compiles": summary["post_warmup_compiles"],
            "metrics_dir": mdir,
        }

    ctl, deg = arms["control"], arms["degrade"]
    verdict = {
        # the acceptance property: under the SAME overload + faults,
        # degrading serves MORE of the trace correctly within SLO than
        # heroically serving everything late (and some of it poisoned)
        "degrade_beats_control_goodput": (
            deg["served_within_slo"] > ctl["served_within_slo"]),
        "served_within_slo_delta": round(
            deg["served_within_slo"] - ctl["served_within_slo"], 4),
        # every degraded exit carries a cause (folded by obs summarize)
        "sheds_caused": deg["degrade"]["shed"],
        "quarantined": deg["quarantined"],
        "preempts": deg["degrade"]["preempts"],
        "zero_post_warmup_compiles": (
            ctl["post_warmup_compiles"] == 0
            and deg["post_warmup_compiles"] == 0),
        "compile_record": engine.compile_record,
    }
    manifest = obs_metrics.manifest_subset(
        obs_metrics.run_manifest(cfg=cfg))
    return {
        "metric": f"{cfg.model}_serve_faults_goodput",
        "value": deg["served_within_slo"],
        "unit": "served_within_slo_frac",
        "vs_baseline": round(
            deg["served_within_slo"]
            / max(ctl["served_within_slo"], 1e-9), 3),
        "extra": {
            "workload": "serve",
            "mode": "faults",
            "model": cfg.model,
            "arrival_rate": cfg.arrival_rate,
            "num_requests": args.num_requests,
            "deadline_ms": args.deadline_ms,
            "fault_spec": spec,
            "decode_attention": cfg.decode_attention,
            "quant": cfg.quant,
            "goodput": deg["goodput"],
            # the regress gate's direction-aware degradation metric
            "shed_frac": deg["shed_frac"],
            "arms": arms,
            "verdict": verdict,
        },
        "manifest": manifest,
    }


def run_signals_ab(args) -> dict:
    """The round-24 sensing A/B: ONE warmed engine, TWO traces —

    - ``control``: the default offered load (``--arrival_rate``), no
      faults.  The health-signal engine must stay silent end to end:
      any fire here is a false positive and fails the verdict.
    - ``overload``: the same request shapes at ``--overload_rate``
      (far above service capacity) plus the round-23 sticky KV-pool
      squeeze landing at t=``FAULT_SQUEEZE_T``.  SUSTAINED_OVERLOAD
      and KV_PRESSURE must both fire, and KV_PRESSURE's first fire
      must land at or after the squeeze's injection instant.

    Degradation policy is pinned OFF on both arms — this A/B measures
    the autoscaler's SENSING half (does the engine see trouble, with
    hysteresis, without crying wolf), not the actuation the policies
    already cover in ``--mode faults``.  Both arms also check the
    merged-sketch p99 against the exact stored-sample tail read back
    from the full per-request stream: the sketch answer must land
    inside the order-statistic bracket widened by the sketch's own
    relative-error guarantee.  VirtualClock keeps the artifact a
    deterministic property of the traces."""
    import tempfile

    from tpu_hc_bench.obs import metrics as obs_metrics
    from tpu_hc_bench.obs import signals as signals_mod
    from tpu_hc_bench.obs import sketch as sketch_mod
    from tpu_hc_bench.serve import arrivals
    from tpu_hc_bench.serve import cli as serve_cli
    from tpu_hc_bench.serve import engine as engine_mod
    from tpu_hc_bench.serve import faults as faults_mod
    from tpu_hc_bench.serve import slo as slo_mod

    log = lambda m: print(m, file=sys.stderr, flush=True)  # noqa: E731
    root = args.metrics_root or tempfile.mkdtemp(prefix="bench_signals_")
    cfg = _build_cfg(args, slo_e2e_ms=args.deadline_ms)
    engine, requests = serve_cli.build_engine_and_requests(cfg, log)
    vocab = engine.spec.vocab_size if engine.decode_mode else None
    ovl_cfg = _build_cfg(args, slo_e2e_ms=args.deadline_ms,
                         arrival_rate=args.overload_rate)
    ovl_requests = arrivals.build_requests(ovl_cfg, vocab)
    squeeze = max(0, engine.num_pages - 2 * engine.table_width)
    spec = (f"pool_squeeze@{FAULT_SQUEEZE_T}:{squeeze}"
            if squeeze else "")
    vclock = {"prefill": 0.004, "decode": 0.003, "classify": 0.002}

    arm_defs = {
        "control": (requests, None),
        "overload": (ovl_requests, spec or None),
    }
    arms: dict[str, dict] = {}
    for arm, (trace, fault_spec) in arm_defs.items():
        mdir = os.path.join(root, arm)
        log(f"--- signals arm: {arm}"
            + (f" ({fault_spec})" if fault_spec else "") + " ---")
        writer = serve_cli.serve_writer(cfg, mdir)
        try:
            summary = engine.run(
                trace, batching="continuous", writer=writer,
                clock=engine_mod.VirtualClock(vclock),
                faults=(faults_mod.parse_serve_plan(fault_spec)
                        if fault_spec else None),
                deadline_ms=args.deadline_ms, shed="off",
                kv_preempt="off")
        finally:
            writer.close()
        # exact stored-sample tail off the FULL per-request stream (the
        # summary's own fold rides the run-lifetime sketches; the raw
        # ring is bounded) — the sketch must land inside the exact
        # order-statistic bracket widened by its alpha guarantee
        e2e: list[float] = []
        with open(os.path.join(mdir, "metrics.jsonl")) as f:
            for line in f:
                rec = json.loads(line)
                if rec.get("kind") == "request":
                    e2e.append(float(rec["e2e_ms"]))
        e2e.sort()
        merged = summary.get("p99_merged_ms")
        alpha = sketch_mod.DEFAULT_ALPHA
        within = None
        exact_p99 = None
        if e2e:
            exact_p99 = slo_mod.percentile(e2e, 99)
            rank = 0.99 * (len(e2e) - 1)
            lo = e2e[int(rank)]
            hi = e2e[min(int(rank) + 1, len(e2e) - 1)]
            within = (merged is not None
                      and lo * (1.0 - alpha) - 1e-6 <= merged
                      <= hi * (1.0 + alpha) + 1e-6)
        events = signals_mod.read_signals(mdir)
        first_fire: dict[str, float] = {}
        for ev in events:
            if ev.get("state") == "fire":
                first_fire.setdefault(ev.get("signal"), ev.get("t"))
        arms[arm] = {
            "arrival_rate": (args.overload_rate if arm == "overload"
                             else cfg.arrival_rate),
            "fault_spec": fault_spec,
            "signals_fired": summary.get("signals_fired"),
            "signals_fired_total": summary.get("signals_fired_total"),
            "first_fire_t": first_fire,
            "signal_events": len(events),
            "p99_merged_ms": summary.get("p99_merged_ms"),
            "p99_exact_ms": (round(exact_p99, 3)
                             if exact_p99 is not None else None),
            "merged_vs_exact_pct": (
                round(100.0 * (merged - exact_p99) / max(exact_p99, 1e-9),
                      2)
                if merged is not None and exact_p99 else None),
            "merged_p99_within_bound": within,
            "sketch_windows": summary.get("sketch_windows"),
            "p99_e2e_ms": summary.get("p99_e2e_ms"),
            "goodput": summary["goodput"],
            "tokens_per_s": summary["tokens_per_s"],
            "completed": summary["completed"],
            "post_warmup_compiles": summary["post_warmup_compiles"],
            "metrics_dir": mdir,
        }

    ctl, ovl = arms["control"], arms["overload"]
    ovl_fired = ovl.get("signals_fired") or {}
    kv_onset = (ovl.get("first_fire_t") or {}).get("KV_PRESSURE")
    verdict = {
        # the sensing acceptance: the injected overload + pool squeeze
        # fire their signals, onset at/after injection, and the clean
        # arm never cries wolf
        "overload_fires_sustained_overload": (
            ovl_fired.get("SUSTAINED_OVERLOAD", 0) >= 1),
        "overload_fires_kv_pressure": (
            ovl_fired.get("KV_PRESSURE", 0) >= 1),
        "kv_onset_after_injection": (
            kv_onset is not None and kv_onset >= FAULT_SQUEEZE_T),
        "kv_pressure_onset_t": kv_onset,
        "control_zero_fires": ctl.get("signals_fired_total") == 0,
        "merged_p99_within_bound": bool(
            ctl.get("merged_p99_within_bound")
            and ovl.get("merged_p99_within_bound")),
        "zero_post_warmup_compiles": (
            ctl["post_warmup_compiles"] == 0
            and ovl["post_warmup_compiles"] == 0),
        "compile_record": engine.compile_record,
    }
    manifest = obs_metrics.manifest_subset(
        obs_metrics.run_manifest(cfg=cfg))
    return {
        "metric": f"{cfg.model}_serve_signal_sensing",
        "value": ovl.get("signals_fired_total"),
        "unit": "signals_fired",
        "vs_baseline": None,
        "extra": {
            "workload": "serve",
            "mode": "signals",
            "model": cfg.model,
            "arrival": cfg.arrival,
            "arrival_rate": cfg.arrival_rate,
            "overload_rate": args.overload_rate,
            "num_requests": args.num_requests,
            "deadline_ms": args.deadline_ms,
            "fault_spec": spec,
            "decode_attention": cfg.decode_attention,
            "quant": cfg.quant,
            # regress-gated: the HEALTHY arm's merged tail and fire
            # count — a drift in the clean config's p99 or ANY fire on
            # it flags (the abs floor is one fire)
            "p99_merged_ms": ctl.get("p99_merged_ms"),
            "latency_source": "sketch",
            "signals_fired": ctl.get("signals_fired"),
            "signals_fired_total": ctl.get("signals_fired_total"),
            "goodput": ctl["goodput"],
            "tokens_per_s": ctl["tokens_per_s"],
            "arms": arms,
            "verdict": verdict,
        },
        "manifest": manifest,
    }


def main() -> int:
    env = os.environ.get
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--model", default=env("BENCH_MODEL", "moe_tiny"))
    ap.add_argument("--arrival", default=env("BENCH_ARRIVAL", "poisson"))
    ap.add_argument("--arrival_rate", type=float,
                    default=float(env("BENCH_ARRIVAL_RATE", "16")))
    ap.add_argument("--num_requests", type=int,
                    default=int(env("BENCH_REQUESTS", "48")))
    ap.add_argument("--serve_buckets",
                    default=env("BENCH_SERVE_BUCKETS", "auto"))
    ap.add_argument("--max_in_flight", type=int,
                    default=int(env("BENCH_MAX_IN_FLIGHT", "8")))
    ap.add_argument("--kv_page_size", type=int, default=16)
    ap.add_argument("--max_prompt_len", type=int, default=32)
    ap.add_argument("--max_output_len", type=int, default=16)
    ap.add_argument("--mode", choices=["batching", "decode", "kv",
                                       "faults", "signals"],
                    default=env("BENCH_MODE", "batching"),
                    help="batching: continuous-vs-static on one warmed "
                         "engine; decode: gather-vs-paged-vs-int8 "
                         "kernel arms, one engine each; kv: the "
                         "round-25 allocation A/B — worst-case "
                         "reservation vs lazy growth vs lazy+COW "
                         "prefix cache on one engine at one fixed "
                         "pool, headline = lazy+prefix kv_pool_util; "
                         "faults: "
                         "the round-23 overload-survival A/B — "
                         "shedding+preemption vs no degradation under "
                         "one fault schedule, headline = served-"
                         "within-SLO goodput; signals: the round-24 "
                         "sensing A/B — injected overload + pool "
                         "squeeze must fire SUSTAINED_OVERLOAD and "
                         "KV_PRESSURE, the clean control arm must "
                         "fire nothing")
    ap.add_argument("--deadline_ms", type=float,
                    default=float(env("BENCH_DEADLINE_MS", "150")),
                    help="faults/signals modes: the per-request e2e "
                         "SLO (shed target in faults; the overload "
                         "signal's violation threshold in signals)")
    ap.add_argument("--overload_rate", type=float,
                    default=float(env("BENCH_OVERLOAD_RATE", "120")),
                    help="signals mode: the overload arm's arrival "
                         "rate (req/s, far above service capacity)")
    ap.add_argument("--decode_attention",
                    choices=["gather", "paged"],
                    default=env("BENCH_DECODE_ATTENTION", "gather"),
                    help="batching mode: the decode program both "
                         "scheduler arms run on")
    ap.add_argument("--quant", choices=["off", "int8_w", "int8_kv"],
                    default=env("BENCH_QUANT", "off"))
    ap.add_argument("--decode_block_pages", type=int,
                    default=int(env("BENCH_DECODE_BLOCK_PAGES", "0")))
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--compile_cache",
                    default=env("BENCH_COMPILE_CACHE") or None,
                    help="persistent compile cache dir — makes the "
                         "post_warmup_compiles=0 assertion a measured "
                         "cache-entry delta instead of a trivial 0")
    ap.add_argument("--metrics_root", default=None,
                    help="write per-arm metrics dirs here; compare with "
                         "`python -m tpu_hc_bench.obs diff "
                         "<root>/static <root>/continuous`")
    ap.add_argument("--json", default=None, metavar="OUT",
                    help="also write the comparison JSON here")
    args = ap.parse_args()

    result = {"decode": run_decode_ab, "kv": run_kv_ab,
              "faults": run_faults_ab,
              "signals": run_signals_ab}.get(args.mode, run_ab)(args)
    print(json.dumps(result, indent=1))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(result, f, indent=1)
        print(f"wrote {args.json}", file=sys.stderr)
    v = result["extra"]["verdict"]
    if args.mode == "decode":
        ok = (v["paged_temp_lt_gather"] and v["paged_token_parity"]
              and v["zero_post_warmup_compiles"] and v["all_completed"])
    elif args.mode == "kv":
        ok = (v["gap_measured"]
              and v["lazy_prefix_beats_control_req_per_s"]
              and v["same_pool_bytes_across_arms"]
              and v["util_above_waste_line"]
              and v["gap_below_r22_waste"]
              and v["lazy_token_parity"] and v["prefix_token_parity"]
              and v["zero_post_warmup_compiles"]
              and v["all_completed"])
    elif args.mode == "faults":
        ok = (v["degrade_beats_control_goodput"]
              and v["zero_post_warmup_compiles"])
    elif args.mode == "signals":
        ok = (v["overload_fires_sustained_overload"]
              and v["overload_fires_kv_pressure"]
              and v["kv_onset_after_injection"]
              and v["control_zero_fires"]
              and v["merged_p99_within_bound"]
              and v["zero_post_warmup_compiles"])
    else:
        ok = (v["continuous_beats_static_p99"]
              and v["continuous_beats_static_goodput"]
              and v["zero_post_warmup_compiles"])
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
