"""Serving-lane A/B benchmark: continuous vs static batching at a fixed
arrival rate.

The acceptance experiment of the round-16 serving subsystem
(``tpu_hc_bench.serve``): ONE warmed engine (every (batch, seqlen)
bucket AOT-compiled once, through ``--compile_cache`` when given), ONE
identical seeded request trace, TWO scheduler arms —

- ``static``: the classic control — collect a full batch, run it to
  completion, only then admit again; arrivals queue while stragglers
  finish.
- ``continuous``: Orca-style — admission and retirement per decode
  step; a retired request's slot is refilled at the very next step.

Both arms share the warmed AOT executables, so the A/B never pays a
second compile and ``post_warmup_compiles`` (compile-cache entry
deltas, the round-10 hit/miss mechanism) must stay 0 for BOTH arms.
Emits a BENCH-style JSON record: headline ``tokens_per_s`` of the
continuous arm, ``vs_baseline`` = continuous/static tokens/s, and
``p99_ms``/``goodput``/``tokens_per_s`` per arm in ``extra`` — plus an
``obs diff``-renderable pair of metrics dirs under ``--metrics_root``.

Env knobs (CI parity with bench.py):

- ``BENCH_MODEL`` (default moe_tiny), ``BENCH_ARRIVAL_RATE``,
  ``BENCH_SERVE_BUCKETS``, ``BENCH_REQUESTS``, ``BENCH_MAX_IN_FLIGHT``,
  ``BENCH_COMPILE_CACHE`` (a dir makes the zero-recompile assertion
  measured, not vacuous).

Usage:
  JAX_PLATFORMS=cpu python scripts/bench_serve.py \
      [--json OUT.json] [--metrics_root DIR]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, ".")


def run_ab(args) -> dict:
    from tpu_hc_bench import flags as flags_mod
    from tpu_hc_bench.obs import metrics as obs_metrics
    from tpu_hc_bench.serve import cli as serve_cli

    cfg = flags_mod.BenchmarkConfig(
        model=args.model,
        workload="serve",
        arrival=args.arrival,
        arrival_rate=args.arrival_rate,
        num_requests=args.num_requests,
        serve_buckets=args.serve_buckets,
        max_in_flight=args.max_in_flight,
        kv_page_size=args.kv_page_size,
        max_prompt_len=args.max_prompt_len,
        max_output_len=args.max_output_len,
        compile_cache=args.compile_cache,
        seed=args.seed,
    ).resolve()

    log = lambda m: print(m, file=sys.stderr, flush=True)  # noqa: E731
    engine, requests = serve_cli.build_engine_and_requests(cfg, log)

    arms: dict[str, dict] = {}
    for arm in ("static", "continuous"):
        mdir = None
        arm_cfg = cfg
        if args.metrics_root:
            mdir = os.path.join(args.metrics_root, arm)
            # per-arm manifest: obs diff renders the batching flip as
            # config drift next to the serve-metric delta rows
            arm_cfg = flags_mod.BenchmarkConfig(
                **{**cfg.__dict__,
                   "translations": {}, "batching": arm,
                   "explicit_flags": None, "tuned_config": None})
        log(f"--- arm: {arm} ---")
        summary = serve_cli.run_serve(
            engine, requests, serve_cli.serve_writer(arm_cfg, mdir),
            batching=arm)
        arms[arm] = {
            "tokens_per_s": summary["tokens_per_s"],
            "p99_e2e_ms": summary["p99_e2e_ms"],
            "p99_ttft_ms": summary["p99_ttft_ms"],
            "p50_e2e_ms": summary["p50_e2e_ms"],
            "goodput": summary["goodput"],
            "queue_depth_max": summary["queue_depth_max"],
            "wall_s": summary["wall_s"],
            "completed": summary["completed"],
            "post_warmup_compiles": summary["post_warmup_compiles"],
            "metrics_dir": mdir,
        }

    st, ct = arms["static"], arms["continuous"]
    verdict = {
        # the two acceptance properties: continuous beats static on the
        # p99 tail AND on goodput-under-load, at the same offered load
        "continuous_beats_static_p99": ct["p99_e2e_ms"] < st["p99_e2e_ms"],
        "continuous_beats_static_goodput": ct["goodput"] > st["goodput"],
        "p99_e2e_delta_pct": round(
            100.0 * (ct["p99_e2e_ms"] - st["p99_e2e_ms"])
            / max(st["p99_e2e_ms"], 1e-9), 1),
        "goodput_delta_pct": round(
            100.0 * (ct["goodput"] - st["goodput"])
            / max(st["goodput"], 1e-9), 1),
        "zero_post_warmup_compiles": (
            ct["post_warmup_compiles"] == 0
            and st["post_warmup_compiles"] == 0),
        "compile_cache": engine.cache_dir,
        "compile_record": engine.compile_record,
    }
    manifest = obs_metrics.manifest_subset(
        obs_metrics.run_manifest(cfg=cfg))
    return {
        "metric": f"{cfg.model}_serve_tokens_per_s",
        "value": ct["tokens_per_s"],
        "unit": "tokens/sec",
        # continuous over the classic static arm at the same load — the
        # serving analog of bench.py's vs-reference ratio
        "vs_baseline": round(
            ct["tokens_per_s"] / max(st["tokens_per_s"], 1e-9), 3),
        "extra": {
            "workload": "serve",
            "model": cfg.model,
            "arrival": cfg.arrival,
            "arrival_rate": cfg.arrival_rate,
            "num_requests": cfg.num_requests,
            "max_prompt_len": cfg.max_prompt_len,
            "max_output_len": cfg.max_output_len,
            "buckets": list(engine.batch_buckets),
            "max_in_flight": engine.cap,
            "kv_page_size": engine.page_size,
            "kv_pages": engine.num_pages,
            "p99_ms": ct["p99_e2e_ms"],
            "goodput": ct["goodput"],
            "tokens_per_s": ct["tokens_per_s"],
            "arms": arms,
            "verdict": verdict,
        },
        "manifest": manifest,
    }


def main() -> int:
    env = os.environ.get
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--model", default=env("BENCH_MODEL", "moe_tiny"))
    ap.add_argument("--arrival", default=env("BENCH_ARRIVAL", "poisson"))
    ap.add_argument("--arrival_rate", type=float,
                    default=float(env("BENCH_ARRIVAL_RATE", "16")))
    ap.add_argument("--num_requests", type=int,
                    default=int(env("BENCH_REQUESTS", "48")))
    ap.add_argument("--serve_buckets",
                    default=env("BENCH_SERVE_BUCKETS", "auto"))
    ap.add_argument("--max_in_flight", type=int,
                    default=int(env("BENCH_MAX_IN_FLIGHT", "8")))
    ap.add_argument("--kv_page_size", type=int, default=16)
    ap.add_argument("--max_prompt_len", type=int, default=32)
    ap.add_argument("--max_output_len", type=int, default=16)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--compile_cache",
                    default=env("BENCH_COMPILE_CACHE") or None,
                    help="persistent compile cache dir — makes the "
                         "post_warmup_compiles=0 assertion a measured "
                         "cache-entry delta instead of a trivial 0")
    ap.add_argument("--metrics_root", default=None,
                    help="write per-arm metrics dirs here; compare with "
                         "`python -m tpu_hc_bench.obs diff "
                         "<root>/static <root>/continuous`")
    ap.add_argument("--json", default=None, metavar="OUT",
                    help="also write the comparison JSON here")
    args = ap.parse_args()

    result = run_ab(args)
    print(json.dumps(result, indent=1))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(result, f, indent=1)
        print(f"wrote {args.json}", file=sys.stderr)
    v = result["extra"]["verdict"]
    ok = (v["continuous_beats_static_p99"]
          and v["continuous_beats_static_goodput"]
          and v["zero_post_warmup_compiles"])
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
