#!/usr/bin/env bash
# Provision a multi-host TPU pod slice — fills the role of the reference's
# EMPTY azure-scripts/create-az-vmss-cluster.sh + manual README Step 4
# (README.md:47): launch N nodes from one image.  On TPU the "image clone"
# is the pod slice itself: every host gets the identical runtime, replacing
# the reference's deprovision/generalize/image-create cycle
# (README.md:32-45) entirely.
#
#   usage: ./create-tpu-pod.sh <name> [zone] [accelerator-type] [version]
set -euo pipefail

NAME="${1:?usage: $0 <name> [zone] [accelerator-type] [runtime-version]}"
ZONE="${2:-us-central2-b}"
ACCEL="${3:-v5litepod-32}"     # BASELINE north star: v5e-32
VERSION="${4:-tpu-ubuntu2204-base}"

command -v gcloud >/dev/null || { echo "gcloud CLI required" >&2; exit 1; }

gcloud compute tpus tpu-vm create "$NAME" \
    --zone="$ZONE" \
    --accelerator-type="$ACCEL" \
    --version="$VERSION"

echo "pod created; prep all hosts with ./prep-cluster.sh $NAME $ZONE"
