#!/usr/bin/env bash
# Provision a single TPU-VM — fills the role of the reference's EMPTY
# azure-scripts/create-az-vm.sh + manual README Step 1 (README.md:10):
# the reference never automated node launch; this script does, for TPU.
#
#   usage: ./create-tpu-vm.sh <name> [zone] [accelerator-type] [version]
set -euo pipefail

NAME="${1:?usage: $0 <name> [zone] [accelerator-type] [runtime-version]}"
ZONE="${2:-us-central2-b}"
ACCEL="${3:-v5litepod-1}"
VERSION="${4:-tpu-ubuntu2204-base}"

command -v gcloud >/dev/null || { echo "gcloud CLI required" >&2; exit 1; }

gcloud compute tpus tpu-vm create "$NAME" \
    --zone="$ZONE" \
    --accelerator-type="$ACCEL" \
    --version="$VERSION"

echo "created; set it up with:"
echo "  gcloud compute tpus tpu-vm ssh $NAME --zone=$ZONE --command='git clone <this-repo> && cd tpu-hc-bench && ./scripts/setup/setup-tpu-vm.sh stable'"
