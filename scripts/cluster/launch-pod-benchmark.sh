#!/usr/bin/env bash
# Fan a benchmark run out to every host of a TPU pod — the mpirun role.
#
# The reference's launch is `mpirun -np N -hostfile ~/nodeips.txt … ` from
# the head node (run-tf-sing-ucx-openmpi.sh:99-109): one command, ranks
# spawned everywhere.  The TPU equivalent: run the same SPMD launcher on
# every pod host via the control plane's all-worker SSH; jax.distributed
# inside each process discovers rank/world from the TPU metadata.
#
#   usage: ./launch-pod-benchmark.sh <pod-name> <zone> <NUM_HOSTS> <WORKERS_PER_HOST> <batch_size> <fabric>
set -euo pipefail

POD="${1:?usage: $0 <pod> <zone> <num_hosts> <workers_per_host> <batch> <fabric>}"
ZONE="${2:?}"
NUM_HOSTS="${3:?}"
WORKERS="${4:?}"
BATCH="${5:?}"
FABRIC="${6:?}"

command -v gcloud >/dev/null || { echo "gcloud CLI required" >&2; exit 1; }

# Env forwarding — the `mpirun -x FOO` / `-genv` role
# (run-tf-sing-ucx-openmpi.sh:104-106): ship the head node's tuning env to
# every worker, and have each worker source the setenv registry
# (register_env.sh) before launching, restoring the host/container setenv
# symmetry of the reference (its launchers source /mnt/shared/setenv and
# forward HOROVOD_*/OMP_* through MPI).
FWD=""
for var in XLA_FLAGS LIBTPU_INIT_ARGS JAX_PLATFORMS TPU_HC_BENCH_SETENV \
           JAX_TRACEBACK_FILTERING MODEL NUM_WARMUP NUM_BATCHES DATA_DIR \
           EXTRA_FLAGS; do
    if [ -n "${!var:-}" ]; then
        FWD+="export $var=$(printf '%q' "${!var}"); "
    fi
done

gcloud compute tpus tpu-vm ssh "$POD" --zone="$ZONE" --worker=all \
    --command="$FWD source \${TPU_HC_BENCH_SETENV:-\$HOME/.tpu_hc_bench/setenv} 2>/dev/null; cd tpu-hc-bench && ./scripts/run-tpu-ici.sh $NUM_HOSTS $WORKERS $BATCH $FABRIC"
