#!/usr/bin/env bash
# Cluster prep for a TPU pod — the counterpart of azure-scripts/
# prep-cluster.sh + setup-pwdless-ssh.sh (README Step 5, README.md:50-60).
#
# The reference needed: nmap subnet sweep for discovery, sshpass all-to-all
# key mesh, per-node IB port checks, IPoIB bring-up, and stopping the Azure
# agent (prep-cluster.sh:20-29).  A TPU pod's control plane already
# provides discovery and all-host SSH (`--worker=all`), and libtpu owns the
# fabric, so prep reduces to: fan software out to every host, write the
# nodeips.txt hostfile contract (setup-pwdless-ssh.sh:32), and run the
# per-host fabric/stack sanity check (ibv_devinfo analog).
#
#   usage: ./prep-cluster.sh <pod-name> [zone] [repo-url]
set -euo pipefail

POD="${1:?usage: $0 <pod-name> [zone] [repo-url]}"
ZONE="${2:-us-central2-b}"
REPO="${3:-}"

command -v gcloud >/dev/null || { echo "gcloud CLI required" >&2; exit 1; }

# 1. discovery -> hostfile contract (~/nodeips.txt, consumed by launchers
#    exactly as mpirun consumed it, run-tf-sing-ucx-openmpi.sh:25,101)
# capture BEFORE touching the hostfile: a control-plane failure must never
# leave a stale/empty nodeips.txt for a later launcher to consume
IPS=$(gcloud compute tpus tpu-vm describe "$POD" --zone="$ZONE" \
    --format='value(networkEndpoints[].ipAddress)') || {
    echo "ERROR: gcloud describe failed for pod '$POD' (zone $ZONE)" >&2
    exit 1
}
IPS=$(printf '%s\n' "$IPS" | tr ';' '\n' | sed '/^$/d')
if [ -z "$IPS" ]; then
    echo "ERROR: no host IPs discovered for pod '$POD' (zone $ZONE)" >&2
    exit 1
fi
printf '%s\n' "$IPS" > "$HOME/nodeips.txt"
N=$(printf '%s\n' "$IPS" | wc -l)
echo "discovered $N hosts -> ~/nodeips.txt"

# 2. software fan-out (replaces the O(N^2) sshpass key mesh: pod SSH is
#    already trusted)
if [ -n "$REPO" ]; then
    gcloud compute tpus tpu-vm ssh "$POD" --zone="$ZONE" --worker=all \
        --command="git clone $REPO tpu-hc-bench 2>/dev/null || (cd tpu-hc-bench && git pull); cd tpu-hc-bench && ./scripts/setup/setup-tpu-vm.sh stable"
fi

# 3. per-host sanity: device visible + stack importable (the
#    `pssh ibv_devinfo | grep state` analog, prep-cluster.sh:23)
gcloud compute tpus tpu-vm ssh "$POD" --zone="$ZONE" --worker=all \
    --command="python -m tpu_hc_bench.utils.sanity"

echo "cluster ready: run benchmarks with scripts/run-tpu-ici.sh via --worker=all"
