"""Round-4 A/B: deepspeech2 hoisted-GRU vs flax RNN(GRUCell) (VERDICT #3).

Round 3 recorded deepspeech2 at 6.4% MFU with the GRU input projections
computed INSIDE the scan (flax.linen.RNN/GRUCell) and called it "the
known RNN ceiling" — one step early, per the verdict: hoisting the
[T, B, 3H] input-gate matmuls out of the recurrence into one big MXU
matmul is the canonical RNN-on-accelerator optimization and had not been
tried.  models/deepspeech.HoistedGRU is that hoist (param-copy parity
with GRUCell pinned in tests/test_models.py); this experiment measures
it whole-model on hardware.

Protocol (env notes in memory): both arms build + compile ONCE in one
process, then timed segments interleave C V C V C V C (C = flax control,
V = hoisted variant) so chip drift cancels — each variant segment is
scored against the mean of its bracketing controls, and the reported
speedup is the MEDIAN of those ratios.  Sync is a value fetch
(jax.device_get), never block_until_ready, per the tunnel rules.

Usage: python scripts/exp_ds2_hoist.py [batch] [steps_per_segment] [reps]
           [control_impl] [variant_impl]
Round 4 follow-up: the same harness A/Bs any rnn_impl pair — e.g.
``... 16 60 3 hoisted bidi`` contests BiHoistedGRU (both directions in
one scan) against the hoisted two-scan default.
"""

from __future__ import annotations

import statistics
import sys
import time

import jax
import jax.numpy as jnp

from tpu_hc_bench import flags
from tpu_hc_bench.data.synthetic import SyntheticSpeech
from tpu_hc_bench.models import create_model
from tpu_hc_bench.models.deepspeech import max_label_for
from tpu_hc_bench.topology import build_mesh, discover_layout
from tpu_hc_bench.train import step as step_mod

BATCH = int(sys.argv[1]) if len(sys.argv) > 1 else 16
STEPS = int(sys.argv[2]) if len(sys.argv) > 2 else 60
REPS = int(sys.argv[3]) if len(sys.argv) > 3 else 3
CONTROL = sys.argv[4] if len(sys.argv) > 4 else "flax"
VARIANT = sys.argv[5] if len(sys.argv) > 5 else "hoisted"


def build_arm(rnn_impl: str, mesh, cfg, batch):
    model, spec = create_model("deepspeech2", dtype=jnp.bfloat16,
                               rnn_impl=rnn_impl)
    state = step_mod.make_train_state(model, cfg, batch)
    state = step_mod.replicate_state(state, mesh)
    train_step = step_mod.build_train_step(mesh, cfg, spec)
    dev_batch = step_mod.shard_batch(batch, mesh)
    rng = jax.random.PRNGKey(1)

    def segment(state, n):
        metrics = None
        for i in range(n):
            state, metrics = train_step(state, dev_batch,
                                        jax.random.fold_in(rng, i))
        return state, metrics

    return state, segment


def main():
    cfg = flags.BenchmarkConfig(model="deepspeech2",
                                batch_size=BATCH).resolve()
    layout = discover_layout()
    mesh = build_mesh(layout)
    frames, freq = 300, 161
    batch = SyntheticSpeech(BATCH * layout.total_workers, frames, freq,
                            max_label_for(frames), seed=0).batch()

    arms = {}
    for impl in (CONTROL, VARIANT):
        t0 = time.perf_counter()
        state, seg = build_arm(impl, mesh, cfg, batch)
        state, metrics = seg(state, 3)           # compile + warm
        loss = float(jax.device_get(metrics["loss"]))
        print(f"{impl}: compiled+warm in {time.perf_counter()-t0:.1f}s "
              f"loss={loss:.3f}", flush=True)
        arms[impl] = (state, seg)

    def timed(impl):
        state, seg = arms[impl]
        state, m0 = seg(state, 1)                # state is DONATED: carry it
        jax.device_get(m0["loss"])               # sync start
        t0 = time.perf_counter()
        state, m = seg(state, STEPS)
        jax.device_get(m["loss"])                # sync end (value fetch)
        dt = time.perf_counter() - t0
        arms[impl] = (state, seg)
        rate = STEPS * BATCH * layout.total_workers / dt
        print(f"  {impl:8s} {1e3*dt/STEPS:7.2f} ms/step "
              f"{rate:8.1f} ex/s", flush=True)
        return rate

    controls, variants = [], []
    controls.append(timed(CONTROL))
    for _ in range(REPS):
        variants.append(timed(VARIANT))
        controls.append(timed(CONTROL))
    ratios = [v / ((controls[i] + controls[i + 1]) / 2)
              for i, v in enumerate(variants)]
    print(f"controls ({CONTROL}): {[f'{c:.1f}' for c in controls]}")
    print(f"variants ({VARIANT}): {[f'{v:.1f}' for v in variants]}")
    print(f"ratios: {[f'{r:.3f}' for r in ratios]}")
    print(f"MEDIAN {VARIANT}/{CONTROL} speedup: "
          f"{statistics.median(ratios):.3f}x")
    print(f"{VARIANT} median rate: {statistics.median(variants):.1f} ex/s; "
          f"{CONTROL} median rate: {statistics.median(controls):.1f} ex/s")


if __name__ == "__main__":
    main()
