"""Prototype: Pallas fused matmul + BN-stats epilogue (round 2).

The ResNet-50 roofline analysis (BASELINE.md) showed the remaining MFU
headroom requires computing BN statistics in the conv's epilogue instead
of a separate pass over the conv output.  A 1x1 conv IS a matmul
([N*H*W, Cin] x [Cin, Cout]), so this experiment answers the viability
question with the smallest possible kernel: can a Pallas matmul that
accumulates per-channel sum/sumsq while its output tiles stream out match
XLA's matmul + stat-reduction fusion?

Shapes = ResNet-50 stage-1 conv3 (the profiled pathology): x [B*56*56, 64]
@ w [64, 256] in bf16, f32 stats.

Usage: python scripts/exp_fused_bnstats.py
"""

from __future__ import annotations

import functools
import time

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

M, K, N = 128 * 56 * 56, 64, 256
BM = 2048
ITERS = 30


def _kernel(x_ref, w_ref, y_ref, s1_ref, s2_ref, acc1, acc2):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        acc1[...] = jnp.zeros_like(acc1)
        acc2[...] = jnp.zeros_like(acc2)

    y = jnp.dot(x_ref[...], w_ref[...], preferred_element_type=jnp.float32)
    y_ref[...] = y.astype(y_ref.dtype)
    acc1[...] += y.sum(axis=0, keepdims=True)
    acc2[...] += (y * y).sum(axis=0, keepdims=True)

    @pl.when(i == pl.num_programs(0) - 1)
    def _flush():
        s1_ref[...] = acc1[...]
        s2_ref[...] = acc2[...]


@functools.partial(jax.jit, static_argnames=())
def fused(x, w):
    grid = (M // BM,)
    y, s1, s2 = pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((BM, K), lambda i: (i, 0)),
            pl.BlockSpec((K, N), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((BM, N), lambda i: (i, 0)),
            pl.BlockSpec((1, N), lambda i: (0, 0)),
            pl.BlockSpec((1, N), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((M, N), jnp.bfloat16),
            jax.ShapeDtypeStruct((1, N), jnp.float32),
            jax.ShapeDtypeStruct((1, N), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((1, N), jnp.float32),
            pltpu.VMEM((1, N), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
    )(x, w)
    return y, s1, s2


@jax.jit
def xla_ref(x, w):
    y = jnp.dot(x, w, preferred_element_type=jnp.float32)
    yb = y.astype(jnp.bfloat16)
    return yb, y.sum(0, keepdims=True), (y * y).sum(0, keepdims=True)


def bench(name, fn, x, w):
    # chain ITERS calls inside ONE compiled program with a data dependency
    # (the OSU-bench pattern): per-call Python dispatch through the tunnel
    # costs ~4 ms, which would swamp a sub-ms kernel
    # w2 consumes the full y each iteration (the BN-apply+next-conv role),
    # so neither arm can dead-code the y output; both pay the same
    # consumer cost and the arm delta isolates the stats-fusion question
    w2 = jnp.full((N, K), 1e-6, jnp.bfloat16)

    @jax.jit
    def chained(x, w):
        def body(_, carry):
            xc, s1_acc = carry
            y, s1, s2 = fn(xc, w)
            xc = xc + jnp.dot(y, w2) * jnp.bfloat16(1e-6)
            return xc, s1_acc + s1 + s2
        return jax.lax.fori_loop(0, ITERS, body,
                                 (x, jnp.zeros((1, N), jnp.float32)))

    out = fn(x, w)               # correctness outputs (single call)
    jax.device_get(out[1])
    r = chained(x, w)
    jax.device_get(r[1])         # warm/compile
    t0 = time.perf_counter()
    r = chained(x, w)
    jax.device_get(r[1])
    dt = (time.perf_counter() - t0) / ITERS
    # per-iteration work INCLUDING the shared consumer matmul (same-FLOP
    # y @ w2): absolutes are then honest per-arm; the fused/xla ratio is
    # still the experiment's signal
    flops = 2 * 2 * M * K * N
    bytes_ = 2 * (M * K * 2) + K * N * 4 + 2 * (M * N * 2)
    print(f"{name:12s} {1e3 * dt:7.3f} ms  {flops / dt / 1e12:6.2f} TF/s  "
          f"{bytes_ / dt / 1e9:6.1f} GB/s  (incl. consumer matmul)",
          flush=True)
    return out, dt


def main():
    kx = jax.random.PRNGKey(0)
    x = jax.random.normal(kx, (M, K), jnp.bfloat16)
    w = jax.random.normal(jax.random.fold_in(kx, 1), (K, N), jnp.bfloat16)
    (y_r, s1_r, s2_r), t_x = bench("xla", xla_ref, x, w)
    try:
        (y_f, s1_f, s2_f), t_f = bench("pallas_fused", fused, x, w)
    except Exception as e:
        print(f"pallas_fused failed: {type(e).__name__}: {str(e)[:200]}")
        return
    import numpy as np

    np.testing.assert_allclose(np.asarray(s1_f), np.asarray(s1_r),
                               rtol=2e-2, atol=2.0)
    np.testing.assert_allclose(np.asarray(s2_f), np.asarray(s2_r),
                               rtol=2e-2, atol=2.0)
    np.testing.assert_allclose(
        np.asarray(y_f, np.float32), np.asarray(y_r, np.float32),
        rtol=2e-2, atol=1e-1)
    print(f"numerics ok; fused/xla = {t_f / t_x:.3f}x "
          f"({'WIN' if t_f < t_x else 'no win'})")


if __name__ == "__main__":
    main()
