"""Round 3: REAL-conv fused bottleneck-segment A/B (VERDICT #1).

Round 2's matmul-proxy (`exp_fused_bnstats.py`) showed XLA already fuses
BN-STAT reductions into a matmul's output stream — but it could not
answer the conv question: the roofline's remaining headroom is the
BN-APPLY + relu pass between convs (normalize the producer's raw output
in the consumer's prologue), and convs have different XLA fusion behavior
than ``dot``.

This experiment builds the real thing for the ResNet-50 stage-1 conv2
segment (the profiled pathology):

    y1_raw [B, 56, 56, 64] (pre-BN conv1 output, bf16, in HBM)
    xn     = relu(y1_raw * a + b)     # BN-apply folded to scale/shift
    y2     = conv3x3(xn, w)           # SAME, NHWC, bf16 in / f32 acc
    s1, s2 = y2.sum((0,1,2)), (y2*y2).sum((0,1,2))   # next BN's stats

Arms (identical math, chained ITERS deep inside one jit so the ~4 ms
tunnel dispatch cost amortizes; sync via device_get per the env notes):

  xla          lax.conv_general_dilated with the normalize+relu as a
               producer and the stat reductions as consumers — XLA fuses
               whatever it can.
  pallas_fused one kernel per image: prologue normalizes into a padded
               VMEM scratch (the halo), 9 shifted [3136,64]x[64,64] MXU
               taps accumulate in f32, epilogue streams y2 out while
               accumulating per-channel sum/sumsq across the grid.
  xla_conv     conv alone (no BN/relu/stats) — the conv compute floor.

If pallas_fused beats xla by >~15% the fused-bottleneck integration is
worth building; if it matches, XLA is already at the fused bound for the
conv pattern too and the round-2 conclusion extends to convs — either way
this closes VERDICT round-3 item #1's measurement demand.

Usage: python scripts/exp_fused_conv.py [B] [H] [C]
"""

from __future__ import annotations

import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

B = int(sys.argv[1]) if len(sys.argv) > 1 else 128
H = int(sys.argv[2]) if len(sys.argv) > 2 else 56
C = int(sys.argv[3]) if len(sys.argv) > 3 else 64
ITERS = 20


def _kernel(x_ref, w_ref, a_ref, b_ref, y_ref, s1_ref, s2_ref,
            xn_ref, sacc1, sacc2):
    """One image per program: prologue BN-apply+relu -> 9-tap conv ->
    epilogue stats."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        sacc1[...] = jnp.zeros_like(sacc1)
        sacc2[...] = jnp.zeros_like(sacc2)

    # --- prologue: normalize + relu into the padded (halo) scratch ---
    x = x_ref[0].astype(jnp.float32)                       # [H, H, C]
    xn = jnp.maximum(x * a_ref[...] + b_ref[...], 0.0)
    xn_ref[...] = jnp.zeros_like(xn_ref)                   # zero halo
    xn_ref[1:H + 1, 1:H + 1, :] = xn.astype(xn_ref.dtype)

    # --- 9 shifted MXU taps, f32 accumulation ---
    acc = jnp.zeros((H * H, C), jnp.float32)
    for dh in range(3):
        for dw in range(3):
            patch = xn_ref[dh:dh + H, dw:dw + H, :].reshape(H * H, C)
            acc += jnp.dot(patch, w_ref[dh, dw],
                           preferred_element_type=jnp.float32)

    # --- epilogue: stream out + accumulate next-BN stats ---
    y_ref[...] = acc.reshape(1, H, H, C).astype(y_ref.dtype)
    sacc1[...] += acc.sum(axis=0, keepdims=True)
    sacc2[...] += (acc * acc).sum(axis=0, keepdims=True)

    @pl.when(i == pl.num_programs(0) - 1)
    def _flush():
        s1_ref[...] = sacc1[...]
        s2_ref[...] = sacc2[...]


@jax.jit
def pallas_fused(x, w, a, b):
    y, s1, s2 = pl.pallas_call(
        _kernel,
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, H, H, C), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((3, 3, C, C), lambda i: (0, 0, 0, 0)),
            pl.BlockSpec((1, C), lambda i: (0, 0)),
            pl.BlockSpec((1, C), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, H, H, C), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((1, C), lambda i: (0, 0)),
            pl.BlockSpec((1, C), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, H, C), jnp.bfloat16),
            jax.ShapeDtypeStruct((1, C), jnp.float32),
            jax.ShapeDtypeStruct((1, C), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((H + 2, H + 2, C), jnp.bfloat16),
            pltpu.VMEM((1, C), jnp.float32),
            pltpu.VMEM((1, C), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
    )(x, w, a, b)
    return y, s1, s2


def _xla_math(x, w, a, b):
    xn = jnp.maximum(x.astype(jnp.float32) * a[0] + b[0], 0.0)
    y = jax.lax.conv_general_dilated(
        xn.astype(jnp.bfloat16), w, (1, 1), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.float32,
    )
    s1 = y.sum((0, 1, 2))[None]
    s2 = (y * y).sum((0, 1, 2))[None]
    return y.astype(jnp.bfloat16), s1, s2


xla_ref = jax.jit(_xla_math)


@jax.jit
def xla_conv_only(x, w, a, b):
    del a, b
    y = jax.lax.conv_general_dilated(
        x, w, (1, 1), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.float32,
    )
    z = jnp.zeros((1, C), jnp.float32)
    return y.astype(jnp.bfloat16), z, z


def bench(name, fn, x, w, a, b):
    """Chained timing: each iteration's input depends on the previous
    output (no overlap-cheating), one jit, value-fetch sync (tunnel)."""
    @jax.jit
    def chained(x, w, a, b):
        def body(_, carry):
            xc, s_acc = carry
            y, s1, s2 = fn(xc, w, a, b)
            # feed y back at ~zero magnitude: keeps y + stats live
            xc = xc + y * jnp.bfloat16(1e-6)
            return xc, s_acc + s1 + s2
        return jax.lax.fori_loop(
            0, ITERS, body, (x, jnp.zeros((1, C), jnp.float32)))

    out = fn(x, w, a, b)
    jax.device_get(out[1])
    r = chained(x, w, a, b)
    jax.device_get(r[1])                     # warm
    t0 = time.perf_counter()
    r = chained(x, w, a, b)
    jax.device_get(r[1])
    dt = (time.perf_counter() - t0) / ITERS
    flops = 2 * B * H * H * C * C * 9
    io_bytes = 2 * (B * H * H * C * 2)       # read x + write y, bf16
    print(f"{name:14s} {1e3 * dt:7.3f} ms  {flops / dt / 1e12:6.2f} TF/s  "
          f"io {io_bytes / dt / 1e9:6.1f} GB/s", flush=True)
    return out, dt


def main():
    k = jax.random.PRNGKey(0)
    x = jax.random.normal(k, (B, H, H, C), jnp.bfloat16)
    w = (jax.random.normal(jax.random.fold_in(k, 1), (3, 3, C, C),
                           jnp.bfloat16) * 0.05)
    a = jnp.abs(jax.random.normal(jax.random.fold_in(k, 2), (1, C),
                                  jnp.float32)) * 0.5 + 0.5
    b = jax.random.normal(jax.random.fold_in(k, 3), (1, C),
                          jnp.float32) * 0.1
    print(f"segment: [{B},{H},{H},{C}] -> 3x3x{C} (SAME) + BN-apply/relu "
          f"prologue + stats epilogue, ITERS={ITERS}")
    (y_r, s1_r, s2_r), t_x = bench("xla", xla_ref, x, w, a, b)
    bench("xla_conv_only", xla_conv_only, x, w, a, b)
    try:
        (y_f, s1_f, s2_f), t_f = bench("pallas_fused", pallas_fused,
                                       x, w, a, b)
    except Exception as e:
        print(f"pallas_fused failed: {type(e).__name__}: {str(e)[:300]}")
        return
    np.testing.assert_allclose(np.asarray(s1_f), np.asarray(s1_r),
                               rtol=2e-2, atol=2.0)
    np.testing.assert_allclose(np.asarray(s2_f), np.asarray(s2_r),
                               rtol=2e-2, atol=4.0)
    np.testing.assert_allclose(
        np.asarray(y_f[:2], np.float32), np.asarray(y_r[:2], np.float32),
        rtol=5e-2, atol=1e-1)
    print(f"numerics ok; fused/xla = {t_f / t_x:.3f}x "
          f"({'WIN' if t_f < 0.87 * t_x else 'no win'})")


if __name__ == "__main__":
    main()
