"""gpt2 / ViT MFU ablations (round 2, VERDICT weak #6).

Round-1 sweep: gpt2 dense 26.1% / flash 38.6%, vit_b16 31.3% — ~15 MFU
points below same-math siblings (bert_base 46.0%, llama_1b 51.4%).  This
harness isolates where the time goes by ablation on the real chip:
attention impl, fused xent, remat, batch size, forward-only split.

Usage: python scripts/exp_gpt_vit.py [exp ...]
  exps: gpt2_flash gpt2_dense gpt2_fwd gpt2_xent gpt2_remat
        vit64 vit128 vit256 vit128_remat bert_base llama_1b
"""

from __future__ import annotations

import sys
import time

import jax
import jax.numpy as jnp

sys.path.insert(0, ".")

from tpu_hc_bench import flags
from tpu_hc_bench.data.synthetic import SyntheticImages, SyntheticTokens
from tpu_hc_bench.models import create_model
from tpu_hc_bench.train import step as step_mod
from tpu_hc_bench.topology import build_mesh, discover_layout

PEAK = 197e12
WARMUP, TIMED = 8, 20


def bench(name, model_name, batch, *, attention_impl="dense",
          fused_xent=False, remat=False, forward_only=False, seq_len=None):
    cfg = flags.BenchmarkConfig(
        model=model_name, batch_size=batch, attention_impl=attention_impl,
        fused_xent=fused_xent, gradient_checkpointing=remat,
        forward_only=forward_only, seq_len=seq_len,
    ).resolve()
    layout = discover_layout()
    mesh = build_mesh(layout)
    model, spec = create_model(
        model_name, dtype=jnp.bfloat16, attention_impl=cfg.attention_impl,
        seq_len=seq_len, gradient_checkpointing=remat)
    if spec.is_text:
        raw = SyntheticTokens(batch, spec.input_shape[0],
                              vocab_size=spec.vocab_size,
                              causal_lm=spec.causal_lm).batch()
    else:
        raw = SyntheticImages(batch, spec.input_shape).batch()
    state = step_mod.make_train_state(model, cfg, raw)
    state = step_mod.replicate_state(state, mesh)
    train_step = step_mod.build_train_step(mesh, cfg, spec)
    dev_batch = step_mod.shard_batch(raw, mesh)
    rng = jax.random.PRNGKey(0)
    for _ in range(WARMUP):
        state, metrics = train_step(state, dev_batch, rng)
    jax.device_get(metrics["loss"])     # tunnel-safe sync
    t0 = time.perf_counter()
    for _ in range(TIMED):
        state, metrics = train_step(state, dev_batch, rng)
    jax.device_get(metrics["loss"])
    dt = (time.perf_counter() - t0) / TIMED
    rate = batch / dt
    mult = 1.0 if forward_only else 3.0
    mfu = mult * spec.flops_per_example * rate / PEAK
    print(f"{name:16s} {1e3 * dt:8.2f} ms  {rate:8.2f} ex/s  "
          f"MFU {100 * mfu:5.1f}%", flush=True)


EXPS = {
    "gpt2_flash": lambda: bench("gpt2_flash", "gpt2", 8,
                                attention_impl="flash"),
    "gpt2_dense": lambda: bench("gpt2_dense", "gpt2", 8),
    "gpt2_fwd": lambda: bench("gpt2_fwd", "gpt2", 8,
                              attention_impl="flash", forward_only=True),
    "gpt2_xent": lambda: bench("gpt2_xent", "gpt2", 8,
                               attention_impl="flash", fused_xent=True),
    "gpt2_remat": lambda: bench("gpt2_remat", "gpt2", 16,
                                attention_impl="flash", remat=True),
    "gpt2_bs16": lambda: bench("gpt2_bs16", "gpt2", 16,
                               attention_impl="flash"),
    "gpt2_bs32": lambda: bench("gpt2_bs32", "gpt2", 32,
                               attention_impl="flash", remat=True),
    "vit64": lambda: bench("vit64", "vit_b16", 64),
    "vit128": lambda: bench("vit128", "vit_b16", 128),
    "vit256": lambda: bench("vit256", "vit_b16", 256),
    "vit128_remat": lambda: bench("vit128_remat", "vit_b16", 128,
                                  remat=True),
    "vit256_remat": lambda: bench("vit256_remat", "vit_b16", 256,
                                  remat=True),
    "vit128_fwd": lambda: bench("vit128_fwd", "vit_b16", 128,
                                forward_only=True),
}


def main():
    names = sys.argv[1:] or list(EXPS)
    for n in names:
        EXPS[n]()


if __name__ == "__main__":
    main()
