"""Count cross-process collectives in the world=2 compiled step (round 5).

Closes the round-4 scaling-table footnote (BASELINE.md "Reading the
table honestly" §2): resnet20_cifar pays 385 ms of boundary cost at
world=2 where bert_tiny pays 55 ms despite shipping ~16x MORE gradient
bytes — asserted to be "the compiled conv graph itself, not the
gradient tree; not attributed further on this box".  This script lowers
the SAME explicit-psum train step both scaling-table members run, for a
size-2 data mesh, and counts the collective ops in the optimized HLO.
A 2-virtual-device single-process mesh compiles the identical program
the two-process world=2 run executes (same mesh shape, same partitioner
input), so the crossing counts need no hardware and no second process.

Round 6: the counting moved into ``tpu_hc_bench.analysis.hlo`` and got
correct (ADVICE r5): the old whole-text regex also matched operand
references (every consumer of %all-reduce.N re-mentions the name) and
the ``-done`` halves of async pairs, inflating absolute counts; the
parser counts *definition sites* only and folds ``-start``/``-done``
into one op.  This script is now a thin wrapper — the same counts for
any member come from::

    JAX_PLATFORMS=cpu python -m tpu_hc_bench.analysis --model <name>

Usage: JAX_PLATFORMS=cpu python scripts/exp_hlo_collectives_r05.py
"""

from __future__ import annotations

import sys

sys.path.insert(0, ".")

import tpu_hc_bench  # noqa: F401, E402  (JAX version shims before config)
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 2)

from tpu_hc_bench.analysis import hlo  # noqa: E402


def count_collectives(model_name: str, batch: int) -> dict[str, int]:
    text = hlo.lower_world_step_hlo(model_name, batch=batch, world=2)
    return hlo.collective_counts(text)


def main() -> int:
    # the literal scaling-table members at their scaling-table batches
    # (scripts/scaling_table.py: resnet20_cifar bs=64, bert_tiny bs=32)
    for name, bs in (("resnet20_cifar", 64), ("bert_tiny", 32)):
        counts = count_collectives(name, bs)
        total = sum(counts.values())
        print(f"{name} bs={bs} world=2 optimized-HLO collectives "
              f"(definition sites, async pairs folded): {total}  {counts}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
