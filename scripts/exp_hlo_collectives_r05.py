"""Count cross-process collectives in the world=2 compiled step (round 5).

Closes the round-4 scaling-table footnote (BASELINE.md "Reading the
table honestly" §2): resnet20_cifar pays 385 ms of boundary cost at
world=2 where bert_tiny pays 55 ms despite shipping ~16x MORE gradient
bytes — asserted to be "the compiled conv graph itself, not the
gradient tree; not attributed further on this box".  This script lowers
the SAME explicit-psum train step both scaling-table members run, for a
size-2 data mesh, and counts the collective ops in the optimized HLO.
A 2-virtual-device single-process mesh compiles the identical program
the two-process world=2 run executes (same mesh shape, same partitioner
input), so the crossing counts need no hardware and no second process.

Usage: JAX_PLATFORMS=cpu python scripts/exp_hlo_collectives_r05.py
"""

from __future__ import annotations

import re
import sys

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 2)

sys.path.insert(0, ".")

import jax.numpy as jnp  # noqa: E402

from tpu_hc_bench import flags  # noqa: E402
from tpu_hc_bench.data.synthetic import SyntheticImages, SyntheticTokens  # noqa: E402
from tpu_hc_bench.models import create_model, get_model_spec  # noqa: E402
from tpu_hc_bench.topology import build_mesh, compute_layout  # noqa: E402
from tpu_hc_bench.train import step as step_mod  # noqa: E402

COLLECTIVE_RE = re.compile(
    r"\b(all-reduce(?:-start)?|all-gather(?:-start)?|"
    r"reduce-scatter|collective-permute(?:-start)?|all-to-all)\b")


def count_collectives(model_name: str, batch: int) -> dict[str, int]:
    cfg = flags.BenchmarkConfig(model=model_name, batch_size=batch).resolve()
    layout = compute_layout(num_hosts=1, workers_per_host=2,
                            chips_per_host=2)
    mesh = build_mesh(layout)
    spec = get_model_spec(model_name)
    model, spec = create_model(model_name, dtype=jnp.bfloat16)
    if spec.is_text:
        raw = SyntheticTokens(batch * 2, spec.input_shape[0],
                              vocab_size=spec.vocab_size,
                              causal_lm=spec.causal_lm).batch()
    else:
        raw = SyntheticImages(batch * 2, spec.input_shape,
                              num_classes=cfg.num_classes).batch()
    state = step_mod.make_train_state(model, cfg, raw)
    state = step_mod.replicate_state(state, mesh)
    dev_batch = step_mod.shard_batch(raw, mesh)
    step_fn = step_mod.build_train_step(mesh, cfg, spec)
    # the builder returns a wrapper around its jitted shard_map; jitting
    # the wrapper inlines it, giving a lowerable handle on the SAME program
    compiled = (jax.jit(step_fn)
                .lower(state, dev_batch, jax.random.PRNGKey(0)).compile())
    text = compiled.as_text()
    counts: dict[str, int] = {}
    for m in COLLECTIVE_RE.finditer(text):
        op = m.group(1).replace("-start", "")
        counts[op] = counts.get(op, 0) + 1
    return counts


def main() -> int:
    # the literal scaling-table members at their scaling-table batches
    # (scripts/scaling_table.py: resnet20_cifar bs=64, bert_tiny bs=32)
    for name, bs in (("resnet20_cifar", 64), ("bert_tiny", 32)):
        counts = count_collectives(name, bs)
        total = sum(counts.values())
        print(f"{name} bs={bs} world=2 optimized-HLO collectives: "
              f"{total}  {counts}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
