"""Round-4 MoE ragged re-contest (VERDICT #4): F-tiled grouped matmuls.

Round 3 left two open wounds on the ragged (grouped-matmul) MoE path:
bs=16/seq=1024 could not run at all (Mosaic scoped-VMEM 19.4M > 16M on
the full [8,3072,768] contraction), and ragged LOST to the O(S^2) einsum
dispatch at seq 1024 (31.2 vs 49.2 ex/s at bs=8) — a grouped matmul with
zero capacity padding losing to dense dispatch means the kernel's
tiling, not the algorithm, was the bottleneck.  models/moe.py now tiles
the FFN dim (`ragged_f_chunk`), so this experiment:

1. proves bs=16/seq=1024 ragged RUNS (the former Mosaic failure);
2. sweeps ragged_f_chunk at the contested shape;
3. re-runs the einsum-vs-ragged crossover at seq 1024 with the tiled
   kernel, drift-paired (einsum control brackets each ragged segment,
   median of ratios).

Whole-model gpt2_moe train steps, bf16, flash attention — the exact
round-3 measurement config (BASELINE.md MoE section).

Usage: python scripts/exp_moe_ragged_r04.py [seq] [batch] [steps] [reps]
"""

from __future__ import annotations

import statistics
import sys
import time

import jax
import jax.numpy as jnp

from tpu_hc_bench import flags
from tpu_hc_bench.data.synthetic import SyntheticTokens
from tpu_hc_bench.models import create_model
from tpu_hc_bench.topology import build_mesh, discover_layout
from tpu_hc_bench.train import step as step_mod

SEQ = int(sys.argv[1]) if len(sys.argv) > 1 else 1024
BATCH = int(sys.argv[2]) if len(sys.argv) > 2 else 8
STEPS = int(sys.argv[3]) if len(sys.argv) > 3 else 30
REPS = int(sys.argv[4]) if len(sys.argv) > 4 else 3


def build_arm(moe_impl: str, mesh, layout, f_chunk: int | None = None):
    cfg = flags.BenchmarkConfig(model="gpt2_moe", batch_size=BATCH,
                                seq_len=SEQ, use_fp16=True,
                                attention_impl="flash",
                                moe_impl=moe_impl).resolve()
    model, spec = create_model("gpt2_moe", dtype=jnp.bfloat16,
                               attention_impl="flash", seq_len=SEQ,
                               moe_impl=moe_impl)
    if f_chunk is not None:
        model = model.clone(moe_f_chunk=f_chunk)
    batch = SyntheticTokens(BATCH * layout.total_workers, SEQ,
                            vocab_size=model.vocab_size,
                            causal_lm=True).batch()
    state = step_mod.make_train_state(model, cfg, batch)
    state = step_mod.replicate_state(state, mesh)
    train_step = step_mod.build_train_step(mesh, cfg, spec)
    dev_batch = step_mod.shard_batch(batch, mesh)
    rng = jax.random.PRNGKey(1)

    def segment(state, n):
        metrics = None
        for i in range(n):
            state, metrics = train_step(state, dev_batch,
                                        jax.random.fold_in(rng, i))
        return state, metrics

    return state, segment


def main():
    layout = discover_layout()
    mesh = build_mesh(layout)
    n_ex = BATCH * layout.total_workers

    arms: dict[str, tuple] = {}

    def warm(name, **kw):
        t0 = time.perf_counter()
        try:
            state, seg = build_arm(**kw, mesh=mesh, layout=layout)
            state, m = seg(state, 2)
            loss = float(jax.device_get(m["loss"]))
        except Exception as e:
            print(f"{name}: FAILED {type(e).__name__}: "
                  f"{str(e).splitlines()[0][:200]}", flush=True)
            return False
        print(f"{name}: compiled+warm {time.perf_counter()-t0:.1f}s "
              f"loss={loss:.3f}", flush=True)
        arms[name] = (state, seg)
        return True

    def timed(name):
        state, seg = arms[name]
        state, m0 = seg(state, 1)
        jax.device_get(m0["loss"])
        t0 = time.perf_counter()
        state, m = seg(state, STEPS)
        jax.device_get(m["loss"])
        dt = time.perf_counter() - t0
        arms[name] = (state, seg)
        rate = STEPS * n_ex / dt
        print(f"  {name:16s} {1e3*dt/STEPS:8.2f} ms/step "
              f"{rate:8.2f} ex/s", flush=True)
        return rate

    print(f"== gpt2_moe seq={SEQ} bs={BATCH} bf16 flash ==", flush=True)
    # phase 1: f-chunk sweep, ONE arm alive at a time (a 16G chip cannot
    # hold four gpt2_moe states + momentum simultaneously)
    sweep: dict[str, float] = {}
    for name, kw in (
            ("ragged_f512", dict(moe_impl="ragged", f_chunk=512)),
            ("ragged_f1024", dict(moe_impl="ragged", f_chunk=1024)),
            ("ragged_f2048", dict(moe_impl="ragged", f_chunk=2048)),
            ("ragged_full", dict(moe_impl="ragged", f_chunk=0))):
        if warm(name, **kw):
            sweep[name] = timed(name)
        arms.pop(name, None)          # free the state before the next arm

    ragged_variants = {n: r for n, r in sweep.items() if n != "ragged_full"}
    if not ragged_variants:
        print("no tiled ragged variant ran; nothing to contest")
        return
    best = max(ragged_variants, key=ragged_variants.get)
    print(f"best tiled variant: {best} ({ragged_variants[best]:.2f} ex/s)",
          flush=True)

    # phase 2: drift-paired crossover — einsum control brackets each
    # ragged segment; only these two arms alive
    if not warm("einsum", moe_impl="einsum"):
        return
    warm(best, moe_impl="ragged",
         f_chunk=int(best.split("_f")[1]))
    controls, variants = [], []
    controls.append(timed("einsum"))
    for _ in range(REPS):
        variants.append(timed(best))
        controls.append(timed("einsum"))
    ratios = [v / ((controls[i] + controls[i + 1]) / 2)
              for i, v in enumerate(variants)]
    print(f"controls (einsum): {[f'{c:.2f}' for c in controls]}")
    print(f"variants ({best}): {[f'{v:.2f}' for v in variants]}")
    print(f"ratios: {[f'{r:.3f}' for r in ratios]}")
    print(f"MEDIAN {best}/einsum: {statistics.median(ratios):.3f}x")


if __name__ == "__main__":
    main()
