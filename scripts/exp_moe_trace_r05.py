"""Measure the ragged-MoE residual deficit instead of asserting it (round 5).

Round 4's re-contest (BASELINE.md "ragged MoE") left the short-seq
einsum-vs-ragged gap with an ASSERTED residual: "per-layer sort/gather +
lower ragged_dot MXU utilization".  This harness replaces the sentence
with a measured decomposition: it traces gpt2_moe under BOTH
``--moe_impl`` arms at the same shape and prints, per arm,

  - the wall step time (tunnel-safe protocol, controls inline),
  - per-op-class device-time fractions (the 0.31-scaled device times are
    used as RATIOS only — tunnel rule, see exp_vit_trace.py docstring),
  - the dispatch decomposition: what fraction of the step is routing
    work (sort/gather/scatter/cumsum), what is the expert matmul itself
    (``ragged_dot`` vs the einsum dispatch matmuls), and the implied MXU
    efficiency of each arm's expert-FLOP execution.

Usage: python scripts/exp_moe_trace_r05.py [--batch 8] [--model gpt2_moe]
"""

from __future__ import annotations

import argparse
import sys

sys.path.insert(0, ".")
sys.path.insert(0, "scripts")

from exp_vit_trace import classify, device_op_times, run_once, TRACED


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="gpt2_moe")
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--top", type=int, default=14)
    args = ap.parse_args(argv)

    results = {}
    for impl in ("einsum", "ragged"):
        tdir = f"/tmp/moe_trace_{args.model}_{impl}_{args.batch}"
        step_ms = run_once(args.model, args.batch, tdir,
                           attention_impl="flash", moe_impl=impl)
        ops, counts = device_op_times(tdir)
        results[impl] = (step_ms, ops, counts)
        total = sum(ops.values())
        print(f"\n=== {args.model} bs={args.batch} moe_impl={impl}: "
              f"{step_ms:.2f} ms/step ===")
        for name, us in sorted(ops.items(), key=lambda kv: -kv[1])[:args.top]:
            print(f"  {us / TRACED:9.0f} us  {us / total:5.1%}  "
                  f"[{classify(name):>17s}]  {name[:86]}")
        # class rollup + the decomposition the verdict asked for
        cls: dict[str, float] = {}
        for n, u in ops.items():
            cls[classify(n)] = cls.get(classify(n), 0.0) + u
        print("  -- class fractions --")
        for c, u in sorted(cls.items(), key=lambda kv: -kv[1]):
            print(f"    {c:>17s}: {u / total:5.1%}")
        expert_frac = sum(
            u for n, u in ops.items()
            if "ragged" in n.lower()
            or ("fusion" not in n.lower() and "dot" in n.lower()))
        routing_frac = cls.get("gather/sort", 0.0)
        print(f"  routing (sort/gather/scatter): {routing_frac/total:5.1%}"
              f"   raw-dot ops: {expert_frac/total:5.1%}")

    a, b = results["einsum"], results["ragged"]
    print(f"\nstep-time ratio ragged/einsum: {b[0] / a[0]:.3f}x "
          f"(wall, same session)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
