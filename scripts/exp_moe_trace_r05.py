"""Measure the ragged-MoE residual deficit instead of asserting it (round 5).

Round 4's re-contest (BASELINE.md "ragged MoE") left the short-seq
einsum-vs-ragged gap with an ASSERTED residual: "per-layer sort/gather +
lower ragged_dot MXU utilization".  This harness replaces the sentence
with a measured decomposition: it traces gpt2_moe under BOTH
``--moe_impl`` arms at the same shape and prints, per arm,

  - the wall step time (tunnel-safe protocol, controls inline),
  - per-op-class device-time fractions (the 0.31-scaled device times are
    used as RATIOS only — tunnel rule, see exp_vit_trace.py docstring),
  - the dispatch decomposition: what fraction of the step is routing
    work (sort/gather/scatter/cumsum), what is the expert matmul itself
    (``ragged_dot`` vs the einsum dispatch matmuls), and the router /
    attention / other matmul split.

Round 6: the matmul split is attributed through the compiled HLO's
``metadata op_name`` paths (``tpu_hc_bench.analysis.hlo``), not event
names — ADVICE r5 flagged the old ``"dot" in name`` test as
fusion-blind: XLA fuses most dots into ``loop_fusion.N`` events whose
names say nothing, so the substring heuristic attributed near-zero
expert time.  Each traced event is looked up in the entry computation
of the SAME program's optimized HLO (same builder, see
exp_vit_trace.build_step), and the dots its fused computation executes
are classified by their jax op paths (``.../moe/router/...`` = router,
``.../moe/...`` = expert, ``.../MultiHeadAttention.../...`` =
attention).  Events the HLO does not know are reported as an
unattributed fraction rather than silently dropped.

Usage: python scripts/exp_moe_trace_r05.py [--batch 8] [--model gpt2_moe]
"""

from __future__ import annotations

import argparse
import sys

sys.path.insert(0, ".")
sys.path.insert(0, "scripts")

# program construction/timing stays with the exp harness; ALL perfetto
# parsing comes from the reusable obs.trace (round 7 promotion)
from exp_vit_trace import run_once, step_hlo_text, TRACED

from tpu_hc_bench.analysis import hlo
from tpu_hc_bench.obs.trace import classify, device_op_times

# leaf opcodes that are MXU matmul work (ragged-dot is the ragged arm's
# grouped expert matmul; plain dot covers einsum dispatch + attention)
_MATMUL_OPCODES = ("dot", "ragged-dot")


def matmul_class(paths: list[str]) -> str:
    """One traced event's matmul class from its dots' jax op paths."""
    classes = set()
    for p in paths:
        if "/router/" in p:
            classes.add("router-matmul")
        elif "/moe/" in p or "moe." in p:
            classes.add("expert-matmul")
        elif "attention" in p.lower() or "attn" in p.lower():
            classes.add("attention-matmul")
        else:
            classes.add("other-matmul")
    if len(classes) == 1:
        return classes.pop()
    return "mixed-matmul"


def attribute_matmuls(ops: dict[str, float],
                      module: hlo.HloModule) -> dict[str, float]:
    """Split traced device time by HLO-metadata matmul class.

    ``ops`` maps trace event name -> device us; event names are XLA
    entry-instruction names, so each is looked up at its definition and
    the dots its (possibly fused) computation executes decide the class.
    Events carrying no dots land in "non-matmul"; events the HLO text
    does not define land in "unattributed" (loudly — a nonzero fraction
    means the lowered program diverged from the traced one).
    """
    # entry_only=False: the ragged arm's chunked dispatch (lax.map over
    # >8192-row token blocks) executes its ragged_dots inside a while
    # BODY computation — entry-only attribution would class that expert
    # time "non-matmul", the exact under-attribution this script fixes
    attr = hlo.op_attribution(module, opcodes=_MATMUL_OPCODES,
                              entry_only=False)
    known = {ins.name for comp in module.computations.values()
             for ins in comp.instructions}
    out: dict[str, float] = {}
    for name, us in ops.items():
        key = name.lstrip("%")
        if key in attr:
            cls = matmul_class(attr[key])
        elif key in known:
            cls = "non-matmul"
        else:
            cls = "unattributed"
        out[cls] = out.get(cls, 0.0) + us
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="gpt2_moe")
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--top", type=int, default=14)
    args = ap.parse_args(argv)

    results = {}
    for impl in ("einsum", "ragged"):
        tdir = f"/tmp/moe_trace_{args.model}_{impl}_{args.batch}"
        step_ms = run_once(args.model, args.batch, tdir,
                           attention_impl="flash", moe_impl=impl)
        ops, counts = device_op_times(tdir)
        results[impl] = (step_ms, ops, counts)
        total = sum(ops.values())
        print(f"\n=== {args.model} bs={args.batch} moe_impl={impl}: "
              f"{step_ms:.2f} ms/step ===")
        for name, us in sorted(ops.items(), key=lambda kv: -kv[1])[:args.top]:
            print(f"  {us / TRACED:9.0f} us  {us / total:5.1%}  "
                  f"[{classify(name):>17s}]  {name[:86]}")
        # class rollup + the decomposition the verdict asked for
        cls: dict[str, float] = {}
        for n, u in ops.items():
            cls[classify(n)] = cls.get(classify(n), 0.0) + u
        print("  -- class fractions --")
        for c, u in sorted(cls.items(), key=lambda kv: -kv[1]):
            print(f"    {c:>17s}: {u / total:5.1%}")
        # HLO-metadata matmul decomposition (same program, re-lowered)
        module = hlo.parse_hlo(step_hlo_text(
            args.model, args.batch, attention_impl="flash", moe_impl=impl))
        split = attribute_matmuls(ops, module)
        routing_frac = cls.get("gather/sort", 0.0)
        print(f"  routing (sort/gather/scatter): {routing_frac/total:5.1%}")
        print("  -- matmul split (HLO metadata op_name, through fusions) --")
        for c, u in sorted(split.items(), key=lambda kv: -kv[1]):
            if c != "non-matmul":
                print(f"    {c:>17s}: {u / total:5.1%}")

    a, b = results["einsum"], results["ragged"]
    print(f"\nstep-time ratio ragged/einsum: {b[0] / a[0]:.3f}x "
          f"(wall, same session)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
