"""Contest XLA's select-and-scatter max-pool backward (round 5).

The googlenet trace attribution (BASELINE.md round 5) put **22.1%** of
device time in `select-and-scatter` — the XLA lowering of max-pool's
VJP — at ~4x its bandwidth roofline.  This script contests the one
XLA-level alternative: an equality-mask backward (per window tap:
strided-slice x, compare to y, multiply by dy, dilate-pad back, add —
compare/mul/pad ops only, no scatter), A/B'd against the native VJP on
the googlenet stem-pool shape, back-to-back on hardware.

Semantics note: on ties the equality mask routes the FULL cotangent to
every tied element (select-and-scatter picks the first); for continuous
inputs ties have measure zero and the parity check below passes
exactly.

Usage: python scripts/exp_pool_bwd_r05.py [--iters 30]
"""

from __future__ import annotations

import argparse
import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

sys.path.insert(0, ".")


def maxpool_native(x, window=(3, 3), strides=(2, 2)):
    return lax.reduce_window(
        x, -jnp.inf, lax.max, (1, *window, 1), (1, *strides, 1), "VALID")


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def maxpool_eq(x, window=(3, 3), strides=(2, 2)):
    return maxpool_native(x, window, strides)


def _mp_fwd(x, window, strides):
    y = maxpool_native(x, window, strides)
    return y, (x, y)


def _mp_bwd(window, strides, res, dy):
    x, y = res
    (wh, ww), (sh, sw) = window, strides
    H, W = x.shape[1], x.shape[2]
    Ho, Wo = y.shape[1], y.shape[2]
    dx = jnp.zeros_like(x, dtype=dy.dtype)
    for ki in range(wh):
        for kj in range(ww):
            # tap (ki,kj) of every window, strided to y's grid
            xk = lax.slice(
                x, (0, ki, kj, 0),
                (x.shape[0], ki + (Ho - 1) * sh + 1,
                 kj + (Wo - 1) * sw + 1, x.shape[3]),
                (1, sh, sw, 1))
            contrib = (xk == y).astype(dy.dtype) * dy
            # dilate back to x's grid: interior s-1 zeros, edges offset k
            dx = dx + lax.pad(
                contrib, jnp.zeros((), dy.dtype),
                ((0, 0, 0),
                 (ki, H - ki - (Ho - 1) * sh - 1, sh - 1),
                 (kj, W - kj - (Wo - 1) * sw - 1, sw - 1),
                 (0, 0, 0)))
    return (dx.astype(x.dtype),)


maxpool_eq.defvjp(_mp_fwd, _mp_bwd)


def time_arm(pool_fn, x, dy, iters):
    @jax.jit
    def step(x):
        y, vjp = jax.vjp(pool_fn, x)
        return vjp(dy)[0].sum() + y.sum()

    step(x).block_until_ready()
    t0 = time.perf_counter()
    for _ in range(iters):
        out = step(x)
    out.block_until_ready()
    return (time.perf_counter() - t0) / iters * 1e3


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=30)
    args = ap.parse_args(argv)

    key = jax.random.PRNGKey(0)
    # parity first (tie-free continuous input, small shape)
    xs = jax.random.normal(key, (2, 17, 17, 8), jnp.float32)
    g_native = jax.grad(lambda x: maxpool_native(x).sum())(xs)
    g_eq = jax.grad(lambda x: maxpool_eq(x).sum())(xs)
    np.testing.assert_allclose(np.asarray(g_native), np.asarray(g_eq))
    print("parity: equality-mask bwd == select-and-scatter bwd (tie-free)")

    from tpu_hc_bench.ops.pool_bwd import max_pool as maxpool_pallas

    # googlenet's two dominant pool-bwd shapes at bs=256, bf16
    for shape in ((256, 112, 112, 64), (256, 56, 56, 192)):
        x = jax.random.normal(key, shape, jnp.bfloat16)
        Ho = (shape[1] - 3) // 2 + 1
        dy = jnp.ones((shape[0], Ho, Ho, shape[3]), jnp.bfloat16)
        pall = functools.partial(maxpool_pallas, window=(3, 3),
                                 strides=(2, 2), padding="VALID")
        # bracketed C V C V C on the same chip
        n1 = time_arm(maxpool_native, x, dy, args.iters)
        e1 = time_arm(maxpool_eq, x, dy, args.iters)
        n2 = time_arm(maxpool_native, x, dy, args.iters)
        p1 = time_arm(pall, x, dy, args.iters)
        n3 = time_arm(maxpool_native, x, dy, args.iters)
        print(f"{shape}: native {n1:.2f}/{n2:.2f}/{n3:.2f} ms  "
              f"eq-mask {e1:.2f} ms ({e1 / ((n1 + n2) / 2):.3f}x)  "
              f"PALLAS {p1:.2f} ms ({p1 / ((n2 + n3) / 2):.3f}x)")
    # the stride-1 SAME branch-pool shape (9 of googlenet's 14 pools) —
    # SAME on both arms, matching what the model actually runs
    for shape in ((256, 28, 28, 256),):
        x = jax.random.normal(key, shape, jnp.bfloat16)
        dy = jnp.ones(shape, jnp.bfloat16)
        nat = functools.partial(
            lax.reduce_window, init_value=-jnp.inf, computation=lax.max,
            window_dimensions=(1, 3, 3, 1), window_strides=(1, 1, 1, 1),
            padding="SAME")
        pall = functools.partial(maxpool_pallas, window=(3, 3),
                                 strides=(1, 1), padding="SAME")
        n1 = time_arm(nat, x, dy, args.iters)
        p1 = time_arm(pall, x, dy, args.iters)
        n2 = time_arm(nat, x, dy, args.iters)
        print(f"{shape} s1 SAME: native {n1:.2f}/{n2:.2f} ms  "
              f"PALLAS {p1:.2f} ms ({p1 / ((n1 + n2) / 2):.3f}x)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
