"""ResNet-50 MFU experiments (round 2): act on the round-1 profile.

Round-1 diagnosis (BASELINE.md): stage-1 backward convs fused with BN-stat
reductions run at ~43% internal MXU efficiency; resnet50_v2's preact order
avoids the worst pattern (+13%).  This harness measures fusion-splitting
variants of the v1 model on the real chip:

  baseline      stock resnet50 (control)
  barrier_pre   optimization_barrier between every conv output and its BN
                (splits conv-bwd from BN-stat reductions in the transpose)
  barrier_post  barrier after each BN+act (splits BN-apply from next conv)
  barrier_both  both
  v2            resnet50_v2 control (known +13%)

Usage: python scripts/exp_resnet_mfu.py [variant ...]
"""

from __future__ import annotations

import functools
import sys
import time

import jax
import jax.numpy as jnp
import optax

sys.path.insert(0, ".")

from tpu_hc_bench import flags
from tpu_hc_bench.models import create_model
from tpu_hc_bench.models import resnet as resnet_mod
from tpu_hc_bench.train import step as step_mod
from tpu_hc_bench.topology import build_mesh, discover_layout

BATCH = 128
WARMUP = 12
TIMED = 30
FWD_FLOPS = 8.2e9          # models/__init__.py resnet50 spec
PEAK = 197e12              # v5e bf16


def make_step(model, spec):
    cfg = flags.BenchmarkConfig(model="resnet50", batch_size=BATCH).resolve()
    layout = discover_layout()
    mesh = build_mesh(layout)
    import numpy as np

    rng = np.random.default_rng(0)
    images = rng.integers(0, 255, (BATCH, 224, 224, 3)).astype(np.float32)
    labels = rng.integers(0, 1000, (BATCH,)).astype(np.int32)
    batch = (images, labels)
    state = step_mod.make_train_state(model, cfg, batch)
    state = step_mod.replicate_state(state, mesh)
    train_step = step_mod.build_train_step(mesh, cfg, spec)
    dev_batch = step_mod.shard_batch(batch, mesh)
    return state, train_step, dev_batch


def bench(name, model, spec):
    state, train_step, batch = make_step(model, spec)
    rng = jax.random.PRNGKey(0)
    for _ in range(WARMUP):
        state, metrics = train_step(state, batch, rng)
    # on the axon tunnel block_until_ready is advisory once the dispatch
    # queue is deep — a value fetch is the only trustworthy sync
    jax.device_get(metrics["loss"])
    t0 = time.perf_counter()
    for _ in range(TIMED):
        state, metrics = train_step(state, batch, rng)
    jax.device_get(metrics["loss"])
    dt = (time.perf_counter() - t0) / TIMED
    rate = BATCH / dt
    mfu = 3 * FWD_FLOPS * rate / PEAK
    print(f"{name:14s} {1e3 * dt:7.2f} ms/step  {rate:7.1f} img/s  "
          f"MFU {100 * mfu:.1f}%", flush=True)
    return rate


def main():
    variants = sys.argv[1:] or [
        "baseline", "barrier_pre", "barrier_post", "barrier_both", "v2"]
    dtype = jnp.bfloat16
    for v in variants:
        if v == "v2":
            model, spec = create_model("resnet50_v2", dtype=dtype)
        elif v == "baseline":
            model, spec = create_model("resnet50", dtype=dtype)
        else:
            _, spec = create_model("resnet50", dtype=dtype)
            model = resnet_mod.ResNet(
                [3, 4, 6, 3], resnet_mod.BottleneckBlock, dtype=dtype,
                barrier=v.removeprefix("barrier_"),
            )
        bench(v, model, spec)


if __name__ == "__main__":
    main()
