"""Profile-backed ViT bs=64 local-optimum attribution (round 4, VERDICT #9).

Round-2 recorded vit_b16 peaking at bs=64 (37.4% MFU) with bs=128 *lower*
— a local optimum explained as "cache-friendly regime" without a trace.
This harness captures real jax.profiler traces for both batch sizes and
aggregates device-track op time per EXAMPLE, so the claim gets op-level
attribution the way ResNet's roofline did (scripts/roofline_resnet.py):
which fusions grow super-linearly from bs=64 -> bs=128, and is the growth
MXU work or data movement?

Usage: python scripts/exp_vit_trace.py [--model vit_b16] [--batches 64,128]
Writes traces under /tmp/vit_trace_<model>_<bs>/ and prints, per batch
size:
  - measured step time + per-example time (tunnel-safe protocol)
  - top device ops by total time, normalized per example
  - the bs-to-bs per-example delta per op class (matmul/conv vs
    elementwise/copy/reduce)

Round 5: generalized from image members to the WHOLE zoo — the synthetic
batch dispatches on the member's spec flags exactly like the driver
(tokens / CTC spectrograms / NCF id pairs / images), and
`--attention_impl` / `--moe_impl` pass through so the text members trace
at their best-known configs.

Round 7: the perfetto parsing (nesting-based envelope filtering with the
same-tid containment rule, op classification) moved to the reusable
`tpu_hc_bench.obs.trace` — this script is now a thin consumer: it builds
and times the traced program; `obs.trace` owns the trace analysis.

Measurement caveats found while building this (recorded in BASELINE.md):
the axon tunnel's profiler reports device event durations scaled by a
constant ~0.31 vs wall for BOTH resnet50 and vit_b16 — absolute device
times are uncalibrated on this box, so everything below is interpreted
as RATIOS (op fractions within a trace; per-example ratios between batch
sizes), where the unknown scale cancels.  Wall step times are also
subject to multi-second transient tunnel stall windows; re-run if the
measured step time is wildly off the recorded zoo table.
"""

from __future__ import annotations

import argparse
import sys
import time
from collections import defaultdict

import jax
import jax.numpy as jnp

sys.path.insert(0, ".")

from tpu_hc_bench import flags
from tpu_hc_bench.obs.trace import classify, device_op_times  # noqa: F401
from tpu_hc_bench.data.synthetic import SyntheticImages, SyntheticTokens
from tpu_hc_bench.models import create_model
from tpu_hc_bench.train import step as step_mod
from tpu_hc_bench.topology import build_mesh, discover_layout

WARMUP, TIMED, TRACED = 8, 20, 3


def synthetic_batch(spec, model, batch: int):
    """The driver's synthetic-dataset dispatch (train/driver.py:660-726),
    keyed on the same spec flags, so any zoo member traces."""
    if spec.is_text:
        return SyntheticTokens(batch, spec.input_shape[0],
                               vocab_size=spec.vocab_size,
                               causal_lm=spec.causal_lm).batch()
    if getattr(spec, "ctc", False):
        from tpu_hc_bench.data.synthetic import SyntheticSpeech
        from tpu_hc_bench.models.deepspeech import max_label_for

        frames, freq = spec.input_shape
        return SyntheticSpeech(batch, frames, freq,
                               max_label_for(frames)).batch()
    if getattr(spec, "integer_input", False):
        from tpu_hc_bench.data.synthetic import SyntheticIds

        return SyntheticIds(batch, num_users=model.num_users,
                            num_items=model.num_items).batch()
    return SyntheticImages(batch, spec.input_shape).batch()


def build_step(model_name: str, batch: int,
               attention_impl: str = "dense", moe_impl: str = "einsum",
               accum: int = 1, accum_dtype: str = "f32"):
    """The traced program, built once: the jitted train step + placed
    state/batch on the discovered mesh.  Shared by the timing/tracing
    path below and by exp_moe_trace_r05's HLO lowering, so the program
    whose compiled text attributes the trace is the SAME program the
    trace measured."""
    cfg = flags.BenchmarkConfig(model=model_name, batch_size=batch,
                                attention_impl=attention_impl,
                                moe_impl=moe_impl,
                                gradient_accumulation_steps=accum,
                                accum_dtype=accum_dtype).resolve()
    layout = discover_layout()
    mesh = build_mesh(layout)
    kwargs = {}
    from tpu_hc_bench.models import get_model_spec

    spec0 = get_model_spec(model_name)
    if spec0.attention or spec0.is_text:
        kwargs["attention_impl"] = attention_impl
    if spec0.moe:
        kwargs["moe_impl"] = moe_impl
    model, spec = create_model(model_name, dtype=jnp.bfloat16, **kwargs)
    raw = synthetic_batch(spec, model, batch)
    state = step_mod.make_train_state(model, cfg, raw)
    state = step_mod.replicate_state(state, mesh)
    train_step = step_mod.build_train_step(mesh, cfg, spec)
    dev_batch = step_mod.shard_batch(raw, mesh)
    return train_step, state, dev_batch


def step_hlo_text(model_name: str, batch: int, **build_kw) -> str:
    """Optimized-HLO text of the program run_once traces (same build).

    The builder's wrapper closes over its jitted shard_map; jitting the
    wrapper inlines it, giving a lowerable handle on the SAME program.
    """
    train_step, state, dev_batch = build_step(model_name, batch, **build_kw)
    return (jax.jit(train_step)
            .lower(state, dev_batch, jax.random.PRNGKey(0))
            .compile().as_text())


def run_once(model_name: str, batch: int, trace_dir: str,
             attention_impl: str = "dense", moe_impl: str = "einsum",
             accum: int = 1, accum_dtype: str = "f32"):
    train_step, state, dev_batch = build_step(
        model_name, batch, attention_impl=attention_impl,
        moe_impl=moe_impl, accum=accum, accum_dtype=accum_dtype)
    rng = jax.random.PRNGKey(0)
    for _ in range(WARMUP):
        state, metrics = train_step(state, dev_batch, rng)
    jax.device_get(metrics["loss"])  # tunnel-safe sync
    t0 = time.perf_counter()
    for _ in range(TIMED):
        state, metrics = train_step(state, dev_batch, rng)
    jax.device_get(metrics["loss"])
    step_ms = (time.perf_counter() - t0) / TIMED * 1e3
    # traced steps are separate so profiler overhead never taints timing
    with jax.profiler.trace(trace_dir):
        for _ in range(TRACED):
            state, metrics = train_step(state, dev_batch, rng)
        jax.device_get(metrics["loss"])
    return step_ms


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="vit_b16")
    ap.add_argument("--batches", default="64,128")
    ap.add_argument("--top", type=int, default=18)
    ap.add_argument("--attention_impl", default="dense")
    ap.add_argument("--moe_impl", default="einsum")
    ap.add_argument("--accum", type=int, default=1,
                    help="--gradient_accumulation_steps for the traced "
                         "step (the accumulation members' best configs)")
    ap.add_argument("--accum_dtype", default="f32")
    args = ap.parse_args(argv)
    batches = [int(b) for b in args.batches.split(",")]

    results = {}
    for bs in batches:
        tdir = f"/tmp/vit_trace_{args.model}_{bs}"
        step_ms = run_once(args.model, bs, tdir,
                           attention_impl=args.attention_impl,
                           moe_impl=args.moe_impl, accum=args.accum,
                           accum_dtype=args.accum_dtype)
        ops, counts = device_op_times(tdir)
        results[bs] = (step_ms, ops, counts)
        print(f"\n=== {args.model} bs={bs}: {step_ms:.2f} ms/step, "
              f"{step_ms / bs * 1e3:.1f} us/example ===")
        total = sum(ops.values())
        for name, us in sorted(ops.items(), key=lambda kv: -kv[1])[:args.top]:
            print(f"  {us / TRACED / bs:9.2f} us/ex  {us / total:5.1%}  "
                  f"[{classify(name):>17s}]  {name[:90]}")
        cls: dict[str, float] = defaultdict(float)
        for n, u in ops.items():
            cls[classify(n)] += u
        print("  -- class fractions --")
        for c, u in sorted(cls.items(), key=lambda kv: -kv[1]):
            print(f"    {c:>17s}: {u / total:5.1%}")

    def by_class(bs):
        _, ops, counts = results[bs]
        us = defaultdict(float)
        count = defaultdict(float)
        for n, u in ops.items():
            c = classify(n)
            us[c] += u / TRACED / bs
            # per-step executions, measured (not assumed once-per-name):
            # raw event count over TRACED steps / TRACED
            count[c] += counts[n] / TRACED
        return us, count

    # compare adjacent batch-size pairs (the common case is exactly two)
    for a, b in zip(batches, batches[1:]):
        cls_a, cnt_a = by_class(a)
        cls_b, cnt_b = by_class(b)
        print(f"\n=== per-example us by op class: bs={a} vs bs={b} "
              f"(count = ops/step) ===")
        print(f"{'class':>18s} {('bs=%d' % a):>10s} {'#':>6s}"
              f" {('bs=%d' % b):>10s} {'#':>6s} {'ratio':>7s}")
        for c in sorted(set(cls_a) | set(cls_b),
                        key=lambda c: -cls_b.get(c, 0)):
            ra, rb = cls_a.get(c, 0.0), cls_b.get(c, 0.0)
            ratio = rb / ra if ra else float("inf")
            print(f"{c:>18s} {ra:10.2f} {cnt_a.get(c, 0):6.0f}"
                  f" {rb:10.2f} {cnt_b.get(c, 0):6.0f} {ratio:7.2f}x")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
