"""Cross-process (DCN-analog) allreduce microbench: fused vs unfused.

Round-3 (VERDICT #6): the reference's second transport stack is a real
alternative fabric (IntelMPI/libfabric, run-tf-sing-libfabric-intelmpi.sh
:86-105); the TPU counterpart is the multislice layout where the gradient
allreduce's outer phase crosses slices over DCN.  No multi-slice pod is
reachable from this box, so the honest measurable form is the same one
the multi-process tests use: 2 OS processes x N CPU devices with the
``dcn`` mesh axis ON the process boundary, sweeping message sizes through
``allreduce_gradients(fuse=True/False)`` over ``(dcn, data)``.

Numbers are host-loopback (no real NIC) — RELATIVE shape is the signal
(fusion amortizes per-collective latency on small tensors, converges on
large ones), matching the ICI microbench's table convention.

Spawns its own workers: ``python scripts/microbench_dcn.py``.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
import textwrap
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

WORKER = textwrap.dedent("""
    import sys, time
    import tpu_hc_bench  # noqa: F401  (JAX version shims before config)
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 2)
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from tpu_hc_bench.parallel import distributed
    from tpu_hc_bench.parallel.collectives import allreduce_gradients
    from tpu_hc_bench import topology

    distributed.initialize(coordinator_port=int(sys.argv[1]))
    layout = topology.discover_layout(workers_per_host=0)
    mesh = topology.build_mesh(layout, num_slices=2)
    axes = (topology.DCN_AXIS, topology.DATA_AXIS)
    ITERS = 30

    def bench(nbytes, fuse):
        n = nbytes // 4
        # 64 leaves when small enough: the fusion buffer's target case
        leaves = max(1, min(64, n // 64))
        per = n // leaves
        tree = {f"g{i}": jnp.arange(per, dtype=jnp.float32) + i
                for i in range(leaves)}

        def step(t):
            def body(_, tt):
                r = allreduce_gradients(tt, axis_name=axes, fuse=fuse)
                return jax.tree.map(lambda x: x * 0.5, r)
            return jax.lax.fori_loop(0, ITERS, body, t)

        f = jax.jit(jax.shard_map(step, mesh=mesh, in_specs=P(),
                                  out_specs=P(), check_vma=False))
        r = f(tree)
        jax.block_until_ready(r)
        t0 = time.perf_counter()
        r = f(tree)
        jax.block_until_ready(r)
        return (time.perf_counter() - t0) / ITERS * 1e6   # us/allreduce

    if jax.process_index() == 0:
        print("# cross-process (dcn x data) allreduce, 2 procs x 2 devs, "
              "fused vs per-leaf", flush=True)
        print(f"{'bytes':>10} {'fused_us':>10} {'unfused_us':>12} "
              f"{'speedup':>8}", flush=True)
    for nbytes in (4096, 65536, 1 << 20, 8 << 20, 64 << 20):
        tf = bench(nbytes, True)
        tu = bench(nbytes, False)
        if jax.process_index() == 0:
            print(f"{nbytes:>10} {tf:>10.1f} {tu:>12.1f} {tu / tf:>8.2f}",
                  flush=True)
    print(f"DCN_BENCH_OK process={jax.process_index()}", flush=True)
""")


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def main() -> int:
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        hostfile = Path(td) / "nodeips.txt"
        hostfile.write_text("127.0.0.1\n127.0.0.1\n")
        script = Path(td) / "worker.py"
        script.write_text(WORKER)
        port = free_port()
        procs = []
        for pid in range(2):
            env = dict(os.environ)
            env.update({
                "TPU_HC_BENCH_HOSTFILE": str(hostfile),
                "TPU_HC_BENCH_PROCESS_ID": str(pid),
                "PYTHONPATH": f"{REPO}:{env.get('PYTHONPATH', '')}",
                "JAX_PLATFORMS": "cpu",
            })
            procs.append(subprocess.Popen(
                [sys.executable, str(script), str(port)], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
        ok = True
        for i, p in enumerate(procs):
            out, _ = p.communicate(timeout=600)
            if i == 0:
                sys.stdout.write(out)
            ok = ok and p.returncode == 0 and "DCN_BENCH_OK" in out
        return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
