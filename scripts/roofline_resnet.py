"""Analytic op-level roofline for ResNet-50 training on TPU v5e.

Walks the v1.5 architecture layer by layer and computes, for forward +
input-grad + weight-grad of every conv and for every BN/ReLU/add pass, the
minimum execution time under the v5e roofline:

    t_op = max(FLOPs / eff_peak, HBM bytes / BW)

with eff_peak derated by MXU tile shape (a matmul with contraction K<128
or output width N<128 cannot use the full 128x128 systolic array:
eff = peak * min(K,128)/128 * min(N,128)/128).

This answers the round-1 verdict question: how much of the measured
ResNet-50 step time is bandwidth/shape physics vs XLA scheduling slack.
Usage: python scripts/roofline_resnet.py [batch]
"""

from __future__ import annotations

import sys

PEAK = 197e12          # v5e bf16 FLOP/s
BW = 819e9             # v5e HBM GB/s
B = int(sys.argv[1]) if len(sys.argv) > 1 else 128
BPE = 2                # bf16 bytes/elem for activations/weights
BPE_W = 4              # f32 for weight grads / BN stats


def conv_ops(h, w, cin, cout, k, stride, name):
    """(name, flops, bytes, K_contract, N_out) for fwd/dgrad/wgrad."""
    ho, wo = h // stride, w // stride
    mac = B * ho * wo * cout * cin * k * k
    fl = 2 * mac
    x_bytes = B * h * w * cin * BPE
    y_bytes = B * ho * wo * cout * BPE
    w_bytes = k * k * cin * cout * BPE
    ops = []
    # fwd: read x,W; write y.   contraction K = k*k*cin, out width N = cout
    ops.append((f"{name}.fwd", fl, x_bytes + w_bytes + y_bytes,
                k * k * cin, cout))
    # dgrad: read dy,W; write dx.  K = k*k*cout, N = cin
    ops.append((f"{name}.dgrad", fl, y_bytes + w_bytes + x_bytes,
                k * k * cout, cin))
    # wgrad: read x,dy; write dW (f32).  K = B*ho*wo (huge), N = cout
    ops.append((f"{name}.wgrad", fl,
                x_bytes + y_bytes + k * k * cin * cout * BPE_W,
                B * ho * wo, cout))
    return ops


def bn_relu_ops(h, w, c, name):
    """BN fwd (read x, write y, stats) + BN bwd (read x,dy, write dx) +
    relu bwd mask — pure HBM traffic."""
    a = B * h * w * c * BPE
    return [
        (f"{name}.bnfwd", 0, 2 * a, 0, 0),
        (f"{name}.bnbwd", 0, 3 * a, 0, 0),
    ]


def add_ops(h, w, c, name):
    a = B * h * w * c * BPE
    return [(f"{name}.add", 0, 3 * a, 0, 0)]


def build_resnet50():
    """Emit every op of fwd+bwd with explicit spatial-size bookkeeping."""
    ops = []
    ops += conv_ops(224, 224, 3, 64, 7, 2, "stem")
    ops += bn_relu_ops(112, 112, 64, "stem")
    ops += [("maxpool", 0, 2 * B * 112 * 112 * 64 * BPE, 0, 0)]
    h = 56
    cin = 64
    for i, blocks in enumerate([3, 4, 6, 3]):
        f = 64 * (2 ** i)
        for j in range(blocks):
            stride = 2 if (i > 0 and j == 0) else 1
            name = f"s{i}b{j}"
            ops += conv_ops(h, h, cin, f, 1, 1, f"{name}.c1")
            ho = h // stride
            ops += bn_relu_ops(h, h, f, f"{name}.c1")
            ops += conv_ops(h, h, f, f, 3, stride, f"{name}.c2")
            ops += bn_relu_ops(ho, ho, f, f"{name}.c2")
            ops += conv_ops(ho, ho, f, 4 * f, 1, 1, f"{name}.c3")
            ops += bn_relu_ops(ho, ho, 4 * f, f"{name}.c3")
            if cin != 4 * f or stride != 1:
                ops += conv_ops(h, h, cin, 4 * f, 1, stride, f"{name}.sc")
                ops += bn_relu_ops(ho, ho, 4 * f, f"{name}.sc")
            ops += add_ops(ho, ho, 4 * f, name)
            cin = 4 * f
            h = ho
    ops += [("head", 2 * 3 * B * 2048 * 1000, 0, 2048, 1000)]
    return ops


def main():
    fused = "--fused" in sys.argv
    ops = build_resnet50()
    if fused:
        # perfect-fusion ceiling: BN/relu/add/pool traffic fully absorbed
        # into conv prologues/epilogues (stats in the conv epilogue, apply
        # in the next conv's prologue) — only conv tensor traffic remains
        ops = [o for o in ops if o[1] > 0]
    t_ideal = t_shape = 0.0
    flops_total = 0
    rows = {}
    for name, fl, by, k, n in ops:
        flops_total += fl
        eff = PEAK
        if fl and k and n:
            eff = PEAK * min(1.0, k / 128) * min(1.0, n / 128)
        ti = max(fl / PEAK, by / BW)
        ts = max(fl / eff, by / BW)
        t_ideal += ti
        t_shape += ts
        stage = name.split(".")[0].split("b")[0]
        r = rows.setdefault(stage, [0.0, 0.0, 0, 0])
        r[0] += ti
        r[1] += ts
        r[2] += fl
        r[3] += by
    print(f"batch={B}  fwd+bwd conv FLOPs={flops_total/1e9:.1f} G")
    print(f"{'stage':8s} {'t_ideal ms':>10s} {'t_shape ms':>10s} "
          f"{'GFLOP':>8s} {'GB':>7s}")
    for stage, (ti, ts, fl, by) in rows.items():
        print(f"{stage:8s} {1e3*ti:10.2f} {1e3*ts:10.2f} "
              f"{fl/1e9:8.1f} {by/1e9:7.2f}")
    print("-" * 46)
    print(f"{'total':8s} {1e3*t_ideal:10.2f} {1e3*t_shape:10.2f}")
    mfu_ideal = flops_total / PEAK / t_ideal
    mfu_shape = flops_total / PEAK / t_shape
    print(f"roofline MFU ceiling: ideal {100*mfu_ideal:.1f}%  "
          f"MXU-shape-adjusted {100*mfu_shape:.1f}%")
    print("measured r1: 49.2 ms (33%); v2: 44.0 ms (36%)")


if __name__ == "__main__":
    main()
