#!/usr/bin/env bash
# Benchmark launcher, DCN / slow-path variant — the counterpart of
# benchmark-scripts/run-tf-sing-libfabric-intelmpi.sh (the reference's
# second, interchangeable comm stack; same semantics, different plumbing,
# SURVEY.md §3.2).  On TPU the "second stack" is the cross-slice DCN path
# (fabric=dcn) or the host-mediated slow path (fabric=host / sock).
set -euo pipefail

if [ "$#" -ne 4 ]; then
    echo "usage: $0 <NUM_HOSTS> <WORKERS_PER_HOST(0=all chips)> <batch_size> <fabric(dcn|host|sock)>"
    exit 1
fi

FABRIC=$4
case "$FABRIC" in
    ici|ib)
        echo "note: $0 is the DCN/slow-path launcher; use run-tpu-ici.sh for fabric=$FABRIC" >&2
        ;;
esac

SETENV="${TPU_HC_BENCH_SETENV:-$HOME/.tpu_hc_bench/setenv}"
[ -f "$SETENV" ] && . "$SETENV"

MODEL="${MODEL:-resnet50}"
NUM_WARMUP="${NUM_WARMUP:-50}"
NUM_BATCHES="${NUM_BATCHES:-100}"
DATA_DIR_ARGS=()
[ -n "${DATA_DIR:-}" ] && DATA_DIR_ARGS=(--data_dir "$DATA_DIR")

# extra tf_cnn-style flags as a space-separated env string
# (EXTRA_FLAGS="--eval True --train_dir /ckpts") — arrays don't cross the
# env boundary, so this is the operator-facing contract.  Values may not
# contain spaces (whitespace is the only separator); a sourced setenv
# registry that already defines the EXTRA_ARGS array takes precedence.
if [ -z "${EXTRA_ARGS+x}" ]; then
    read -r -a EXTRA_ARGS <<< "${EXTRA_FLAGS:-}"
fi

mkdir -p "$HOME/logs"

exec python -m tpu_hc_bench \
    "$1" "$2" "$3" "$FABRIC" \
    --model "$MODEL" \
    --num_warmup_batches "$NUM_WARMUP" \
    --num_batches "$NUM_BATCHES" \
    --optimizer momentum \
    --display_every 10 \
    "${DATA_DIR_ARGS[@]}" \
    ${EXTRA_ARGS[@]+"${EXTRA_ARGS[@]}"}
