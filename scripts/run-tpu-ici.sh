#!/usr/bin/env bash
# Benchmark launcher, ICI fast path — the TPU-native counterpart of
# benchmark-scripts/run-tf-sing-ucx-openmpi.sh (same 4-arg signature,
# reference :4,27-30):
#
#   ./run-tpu-ici.sh <NUM_HOSTS> <WORKERS_PER_HOST> <batch_size> <fabric(ici,host)>
#
# Reference ib|sock names are accepted for the 4th arg.  Where the
# reference's mpirun fans ranks out over ~/nodeips.txt via the pwdless-SSH
# mesh (:99-109), a TPU pod runs this same script on every host (e.g. via
# `gcloud compute tpus tpu-vm ssh --worker=all --command=...`) and
# jax.distributed coordinates; on a single host it just runs.
set -euo pipefail

if [ "$#" -ne 4 ]; then
    echo "usage: $0 <NUM_HOSTS> <WORKERS_PER_HOST(0=all chips)> <batch_size> <fabric(ici|host|ib|sock)>"
    exit 1
fi

NUM_HOSTS=$1
WORKERS_PER_HOST=$2
BATCH_SIZE=$3
FABRIC=$4

# env registry, the setenv contract (reference sources /mnt/shared/setenv :14)
SETENV="${TPU_HC_BENCH_SETENV:-$HOME/.tpu_hc_bench/setenv}"
[ -f "$SETENV" ] && . "$SETENV"

# experiment constants mirroring the reference launcher (:32-35)
MODEL="${MODEL:-resnet50}"
NUM_WARMUP="${NUM_WARMUP:-50}"
NUM_BATCHES="${NUM_BATCHES:-100}"
DATA_DIR_ARGS=()
[ -n "${DATA_DIR:-}" ] && DATA_DIR_ARGS=(--data_dir "$DATA_DIR")

# extra tf_cnn-style flags as a space-separated env string
# (EXTRA_FLAGS="--eval True --train_dir /ckpts") — arrays don't cross the
# env boundary, so this is the operator-facing contract.  Values may not
# contain spaces (whitespace is the only separator); a sourced setenv
# registry that already defines the EXTRA_ARGS array takes precedence.
if [ -z "${EXTRA_ARGS+x}" ]; then
    read -r -a EXTRA_ARGS <<< "${EXTRA_FLAGS:-}"
fi

mkdir -p "$HOME/logs"

exec python -m tpu_hc_bench \
    "$NUM_HOSTS" "$WORKERS_PER_HOST" "$BATCH_SIZE" "$FABRIC" \
    --model "$MODEL" \
    --num_warmup_batches "$NUM_WARMUP" \
    --num_batches "$NUM_BATCHES" \
    --optimizer momentum \
    --display_every 10 \
    "${DATA_DIR_ARGS[@]}" \
    ${EXTRA_ARGS[@]+"${EXTRA_ARGS[@]}"}
