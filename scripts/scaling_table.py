#!/usr/bin/env python
"""The multi-node scaling-efficiency table, through the literal CLI.

The reference's headline deliverable is the 1/2/4-node sweep with the
fabric flip — `./run-tf-sing-ucx-openmpi.sh N 1 64 ib|sock` for N in
{1,2,4} (`/root/reference/README.md:68-73`, launch at
`run-tf-sing-ucx-openmpi.sh:85-95,99-109`).  This harness produces its
analog on the virtual CPU mesh: for each world size it spawns WORLD real
OS processes, each running the literal 4-positional CLI

    python -m tpu_hc_bench WORLD 0 BATCH FABRIC --model=... \
        --virtual_devices=(TOTAL_DEVICES/WORLD)

joined through the nodeips.txt hostfile contract + jax.distributed (the
proven tests/test_multiprocess.py launch pattern), full 50+100 protocol,
and parses each rank-0 "total images/sec" line into one table.

Design note — why the TOTAL device count stays fixed while the world
grows: on real hardware the reference grows the fleet (more nodes = more
compute) and efficiency is total(N)/(N*total(1)).  On this one-box CPU
mesh, growing the device count would just oversubscribe the same vCPUs
and measure host contention.  Holding total devices at 8 and splitting
them over 1/2/4 processes keeps the device work constant so the measured
ratio total(world=N)/total(world=1) isolates exactly what the reference's
fabric flip probes: the cost of gradient reduction crossing process
boundaries (ici-analog = compiled XLA collectives over the distributed
backend; host = the sock-analog bounce through host memory + a
process_allgather hop).  Numbers are RELATIVE, clearly CPU-mesh, and
recorded as such in BASELINE.md.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import socket
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def run_config(world: int, fabric: str, model: str, batch: int,
               total_devices: int, warmup: int, batches: int,
               workdir: Path, timeout: int = 2400,
               metrics_dir: Path | None = None) -> dict:
    """One table cell: WORLD processes through the literal CLI."""
    devices_per = total_devices // world
    assert devices_per * world == total_devices
    cmd = [sys.executable, "-m", "tpu_hc_bench",
           str(world), "0", str(batch), fabric,
           f"--model={model}", f"--num_warmup_batches={warmup}",
           f"--num_batches={batches}", f"--virtual_devices={devices_per}"]
    if metrics_dir is not None:
        # per-cell obs artifact: rank 0 writes metrics.jsonl + manifest
        # there, so each world size leaves a diffable record
        # (python -m tpu_hc_bench.obs diff <cell_a> <cell_b>)
        cmd.append(f"--metrics_dir={metrics_dir}")
    hostfile = workdir / f"nodeips_{world}.txt"
    hostfile.write_text("127.0.0.1\n" * world)
    port = free_port()
    procs = []
    for pid in range(world):
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "PYTHONPATH": f"{REPO}:{env.get('PYTHONPATH', '')}",
            # share the suite's warm XLA executable cache
            "JAX_COMPILATION_CACHE_DIR": env.get(
                "JAX_COMPILATION_CACHE_DIR", "/tmp/tpu_hc_bench_jax_cache"),
        })
        if world > 1:
            env.update({
                "TPU_HC_BENCH_HOSTFILE": str(hostfile),
                "TPU_HC_BENCH_PROCESS_ID": str(pid),
                "TPU_HC_BENCH_COORDINATOR_PORT": str(port),
            })
        procs.append(subprocess.Popen(
            cmd, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True))
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs[len(outs):]:
            out, _ = p.communicate()
            outs.append(out)
        raise RuntimeError(
            f"config world={world} {fabric} {model} timed out:\n"
            + "\n---\n".join(outs))
    for i, (p, out) in enumerate(zip(procs, outs)):
        if p.returncode != 0:
            raise RuntimeError(
                f"rank {i} failed (world={world} {fabric} {model}):\n{out}")
    rank0 = outs[0]
    m = re.search(r"total (?:images|examples)/sec: ([\d.]+)", rank0)
    s = re.search(r"step: ([\d.]+)ms", rank0)
    if not m:
        raise RuntimeError(f"no throughput line in rank-0 output:\n{rank0}")
    return {
        "world": world, "fabric": fabric, "model": model,
        "batch_per_worker": batch, "total_devices": total_devices,
        "warmup": warmup, "batches": batches,
        "total_ex_per_sec": float(m.group(1)),
        "mean_step_ms": float(s.group(1)) if s else None,
        "metrics_dir": str(metrics_dir) if metrics_dir else None,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--worlds", default="1,2,4")
    ap.add_argument("--fabrics", default="ici,host")
    ap.add_argument("--models", default="resnet20_cifar,bert_tiny")
    ap.add_argument("--batch", type=int, default=2,
                    help="per-worker batch (reference semantics)")
    ap.add_argument("--total_devices", type=int, default=8)
    ap.add_argument("--warmup", type=int, default=50)
    ap.add_argument("--batches", type=int, default=100)
    ap.add_argument("--out", default="artifacts/scaling_r04")
    ap.add_argument("--timeout", type=int, default=2400)
    ap.add_argument("--no-metrics", dest="metrics", action="store_false",
                    default=True,
                    help="skip the per-cell obs.metrics artifacts")
    args = ap.parse_args(argv)

    worlds = [int(w) for w in args.worlds.split(",")]
    fabrics = args.fabrics.split(",")
    models = args.models.split(",")
    out_dir = REPO / args.out
    out_dir.mkdir(parents=True, exist_ok=True)
    jsonl = out_dir / "scaling.jsonl"

    rows = []
    with jsonl.open("a") as f:
        for model in models:
            for fabric in fabrics:
                for world in worlds:
                    t0 = time.time()
                    cell_metrics = (
                        out_dir / "obs" / f"w{world}_{fabric}_{model}"
                        if args.metrics else None)
                    row = run_config(world, fabric, model, args.batch,
                                     args.total_devices, args.warmup,
                                     args.batches, out_dir,
                                     timeout=args.timeout,
                                     metrics_dir=cell_metrics)
                    row["wall_s"] = round(time.time() - t0, 1)
                    rows.append(row)
                    f.write(json.dumps(row) + "\n")
                    f.flush()
                    print(f"done: world={world} {fabric} {model}: "
                          f"{row['total_ex_per_sec']:.1f} ex/s "
                          f"({row['wall_s']}s wall)", flush=True)

    # markdown table with efficiency vs the world-1 row of the same
    # (model, fabric) — the reference's scaling-efficiency metric reshaped
    # for the fixed-total-device design (see module docstring)
    lines = [
        "| model | fabric | world | total ex/s | step ms | eff vs world-1 |",
        "|---|---|---|---|---|---|",
    ]
    base = {(r["model"], r["fabric"]): r["total_ex_per_sec"]
            for r in rows if r["world"] == 1}
    for r in rows:
        b = base.get((r["model"], r["fabric"]))
        eff = f"{r['total_ex_per_sec'] / b:.3f}" if b else "—"
        lines.append(
            f"| {r['model']} | {r['fabric']} | {r['world']} "
            f"| {r['total_ex_per_sec']:.1f} | {r['mean_step_ms']:.1f} "
            f"| {eff} |")
    table = "\n".join(lines)
    (out_dir / "scaling.md").write_text(table + "\n")
    print(table)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
