#!/usr/bin/env bash
# Build the runtime image as a relocatable venv tarball — the degraded-
# but-runnable form of the reference's L2 contract (build once, sanity-run,
# exec everywhere: /root/reference/install-scripts/build-container.sh:23-30)
# for hosts without a container runtime.  The Dockerfile encodes the same
# contract for hosts WITH one; both consume scripts/setup/stack-pins.txt
# so the image can never drift from the host stack.
#
#   usage: ./build-venv-image.sh [out_dir]        (default ./build/venv-image)
#
# Produces:
#   <out_dir>/tpu-hc-bench-venv.tar.gz       the image
#   <out_dir>/build.log                      full build transcript
#   <out_dir>/sanity.txt                     the image's OWN sanity report
#                                            (the `singularity run` analog —
#                                            a failing report fails the build)
#
# Assembly strategy, in order:
#   1. online:  pip install the pinned set from PyPI into a fresh venv
#   2. offline: VERIFY the live interpreter's packages match the pins
#      exactly, then clone them into the fresh venv (same artifact, with
#      provenance recorded in build.log) — this is the path on air-gapped
#      boxes like this dev environment.
set -euo pipefail

HERE="$(cd "$(dirname "$0")" && pwd)"
REPO="$(cd "$HERE/../.." && pwd)"
OUT="${1:-$REPO/build/venv-image}"
PINS="$HERE/stack-pins.txt"
VENV="$OUT/venv"

mkdir -p "$OUT"
exec > >(tee "$OUT/build.log") 2>&1
echo "== build-venv-image $(date -u +%Y-%m-%dT%H:%M:%SZ) =="
echo "pins: $PINS"

rm -rf "$VENV"
python -m venv --copies "$VENV"

PIN_JAX="$(grep -oP '^jax==\K.*' "$PINS")"
if pip download --no-deps --dest "$OUT/probe" "jax==${PIN_JAX}" \
        >/dev/null 2>&1; then
    echo "mode: online (PyPI)"
    # jax[tpu] + the libtpu wheel index, exactly like install_jax_stack.sh
    # and the Dockerfile — the image must be able to drive a TPU
    "$VENV/bin/pip" install --no-cache-dir "jax[tpu]==${PIN_JAX}" \
        -r "$PINS" \
        -f https://storage.googleapis.com/jax-releases/libtpu_releases.html
else
    echo "mode: offline — cloning the live stack after verifying the pins"
    python - "$PINS" <<'EOF'
import importlib.metadata as md, sys
pins = {}
for line in open(sys.argv[1]):
    line = line.split("#")[0].strip()
    if line:
        name, ver = line.split("==")
        pins[name] = ver
bad = []
for name, want in pins.items():
    try:
        have = md.version(name)
    except md.PackageNotFoundError:
        bad.append(f"{name}: MISSING (pin {want})"); continue
    if have != want:
        bad.append(f"{name}: {have} != pin {want}")
if bad:
    print("live stack does NOT match stack-pins.txt:\n  " + "\n  ".join(bad))
    sys.exit(1)
print("live stack matches stack-pins.txt exactly "
      f"({len(pins)} pins verified)")
EOF
    SRC_SITE="$(python -c 'import sysconfig; print(sysconfig.get_paths()["purelib"])')"
    DST_SITE="$("$VENV/bin/python" -c 'import sysconfig; print(sysconfig.get_paths()["purelib"])')"
    echo "cloning $SRC_SITE -> $DST_SITE"
    cp -a "$SRC_SITE/." "$DST_SITE/"
fi

echo "installing tpu_hc_bench into the image"
cp -a "$REPO/tpu_hc_bench" \
    "$("$VENV/bin/python" -c 'import sysconfig; print(sysconfig.get_paths()["purelib"])')/"

echo "building the native data plane inside the image"
make -C "$("$VENV/bin/python" -c 'import sysconfig; print(sysconfig.get_paths()["purelib"])')/tpu_hc_bench/native"

# --- the sanity gate (build-container.sh:29-30's `singularity run`) ---
echo "running the image sanity report"
JAX_PLATFORMS=cpu "$VENV/bin/python" -m tpu_hc_bench.utils.sanity \
    | tee "$OUT/sanity.txt"

echo "packing"
# gzip -1: the stack is ~6 GB of already-compressed wheels content; fast
# compression keeps the pack step minutes, not tens of minutes, on 1 vCPU
tar -C "$OUT" -c venv | gzip -1 > "$OUT/tpu-hc-bench-venv.tar.gz"
SIZE=$(du -h "$OUT/tpu-hc-bench-venv.tar.gz" | cut -f1)
SHA=$(sha256sum "$OUT/tpu-hc-bench-venv.tar.gz" | cut -d' ' -f1)
echo "image: $OUT/tpu-hc-bench-venv.tar.gz ($SIZE, sha256 $SHA)"
echo "unpack anywhere and run: venv/bin/python -m tpu_hc_bench ..."
echo "== build OK =="
