#!/usr/bin/env bash
# The `singularity exec` leg of the L2 contract: run a benchmark FROM the
# built image, not host python.  The reference wraps every rank in
# `singularity exec <sif> ...` (run-tf-sing-ucx-openmpi.sh:107); our image
# form is the relocatable venv tarball from build-venv-image.sh, so the
# analog is: unpack the tarball to a FRESH prefix (proving relocation, not
# just the build venv working in place) and run the literal 4-positional
# CLI with the image's own interpreter.
#
#   usage: ./exec-image-benchmark.sh <tarball> [out_dir] [-- extra args...]
#
# DRIVER_SITE (env, optional): a host path holding the TPU access shim,
# made visible to the image's python via PYTHONPATH.  This is the
# `singularity exec --nv` analog — the container brings its own stack
# but the host's device driver must be bound in.  On a real TPU-VM the
# image's own libtpu drives the local chips and this stays empty; on a
# tunneled dev box the shim (e.g. /root/.axon_site) is the only road to
# the device.  Everything else still comes from the image: the shim dir
# contains only the driver plugin, no python stack.
#
# Defaults to the reference's literal single-node config `1 0 64 ici`
# (README.md:68-73 analog) on a short protocol; pass extra args after --
# to override.  Writes the full transcript + the result line to
# <out_dir>/exec-rehearsal.txt.  A missing throughput line fails loudly.
set -euo pipefail

TARBALL="${1:?usage: exec-image-benchmark.sh <tarball> [out_dir] [-- args]}"
shift
OUT="$(dirname "$TARBALL")"
case "${1:-}" in
  --) ;;                          # no out_dir given, args follow
  -*) echo "error: flags must follow a literal -- separator" >&2
      exit 2 ;;                   # not silently an out_dir named "-x..."
  ?*) OUT="$1"; shift ;;
esac
if [ "${1:-}" = "--" ]; then shift; fi
EXTRA=("$@")
[ ${#EXTRA[@]} -gt 0 ] || EXTRA=(--num_warmup_batches=10 --num_batches=30)

PREFIX="$(mktemp -d /tmp/tpu-hc-image-exec.XXXXXX)"
trap 'rm -rf "$PREFIX"' EXIT
mkdir -p "$OUT"
REC="$OUT/exec-rehearsal.txt"

{
  echo "== exec-image-benchmark $(date -u +%Y-%m-%dT%H:%M:%SZ) =="
  echo "image: $TARBALL ($(du -h "$TARBALL" | cut -f1))"
  echo "sha256: $(sha256sum "$TARBALL" | cut -d' ' -f1)"
  echo "fresh prefix: $PREFIX"
  tar -C "$PREFIX" -xzf "$TARBALL"
  PY="$PREFIX/venv/bin/python"
  echo "image python: $($PY --version 2>&1)"
  # no host PYTHONPATH, no repo cwd: everything must come from the image
  # (except the optional device-driver shim — see DRIVER_SITE above)
  if [ -n "${DRIVER_SITE:-}" ]; then
    echo "driver shim bound in: DRIVER_SITE=$DRIVER_SITE"
    PYENV=(env "PYTHONPATH=$DRIVER_SITE")
  else
    PYENV=(env -u PYTHONPATH)
  fi
  echo "+ $PY -m tpu_hc_bench 1 0 64 ici ${EXTRA[*]}"
  ( cd "$PREFIX" && "${PYENV[@]}" "$PY" -m tpu_hc_bench \
      1 0 64 ici "${EXTRA[@]}" )
  echo "== exec OK =="
} 2>&1 | tee "$REC"

# image members print "total images/sec", text/CTC/integer members
# "total examples/sec" (driver _example_units) — accept either
grep -Eq "total (images|examples)/sec" "$REC" || {
  echo "FAIL: no throughput line in $REC" >&2; exit 1; }
