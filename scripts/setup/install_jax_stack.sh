#!/usr/bin/env bash
# Install the pinned JAX/TPU software stack — the counterpart of the
# reference's from-source toolchain builds (install_gcc-8.2.sh,
# install_ucx_ompi.sh, install_conda_tf_hvd.sh).  Pinned-version ethos
# preserved: a known-good version set, installed idempotently.  On images
# where the stack is already baked (this repo's CI container, Cloud TPU-VM
# base images), detection short-circuits to a no-op.
#
#   usage: ./install_jax_stack.sh <stable|nightly>
set -euo pipefail

CHANNEL="${1:-stable}"
# the version lock lives in ONE place (stack-pins.txt) shared with the
# Dockerfile and build-venv-image.sh, so host and image cannot drift
PINS="$(cd "$(dirname "$0")" && pwd)/stack-pins.txt"

if python - <<'EOF'
import sys
try:
    import jax, flax, optax  # noqa
except Exception:
    sys.exit(1)
sys.exit(0)
EOF
then
    echo "jax stack already present: $(python -c 'import jax; print(jax.__version__)') — skipping install"
    exit 0
fi

if ! command -v pip >/dev/null; then
    echo "pip unavailable and jax missing; cannot install" >&2
    exit 1
fi

case "$CHANNEL" in
    stable)
        PIN_JAX="$(grep -oP '^jax==\K.*' "$PINS")"
        pip install "jax[tpu]==${PIN_JAX}" -r "$PINS" \
            -f https://storage.googleapis.com/jax-releases/libtpu_releases.html
        ;;
    nightly)
        pip install --pre -U jax[tpu] flax optax chex einops \
            -f https://storage.googleapis.com/jax-releases/libtpu_releases.html
        ;;
esac
