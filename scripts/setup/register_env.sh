#!/usr/bin/env bash
# Append this install's exports to the env registry — the setenv role
# (reference: every installer appends to /mnt/shared/setenv,
# install_gcc-8.2.sh:34-41).  Uses the idempotent python registry so
# re-running replaces rather than duplicates.
set -euo pipefail

python - <<'EOF'
from tpu_hc_bench import envfile
import sys, pathlib

repo = str(pathlib.Path(__file__ if "__file__" in dir() else ".").resolve())
path = envfile.register("stack", {
    "TPU_HC_BENCH_PYTHON": sys.executable,
    # jit-cache directory: makes recompiles across runs warm, the analog of
    # the reference's one-time 80-minute build amortization
    "JAX_COMPILATION_CACHE_DIR": str(pathlib.Path.home() / ".tpu_hc_bench" / "jit-cache"),
})
print(f"env registry updated: {path}")
EOF
