#!/usr/bin/env bash
# Top-level TPU-VM setup — the counterpart of
# 2-setup-host-and-build-container.sh (reference :6-26): one command that
# prepares a freshly created TPU-VM to run benchmarks.  Where the
# reference's ~80-minute build compiles GCC twice and bakes a Singularity
# image, the TPU-VM path is minutes: install the pinned JAX stack (libtpu
# ships with the TPU-VM image, playing OFED's role — SURVEY.md §2b #24),
# tune the OS, register the env, and run the sanity report (the
# `singularity run` equivalent, build-container.sh:29-30).
#
#   usage: ./setup-tpu-vm.sh <stable|nightly>     (reference: <intelmpi|openmpi>)
set -euo pipefail

CHANNEL="${1:-stable}"
HERE="$(cd "$(dirname "$0")" && pwd)"

case "$CHANNEL" in
    stable|nightly) ;;
    *) echo "usage: $0 <stable|nightly>"; exit 1 ;;
esac

"$HERE/update_config.sh"
"$HERE/install_jax_stack.sh" "$CHANNEL"
"$HERE/register_env.sh"

# sanity report gates success, as singularity run gates the container build
python -m tpu_hc_bench.utils.sanity
echo "setup complete; source \${TPU_HC_BENCH_SETENV:-\$HOME/.tpu_hc_bench/setenv} before running benchmarks"
