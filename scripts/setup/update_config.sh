#!/usr/bin/env bash
# OS tuning for benchmark hosts — the counterpart of
# install-scripts/update_config.sh (memlock/nofile limits :6-11,
# zone_reclaim :18-23, firewalld stop :26).  TPU-VMs need far less: raise
# fd limits for sharded input pipelines and disable transparent hugepage
# defrag stalls.  Every change is skipped gracefully without root.
set -uo pipefail

if [ "$(id -u)" -eq 0 ] && [ -d /etc/security ]; then
    if ! grep -q tpu_hc_bench /etc/security/limits.conf 2>/dev/null; then
        cat >> /etc/security/limits.conf <<'EOF'
# tpu_hc_bench: fd limits for sharded TFRecord input pipelines
* soft nofile 65535
* hard nofile 65535
EOF
        echo "limits.conf: nofile raised to 65535"
    fi
    if [ -w /sys/kernel/mm/transparent_hugepage/defrag ]; then
        echo madvise > /sys/kernel/mm/transparent_hugepage/defrag || true
        echo "transparent_hugepage defrag -> madvise"
    fi
else
    echo "update_config: not root, skipping OS tuning (non-fatal)"
fi
exit 0
