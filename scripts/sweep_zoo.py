"""Measure every zoo model on the local chip(s); emit one JSON line each.

The reference records one number per (model, batch, fabric) run in a tee'd
log (run-tf-sing-ucx-openmpi.sh:9-12); this sweep automates the matrix the
way an operator would drive it, writing ``sweep_results.jsonl`` for
BASELINE.md.  Usage:

    python scripts/sweep_zoo.py [--out FILE] [--models a,b,c]
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys
import time

# (model, per-chip batch) — each entry is the member's BEST-KNOWN config
# (BASELINE.md zoo table) and is only valid TOGETHER with its EXTRA_FLAGS
# entry below: the accumulation members' batches exceed HBM as plain
# one-shot batches and fit only as accum microbatches.  Members without
# an EXTRA_FLAGS entry run plain batches chosen to fill HBM without OOM,
# mirroring tf_cnn_benchmarks' per-model defaults where it has them.
DEFAULT_MATRIX = [
    ("trivial", 512),
    ("lenet", 2048),
    ("alexnet", 2048),
    ("overfeat", 4096),
    ("googlenet", 256),
    ("mobilenet", 256),
    ("nasnet", 128),
    ("nasnetlarge", 128),
    ("densenet40_k12", 512),
    ("densenet100_k12", 256),
    ("resnet18", 256),
    ("resnet34", 256),
    ("resnet50", 128),
    ("resnet101", 512),
    ("resnet152", 512),
    ("resnet50_v2", 1024),
    ("resnet101_v2", 512),
    ("resnet152_v2", 512),
    ("resnet20_cifar", 1024),
    ("resnet56_cifar", 512),
    ("resnet110_cifar", 256),
    ("vgg11", 1024),
    ("vgg16", 1024),
    ("vgg19", 1024),
    ("inception3", 128),
    ("vit_b16", 256),
    ("vit_l16", 512),
    ("inception4", 512),
    ("bert_base", 1024),
    ("bert_large", 1024),
    ("gpt2", 128),
    ("gpt2_medium", 64),
    # round 5: the bf16 accumulator unlocked batch scaling past the
    # bs=16 OOM wall (microbatch 8; BASELINE.md round 5) — +37%
    ("gpt2_moe", 512),
    ("llama_1b", 2),
    # zoo completed round 3 (tf_cnn's last two members)
    # round 4: both members' old tf_cnn-default batches starved the chip
    # (ds2 bs=16 ran the recurrence at M=16; see BASELINE.md "the plain
    # batch-size levers") — these are the measured TPU operating points
    ("ncf", 1048576),
    ("deepspeech2", 256),
]

# per-model extra flags (best-known single-chip configs, BASELINE.md)
EXTRA_FLAGS = {
    "gpt2": ["--attention_impl=flash", "--gradient_accumulation_steps=8"],
    "gpt2_medium": ["--attention_impl=flash",
                    "--gradient_accumulation_steps=16"],
    "gpt2_moe": ["--attention_impl=flash",
                 "--gradient_accumulation_steps=64", "--accum_dtype=bf16"],
    "llama_1b": ["--attention_impl=flash"],
    "bert_base": ["--gradient_accumulation_steps=8"],
    "bert_large": ["--gradient_accumulation_steps=32"],
    "vit_b16": ["--gradient_accumulation_steps=4"],
    "vit_l16": ["--gradient_accumulation_steps=8"],
    "vgg16": ["--gradient_accumulation_steps=8"],
    "vgg11": ["--gradient_accumulation_steps=8"],
    "inception4": ["--gradient_accumulation_steps=8"],
    "resnet101": ["--gradient_accumulation_steps=8"],
    "resnet152": ["--gradient_accumulation_steps=8"],
    "resnet50_v2": ["--gradient_accumulation_steps=8"],
    "resnet101_v2": ["--gradient_accumulation_steps=8"],
    "resnet152_v2": ["--gradient_accumulation_steps=8"],
    "nasnetlarge": ["--gradient_accumulation_steps=8"],
    # round 5: the big-FC conv members amortize optimizer traffic too
    "alexnet": ["--gradient_accumulation_steps=4"],
    "overfeat": ["--gradient_accumulation_steps=16"],
    "vgg19": ["--gradient_accumulation_steps=8"],
}


def run_one(model: str, batch: int, warmup: int, batches: int) -> dict:
    cmd = [
        sys.executable, "-m", "tpu_hc_bench", "1", "0", str(batch), "ici",
        f"--model={model}", "--use_fp16=True",
        f"--num_warmup_batches={warmup}", f"--num_batches={batches}",
        *EXTRA_FLAGS.get(model, []),
    ]
    t0 = time.time()
    rec: dict = {"model": model, "batch_size": batch}
    if EXTRA_FLAGS.get(model):
        rec["flags"] = EXTRA_FLAGS[model]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=1800)
    except subprocess.TimeoutExpired:
        rec.update(wall_s=round(time.time() - t0, 1), error="timeout")
        return rec
    out = proc.stdout + proc.stderr
    rec["wall_s"] = round(time.time() - t0, 1)
    if proc.returncode != 0:
        rec["error"] = out.strip().splitlines()[-1] if out.strip() else "?"
        return rec
    for line in out.splitlines():
        if line.startswith("images/sec/chip:") or "examples/sec/chip" in line:
            # "images/sec/chip: X  step: Yms (p50 Zms)  MFU: W%"
            parts = line.replace("%", "").split()
            rec["per_chip"] = float(parts[1])
            rec["step_ms"] = float(parts[3].rstrip("ms"))
            rec["mfu_pct"] = float(parts[-1])
    return rec


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="sweep_results.jsonl")
    ap.add_argument("--models", default=None,
                    help="comma list; default = full matrix")
    ap.add_argument("--warmup", type=int, default=25)
    ap.add_argument("--batches", type=int, default=60)
    args = ap.parse_args()

    matrix = DEFAULT_MATRIX
    if args.models:
        wanted = set(args.models.split(","))
        matrix = [(m, b) for m, b in DEFAULT_MATRIX if m in wanted]

    with open(args.out, "a") as f:
        for model, batch in matrix:
            rec = run_one(model, batch, args.warmup, args.batches)
            f.write(json.dumps(rec) + "\n")
            f.flush()
            print(json.dumps(rec), flush=True)


if __name__ == "__main__":
    main()
