"""Measure every zoo model on the local chip(s); emit one JSON line each.

The reference records one number per (model, batch, fabric) run in a tee'd
log (run-tf-sing-ucx-openmpi.sh:9-12); this sweep automates the matrix the
way an operator would drive it, writing ``sweep_results.jsonl`` for
BASELINE.md.

The matrix itself (the best-known per-member configs that used to live
here as ``DEFAULT_MATRIX``/``EXTRA_FLAGS``) now lives in
``tpu_hc_bench.tune.space.SEED_CONFIGS`` — one copy shared by this
sweep, the autotuner's search space, and the pruner's HBM model — and
the subprocess launch/timeout/exit-contract/parse logic is
``tpu_hc_bench.tune.runner.run_one``, shared with the successive-halving
search.  Usage:

    python scripts/sweep_zoo.py [--out FILE] [--models a,b,c]

    # re-validate the tuned registry rows for this hardware instead of
    # the seeded matrix (the autotuner's regression loop)
    python scripts/sweep_zoo.py --from_registry [--hardware KEY]
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="sweep_results.jsonl")
    ap.add_argument("--models", default=None,
                    help="comma list; default = full matrix")
    ap.add_argument("--warmup", type=int, default=25)
    ap.add_argument("--batches", type=int, default=60)
    ap.add_argument("--from_registry", action="store_true",
                    help="sweep the tuned-config registry rows for this "
                         "hardware (tpu_hc_bench.tune) instead of the "
                         "seeded best-known matrix")
    ap.add_argument("--hardware", default=None,
                    help="registry hardware key (default: the live "
                         "backend's, honoring TPU_HC_TUNE_HW)")
    args = ap.parse_args()

    from tpu_hc_bench.tune import registry as registry_mod
    from tpu_hc_bench.tune import runner as runner_mod
    from tpu_hc_bench.tune import space as space_mod

    wanted = set(args.models.split(",")) if args.models else None

    # (model, batch, extra flags, provenance) rows to run
    if args.from_registry:
        hardware = args.hardware or registry_mod.hardware_key()
        rows = registry_mod.load_rows(hardware)
        if not rows:
            print(f"no tuned rows for hardware {hardware!r} "
                  f"({registry_mod.registry_path(hardware)}) — run "
                  f"`python -m tpu_hc_bench.tune search` first",
                  file=sys.stderr)
            raise SystemExit(1)
        matrix = []
        for model in sorted(rows):
            if wanted is not None and model not in wanted:
                continue
            try:
                c = space_mod.Candidate.make(
                    model, dict(rows[model]["overrides"]),
                    dict(rows[model].get("base") or {}))
            except ValueError as e:
                # one stale row (lever renamed since the search) must
                # not block re-validating every other member; the
                # tuned-config-staleness lint is the loud gate
                print(f"skipping {model}: {e} (stale registry row?)",
                      file=sys.stderr)
                continue
            matrix.append((model, c.batch_size, c.to_flags(), "registry"))
    else:
        matrix = []
        for model, batch in space_mod.seed_matrix():
            if wanted is not None and model not in wanted:
                continue
            matrix.append((model, batch,
                           space_mod.seed_extra_flags(model), "seed"))

    with open(args.out, "a") as f:
        for model, batch, flags, source in matrix:
            rec = runner_mod.run_one(model, batch, flags,
                                     warmup=args.warmup,
                                     batches=args.batches)
            rec["config_source"] = source
            f.write(json.dumps(rec) + "\n")
            f.flush()
            print(json.dumps(rec), flush=True)


if __name__ == "__main__":
    main()
