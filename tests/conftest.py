"""Test harness: 8 virtual CPU devices standing in for a TPU slice.

The reference has no test suite at all (SURVEY.md §4); its verification is
operational.  We close that gap with unit tests running on a simulated
8-device mesh — the multi-process simulation story SURVEY.md §4 calls for.

NOTE: ``jax_num_cpu_devices`` must be set before the backend initializes,
hence the config calls at conftest import time (before any test module
imports build arrays).
"""

import jax
import pytest

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)
# Persistent XLA executable cache: the suite's cost is dominated by
# compiles of 8-device CPU programs, which are identical run to run —
# a warm cache turns the ~20-min cold lane into a few minutes.
jax.config.update("jax_compilation_cache_dir",
                  "/tmp/tpu_hc_bench_jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


def pytest_addoption(parser):
    parser.addoption(
        "--runslow", action="store_true", default=False,
        help="also run tests marked slow (whole-model param counts and "
             "other heavyweight compiles) — the full lane; the true "
             "multi-process tests are NOT slow-marked and always run")


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: heavyweight whole-model test (runs only with --runslow); "
        "the multi-process suite is deliberately unmarked")


def pytest_collection_modifyitems(config, items):
    if config.getoption("--runslow"):
        return
    skip = pytest.mark.skip(reason="slow test: pass --runslow")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip)


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs


@pytest.fixture(scope="session")
def mesh8():
    from tpu_hc_bench.topology import build_mesh, discover_layout

    return build_mesh(discover_layout())
