"""Test harness: 8 virtual CPU devices standing in for a TPU slice.

The reference has no test suite at all (SURVEY.md §4); its verification is
operational.  We close that gap with unit tests running on a simulated
8-device mesh — the multi-process simulation story SURVEY.md §4 calls for.

NOTE: ``jax_num_cpu_devices`` must be set before the backend initializes,
hence the config calls at conftest import time (before any test module
imports build arrays).
"""

import jax
import pytest

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs


@pytest.fixture(scope="session")
def mesh8():
    from tpu_hc_bench.topology import build_mesh, discover_layout

    return build_mesh(discover_layout())
