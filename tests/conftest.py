"""Test harness: 8 virtual CPU devices standing in for a TPU slice.

The reference has no test suite at all (SURVEY.md §4); its verification is
operational.  We close that gap with unit tests running on a simulated
8-device mesh — the multi-process simulation story SURVEY.md §4 calls for.

NOTE: ``jax_num_cpu_devices`` must be set before the backend initializes,
hence the config calls at conftest import time (before any test module
imports build arrays).  On jax stacks predating the option (0.4.x, where
a bare ``config.update`` raises AttributeError and killed collection of
the whole suite) the ``tpu_hc_bench._compat`` shim — installed by the
package import below, BEFORE the config call — reroutes the update to
the legacy ``XLA_FLAGS=--xla_force_host_platform_device_count=8`` env
flag, which equally must land before backend init.  No try/except here
on purpose: if the (shimmed) call still fails, the backend is already
initialized with the wrong device count, and aborting collection loudly
beats every mesh test failing with confusing shape errors.
"""

import os

import tpu_hc_bench  # noqa: F401  (installs the JAX version shims first)
import jax
import pytest

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)
# Persistent XLA executable cache: the suite's cost is dominated by
# compiles of 8-device CPU programs, which are identical run to run —
# a warm cache turns the ~20-min cold lane into a few minutes.  Gated
# on the stack: on 0.4.x jaxlib, *executing* a cache-deserialized
# CPU executable corrupts the heap (glibc "corrupted double-linked
# list" abort in the PP/donation programs of test_checkpoint_driver),
# so warm runs crashed mid-suite — cold compiles are the price of
# finishing.
from tpu_hc_bench._compat import CAPABILITIES  # noqa: E402

if CAPABILITIES["persistent_compilation_cache"]:
    jax.config.update("jax_compilation_cache_dir",
                      "/tmp/tpu_hc_bench_jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
else:
    # No cache means EVERY run pays full compiles, and LLVM codegen at
    # the default -O3 is the bulk of each one.  -O0 codegen keeps IEEE
    # semantics and the HLO pipeline (fusion/partitioning untouched —
    # only LLVM's optimization of the emitted kernels is skipped) and
    # measures ~60% faster on the compile-bound majority of the suite,
    # against a ~20% runtime penalty on the few conv-runtime-bound
    # tests — the difference between fitting the CI budget and not.
    flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
             if not f.startswith("--xla_backend_optimization_level")]
    flags.append("--xla_backend_optimization_level=0")
    os.environ["XLA_FLAGS"] = " ".join(flags)


def pytest_addoption(parser):
    parser.addoption(
        "--runslow", action="store_true", default=False,
        help="also run tests marked slow (whole-model param counts and "
             "other heavyweight compiles) — the full lane; the true "
             "multi-process tests are NOT slow-marked and always run")


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: heavyweight whole-model test (runs only with --runslow); "
        "the multi-process suite is deliberately unmarked")


def pytest_collection_modifyitems(config, items):
    if config.getoption("--runslow"):
        return
    skip = pytest.mark.skip(reason="slow test: pass --runslow")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip)


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs


@pytest.fixture(scope="session")
def mesh8():
    from tpu_hc_bench.topology import build_mesh, discover_layout

    return build_mesh(discover_layout())


def ceiling_file(tmp_path) -> str:
    """The ONE copy of the test fabric-ceiling sweep (schema 1), shared
    by the session ``rewind_run`` fixture and test_goodput's ceiling
    unit tests — two drifting copies of the sweep schema is how table
    rot starts."""
    import json

    data = {
        "schema": 1, "world_size": 8, "device_kind": "cpu",
        "sweeps": {"allreduce": [
            {"op": "allreduce", "world_size": 8, "message_bytes": 1024,
             "mean_us": 10.0, "algbw_gbps": 0.1, "busbw_gbps": 0.18},
            {"op": "allreduce", "world_size": 8,
             "message_bytes": 1 << 20, "mean_us": 100.0,
             "algbw_gbps": 10.0, "busbw_gbps": 17.5},
        ]},
    }
    p = tmp_path / "sweep.json"
    p.write_text(json.dumps(data))
    return str(p)


# --- serving-lane session fixtures (rounds 16-20) ---------------------
# ONE warmed engine per family, shared by test_serve AND
# test_requests_obs — engine warmup is the serving lane's whole test
# cost, so every closed loop below rides these in VIRTUAL time.

SERVE_VCOSTS = {"prefill": 0.004, "decode": 0.003, "classify": 0.002}


def _serve_quiet(_msg):
    pass


@pytest.fixture(scope="session")
def serve_cfg():
    from tpu_hc_bench import flags

    return flags.BenchmarkConfig(
        model="moe_tiny", workload="serve",
        arrival_rate=50.0, num_requests=8,
        max_prompt_len=8, max_output_len=4,
        max_in_flight=2, kv_page_size=4, seed=0,
    ).resolve()


@pytest.fixture(scope="session")
def moe_engine(serve_cfg):
    from tpu_hc_bench.serve import engine as engine_mod

    return engine_mod.ServeEngine(serve_cfg, print_fn=_serve_quiet)


@pytest.fixture(scope="session")
def moe_requests(serve_cfg, moe_engine):
    from tpu_hc_bench.serve import arrivals

    return arrivals.build_requests(serve_cfg, moe_engine.spec.vocab_size)


@pytest.fixture(scope="session")
def moe_ab(tmp_path_factory, moe_engine, moe_requests):
    """BOTH scheduler arms over the same trace and warmed engine, each
    leaving a real metrics dir — the serving lane's only closed-loop
    runs in the default lane."""
    from tpu_hc_bench.obs import metrics as obs_metrics
    from tpu_hc_bench.serve import engine as engine_mod

    root = tmp_path_factory.mktemp("serve_ab")
    out = {}
    for arm in ("static", "continuous"):
        mdir = str(root / arm)
        writer = obs_metrics.MetricsWriter(
            mdir, obs_metrics.run_manifest(
                cfg=moe_engine.cfg, extra={"workload": "serve"}))
        try:
            summary = moe_engine.run(
                moe_requests, batching=arm, writer=writer,
                clock=engine_mod.VirtualClock(SERVE_VCOSTS))
        finally:
            writer.close()
        out[arm] = {"summary": summary, "mdir": mdir}
    return out


@pytest.fixture(scope="session")
def trivial_engine():
    from tpu_hc_bench import flags
    from tpu_hc_bench.serve import engine as engine_mod

    cfg = flags.BenchmarkConfig(
        model="trivial", workload="serve",
        arrival_rate=100.0, num_requests=6, max_in_flight=2,
        # regression pin: classify members allocate no KV pool, so an
        # explicit --kv_pages below one request's worst case must not
        # crash their construction (it used to trip the decode-lane
        # pool validation)
        kv_pages=2,
    ).resolve()
    return engine_mod.ServeEngine(cfg, print_fn=_serve_quiet)


@pytest.fixture(scope="session")
def rewind_run(tmp_path_factory):
    """ONE tiny driver run with an injected rewind fault, shared by
    every default-lane e2e assertion (test_goodput's acceptance checks
    AND test_memory_obs's ledger/report checks) — session scope so the
    lane pays for a single run no matter how many modules consume it.

    nan at step 1: the double-buffered guard fetch processes window 2's
    counters at window 4, so the rewind lands mid-run with clean replay
    steps after it (goodput strictly between 0 and 1).
    """
    from tpu_hc_bench import flags
    from tpu_hc_bench.train import driver

    tmp = tmp_path_factory.mktemp("shared_e2e")
    ceiling = ceiling_file(tmp)
    mdir = str(tmp / "m")
    cfg = flags.BenchmarkConfig(
        batch_size=2, num_warmup_batches=1, num_batches=6,
        display_every=2, model="trivial", num_classes=10,
        init_learning_rate=0.05, on_nonfinite="rewind",
        inject_fault="nan_loss@1", train_dir=str(tmp / "ck"),
        metrics_dir=mdir, fabric_ceiling=ceiling,
    ).resolve()
    out: list[str] = []
    res = driver.run_benchmark(cfg, print_fn=out.append)
    return {"dir": mdir, "ceiling": ceiling, "result": res,
            "out": out, "tmp": tmp}
