"""The static-analysis subsystem: HLO parser, lint passes, CI gate.

Three layers, matching the acceptance contract:

1. The definition-site HLO parser against a HAND-COUNTED fixture —
   operand references and ``-done`` async halves must be excluded, the
   exact miscounting modes ADVICE r5 flagged in the old whole-text
   regexes.
2. The AST lint passes against deliberately-planted defect fixtures
   (host sync in jit, recompile closure leak, donated-buffer reread)
   AND against the shipped zoo, where they must run clean.
3. The baseline gate plumbing: accepted keys suppress, new
   error/warning findings regress, ``info`` never gates.
4. The round-21 distributed-correctness passes: rank-taint fixtures
   that MUST flag (and clean twins that MUST NOT), dict/set-ordered
   collective loops, and the stream-schema contract checker against a
   synthetic mini-tree plus the real repo's allowlisted seams.
5. The registry/CLI plumbing: pass index completeness, inline
   suppression counted into the report JSON, the atomic ``baseline``
   subcommand, ``--changed-only`` file discovery, and the <30s
   wall-time budget on the repo source gate.

Everything here is in the default (not-slow) lane except the real
world=2 lowering, which pays a full XLA compile.
"""

import collections
import json
import os
import subprocess
import sys

import pytest

from tpu_hc_bench.analysis import contracts, dataflow, hlo, lints, registry, report

# ---------------------------------------------------------------------
# hand-counted fixture: 2 computations; entry has FIVE collective
# definition sites (1 async all-reduce pair = 1, 1 sync all-reduce,
# 1 all-gather, 1 reduce-scatter, 1 collective-permute) but many more
# collective *mentions* (operand references on the fusion/tuple lines,
# the -done line), plus a dot hidden inside a fusion with metadata.
FIXTURE_HLO = """\
HloModule fixture_module, entry_computation_layout={()->f32[2,2]{1,0}}

%add_comp (a: f32[], b: f32[]) -> f32[] {
  %a = f32[] parameter(0)
  %b = f32[] parameter(1)
  ROOT %add.9 = f32[] add(%a, %b)
}

%fused_computation (p0: f32[2,2]) -> f32[2,2] {
  %p0 = f32[2,2]{1,0} parameter(0)
  %dot.7 = f32[2,2]{1,0} dot(%p0, %p0), lhs_contracting_dims={1}, rhs_contracting_dims={0}, metadata={op_name="jit(step)/mlp/dot_general" source_file="model.py" source_line=42}
  ROOT %add.3 = f32[2,2]{1,0} add(%dot.7, %p0)
}

ENTRY %main () -> f32[2,2] {
  %c = f32[2,2]{1,0} constant({{1,2},{3,4}})
  %all-reduce-start.1 = f32[2,2]{1,0} all-reduce-start(%c), replica_groups={{0,1}}, to_apply=%add_comp
  %all-reduce-done.1 = f32[2,2]{1,0} all-reduce-done(%all-reduce-start.1)
  %all-reduce.5 = f32[2,2]{1,0} all-reduce(%all-reduce-done.1), replica_groups={{0,1}}, to_apply=%add_comp
  %all-gather.2 = f32[4,2]{1,0} all-gather(%all-reduce.5), dimensions={0}
  %reduce-scatter.3 = f32[2,2]{1,0} reduce-scatter(%all-gather.2), dimensions={0}, to_apply=%add_comp
  %collective-permute.4 = f32[2,2]{1,0} collective-permute(%reduce-scatter.3), source_target_pairs={{0,1},{1,0}}
  %fusion.1 = f32[2,2]{1,0} fusion(%collective-permute.4, %all-reduce.5), kind=kLoop, calls=%fused_computation
  ROOT %tuple.8 = f32[2,2]{1,0} add(%fusion.1, %all-reduce-done.1)
}
"""

# the hand count: definitions only, -start/-done folded
HAND_COUNT = {
    "all-reduce": 2,        # the async pair (1) + the sync one (1)
    "all-gather": 1,
    "reduce-scatter": 1,
    "collective-permute": 1,
}


def test_collective_counts_match_hand_count_exactly():
    assert hlo.collective_counts(FIXTURE_HLO) == HAND_COUNT


def test_operand_references_never_count():
    # %all-reduce.5 is defined once but *mentioned* on 2 later lines
    # (all-gather operand, fusion operand), and the async pair's names
    # recur as operand references too: 11 "all-reduce" substrings in
    # total — what a whole-text regex (the round-5 approach) counts
    assert FIXTURE_HLO.count("all-reduce") == 11
    assert hlo.collective_counts(FIXTURE_HLO)["all-reduce"] == 2


def test_async_done_unfolded_when_asked():
    raw = hlo.collective_counts(FIXTURE_HLO, fold_async=False)
    # unfolded, the -start and -done halves are distinct opcodes
    assert raw["all-reduce-start"] == 1
    assert raw["all-reduce-done"] == 1
    assert raw["all-reduce"] == 1


def test_parse_structure():
    m = hlo.parse_hlo(FIXTURE_HLO)
    assert m.name == "fixture_module"
    assert set(m.computations) == {"add_comp", "fused_computation", "main"}
    assert m.entry.name == "main"
    assert m.entry.instructions[-1].is_root
    dot = m.find("dot.7")
    assert dot is not None
    assert dot.op_name == "jit(step)/mlp/dot_general"
    assert dot.source == "model.py:42"


def test_fusion_attribution_through_metadata():
    m = hlo.parse_hlo(FIXTURE_HLO)
    attr = hlo.op_attribution(m, opcodes=("dot",))
    # the fusion's dot is attributed via its metadata op_name, not the
    # event-name substring (the fusion's own name says nothing)
    assert attr == {"fusion.1": ["jit(step)/mlp/dot_general"]}
    leaves = hlo.fusion_ops(m, "fusion.1")
    assert [i.opcode for i in leaves] == ["parameter", "dot", "add"]


# ---------------------------------------------------------------------
# lint fixtures: one deliberately-planted defect per family


HOST_SYNC_FIXTURE = """\
import jax
import jax.numpy as jnp
import numpy as np

@jax.jit
def bad_step(x):
    s = x.sum()
    host = s.item()
    arr = np.asarray(x)
    jax.device_get(s)
    return x * host + arr.shape[0]

def good_host_code(x):
    return float(x.sum().item())
"""


def test_host_sync_in_jit_flagged():
    fs = lints.lint_source_text(HOST_SYNC_FIXTURE, "fixture.py")
    msgs = [f for f in fs if f.lint == lints.HOST_SYNC]
    assert len(msgs) == 3, [f.render() for f in fs]
    assert all(f.severity == "error" for f in msgs)
    lines = {int(f.location.rsplit(":", 1)[1]) for f in msgs}
    assert lines == {8, 9, 10}
    # the same .item() OUTSIDE a traced function is host code, not a bug
    assert not any("good_host_code" in f.message for f in fs)


def test_host_sync_suppression_comment():
    src = HOST_SYNC_FIXTURE.replace(
        "host = s.item()",
        "host = s.item()  # thb:lint-ok[host-sync-in-jit]")
    fs = lints.lint_source_text(src, "fixture.py")
    lines = {int(f.location.rsplit(":", 1)[1])
             for f in fs if f.lint == lints.HOST_SYNC}
    assert lines == {9, 10}


RECOMPILE_FIXTURE = """\
import jax

def train(n_steps, data):
    scale = 0
    def step(x):
        return x * scale
    jitted = jax.jit(step)
    for scale in range(n_steps):
        jitted(data)
"""


def test_recompile_closure_leak_flagged():
    fs = lints.lint_source_text(RECOMPILE_FIXTURE, "fixture.py")
    hits = [f for f in fs if f.lint == lints.RECOMPILE]
    assert len(hits) == 1
    assert hits[0].severity == "warning"
    assert "`scale`" in hits[0].message


SHAPE_BRANCH_FIXTURE = """\
import jax

@jax.jit
def f(x):
    if x.shape[0] > 128:
        return x[:128]
    return x
"""


def test_shape_vs_literal_branch_is_info_only():
    fs = lints.lint_source_text(SHAPE_BRANCH_FIXTURE, "fixture.py")
    hits = [f for f in fs if f.lint == lints.RECOMPILE]
    assert len(hits) == 1
    assert hits[0].severity == "info"
    # info findings never gate
    assert report.compare_to_baseline(hits, baseline=set()) == []


DONATION_FIXTURE = """\
import jax

def run(state, batch):
    step = jax.jit(do_step, donate_argnums=(0,))
    new_state = step(state, batch)
    loss = state.params  # read-after-donate: invalidated buffer
    return new_state, loss

def run_ok(state, batch):
    step = jax.jit(do_step, donate_argnums=(0,))
    state = step(state, batch)  # donate-and-rebind, the idiom
    return state.params
"""


def test_donation_reread_flagged_rebind_clean():
    fs = lints.lint_source_text(DONATION_FIXTURE, "fixture.py")
    hits = [f for f in fs if f.lint == lints.DONATION]
    assert len(hits) == 1
    assert "`state`" in hits[0].message
    assert int(hits[0].location.rsplit(":", 1)[1]) == 6


# ---------------------------------------------------------------------
# the shipped zoo must lint clean (3 representative members: a BN CNN,
# a transformer with the TP rule table, and the MoE member)


@pytest.mark.parametrize("name", ["resnet20_cifar", "bert_tiny", "moe_tiny"])
def test_zoo_member_lints_clean(name):
    findings = lints.lint_model(name)
    gating = [f for f in findings if f.severity in ("error", "warning")]
    assert gating == [], [f.render() for f in gating]


@pytest.fixture(scope="module")
def repo_findings():
    # ONE full repo-source scan shared by the gate test and the
    # contract-seam test below — repeating it mid-suite pays GC churn
    # over the loaded heap, not parse time
    return lints.lint_repo_sources()


def test_repo_sources_have_no_unbaselined_findings(repo_findings):
    regressions = report.compare_to_baseline(repo_findings)
    assert regressions == [], [f.render() for f in regressions]


# ---------------------------------------------------------------------
# baseline gate plumbing


def test_baseline_roundtrip_and_gate(tmp_path):
    f1 = report.Finding(lint="host-sync-in-jit", severity="error",
                        model="repo", location="pkg/mod.py:10", message="m")
    f2 = report.Finding(lint="sharding-consistency", severity="warning",
                        model="bert_tiny", location="param:qkv/kernel",
                        message="n")
    path = tmp_path / "baseline.json"
    report.save_baseline([f1], path)
    accepted = report.load_baseline(path)
    assert accepted == {f1.key}
    # accepted finding passes; novel finding regresses
    assert report.compare_to_baseline([f1], accepted) == []
    assert report.compare_to_baseline([f1, f2], accepted) == [f2]
    # line-number churn does not churn identity (key drops the line)
    moved = report.Finding(lint=f1.lint, severity=f1.severity,
                           model=f1.model, location="pkg/mod.py:99",
                           message=f1.message)
    assert report.compare_to_baseline([moved], accepted) == []


def test_non_file_locations_keep_distinct_keys():
    # only a NUMERIC (line) suffix is stripped from the key: two
    # sharding findings on different params of the same model must NOT
    # collapse to one baseline key (accepting one would mask the other)
    f_a = report.Finding(lint="sharding-consistency", severity="warning",
                         model="bert_tiny", location="param:layer_0/qkv",
                         message="m")
    f_b = report.Finding(lint="sharding-consistency", severity="warning",
                         model="bert_tiny", location="param:layer_5/out",
                         message="m")
    assert f_a.key != f_b.key
    assert report.compare_to_baseline([f_b], {f_a.key}) == [f_b]
    j = report.Finding(lint="host-sync-in-jit", severity="warning",
                       model="bert_tiny", location="jaxpr:pure_callback",
                       message="m")
    assert "pure_callback" in j.key


def test_save_baseline_merge_preserves_other_keys(tmp_path):
    # a partial (--model) --update-baseline run must only ADD keys
    f1 = report.Finding(lint="host-sync-in-jit", severity="error",
                        model="bert_tiny", location="a.py:1", message="m")
    f2 = report.Finding(lint="host-sync-in-jit", severity="error",
                        model="resnet50", location="b.py:2", message="m")
    path = tmp_path / "baseline.json"
    report.save_baseline([f1, f2], path)
    report.save_baseline([f1], path, merge=report.load_baseline(path))
    assert report.load_baseline(path) == {f1.key, f2.key}


def test_checked_in_baseline_is_loadable():
    accepted = report.load_baseline()
    assert isinstance(accepted, set)
    data = json.loads(report.BASELINE_PATH.read_text())
    assert sorted(accepted) == data["accepted"]


def test_findings_json_stable_shape():
    f = report.Finding(lint="host-sync-in-jit", severity="error",
                       model="repo", location="a.py:1", message="m")
    payload = json.loads(report.findings_to_json(
        [f], {"resnet20_cifar": {"all-reduce": 3}}))
    assert payload["findings"][0]["lint"] == "host-sync-in-jit"
    assert payload["collectives"]["resnet20_cifar"] == {"all-reduce": 3}


# ---------------------------------------------------------------------
# the real thing: the compiled world=2 step (one full XLA compile, so
# slow-lane; the counts themselves are pinned in BASELINE.md and
# re-emitted by scripts/exp_hlo_collectives_r05.py)


@pytest.mark.slow
def test_world2_lowering_counts_definition_sites(devices):
    text = hlo.lower_world_step_hlo("resnet20_cifar", batch=8, world=2)
    counts = hlo.collective_counts(text)
    # post-BN-bucketing resnet20: gradient+BN-stat fusion buckets only —
    # and definition-site counting must come in far below the raw
    # mention count the old regex reported (operand refs inflate it)
    assert set(counts) == {"all-reduce"}
    assert counts["all-reduce"] == 3
    assert text.count("all-reduce") > counts["all-reduce"]


def test_zero1_lowering_emits_reduce_scatter_all_gather(devices):
    """The zero1 arm's compiled world=2 step must shard the gradient
    path: reduce-scatter + all-gather present, all-reduce budget only
    for the loss pmean — the program property the arm exists for.
    Trivial member: cheap compile, no BN stats."""
    text = hlo.lower_world_step_hlo(
        "trivial", batch=2, world=2, variable_update="zero1",
        fusion_threshold_bytes=256, num_classes=10)
    counts = hlo.collective_counts(text)
    assert counts.get("reduce-scatter", 0) >= 1
    assert counts.get("all-gather", 0) >= 1
    assert counts.get("all-reduce", 0) <= 1     # the scalar loss pmean


def test_check_zero1_collectives_clean_and_loud():
    """The lint wrapper: clean on the healthy arm; doctored count sets
    produce collective-shape findings (the pure half, no compile)."""
    from tpu_hc_bench.analysis import lints

    assert lints.check_zero1_collectives(
        "trivial", world=2, fusion_threshold_bytes=256) == []
    # gradient path not sharded at all
    got = lints.zero1_shape_findings("m", {"all-reduce": 5})
    assert len(got) == 2 and all(f.lint == "collective-shape" for f in got)
    assert "not optimizer-sharded" in got[0].message
    # sharded, but gradient buckets ALSO riding a full all-reduce
    got = lints.zero1_shape_findings(
        "m", {"reduce-scatter": 4, "all-gather": 4, "all-reduce": 6})
    assert len(got) == 1 and "full all-reduce" in got[0].message
    # healthy: rs/ag pair + the loss pmean
    assert lints.zero1_shape_findings(
        "m", {"reduce-scatter": 2, "all-gather": 2, "all-reduce": 1}) == []


def test_overlap_off_pins_optimization_barrier(devices):
    """--overlap_grad_comm=off must compile the full-gradient-tree
    barrier into the program (comm strictly after the complete
    backward); on must not.  Asserted on the PRE-optimization text —
    the CPU backend deletes opt-barrier during optimization (no latency
    scheduling), the TPU pipeline schedules around it."""
    on = hlo.lower_world_step_hlo(
        "trivial", batch=2, world=2, fusion_threshold_bytes=256,
        num_classes=10, optimize=False)
    off = hlo.lower_world_step_hlo(
        "trivial", batch=2, world=2, fusion_threshold_bytes=256,
        num_classes=10, overlap_grad_comm="off", optimize=False)
    assert "optimization_barrier" not in on
    assert "optimization_barrier" in off
    # zero1 honors the same flag
    z_off = hlo.lower_world_step_hlo(
        "trivial", batch=2, world=2, variable_update="zero1",
        fusion_threshold_bytes=256, num_classes=10,
        overlap_grad_comm="off", optimize=False)
    assert "optimization_barrier" in z_off


# ---------------------------------------------------------------------
# round-21 dataflow passes: rank taint -> collectives.  Hazard fixtures
# that MUST flag; clean twins (the repo's own idioms) that MUST NOT.


RANK_DIVERGENT_FIXTURE = """\
import jax
from tpu_hc_bench.parallel import collectives

def commit_step(grads, step):
    if jax.process_index() == 0:
        total = collectives.psum(grads)      # only rank 0 enters
        return total
    return step

def gated_early_exit(state, rank):
    if rank != 0:
        return state
    return collectives.all_gather(state)

def laundered_through_assignment(x):
    me = jax.process_index()
    is_leader = me == 0
    if is_leader:
        collectives.broadcast_one_to_all(x)

def divergent_trip_count(queue, process_index):
    while process_index < len(queue):
        collectives.psum(queue[0])
        process_index += 1
"""


def test_rank_divergent_collectives_flagged():
    fs = lints.lint_source_text(RANK_DIVERGENT_FIXTURE, "fixture.py")
    hits = [f for f in fs if f.lint == dataflow.RANK_DIVERGENT]
    assert len(hits) == 4, [f.render() for f in fs]
    assert all(f.severity == "error" for f in hits)
    lines = {int(f.location.rsplit(":", 1)[1]) for f in hits}
    # the one-sided psum, the post-early-exit all_gather, the broadcast
    # behind a laundered taint, and the while-loop psum
    assert lines == {6, 13, 19, 23}
    assert any("early exit" in f.message for f in hits)
    assert any("while-loop" in f.message for f in hits)


RANK_CLEAN_FIXTURE = """\
import jax
from tpu_hc_bench.parallel import collectives
from tpu_hc_bench.utils import sync

def log_on_worker_zero(metrics, step):
    if jax.process_index() == 0:
        print("step", step, metrics)     # rank-gated HOST work: fine
    return step

def single_host_fast_path(flag):
    # the utils.sync idiom: process_count() is uniform across ranks,
    # so this branch does NOT diverge — every rank takes the same arm
    if jax.process_count() <= 1:
        return bool(flag)
    return sync.all_processes_any(flag)

def matched_arms(x, rank):
    if rank == 0:
        y = collectives.psum(x)
    else:
        y = collectives.psum(x * 0)      # both arms issue the psum
    return y

def raise_only_guard(cfg, rank):
    if rank >= cfg.world:
        raise ValueError("rank out of range")   # no collectives follow
"""


def test_rank_divergence_clean_twins_do_not_flag():
    fs = lints.lint_source_text(RANK_CLEAN_FIXTURE, "fixture.py")
    hits = [f for f in fs if f.lint == dataflow.RANK_DIVERGENT]
    assert hits == [], [f.render() for f in hits]


NONDET_ORDER_FIXTURE = """\
from tpu_hc_bench.parallel import collectives

def allreduce_by_dict_walk(grads):
    for name, g in grads.items():
        grads[name] = collectives.psum(g)

def barrier_per_set_member(x):
    for h in {"alpha", "beta"}:
        collectives.barrier(x)

def allreduce_sorted(grads):
    for name, g in sorted(grads.items()):
        grads[name] = collectives.psum(g)    # canonical order: fine

def fold_host_side(stats):
    out = 0.0
    for k, v in stats.items():
        out += v                             # no collective: fine
    return out
"""


def test_nondeterministic_collective_order():
    fs = lints.lint_source_text(NONDET_ORDER_FIXTURE, "fixture.py")
    hits = [f for f in fs if f.lint == dataflow.NONDET_ORDER]
    assert len(hits) == 2, [f.render() for f in fs]
    assert all(f.severity == "error" for f in hits)
    lines = {int(f.location.rsplit(":", 1)[1]) for f in hits}
    assert lines == {4, 8}       # the dict walk and the set literal
    assert any("insertion" in f.message for f in hits)
    assert any("hash order" in f.message for f in hits)


def test_dataflow_suppression_counted_into_report_json():
    src = RANK_DIVERGENT_FIXTURE.replace(
        "total = collectives.psum(grads)      # only rank 0 enters",
        "total = collectives.psum(grads)  "
        "# tpu-hc: disable=rank-divergent-collective")
    counters = collections.Counter()
    fs = lints.lint_source_text(src, "fixture.py", counters=counters)
    lines = {int(f.location.rsplit(":", 1)[1])
             for f in fs if f.lint == dataflow.RANK_DIVERGENT}
    assert 6 not in lines and len(lines) == 3
    assert counters[dataflow.RANK_DIVERGENT] == 1
    # the suppression hit survives into the report payload
    payload = json.loads(report.findings_to_json(
        [], suppressed=dict(counters)))
    assert payload["suppressed"] == {dataflow.RANK_DIVERGENT: 1}


# ---------------------------------------------------------------------
# the stream-schema contract checker: a synthetic mini-tree with a
# planted typo'd read, a phantom kind, and a dead stream field — then
# the real repo, where every contract finding must be an allowlisted
# (info) seam


def _mini_tree(tmp_path):
    obs = tmp_path / "tpu_hc_bench" / "obs"
    obs.mkdir(parents=True)
    (obs / "metrics.py").write_text(
        'def _of_kind(records, kind):\n'
        '    return [r for r in records if r.get("kind") == kind]\n'
        '\n'
        'def summarize(records):\n'
        '    steps = [r for r in records if r.get("kind") == "step"]\n'
        '    ghosts = _of_kind(records, "phantom")\n'
        '    return {\n'
        '        "good": sum(r.get("good_key", 0) for r in steps),\n'
        '        "typo": sum(r.get("typo_keyy", 0) for r in steps),\n'
        '        "ghost": len(ghosts),\n'
        '    }\n')
    pkg = tmp_path / "tpu_hc_bench"
    (pkg / "writer.py").write_text(
        'def emit(writer, x, now):\n'
        '    writer.event("step", good_key=x, dead_field=2 * x)\n'
        '    return {"kind": "hb", "dead_field": now}\n')
    return tmp_path


def test_contract_checker_flags_orphans(tmp_path):
    root = _mini_tree(tmp_path)
    no_allow = tmp_path / "missing_allowlist.json"
    fs = contracts.check_stream_contracts(root=root,
                                          allowlist_path=no_allow)
    warn = sorted(f.location for f in fs if f.severity == "warning")
    # the typo'd field read and the never-emitted kind gate; the
    # correctly-spelled good_key and the written kinds do not
    assert warn == ["obs/metrics.py::kind=phantom",
                    "obs/metrics.py::typo_keyy"], \
        [f.render() for f in fs]
    infos = [f for f in fs if f.severity == "info"]
    assert any(f.location == "stream-writers"
               and "dead_field" in f.message for f in infos)
    assert any(f.location == "stream-writers::kinds"
               and "hb" in f.message for f in infos)


def test_contract_allowlist_downgrades_to_visible_info(tmp_path):
    root = _mini_tree(tmp_path)
    allow = tmp_path / "allow.json"
    allow.write_text(json.dumps({
        "reads": {"typo_keyy": "test seam: external writer",
                  "phantom": "test seam: external kind"},
        "writes": {"dead_field": "forensics only", "hb": "external"},
    }))
    fs = contracts.check_stream_contracts(root=root, allowlist_path=allow)
    assert all(f.severity == "info" for f in fs), [f.render() for f in fs]
    # the allowlisted seam is REPORTED (visible), not silenced, and
    # carries its reason
    seam = [f for f in fs if f.location.endswith("::typo_keyy")]
    assert len(seam) == 1
    assert "test seam: external writer" in seam[0].message
    # info never gates
    assert report.compare_to_baseline(fs, baseline=set()) == []


def test_contract_extract_sides(tmp_path):
    root = _mini_tree(tmp_path)
    reads, kind_reads = contracts.extract_reads(root)
    assert {"good_key", "typo_keyy", "kind"} <= set(reads)
    assert {"step", "phantom"} <= set(kind_reads)
    broad, stream, kind_writes = contracts.extract_writes(root)
    assert {"good_key", "dead_field", "kind"} <= set(broad)
    assert set(stream) == {"good_key", "dead_field"}
    assert set(kind_writes) == {"step", "hb"}


def test_repo_contract_findings_all_allowlisted_info(repo_findings):
    fs = [f for f in repo_findings
          if f.lint in (contracts.ORPHAN_READ, contracts.ORPHAN_WRITE)]
    assert fs, "contract pass produced no findings — seams went silent"
    gating = [f for f in fs if f.severity in ("error", "warning")]
    assert gating == [], [f.render() for f in gating]
    # the r20 zero-component-normalizer seam round-trips through the
    # allowlist: visible as info, never silent
    assert any(f.location.endswith("::queue_wait") for f in fs), \
        [f.render() for f in fs]


# ---------------------------------------------------------------------
# registry + CLI plumbing


def test_pass_registry_index_complete():
    rows = registry.pass_index()
    names = {r[0] for r in rows}
    assert {"host-sync-in-jit", "recompile-hazard",
            dataflow.RANK_DIVERGENT, dataflow.NONDET_ORDER,
            contracts.ORPHAN_READ, contracts.ORPHAN_WRITE} <= names
    assert len(rows) >= 18
    for name, severity, scope, doc, _example in rows:
        assert severity in ("error", "warning", "info"), name
        assert scope in ("jit", "file", "repo", "model"), name
        assert doc, f"pass {name} registered without a doc line"
    assert registry.default_severity(dataflow.RANK_DIVERGENT) == "error"
    assert registry.default_severity("no-such-pass") == "warning"


def test_changed_python_files_discovery(tmp_path):
    root = __import__("pathlib").Path(lints.__file__).resolve().parents[2]
    files = registry.changed_python_files(root)
    if files is None:
        pytest.skip("git unavailable in this environment")
    assert all(str(p).endswith(".py") for p in files)
    # a non-repo directory fails OPEN (None -> caller uses full tree)
    assert registry.changed_python_files(tmp_path) is None


def test_baseline_subcommand_dry_run_then_update(tmp_path, monkeypatch):
    from tpu_hc_bench.analysis import __main__ as cli
    f1 = report.Finding(lint="host-sync-in-jit", severity="error",
                        model="repo", location="x.py:3", message="m")
    f2 = report.Finding(lint="dead-info", severity="info",
                        model="repo", location="y.py:1", message="m")
    monkeypatch.setattr(
        lints, "lint_repo_sources",
        lambda root=None, files=None, counters=None: [f1, f2])
    path = tmp_path / "baseline.json"
    # dry run against an empty baseline: diff -> exit 1, file untouched
    assert cli.main(["baseline", "--baseline", str(path)]) == 1
    assert not path.exists()
    # --update writes it (error/warning keys only; info never baselines)
    assert cli.main(["baseline", "--update", "--baseline", str(path)]) == 0
    assert report.load_baseline(path) == {f1.key}
    # now the dry run agrees, and no tmp litter remains from the
    # atomic tmp -> fsync -> rename write
    assert cli.main(["baseline", "--baseline", str(path)]) == 0
    assert [p.name for p in tmp_path.iterdir()] == ["baseline.json"]


def test_save_baseline_reports_key_diff(tmp_path):
    f1 = report.Finding(lint="a-lint", severity="error", model="repo",
                        location="a.py:1", message="m")
    f2 = report.Finding(lint="b-lint", severity="error", model="repo",
                        location="b.py:1", message="m")
    path = tmp_path / "b.json"
    added, removed = report.save_baseline([f1], path)
    assert (added, removed) == ([f1.key], [])
    added, removed = report.save_baseline([f2], path)
    assert (added, removed) == ([f2.key], [f1.key])


def test_repo_source_gate_under_wall_budget(tmp_path):
    # the ISSUE's default-lane budget: the full repo source gate (every
    # file pass over the tree + the repo-scope contract/staleness
    # passes) must stay interactive.  Measured on the REAL CLI in a
    # fresh subprocess — an in-process rerun here would time GC churn
    # over the loaded suite's heap, not the gate — using the gate's own
    # wall_s as threaded into the report JSON.  rc 0 doubles as the
    # "repo baseline is up to date" acceptance check.
    # wall_s on a contended runner times the neighbors, not the gate:
    # one retry absorbs transient load while a genuinely slow gate
    # still fails both measurements.
    out = tmp_path / "report.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    for _ in range(2):
        proc = subprocess.run(
            [sys.executable, "-m", "tpu_hc_bench.analysis", "baseline",
             "--json", str(out)],
            capture_output=True, text=True, timeout=120, env=env)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "baseline up to date" in proc.stdout
        payload = json.loads(out.read_text())
        if payload["wall_s"] < 30.0:
            break
    assert payload["wall_s"] < 30.0, payload["wall_s"]
    assert "findings" in payload
