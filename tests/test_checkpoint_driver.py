"""--train_dir / --eval checkpoint wiring through the benchmark driver.

The round-1 gap (VERDICT weak #3): utils/checkpoint.py existed but was
unreachable from the CLI, and --eval measured random init.  These tests
drive the full tf_cnn_benchmarks train_dir contract: train -> checkpoint ->
eval-from-checkpoint, resume, the random-init warning, and the DP<->DPxPP
checkpoint interchange through run_benchmark.
"""

import numpy as np
import pytest

from tpu_hc_bench import flags
from tpu_hc_bench._compat import CAPABILITIES
from tpu_hc_bench.train import driver


def tiny_cfg(**kw):
    base = dict(
        batch_size=2, num_warmup_batches=1, num_batches=4, display_every=2,
        model="trivial", num_classes=10, init_learning_rate=0.05,
    )
    base.update(kw)
    return flags.BenchmarkConfig(**base).resolve()


def test_train_checkpoint_eval_roundtrip(mesh8, tmp_path):
    train_dir = str(tmp_path / "ckpt")
    out = []
    cfg = tiny_cfg(train_dir=train_dir)
    driver.run_benchmark(cfg, print_fn=out.append)
    text = "\n".join(out)
    assert "checkpoint saved" in text

    # eval restores the trained params (not random init: no warning)
    out = []
    cfg = tiny_cfg(train_dir=train_dir, eval=True, num_batches=2)
    res = driver.run_benchmark(cfg, print_fn=out.append)
    text = "\n".join(out)
    assert "restored checkpoint step 5" in text   # 1 warmup + 4 timed
    assert "RANDOMLY" not in text
    assert np.isfinite(res.final_loss)

    # training again from the same dir resumes
    out = []
    cfg = tiny_cfg(train_dir=train_dir)
    driver.run_benchmark(cfg, print_fn=out.append)
    assert "restored checkpoint step 5" in "\n".join(out)


def test_eval_random_init_warns(mesh8):
    out = []
    cfg = tiny_cfg(eval=True, num_batches=2)
    driver.run_benchmark(cfg, print_fn=out.append)
    assert "RANDOMLY" in "\n".join(out)


def test_eval_missing_checkpoint_refuses(mesh8, tmp_path):
    cfg = tiny_cfg(eval=True, train_dir=str(tmp_path / "nope"))
    with pytest.raises(FileNotFoundError, match="no checkpoint"):
        driver.run_benchmark(cfg, print_fn=lambda s: None)


def test_save_model_steps_periodic(mesh8, tmp_path):
    from tpu_hc_bench.utils import checkpoint as ckpt

    train_dir = str(tmp_path / "periodic")
    cfg = tiny_cfg(train_dir=train_dir, save_model_steps=2)
    driver.run_benchmark(cfg, print_fn=lambda s: None)
    # saves at timed step 2 (step counter 3) and at the end (step 5)
    assert ckpt.latest_step(train_dir) == 5


@pytest.mark.slow
def test_dp_checkpoint_resumes_under_pp(mesh8, tmp_path):
    """The DP<->DPxPP interchange through the CLI surface: train DP with
    --train_dir, then continue the same checkpoint under
    --pipeline_parallel, then eval it under DP again.

    Slow lane: three full driver compiles for an interchange whose
    restack mechanism is pinned numerically (to 1e-5) by the default-lane
    test of the same name in test_checkpoint_interchange.py."""
    train_dir = str(tmp_path / "interchange")
    out = []
    cfg = tiny_cfg(model="moe_tiny", batch_size=4, train_dir=train_dir)
    driver.run_benchmark(cfg, print_fn=out.append)
    assert "checkpoint saved" in "\n".join(out)

    out = []
    cfg = tiny_cfg(model="moe_tiny", batch_size=4, pipeline_parallel=4,
                   num_batches=2, train_dir=train_dir)
    driver.run_benchmark(cfg, print_fn=out.append)
    text = "\n".join(out)
    assert "restored checkpoint step 5" in text
    assert "checkpoint saved" in text
    # resume-aware stamping: the PP continuation saves ABOVE the restored
    # step (5 restored + 1 warmup + 2 timed), not from zero
    from tpu_hc_bench.utils import checkpoint as ckpt

    assert ckpt.latest_step(train_dir) == 8

    # PP run saved in the DP layout: eval restores it without PP
    out = []
    cfg = tiny_cfg(model="moe_tiny", batch_size=4, eval=True, num_batches=2,
                   train_dir=train_dir)
    res = driver.run_benchmark(cfg, print_fn=out.append)
    assert "restored checkpoint step 8" in "\n".join(out)
    assert np.isfinite(res.final_loss)


# The multi-process --train_dir policy (plain-DP process-0 write, TP/EP/
# SPxTP sharded Orbax I/O, PP-native stacked saves) is covered ONLY by
# the REAL 2-process tests in test_multiprocess.py: a faked
# jax.process_count here would break orbax's multihost gather, and as of
# round 4 no multi-process combination is rejected anymore.


def test_eval_under_tp_matches_dp(mesh8, tmp_path):
    """Round-3: --eval --model_parallel follows the committed TP shardings
    (GSPMD eval arm) and must report the same accuracy/loss as DP eval of
    the same checkpoint."""
    train_dir = str(tmp_path / "tp_eval")
    cfg = tiny_cfg(model="bert_tiny", batch_size=2, train_dir=train_dir)
    driver.run_benchmark(cfg, print_fn=lambda s: None)

    def run_eval(batch_size, **kw):
        out = []
        cfg = tiny_cfg(model="bert_tiny", batch_size=batch_size, eval=True,
                       num_batches=2, train_dir=train_dir, **kw)
        res = driver.run_benchmark(cfg, print_fn=out.append)
        top1 = [l for l in out if "top_1 accuracy" in l][0]
        return res, top1

    # per-worker batch doubled under TP so BOTH runs see the same global
    # batch (16) and therefore the same synthetic token stream
    res_dp, top1_dp = run_eval(batch_size=2)
    res_tp, top1_tp = run_eval(batch_size=4, model_parallel=2)
    assert top1_tp == top1_dp
    np.testing.assert_allclose(res_tp.final_loss, res_dp.final_loss,
                               rtol=1e-5)


def test_eval_under_pp_matches_dp(mesh8, tmp_path):
    """Round 3: --eval under --pipeline_parallel — the forward-only
    pipeline reports the same top-1/loss as DP eval of the same
    checkpoint (per-worker batches chosen so both arms see the same
    global batch of 8 and the same synthetic token stream)."""
    train_dir = str(tmp_path / "pp_eval")
    cfg = tiny_cfg(model="llama_tiny", batch_size=2, train_dir=train_dir)
    driver.run_benchmark(cfg, print_fn=lambda _: None)

    def run_eval(batch_size, **kw):
        out = []
        cfg = tiny_cfg(model="llama_tiny", batch_size=batch_size,
                       eval=True, num_batches=2, train_dir=train_dir, **kw)
        res = driver.run_benchmark(cfg, print_fn=out.append)
        return res, [l for l in out if "top_1 accuracy" in l][0]

    res_dp, top1_dp = run_eval(batch_size=1)
    res_pp, top1_pp = run_eval(batch_size=4, pipeline_parallel=4)
    assert top1_pp == top1_dp
    np.testing.assert_allclose(res_pp.final_loss, res_dp.final_loss,
                               rtol=1e-4)


@pytest.mark.skipif(
    not CAPABILITIES["partial_auto_shard_map"],
    reason="this jax's SPMD partitioner cannot compile the partial-manual "
           "SP eval arm (PartitionId unimplemented)")
def test_eval_under_sp_matches_dp(mesh8, tmp_path):
    """Round 3: --eval under --sequence_parallel — the (data, seq)
    shard_map eval arm reports the same top-1/loss as DP eval of the same
    checkpoint (equal global batch of 8, same token stream)."""
    train_dir = str(tmp_path / "sp_eval")
    cfg = tiny_cfg(model="bert_tiny", batch_size=2, train_dir=train_dir)
    driver.run_benchmark(cfg, print_fn=lambda _: None)

    def run_eval(batch_size, **kw):
        out = []
        cfg = tiny_cfg(model="bert_tiny", batch_size=batch_size,
                       eval=True, num_batches=2, train_dir=train_dir, **kw)
        res = driver.run_benchmark(cfg, print_fn=out.append)
        return res, [l for l in out if "top_1 accuracy" in l][0]

    res_dp, top1_dp = run_eval(batch_size=1)
    res_sp, top1_sp = run_eval(batch_size=2, sequence_parallel=2)
    assert top1_sp == top1_dp
    np.testing.assert_allclose(res_sp.final_loss, res_dp.final_loss,
                               rtol=1e-4)
    # round 4: the DP x SP x TP hybrid eval arm (partial-manual shard_map,
    # model axis auto) reports the same numbers too (global batch still 8:
    # 8 workers x bs 4 / (sp 2 x tp 2))
    res_h, top1_h = run_eval(batch_size=4, sequence_parallel=2,
                             model_parallel=2)
    assert top1_h == top1_dp
    np.testing.assert_allclose(res_h.final_loss, res_dp.final_loss,
                               rtol=1e-4)


@pytest.mark.slow
def test_eval_under_ep_matches_dp(mesh8, tmp_path):
    """--eval --expert_parallel rides the same follow-inputs GSPMD arm as
    TP eval; parity vs DP eval of the same MoE checkpoint.

    Slow lane: the suite's second-heaviest compile, and the GSPMD eval
    arm it exercises is the same one test_eval_under_tp_matches_dp pins
    in the default lane."""
    train_dir = str(tmp_path / "ep_eval")
    cfg = tiny_cfg(model="moe_tiny", batch_size=2, train_dir=train_dir)
    driver.run_benchmark(cfg, print_fn=lambda _: None)

    def run_eval(batch_size, **kw):
        out = []
        cfg = tiny_cfg(model="moe_tiny", batch_size=batch_size, eval=True,
                       num_batches=2, train_dir=train_dir, **kw)
        res = driver.run_benchmark(cfg, print_fn=out.append)
        return res, [l for l in out if "top_1 accuracy" in l][0]

    res_dp, top1_dp = run_eval(batch_size=1)
    res_ep, top1_ep = run_eval(batch_size=2, expert_parallel=2)
    assert top1_ep == top1_dp
    np.testing.assert_allclose(res_ep.final_loss, res_dp.final_loss,
                               rtol=1e-5)
