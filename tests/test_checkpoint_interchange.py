"""Checkpoint interchange between DP TrainState and the PP stacked layout.

The resume contract across parallelism modes: train unsharded (the DP
layout), checkpoint through Orbax, restore, restack into the pipeline
layout — and the DP x PP continuation must match the unsharded
continuation exactly (params AND momentum trace carry over).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from tpu_hc_bench import flags
from tpu_hc_bench.data.synthetic import SyntheticTokens
from tpu_hc_bench.models.gpt import GPTLM
from tpu_hc_bench.parallel import pipeline as pp
from tpu_hc_bench.topology import build_mesh, compute_layout
from tpu_hc_bench.train.step import TrainState
from tpu_hc_bench.utils import checkpoint


def _sgd_step(model, params, opt_state, tx, batch):
    tokens, targets, weights = batch

    def loss_fn(p):
        logits = model.apply({"params": p}, tokens, train=False)
        losses = optax.softmax_cross_entropy_with_integer_labels(
            logits, targets)
        return (losses * weights).sum() / jnp.maximum(weights.sum(), 1.0)

    loss, grads = jax.value_and_grad(loss_fn)(params)
    updates, opt_state = tx.update(grads, opt_state, params)
    return optax.apply_updates(params, updates), opt_state, loss


def test_dp_checkpoint_resumes_under_pp(devices, tmp_path):
    model = GPTLM(vocab_size=256, hidden=32, num_layers=4, heads=4, ffn=64,
                  max_len=32)
    cfg = flags.BenchmarkConfig(model="gpt2", batch_size=1,
                                pipeline_parallel=4).resolve()
    batch = SyntheticTokens(8, 16, vocab_size=256, seed=5,
                            causal_lm=True).batch()
    tx = optax.sgd(cfg.init_learning_rate, momentum=cfg.momentum)

    params0 = model.init(jax.random.PRNGKey(0), batch[0][:1],
                         train=False)["params"]
    opt0 = tx.init(params0)

    # step 1 unsharded, then checkpoint the TrainState layout
    params1, opt1, _ = _sgd_step(model, params0, opt0, tx, batch)
    state1 = TrainState(step=jnp.ones((), jnp.int32), params=params1,
                        batch_stats={}, opt_state=opt1,
                        apply_fn=model.apply, tx=tx)
    checkpoint.save(state1, tmp_path)

    # unsharded continuation (ground truth for step 2)
    ref_params2, _, ref_loss2 = _sgd_step(model, params1, opt1, tx, batch)

    # restore -> restack -> continue under DP x PP
    template = TrainState(step=jnp.zeros((), jnp.int32), params=params0,
                          batch_stats={}, opt_state=tx.init(params0),
                          apply_fn=model.apply, tx=tx)
    restored = checkpoint.restore(template, tmp_path)
    assert int(restored.step) == 1
    pp_params, pp_opt = pp.pp_state_from_train_state(restored,
                                                     model.num_layers)
    mesh = build_mesh(compute_layout(1, 8, 8), pipeline_parallel=4)
    step, _ = pp.build_pp_train_step(mesh, model, cfg, 2, pp_params, pp_opt,
                                     deterministic=True)
    pp_params2, pp_opt2, pp_loss2 = step(pp_params, pp_opt, batch)

    np.testing.assert_allclose(float(pp_loss2), float(ref_loss2), rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-5),
        pp_params2, pp.stack_layer_params(ref_params2, model.num_layers),
    )

    # and back: PP state -> TrainState layout roundtrips exactly
    back = pp.train_state_from_pp(pp_params2, pp_opt2, template,
                                  model.num_layers)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)),
        pp.stack_layer_params(back.params, model.num_layers), pp_params2,
    )


def test_pp_native_checkpoint_roundtrip(mesh8):
    """Round 4: the PP-native sharded checkpoint format (save_pp/
    restore_pp) — a placed pipe-sharded (params, opt_state) round-trips
    bit-exactly through Orbax into a freshly initialized placed template,
    params-only restore included (the eval arm)."""
    import tempfile

    import jax
    import numpy as np

    from tpu_hc_bench import flags, topology
    from tpu_hc_bench.data.synthetic import SyntheticTokens
    from tpu_hc_bench.models import create_model
    from tpu_hc_bench.parallel import pipeline as pipe_mod
    from tpu_hc_bench.utils import checkpoint as ckpt

    layout = topology.discover_layout(workers_per_host=0)
    mesh = topology.build_mesh(layout, pipeline_parallel=4)
    cfg = flags.BenchmarkConfig(model="llama_tiny", batch_size=2,
                                pipeline_parallel=4).resolve()
    model, _ = create_model("llama_tiny")
    tokens = SyntheticTokens(2, 64, vocab_size=1024).batch()[0]
    params, opt_state = pipe_mod.make_pp_state(model, cfg, tokens, mesh)

    with tempfile.TemporaryDirectory() as d:
        ckpt.save_pp(params, opt_state, 7, d)
        assert ckpt.latest_step(d) == 7

        # fresh template with different values but the same shardings
        p2, o2 = pipe_mod.make_pp_state(
            model.clone(), flags.BenchmarkConfig(
                model="llama_tiny", batch_size=2, pipeline_parallel=4,
                seed=99).resolve(), tokens, mesh)
        p2, o2, step = ckpt.restore_pp(p2, o2, d)
        assert step == 7
        for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(p2)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree.leaves(opt_state), jax.tree.leaves(o2)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

        # params-only restore (the eval path)
        p3, _ = pipe_mod.make_pp_state(model.clone(), cfg, tokens, mesh)
        p3, none_opt, step = ckpt.restore_pp(p3, None, d)
        assert none_opt is None and step == 7
        for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(p3)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
