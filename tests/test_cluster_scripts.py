"""The cluster/ops shell layer under test — a gcloud PATH shim.

The reference's L3/L5 scripts were operationally proven on real clusters
(the nmap/sshpass mesh, `setup-pwdless-ssh.sh:37-54`; the pssh fan-out,
`prep-cluster.sh:23-29`; the mpirun hostfile launch,
`run-tf-sing-ucx-openmpi.sh:99-109`) but carried no automated coverage —
and neither did our analogs in `scripts/cluster/` until this file.  A fake
`gcloud` placed first on PATH records every invocation (argv preserved
verbatim, one record per call) and emits canned control-plane output, so
these tests assert, with no network and no cloud project:

- `prep-cluster.sh` writes the right `~/nodeips.txt` (the hostfile
  contract of `setup-pwdless-ssh.sh:32` that our launchers consume),
  fans setup out to every worker, runs the per-host sanity check, and
  fails LOUDLY (nonzero, no stale hostfile) on control-plane errors;
- `launch-pod-benchmark.sh` assembles the right per-worker command
  (the 4-positional `run-tpu-ici.sh` contract) and forwards the full
  documented env list with values that survive shell quoting
  (the `mpirun -x FOO` role, run-tf-sing-ucx-openmpi.sh:104-106);
- the provisioners pass the right create flags and all scripts refuse
  to run without their required arguments or without a gcloud CLI.

No jax import here: pure subprocess tests, fast, in the default gate.
"""

import os
import stat
import subprocess
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
SCRIPTS = REPO / "scripts" / "cluster"

SHIM = """#!/usr/bin/env bash
# fake gcloud: log argv verbatim, emit canned control-plane output
log="${GCLOUD_SHIM_LOG:?shim needs GCLOUD_SHIM_LOG}"
{
  echo "==CALL=="
  printf '%s\\n' "$@"
} >> "$log"
for arg in "$@"; do
  if [ "$arg" = "${GCLOUD_SHIM_FAIL:-__never__}" ]; then
    echo "fake gcloud: simulated $arg failure" >&2
    exit 1
  fi
done
case " $* " in
  *" describe "*) echo "${GCLOUD_SHIM_IPS-10.0.0.1;10.0.0.2;10.0.0.3;10.0.0.4}" ;;
esac
exit 0
"""


def _make_shim(tmp_path):
    """Install the fake gcloud first on PATH; return (env, log_path)."""
    bin_dir = tmp_path / "shimbin"
    bin_dir.mkdir()
    shim = bin_dir / "gcloud"
    shim.write_text(SHIM)
    shim.chmod(shim.stat().st_mode | stat.S_IXUSR)
    log = tmp_path / "gcloud_calls.log"
    home = tmp_path / "home"
    home.mkdir()
    env = dict(os.environ)
    env.update({
        "PATH": f"{bin_dir}:{env['PATH']}",
        "GCLOUD_SHIM_LOG": str(log),
        "HOME": str(home),
    })
    return env, log, home


def _calls(log: Path) -> list[list[str]]:
    """Parse the shim log back into one argv list per gcloud invocation."""
    if not log.exists():
        return []
    records = log.read_text().split("==CALL==\n")
    return [rec.splitlines() for rec in records if rec]


def _run(script, args, env, **kw):
    return subprocess.run(
        ["bash", str(SCRIPTS / script), *args],
        env=env, capture_output=True, text=True, timeout=60, **kw)


# ---------------------------------------------------------------- prep-cluster

def test_prep_cluster_writes_hostfile_contract(tmp_path):
    env, log, home = _make_shim(tmp_path)
    r = _run("prep-cluster.sh", ["mypod", "us-east5-a"], env)
    assert r.returncode == 0, r.stderr
    # the hostfile contract: one IP per line, exactly the endpoints the
    # control plane reported (semicolon-joined in gcloud value format)
    hostfile = home / "nodeips.txt"
    assert hostfile.read_text() == "10.0.0.1\n10.0.0.2\n10.0.0.3\n10.0.0.4\n"
    assert "discovered 4 hosts" in r.stdout
    calls = _calls(log)
    describe = calls[0]
    assert describe[:5] == ["compute", "tpus", "tpu-vm", "describe", "mypod"]
    assert "--zone=us-east5-a" in describe
    assert "--format=value(networkEndpoints[].ipAddress)" in describe
    # no repo-url arg -> no fan-out clone; the per-host sanity check still
    # runs on every worker (the `pssh ibv_devinfo | grep state` analog)
    sanity = calls[-1]
    assert "ssh" in sanity and "--worker=all" in sanity
    cmd = sanity[sanity.index("--command") + 1] if "--command" in sanity \
        else next(a for a in sanity if "sanity" in a)
    assert "python -m tpu_hc_bench.utils.sanity" in cmd
    assert len(calls) == 2


def test_prep_cluster_repo_fanout(tmp_path):
    env, log, _ = _make_shim(tmp_path)
    r = _run("prep-cluster.sh",
             ["mypod", "us-east5-a", "https://example.com/repo.git"], env)
    assert r.returncode == 0, r.stderr
    calls = _calls(log)
    assert len(calls) == 3          # describe, clone fan-out, sanity
    clone = calls[1]
    assert "--worker=all" in clone
    joined = "\n".join(clone)
    assert "git clone https://example.com/repo.git" in joined
    assert "setup-tpu-vm.sh stable" in joined


def test_prep_cluster_single_host_pod(tmp_path):
    env, _, home = _make_shim(tmp_path)
    env["GCLOUD_SHIM_IPS"] = "10.1.2.3"     # v5litepod-1: no semicolons
    r = _run("prep-cluster.sh", ["solo"], env)
    assert r.returncode == 0, r.stderr
    assert (home / "nodeips.txt").read_text() == "10.1.2.3\n"


def test_prep_cluster_fails_loudly_on_describe_error(tmp_path):
    env, _, home = _make_shim(tmp_path)
    env["GCLOUD_SHIM_FAIL"] = "describe"
    r = _run("prep-cluster.sh", ["mypod"], env)
    assert r.returncode != 0
    # a failed discovery must not leave a stale/empty hostfile for a later
    # launcher to consume
    assert not (home / "nodeips.txt").exists()


def test_prep_cluster_fails_loudly_on_empty_discovery(tmp_path):
    env, _, home = _make_shim(tmp_path)
    env["GCLOUD_SHIM_IPS"] = ""
    r = _run("prep-cluster.sh", ["ghostpod"], env)
    assert r.returncode != 0
    assert "no host IPs discovered" in r.stderr
    assert not (home / "nodeips.txt").exists()


def test_prep_cluster_requires_pod_name(tmp_path):
    env, _, _ = _make_shim(tmp_path)
    r = _run("prep-cluster.sh", [], env)
    assert r.returncode != 0
    assert "usage" in r.stderr


# ------------------------------------------------------- launch-pod-benchmark

def test_launch_pod_benchmark_command_assembly(tmp_path):
    env, log, _ = _make_shim(tmp_path)
    r = _run("launch-pod-benchmark.sh",
             ["mypod", "us-east5-a", "2", "0", "64", "ici"], env)
    assert r.returncode == 0, r.stderr
    calls = _calls(log)
    assert len(calls) == 1
    ssh = calls[0]
    assert ssh[:4] == ["compute", "tpus", "tpu-vm", "ssh"]
    assert "mypod" in ssh and "--zone=us-east5-a" in ssh
    assert "--worker=all" in ssh
    cmd = next(a for a in ssh if a.startswith("--command="))
    # the per-worker command: the literal 4-positional launcher contract
    assert "./scripts/run-tpu-ici.sh 2 0 64 ici" in cmd
    # every worker sources the setenv registry first (host/container
    # symmetry of the reference's /mnt/shared/setenv)
    assert "source ${TPU_HC_BENCH_SETENV:-$HOME/.tpu_hc_bench/setenv}" in cmd


def test_launch_pod_benchmark_forwards_full_env_list(tmp_path):
    env, log, _ = _make_shim(tmp_path)
    # every var in the documented forwarding list, with values that break
    # naive quoting (spaces, equals signs) — the `mpirun -x` contract
    fwd = {
        "XLA_FLAGS": "--xla_flag_a=1 --xla_flag_b=2",
        "LIBTPU_INIT_ARGS": "--arg with spaces",
        "JAX_PLATFORMS": "tpu",
        "TPU_HC_BENCH_SETENV": "/opt/custom/setenv",
        "JAX_TRACEBACK_FILTERING": "off",
        "MODEL": "resnet50",
        "NUM_WARMUP": "50",
        "NUM_BATCHES": "100",
        "DATA_DIR": "/mnt/data dir/tfrecords",
        "EXTRA_FLAGS": "--model_parallel=2 --eval",
    }
    env.update(fwd)
    r = _run("launch-pod-benchmark.sh",
             ["mypod", "us-east5-a", "4", "0", "128", "dcn"], env)
    assert r.returncode == 0, r.stderr
    cmd = next(a for a in _calls(log)[0] if a.startswith("--command="))
    for var, val in fwd.items():
        assert f"export {var}=" in cmd, f"{var} not forwarded"
        # the %q-quoted value must round-trip through a shell eval
        check = subprocess.run(
            ["bash", "-c",
             cmd[len("--command="):].split("cd tpu-hc-bench")[0]
             + f'printf %s "${var}"'],
            capture_output=True, text=True, timeout=30,
            env={"PATH": os.environ["PATH"], "HOME": str(tmp_path)})
        assert check.stdout == val, (var, check.stdout, val)
    assert "./scripts/run-tpu-ici.sh 4 0 128 dcn" in cmd


def test_launch_pod_benchmark_omits_unset_env(tmp_path):
    env, log, _ = _make_shim(tmp_path)
    for var in ("XLA_FLAGS", "MODEL", "EXTRA_FLAGS", "DATA_DIR"):
        env.pop(var, None)
    r = _run("launch-pod-benchmark.sh",
             ["mypod", "z", "1", "0", "32", "ici"], env)
    assert r.returncode == 0, r.stderr
    cmd = next(a for a in _calls(log)[0] if a.startswith("--command="))
    assert "export XLA_FLAGS" not in cmd
    assert "export MODEL" not in cmd


def test_launch_pod_benchmark_requires_all_positionals(tmp_path):
    env, _, _ = _make_shim(tmp_path)
    r = _run("launch-pod-benchmark.sh", ["mypod", "zone", "2"], env)
    assert r.returncode != 0


# ------------------------------------------------------------- provisioners

def test_create_tpu_vm_flags(tmp_path):
    env, log, _ = _make_shim(tmp_path)
    r = _run("create-tpu-vm.sh", ["node1"], env)
    assert r.returncode == 0, r.stderr
    create = _calls(log)[0]
    assert create[:5] == ["compute", "tpus", "tpu-vm", "create", "node1"]
    assert "--accelerator-type=v5litepod-1" in create
    assert "--zone=us-central2-b" in create
    assert any(a.startswith("--version=") for a in create)


def test_create_tpu_pod_north_star_default(tmp_path):
    env, log, _ = _make_shim(tmp_path)
    r = _run("create-tpu-pod.sh", ["pod1", "eu-west4-b"], env)
    assert r.returncode == 0, r.stderr
    create = _calls(log)[0]
    # BASELINE north star hardware: v5e-32
    assert "--accelerator-type=v5litepod-32" in create
    assert "--zone=eu-west4-b" in create


def test_create_scripts_require_name(tmp_path):
    env, _, _ = _make_shim(tmp_path)
    for script in ("create-tpu-vm.sh", "create-tpu-pod.sh"):
        r = _run(script, [], env)
        assert r.returncode != 0
        assert "usage" in r.stderr


def test_scripts_require_gcloud_cli(tmp_path):
    """Without any gcloud on PATH every script refuses loudly (this box
    has a real /usr/bin/gcloud, so build a minimal PATH that excludes it
    but keeps the coreutils the scripts need)."""
    tools = tmp_path / "tools"
    tools.mkdir()
    for tool in ("bash", "env", "tr", "wc", "sed", "rm", "printf", "echo"):
        src = Path("/usr/bin") / tool
        if not src.exists():
            src = Path("/bin") / tool
        (tools / tool).symlink_to(src)
    env = {"PATH": str(tools), "HOME": str(tmp_path)}
    for script, args in (
            ("prep-cluster.sh", ["pod"]),
            ("launch-pod-benchmark.sh", ["pod", "z", "1", "0", "32", "ici"]),
            ("create-tpu-vm.sh", ["n"]),
            ("create-tpu-pod.sh", ["n"])):
        r = _run(script, args, env)
        assert r.returncode != 0, script
        assert "gcloud CLI required" in r.stderr, script
