"""Collective wrapper + fusion-buffer tests on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tpu_hc_bench.parallel import collectives
from tpu_hc_bench.topology import DATA_AXIS


def shard(mesh, fn, in_specs=P(DATA_AXIS), out_specs=P(DATA_AXIS)):
    return jax.jit(
        jax.shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    )


def test_psum(mesh8):
    x = jnp.arange(8.0)
    out = shard(mesh8, lambda v: collectives.psum(v), out_specs=P(DATA_AXIS))(x)
    np.testing.assert_allclose(np.asarray(out), np.full(8, 28.0))


def test_pmean(mesh8):
    x = jnp.arange(8.0)
    out = shard(mesh8, lambda v: collectives.pmean(v))(x)
    np.testing.assert_allclose(np.asarray(out), np.full(8, 3.5))


def test_all_gather(mesh8):
    x = jnp.arange(8.0)
    f = shard(mesh8, lambda v: collectives.all_gather(v),
              out_specs=P(DATA_AXIS))
    out = f(x)
    assert out.shape == (64,)
    np.testing.assert_allclose(np.asarray(out)[:8], np.arange(8.0))


def test_reduce_scatter(mesh8):
    x = jnp.ones((128,))  # 16 elems/device; scatter dim must divide by 8
    f = shard(mesh8, lambda v: collectives.reduce_scatter(v))
    out = f(x)
    # psum_scatter of ones over 8 devs -> each element is the sum 8.0
    assert out.shape == (16,)
    np.testing.assert_allclose(np.asarray(out), np.full(16, 8.0))


def test_ppermute_ring(mesh8):
    x = jnp.arange(8.0)
    out = shard(mesh8, lambda v: collectives.ppermute_ring(v))(x)
    # device i's value moves to device i+1
    np.testing.assert_allclose(np.asarray(out), np.roll(np.arange(8.0), 1))


def test_bucket_grouping_respects_threshold():
    leaves = [jnp.ones((n,), jnp.float32) for n in (10, 10, 10, 100, 2)]
    # threshold 80 bytes = 20 f32 elems
    buckets = collectives._flatten_to_buckets(leaves, 80)
    flat = [i for b in buckets for i in b]
    assert flat == list(range(5))  # order preserved, all leaves covered
    # the 400-byte leaf sits alone in its bucket
    assert [3] in buckets


def test_fused_psum_tree_matches_unfused(mesh8):
    key = jax.random.PRNGKey(0)
    tree = {
        "w": jax.random.normal(key, (8, 4)),
        "b": jnp.arange(8.0).reshape(8, 1),
        "small": jnp.ones((8, 2), jnp.bfloat16),
    }

    def fused(t):
        return collectives.fused_psum_tree(t, threshold_bytes=16, average=True)

    def unfused(t):
        return jax.tree.map(lambda g: jax.lax.pmean(g, DATA_AXIS), t)

    f = shard(mesh8, fused, in_specs=P(DATA_AXIS), out_specs=P(DATA_AXIS))
    u = shard(mesh8, unfused, in_specs=P(DATA_AXIS), out_specs=P(DATA_AXIS))
    out_f, out_u = f(tree), u(tree)
    for k in tree:
        np.testing.assert_allclose(
            np.asarray(out_f[k], np.float32),
            np.asarray(out_u[k], np.float32),
            rtol=1e-5,
        )
        assert out_f[k].dtype == tree[k].dtype  # dtype restored after wire


def test_allreduce_gradients_both_paths(mesh8):
    grads = {"a": jnp.ones((8, 3)), "b": jnp.full((8, 2), 2.0)}
    for fuse in (True, False):
        f = shard(
            mesh8,
            lambda g: collectives.allreduce_gradients(g, fuse=fuse),
            in_specs=P(DATA_AXIS),
            out_specs=P(DATA_AXIS),
        )
        out = f(grads)
        np.testing.assert_allclose(np.asarray(out["a"]), np.ones((8, 3)))
        np.testing.assert_allclose(np.asarray(out["b"]), np.full((8, 2), 2.0))


def test_fused_empty_tree_is_noop(mesh8):
    assert collectives.fused_psum_tree({}) == {}


def test_fused_psum_mixed_dtype_bucket_promotes_and_restores(mesh8):
    """Regression (round 6): bf16 + f32 leaves grouped into ONE bucket
    must promote to the wire ``jnp.result_type`` (f32) and restore each
    leaf's original dtype/shape; leaves already at the wire dtype come
    back bitwise."""
    tree = {
        "a_f32": jax.random.normal(jax.random.PRNGKey(1), (8, 3)),
        "b_bf16": (jnp.arange(16.0).reshape(8, 2) / 7).astype(jnp.bfloat16),
        "c_f32": jnp.linspace(0.0, 1.0, 8).reshape(8, 1),
    }

    def one_bucket(t):
        return collectives.fused_psum_tree(t, threshold_bytes=1 << 20,
                                           average=True)

    def per_leaf(t):
        return jax.tree.map(lambda g: jax.lax.pmean(g, DATA_AXIS), t)

    fused = shard(mesh8, one_bucket)(tree)
    ref = shard(mesh8, per_leaf)(tree)
    for k in tree:
        assert fused[k].dtype == tree[k].dtype
        assert fused[k].shape == tree[k].shape
    # f32 leaves rode the wire at their own dtype: bitwise vs plain pmean
    np.testing.assert_array_equal(np.asarray(fused["a_f32"]),
                                  np.asarray(ref["a_f32"]))
    np.testing.assert_array_equal(np.asarray(fused["c_f32"]),
                                  np.asarray(ref["c_f32"]))
    # the bf16 leaf was promoted to the f32 wire (MORE precise than a
    # bf16-wire pmean) then cast back: equals the f32 mean rounded once
    want = np.asarray(
        shard(mesh8, per_leaf)({"b": tree["b_bf16"].astype(jnp.float32)})
        ["b"]).astype(jnp.bfloat16)
    np.testing.assert_array_equal(
        np.asarray(fused["b_bf16"]).astype(np.float32),
        want.astype(np.float32))


def test_fused_psum_same_dtype_bucket_bitwise(mesh8):
    """A same-dtype bucket's pack/reduce/unpack is bitwise lossless:
    fused result == per-leaf psum, element for element."""
    tree = {"w": jax.random.normal(jax.random.PRNGKey(2), (8, 7)),
            "b": jax.random.normal(jax.random.PRNGKey(3), (8, 2))}
    fused = shard(
        mesh8, lambda t: collectives.fused_psum_tree(
            t, threshold_bytes=1 << 20))(tree)
    ref = shard(
        mesh8, lambda t: jax.tree.map(
            lambda g: jax.lax.psum(g, DATA_AXIS), t))(tree)
    for k in tree:
        np.testing.assert_array_equal(np.asarray(fused[k]),
                                      np.asarray(ref[k]))


def test_bucket_order_backward_vs_forward():
    """overlap=on packs buckets in reversed (backward-completion) leaf
    order; off keeps flatten order.  Membership changes, coverage never."""
    leaves = [jnp.ones((n,), jnp.float32) for n in (10, 10, 10, 100, 2)]
    fwd = collectives._flatten_to_buckets(
        leaves, 80, collectives._bucket_order(len(leaves), overlap=False))
    bwd = collectives._flatten_to_buckets(
        leaves, 80, collectives._bucket_order(len(leaves), overlap=True))
    assert [i for b in fwd for i in b] == list(range(5))
    assert [i for b in bwd for i in b] == list(range(5))[::-1]
    assert bwd[0][0] == 4           # last leaf's grad lands first
    assert [3] in bwd               # oversized leaf still alone


def test_reduce_scatter_all_gather_tree_roundtrip(mesh8):
    """The ZeRO-1 wire pair: bucketed reduce-scatter shards then
    all-gather reconstructs the per-leaf pmean exactly — odd leaf sizes
    exercise the per-leaf padding, the small threshold multiple
    buckets, and both overlap arms must agree bitwise."""
    tree = {
        "w": jax.random.normal(jax.random.PRNGKey(0), (8, 5, 3)),
        "b": jnp.arange(8.0).reshape(8, 1),        # 1 elem/shard, pad 0
        "t": jnp.ones((8, 3), jnp.bfloat16),
    }

    def ref(t):
        return jax.tree.map(lambda g: jax.lax.pmean(g, DATA_AXIS), t)

    outs = {}
    for overlap in (True, False):
        def rs_ag(t, ov=overlap):
            shards = collectives.reduce_scatter_tree(
                t, threshold_bytes=64, average=True, overlap=ov)
            # every shard is 1-D of ceil(size/8) elements, leaf dtype
            for leaf, s in zip(jax.tree.leaves(t), jax.tree.leaves(shards)):
                assert s.shape == (collectives.zero1_shard_len(leaf.size, 8),)
                assert s.dtype == leaf.dtype
            return collectives.all_gather_tree(
                shards, t, threshold_bytes=64, overlap=ov)

        outs[overlap] = shard(mesh8, rs_ag)(tree)
    want = shard(mesh8, ref)(tree)
    for k in tree:
        np.testing.assert_allclose(
            np.asarray(outs[True][k], np.float32),
            np.asarray(want[k], np.float32), rtol=1e-6)
        np.testing.assert_array_equal(
            np.asarray(outs[True][k], np.float32),
            np.asarray(outs[False][k], np.float32))
        assert outs[True][k].shape == tree[k].shape


def test_fused_psum_tree_dual_axis(devices):
    """Fusion buckets reduce over a tuple of mesh axes (the DP x SP path)."""
    from jax.sharding import Mesh

    from tpu_hc_bench.topology import SEQ_AXIS

    mesh = Mesh(np.array(devices).reshape(4, 2), (DATA_AXIS, SEQ_AXIS))
    tree = {"a": jnp.arange(8.0).reshape(4, 2), "b": jnp.ones((4, 2))}

    def f(t):
        return collectives.fused_psum_tree(
            t, axis_name=(DATA_AXIS, SEQ_AXIS), threshold_bytes=1 << 20,
            average=True)

    spec = P(DATA_AXIS, SEQ_AXIS)
    out = jax.jit(jax.shard_map(
        f, mesh=mesh, in_specs=(spec,), out_specs=P(),
        check_vma=False))(tree)
    # average over all 8 shards: every leaf equals the global mean of its
    # per-shard values (each shard holds one scalar here)
    np.testing.assert_allclose(float(out["a"][0, 0]), np.arange(8.0).mean())
    np.testing.assert_allclose(float(out["b"][0, 0]), 1.0)
