"""Collective wrapper + fusion-buffer tests on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tpu_hc_bench.parallel import collectives
from tpu_hc_bench.topology import DATA_AXIS


def shard(mesh, fn, in_specs=P(DATA_AXIS), out_specs=P(DATA_AXIS)):
    return jax.jit(
        jax.shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    )


def test_psum(mesh8):
    x = jnp.arange(8.0)
    out = shard(mesh8, lambda v: collectives.psum(v), out_specs=P(DATA_AXIS))(x)
    np.testing.assert_allclose(np.asarray(out), np.full(8, 28.0))


def test_pmean(mesh8):
    x = jnp.arange(8.0)
    out = shard(mesh8, lambda v: collectives.pmean(v))(x)
    np.testing.assert_allclose(np.asarray(out), np.full(8, 3.5))


def test_all_gather(mesh8):
    x = jnp.arange(8.0)
    f = shard(mesh8, lambda v: collectives.all_gather(v),
              out_specs=P(DATA_AXIS))
    out = f(x)
    assert out.shape == (64,)
    np.testing.assert_allclose(np.asarray(out)[:8], np.arange(8.0))


def test_reduce_scatter(mesh8):
    x = jnp.ones((128,))  # 16 elems/device; scatter dim must divide by 8
    f = shard(mesh8, lambda v: collectives.reduce_scatter(v))
    out = f(x)
    # psum_scatter of ones over 8 devs -> each element is the sum 8.0
    assert out.shape == (16,)
    np.testing.assert_allclose(np.asarray(out), np.full(16, 8.0))


def test_ppermute_ring(mesh8):
    x = jnp.arange(8.0)
    out = shard(mesh8, lambda v: collectives.ppermute_ring(v))(x)
    # device i's value moves to device i+1
    np.testing.assert_allclose(np.asarray(out), np.roll(np.arange(8.0), 1))


def test_bucket_grouping_respects_threshold():
    leaves = [jnp.ones((n,), jnp.float32) for n in (10, 10, 10, 100, 2)]
    # threshold 80 bytes = 20 f32 elems
    buckets = collectives._flatten_to_buckets(leaves, 80)
    flat = [i for b in buckets for i in b]
    assert flat == list(range(5))  # order preserved, all leaves covered
    # the 400-byte leaf sits alone in its bucket
    assert [3] in buckets


def test_fused_psum_tree_matches_unfused(mesh8):
    key = jax.random.PRNGKey(0)
    tree = {
        "w": jax.random.normal(key, (8, 4)),
        "b": jnp.arange(8.0).reshape(8, 1),
        "small": jnp.ones((8, 2), jnp.bfloat16),
    }

    def fused(t):
        return collectives.fused_psum_tree(t, threshold_bytes=16, average=True)

    def unfused(t):
        return jax.tree.map(lambda g: jax.lax.pmean(g, DATA_AXIS), t)

    f = shard(mesh8, fused, in_specs=P(DATA_AXIS), out_specs=P(DATA_AXIS))
    u = shard(mesh8, unfused, in_specs=P(DATA_AXIS), out_specs=P(DATA_AXIS))
    out_f, out_u = f(tree), u(tree)
    for k in tree:
        np.testing.assert_allclose(
            np.asarray(out_f[k], np.float32),
            np.asarray(out_u[k], np.float32),
            rtol=1e-5,
        )
        assert out_f[k].dtype == tree[k].dtype  # dtype restored after wire


def test_allreduce_gradients_both_paths(mesh8):
    grads = {"a": jnp.ones((8, 3)), "b": jnp.full((8, 2), 2.0)}
    for fuse in (True, False):
        f = shard(
            mesh8,
            lambda g: collectives.allreduce_gradients(g, fuse=fuse),
            in_specs=P(DATA_AXIS),
            out_specs=P(DATA_AXIS),
        )
        out = f(grads)
        np.testing.assert_allclose(np.asarray(out["a"]), np.ones((8, 3)))
        np.testing.assert_allclose(np.asarray(out["b"]), np.full((8, 2), 2.0))


def test_fused_empty_tree_is_noop(mesh8):
    assert collectives.fused_psum_tree({}) == {}


def test_fused_psum_tree_dual_axis(devices):
    """Fusion buckets reduce over a tuple of mesh axes (the DP x SP path)."""
    from jax.sharding import Mesh

    from tpu_hc_bench.topology import SEQ_AXIS

    mesh = Mesh(np.array(devices).reshape(4, 2), (DATA_AXIS, SEQ_AXIS))
    tree = {"a": jnp.arange(8.0).reshape(4, 2), "b": jnp.ones((4, 2))}

    def f(t):
        return collectives.fused_psum_tree(
            t, axis_name=(DATA_AXIS, SEQ_AXIS), threshold_bytes=1 << 20,
            average=True)

    spec = P(DATA_AXIS, SEQ_AXIS)
    out = jax.jit(jax.shard_map(
        f, mesh=mesh, in_specs=(spec,), out_specs=P(),
        check_vma=False))(tree)
    # average over all 8 shards: every leaf equals the global mean of its
    # per-shard values (each shard holds one scalar here)
    np.testing.assert_allclose(float(out["a"][0, 0]), np.arange(8.0).mean())
    np.testing.assert_allclose(float(out["b"][0, 0]), 1.0)
