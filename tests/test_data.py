"""TFRecord codec + ImageNet pipeline tests (pure host-side, no TF)."""

import jax.numpy as jnp
import numpy as np
import pytest

from tpu_hc_bench.data import imagenet, tfrecord


def test_crc32c_known_vectors():
    # RFC 3720 test vectors for CRC32C
    assert tfrecord.crc32c(b"") == 0
    assert tfrecord.crc32c(b"\x00" * 32) == 0x8A9136AA
    assert tfrecord.crc32c(b"123456789") == 0xE3069283


def test_record_roundtrip(tmp_path):
    path = tmp_path / "test.tfrecord"
    records = [b"hello", b"", b"x" * 1000]
    assert tfrecord.write_records(path, records) == 3
    back = list(tfrecord.read_records(path, verify_crc=True))
    assert back == records


def test_count_records(tmp_path):
    path = tmp_path / "n.tfrecord"
    tfrecord.write_records(path, [b"a", b"bb" * 500, b""])
    assert tfrecord.count_records(path) == 3
    # count_examples sums over a split's shards
    imagenet.make_synthetic_shards(
        tmp_path / "ds", num_shards=3, examples_per_shard=5, image_size=16)
    assert imagenet.count_examples(tmp_path / "ds") == 15


def test_corrupt_crc_detected(tmp_path):
    path = tmp_path / "bad.tfrecord"
    tfrecord.write_records(path, [b"payload"])
    raw = bytearray(path.read_bytes())
    raw[-5] ^= 0xFF  # flip a byte inside the data
    path.write_bytes(bytes(raw))
    with pytest.raises(IOError):
        list(tfrecord.read_records(path, verify_crc=True))


def test_example_roundtrip():
    features = {
        "image/encoded": [b"\xff\xd8jpegdata"],
        "image/class/label": [42],
        "floats": [1.5, -2.25],
        "negative": [-7],
        "text": ["n01440764"],
    }
    data = tfrecord.build_example(features)
    parsed = tfrecord.parse_example(data)
    assert parsed["image/encoded"] == [b"\xff\xd8jpegdata"]
    assert parsed["image/class/label"] == [42]
    assert parsed["floats"] == pytest.approx([1.5, -2.25])
    assert parsed["negative"] == [-7]
    assert parsed["text"] == [b"n01440764"]


def test_shard_assignment():
    shards = [f"s{i}" for i in range(20)]  # the 20-of-1024 subset size
    a = imagenet.shards_for_worker(shards, 0, 4)
    b = imagenet.shards_for_worker(shards, 1, 4)
    assert len(a) == len(b) == 5
    assert not set(a) & set(b)
    # more workers than shards: wraps rather than starving
    c = imagenet.shards_for_worker(shards[:2], 5, 8)
    assert len(c) == 1


def test_synthetic_shards_and_pipeline(tmp_path):
    paths = imagenet.make_synthetic_shards(
        tmp_path, num_shards=2, examples_per_shard=8, image_size=32,
        num_classes=10,
    )
    assert len(paths) == 2
    ds = imagenet.ImageNetDataset(
        tmp_path, global_batch=4, image_size=16, train=True
    )
    it = iter(ds)
    images, labels = next(it)
    assert images.shape == (4, 16, 16, 3)
    assert images.dtype == np.float32
    assert labels.shape == (4,)
    assert (labels >= 0).all() and (labels < 10).all()  # 1-based -> 0-based
    # second batch differs (stream advances)
    images2, labels2 = next(it)
    assert not np.array_equal(images, images2)


def test_eval_central_crop(tmp_path):
    imagenet.make_synthetic_shards(
        tmp_path, num_shards=1, examples_per_shard=4, image_size=48,
        num_classes=5,
    )
    ds = imagenet.ImageNetDataset(
        tmp_path, global_batch=2, image_size=24, train=False
    )
    images, labels = next(iter(ds))
    assert images.shape == (2, 24, 24, 3)
    assert np.isfinite(images).all()


def test_uint8_wire_format_matches_float32(tmp_path):
    """uint8 wire format + device-side normalize == float32 wire format."""
    imagenet.make_synthetic_shards(
        tmp_path, num_shards=1, examples_per_shard=6, image_size=32,
        num_classes=7,
    )
    kw = dict(global_batch=4, image_size=16, train=True, seed=3)
    f32_img, f32_lab = next(iter(
        imagenet.ImageNetDataset(tmp_path, **kw)))
    u8_img, u8_lab = next(iter(
        imagenet.ImageNetDataset(tmp_path, wire_dtype="uint8", **kw)))
    assert u8_img.dtype == np.uint8
    np.testing.assert_array_equal(f32_lab, u8_lab)

    from tpu_hc_bench.train.step import prep_inputs

    np.testing.assert_allclose(np.asarray(prep_inputs(jnp.asarray(u8_img))),
                               f32_img, rtol=1e-5, atol=1e-5)
    # float32 batches pass through untouched
    np.testing.assert_array_equal(
        np.asarray(prep_inputs(jnp.asarray(f32_img))), f32_img)
