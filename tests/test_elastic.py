"""Elastic resume (round 12): topology-neutral checkpoints, restore
onto a different world size/mesh, the kill-N/resume-M proof.

Budget-conscious layout (tier-1 sits near the 870s ceiling): ONE
module-scoped save fixture feeds every default-lane restore assertion
— the psum arm saves its INIT state (no step compile; restore
neutrality doesn't need trained values), the zero1 arm pays the two
step compiles its ``[N, k]`` resplit proof genuinely needs (one on the
8-mesh to make the optimizer state non-trivial, one on the 4-mesh to
prove the resharded state trains).  No new default-lane driver runs;
the kill-8/resume-4 subprocess e2e is ``slow``-marked like the round-8
kill/resume proof it extends.
"""

import os
import shutil
import subprocess
import sys
import threading
import time
from pathlib import Path

import jax
import numpy as np
import pytest

from tpu_hc_bench import flags, resilience, topology
from tpu_hc_bench.analysis import lints
from tpu_hc_bench.data.synthetic import SyntheticImages
from tpu_hc_bench.models import ModelSpec, TrivialModel
from tpu_hc_bench.parallel.collectives import (
    zero1_resplit_rows, zero1_shard_len,
)
from tpu_hc_bench.train import step as step_mod
from tpu_hc_bench.utils import checkpoint as ckpt

REPO = Path(__file__).resolve().parent.parent


def tiny_cfg(**kw):
    base = dict(
        batch_size=2, num_warmup_batches=1, num_batches=4, display_every=2,
        model="trivial", num_classes=10, init_learning_rate=0.05,
    )
    base.update(kw)
    return flags.BenchmarkConfig(**base).resolve()


@pytest.fixture(scope="module")
def mesh4():
    """4 of the 8 virtual devices — the 'survivors' mesh."""
    return topology.build_mesh(topology.discover_layout(workers_per_host=4))


@pytest.fixture(scope="module")
def saved_runs(mesh8, mesh4, tmp_path_factory):
    """The one shared save fixture: psum (init state) and zero1 (stepped
    twice on the 8-mesh) checkpoints with topology sidecars, plus their
    fingerprints and live-topology records for both world sizes."""
    shape = (8, 8, 3)
    spec = ModelSpec("trivial", TrivialModel, shape, 1e6)
    model = TrivialModel(num_classes=10)
    batch = SyntheticImages(16, shape, num_classes=10).batch()
    lay8 = topology.discover_layout()
    lay4 = topology.discover_layout(workers_per_host=4)

    cfg_p = tiny_cfg(fusion_threshold_bytes=256)
    cfg_z = tiny_cfg(variable_update="zero1", fusion_threshold_bytes=256)
    topos = {
        ("psum", 8): topology.topology_record(lay8, mesh8, cfg_p),
        ("psum", 4): topology.topology_record(lay4, mesh4, cfg_p),
        ("zero1", 8): topology.topology_record(lay8, mesh8, cfg_z),
        ("zero1", 4): topology.topology_record(lay4, mesh4, cfg_z),
    }

    state_p = step_mod.replicate_state(
        step_mod.make_train_state(model, cfg_p, batch), mesh8)
    state_z = step_mod.place_zero1_state(
        step_mod.make_zero1_state(model, cfg_z, batch, 8), mesh8)
    sz = step_mod.build_train_step(mesh8, cfg_z, spec)
    dev_batch = step_mod.shard_batch(batch, mesh8)
    rng = jax.random.PRNGKey(0)
    for _ in range(2):
        state_z, _ = sz(state_z, dev_batch, rng)

    dirs = {}
    for arm, state in (("psum", state_p), ("zero1", state_z)):
        d = tmp_path_factory.mktemp(f"ck_{arm}")
        ckpt.save(state, d, topology=topos[(arm, 8)])
        dirs[arm] = d

    # zero-filled HOST restore templates (correct tree/shapes, all-zero
    # arrays, apply_fn/tx carried over): restoring into these proves the
    # values came from DISK, and building them is tree.map(np.zeros_like)
    # + eval_shape — zero extra init compiles in the default lane
    def blank(state):
        host = jax.device_get(state)
        return host.replace(
            **{f: jax.tree.map(np.zeros_like, getattr(host, f))
               for f in ("step", "params", "batch_stats", "opt_state")})

    blank_p = blank(state_p)
    blank_z = blank(state_z)
    tmpl_z4 = blank_z.replace(opt_state=step_mod.zero1_opt_template(
        blank_z.params, blank_z.tx, 4))
    return {
        "model": model, "spec": spec, "batch": batch,
        "cfg_p": cfg_p, "cfg_z": cfg_z, "topos": topos, "dirs": dirs,
        "state_p": state_p, "state_z": state_z,
        "blank_p": blank_p, "blank_z": blank_z, "tmpl_z4": tmpl_z4,
        "fp_p": ckpt.fingerprint(state_p.params),
        "fp_z": ckpt.fingerprint(state_z.params),
        "fp_z_opt": ckpt.fingerprint(state_z.opt_state),
    }


# ---------------------------------------------------------------------
# topology records + the elastic compatibility matrix (pure)


def test_topology_record_fields(saved_runs):
    rec = saved_runs["topos"][("zero1", 8)]
    assert rec["world"] == 8 and rec["mesh"] == {"data": 8, "model": 1}
    assert rec["variable_update"] == "zero1"
    assert rec["layout"] == "host" and rec["dtype"] == "float32"
    assert "world=8" in topology.describe_topology(rec)
    assert topology.describe_topology(None).startswith("unknown")


def test_elastic_plan_matrix(saved_runs):
    t = saved_runs["topos"]
    # identical -> ok
    assert topology.elastic_plan(t[("psum", 8)], t[("psum", 8)])[0] == "ok"
    # replicated tree, world change -> noop (re-place only)
    action, plan = topology.elastic_plan(t[("psum", 8)], t[("psum", 4)])
    assert action == "noop" and "8->4" in plan
    # psum <-> replicated: same on-disk tree -> noop
    repl = dict(t[("psum", 4)], variable_update="replicated")
    assert topology.elastic_plan(t[("psum", 8)], repl)[0] == "noop"
    # zero1 world change -> reshard, and the plan names the resplit
    action, plan = topology.elastic_plan(t[("zero1", 8)], t[("zero1", 4)])
    assert action == "reshard" and "resplit" in plan
    # zero1 <-> replicated optimizer trees are different structures
    assert topology.elastic_plan(t[("zero1", 8)],
                                 t[("psum", 4)])[0] == "refuse"
    assert topology.elastic_plan(t[("psum", 8)],
                                 t[("zero1", 4)])[0] == "refuse"
    # pp-native <-> DP layout: different trees
    ppn = dict(t[("psum", 8)], layout="pp-native", pipeline_parallel=4)
    assert topology.elastic_plan(ppn, t[("psum", 4)])[0] == "refuse"
    # multi-host model-sharded shards are not reassemblable elsewhere
    sh8 = dict(t[("psum", 8)], layout="sharded")
    sh4 = dict(t[("psum", 4)], layout="sharded")
    assert topology.elastic_plan(sh8, sh4)[0] == "refuse"
    # dtype drift on a benign transition is a note, not a refusal
    bf = dict(t[("psum", 4)], dtype="bfloat16")
    action, plan = topology.elastic_plan(t[("psum", 8)], bf)
    assert action == "noop" and "dtype policy" in plan


def test_flag_surface():
    with pytest.raises(ValueError, match="--resume=elastic"):
        tiny_cfg(resume="elastic")              # needs --train_dir
    cfg = tiny_cfg(resume="elastic", train_dir="/tmp/x")
    assert cfg.resume == "elastic"


# ---------------------------------------------------------------------
# sidecar plumbing


def test_topology_sidecar_written_and_readable(saved_runs):
    d = saved_runs["dirs"]["zero1"]
    sides = sorted(p.name for p in d.iterdir()
                   if p.name.endswith(".topology.json"))
    assert sides == ["step_00000002.topology.json"]
    assert ckpt.read_topology(d) == saved_runs["topos"][("zero1", 8)]
    assert ckpt.read_topology(d, step=7) is None      # no such step


def test_gc_reaps_topology_sidecars(saved_runs, tmp_path):
    state = saved_runs["state_p"]
    topo = saved_runs["topos"][("psum", 8)]
    for s in (1, 2):
        ckpt.save(state.replace(step=jax.numpy.asarray(s, jax.numpy.int32)),
                  tmp_path, topology=topo)
    assert len(list(tmp_path.glob("*.topology.json"))) == 2
    assert ckpt.gc_checkpoints(tmp_path, keep=1) == [1]
    assert [p.name for p in tmp_path.glob("*.topology.json")] == \
        ["step_00000002.topology.json"]


# ---------------------------------------------------------------------
# elastic restore: 8 -> 4 -> 8 on a single process (mesh reshapes)


def test_psum_restore_is_world_neutral(saved_runs, mesh4):
    """Replicated-tree checkpoints drop onto any world size: restore the
    8-way save into a blank template, re-place on the 4-mesh, bitwise."""
    info = saved_runs
    live4 = info["topos"][("psum", 4)]
    restored = ckpt.restore(info["blank_p"], info["dirs"]["psum"],
                            expect_topology=live4)    # noop: no raise
    assert ckpt.fingerprint(restored.params) == info["fp_p"]
    placed = step_mod.replicate_state(restored, mesh4)
    assert ckpt.fingerprint(placed.params) == info["fp_p"]


def test_zero1_elastic_restore_8_to_4_to_8(saved_runs, mesh4, tmp_path):
    """The tentpole proof: a zero1 checkpoint saved at world 8 restores
    at world 4 (opt shards resplit [8,k]->[4,k']), places on the 4-mesh
    in the genuine world-4 layout, and a 4-way save restores back at 8
    — params AND optimizer state bitwise at every hop.  (That the
    resharded state *trains* at world 4 is proven by the slow-lane
    subprocess e2e through the real driver — no second step compile in
    the default lane.)"""
    info = saved_runs
    saved_topo = ckpt.read_topology(info["dirs"]["zero1"])
    r4 = ckpt.restore_elastic(info["tmpl_z4"], info["dirs"]["zero1"],
                              saved_topo, 4)
    assert ckpt.fingerprint(r4.params) == info["fp_z"]
    # resplit is lossless: 4 -> 8 round-trips to the original opt state
    back = step_mod.resplit_zero1_opt(r4.opt_state, r4.params, r4.tx, 4, 8)
    assert ckpt.fingerprint(back) == info["fp_z_opt"]

    # placement commits the genuine world-4 zero1 layout to the 4-mesh
    st4 = step_mod.place_zero1_state(r4, mesh4)
    sharded_leaves = 0
    for leaf in jax.tree.leaves(st4.opt_state):
        if getattr(leaf, "ndim", 0) >= 2 and leaf.shape[0] == 4:
            assert leaf.sharding.shard_shape(leaf.shape)[0] == 1
            sharded_leaves += 1
    assert sharded_leaves > 0

    # ...and scales back up: save at 4 (gather-on-save of the 4-way
    # shards), elastic-restore at 8, bitwise
    ckpt.save(st4, tmp_path, topology=info["topos"][("zero1", 4)])
    r8 = ckpt.restore_elastic(info["blank_z"], tmp_path,
                              ckpt.read_topology(tmp_path), 8)
    assert ckpt.fingerprint(r8.params) == info["fp_z"]
    exp8 = step_mod.resplit_zero1_opt(r4.opt_state, r4.params, r4.tx, 4, 8)
    assert ckpt.fingerprint(r8.opt_state) == ckpt.fingerprint(exp8)


def test_resplit_handles_param_shaped_like_its_own_stack():
    """Regression: a param whose RAW shape coincides with its stacked
    ``[n_old, k]`` layout (here ``(8, 16)`` at world 8) must still be
    resplit — the old raw-template comparison misclassified it as
    stacking-invariant and silently kept the stale old-world leaf."""
    import optax

    params = {"w": np.arange(128, dtype=np.float32).reshape(8, 16),
              "b": np.arange(5, dtype=np.float32)}
    tx = optax.sgd(0.1, momentum=0.9)
    stacked8 = jax.tree.map(
        lambda p: step_mod._stack_param_shards(jax.numpy.asarray(p), 8),
        params)
    opt8 = jax.tree.map(np.asarray, tx.init(stacked8))
    opt4 = step_mod.resplit_zero1_opt(opt8, params, tx, 8, 4)
    trace4 = jax.tree.leaves(opt4)
    # every momentum leaf carries the world-4 stacked layout now
    shapes = sorted(tuple(l.shape) for l in trace4
                    if getattr(l, "ndim", 0) >= 2)
    assert shapes == [(4, 2), (4, 32)]
    # and the real elements survived the relayout bitwise
    back = step_mod.resplit_zero1_opt(opt4, params, tx, 4, 8)
    for a, b in zip(jax.tree.leaves(back), jax.tree.leaves(opt8)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # n_old == n_new is the identity
    same = step_mod.resplit_zero1_opt(opt8, params, tx, 8, 8)
    for a, b in zip(jax.tree.leaves(same), jax.tree.leaves(opt8)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_zero1_resplit_rows_unit():
    rows8 = zero1_resplit_rows(np.arange(10, dtype=np.float32), 10, 8)
    assert rows8.shape == (8, zero1_shard_len(10, 8))
    rows3 = zero1_resplit_rows(rows8, 10, 3)
    assert rows3.shape == (3, 4)
    np.testing.assert_array_equal(rows3.reshape(-1)[:10],
                                  np.arange(10, dtype=np.float32))
    assert (rows3.reshape(-1)[10:] == 0).all()


# ---------------------------------------------------------------------
# bugfix: mismatched restore is ONE loud error, not an Orbax shape error


def test_mismatched_restore_raises_loud_pinned_error(saved_runs):
    info = saved_runs
    live4 = info["topos"][("zero1", 4)]
    with pytest.raises(
            ckpt.TopologyMismatchError,
            match=r"checkpoint topology mismatch.*saved world=8 "
                  r".*vs live world=4 .*--resume=elastic"):
        ckpt.restore(info["tmpl_z4"], info["dirs"]["zero1"],
                     expect_topology=live4)
    # incompatible arm transition: actionable refusal, same error type
    live_psum = info["topos"][("psum", 4)]
    with pytest.raises(ckpt.TopologyMismatchError,
                       match="zero1 optimizer-state tree"):
        ckpt.restore(info["tmpl_z4"], info["dirs"]["zero1"],
                     expect_topology=live_psum)


# ---------------------------------------------------------------------
# bugfix: retention GC vs the in-flight async writer


def test_gc_waits_on_inflight_async_writer(saved_runs, tmp_path,
                                           monkeypatch):
    """Tight cadence: GC must barrier on the writer instead of reaping
    the ``.tmp`` the overlapped save is still Orbax-writing into."""
    state = saved_runs["state_p"]
    topo = saved_runs["topos"][("psum", 8)]
    for s in (1, 2):
        ckpt.save(state.replace(step=jax.numpy.asarray(s, jax.numpy.int32)),
                  tmp_path, topology=topo)
    gate = threading.Event()
    real = ckpt.write_host_payload

    def stalled(payload, directory, step, topology=None):
        gate.wait(10.0)
        return real(payload, directory, step, topology=topology)

    monkeypatch.setattr(ckpt, "write_host_payload", stalled)
    writer = ckpt.AsyncCheckpointWriter(tmp_path)
    writer.submit(state.replace(step=jax.numpy.asarray(3, jax.numpy.int32)))
    assert writer.in_flight
    threading.Timer(0.25, gate.set).start()
    t0 = time.monotonic()
    ckpt.gc_checkpoints(tmp_path, keep=1, writer=writer)
    assert time.monotonic() - t0 >= 0.2     # it actually waited
    # the in-flight save landed complete and retention kept it
    assert ckpt.complete_steps(tmp_path) == [3]
    assert not list(tmp_path.glob("step_*.tmp"))


# ---------------------------------------------------------------------
# CI lint: checkpoint writes must record topology


def test_checkpoint_topology_lint_fires_and_suppresses():
    bad = (
        "def f(state, d, p, o, payload, async_ckpt):\n"
        "    from tpu_hc_bench.utils import checkpoint as ckpt\n"
        "    ckpt.save(state, d)\n"
        "    ckpt.save_pp(p, o, 3, d)\n"
        "    write_host_payload(payload, d, 3)\n"
        "    async_ckpt.submit(state, gc_keep=2)\n"
    )
    found = [f for f in lints.lint_source_text(bad, "fixture.py")
             if f.lint == lints.CKPT_TOPOLOGY]
    assert len(found) == 4 and all(f.severity == "warning" for f in found)
    assert "topology=" in found[0].message
    ok = (
        "def f(state, d, p, o, async_ckpt, ckptr, q):\n"
        "    from tpu_hc_bench.utils import checkpoint as ckpt\n"
        "    ckpt.save(state, d, topology=topo)\n"
        "    ckpt.save_pp(p, o, 3, d, topology=topo)\n"
        "    async_ckpt.submit(state, topology=topo)\n"
        "    ckptr.save(path, payload, force=True)\n"   # orbax raw writer
        "    q.submit(job)\n"                           # unrelated submit
        "    ckpt.save(state, d)  # thb:lint-ok[checkpoint-topology]\n"
    )
    assert not [f for f in lints.lint_source_text(ok, "fixture.py")
                if f.lint == lints.CKPT_TOPOLOGY]
    assert lints.CKPT_TOPOLOGY in lints.ALL_SOURCE_LINTS


# ---------------------------------------------------------------------
# the kill-N / resume-M proof (subprocess e2e; slow lane)


def _launch(workers, *extra, timeout=240):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    cmd = [sys.executable, "-m", "tpu_hc_bench", "1", str(workers), "2",
           "ici", "--model", "trivial", "--num_classes", "10",
           "--num_warmup_batches", "1", "--num_batches", "6",
           "--display_every", "2", "--virtual_devices", "8", *extra]
    return subprocess.run(cmd, cwd=REPO, env=env, capture_output=True,
                          text=True, timeout=timeout)


def _fingerprints(proc):
    return [l for l in proc.stdout.splitlines()
            if "params fingerprint" in l]


@pytest.mark.slow
@pytest.mark.parametrize("arm", ["psum", "zero1"])
def test_kill8_resume4_e2e_subprocess(tmp_path, arm):
    """Acceptance: an 8-device run killed mid-stream resumes at 4 —
    the continuation's params fingerprint is bitwise-identical (f32) to
    the same-topology (resume-at-8) continuation's, on both the psum
    and zero1 arms, and the elastic plan line names the reshape."""
    ckdir = tmp_path / "ck"
    proc1 = _launch(0, "--variable_update", arm,
                    "--inject_fault", "sigterm@2",
                    "--train_dir", str(ckdir))
    assert proc1.returncode == resilience.EXIT_PREEMPTED, \
        proc1.stdout[-2000:] + proc1.stderr[-2000:]
    assert "emergency checkpoint saved (world 8)" in proc1.stdout
    fp_save = _fingerprints(proc1)
    assert fp_save
    assert (ckdir / "step_00000003.topology.json").exists()

    # same-topology continuation (the control arm)
    d8 = tmp_path / "ck8"
    shutil.copytree(ckdir, d8)
    proc8 = _launch(0, "--variable_update", arm, "--resume", "must",
                    "--train_dir", str(d8))
    assert proc8.returncode == resilience.EXIT_OK, \
        proc8.stdout[-2000:] + proc8.stderr[-2000:]
    assert "restored checkpoint step 3" in proc8.stdout
    fp8 = _fingerprints(proc8)

    # elastic continuation on the 4 surviving chips
    d4 = tmp_path / "ck4"
    shutil.copytree(ckdir, d4)
    proc4 = _launch(4, "--variable_update", arm, "--resume", "elastic",
                    "--train_dir", str(d4))
    assert proc4.returncode == resilience.EXIT_OK, \
        proc4.stdout[-2000:] + proc4.stderr[-2000:]
    assert "restored checkpoint step 3" in proc4.stdout
    assert "elastic resume:" in proc4.stdout
    if arm == "zero1":
        assert "resplit [8, k]->[4, k']" in proc4.stdout
    fp4 = _fingerprints(proc4)

    # both continuations start from bitwise-identical f32 params
    assert fp4[0] == fp8[0] == fp_save[-1]

    # zero1 without --resume=elastic refuses loudly instead of dying in
    # an opaque Orbax shape error
    if arm == "zero1":
        d4b = tmp_path / "ck4b"
        shutil.copytree(ckdir, d4b)
        procx = _launch(4, "--variable_update", arm, "--resume", "auto",
                        "--train_dir", str(d4b))
        assert procx.returncode not in (resilience.EXIT_OK,
                                        resilience.EXIT_PREEMPTED)
        assert "checkpoint topology mismatch" in procx.stderr
        assert "--resume=elastic" in procx.stderr
