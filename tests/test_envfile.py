"""setenv-registry tests (reference contract: /mnt/shared/setenv, sourced
everywhere — install_gcc-8.2.sh:34-41, run-tf-sing-ucx-openmpi.sh:14)."""

import subprocess

from tpu_hc_bench import envfile


def test_register_and_read(tmp_path):
    p = tmp_path / "setenv"
    envfile.register("jax", {"TPU_HC_BENCH_FABRIC": "ici"}, path=p)
    envfile.register("data", {"TPU_HC_BENCH_DATA_DIR": "/mnt/data"}, path=p)
    env = envfile.read(p)
    assert env["TPU_HC_BENCH_FABRIC"] == "ici"
    assert env["TPU_HC_BENCH_DATA_DIR"] == "/mnt/data"


def test_reregister_replaces_not_duplicates(tmp_path):
    p = tmp_path / "setenv"
    envfile.register("jax", {"A": "1"}, path=p)
    envfile.register("jax", {"A": "2"}, path=p)
    text = p.read_text()
    assert text.count("export A=") == 1
    assert envfile.read(p)["A"] == "2"


def test_file_is_sourceable_by_sh(tmp_path):
    p = tmp_path / "setenv"
    envfile.register("t", {"MY_VAR": "hello world", "Q": "it's"}, path=p)
    out = subprocess.run(
        ["sh", "-c", f". {p} && printf '%s|%s' \"$MY_VAR\" \"$Q\""],
        capture_output=True, text=True, check=True,
    )
    assert out.stdout == "hello world|it's"
