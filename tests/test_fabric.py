"""Fabric selection tests: ib|sock (reference names) and ici|dcn|host."""

import jax.numpy as jnp
import numpy as np
import pytest

from tpu_hc_bench.parallel import fabric


def test_reference_aliases():
    # run-tf-sing-ucx-openmpi.sh:27-30 contract: fabric in {ib, sock}
    assert fabric.resolve_fabric("ib") is fabric.Fabric.ICI
    assert fabric.resolve_fabric("sock") is fabric.Fabric.HOST


def test_native_names():
    assert fabric.resolve_fabric("ici") is fabric.Fabric.ICI
    assert fabric.resolve_fabric("dcn") is fabric.Fabric.DCN
    assert fabric.resolve_fabric("HOST") is fabric.Fabric.HOST


def test_unknown_fabric_raises():
    with pytest.raises(ValueError):
        fabric.resolve_fabric("infiniband")


def test_fast_flag():
    assert fabric.Fabric.ICI.is_fast and fabric.Fabric.DCN.is_fast
    assert not fabric.Fabric.HOST.is_fast


def test_env_exports_roundtrip():
    cfg = fabric.FabricConfig(fabric.Fabric.ICI, 134217728)
    env = cfg.env_exports()
    assert env["TPU_HC_BENCH_FABRIC"] == "ici"
    assert env["TPU_HC_BENCH_FUSION_THRESHOLD"] == "134217728"
    assert "ici" in cfg.summary()


def test_host_allreduce_means_over_leading_axis():
    tree = {"g": jnp.stack([jnp.full((3,), float(i)) for i in range(8)])}
    out = fabric.host_allreduce(tree)
    np.testing.assert_allclose(out["g"], np.full(3, 3.5))
