"""Flag-surface tests: the reference's exact argv must parse and translate.

The argv below is the literal flag set the reference assembles at
run-tf-sing-ucx-openmpi.sh:62-81 (SURVEY.md §2d), which our driver must
honor with TPU-translated semantics.
"""

from tpu_hc_bench import flags

REFERENCE_ARGV = [
    "--batch_size", "64",
    "--num_warmup_batches", "50",
    "--num_batches", "100",
    "--model", "resnet50",
    "--num_intra_threads", "22",
    "--num_inter_threads", "2",
    "--kmp_blocktime", "1",
    "--kmp_affinity", "granularity=fine,noverbose,compact,1,0",
    "--display_every", "10",
    "--data_format", "NCHW",
    "--optimizer", "momentum",
    "--forward_only", "False",
    "--device", "cpu",
    "--mkl", "TRUE",
    "--variable_update", "horovod",
    "--horovod_device", "cpu",
    "--local_parameter_device", "cpu",
    "--data_name", "imagenet",
]


def test_reference_argv_parses_and_translates():
    cfg = flags.parse_flags(REFERENCE_ARGV)
    # experiment knobs preserved verbatim
    assert cfg.batch_size == 64
    assert cfg.num_warmup_batches == 50
    assert cfg.num_batches == 100
    assert cfg.model == "resnet50"
    assert cfg.display_every == 10
    assert cfg.optimizer == "momentum"
    assert cfg.forward_only is False
    assert cfg.data_name == "imagenet"
    # TPU translations applied
    assert cfg.data_format == "NHWC"
    assert cfg.device == "tpu"
    assert cfg.mkl is False
    assert cfg.variable_update == "psum"
    assert cfg.horovod_device == "tpu"
    assert cfg.local_parameter_device == "tpu"
    # translations recorded for the log banner
    assert "data_format" in cfg.translations
    assert "mkl" in cfg.translations
    assert "variable_update" in cfg.translations


def test_defaults_match_reference_constants():
    cfg = flags.parse_flags([])
    assert cfg.num_warmup_batches == 50      # run-tf-sing-ucx-openmpi.sh:32
    assert cfg.num_batches == 100            # :33
    assert cfg.model == "resnet50"           # :34
    assert cfg.display_every == 10           # :71
    assert cfg.fusion_threshold_bytes == 134217728  # :105


def test_bool_flag_spellings():
    for spelling, expected in [("TRUE", True), ("true", True), ("1", True),
                               ("False", False), ("f", False), ("0", False)]:
        cfg = flags.parse_flags(["--forward_only", spelling])
        assert cfg.forward_only is expected


def test_fp16_maps_to_bf16():
    cfg = flags.parse_flags(["--use_fp16", "True"])
    assert cfg.compute_dtype == "bfloat16"
    assert flags.parse_flags([]).compute_dtype == "float32"


def test_summary_lines_cover_config():
    cfg = flags.parse_flags(REFERENCE_ARGV)
    text = "\n".join(cfg.summary_lines())
    assert "resnet50" in text and "momentum" in text and "translated:" in text


def test_resilience_flags_parse():
    cfg = flags.parse_flags([
        "--on_nonfinite", "skip", "--max_bad_steps", "3",
        "--resume", "auto", "--step_timeout_s", "auto",
        "--keep_checkpoints", "5",
        "--inject_fault", "nan_loss@40,hang@80:30,sigterm@120,io_error@ckpt",
    ])
    assert cfg.on_nonfinite == "skip"
    assert cfg.max_bad_steps == 3
    assert cfg.step_timeout_s == "auto"
    assert cfg.keep_checkpoints == 5
    assert "sigterm@120" in cfg.inject_fault
    # defaults: resilience machinery entirely off / abort-loudly
    d = flags.parse_flags([])
    assert d.on_nonfinite == "abort" and d.resume == "auto"
    assert d.step_timeout_s is None and d.keep_checkpoints == 0
    assert d.inject_fault is None
