"""Pallas flash attention vs the dense XLA reference.

Runs in Pallas interpreter mode on the CPU backend (ops.flash_attention
auto-detects).  Small block sizes force multi-block grids so the online
softmax accumulation and the padding/masking paths are all exercised.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_hc_bench.ops.flash_attention import flash_attention
from tpu_hc_bench.parallel import sequence as seq


def _qkv(b=2, s=64, h=2, d=16, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (b, s, h, d), dtype) for k in ks)


@pytest.mark.parametrize("causal", [False, True])
def test_forward_matches_dense(causal):
    q, k, v = _qkv()
    ref = seq.dense_attention(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal=causal, block_q=16, block_k=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("s", [24, 40])
def test_forward_unaligned_seq_pads(s):
    """Sequence lengths not divisible by the block: pad + mask path."""
    q, k, v = _qkv(s=s)
    ref = seq.dense_attention(q, k, v)
    out = flash_attention(q, k, v, block_q=16, block_k=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_grads_match_dense(causal):
    q, k, v = _qkv(b=1, s=32, h=2, d=8)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=causal, block_q=8, block_k=8)
        return jnp.sum(o * jnp.cos(o))        # non-trivial cotangent

    def loss_dense(q, k, v):
        o = seq.dense_attention(q, k, v, causal=causal)
        return jnp.sum(o * jnp.cos(o))

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for gf, gd, name in zip(g_flash, g_dense, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gd), rtol=1e-4, atol=1e-4,
            err_msg=f"d{name} mismatch",
        )


def test_grads_unaligned_seq():
    """Padded rows/keys must contribute zero gradient."""
    q, k, v = _qkv(b=1, s=20, h=1, d=8)
    f = lambda fn: lambda *a: jnp.sum(fn(*a) ** 2)
    g_flash = jax.grad(f(lambda q, k, v: flash_attention(
        q, k, v, block_q=8, block_k=8)), argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(f(seq.dense_attention), argnums=(0, 1, 2))(q, k, v)
    for gf, gd in zip(g_flash, g_dense):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gd),
                                   rtol=1e-4, atol=1e-4)


def test_bf16_forward():
    q, k, v = _qkv(dtype=jnp.bfloat16)
    ref = seq.dense_attention(q.astype(jnp.float32), k.astype(jnp.float32),
                              v.astype(jnp.float32))
    out = flash_attention(q, k, v, block_q=16, block_k=16)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, np.float32), np.asarray(ref),
                               rtol=0.05, atol=0.05)


def test_local_attention_flash_dispatch():
    q, k, v = _qkv(s=16)
    ref = seq.dense_attention(q, k, v)
    out = seq.local_attention(q, k, v, impl="flash")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_ulysses_with_flash_inner(devices):
    """Flash as the local attention inside Ulysses sequence parallelism."""
    from jax.sharding import Mesh, PartitionSpec as P

    q, k, v = _qkv(s=32, h=4)
    ref = seq.dense_attention(q, k, v)
    mesh = Mesh(np.array(devices[:2]), (seq.SEQ_AXIS,))
    spec = P(None, seq.SEQ_AXIS)
    mapped = jax.jit(jax.shard_map(
        lambda q, k, v: seq.ulysses_attention(
            q, k, v, attn_fn=functools.partial(
                flash_attention, block_q=16, block_k=16)),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    ))
    np.testing.assert_allclose(np.asarray(mapped(q, k, v)), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_bert_flash_matches_dense():
    """Same params, both attention impls: identical logits."""
    from tpu_hc_bench.models.bert import bert_tiny_mlm

    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 1024)
    dense = bert_tiny_mlm()
    flash = bert_tiny_mlm(attention_impl="flash")
    params = dense.init(jax.random.PRNGKey(0), tokens, train=False)
    out_d = dense.apply(params, tokens, train=False)
    out_f = flash.apply(params, tokens, train=False)
    np.testing.assert_allclose(np.asarray(out_f), np.asarray(out_d),
                               rtol=2e-4, atol=2e-4)
