"""Fleet orchestrator (tpu_hc_bench/fleet/, round 19).

Default lane is pure host-side work — the ``test_tune`` pattern: job
specs, pool admission (chips + the measured-anchors-first HBM model),
the scheduler's priority/gang/grow policy, deterministic churn, the
heartbeat-staleness classifier, and the WHOLE control loop driven in
virtual time over a stub backend (no subprocesses, no driver runs —
tier-1 sits against a tight 870s budget).  The load-bearing pins:

- admission is gang-or-nothing, and HBM refusals carry provenance
  (seeded vs measured — the tune/prune.hbm_model_for rule);
- a higher-priority arrival shrinks (not preempts) when shrinking
  suffices, never evicts equals, and never double-evicts while chips
  are already in flight back to the pool;
- a churn kill rides the preempt path: exit 75 → requeue → relaunch
  with ``--resume=elastic``; a completion regrows a shrunken job;
- every intentional stop (escalation SIGKILL included) requeues; a
  crash fails; a heartbeat-dead job is force-killed and requeued;
- the journal folds into the fleet goodput ledger exactly
  (chip-second arithmetic pinned), and the verdict artifact is
  regress-gateable (``fleet_goodput`` regresses DOWN).

Slow lane: the process-group kill regression (a child-spawning stub
job must not orphan its grandchild) and the real 3-member soak —
kill → elastic resume at a smaller world → regrow, params-fingerprint
control, zero orphaned processes, churn-vs-control goodput bound.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from tpu_hc_bench.fleet import churn as churn_mod
from tpu_hc_bench.fleet import report as report_mod
from tpu_hc_bench.fleet import scheduler as sched
from tpu_hc_bench.fleet.pool import DevicePool, JobSpec
from tpu_hc_bench.fleet.supervisor import (
    DONE,
    FAILED,
    FleetController,
    REFUSED,
)
from tpu_hc_bench.obs import fleet as obs_fleet

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def spec(name="a", model="trivial", batch=2, pref=4, wmin=2, prio=0,
         arrival=0.0, **kw):
    return JobSpec(name=name, model=model, batch_size=batch,
                   world_pref=pref, world_min=wmin, priority=prio,
                   arrival_s=arrival, **kw)


# ---------------------------------------------------------------------
# job spec + pool


def test_jobspec_roundtrip_and_validation():
    s = spec(flags=("--num_classes=10",))
    assert JobSpec.from_dict(s.to_dict()) == s
    with pytest.raises(ValueError, match="unknown field"):
        JobSpec.from_dict({**s.to_dict(), "chips": 4})
    with pytest.raises(ValueError, match="world_min"):
        spec(pref=2, wmin=4)
    with pytest.raises(ValueError, match="plain token"):
        spec(name="a/b")
    assert spec(batch=64, accum=8).microbatch == 8


def test_pool_gang_reserve_release():
    p = DevicePool(8)
    p.reserve("a", 4)
    p.reserve("b", 4)
    assert p.free == 0 and not p.can_reserve(1)
    with pytest.raises(ValueError, match="cannot reserve"):
        p.reserve("c", 2)
    with pytest.raises(ValueError, match="already holds"):
        p.reserve("a", 2)
    assert p.release("a") == 4
    assert p.free == 4
    assert p.release("a") == 0      # idempotent


def test_pool_hbm_admission_seeded():
    p = DevicePool(8)
    ok = p.hbm_admission(spec(batch=2))
    assert ok.fits and ok.source == "seeded"
    bad = p.hbm_admission(spec(name="big", batch=4096))
    assert not bad.fits and bad.source == "seeded"
    assert "seeded HBM anchor" in bad.reason
    # accumulation shrinks the microbatch back under the anchor
    assert p.hbm_admission(spec(name="acc", batch=4096, accum=8)).fits
    # a member outside the seed table admits with unknown provenance
    unk = p.hbm_admission(spec(name="u", model="moe_tiny", batch=4))
    assert unk.fits and unk.source == "unknown"


def test_pool_hbm_admission_measured_wins():
    # a measured OOM row at microbatch 64 caps the anchor below the
    # seeded guess — and the refusal says so
    rows = [{"model": "trivial", "overrides": {"batch_size": 64},
             "error": "hbm-oom"},
            {"model": "trivial", "overrides": {"batch_size": 16},
             "peak_hbm_bytes": 1 << 28, "hbm_bytes_limit": 1 << 30}]
    p = DevicePool(8, measured_rows=rows)
    v = p.hbm_admission(spec(batch=512))
    assert not v.fits and v.source == "measured"
    assert p.hbm_admission(spec(name="ok", batch=32)).fits
    # verdicts are cached per (model, batch, accum)
    assert p.hbm_admission(spec(batch=512)) is v
    # rows are per-model: trivial's measured anchor must not decide a
    # lenet admission (lenet falls back to its own seeded anchor)
    lv = p.hbm_admission(spec(name="l", model="lenet", batch=512))
    assert lv.fits and lv.source == "seeded"
    # a row with no model field carries no provenance: dropped
    anon = DevicePool(8, measured_rows=[
        {"overrides": {"batch_size": 2}, "error": "hbm-oom"}])
    assert anon.hbm_admission(spec(batch=2)).source == "seeded"


# ---------------------------------------------------------------------
# scheduler policy


def run_view(s, world, since=0.0, stopping=False):
    return sched.RunView(spec=s, world=world, since_s=since,
                         stopping=stopping)


def test_world_ladder_and_gang_admission():
    assert sched.world_ladder(spec()) == [4, 2]
    assert sched.world_ladder(spec(pref=6, wmin=4)) == [6, 4]
    assert sched.world_ladder(spec(), cap=2) == [2]
    # largest feasible world wins; below world_min nothing is granted
    d = sched.plan(0.0, 8, [], [sched.PendView(spec=spec())])
    assert d == [sched.Decision("admit", "a", 4, reason="fits")]
    d = sched.plan(0.0, 3, [], [sched.PendView(spec=spec())])
    assert d[0].world == 2          # gang shrinks to the ladder fit
    assert sched.plan(0.0, 1, [], [sched.PendView(spec=spec())]) == []


def test_plan_requeue_target_caps_the_ladder():
    d = sched.plan(0.0, 8, [],
                   [sched.PendView(spec=spec(), target_world=2)])
    assert d[0].world == 2


def test_plan_priority_shrinks_before_preempting():
    lo1, lo2 = spec(name="lo1"), spec(name="lo2")
    hi = spec(name="hi", prio=1)
    d = sched.plan(0.0, 0,
                   [run_view(lo1, 4), run_view(lo2, 4)],
                   [sched.PendView(spec=hi)])
    assert [x.kind for x in d] == ["shrink", "reserve"]
    assert d[0].world == 2
    # victims already at world_min: whole-gang preemption instead,
    # lowest priority first
    lo_min = spec(name="lomin", pref=2, wmin=2)
    d = sched.plan(0.0, 0, [run_view(lo_min, 2)],
                   [sched.PendView(spec=hi)])
    assert [x.kind for x in d] == ["preempt"]
    # equal priority NEVER evicts
    d = sched.plan(0.0, 0, [run_view(lo1, 4), run_view(lo2, 4)],
                   [sched.PendView(spec=spec(name="eq", prio=0))])
    assert d == []


def test_plan_shrink_reserves_beneficiary_cap():
    """The shrink pass budgets exactly world_min for the arrival — the
    RESERVE decision caps its later admission so it cannot take its
    full ladder top from the victim's freed chips (which would starve
    the victim the policy promised to keep running, smaller)."""
    v = spec(name="v")
    p = spec(name="p", prio=1)
    d = sched.plan(0.0, 0, [run_view(v, 4)], [sched.PendView(spec=p)])
    kinds = [(x.kind, x.job, x.world) for x in d]
    assert ("shrink", "v", 2) in kinds
    assert ("reserve", "p", 2) in kinds
    # next tick: v released its 4 chips and requeued at target 2; the
    # beneficiary admits at its BUDGETED 2, v re-admits beside it
    d2 = sched.plan(1.0, 4, [],
                    [sched.PendView(spec=p, target_world=2),
                     sched.PendView(spec=v, target_world=2)])
    assert [(x.kind, x.job, x.world) for x in d2] == [
        ("admit", "p", 2), ("admit", "v", 2)]


def test_plan_incoming_chips_stop_double_eviction():
    lo1, lo2 = spec(name="lo1"), spec(name="lo2")
    hi = spec(name="hi", prio=1)
    # lo1 is already stopping: its 4 chips are on the way back, so lo2
    # must NOT also be shrunk for the same pending job
    d = sched.plan(0.0, 0,
                   [run_view(lo1, 4, stopping=True), run_view(lo2, 4)],
                   [sched.PendView(spec=hi)])
    assert d == []


def test_plan_grows_one_settled_job_toward_pref():
    a, b = spec(name="a"), spec(name="b")
    running = [run_view(a, 2, since=0.0), run_view(b, 2, since=0.0)]
    # not settled yet
    assert sched.plan(1.0, 4, running, [], settle_s=5.0) == []
    d = sched.plan(10.0, 4, running, [], settle_s=5.0)
    assert len(d) == 1 and d[0].kind == "grow" and d[0].world == 4
    # pending work blocks growth (chips go to the queue first)
    assert sched.plan(10.0, 4, running,
                      [sched.PendView(spec=spec(name="p"))],
                      settle_s=5.0)[0].kind == "admit"
    # a stopping job never grows
    assert sched.plan(10.0, 4,
                      [run_view(a, 2, stopping=True)], [],
                      settle_s=5.0) == []


# ---------------------------------------------------------------------
# churn


def test_churn_parse_format_roundtrip():
    ev = churn_mod.parse_churn("kill@8:jobA, shrink@14:jobB,arrive@6:c")
    assert [e.op for e in ev] == ["arrive", "kill", "shrink"]  # sorted
    assert churn_mod.parse_churn(churn_mod.format_churn(ev)) == ev
    with pytest.raises(ValueError, match="malformed churn"):
        churn_mod.parse_churn("kill@8")
    with pytest.raises(ValueError, match="unknown churn op"):
        churn_mod.parse_churn("explode@8:jobA")


def test_seeded_churn_is_deterministic():
    a = churn_mod.seeded_churn(7, ["a", "b", "c"], 60.0,
                               kills=2, shrinks=1)
    assert a == churn_mod.seeded_churn(7, ["a", "b", "c"], 60.0,
                                       kills=2, shrinks=1)
    assert a != churn_mod.seeded_churn(8, ["a", "b", "c"], 60.0,
                                       kills=2, shrinks=1)
    assert sum(1 for e in a if e.op == "kill") == 2
    assert sum(1 for e in a if e.op == "shrink") == 1
    # events live in the soak's steady-state window
    assert all(0.2 * 60 <= e.t_s <= 0.8 * 60 for e in a)


# ---------------------------------------------------------------------
# heartbeat liveness (obs/fleet satellite)


def beat(t_unix, step=5, incarnation=0):
    return {"kind": "heartbeat", "t_unix": t_unix, "step": step,
            "incarnation": incarnation}


def test_classify_liveness_ages():
    now = 1000.0
    assert obs_fleet.classify_liveness(
        [beat(999.0)], now=now)["status"] == obs_fleet.ALIVE
    assert obs_fleet.classify_liveness(
        [beat(980.0)], now=now)["status"] == obs_fleet.STALE
    v = obs_fleet.classify_liveness([beat(900.0)], now=now)
    assert v["status"] == obs_fleet.DEAD and v["age_s"] == 100.0
    # the NEWEST beat decides, not file order
    assert obs_fleet.classify_liveness(
        [beat(900.0), beat(999.0)], now=now)["status"] == obs_fleet.ALIVE
    none = obs_fleet.classify_liveness([], now=now)
    assert none["status"] == obs_fleet.DEAD and none["age_s"] is None


def test_classify_liveness_incarnation_guard():
    now = 1000.0
    # a fresh-looking beat from an OLDER life never reads ALIVE
    v = obs_fleet.classify_liveness([beat(999.0, incarnation=0)],
                                    now=now, expect_incarnation=1)
    assert v["status"] == obs_fleet.STALE
    v = obs_fleet.classify_liveness([beat(900.0, incarnation=0)],
                                    now=now, expect_incarnation=1)
    assert v["status"] == obs_fleet.DEAD
    v = obs_fleet.classify_liveness([beat(999.0, incarnation=1)],
                                    now=now, expect_incarnation=1)
    assert v["status"] == obs_fleet.ALIVE


def test_watch_renders_liveness_column(rewind_run):
    from tpu_hc_bench.obs import metrics as obs_metrics
    from tpu_hc_bench.obs import watch as watch_mod

    manifest, records = obs_metrics.read_run(rewind_run["dir"])
    lines = watch_mod.render(rewind_run["dir"], manifest, records)
    row = [ln for ln in lines if ln.strip().startswith("rank0:")]
    assert row
    assert any(tok in row[0] for tok in
               (obs_fleet.ALIVE, obs_fleet.STALE, obs_fleet.DEAD))


# ---------------------------------------------------------------------
# the control loop, in virtual time over a stub backend


class VirtualClock:
    def __init__(self):
        self.now = 0.0

    def monotonic(self):
        return self.now

    def wall(self):
        return 1_000_000.0 + self.now

    def sleep(self, dt):
        self.now += dt


class StubHandle:
    _next_pid = 900_000_000     # far past any real pid

    def __init__(self, clock, run_s, now, fail_code=None, hang=False,
                 ckdir=None):
        StubHandle._next_pid += 1
        self.pid = StubHandle._next_pid
        self.clock = clock
        self.end_at = None if hang else now + run_s
        self.exit_code = fail_code if fail_code is not None else 0
        self.preempt_at = None
        self.killed_at = None
        self.honors_sigterm = not hang
        self._ckdir = ckdir

    def poll(self):
        now = self.clock.monotonic()
        if self.killed_at is not None and now >= self.killed_at:
            return -9
        if self.preempt_at is not None and now >= self.preempt_at:
            return 75
        if self.end_at is not None and now >= self.end_at:
            return self.exit_code
        return None

    def send_preempt(self):
        if not self.honors_sigterm:
            return              # a hung job ignores SIGTERM
        if self.preempt_at is None:
            # emulate the emergency checkpoint commit so the requeue
            # sees a resumable job (the sentinel contract)
            if self._ckdir:
                os.makedirs(self._ckdir, exist_ok=True)
                open(os.path.join(self._ckdir,
                                  "step_00000002.complete"), "w").close()
            self.preempt_at = self.clock.monotonic() + 0.2

    def force_kill(self):
        self.killed_at = self.clock.monotonic()


class StubBackend:
    def __init__(self, clock, behaviors):
        self.clock = clock
        self.behaviors = behaviors
        self.launches = []

    def launch(self, s, world, resume, run_dir, incarnation):
        os.makedirs(run_dir, exist_ok=True)
        self.launches.append((s.name, world, resume, incarnation))
        b = dict(self.behaviors.get(s.name, {}))
        return StubHandle(self.clock, b.get("run_s", 10.0),
                          self.clock.monotonic(),
                          fail_code=b.get("fail_code"),
                          hang=b.get("hang", False),
                          ckdir=os.path.join(run_dir, "ck"))

    def harvest(self, s, run_dir, exit_code):
        return {"goodput": 0.8}


def stub_fleet(tmp_path, specs, behaviors, churn=(), chips=8, **ctl_kw):
    clock = VirtualClock()
    backend = StubBackend(clock, behaviors)
    ctl = FleetController(
        DevicePool(chips), specs, str(tmp_path / "fleet"),
        backend=backend, churn=list(churn),
        now_fn=clock.monotonic, wall_fn=clock.wall,
        sleep_fn=clock.sleep, tick_s=0.5,
        print_fn=lambda s: None,
        **{"settle_s": 1.0, "kill_grace_s": 5.0,
           "deadline_s": 300.0, **ctl_kw})
    return ctl, backend, clock


def soak_specs():
    return [
        spec(name="a", batches=10),
        spec(name="b", model="lenet", batches=10),
        spec(name="hi", prio=1, arrival=6.0, batches=10),
    ]


@pytest.fixture(scope="module")
def stub_soak(tmp_path_factory):
    """ONE virtual-time kill/shrink/regrow story shared by the journal,
    ledger, report, verdict, and CLI assertions below."""
    tmp = tmp_path_factory.mktemp("stub_soak")
    ctl, backend, clock = stub_fleet(
        tmp, soak_specs(),
        {"a": {"run_s": 20.0}, "b": {"run_s": 20.0},
         "hi": {"run_s": 5.0}},
        churn=churn_mod.parse_churn("kill@3:a"))
    result = ctl.run()
    return {"dir": ctl.out_dir, "result": result,
            "launches": backend.launches, "tmp": tmp}


def test_stub_soak_story(stub_soak):
    """The acceptance story in virtual time: churn kill → elastic
    requeue, priority arrival → shrink, completion → regrow, all jobs
    complete, zero orphans."""
    assert stub_soak["result"]["status"] == "done"
    assert stub_soak["result"]["jobs"] == {
        "a": "done", "b": "done", "hi": "done"}
    assert stub_soak["result"]["orphans"] == []
    launches = stub_soak["launches"]
    # a: first launch fresh, every relaunch elastic
    a_launches = [l for l in launches if l[0] == "a"]
    assert a_launches[0][2] == "auto"
    assert all(l[2] == "elastic" for l in a_launches[1:])
    assert len(a_launches) == 4     # initial, post-kill, shrink, grow
    assert [l[1] for l in a_launches] == [4, 4, 2, 4]
    # the higher-priority arrival got chips while a and b were running
    # — at the world the shrink pass budgeted (NOT its ladder top: the
    # freed chips beyond the budget go back to the shrink victim)
    assert ("hi", 2, "auto", 0) in launches
    events = report_mod.read_events(stub_soak["dir"])
    kinds = [e["kind"] for e in events]
    for expected in ("fleet_start", "arrive", "admit", "launch",
                     "preempt_sent", "exit", "requeue", "shrink",
                     "grow", "done", "fleet_end"):
        assert expected in kinds, expected
    # the churn kill is journaled as a preempt with its reason
    assert any(e["kind"] == "preempt_sent"
               and e.get("reason") == "churn-kill" for e in events)
    # accounting: the preempted incarnation is billed its WHOLE
    # running wall (launched ~0, killed at 3, exited ~3.5 — not just
    # the stop-grace seconds)
    first_exit = next(e for e in events
                      if e["kind"] == "exit" and e["job"] == "a")
    assert first_exit["code"] == 75
    assert first_exit["wall_s"] >= 3.0, first_exit


def test_stub_soak_ledger_arithmetic(stub_soak):
    ledger = report_mod.fleet_ledger(stub_soak["dir"])
    assert ledger is not None
    events = report_mod.read_events(stub_soak["dir"])
    exits = [e for e in events if e["kind"] == "exit"]
    productive = sum(0.8 * e["world"] * e["wall_s"] for e in exits)
    pool = 8 * ledger["wall_s"]
    assert ledger["fleet_goodput"] == pytest.approx(
        productive / pool, abs=1e-3)
    assert 0 < ledger["fleet_goodput"] < 1
    assert ledger["counts"]["kills"] == 1
    assert ledger["counts"]["grows"] >= 1
    assert ledger["counts"]["elastic_resumes"] >= 2
    assert ledger["jobs"]["a"]["incarnations"] == 4


def test_stub_soak_report_and_status_cli(stub_soak):
    import io

    from tpu_hc_bench.fleet.__main__ import main as fleet_main

    buf = io.StringIO()
    assert fleet_main(["report", stub_soak["dir"]], out=buf) == 0
    text = buf.getvalue()
    assert "goodput" in text and "worlds 4->4->2->4" in text
    buf = io.StringIO()
    assert fleet_main(["status", stub_soak["dir"]], out=buf) == 0
    text = buf.getvalue()
    assert "a" in text and "done" in text
    # unusable dirs are loud, not tracebacks
    buf = io.StringIO()
    assert fleet_main(["status", str(stub_soak["tmp"] / "nope")],
                      out=buf) == 2
    buf = io.StringIO()
    assert fleet_main(["report", str(stub_soak["tmp"] / "nope")],
                      out=buf) == 2


def test_stub_soak_verdict_artifact_and_regress(stub_soak, tmp_path):
    # a no-churn control of the same fleet
    ctl, _, _ = stub_fleet(
        tmp_path, soak_specs(),
        {"a": {"run_s": 20.0}, "b": {"run_s": 20.0},
         "hi": {"run_s": 5.0}})
    ctl.run()
    art = tmp_path / "verdict.json"
    rec = report_mod.write_verdict(stub_soak["dir"], str(art),
                                   control_dir=ctl.out_dir,
                                   bound_frac=0.5)
    on_disk = json.loads(art.read_text())
    assert on_disk == rec
    assert rec["metric"] == "fleet_goodput"
    assert rec["value"] == pytest.approx(
        report_mod.fleet_ledger(stub_soak["dir"])["fleet_goodput"])
    assert rec["extra"]["fleet_goodput_nochurn"] > 0
    assert rec["extra"]["within_bound"] is True
    assert rec["extra"]["kills"] == 1
    # the regress gate consumes it: identical rerun passes, a halved
    # fleet goodput flags as a DOWN regression
    from tpu_hc_bench.obs import regress

    ok = regress.regress_check(rec, [rec])
    assert not ok["regressions"]
    worse = json.loads(json.dumps(rec))
    worse["value"] = rec["value"] / 2
    worse["extra"]["fleet_goodput"] = rec["value"] / 2
    bad = regress.regress_check(worse, [rec])
    assert any(r["metric"] == "fleet goodput"
               for r in bad["regressions"])


def test_controller_liveness_dead_job_requeues_then_fails(tmp_path):
    """A job that hangs (ignores SIGTERM, never heartbeats) is declared
    DEAD after the grace windows, force-killed, requeued — and a
    serial crasher stops requeueing at the relaunch budget."""
    ctl, backend, clock = stub_fleet(
        tmp_path, [spec(name="h", batches=5)],
        {"h": {"hang": True}},
        startup_grace_s=2.0, dead_after_s=3.0, kill_grace_s=2.0)
    ctl.supervisor.max_relaunches = 2
    result = ctl.run()
    events = report_mod.read_events(ctl.out_dir)
    assert any(e["kind"] == "dead" for e in events)
    assert any(e["kind"] == "requeue" for e in events)
    assert result["jobs"]["h"] == "failed"
    assert any(e["kind"] == "failed"
               and e.get("exit_class") == "relaunch-budget"
               for e in events)


def test_controller_crash_fails_watchdog_class(tmp_path):
    ctl, _, _ = stub_fleet(
        tmp_path, [spec(name="w")], {"w": {"run_s": 2.0,
                                           "fail_code": 70}})
    result = ctl.run()
    assert result["jobs"]["w"] == "failed"
    events = report_mod.read_events(ctl.out_dir)
    assert any(e["kind"] == "failed"
               and e.get("exit_class") == "watchdog-timeout"
               for e in events)


def test_latest_heartbeats_tail_read(tmp_path):
    """The supervisor's per-tick liveness source reads only the file
    TAIL — newest record per host, O(1) in run length."""
    d = tmp_path / "m"
    d.mkdir()
    with open(d / "metrics.0.jsonl", "w") as f:
        for i in range(5000):       # well past one 8KB tail window
            f.write(json.dumps(beat(1000.0 + i, step=i,
                                    incarnation=1)) + "\n")
    latest = obs_fleet.latest_heartbeats(str(d))
    assert latest[0]["step"] == 4999
    v = obs_fleet.classify_liveness([latest[0]], now=6000.0,
                                    expect_incarnation=1)
    assert v["status"] == obs_fleet.ALIVE
    assert obs_fleet.latest_heartbeats(str(tmp_path / "nope")) == {}


def test_controller_crash_kills_live_jobs(tmp_path):
    """An exception inside the loop must not leave job processes
    running unsupervised: the finally force-kills every live handle."""
    ctl, backend, clock = stub_fleet(
        tmp_path, [spec(name="a", batches=5)], {"a": {"run_s": 50.0}})
    ticks = {"n": 0}
    orig_tick = ctl.tick

    def exploding_tick():
        ticks["n"] += 1
        if ticks["n"] == 3:
            raise OSError("disk full")
        orig_tick()

    ctl.tick = exploding_tick
    with pytest.raises(OSError, match="disk full"):
        ctl.run()
    st = ctl.supervisor.jobs["a"]
    # the launched stub was force-killed and reaped on the way out
    assert st.handle is None
    events = report_mod.read_events(ctl.out_dir)
    assert any(e["kind"] == "fleet_crash" for e in events)
    assert any(e["kind"] == "exit" for e in events)


def test_controller_refuses_before_spawning(tmp_path):
    """HBM-hopeless and oversized-gang jobs are refused at submission —
    the fleet never burns a gang discovering it."""
    ctl, backend, _ = stub_fleet(
        tmp_path,
        [spec(name="big", batch=4096),
         spec(name="wide", wmin=16, pref=16),
         spec(name="ok", batches=3)],
        {"ok": {"run_s": 2.0}})
    result = ctl.run()
    assert result["jobs"] == {"big": "refused", "wide": "refused",
                              "ok": "done"}
    assert [l[0] for l in backend.launches] == ["ok"]
    events = report_mod.read_events(ctl.out_dir)
    refusals = {e["job"]: e for e in events if e["kind"] == "refuse"}
    assert "seeded" == refusals["big"]["hbm_source"]
    assert "exceeds the pool" in refusals["wide"]["reason"]


# ---------------------------------------------------------------------
# runner hardening + exit-class home


def test_exit_classes_one_home():
    from tpu_hc_bench import resilience
    from tpu_hc_bench.tune import runner

    assert runner.EXIT_CLASSES is resilience.EXIT_CLASSES
    assert resilience.classify_exit(0) is None
    assert resilience.classify_exit(75) == "preempted"
    assert resilience.classify_exit(70) == "watchdog-timeout"
    assert resilience.classify_exit(1) == "zero-throughput"
    assert resilience.classify_exit(3) == "exit-3"
    assert resilience.classify_exit(-9) == "signal-9"


def test_build_cmd_positional_contract():
    from tpu_hc_bench.tune import runner

    cmd = runner.build_cmd("lenet", 32, ["--virtual_devices=4"],
                           warmup=2, batches=10, use_fp16=False)
    assert cmd[1:5] == ["-m", "tpu_hc_bench", "1", "0"]
    assert cmd[5:7] == ["32", "ici"]
    assert "--model=lenet" in cmd and "--virtual_devices=4" in cmd
    assert not any(f.startswith("--use_fp16") for f in cmd)


def test_kill_process_tree_safe_on_dead_proc():
    from tpu_hc_bench.tune import runner

    proc = subprocess.Popen([sys.executable, "-c", "pass"])
    proc.wait(timeout=30)
    runner.kill_process_tree(proc)          # must not raise
    runner.kill_process_tree(proc, sig=signal.SIGKILL)


@pytest.mark.slow
def test_kill_process_tree_reaps_grandchildren():
    """Satellite regression: a job that spawns its own children (feeder
    pools, service processes) dies as a GROUP — the grandchild must not
    survive the kill.  Stub job, no driver run."""
    from tpu_hc_bench.tune import runner

    child_src = (
        "import subprocess, sys, time\n"
        "p = subprocess.Popen([sys.executable, '-c',"
        " 'import time; print(\"gc-ready\", flush=True);"
        " time.sleep(120)'], stdout=sys.stdout)\n"
        "time.sleep(120)\n"
    )
    proc = runner.launch_one([sys.executable, "-c", child_src],
                             stdout=subprocess.PIPE)
    # wait for the grandchild to exist
    line = proc.stdout.readline()
    assert "gc-ready" in line
    pgid = os.getpgid(proc.pid)
    assert pgid == proc.pid         # its own session
    runner.kill_process_tree(proc, grace_s=2.0)
    proc.wait(timeout=30)
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        try:
            pids = [int(os.path.basename(d))
                    for d in __import__("glob").glob("/proc/[0-9]*")]
            alive = [p for p in pids
                     if _pgid_of(p) == pgid]
        except OSError:
            alive = []
        if not alive:
            break
        time.sleep(0.2)
    assert not alive, f"orphaned pids in group {pgid}: {alive}"


def _pgid_of(pid):
    try:
        return os.getpgid(pid)
    except (ProcessLookupError, OSError):
        return None


# ---------------------------------------------------------------------
# fleet-blocking-wait lint


def test_fleet_blocking_wait_lint():
    from tpu_hc_bench.analysis.lints import FLEET_WAIT, lint_source_text

    src = (
        "def loop(jobs):\n"
        "    for j in jobs:\n"
        "        j.proc.wait()\n"              # flags
        "        j.thread.join()\n"            # flags
        "        j.proc.wait(5)\n"             # bounded
        "        j.thread.join(timeout=2.0)\n"  # bounded
        "        ','.join(j.names)\n"          # has an arg: not it
        "def once(j):\n"
        "    j.proc.wait()\n"                  # not in a loop
    )
    found = [f for f in lint_source_text(
        src, "tpu_hc_bench/fleet/supervisor.py")
        if f.lint == FLEET_WAIT]
    assert len(found) == 2
    assert all(f.severity == "error" for f in found)
    # scope: only the fleet package
    assert not [f for f in lint_source_text(
        src, "tpu_hc_bench/serve/engine.py") if f.lint == FLEET_WAIT]
    # suppression token works
    sup = src.replace("j.proc.wait()\n        j.thread.join()",
                      "j.proc.wait()  # thb:lint-ok[fleet-blocking-wait]"
                      "\n        j.thread.join()")
    found = [f for f in lint_source_text(
        sup, "tpu_hc_bench/fleet/supervisor.py")
        if f.lint == FLEET_WAIT]
    assert len(found) == 1


def test_repo_baseline_clean_with_fleet_lint():
    """The shipped fleet package itself holds the invariant the lint
    enforces (and the whole-repo lint gate stays green)."""
    from tpu_hc_bench.analysis import compare_to_baseline
    from tpu_hc_bench.analysis.lints import lint_repo_sources

    regressions = compare_to_baseline(lint_repo_sources())
    assert not regressions, [f.render() for f in regressions]


# ---------------------------------------------------------------------
# the real soak (slow lane): >=3 zoo members, deterministic churn,
# kill -> elastic resume at a smaller world, a regrow, the own-world
# fingerprint control, zero orphans, churn-vs-control goodput bound


SOAK_FLAGS = ("--num_classes=10", "--init_learning_rate=0.05")


def soak_real_specs():
    """Three distinct zoo members.  The heavyweight ``resnet20_cifar``
    keeps its gang busy across the kill window, so the killed lenet's
    elastic resume genuinely finds a smaller pool; the trivial member
    is the delayed priority arrival (enters via the churn schedule)."""
    return [
        spec(name="cifar-a", model="resnet20_cifar", batches=80,
             warmup=2, save_every=4, flags=SOAK_FLAGS),
        spec(name="lenet-b", model="lenet", batches=150, warmup=2,
             save_every=4, flags=SOAK_FLAGS),
        spec(name="triv-hi", prio=1, pref=2, wmin=2, arrival=9999.0,
             batches=30, warmup=2, save_every=4, flags=SOAK_FLAGS),
    ]


def _fingerprints(text_or_path, from_path=True):
    lines = (open(text_or_path).read() if from_path
             else text_or_path).splitlines()
    return [ln.split("params fingerprint:", 1)[1].strip()
            for ln in lines if "params fingerprint:" in ln]


def _resume_fingerprint(ck_src, model, world, resume, batches, tmp,
                        tag):
    """Relaunch a copy of a checkpoint dir at ``world`` and return the
    restore-time params fingerprint (the control arm of the soak's
    bitwise identity proof)."""
    import shutil

    ckdir = tmp / f"ck_{tag}"
    shutil.copytree(ck_src, ckdir)
    proc = subprocess.run(
        [sys.executable, "-m", "tpu_hc_bench", "1", "0", "2", "ici",
         f"--model={model}", *SOAK_FLAGS,
         "--num_warmup_batches", "2", f"--num_batches={batches}",
         "--display_every", "4",
         f"--virtual_devices={world}",
         f"--resume={resume}", "--train_dir", str(ckdir)],
        cwd=REPO, env={**os.environ, "JAX_PLATFORMS": "cpu"},
        capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout[-2000:] + \
        proc.stderr[-2000:]
    fps = _fingerprints(proc.stdout, from_path=False)
    assert fps, proc.stdout[-2000:]
    return fps[0]


@pytest.mark.slow
def test_fleet_soak_e2e(tmp_path):
    from tpu_hc_bench.fleet.supervisor import LocalBackend

    env = {"JAX_PLATFORMS": "cpu",
           "PYTHONPATH": REPO + os.pathsep
           + os.environ.get("PYTHONPATH", "")}
    # kill at 30 (the lenet is past warmup and checkpointing by then);
    # the priority arrival lands BEFORE the killed job's relaunch tick,
    # so the elastic resume finds a smaller pool
    events = churn_mod.parse_churn(
        "kill@30:lenet-b,arrive@30.5:triv-hi")
    out = str(tmp_path / "fleet")
    ctl = FleetController(
        DevicePool(8), soak_real_specs(), out,
        backend=LocalBackend(
            base_env=env,
            cache_dir=os.path.join(out, "compile_cache")),
        churn=events, settle_s=4.0, kill_grace_s=30.0,
        deadline_s=600.0, print_fn=lambda s: None)
    result = ctl.run()
    assert result["status"] == "done", result
    assert all(s == "done" for s in result["jobs"].values()), result

    # zero orphaned processes (the process-group contract, fleet-wide)
    assert result["orphans"] == []

    journal = report_mod.read_events(out)
    by_job: dict[str, list[dict]] = {}
    for e in journal:
        if e["kind"] == "launch":
            by_job.setdefault(e["job"], []).append(e)
    assert set(by_job) == {"cifar-a", "lenet-b", "triv-hi"}
    # the kill -> elastic resume at a SMALLER world (the arrival took
    # part of the pool between the kill and the relaunch)
    b_worlds = [e["world"] for e in by_job["lenet-b"]]
    assert len(b_worlds) >= 2, b_worlds
    assert min(b_worlds[1:]) < b_worlds[0], b_worlds
    assert any(e["resume"] == "elastic"
               for e in by_job["lenet-b"][1:])
    # ... and a regrow back up once capacity freed
    assert any(e["kind"] == "grow" for e in journal), \
        [e["kind"] for e in journal]
    assert max(b_worlds[1:]) > min(b_worlds[1:]), b_worlds

    # in-soak bitwise identity: every emergency save's fingerprint is
    # restored EXACTLY by the incarnation that follows it
    st = ctl.supervisor.jobs["lenet-b"]
    pairs = 0
    for k in range(st.incarnations - 1):
        log_k = os.path.join(st.run_dir, f"job-{k}.log")
        log_next = os.path.join(st.run_dir, f"job-{k + 1}.log")
        if not (os.path.exists(log_k) and os.path.exists(log_next)):
            continue
        if "emergency checkpoint saved" not in open(log_k).read():
            continue
        fp_save = _fingerprints(log_k)[-1]
        fp_restore = _fingerprints(log_next)[0]
        assert fp_restore == fp_save, (k, fp_save, fp_restore)
        pairs += 1

    # own-world control, EVERY surviving job: from its final
    # checkpoint, an elastic continuation at HALF the world starts
    # from params bitwise-identical to the own-world (--resume=must)
    # control — the kill-8/resume-4 identity, fleet-wide
    for s in soak_real_specs():
        ck = os.path.join(ctl.supervisor.jobs[s.name].run_dir, "ck")
        steps = sorted(int(f[len("step_"):-len(".complete")])
                       for f in os.listdir(ck)
                       if f.endswith(".complete"))
        assert steps, s.name
        topo = json.load(open(os.path.join(
            ck, f"step_{steps[-1]:08d}.topology.json")))
        own_world = int(topo["world"])
        batches = steps[-1] + 8
        fp_own = _resume_fingerprint(
            ck, s.model, own_world, "must", batches, tmp_path,
            f"{s.name}_own")
        fp_elastic = _resume_fingerprint(
            ck, s.model, max(1, own_world // 2), "elastic", batches,
            tmp_path, f"{s.name}_elastic")
        assert fp_elastic == fp_own, s.name

    # churn-vs-control goodput: the same fleet without the kill (the
    # arrival kept at the same time so only the spot-churn tax
    # differs), held to the stated bound
    out2 = str(tmp_path / "control_fleet")
    ctl2 = FleetController(
        DevicePool(8), soak_real_specs(), out2,
        backend=LocalBackend(
            base_env=env,
            cache_dir=os.path.join(out2, "compile_cache")),
        churn=churn_mod.parse_churn("arrive@30.5:triv-hi"),
        settle_s=4.0, kill_grace_s=30.0, deadline_s=600.0,
        print_fn=lambda s: None)
    res2 = ctl2.run()
    assert res2["status"] == "done"
    churned = report_mod.fleet_ledger(out)["fleet_goodput"]
    control = report_mod.fleet_ledger(out2)["fleet_goodput"]
    art = tmp_path / "verdict.json"
    rec = report_mod.write_verdict(
        out, str(art), control_dir=out2, bound_frac=0.5,
        extra={"fingerprint_pairs": pairs})
    assert rec["extra"]["within_bound"], (churned, control)
    assert churned >= 0.5 * control, (churned, control)
