"""Fused BN-apply+relu+conv3x3 kernel: numerics vs the XLA composition.

Runs in Pallas interpreter mode on the CPU backend (same pattern as
tests/test_flash.py); the performance claims live in BASELINE.md's
round-3 table (scripts/exp_fused_conv.py on hardware).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_hc_bench.ops import fused_conv


def _ref(y1, a, b, w):
    xn = jnp.maximum(y1.astype(jnp.float32) * a + b, 0.0).astype(y1.dtype)
    y2 = jax.lax.conv_general_dilated(
        xn, w, (1, 1), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.float32,
    ).astype(y1.dtype)
    yf = y2.astype(jnp.float32)
    return y2, yf.sum((0, 1, 2)), (yf * yf).sum((0, 1, 2))


def _inputs(b=4, h=8, cin=16, cout=16, dtype=jnp.float32, seed=0):
    k = jax.random.PRNGKey(seed)
    y1 = jax.random.normal(k, (b, h, h, cin), dtype)
    w = jax.random.normal(jax.random.fold_in(k, 1), (3, 3, cin, cout),
                          dtype) * 0.1
    a = (jnp.abs(jax.random.normal(jax.random.fold_in(k, 2), (cin,),
                                   jnp.float32)) * 0.5 + 0.5)
    bb = jax.random.normal(jax.random.fold_in(k, 3), (cin,),
                           jnp.float32) * 0.1
    return y1, a, bb, w


def test_forward_matches_xla():
    y1, a, b, w = _inputs()
    y_f, s1_f, s2_f = fused_conv.fused_bn_relu_conv(y1, a, b, w)
    y_r, s1_r, s2_r = _ref(y1, a, b, w)
    np.testing.assert_allclose(np.asarray(y_f), np.asarray(y_r),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(s1_f), np.asarray(s1_r),
                               rtol=1e-4, atol=1e-3)
    np.testing.assert_allclose(np.asarray(s2_f), np.asarray(s2_r),
                               rtol=1e-4, atol=1e-3)


def test_forward_grouped_batch():
    # small maps pack multiple images per program (G > 1)
    y1, a, b, w = _inputs(b=8, h=4, cin=8, cout=8, seed=1)
    assert fused_conv._pick_group(8, 16) > 1
    y_f, s1_f, s2_f = fused_conv.fused_bn_relu_conv(y1, a, b, w)
    y_r, s1_r, s2_r = _ref(y1, a, b, w)
    np.testing.assert_allclose(np.asarray(y_f), np.asarray(y_r),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(s1_f), np.asarray(s1_r),
                               rtol=1e-4, atol=1e-3)


@pytest.mark.parametrize("use_stats", [False, True])
def test_grads_match_xla(use_stats):
    """custom_vjp vs autodiff of the XLA composition, with and without
    the stats outputs participating in the loss (the next-BN path)."""
    y1, a, b, w = _inputs(b=2, h=6, cin=8, cout=8, seed=2)

    def loss_fused(y1, a, b, w):
        y2, s1, s2 = fused_conv.fused_bn_relu_conv(y1, a, b, w)
        out = jnp.sum(y2 * jnp.cos(jnp.arange(y2.size).reshape(y2.shape)))
        if use_stats:
            out = out + jnp.sum(s1 * 0.3) + jnp.sum(s2 * 0.1)
        return out

    def loss_ref(y1, a, b, w):
        y2, s1, s2 = _ref(y1, a, b, w)
        out = jnp.sum(y2 * jnp.cos(jnp.arange(y2.size).reshape(y2.shape)))
        if use_stats:
            out = out + jnp.sum(s1 * 0.3) + jnp.sum(s2 * 0.1)
        return out

    g_f = jax.grad(loss_fused, argnums=(0, 1, 2, 3))(y1, a, b, w)
    g_r = jax.grad(loss_ref, argnums=(0, 1, 2, 3))(y1, a, b, w)
    for gf, gr, name in zip(g_f, g_r, ["dy1", "da", "db", "dw"]):
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gr), rtol=2e-4, atol=2e-4,
            err_msg=name)


def test_eligibility_is_the_measured_win_region():
    el = fused_conv.eligible
    assert not el((128, 56, 56, 64), (3, 3), (1, 1), 64)    # stage 1
    assert el((128, 28, 28, 128), (3, 3), (1, 1), 128)      # stage 2
    assert el((128, 14, 14, 256), (3, 3), (1, 1), 256)      # stage 3
    assert not el((128, 7, 7, 512), (3, 3), (1, 1), 512)    # stage 4
    assert not el((128, 28, 28, 128), (3, 3), (2, 2), 128)  # strided
    assert not el((128, 28, 28, 128), (1, 1), (1, 1), 128)  # 1x1
