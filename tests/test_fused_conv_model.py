"""Fused bottleneck modules vs the plain composition (param-copied).

The ops-level numerics live in tests/test_fused_conv.py; these tests pin
the MODEL integration: FusedBNReluConv3x3 == BatchNorm->relu->Conv,
FusedBottleneckBlock == BottleneckBlock, running-stat updates match, and
the --fused_conv flag reaches the driver end to end.
"""

import functools

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_hc_bench.models import resnet


def _plain_seg(use_running_average):
    """BatchNorm -> relu -> 3x3 conv, the unfused composition."""

    class Seg(nn.Module):
        @nn.compact
        def __call__(self, x):
            y = nn.BatchNorm(use_running_average=use_running_average,
                             momentum=0.9, epsilon=1e-5, name="bn")(x)
            y = nn.relu(y)
            return nn.Conv(8, (3, 3), use_bias=False, padding="SAME",
                           name="conv")(y)

    return Seg()


def _copy_seg_params(fused_vars):
    """Map FusedBNReluConv3x3's tree onto the plain segment's."""
    p = fused_vars["params"]
    bs = fused_vars["batch_stats"]
    return {
        "params": {
            "bn": {"scale": p["scale"], "bias": p["bias"]},
            "conv": {"kernel": p["kernel"]},
        },
        "batch_stats": {"bn": {"mean": bs["mean"], "var": bs["var"]}},
    }


def test_fused_module_matches_plain_train_and_eval():
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 8, 8, 8))
    for use_ra in (False, True):
        fused = resnet.FusedBNReluConv3x3(8, use_running_average=use_ra)
        fvars = fused.init(jax.random.PRNGKey(1), x)
        plain = _plain_seg(use_ra)
        pvars = _copy_seg_params(fvars)

        (y_f, (s1, s2)), fupd = fused.apply(fvars, x,
                                            mutable=["batch_stats"])
        y_p, pupd = plain.apply(pvars, x, mutable=["batch_stats"])
        np.testing.assert_allclose(np.asarray(y_f), np.asarray(y_p),
                                   rtol=1e-5, atol=1e-5)
        # the epilogue stats equal a direct reduction over y
        yf = np.asarray(y_f, np.float64)
        np.testing.assert_allclose(np.asarray(s1), yf.sum((0, 1, 2)),
                                   rtol=1e-4, atol=1e-3)
        if not use_ra:
            # running-average updates match nn.BatchNorm's
            np.testing.assert_allclose(
                np.asarray(fupd["batch_stats"]["mean"]),
                np.asarray(pupd["batch_stats"]["bn"]["mean"]),
                rtol=1e-5, atol=1e-6)
            np.testing.assert_allclose(
                np.asarray(fupd["batch_stats"]["var"]),
                np.asarray(pupd["batch_stats"]["bn"]["var"]),
                rtol=1e-4, atol=1e-5)


def _copy_block_params(fused_vars):
    """FusedBottleneckBlock tree -> BottleneckBlock tree."""
    p, bs = fused_vars["params"], fused_vars["batch_stats"]
    seg_p, seg_bs = p["FusedBNReluConv3x3_0"], bs["FusedBNReluConv3x3_0"]
    sbn_p, sbn_bs = p["StatsBatchNorm_0"], bs["StatsBatchNorm_0"]
    out_p = {
        "Conv_0": p["Conv_0"],
        "BatchNorm_0": {"scale": seg_p["scale"], "bias": seg_p["bias"]},
        "Conv_1": {"kernel": seg_p["kernel"]},
        "BatchNorm_1": {"scale": sbn_p["scale"], "bias": sbn_p["bias"]},
        "Conv_2": p["Conv_1"],
        "BatchNorm_2": p["BatchNorm_0"],
    }
    out_bs = {
        "BatchNorm_0": {"mean": seg_bs["mean"], "var": seg_bs["var"]},
        "BatchNorm_1": {"mean": sbn_bs["mean"], "var": sbn_bs["var"]},
        "BatchNorm_2": bs["BatchNorm_0"],
    }
    for k in ("shortcut_conv",):
        if k in p:
            out_p[k] = p[k]
    for k in ("shortcut_bn",):
        if k in p:
            out_p[k] = p[k]
            out_bs[k] = bs[k]
    return {"params": out_p, "batch_stats": out_bs}


def _mk_blocks(train, strides=1):
    conv = functools.partial(nn.Conv, use_bias=False, padding="SAME")
    norm = functools.partial(nn.BatchNorm, use_running_average=not train,
                             momentum=0.9, epsilon=1e-5)
    kw = dict(filters=4, strides=strides, conv=conv, norm=norm, act=nn.relu)
    return (resnet.FusedBottleneckBlock(use_running_average=not train, **kw),
            resnet.BottleneckBlock(**kw))


def test_fused_block_matches_plain():
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 8, 16))
    for train in (True, False):
        for strides in (1, 2):
            fused, plain = _mk_blocks(train, strides)
            fvars = fused.init(jax.random.PRNGKey(1), x)
            pvars = _copy_block_params(fvars)
            y_f, _ = fused.apply(fvars, x, mutable=["batch_stats"])
            y_p, _ = plain.apply(pvars, x, mutable=["batch_stats"])
            np.testing.assert_allclose(
                np.asarray(y_f), np.asarray(y_p), rtol=1e-5, atol=1e-5,
                err_msg=f"train={train} strides={strides}")


@pytest.mark.slow
def test_fused_resnet_through_driver(mesh8):
    # slow lane: the heaviest single compile+run in the suite for a path
    # recorded as a whole-model NULL (BASELINE.md); block-level fused==
    # plain parity stays in the default lane above
    from tpu_hc_bench import flags
    from tpu_hc_bench.train import driver

    cfg = flags.BenchmarkConfig(
        model="resnet50", batch_size=1, num_warmup_batches=1, num_batches=2,
        display_every=1, num_classes=10, fused_conv=True,
    ).resolve()
    out = []
    res = driver.run_benchmark(cfg, print_fn=out.append)
    assert np.isfinite(res.final_loss)


def test_fused_conv_rejected_for_non_bottleneck():
    from tpu_hc_bench.models import create_model
    import pytest

    with pytest.raises(ValueError, match="fused_conv"):
        create_model("vgg16", fused_conv=True)
    with pytest.raises(ValueError, match="fused_conv"):
        create_model("resnet18", fused_conv=True)
