"""The goodput & efficiency layer: ledger, fleet, MFU sources, ceilings.

Five sections, matching the round-9 acceptance contract:

1. ``obs.goodput`` against hand-built record streams: the phase fold,
   the data_wait carve-out, the resilience waste fold (rewound/skipped
   steps scaled by the mean step time), and the PhaseTracker's
   emit/mirror equivalence.
2. ``obs.fleet``: heartbeat files, the step EWMA, clock-free skew.
3. ``obs.efficiency``: measured FLOPs (exact on a matmul), the
   analytic-vs-measured cross-check for two zoo members (the
   table-rot tripwire), MFU source labeling, and the fabric-ceiling
   arithmetic against a fixture sweep.
4. Degraded-artifact CLI behavior: one-line errors + distinct exit
   codes on missing/truncated run dirs (no tracebacks mid-incident).
5. End-to-end: ONE driver run with an injected rewind fault feeds the
   acceptance assertions (goodput < 1 with rewind attributed, MFU line
   labeled with its source, ceiling line under --fabric_ceiling,
   ``obs watch`` rendering and exiting cleanly) — the session-scoped
   ``rewind_run`` fixture in conftest.py, shared with test_memory_obs,
   so the default lane pays for a single tiny run.
"""

from __future__ import annotations

import io
import json
import os
import signal
import subprocess
import sys
from pathlib import Path

import pytest

from tpu_hc_bench import flags
from tpu_hc_bench.obs import efficiency, fleet, goodput
from tpu_hc_bench.obs import metrics as obs_metrics
from tpu_hc_bench.obs import watch as watch_mod
from tpu_hc_bench.obs.__main__ import main as obs_main
from tpu_hc_bench.train import driver

REPO = Path(__file__).resolve().parent.parent


# ---------------------------------------------------------------------
# 1. the goodput ledger


def _phase(p, t, step=None):
    return {"kind": "phase", "phase": p, "t": t, "step": step}


def test_ledger_basic_fold():
    recs = [
        _phase("init", 0.0), _phase("compile", 2.0), _phase("step", 4.0),
        {"kind": "phase_acc", "phase": "data_wait", "seconds": 0.5,
         "step": 8},
        _phase("checkpoint", 10.0, 8), _phase("step", 11.0, 8),
        _phase("end", 14.0, 10),
    ]
    led = goodput.build_ledger(recs)
    assert led is not None and led.complete
    assert led.wall_s == pytest.approx(14.0)
    assert led.seconds["init"] == pytest.approx(2.0)
    assert led.seconds["compile"] == pytest.approx(2.0)
    assert led.seconds["checkpoint"] == pytest.approx(1.0)
    assert led.seconds["data_wait"] == pytest.approx(0.5)
    # step spans [4,10) + [11,14) minus the carved-out data_wait
    assert led.seconds["step"] == pytest.approx(9.0 - 0.5)
    assert led.steps == 10
    assert led.goodput == pytest.approx(8.5 / 14.0)
    assert "goodput" in led.format_lines()[0]


def test_ledger_none_without_step_phase():
    assert goodput.build_ledger([]) is None
    assert goodput.build_ledger([_phase("init", 0.0)]) is None
    assert goodput.build_ledger([{"kind": "window", "step": 3}]) is None


def test_ledger_folds_rewind_and_skip_waste():
    recs = [
        _phase("init", 0.0), _phase("step", 1.0),
        {"kind": "rewind", "step": 6, "restored_step": 3, "lost_steps": 4},
        {"kind": "nonfinite_skip", "step": 8, "new_bad": 2},
        _phase("end", 11.0, 10),
    ]
    led = goodput.build_ledger(recs)
    # 10 timed steps over 10s of step phase -> 1 s/step mean
    assert led.mean_step_s == pytest.approx(1.0)
    assert led.rewind_lost_s == pytest.approx(4.0)
    assert led.skipped_updates_s == pytest.approx(2.0)
    assert led.goodput == pytest.approx((10.0 - 6.0) / 11.0)
    text = "\n".join(led.format_lines())
    assert "rewind_lost" in text and "skipped_updates" in text


def test_rewind_lost_steps_resume_aware():
    """The rewind waste formula must survive --resume: on a resumed run
    the checkpoint's absolute step counter includes prior runs' steps,
    and a naive ``i - (restored_step - warmup)`` clamps to 0 — a rewound
    run would post a clean goodput."""
    # fresh run (base 0, warmup 1): checkpoint at timed step 2, rewind
    # at 6 -> 4 steps lost
    assert goodput.rewind_lost_steps(6, 3, 0, 1) == 4
    # resumed run (base 100): same shape, same answer
    assert goodput.rewind_lost_steps(6, 103, 100, 1) == 4
    # rewind restores the resume-source checkpoint itself (predates this
    # run's timed loop): ALL timed steps so far are lost
    assert goodput.rewind_lost_steps(6, 100, 100, 1) == 6
    assert goodput.rewind_lost_steps(6, 100, 100, 50) == 6


def test_ledger_incomplete_run_is_labeled():
    recs = [_phase("init", 0.0), _phase("step", 1.0),
            _phase("checkpoint", 3.0, 2)]     # no "end": the run died
    led = goodput.build_ledger(recs)
    assert not led.complete
    assert "did not end cleanly" in led.format_lines()[0]


def test_phase_tracker_emits_and_mirrors(tmp_path):
    w = obs_metrics.MetricsWriter(str(tmp_path), {"schema": 1},
                                  primary=True)
    tr = goodput.PhaseTracker(w)            # enters "init"
    tr.enter("compile")
    tr.enter("step")
    tr.note_data_wait(0.25)
    tr.flush(4)
    tr.note_lost_steps(2)
    tr.end(8)
    w.close()
    recs = [json.loads(line) for line in
            (tmp_path / "metrics.jsonl").read_text().splitlines()]
    assert [r["kind"] for r in recs] == \
        ["phase", "phase", "phase", "phase_acc", "phase"]
    assert recs[3]["seconds"] == pytest.approx(0.25)
    # the local mirror folds identically to the on-disk stream
    led_local = tr.ledger()
    led_file = goodput.build_ledger(recs, fold_resilience=False)
    assert led_local.seconds == led_file.seconds
    assert led_local.steps == led_file.steps == 8
    # note_lost_steps reached the local fold (the stream's rewind event
    # carries the same number for the offline fold)
    assert led_local.rewind_lost_s >= 0.0


# ---------------------------------------------------------------------
# 2. fleet heartbeats + straggler skew


def test_fleet_heartbeats_roundtrip(tmp_path):
    w = fleet.FleetWriter(str(tmp_path), process_index=3)
    assert w.enabled
    w.heartbeat(step=10, step_ewma_ms=12.5)
    w.heartbeat(step=20, step_ewma_ms=11.0, mem_peak_bytes=123)
    w.close()
    beats = fleet.read_heartbeats(str(tmp_path))
    assert list(beats) == [3]
    assert beats[3][-1]["step"] == 20
    # the ONE unified heartbeat memory field name (round 15), readable
    # through the accessor that also tolerates pre-unification dirs
    assert beats[3][-1]["mem_peak_bytes"] == 123
    assert fleet.heartbeat_mem_peak(beats[3][-1]) == 123
    assert fleet.heartbeat_mem_peak({"peak_bytes_in_use": 7}) == 7
    # disabled writer no-ops
    off = fleet.FleetWriter(None)
    assert not off.enabled
    off.heartbeat(step=1, step_ewma_ms=0.0)
    off.close()


def test_step_ewma():
    e = fleet.StepEwma()
    assert e.update(0, now=0.0) == 0.0      # one sample: no duration yet
    assert e.update(10, now=1.0) == pytest.approx(100.0)
    ms = e.update(20, now=3.0)              # 200 ms/step sample
    assert 100.0 < ms < 200.0               # EWMA moves toward it


def test_compute_skew_is_max_minus_median():
    s = fleet.compute_skew([10, 10, 8, 10], [100.0] * 4)
    assert s["skew_steps"] == 0.0           # the straggler is BELOW median
    s = fleet.compute_skew([12, 10, 8], [100.0, 100.0, 100.0])
    assert s["skew_steps"] == 2.0
    assert s["skew_ms"] == pytest.approx(200.0)


def test_straggler_lines_render(tmp_path):
    for host, step in ((0, 10), (1, 7)):
        w = fleet.FleetWriter(str(tmp_path), process_index=host)
        w.heartbeat(step=step, step_ewma_ms=5.0)
        w.close()
    recs = [{"kind": "straggler", "step": 8, "host_steps": [10, 7],
             "skew_steps": 1.5, "skew_ms": 7.5}]
    text = "\n".join(fleet.straggler_lines(str(tmp_path), recs))
    assert "straggler skew: max-median 2 step(s)" in text  # 1.5 -> %.0f
    assert "heartbeats: 2 host file(s)" in text
    assert "host1" in text                  # 1.5 behind the 8.5 median


# ---------------------------------------------------------------------
# 3. efficiency: measured FLOPs, MFU sources, fabric ceiling


def test_measured_flops_exact_on_matmul():
    import jax
    import jax.numpy as jnp

    jitted = jax.jit(lambda x, w: x @ w)

    def step(x, w):
        return jitted(x, w)

    step._jitted = jitted
    x = jnp.ones((4, 64))
    w = jnp.ones((64, 32))
    f = efficiency.measured_step_flops(step, x, w)
    assert f == pytest.approx(2 * 4 * 64 * 32, rel=0.01)
    # a step without the handle (PP/host arms) degrades to None
    assert efficiency.measured_step_flops(lambda *a: None, x, w) is None


@pytest.mark.parametrize("name,tol", [("trivial", 0.02), ("lenet", 0.02)])
def test_flops_table_cross_check(name, tol):
    """Satellite: the hand-maintained ``spec.flops_per_example`` table
    must agree with XLA's compiled cost analysis of the actual forward
    pass — the tripwire that keeps the analytic MFU honest."""
    import jax
    import numpy as np

    from tpu_hc_bench.models import create_model

    model, spec = create_model(name)
    batch = 2
    x = np.ones((batch,) + spec.input_shape, np.float32)
    rng = jax.random.PRNGKey(0)
    variables = jax.jit(
        lambda r, xx: model.init(
            {"params": r, "dropout": jax.random.fold_in(r, 1)}, xx,
            train=False))(rng, x[:1])
    fwd = jax.jit(lambda v, xx: model.apply(v, xx, train=False))
    compiled = fwd.lower(variables, x).compile()
    measured = efficiency.flops_of_compiled(compiled)
    assert measured is not None
    assert measured / batch == pytest.approx(spec.flops_per_example,
                                             rel=tol)


def test_mfu_report_sources_and_disagreement():
    rep = efficiency.mfu_report(None, 1e9, 0.1, 1e12)
    assert rep["mfu_source"] == "analytic"
    assert rep["mfu"] == pytest.approx(0.01)
    assert "measured_flops_per_step" not in rep

    rep = efficiency.mfu_report(2e9, 1e9, 0.1, 1e12)
    assert rep["mfu_source"] == "measured"
    assert rep["mfu"] == pytest.approx(0.02)
    assert rep["flops_disagree"]
    assert rep["flops_disagreement"] == pytest.approx(1.0)
    lines = efficiency.mfu_lines(rep)
    assert "measured" in lines[0]
    assert "disagree" in lines[1]

    rep = efficiency.mfu_report(1.05e9, 1e9, 0.1, 1e12)
    assert not rep.get("flops_disagree")    # within the 10% band
    assert len(efficiency.mfu_lines(rep)) == 1


def test_grad_allreduce_bytes():
    import numpy as np

    params = {"w": np.zeros((4, 4), np.float32),
              "b": np.zeros((4,), np.float32)}
    assert efficiency.grad_allreduce_bytes(params) == 20 * 4
    assert efficiency.grad_allreduce_bytes(params, "bf16") == 20 * 2


# the ONE copy of the test fabric-ceiling sweep lives in conftest.py,
# next to the session rewind_run fixture that also consumes it
from conftest import ceiling_file  # noqa: E402


def test_load_fabric_ceiling(tmp_path):
    c = efficiency.load_fabric_ceiling(ceiling_file(tmp_path))
    assert c["world_size"] == 8
    assert c["ceilings"]["allreduce"]["busbw_gbps"] == 17.5
    with pytest.raises(FileNotFoundError):
        efficiency.load_fabric_ceiling(str(tmp_path / "nope.json"))
    bad = tmp_path / "bad.json"
    bad.write_text("{}")
    with pytest.raises(ValueError, match="osu sweep export"):
        efficiency.load_fabric_ceiling(str(bad))


def test_ceiling_utilization_arithmetic():
    summary = {"mean_step_ms": 100.0, "total_workers": 8,
               "allreduce_bytes_per_step": 100 * 10**6}
    trace = {"buckets": {"compute": 70.0, "collective": 30.0},
             "steps": 2, "collective_ops": {"allreduce": 30.0}}
    ceiling = {"world_size": 8,
               "ceilings": {"allreduce": {"busbw_gbps": 10.0,
                                          "message_bytes": 1 << 20}}}
    text = "\n".join(
        efficiency.ceiling_utilization_lines(summary, trace, ceiling))
    # collective = 30% of a 100ms step -> 0.03 s/step;
    # algbw = 1e8 B / 0.03 s = 3.333 GB/s; busbw = x 2*7/8 = 5.833;
    # utilization = 5.833 / 10 = 58%
    assert "5.83 GB/s busbw = 58% of measured ceiling 10.00 GB/s" in text
    # graceful degradations, never silence
    assert "no trace buckets" in "\n".join(
        efficiency.ceiling_utilization_lines(summary, None, ceiling))
    assert "sweep world" in "\n".join(efficiency.ceiling_utilization_lines(
        dict(summary, total_workers=4), trace, ceiling))[:200]


def test_driver_rejects_missing_ceiling_file(tmp_path):
    """--fabric_ceiling is validated at RUN start (flag parsing stays
    filesystem-pure): a typo'd path dies before warmup, not after the
    full run when the summary needs the sweep."""
    cfg = flags.BenchmarkConfig(
        model="trivial", fabric_ceiling=str(tmp_path / "nope.json"),
    ).resolve()
    with pytest.raises(FileNotFoundError, match="fabric_ceiling"):
        driver.run_benchmark(cfg, print_fn=lambda s: None)


def _device_trace_events(ops):
    """Minimal perfetto event list with a TPU device pid: ``ops`` are
    (tid, name, ts, dur) X events."""
    events = [{"ph": "M", "pid": 1, "name": "process_name",
               "args": {"name": "/device:TPU:0"}}]
    for tid, name, ts, dur in ops:
        events.append({"ph": "X", "pid": 1, "tid": tid, "name": name,
                       "ts": ts, "dur": dur})
    return events


def test_collective_overlap_exposed_fraction():
    """The --overlap_grad_comm measurement: a collective hidden behind
    concurrent compute on a sibling track is overlapped; one running
    alone is exposed.  The overlapped trace must report a strictly
    lower exposed fraction than the serialized one."""
    from tpu_hc_bench.obs import trace as trace_mod

    # serialized (off): backward compute [0,100), then the all-reduce
    # [100,140) with the device otherwise idle
    off = trace_mod.leaf_intervals(_device_trace_events([
        (1, "fusion.backward", 0, 100),
        (2, "all-reduce.1", 100, 40),
    ]))
    rec_off = efficiency.collective_overlap(off)
    assert rec_off["exposed_frac"] == pytest.approx(1.0)
    # overlapped (on): the same 40us of all-reduce, 30 of them under
    # the still-running backward
    on = trace_mod.leaf_intervals(_device_trace_events([
        (1, "fusion.backward", 0, 100),
        (2, "all-reduce.1", 70, 40),
    ]))
    rec_on = efficiency.collective_overlap(on)
    assert rec_on["collective_us"] == pytest.approx(40.0)
    assert rec_on["exposed_frac"] == pytest.approx(10.0 / 40.0)
    assert rec_on["exposed_frac"] < rec_off["exposed_frac"]
    assert rec_on["overlapped_frac"] == pytest.approx(30.0 / 40.0)
    lines = efficiency.overlap_lines(rec_on)
    assert "exposed" in lines[0] and "overlapped" in lines[0]
    # no collectives at all -> None, not a zero-division
    assert efficiency.collective_overlap(
        [("fusion.fwd", 0.0, 10.0)]) is None


def test_collective_busbw_absolute_lines():
    """Satellite: achieved allreduce busbw in absolute GB/s with NO
    ceiling sweep — same arithmetic as the ceiling line (100 MB over
    30% of a 100ms step at world 8 -> 5.83 GB/s busbw)."""
    summary = {"mean_step_ms": 100.0, "total_workers": 8,
               "allreduce_bytes_per_step": 100 * 10**6}
    trace = {"buckets": {"compute": 70.0, "collective": 30.0},
             "steps": 2, "collective_ops": {"allreduce": 30.0}}
    text = "\n".join(efficiency.collective_busbw_lines(summary, trace))
    assert "5.83 GB/s busbw" in text
    assert "absolute" in text
    # the zero1 arm's split collectives fold into the same figure — and
    # a realistic zero1 trace ALSO carries a tiny loss-pmean all-reduce,
    # which must sum into the denominator, not replace it (the 0.5us
    # all-reduce alone would report thousands of GB/s)
    z = {"buckets": {"compute": 70.0, "collective": 30.0},
         "collective_ops": {"reduce_scatter": 18.0, "all_gather": 12.0}}
    assert "5.83 GB/s busbw" in "\n".join(
        efficiency.collective_busbw_lines(summary, z))
    z2 = {"buckets": {"compute": 70.0, "collective": 30.0},
          "collective_ops": {"allreduce": 0.5, "reduce_scatter": 18.0,
                             "all_gather": 11.5}}
    assert "5.83 GB/s busbw" in "\n".join(
        efficiency.collective_busbw_lines(summary, z2))
    # degradations stay silent (the ceiling path owns the loud lines)
    assert efficiency.collective_busbw_lines(summary, None) == []
    assert efficiency.collective_busbw_lines(
        dict(summary, total_workers=1), trace) == []


def test_summarize_prints_busbw_and_overlap_without_ceiling(tmp_path):
    """obs summarize on a run with trace buckets but NO --fabric_ceiling
    must print the absolute busbw line (previously ceiling-gated) and
    the collective-exposure attribution when the record carries one."""
    d = tmp_path / "m"
    d.mkdir()
    (d / "manifest.json").write_text('{"schema": 1, "model": "trivial"}\n')
    (d / "metrics.jsonl").write_text(
        '{"kind": "summary", "mean_step_ms": 100.0, "total_workers": 8, '
        '"allreduce_bytes_per_step": 100000000, "mfu": 0.3, '
        '"mfu_source": "measured"}\n'
        '{"kind": "trace_buckets", '
        '"buckets": {"compute": 70.0, "collective": 30.0}, "steps": 2, '
        '"collective_ops": {"allreduce": 30.0}, '
        '"overlap": {"collective_us": 40.0, "exposed_us": 10.0, '
        '"exposed_frac": 0.25, "overlapped_frac": 0.75}}\n')
    out = io.StringIO()
    assert obs_main(["summarize", str(d)], out=out) == 0
    text = out.getvalue()
    assert "GB/s busbw" in text
    assert "collective exposure: 25.0%" in text


def test_osu_sweep_json_roundtrip(tmp_path):
    from tpu_hc_bench.microbench import osu

    rows = [osu.SweepResult("allreduce", 8, 1024, 10.0, 0.1, 0.175)]
    data = osu.sweep_json({"allreduce": rows})
    assert data["world_size"] == 8
    p = tmp_path / "s.json"
    p.write_text(json.dumps(data))
    c = efficiency.load_fabric_ceiling(str(p))
    assert c["ceilings"]["allreduce"]["busbw_gbps"] == pytest.approx(0.175)


# ---------------------------------------------------------------------
# 4. degraded artifacts: one-line errors, distinct exit codes
#    (satellites: fsync'd close + graceful summarize/diff)


def test_metrics_stream_survives_sigkill(tmp_path):
    """Kill -9 mid-stream: every event() up to the kill must be on disk
    (per-event flush; close() additionally fsyncs for the exit-70/75
    paths, which DO close before dying)."""
    mdir = str(tmp_path / "m")
    prog = (
        "import os, signal\n"
        "from tpu_hc_bench.obs import metrics\n"
        f"w = metrics.MetricsWriter({mdir!r}, {{'schema': 1}}, "
        "primary=True)\n"
        "w.event('window', step=1, rate=10.0)\n"
        "w.event('preempt', step=2)\n"
        "os.kill(os.getpid(), signal.SIGKILL)\n"
    )
    proc = subprocess.run([sys.executable, "-c", prog], cwd=REPO,
                          env=dict(os.environ, JAX_PLATFORMS="cpu"),
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == -signal.SIGKILL
    _, records = obs_metrics.read_run(mdir)
    assert [r["kind"] for r in records] == ["window", "preempt"]
    assert records[-1]["step"] == 2         # the tail survived


def test_summarize_missing_manifest_degrades(tmp_path, capsys):
    d = tmp_path / "m"
    d.mkdir()
    (d / "metrics.jsonl").write_text(
        '{"kind": "window", "step": 2, "rate": 8.0, "step_ms": 2.0, '
        '"loss": 0.5}\n')
    out = io.StringIO()
    assert obs_main(["summarize", str(d)], out=out) == 1
    assert "manifest" in capsys.readouterr().err
    assert "run:" in out.getvalue()         # still rendered what survived


def test_summarize_truncated_tail_degrades(tmp_path, capsys):
    d = tmp_path / "m"
    d.mkdir()
    (d / "manifest.json").write_text('{"schema": 1, "model": "trivial"}\n')
    (d / "metrics.jsonl").write_text(
        '{"kind": "window", "step": 2, "rate": 8.0, "step_ms": 2.0, '
        '"loss": 0.5}\n'
        '{"kind": "summary", "mfu": 0.')     # killed mid-write
    out = io.StringIO()
    assert obs_main(["summarize", str(d)], out=out) == 1
    assert "corrupt/truncated" in capsys.readouterr().err
    assert "model=trivial" in out.getvalue()


def test_summarize_missing_stream_is_one_line_error(tmp_path, capsys):
    assert obs_main(["summarize", str(tmp_path / "nope")],
                    out=io.StringIO()) == 2
    err = capsys.readouterr().err
    assert err.startswith("error:") and "Traceback" not in err


def test_diff_degraded_side_nonzero_exit(tmp_path, capsys):
    good = tmp_path / "good"
    good.mkdir()
    (good / "manifest.json").write_text('{"schema": 1}\n')
    (good / "metrics.jsonl").write_text('{"kind": "summary", "mfu": 1}\n')
    bad = tmp_path / "bad"
    bad.mkdir()
    (bad / "metrics.jsonl").write_text('{"kind": "summary", "mfu": 1}\n')
    out = io.StringIO()
    assert obs_main(["diff", str(good), str(bad)], out=out) == 1
    assert "manifest" in capsys.readouterr().err
    assert "diff:" in out.getvalue()


def test_corrupt_manifest_degrades(tmp_path, capsys):
    d = tmp_path / "m"
    d.mkdir()
    (d / "manifest.json").write_text("{not json")
    (d / "metrics.jsonl").write_text('{"kind": "summary", "mfu": 1}\n')
    out = io.StringIO()
    assert obs_main(["summarize", str(d)], out=out) == 1
    assert "unreadable manifest" in capsys.readouterr().err


# ---------------------------------------------------------------------
# 5. end-to-end: one rewind-injected run feeds the acceptance checks


# the shared rewind-injected driver run lives in conftest.py
# (session-scoped `rewind_run`): test_memory_obs consumes the same
# single run, so the default lane still pays for it exactly once


def test_rewind_run_goodput_below_one(rewind_run):
    res = rewind_run["result"]
    assert 0.0 < res.goodput < 1.0
    # the driver printed the account
    text = "\n".join(rewind_run["out"])
    assert "goodput:" in text
    assert "rewind_lost" in text
    # ... and summarize folds the same account from the artifacts, with
    # the rewind_replay/rewind_lost time attributed
    out = io.StringIO()
    assert obs_main(["summarize", rewind_run["dir"]], out=out) == 0
    stext = out.getvalue()
    assert "goodput:" in stext and "rewind_lost" in stext
    led = goodput.build_ledger(
        obs_metrics.read_run(rewind_run["dir"])[1])
    assert led.rewind_lost_s > 0.0


def test_rewind_run_mfu_line_labeled(rewind_run):
    res = rewind_run["result"]
    assert res.mfu_source in ("measured", "analytic")
    text = "\n".join(rewind_run["out"])
    assert f"({res.mfu_source})" in text
    out = io.StringIO()
    obs_main(["summarize", rewind_run["dir"]], out=out)
    assert "flops source:" in out.getvalue()
    # on this backend the AOT cost analysis works, so the honest path ran
    assert res.mfu_source == "measured"
    # num_classes=10 vs the canonical 1000-class table: the measured
    # figure must be FAR below the analytic one, and flagged
    summary = obs_metrics.read_run(rewind_run["dir"])[1][-1]
    assert summary["kind"] == "summary"
    assert summary["flops_disagree"]


def test_rewind_run_heartbeats_and_summarize_fleet(rewind_run):
    beats = fleet.read_heartbeats(rewind_run["dir"])
    assert 0 in beats and beats[0][-1]["step"] >= 1
    out = io.StringIO()
    obs_main(["summarize", rewind_run["dir"]], out=out)
    assert "heartbeats: 1 host file(s)" in out.getvalue()


def test_rewind_run_ceiling_lines(rewind_run):
    # a CPU run writes no device trace, so the driver and the CLI both
    # degrade to the explanatory line...
    assert any("fabric ceiling: no trace buckets" in ln
               for ln in rewind_run["out"])
    out = io.StringIO()
    rc = obs_main(["summarize", rewind_run["dir"],
                   "--fabric_ceiling", rewind_run["ceiling"]], out=out)
    assert rc == 0 and "no trace buckets" in out.getvalue()
    # ... and once trace buckets exist (here: appended as a TPU run
    # would have recorded them), the per-collective %-of-ceiling renders
    with open(os.path.join(rewind_run["dir"], "metrics.jsonl"), "a") as f:
        f.write(json.dumps({
            "kind": "trace_buckets",
            "buckets": {"compute": 70.0, "collective": 30.0},
            "steps": 2, "collective_ops": {"allreduce": 30.0}}) + "\n")
    out = io.StringIO()
    rc = obs_main(["summarize", rewind_run["dir"],
                   "--fabric_ceiling", rewind_run["ceiling"]], out=out)
    assert rc == 0
    assert "% of measured ceiling" in out.getvalue()


def test_rewind_run_watch_renders_and_exits(rewind_run):
    buf = io.StringIO()
    rc = watch_mod.watch(rewind_run["dir"], out=buf, interval=0.01)
    assert rc == 0                          # completed run: exits clean
    text = buf.getvalue()
    assert "DONE" in text
    assert "goodput" in text
    assert "last resilience event: rewind" in text


def test_watch_live_headline_from_heartbeats(tmp_path):
    """Mid-run there are no window records yet (they land when the
    timed loop finishes) — the headline must fall back to the freshest
    heartbeat, and degradations render in-panel, not as per-poll
    stderr spam."""
    d = tmp_path / "live"
    d.mkdir()
    (d / "metrics.jsonl").write_text(
        '{"kind": "phase", "phase": "step", "t": 1.0}\n'
        '{"kind": "window", "st')                # live truncated tail
    w = fleet.FleetWriter(str(d), process_index=0)
    w.heartbeat(step=42, step_ewma_ms=9.5)
    w.close()
    buf = io.StringIO()
    assert watch_mod.watch(str(d), out=buf, follow=False) == 0
    text = buf.getvalue()
    assert "step 42 (heartbeat)" in text
    assert "WARNING" in text                     # in the panel itself


def test_watch_timeout_on_unfinished_run(tmp_path):
    d = tmp_path / "live"
    d.mkdir()
    (d / "manifest.json").write_text('{"schema": 1, "model": "t"}\n')
    (d / "metrics.jsonl").write_text(
        '{"kind": "window", "step": 2, "rate": 8.0, "step_ms": 2.0, '
        '"loss": 0.5}\n')
    buf = io.StringIO()
    rc = watch_mod.watch(str(d), out=buf, interval=0.01, timeout_s=0.05)
    assert rc == 1
    assert "timeout" in buf.getvalue()
    # --no-follow: one snapshot, exit 0 even mid-run
    assert watch_mod.watch(str(d), out=io.StringIO(), follow=False) == 0


@pytest.mark.slow
def test_watch_cli_subprocess(rewind_run):
    proc = subprocess.run(
        [sys.executable, "-m", "tpu_hc_bench.obs", "watch",
         rewind_run["dir"], "--interval", "0.1", "--timeout", "30"],
        cwd=REPO, env=dict(os.environ, JAX_PLATFORMS="cpu"),
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "DONE" in proc.stdout
