"""3-D hybrid sharding (round 2): DPxSPxTP and DPxPPxTP.

The one-minor-axis restriction is lifted: ``build_mesh`` composes minor
axes, and the step builders run PP/SP as *manual* shard_map axes with the
model axis *auto* (GSPMD partitions the per-shard math and inserts the
Megatron all-reduces).  Numeric checks pin the hybrid against a control
with the SAME dp/sp (or dp/pp) degrees on half the devices, so tensor
parallelism is the only difference — its transparency is the property
under test.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_hc_bench import flags
from tpu_hc_bench.data.synthetic import SyntheticTokens
from tpu_hc_bench.models import create_model
from tpu_hc_bench.topology import (
    DATA_AXIS, MODEL_AXIS, PIPE_AXIS, SEQ_AXIS, build_mesh, compute_layout,
)
from tpu_hc_bench.train import step as step_mod
from tpu_hc_bench._compat import CAPABILITIES

# the SP x TP / PP x TP hybrids compose manual shard_map axes with an
# auto (GSPMD) model axis; the 0.4.x CPU SPMD partitioner rejects the
# program ("PartitionId instruction is not supported")
requires_partial_auto = pytest.mark.skipif(
    not CAPABILITIES["partial_auto_shard_map"],
    reason="this jax's SPMD partitioner cannot compile "
           "partial-manual (auto model axis) shard_map programs")


def test_build_mesh_composes_minor_axes(devices):
    layout = compute_layout(1, len(devices), len(devices))
    mesh = build_mesh(layout, pipeline_parallel=2, model_parallel=2)
    assert mesh.axis_names == (DATA_AXIS, PIPE_AXIS, MODEL_AXIS)
    assert mesh.shape == {DATA_AXIS: 2, PIPE_AXIS: 2, MODEL_AXIS: 2}
    mesh = build_mesh(layout, sequence_parallel=2, model_parallel=2)
    assert mesh.axis_names == (DATA_AXIS, SEQ_AXIS, MODEL_AXIS)
    # DP-only keeps the 2-D (data, model=1) shape
    mesh = build_mesh(layout)
    assert mesh.axis_names == (DATA_AXIS, MODEL_AXIS)
    assert mesh.shape[MODEL_AXIS] == 1
    with pytest.raises(ValueError, match="not divisible"):
        build_mesh(layout, pipeline_parallel=3, model_parallel=2)


def _sp_tp_setup(devices, n_devices, tp):
    """llama_tiny (no dropout) with ring attention, dp=2 x sp=2 x tp."""
    layout = compute_layout(1, n_devices, len(devices))
    mesh = build_mesh(layout, sequence_parallel=2, model_parallel=tp)
    cfg = flags.BenchmarkConfig(
        model="llama_tiny", batch_size=1, sequence_parallel=2,
        model_parallel=tp, attention_impl="ring",
    ).resolve()
    model, spec = create_model("llama_tiny", attention_impl="ring",
                               seq_axis=SEQ_AXIS)
    batch = SyntheticTokens(4, 64, vocab_size=1024, seed=0,
                            causal_lm=True).batch()
    init_model = model.clone(attention_impl="dense", seq_axis=None)
    state = step_mod.make_train_state(init_model, cfg, batch)
    state = state.replace(apply_fn=model.apply)
    if tp > 1:
        state = step_mod.shard_state_tp(state, mesh)
    else:
        state = step_mod.replicate_state(state, mesh)
    train_step = step_mod.build_train_step(mesh, cfg, spec)
    from jax.sharding import PartitionSpec as P

    dev_batch = step_mod.shard_batch(batch, mesh, P(DATA_AXIS, SEQ_AXIS))
    return state, train_step, dev_batch


@requires_partial_auto
def test_dp_sp_tp_matches_dp_sp(devices):
    """dp2 x sp2 x tp2 (8 devs) == dp2 x sp2 (4 devs): TP transparent."""
    rng = jax.random.PRNGKey(0)
    losses = []
    for n, tp in ((4, 1), (8, 2)):
        state, train_step, batch = _sp_tp_setup(devices, n, tp)
        if tp > 1:
            wq = state.params["layer_0"]["attn"]["wq"]["kernel"]
            assert MODEL_AXIS in wq.sharding.spec
        for _ in range(3):
            state, metrics = train_step(state, batch, rng)
        losses.append(float(jax.device_get(metrics["loss"])))
    np.testing.assert_allclose(losses[0], losses[1], rtol=1e-4)


def _pp_tp_setup(devices, n_devices, tp):
    """GPT-tiny, deterministic (dropout off), dp=2 x pp=2 x tp."""
    from tpu_hc_bench.models.gpt import GPTLM
    from tpu_hc_bench.parallel import pipeline as pipe_mod

    layout = compute_layout(1, n_devices, len(devices))
    mesh = build_mesh(layout, pipeline_parallel=2, model_parallel=tp)
    cfg = flags.BenchmarkConfig(model="gpt2", batch_size=4,
                                pipeline_parallel=2).resolve()
    model = GPTLM(vocab_size=64, hidden=32, num_layers=4, heads=4,
                  ffn=64, max_len=16)
    batch = SyntheticTokens(8, 16, vocab_size=64, seed=0,
                            causal_lm=True).batch()
    params, opt_state = pipe_mod.make_pp_state(model, cfg, batch[0], mesh,
                                               tp=tp > 1)
    step, _ = pipe_mod.build_pp_train_step(
        mesh, model, cfg, 2, params, opt_state, deterministic=True,
        tp=tp > 1)
    from jax.sharding import NamedSharding, PartitionSpec as P

    dev_batch = jax.tree.map(
        lambda x: jax.device_put(x, NamedSharding(mesh, P(DATA_AXIS))),
        batch)
    return params, opt_state, step, dev_batch


@requires_partial_auto
def test_dp_pp_tp_matches_dp_pp(devices):
    """dp2 x pp2 x tp2 (8 devs) == dp2 x pp2 (4 devs)."""
    losses = []
    for n, tp in ((4, 1), (8, 2)):
        params, opt_state, step, batch = _pp_tp_setup(devices, n, tp)
        if tp > 1:
            fc = params["trunk"]["fc"]["kernel"]
            assert MODEL_AXIS in fc.sharding.spec
            assert fc.sharding.spec[0] == PIPE_AXIS
        for _ in range(3):
            params, opt_state, loss = step(params, opt_state, batch)
        losses.append(float(jax.device_get(loss)))
    np.testing.assert_allclose(losses[0], losses[1], rtol=1e-4)


@requires_partial_auto
def test_driver_sp_tp_end_to_end(mesh8):
    """--sequence_parallel 2 --model_parallel 2 through run_benchmark."""
    from tpu_hc_bench.train import driver

    cfg = flags.BenchmarkConfig(
        model="llama_tiny", batch_size=2, num_warmup_batches=1,
        num_batches=2, display_every=1, sequence_parallel=2,
        model_parallel=2, attention_impl="ring",
    ).resolve()
    out = []
    res = driver.run_benchmark(cfg, print_fn=out.append)
    text = "\n".join(out)
    assert "tensor parallel: 2-way (hybrid with SP)" in text
    assert np.isfinite(res.final_loss)


@requires_partial_auto
def test_driver_pp_tp_end_to_end(mesh8):
    """--pipeline_parallel 2 --model_parallel 2 through run_benchmark."""
    from tpu_hc_bench.train import driver

    cfg = flags.BenchmarkConfig(
        model="moe_tiny", batch_size=4, num_warmup_batches=1,
        num_batches=2, display_every=1, pipeline_parallel=2,
        model_parallel=2,
    ).resolve()
    out = []
    res = driver.run_benchmark(cfg, print_fn=out.append)
    text = "\n".join(out)
    assert "tensor parallel: 2-way (hybrid with PP)" in text
    assert np.isfinite(res.final_loss)


def test_rejects_unsupported_combos():
    # rejected at flag resolution, before any mesh is built
    with pytest.raises(ValueError, match="not a supported composition"):
        flags.BenchmarkConfig(
            model="bert_tiny", batch_size=2, pipeline_parallel=2,
            sequence_parallel=2,
        ).resolve()
    with pytest.raises(ValueError, match="'model' axis"):
        flags.BenchmarkConfig(
            model="moe_tiny", batch_size=2, model_parallel=2,
            expert_parallel=2,
        ).resolve()
    with pytest.raises(ValueError, match="data parallelism only"):
        flags.BenchmarkConfig(
            model="moe_tiny", batch_size=2, expert_parallel=2,
            pipeline_parallel=2,
        ).resolve()
