"""Host-level shared input service (data/service.py, round 13).

Default lane is pure host-side work — shm rings + threads + tiny
synthetic in-memory shards — near-zero cost, NO driver runs (tier-1
sits ~805s of the 870s budget).  The 4-worker multi-process e2e and
the real driver smoke are slow-marked like the kill/resume e2es.

The load-bearing pins:
- ring-buffer handoff correctness under concurrent producer/consumer
  (order, content integrity, backpressure counters);
- service-vs-per-process batch streams bitwise-identical at a fixed
  seed (the regression the whole design hangs on);
- sliced serving decodes only the consumed rows yet delivers the same
  bytes the full pipeline would for those rows;
- packed token batches keep ONE bucket shape (service consumers never
  recompile);
- the input-pool-width lint + the obs input line/diff row.
"""

from __future__ import annotations

import json
import threading
import time

import numpy as np
import pytest

from tpu_hc_bench import flags
from tpu_hc_bench.data import imagenet, tokens
from tpu_hc_bench.data import service as svc
from tpu_hc_bench.obs import fleet, goodput
from tpu_hc_bench.obs import metrics as obs_metrics


@pytest.fixture(scope="module")
def shards(tmp_path_factory):
    d = tmp_path_factory.mktemp("svc_shards")
    imagenet.make_synthetic_shards(
        d, num_shards=4, examples_per_shard=6, image_size=32,
        num_classes=10)
    return d


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    d = tmp_path_factory.mktemp("svc_corpus")
    rng = np.random.default_rng(0)
    stream: list[int] = []
    while len(stream) < 6000:
        stream.extend(rng.integers(1, 90, int(rng.integers(3, 40))).tolist()
                      + [0])
    tokens.write_token_file(d / "train.bin", np.asarray(stream),
                            vocab_size=90)
    return d


# ---------------------------------------------------------------------
# shm ring


def _layout():
    return svc.BatchLayout([svc.ArraySpec("img", (4, 8), "uint8"),
                            svc.ArraySpec("lab", (4,), "int32")])


def test_ring_concurrent_handoff_order_and_integrity():
    """Producer thread vs consumer under jitter: every batch arrives
    once, in order, contents intact; occupancy histogram accounts for
    every publish."""
    lay = _layout()
    ring = svc.ShmRing.create("thbt_ring1", lay, 2)
    try:
        peer = svc.ShmRing.attach("thbt_ring1", lay, 2)
        n = 60

        def produce():
            for i in range(n):
                ring.put((np.full((4, 8), i % 251, np.uint8),
                          np.full((4,), i, np.int32)))
            ring.close_producer()

        t = threading.Thread(target=produce)
        t.start()
        seen = []
        while True:
            views = peer.get(timeout=30.0)
            if views is None:
                break
            img, lab = views
            i = int(lab[0])
            assert (img == i % 251).all()      # integrity under reuse
            seen.append(i)
            if i % 7 == 0:
                time.sleep(0.002)              # consumer jitter
            peer.advance()
        t.join()
        assert seen == list(range(n))
        s = ring.stats()
        assert s["produced"] == s["consumed"] == n
        assert sum(s["occ_hist"]) == n
        # depth-2 ring with a jittery consumer: the producer stalled
        assert s["producer_stall_s"] > 0.0
        assert 0 <= s["occ_p50"] <= s["occ_p99"] <= 2
        peer.close()
    finally:
        ring.close()
        ring.unlink()


def test_ring_error_and_close_signalling():
    lay = _layout()
    ring = svc.ShmRing.create("thbt_ring2", lay, 2)
    try:
        ring.close_producer(error=True)
        with pytest.raises(RuntimeError, match="producer died"):
            ring.get()
    finally:
        ring.close()
        ring.unlink()


def test_ring_attach_missing_times_out():
    with pytest.raises(FileNotFoundError, match="did not appear"):
        svc.ShmRing.attach("thbt_never_exists", _layout(), 2, timeout=0.2)


def test_ring_layout_mismatch_rejected():
    small = _layout()
    big = svc.BatchLayout([svc.ArraySpec("img", (64, 64, 64, 3), "uint8")])
    ring = svc.ShmRing.create("thbt_ring3", small, 6)
    try:
        with pytest.raises(ValueError, match="disagree"):
            svc.ShmRing.attach("thbt_ring3", big, 6, timeout=1.0)
        # a SMALLER geometry fits size-wise but would read wrong
        # offsets — the header handshake must refuse it loudly
        with pytest.raises(ValueError, match="geometry"):
            svc.ShmRing.attach("thbt_ring3", small, 2, timeout=1.0)
    finally:
        ring.close()
        ring.unlink()


def test_service_stop_unblocks_waiting_consumer(shards):
    """stop() (also the rank-0 error/atexit path) marks every ring
    closed, so a consumer blocked in get() sees end-of-stream instead
    of polling a dead ring forever."""
    service = svc.make_image_service(
        [str(shards)], num_workers=1, global_batch=4, image_size=16,
        depth=2).start()
    lay = svc.image_batch_layout(4, 16, "uint8")
    client = svc.ServiceClient(service.name, lay, worker=0, copy=True)
    it = iter(client)
    next(it)

    got = {}

    def drain():
        got["n"] = sum(1 for _ in it)       # ends when the ring closes

    t = threading.Thread(target=drain)
    t.start()
    time.sleep(0.05)
    service.stop()
    t.join(timeout=10.0)
    assert not t.is_alive(), "consumer still blocked after service.stop()"
    client.close()


# ---------------------------------------------------------------------
# the identity pin: service == per-process pipeline, bitwise


def _reference_stream(shards, worker, num_workers, n, seed=7,
                      wire="uint8"):
    ds = imagenet.ImageNetDataset(
        shards, global_batch=4, image_size=16, train=True, worker=worker,
        num_workers=num_workers, seed=seed, wire_dtype=wire)
    it = ds._batches()
    out = [next(it) for _ in range(n)]
    it.close()
    return out


def test_service_stream_bitwise_identity(shards):
    """THE pinned regression: each worker's delivered ring stream is
    bitwise-identical to the per-process pipeline at a fixed seed."""
    ref = {w: _reference_stream(shards, w, 2, 3) for w in range(2)}
    service = svc.make_image_service(
        [str(shards)], num_workers=2, global_batch=4, image_size=16,
        seed=7, wire_dtype="uint8", depth=2).start()
    try:
        for w in range(2):
            client = svc.ServiceClient(
                service.name, svc.image_batch_layout(4, 16, "uint8"),
                worker=w, copy=True)
            it = iter(client)
            for n in range(3):
                img, lab = next(it)
                np.testing.assert_array_equal(img, ref[w][n][0])
                np.testing.assert_array_equal(lab, ref[w][n][1])
            client.close()
    finally:
        service.stop()


def test_service_stream_identity_float32(shards):
    (ref_img, ref_lab), = _reference_stream(shards, 0, 1, 1,
                                            wire="float32")
    service = svc.make_image_service(
        [str(shards)], num_workers=1, global_batch=4, image_size=16,
        seed=7, wire_dtype="float32", depth=2).start()
    try:
        client = svc.ServiceClient(
            service.name, svc.image_batch_layout(4, 16, "float32"),
            worker=0, copy=True)
        img, lab = next(iter(client))
        np.testing.assert_array_equal(img, ref_img)   # bitwise, f32 too
        np.testing.assert_array_equal(lab, ref_lab)
        client.close()
    finally:
        service.stop()


def test_sliced_mode_decodes_only_consumed_rows(shards):
    """slice_per_worker: worker w's ring carries rows [w*b,(w+1)*b) of
    its stream, bitwise-equal to the full pipeline's same rows — the
    W-fold host decode saving with unchanged delivered pixels."""
    ref = {w: _reference_stream(shards, w, 2, 2) for w in range(2)}
    service = svc.make_image_service(
        [str(shards)], num_workers=2, global_batch=4, image_size=16,
        seed=7, wire_dtype="uint8", depth=2, slice_per_worker=True,
    ).start()
    try:
        for w in range(2):
            client = svc.ServiceClient(
                service.name, svc.image_batch_layout(2, 16, "uint8"),
                worker=w, copy=True)
            it = iter(client)
            for n in range(2):
                img, lab = next(it)
                lo, hi = w * 2, (w + 1) * 2
                np.testing.assert_array_equal(img, ref[w][n][0][lo:hi])
                np.testing.assert_array_equal(lab, ref[w][n][1][lo:hi])
            client.close()
    finally:
        service.stop()


def test_decode_rows_rng_alignment(shards):
    """decode_rows advances the per-row RNG over every row, so the
    decoded slice is bitwise-identical to the full pipeline's."""
    full = _reference_stream(shards, 0, 1, 2)
    ds = imagenet.ImageNetDataset(
        shards, global_batch=4, image_size=16, train=True, seed=7,
        wire_dtype="uint8", decode_rows=(1, 3))
    it = ds._batches()
    for n in range(2):
        img, lab = next(it)
        np.testing.assert_array_equal(img[1:3], full[n][0][1:3])
        np.testing.assert_array_equal(lab, full[n][1])
    it.close()
    assert ds.stats()["examples"] == 4      # 2 rows/batch decoded, not 8


def test_decode_rows_validation(shards):
    with pytest.raises(ValueError, match="decode_rows"):
        imagenet.ImageNetDataset(shards, global_batch=4,
                                 decode_rows=(2, 9))


def test_divided_default_pool_width(shards):
    solo = imagenet.ImageNetDataset(shards, global_batch=2)
    quad = imagenet.ImageNetDataset(shards, global_batch=2,
                                    local_workers=4)
    import os

    host_budget = max(1, min(32, (os.cpu_count() or 2) - 1))
    assert solo.decode_workers == host_budget
    assert quad.decode_workers == max(1, host_budget // 4)


# ---------------------------------------------------------------------
# backpressure accounting


def test_service_backpressure_stats(shards):
    service = svc.make_image_service(
        [str(shards)], num_workers=1, global_batch=4, image_size=16,
        seed=0, depth=2).start()
    try:
        client = svc.ServiceClient(
            service.name, svc.image_batch_layout(4, 16, "uint8"),
            worker=0, copy=True)
        it = iter(client)
        next(it)
        time.sleep(0.3)     # rings fill -> producer stalls accumulate
        next(it)
        s = service.stats()
        assert s["workers"] == 1 and s["depth"] == 2
        assert s["produced"] >= 2 and s["errors"] == 0
        assert s["producer_stall_s"] > 0.0
        assert set(s) >= {"occ_p50", "occ_p99", "consumer_wait_s",
                          "decode_workers"}
        win = client.window_stats()
        assert set(win) == {"ring_occ", "ring_depth", "wait_ms"}
        cstats = client.stats()
        assert cstats["input_service"] is True
        assert cstats["examples"] == cstats["batches"] * 4
        client.close()
    finally:
        service.stop()


def test_feeder_error_reaches_consumer(tmp_path):
    def bad_stream(w):
        def gen():
            raise RuntimeError("boom")
            yield  # pragma: no cover
        return gen()

    lay = _layout()
    service = svc.InputService("thbt_err", lay, 1, bad_stream,
                               depth=2).start()
    try:
        client = svc.ServiceClient("thbt_err", lay, worker=0)
        with pytest.raises(RuntimeError, match="producer died"):
            next(iter(client))
        assert service.errors and "boom" in service.errors[0]
        client.close()
    finally:
        service.stop()


# ---------------------------------------------------------------------
# dataset mixing


def test_mixture_schedule_deterministic_and_weighted():
    a = svc.mixture_schedule([3.0, 1.0], seed=5, n=400)
    b = svc.mixture_schedule([3.0, 1.0], seed=5, n=400)
    np.testing.assert_array_equal(a, b)
    frac = float((a == 0).mean())
    assert 0.6 < frac < 0.9         # ~0.75 expected
    with pytest.raises(ValueError, match="weights"):
        svc.mixture_schedule([0.0, 0.0], seed=0, n=4)


def test_weighted_mixture_follows_schedule():
    import itertools

    streams = [iter(("a", i) for i in itertools.count()),
               iter(("b", i) for i in itertools.count())]
    mix = svc.weighted_mixture(streams, [0.5, 0.5], seed=11)
    got = [next(mix)[0] for _ in range(32)]
    sched = svc.mixture_schedule([0.5, 0.5], seed=11, n=32)
    assert got == ["ab"[i] for i in sched]


def test_image_mixture_service_deterministic(shards, tmp_path):
    """Two shard sets interleaved: the delivered stream follows the
    counter-keyed schedule, so it is reproducible run to run."""
    other = tmp_path / "other"
    imagenet.make_synthetic_shards(other, num_shards=2,
                                   examples_per_shard=6, image_size=32,
                                   num_classes=10, seed=3)

    def grab():
        service = svc.make_image_service(
            [str(shards), str(other)], mix_weights=[0.5, 0.5],
            num_workers=1, global_batch=4, image_size=16, seed=2,
            depth=2).start()
        try:
            client = svc.ServiceClient(
                service.name, svc.image_batch_layout(4, 16, "uint8"),
                worker=0, copy=True)
            it = iter(client)
            out = [next(it) for _ in range(4)]
            client.close()
            return out
        finally:
            service.stop()

    one, two = grab(), grab()
    for (i1, l1), (i2, l2) in zip(one, two):
        np.testing.assert_array_equal(i1, i2)
        np.testing.assert_array_equal(l1, l2)


# ---------------------------------------------------------------------
# packed token batching


def test_split_documents_keeps_eod_drops_empty():
    # consecutive eods are EMPTY documents and must not waste bucket
    # capacity on 1-token [eod] segments; the trailing partial doc
    # (no eod yet) is kept
    docs = tokens.split_documents(np.array([5, 6, 0, 0, 7, 0, 8, 9]),
                                  eod_id=0)
    assert [d.tolist() for d in docs] == [[5, 6, 0], [7, 0], [8, 9]]
    assert tokens.split_documents(np.array([0, 0, 0]), eod_id=0) == []


def test_pack_sequences_first_fit_and_chunking():
    docs = [np.array([1, 2, 3]), np.array([4]),
            np.array([5, 6, 7, 8, 9, 10])]      # long doc chunks to 4+2
    p = tokens.pack_sequences(docs, 4)
    assert p["tokens"].shape == p["segment_ids"].shape \
        == p["positions"].shape
    assert p["tokens"].tolist() == [[1, 2, 3, 4], [5, 6, 7, 8],
                                    [9, 10, 0, 0]]
    assert p["segment_ids"].tolist() == [[1, 1, 1, 2], [1, 1, 1, 1],
                                         [1, 1, 0, 0]]
    assert p["positions"].tolist() == [[0, 1, 2, 0], [0, 1, 2, 3],
                                       [0, 1, 0, 0]]


def test_packed_dataset_fixed_bucket_and_determinism(corpus):
    ds = tokens.PackedTokenDataset(corpus, global_batch=8, seq_len=32,
                                   eod_id=0, seed=1)
    b0, b1, b0_again = ds.batch(0), ds.batch(1), ds.batch(0)
    # ONE bucket shape forever: consumers never recompile
    for arr in (*b0, *b1):
        assert arr.shape == (8, 32)
    for a, b in zip(b0, b0_again):
        np.testing.assert_array_equal(a, b)
    assert not np.array_equal(b0[0], b1[0])
    toks, targets, weights, segs = b0
    # weights only where the next token continues the same document
    assert weights.min() >= 0 and weights.max() == 1.0
    live = weights > 0
    assert (segs[live] > 0).all()
    # spot-check: a weighted position's target is the next stream token
    r, c = np.argwhere(live)[0]
    assert targets[r, c] == (toks[r, c + 1] if c + 1 < 32
                             else targets[r, c])


def test_packed_token_service_roundtrip(corpus):
    ref_ds = tokens.PackedTokenDataset(corpus, global_batch=4,
                                       seq_len=16, eod_id=0, worker=0,
                                       num_workers=1, seed=4)
    ref = [ref_ds.batch(0), ref_ds.batch(1)]
    service = svc.make_packed_token_service(
        str(corpus), num_workers=1, global_batch=4, seq_len=16,
        eod_id=0, seed=4, depth=2).start()
    try:
        client = svc.ServiceClient(
            service.name, svc.packed_token_layout(4, 16), worker=0,
            copy=True)
        it = iter(client)
        for n in range(2):
            got = next(it)
            for a, b in zip(got, ref[n]):
                np.testing.assert_array_equal(a, b)
        client.close()
    finally:
        service.stop()


# ---------------------------------------------------------------------
# flags + lint


def test_input_service_flags_parse_and_translate(shards):
    cfg = flags.parse_flags(["--input_service", "on", "--data_dir",
                             str(shards)])
    assert cfg.input_service == "on"
    # synthetic input: on -> off with a loud translation note
    cfg = flags.parse_flags(["--input_service", "on"])
    assert cfg.input_service == "off"
    assert "input_service" in cfg.translations
    # repeat_cached_sample shuts the pipeline down: nothing to serve
    cfg = flags.parse_flags(["--input_service", "on", "--data_dir",
                             str(shards),
                             "--datasets_repeat_cached_sample", "true"])
    assert cfg.input_service == "off"
    # text members: the packed-token service is API-only, so an
    # explicit on translates loudly instead of silently no-opping
    cfg = flags.parse_flags(["--input_service", "on", "--model", "gpt2",
                             "--data_dir", str(shards)])
    assert cfg.input_service == "off"
    assert "text members" in cfg.translations["input_service"]
    with pytest.raises(SystemExit):
        flags.parse_flags(["--input_service", "sometimes"])
    with pytest.raises(ValueError, match="service_decode_workers"):
        flags.BenchmarkConfig(service_decode_workers=-1).resolve()


def test_input_pool_width_lint():
    from tpu_hc_bench.analysis import lints

    over = lints.lint_source_text(
        "ds = ImageNetDataset('d', decode_workers=4096)\n", cpu_count=8)
    assert [f.lint for f in over] == ["input-pool-width"]
    full = lints.lint_source_text(
        "import os\nds = ImageNetDataset('d', "
        "decode_workers=os.cpu_count())\n", cpu_count=8)
    assert [f.lint for f in full] == ["input-pool-width"]
    divided = lints.lint_source_text(
        "import os\nds = ImageNetDataset('d', "
        "decode_workers=(os.cpu_count() or 2) // 4)\n", cpu_count=8)
    assert divided == []
    in_range = lints.lint_source_text(
        "ds = ImageNetDataset('d', decode_workers=2)\n", cpu_count=8)
    assert in_range == []
    suppressed = lints.lint_source_text(
        "ds = ImageNetDataset('d', decode_workers=4096)"
        "  # thb:lint-ok[input-pool-width]\n", cpu_count=8)
    assert suppressed == []


# ---------------------------------------------------------------------
# obs: input line + diff row + heartbeat fields


def _ledger(data_wait=2.0):
    recs = [
        {"kind": "phase", "phase": "init", "t": 0.0, "step": None},
        {"kind": "phase", "phase": "step", "t": 1.0, "step": None},
        {"kind": "phase_acc", "phase": "data_wait", "seconds": data_wait,
         "step": 8},
        {"kind": "phase", "phase": "end", "t": 10.0, "step": 10},
    ]
    return recs, goodput.build_ledger(recs)


def test_input_lines_service_and_per_process(tmp_path):
    recs, led = _ledger(data_wait=2.0)
    recs.append({"kind": "data", "examples": 80, "decode_workers": 2})
    # per-process arm: fraction + the arm label
    lines = fleet.input_lines(str(tmp_path), recs, led)
    assert any("data_wait 20.0% of wall" in ln for ln in lines)
    assert any("per-process pipeline" in ln for ln in lines)
    # service arm: ring occupancy + stalls from the input_service record
    recs.append({"kind": "input_service", "workers": 4, "depth": 6,
                 "decode_workers": 3, "produced": 100, "consumed": 99,
                 "producer_stall_s": 1.25, "consumer_wait_s": 0.5,
                 "occ_p50": 5, "occ_p99": 6, "errors": 0})
    lines = fleet.input_lines(str(tmp_path), recs, led)
    joined = "\n".join(lines)
    assert "service rings occ p50 5/6 p99 6/6" in joined
    assert "producer stalls 1.25s" in joined
    # synthetic runs (no data/input_service record): no input line
    assert fleet.input_lines(str(tmp_path), _ledger()[0], led) == []


def test_input_lines_mine_heartbeat_ring_fields(tmp_path):
    w = fleet.FleetWriter(str(tmp_path), process_index=0)
    for occ in (1, 2, 6):
        w.heartbeat(step=occ, step_ewma_ms=1.0,
                    input={"ring_occ": occ, "ring_depth": 6,
                           "wait_ms": 0.1})
    w.close()
    recs = [{"kind": "data", "examples": 8}]
    lines = fleet.input_lines(str(tmp_path), recs, None)
    joined = "\n".join(lines)
    assert "host rings (heartbeats)" in joined and "p50 2" in joined


def test_summarize_and_diff_render_input(tmp_path):
    for name, wait in (("a", 4.0), ("b", 0.2)):
        run = tmp_path / name
        w = obs_metrics.MetricsWriter(str(run), {"model": "trivial"},
                                      primary=True)
        w.event("phase", phase="init", t=0.0)
        w.event("phase", phase="step", t=1.0)
        w.event("phase_acc", phase="data_wait", seconds=wait, step=8)
        w.event("data", examples=80, decode_workers=2, decode_wall_s=1.0)
        w.event("phase", phase="end", t=11.0, step=10)
        w.close()
    # run a: wall 11s, data_wait 4s -> 36.4%
    out = obs_metrics.summarize_run(str(tmp_path / "a"))
    assert any("input: data_wait 36.4% of wall" in ln for ln in out)
    diff = obs_metrics.diff_runs(str(tmp_path / "a"), str(tmp_path / "b"))
    row = [ln for ln in diff if "data_wait frac" in ln]
    assert row and "-95.0%" in row[0]


# ---------------------------------------------------------------------
# slow lane: multi-process e2e + driver smoke


@pytest.mark.slow
def test_four_worker_multiprocess_e2e(shards):
    """The tentpole proof at 4 REAL consumer processes: every worker's
    ring stream crosses a process boundary bitwise-intact."""
    import multiprocessing as mp

    ref = {w: _reference_stream(shards, w, 4, 2) for w in range(4)}
    service = svc.make_image_service(
        [str(shards)], num_workers=4, global_batch=4, image_size=16,
        seed=7, wire_dtype="uint8", depth=2).start()

    def consume(name, w, q):
        try:
            client = svc.ServiceClient(
                name, svc.image_batch_layout(4, 16, "uint8"), worker=w,
                copy=True, timeout=60.0)
            it = iter(client)
            got = [next(it) for _ in range(2)]
            client.close()
            q.put((w, [(img.tobytes(), lab.tobytes())
                       for img, lab in got]))
        except Exception as e:  # pragma: no cover
            q.put((w, f"error: {e}"))

    try:
        ctx = mp.get_context("fork")
        q = ctx.Queue()
        procs = [ctx.Process(target=consume, args=(service.name, w, q))
                 for w in range(4)]
        for p in procs:
            p.start()
        results = dict(q.get(timeout=120) for _ in procs)
        for p in procs:
            p.join(timeout=30)
        for w in range(4):
            assert not isinstance(results[w], str), results[w]
            for n, (img_b, lab_b) in enumerate(results[w]):
                assert img_b == ref[w][n][0].tobytes(), (w, n)
                assert lab_b == ref[w][n][1].tobytes(), (w, n)
    finally:
        service.stop()


@pytest.mark.slow
def test_driver_input_service_smoke(shards, tmp_path):
    """--input_service=on through the real driver (single process): the
    service banner prints, the run completes, the input_service record
    lands, and `obs summarize` renders the input line."""
    from tpu_hc_bench.train import driver

    cfg = flags.BenchmarkConfig(
        model="trivial", num_classes=10, batch_size=1,
        num_warmup_batches=1, num_batches=3, display_every=1,
        data_dir=str(shards), input_service="on",
        metrics_dir=str(tmp_path / "m"), prefetch_depth=3,
    ).resolve()
    out: list[str] = []
    result = driver.run_benchmark(cfg, fabric_name="ici",
                                  print_fn=out.append)
    text = "\n".join(out)
    assert "input service: host decode pool" in text
    assert result.total_images_per_sec > 0
    assert result.data_wait_frac == result.data_wait_frac  # ledger ran
    recs = [json.loads(ln) for ln in
            (tmp_path / "m" / "metrics.jsonl").read_text().splitlines()]
    assert any(r.get("kind") == "input_service" for r in recs)
    hb = [r for r in fleet.read_heartbeats(str(tmp_path / "m")).get(0, [])
          if "input" in r]
    assert hb and "ring_occ" in hb[-1]["input"]
    lines = obs_metrics.summarize_run(str(tmp_path / "m"))
    assert any("service rings occ" in ln for ln in lines)
