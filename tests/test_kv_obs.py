"""KV-pool utilization ledger & admission forensics (round 22,
``tpu_hc_bench/obs/kv.py`` + serve-lane wiring).

Default lane rides the session serve fixtures from conftest (the ONE
warmed moe engine and the shared two-arm ``moe_ab`` closed loop in
virtual time) — zero new engine warmups; the extra closed loops below
are VirtualClock replays on the warmed engine, the same budget shape as
test_requests_obs.

The load-bearing pins:

- **ledger honesty**: every ``kv_pool`` snapshot obeys written <=
  reserved, the page-second integrals are monotone, and the
  per-request footprint reproduces ceil(length / page_size) exactly;
- **cause attribution**: a batch-bound burst charges ``batch_full``,
  a starved pool charges ``pool_starved``, and the split never exceeds
  the measured queue_ms;
- **back-compat**: pre-round-22 streams (no ``kv_pool`` records, no
  footprint fields) flow through fold/diff/regress absent-and-labeled,
  never KeyError — mirroring the r20 ``attribution_of`` seam;
- **bounded overhead**: the per-step ledger bookkeeping costs well
  under the round-17 1%-of-step recorder guard.
"""

from __future__ import annotations

import json
import os
import time

import pytest

from tpu_hc_bench import flags
from tpu_hc_bench.obs import fleet as fleet_mod
from tpu_hc_bench.obs import kv
from tpu_hc_bench.obs import metrics as obs_metrics
from tpu_hc_bench.obs import regress
from tpu_hc_bench.obs import timeline as timeline_mod
from tpu_hc_bench.serve import engine as engine_mod
from tpu_hc_bench.serve import slo

from conftest import SERVE_VCOSTS


def _records_of(mdir: str) -> list[dict]:
    return [json.loads(l) for l in open(os.path.join(mdir,
                                                     "metrics.jsonl"))]


def _burst_run(moe_engine, batching="continuous", num_pages=None):
    """One VirtualClock replay on the warmed session engine with every
    request arriving at once (admission must queue), records captured
    in memory; optionally with the pool pinned smaller for the run."""
    from tpu_hc_bench.serve import arrivals

    cfg = flags.BenchmarkConfig(
        model="moe_tiny", workload="serve", arrival_rate=10000.0,
        num_requests=8, max_prompt_len=8, max_output_len=4,
        max_in_flight=2, kv_page_size=4, seed=0).resolve()
    reqs = arrivals.build_requests(cfg, moe_engine.spec.vocab_size)
    events = []
    writer = obs_metrics.MetricsWriter(None)
    writer.event = lambda kind, **f: events.append({"kind": kind, **f})
    saved = moe_engine.num_pages
    try:
        if num_pages is not None:
            moe_engine.num_pages = num_pages
        summary = moe_engine.run(
            reqs, batching=batching, writer=writer,
            clock=engine_mod.VirtualClock(SERVE_VCOSTS))
    finally:
        moe_engine.num_pages = saved
    return summary, events


# --- the engine-side ledger -------------------------------------------


def test_kv_pool_records_on_stream(moe_ab):
    for arm in ("static", "continuous"):
        pools = [r for r in _records_of(moe_ab[arm]["mdir"])
                 if r.get("kind") == "kv_pool"]
        assert pools, arm
        prev_rs = prev_ws = 0.0
        for p in pools:
            # written pages are a subset of reserved pages, always
            assert 0 <= p["pages_written"] <= p["pages_reserved"]
            assert p["free_pages"] >= 0
            # cumulative page-second integrals are monotone
            assert p["reserved_page_s"] >= prev_rs
            assert p["written_page_s"] >= prev_ws
            assert p["written_page_s"] <= p["reserved_page_s"] + 1e-9
            prev_rs, prev_ws = p["reserved_page_s"], p["written_page_s"]
        # the terminal snapshot: everything retired, nothing leaked
        assert pools[-1]["pages_reserved"] == 0
        assert pools[-1]["pages_written"] == 0


def test_request_footprints_reproduce_page_math(moe_ab, serve_cfg):
    page = serve_cfg.kv_page_size
    for arm in ("static", "continuous"):
        reqs = [r for r in _records_of(moe_ab[arm]["mdir"])
                if r.get("kind") == "request"]
        assert reqs
        for r in reqs:
            fp = kv.footprint_of(r)
            assert fp is not None, r
            # worst-case reservation: every request reserves the full
            # table width regardless of its actual lengths
            assert fp["pages_reserved"] == 3
            # tokens that ever landed in the pool: the prompt plus
            # every generated token except the last (sampled and
            # returned, never written back)
            want = -(-(r["prompt_len"] + r["output_len"] - 1) // page)
            assert fp["pages_final"] == want, r
            # peak == final until mid-flight release exists
            assert fp["pages_peak_used"] == fp["pages_final"]
            assert 1 <= fp["pages_final"] <= fp["pages_reserved"]


def test_engine_summary_carries_kv_ledger(moe_ab):
    for arm in ("static", "continuous"):
        s = moe_ab[arm]["summary"]
        kvf = s["kv_pool"]
        assert kvf is not None
        assert 0.0 < kvf["util"] <= 1.0
        assert s["kv_pool_util"] == kvf["util"]
        # the trace's outputs run short of max: the gap is real
        assert kvf["req_gap_frac"] > 0.0
        assert s["kv_req_gap_frac"] == kvf["req_gap_frac"]
        assert kvf["req_n"] == s["completed"]
        assert kvf["pages_peak"] <= s["kv_pages"] - 1
        # satellite: the pool geometry is measured off the real arrays
        assert s["kv_pool_bytes"] > 0
        assert s["kv_layers"] > 0
        assert s["kv_scale_bytes"] == 0      # quant=off arm


def test_offline_fold_matches_engine_summary(moe_ab):
    s = moe_ab["continuous"]["summary"]
    fold = slo.fold_serve_records(_records_of(moe_ab["continuous"]["mdir"]))
    # the stream's terminal snapshot rounds to 6dp; the folds agree
    assert fold["kv_pool"]["util"] == pytest.approx(
        s["kv_pool"]["util"], abs=1e-3)
    assert fold["kv_pool"]["req_gap_frac"] == s["kv_pool"]["req_gap_frac"]
    assert fold["kv_pool_util"] == fold["kv_pool"]["util"]


def test_allocator_counts_peak_and_recycling():
    a = engine_mod.PageAllocator(7)
    p1 = a.alloc(3)
    assert a.pages_peak == 3 and a.recycled == 0
    a.free(p1)
    p2 = a.alloc(3)
    # LIFO free list: the same physical pages come back — recycled
    assert a.recycled == 3 and a.pages_peak == 3
    p3 = a.alloc(3)
    assert a.pages_peak == 6 and a.recycled == 3
    a.free(p2)
    a.free(p3)
    assert a.used_pages == 0


# --- the queue-wait cause split ---------------------------------------


def test_burst_charges_batch_full(moe_engine):
    """Everything arrives at once with cap=2: the queue blocks on the
    full batch (precedence: freeing pool pages would not open a slot),
    and the split never exceeds the measured queue_ms."""
    summary, events = _burst_run(moe_engine, batching="continuous")
    reqs = [e for e in events if e["kind"] == "request"]
    assert any(r["queue_batch_full_ms"] > 0 for r in reqs)
    assert all(r["queue_pool_starved_ms"] == 0.0 for r in reqs)
    for r in reqs:
        assert (r["queue_pool_starved_ms"] + r["queue_batch_full_ms"]
                <= r["queue_ms"] + 1e-3), r
    wc = summary["kv_pool"]["wait_causes"]
    assert wc["has_causes"]
    assert wc["tail_frac"]["batch_full"] >= 0.0


def test_starved_pool_charges_pool_starved(moe_engine):
    """With the pool pinned to ONE request's worst case, cap=2 never
    binds — the queue blocks on pages, and the tail names the pool."""
    table_width = moe_engine.table_width
    summary, events = _burst_run(
        moe_engine, batching="continuous", num_pages=1 + table_width)
    reqs = [e for e in events if e["kind"] == "request"]
    assert any(r["queue_pool_starved_ms"] > 0 for r in reqs)
    wc = summary["kv_pool"]["wait_causes"]
    assert wc["tail_ms"]["pool_starved"] > 0.0
    # at most one in flight: the batch never fills
    assert all(r["queue_batch_full_ms"] == 0.0 for r in reqs)


def test_static_arm_charges_batch_policy(moe_engine):
    """Static's run-to-completion policy is the binding resource even
    when the pool is also full — scale-out, not pool growth, is the
    remedy the attribution must name."""
    _, events = _burst_run(moe_engine, batching="static")
    reqs = [e for e in events if e["kind"] == "request"]
    assert any(r["queue_batch_full_ms"] > 0 for r in reqs)
    assert all(r["queue_pool_starved_ms"] == 0.0 for r in reqs)


def test_fold_wait_causes_tail_selection():
    recs = [{"e2e_ms": float(10 * (i + 1)), "queue_ms": float(i),
             "prefill_ms": 1.0, "decode_active_ms": 2.0,
             "decode_stall_ms": 0.5, "retire_ms": 0.0,
             "queue_pool_starved_ms": float(i) * 0.25,
             "queue_batch_full_ms": float(i) * 0.75}
            for i in range(20)]
    wc = kv.fold_wait_causes(recs)
    assert wc["n"] == 20 and wc["tail_n"] == 2
    # the slowest decile's queue wait splits 25/75 by construction
    assert wc["tail_frac"]["pool_starved"] == pytest.approx(0.25, abs=0.01)
    assert wc["tail_frac"]["batch_full"] == pytest.approx(0.75, abs=0.01)
    assert wc["has_causes"]
    assert kv.fold_wait_causes([]) is None


# --- back-compat: pre-round-22 streams --------------------------------


def test_pre_r22_stream_folds_absent_not_error(moe_ab):
    recs = _records_of(moe_ab["continuous"]["mdir"])
    old = []
    for r in recs:
        if r.get("kind") == "kv_pool":
            continue            # pre-r22: the record kind doesn't exist
        old.append({k: v for k, v in r.items()
                    if k not in ("pages_reserved", "pages_peak_used",
                                 "pages_final", "queue_pool_starved_ms",
                                 "queue_batch_full_ms", "kv_pool",
                                 "kv_pool_util", "kv_req_gap_frac",
                                 "kv_pool_bytes", "kv_scale_bytes",
                                 "kv_layers")})
    assert kv.fold_kv(old) is None
    fold = slo.fold_serve_records(old)
    assert fold is not None and "kv_pool" not in fold
    # rendering an old fold adds no kv lines and raises nothing
    assert all("kv_pool_util" not in ln for ln in slo.slo_lines(fold))
    # normalizers: absent fields read as absent / zero
    old_reqs = [r for r in old if r.get("kind") == "request"]
    assert old_reqs and all(kv.footprint_of(r) is None for r in old_reqs)
    assert not kv.has_footprints(old_reqs)
    assert kv.wait_cause_of({"queue_ms": 5.0}) == {
        "pool_starved": 0.0, "batch_full": 0.0}


def test_diff_labels_pre_r22_side(moe_ab):
    recs = _records_of(moe_ab["continuous"]["mdir"])
    old = [{k: v for k, v in r.items()
            if k not in ("pages_reserved", "pages_peak_used",
                         "pages_final", "kv_pool", "kv_pool_util",
                         "kv_req_gap_frac")}
           for r in recs if r.get("kind") != "kv_pool"]
    fold_old = slo.fold_serve_records(old)
    fold_new = slo.fold_serve_records(recs)
    lines = slo.serve_diff_lines(fold_old, fold_new)
    text = "\n".join(lines)
    assert "kv_pool_util" in text
    assert "note: run a predates the KV-pool ledger" in text
    # both sides pre-r22: no kv section at all
    assert kv.kv_diff_lines(fold_old, fold_old) == []
    assert kv.kv_diff_lines(None, None) == []


# --- summarize / diff / regress / timeline surfaces -------------------


def test_summarize_renders_kv_headline(moe_ab):
    text = "\n".join(obs_metrics.summarize_run(
        moe_ab["continuous"]["mdir"]))
    assert "kv_pool_util" in text
    assert "reservation honesty" in text and "gap" in text
    assert "kv pool geometry" in text and "MiB" in text
    assert "queue_wait cause" in text


def test_diff_renders_kv_delta_rows(moe_ab):
    lines = obs_metrics.diff_runs(moe_ab["static"]["mdir"],
                                  moe_ab["continuous"]["mdir"])
    text = "\n".join(lines)
    assert "kv pool" in text
    assert "kv_pool_util" in text and "pp" in text


def test_regress_gates_on_util_drop():
    """An injected utilization drop flags direction-aware (down =
    regression); pre-r22 history (no field) skips, never KeyError."""
    base = {"metric": "moe_tiny_serve_tokens_per_s", "value": 100.0,
            "unit": "tokens/sec",
            "extra": {"batching": "continuous", "arrival_rate": 16.0,
                      "p99_ms": 100.0, "goodput": 0.5,
                      "tokens_per_s": 100.0,
                      "kv_pool_util": 0.50}}
    hist = [json.loads(json.dumps(base)) for _ in range(4)]
    fresh = json.loads(json.dumps(base))
    fresh["extra"]["kv_pool_util"] = 0.20       # admission got wasteful
    verdict = regress.regress_check(fresh, hist)
    assert any(r["metric"] == "kv pool util"
               for r in verdict["regressions"])
    # a RISE in utilization is an improvement, never a regression
    better = json.loads(json.dumps(base))
    better["extra"]["kv_pool_util"] = 0.90
    assert not any(r["metric"] == "kv pool util" for r in
                   regress.regress_check(better, hist)["regressions"])
    # sub-floor jitter on the fraction never flags (5pp absolute floor)
    jitter = json.loads(json.dumps(base))
    jitter["extra"]["kv_pool_util"] = 0.47
    assert not any(r["metric"] == "kv pool util" for r in
                   regress.regress_check(jitter, hist)["regressions"])
    # pre-r22 history: the field is simply absent, checks skip
    old_hist = []
    for h in hist:
        h = json.loads(json.dumps(h))
        del h["extra"]["kv_pool_util"]
        old_hist.append(h)
    verdict = regress.regress_check(fresh, old_hist)
    assert not any(r["metric"] == "kv pool util"
                   for r in verdict["regressions"])
    assert verdict["history_n"] == 4


def test_timeline_exports_kv_counter_track(moe_ab):
    trace = timeline_mod.merge_chrome_trace(moe_ab["continuous"]["mdir"])
    counters = [e for e in trace["traceEvents"]
                if e.get("pid") == kv.KV_COUNTER_PID
                and e.get("ph") == "C"]
    assert counters
    assert trace["metadata"]["kv_counter_samples"] == len(counters)
    for e in counters:
        assert e["name"] == "kv pool pages"
        assert set(e["args"]) == {"written", "reserved_unwritten", "free"}
        assert "ts" in e and "ts_unix" not in e   # rebased like lanes
    # the track is named beside the request lanes
    assert any(e.get("ph") == "M" and e.get("pid") == kv.KV_COUNTER_PID
               for e in trace["traceEvents"])


def test_kv_counter_skips_unanchored_streams():
    # no serve_clock record -> no counter track, never a misplaced one
    assert kv.kv_counter_events(
        [{"kind": "kv_pool", "t": 1.0, "pages_reserved": 3,
          "pages_written": 2, "free_pages": 3}]) == []
    # a serve_clock but no kv_pool records (pre-r22) -> empty
    assert kv.kv_counter_events(
        [{"kind": "serve_clock", "t_unix": 100.0, "t": 0.0}]) == []


# --- heartbeats + watch ------------------------------------------------


def test_heartbeats_carry_kv_peak_pages(tmp_path, moe_engine,
                                        moe_requests):
    """run_serve wires a FleetWriter beside the metrics stream: the
    heartbeat carries kv_peak_pages and the reader accessor returns it
    (writer + reader in one PR, per the r15 mem_peak_bytes lesson)."""
    from tpu_hc_bench.serve import cli as serve_cli

    mdir = str(tmp_path / "hb")
    writer = obs_metrics.MetricsWriter(
        mdir, obs_metrics.run_manifest(
            cfg=moe_engine.cfg, extra={"workload": "serve"}))
    summary = serve_cli.run_serve(
        moe_engine, moe_requests, writer, batching="continuous",
        clock=engine_mod.VirtualClock(SERVE_VCOSTS))
    beats = fleet_mod.read_heartbeats(mdir)
    assert beats, os.listdir(mdir)
    last = beats[0][-1]
    peak = fleet_mod.heartbeat_kv_peak(last)
    # the final beat carries the run's pool high-water, exactly as the
    # summary ledger reports it
    assert peak == summary["kv_pool"]["pages_peak"]
    assert moe_engine.table_width <= peak <= moe_engine.num_pages - 1
    assert last.get("phase") == "serve"
    # train-lane / pre-r22 beats read absent, never KeyError
    assert fleet_mod.heartbeat_kv_peak({"kind": "heartbeat"}) is None
    # the fleet view renders the per-host pressure column
    from tpu_hc_bench.obs import watch as watch_mod

    text = "\n".join(watch_mod.render(mdir, {}, _records_of(mdir)))
    assert "kv peak pages" in text


def test_watch_renders_live_pool_occupancy():
    recs = [{"kind": "kv_pool", "t": 1.0, "pages_reserved": 6,
             "pages_written": 4, "free_pages": 0, "pages_peak": 6,
             "pages_recycled": 9}]
    text = "\n".join(slo.watch_lines(recs))
    assert "kv pool:" in text
    assert "6 reserved / 4 written / 0 free" in text


# --- overhead guard + registry ----------------------------------------


def test_ledger_stamp_overhead_bounded():
    """The per-step ledger bookkeeping (one token() + one charge())
    must cost well under the round-17 1%-of-step guard — it runs every
    decode step on the hot path."""
    step_s = SERVE_VCOSTS["decode"]
    ledger = engine_mod.KVLedger(4)
    ledger.admit(3, 5)
    n = 2000
    t0 = time.perf_counter()
    for i in range(n):
        ledger.token(5 + (i % 7))
        ledger.charge(step_s)
    per_call = (time.perf_counter() - t0) / n
    assert per_call < 0.01 * step_s, \
        f"KVLedger step cost {per_call * 1e6:.1f}us vs 1% of " \
        f"{step_s * 1e3:.0f}ms step"


def test_known_spans_cover_kv_instants():
    # the engine's edge-triggered cause instants are literal names the
    # span-name-registry lint checks against KNOWN_SPANS
    assert {"pool_starved", "batch_full"} <= timeline_mod.KNOWN_SPANS


# --- round 25: growth/sharing counters, back-compat + regress ---------


def test_pre_r25_stream_folds_growth_absent_not_error(moe_ab):
    """Records predating round 25 carry neither the growth counters on
    kv_pool nor the pages_grown/prefix_pages_shared footprint fields:
    the fold omits the section fields entirely (no fake zeros) and the
    footprint normalizer reads 0, labeled — the same seam as r20/r22."""
    r25_keys = ("pages_grown", "prefix_pages_shared", "pages_cow",
                "prefix_hits", "prefix_lookups", "prefix_hit_frac")
    old = [{k: v for k, v in r.items() if k not in r25_keys}
           for r in _records_of(moe_ab["continuous"]["mdir"])]
    fold = kv.fold_kv(old)
    assert fold is not None and fold["util"] is not None
    assert "pages_grown" not in fold
    assert "prefix_hit_frac" not in fold and "prefix_lookups" not in fold
    for r in old:
        if r.get("kind") == "request":
            fp = kv.footprint_of(r)
            assert fp["pages_grown"] == 0
            assert fp["prefix_pages_shared"] == 0
    flat = kv.flatten_kv(fold)
    assert "prefix_hit_frac" not in flat
    assert "pages_grown_total" not in flat
    # rendering an old fold raises nothing and adds no prefix line
    assert all("prefix cache" not in ln for ln in kv.kv_lines(
        {"kv_pool": fold}))


def test_r25_stream_carries_growth_counters(moe_ab):
    """The post-r25 engine always stamps the counters (0 on a cache-off
    run) so the offline fold and the engine's own summary agree."""
    recs = _records_of(moe_ab["continuous"]["mdir"])
    pools = [r for r in recs if r.get("kind") == "kv_pool"]
    assert all("pages_grown" in p and "prefix_pages_shared" in p
               for p in pools)
    fold = kv.fold_kv(recs)
    assert fold["pages_grown"] == 0 and fold["cow_copies"] == 0
    # cache off: no lookups -> structurally absent hit rate, never 0.0
    assert fold["prefix_lookups"] == 0
    assert fold["prefix_hit_frac"] is None
    reqs = [r for r in recs if r.get("kind") == "request"]
    assert all(kv.footprint_of(r)["pages_grown"] == 0 for r in reqs)


def test_regress_gates_on_prefix_hit_drop():
    """A prefix-cache hit-rate drop flags direction-aware (down =
    regression, the pool re-pays prefill writes it had been sharing);
    cache-off and pre-r25 records lack the field and skip structurally."""
    base = {"metric": "moe_tiny_serve_tokens_per_s", "value": 100.0,
            "unit": "tokens/sec",
            "extra": {"batching": "continuous", "arrival_rate": 16.0,
                      "p99_ms": 100.0, "goodput": 0.5,
                      "tokens_per_s": 100.0,
                      "kv_reserve": "lazy", "prefix_cache": "on",
                      "prefix_hit_frac": 0.40}}
    hist = [json.loads(json.dumps(base)) for _ in range(4)]
    fresh = json.loads(json.dumps(base))
    fresh["extra"]["prefix_hit_frac"] = 0.05     # sharing collapsed
    verdict = regress.regress_check(fresh, hist)
    assert any(r["metric"] == "prefix hit frac"
               for r in verdict["regressions"])
    # a RISE in hit rate is an improvement, never a regression
    better = json.loads(json.dumps(base))
    better["extra"]["prefix_hit_frac"] = 0.90
    assert not any(r["metric"] == "prefix hit frac" for r in
                   regress.regress_check(better, hist)["regressions"])
    # sub-floor jitter never flags (5pp absolute floor)
    jitter = json.loads(json.dumps(base))
    jitter["extra"]["prefix_hit_frac"] = 0.37
    assert not any(r["metric"] == "prefix hit frac" for r in
                   regress.regress_check(jitter, hist)["regressions"])
    # history with the cache on but no hit field (truncated runs):
    # the check skips, the rest of the gate still runs
    old_hist = []
    for h in hist:
        h = json.loads(json.dumps(h))
        del h["extra"]["prefix_hit_frac"]
        old_hist.append(h)
    verdict = regress.regress_check(fresh, old_hist)
    assert verdict["history_n"] == 4
    assert not any(r["metric"] == "prefix hit frac"
                   for r in verdict["regressions"])


def test_regress_fingerprints_reservation_arms():
    """A lazy+prefix run must never gate against worst-case history —
    the arms are config identity; pre-r25 records (no fields at all)
    normalize to worst/off and keep comparing against fresh
    default-arm runs instead of being orphaned."""
    base = {"metric": "moe_tiny_serve_tokens_per_s", "value": 100.0,
            "unit": "tokens/sec",
            "extra": {"batching": "continuous", "arrival_rate": 16.0,
                      "tokens_per_s": 100.0}}
    pre_r25 = [json.loads(json.dumps(base)) for _ in range(4)]
    shared = json.loads(json.dumps(base))
    shared["extra"].update(kv_reserve="lazy", prefix_cache="on")
    shared["extra"]["tokens_per_s"] = 10.0       # huge drop, wrong arm
    verdict = regress.regress_check(shared, pre_r25)
    assert verdict["history_n"] == 0             # never cross-gated
    # a fresh default-arm run (explicit worst/off) still compares
    # against the same pre-r25 history via the fingerprint defaults
    default_arm = json.loads(json.dumps(base))
    default_arm["extra"].update(kv_reserve="worst", prefix_cache="off")
    default_arm["extra"]["tokens_per_s"] = 10.0
    verdict = regress.regress_check(default_arm, pre_r25)
    assert verdict["history_n"] == 4
    assert any(r["metric"] == "tokens/s"
               for r in verdict["regressions"])
