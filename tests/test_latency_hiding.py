"""Round 10: the latency-hiding layer — async checkpointing, persistent
compile cache, non-blocking sync windows, prefetch depth.

Default-lane cost discipline: the driver-level assertions share TWO
tiny module-scoped runs (async and sync-baseline, same model so the
in-process jit cache absorbs the second compile); everything else is
unit-level.  The crash-mid-async-save proof runs the writer in a
subprocess and SIGKILLs it between snapshot and commit — the async
extension of the round-8 kill/resume contract.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

import numpy as np
import pytest

from tpu_hc_bench import flags
from tpu_hc_bench.obs import metrics as obs_metrics
from tpu_hc_bench.train import driver
from tpu_hc_bench.utils import checkpoint as ckpt

REPO = Path(__file__).resolve().parent.parent


def tiny_cfg(**kw):
    base = dict(
        batch_size=2, num_warmup_batches=1, num_batches=6, display_every=2,
        model="trivial", num_classes=10, init_learning_rate=0.05,
    )
    base.update(kw)
    return flags.BenchmarkConfig(**base).resolve()


def _tiny_state():
    from tpu_hc_bench.data.synthetic import SyntheticImages
    from tpu_hc_bench.models import create_model
    from tpu_hc_bench.train import step as step_mod

    cfg = tiny_cfg()
    model, spec = create_model("trivial", num_classes=10)
    batch = SyntheticImages(2, spec.input_shape, num_classes=10,
                            seed=0).batch()
    return step_mod.make_train_state(model, cfg, batch)


def read_metrics(metrics_dir):
    with open(os.path.join(metrics_dir, "metrics.jsonl")) as f:
        return [json.loads(line) for line in f if line.strip()]


# ---------------------------------------------------------------------
# 1. AsyncCheckpointWriter: commit protocol, bounded in-flight, barrier
#    error propagation


def test_async_writer_roundtrip_and_bounded_inflight(mesh8, tmp_path):
    import jax
    import jax.numpy as jnp

    state = _tiny_state()
    w = ckpt.AsyncCheckpointWriter(tmp_path)
    step1 = w.submit(state)
    # in-flight <= 1: the next submit barriers on the previous write,
    # so by the time it returns, step1 is committed on disk
    state2 = state.replace(
        step=jnp.asarray(7, jnp.int32),
        params=jax.tree.map(lambda x: x + 1.0, state.params))
    step2 = w.submit(state2)
    assert step1 in ckpt.complete_steps(tmp_path)
    w.wait()
    assert ckpt.complete_steps(tmp_path) == [step1, step2]
    assert [c["step"] for c in w.commits] == [step1, step2]
    # the committed bytes match the snapshotted state bitwise
    restored = ckpt.restore(state, tmp_path, step=step2)
    assert ckpt.fingerprint(restored.params) == ckpt.fingerprint(
        state2.params)


def test_async_writer_error_surfaces_at_barrier(tmp_path, monkeypatch):
    """A persistent write failure exhausts the retry budget (same
    retry_io contract as the sync path) and re-raises at the barrier;
    a transient one is absorbed and the save lands."""
    from tpu_hc_bench.resilience import retry as retry_mod

    state = _tiny_state()
    w = ckpt.AsyncCheckpointWriter(tmp_path)
    boom = [1] * retry_mod.DEFAULT_ATTEMPTS    # every attempt fails

    def failing(payload, directory, step, topology=None):
        if boom:
            boom.pop()
            raise OSError("disk full")
        return real(payload, directory, step, topology=topology)

    real = ckpt.write_host_payload
    monkeypatch.setattr(ckpt, "write_host_payload", failing)
    w.submit(state)
    with pytest.raises(OSError, match="disk full"):
        w.wait()
    # the error cleared at the barrier: the writer is usable again
    # (and a transient single failure would have been retried away)
    w.submit(state)
    w.wait()
    assert ckpt.complete_steps(tmp_path)


def test_snapshot_to_host_is_host_arrays(mesh8):
    state = _tiny_state()
    step, payload = ckpt.snapshot_to_host(state)
    assert step == int(np.asarray(payload["step"]))
    for leaf in __import__("jax").tree.leaves(payload["params"]):
        assert isinstance(leaf, np.ndarray)


# ---------------------------------------------------------------------
# 2. the driver's async save path (shared runs: async + sync baseline)


@pytest.fixture(scope="module")
def async_run(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("latency_async")
    mdir, ckdir = str(tmp / "m"), str(tmp / "ck")
    out: list[str] = []
    res = driver.run_benchmark(
        tiny_cfg(train_dir=ckdir, metrics_dir=mdir, save_model_steps=2),
        print_fn=out.append)
    return {"out": out, "mdir": mdir, "ckdir": ckdir, "result": res}


@pytest.fixture(scope="module")
def sync_run(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("latency_sync")
    mdir, ckdir = str(tmp / "m"), str(tmp / "ck")
    out: list[str] = []
    res = driver.run_benchmark(
        tiny_cfg(train_dir=ckdir, metrics_dir=mdir, save_model_steps=2,
                 async_checkpoint=False),
        print_fn=out.append)
    return {"out": out, "mdir": mdir, "ckdir": ckdir, "result": res}


def test_async_run_overlaps_saves(async_run):
    text = "\n".join(async_run["out"])
    assert "checkpointing: async" in text
    assert "checkpoint snapshot: step" in text     # the blocking slice
    assert "(async write" in text                  # the overlapped write
    recs = read_metrics(async_run["mdir"])
    phases = [r.get("phase") for r in recs if r.get("kind") == "phase"]
    assert "checkpoint_async" in phases
    assert "checkpoint" not in phases              # nothing saved sync
    # every save landed and was reported through the main thread
    commits = [r for r in recs if r["kind"] == "checkpoint_commit"]
    # saves at timed steps 2, 4 and the final 6 -> counters 3, 5, 7
    assert [c["step"] for c in commits] == [3, 5, 7]
    assert ckpt.latest_step(async_run["ckdir"]) == 7
    # the ledger separates blocking snapshot cost from overlapped writes
    assert "checkpoint_async" in async_run["result"].goodput_phases
    assert "checkpoint" not in async_run["result"].goodput_phases
    # ... and summarize surfaces the overlapped writes from the artifacts
    text = "\n".join(obs_metrics.summarize_run(async_run["mdir"]))
    assert "async checkpoints: 3 landed" in text


def test_sync_baseline_still_blocks(sync_run):
    text = "\n".join(sync_run["out"])
    assert "checkpointing: async" not in text
    assert "(async write" not in text
    recs = read_metrics(sync_run["mdir"])
    phases = [r.get("phase") for r in recs if r.get("kind") == "phase"]
    assert "checkpoint" in phases
    assert "checkpoint_async" not in phases
    assert not [r for r in recs if r["kind"] == "checkpoint_commit"]
    assert "checkpoint" in sync_run["result"].goodput_phases


def test_async_run_resumes(async_run):
    out: list[str] = []
    res = driver.run_benchmark(
        tiny_cfg(train_dir=async_run["ckdir"], num_batches=2),
        print_fn=out.append)
    assert any("restored checkpoint step 7" in l for l in out)
    assert np.isfinite(res.final_loss)


def test_async_vs_sync_fingerprint_identical(async_run, sync_run):
    """Same seed, same schedule: the async writer must persist
    bit-identical state to the synchronous baseline.  Step pinned to 7
    (the shared runs' final save) — the resume test appends later
    checkpoints to the async dir."""
    state = _tiny_state()
    a = ckpt.restore(state, async_run["ckdir"], step=7)
    s = ckpt.restore(state, sync_run["ckdir"], step=7)
    assert ckpt.fingerprint(a.params) == ckpt.fingerprint(s.params)


# ---------------------------------------------------------------------
# 3. crash-mid-async-save: SIGKILL between snapshot and commit


_CRASH_PROG = """
import os, signal, sys, threading, time
import tpu_hc_bench
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
sys.path.insert(0, {test_dir!r})
from test_latency_hiding import _tiny_state
from tpu_hc_bench.utils import checkpoint as ckpt

d = {ckdir!r}
state = _tiny_state().replace(step=jnp.asarray(1, jnp.int32))
ckpt.save(state, d)                        # the last COMPLETE step
print("fp_complete:", ckpt.fingerprint(state.params), flush=True)

in_commit = threading.Event()
def stuck_commit(*a, **k):
    in_commit.set()                        # tmp fully written, sentinel not
    time.sleep(300)
ckpt._commit_step_dir = stuck_commit

w = ckpt.AsyncCheckpointWriter(d)
state2 = state.replace(step=jnp.asarray(2, jnp.int32),
                       params=jax.tree.map(lambda x: x + 1.0, state.params))
w.submit(state2)
assert in_commit.wait(120), "writer never reached the commit"
os.kill(os.getpid(), signal.SIGKILL)       # die between snapshot and commit
"""


@pytest.mark.slow
def test_sigkill_mid_async_save_falls_back_to_complete_step(
        mesh8, tmp_path):
    """The async extension of the round-8 kill/resume proof: a writer
    SIGKILLed after the Orbax tmp write but before the sentinel commit
    must leave discovery on the newest COMPLETE step, and the restored
    params must be bitwise-identical to that step's (fingerprint).

    Slow lane, like the round-8 kill/resume e2e it extends: the
    subprocess pays a fresh jax import + state compile, and the
    commit-protocol fallback it proves is also pinned (in-process,
    cheaply) by test_latest_step_ignores_partial_dirs — tier-1 lands
    ~805s against the 870s budget, so the fresh compile can't ride the
    default lane."""
    ckdir = str(tmp_path / "ck")
    prog = _CRASH_PROG.format(test_dir=str(REPO / "tests"), ckdir=ckdir)
    proc = subprocess.run(
        [sys.executable, "-c", prog], cwd=REPO,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        capture_output=True, text=True, timeout=240)
    assert proc.returncode == -signal.SIGKILL, proc.stderr[-2000:]
    fp_lines = [l for l in proc.stdout.splitlines()
                if l.startswith("fp_complete:")]
    assert fp_lines, proc.stdout
    fp_complete = fp_lines[0].split()[-1]

    # the crashed save left an uncommitted .tmp; discovery ignores it
    assert ckpt.complete_steps(ckdir) == [1]
    assert list(Path(ckdir).glob("step_*.tmp"))
    with pytest.raises(FileNotFoundError, match="incomplete|no complete"):
        ckpt.restore(_tiny_state(), ckdir, step=2)
    # restore falls back to the newest complete step, bit-identical
    restored = ckpt.restore(_tiny_state(), ckdir)
    assert int(np.asarray(restored.step)) == 1
    assert ckpt.fingerprint(restored.params) == fp_complete
    # retention GC reaps the crashed partial write
    ckpt.gc_checkpoints(ckdir, keep=1)
    assert not list(Path(ckdir).glob("step_*.tmp"))


# ---------------------------------------------------------------------
# 4. persistent compile cache resolution + accounting


def test_compile_cache_off_disables(tmp_path):
    cfg = tiny_cfg(compile_cache="off", train_dir=str(tmp_path))
    assert driver._resolve_compile_cache(cfg, lambda s: None) is None


def test_compile_cache_reuses_preconfigured_dir(tmp_path):
    import jax

    try:
        old = jax.config.jax_compilation_cache_dir
    except Exception:
        old = None
    pre = str(tmp_path / "pre")
    jax.config.update("jax_compilation_cache_dir", pre)
    try:
        # auto (unset flag): an already-configured cache wins, untouched
        assert driver._resolve_compile_cache(
            tiny_cfg(), lambda s: None) == pre
        # an explicit dir overrides it
        explicit = str(tmp_path / "mine")
        out: list[str] = []
        assert driver._resolve_compile_cache(
            tiny_cfg(compile_cache=explicit), out.append) == explicit
        assert jax.config.jax_compilation_cache_dir == explicit
        assert os.path.isdir(explicit)
    finally:
        jax.config.update("jax_compilation_cache_dir", old)


def test_compile_cache_auto_without_train_dir_is_off(tmp_path):
    import jax

    try:
        preconfigured = jax.config.jax_compilation_cache_dir
    except Exception:
        preconfigured = None
    if preconfigured:
        pytest.skip("harness configured a global compile cache")
    assert driver._resolve_compile_cache(tiny_cfg(), lambda s: None) is None


def test_cache_entry_count(tmp_path):
    (tmp_path / "sub").mkdir()
    (tmp_path / "a").write_text("x")
    (tmp_path / "sub" / "b").write_text("y")
    assert driver._cache_entry_count(str(tmp_path)) == 2


def test_update_manifest_merges(tmp_path):
    w = obs_metrics.MetricsWriter(str(tmp_path), {"schema": 1, "model": "t"},
                                  primary=True)
    w.update_manifest({"compile_cache": {"warm": True}})
    w.close()
    man = json.loads((tmp_path / "manifest.json").read_text())
    assert man["model"] == "t"
    assert man["compile_cache"] == {"warm": True}


def test_flags_validate_latency_hiding():
    with pytest.raises(ValueError, match="prefetch_depth"):
        tiny_cfg(prefetch_depth=0)
    cfg = tiny_cfg(prefetch_depth=4)
    assert any("prefetch_depth=4" in l for l in cfg.summary_lines())


def test_prefetch_honors_depth():
    pulled: list[int] = []

    def gen():
        for i in range(6):
            pulled.append(i)
            yield i

    it = driver._prefetch(gen(), 3)
    assert next(it) == 0
    assert pulled == [0, 1, 2]      # 3 batches in flight at first yield
    assert list(it) == [1, 2, 3, 4, 5]


# ---------------------------------------------------------------------
# 5. deferred guard fetch + diff's ledger-phase rows


def test_guard_tracker_handles_are_stable_snapshots():
    import jax
    import jax.numpy as jnp

    from tpu_hc_bench.resilience import guards

    t = guards.GuardTracker()
    t.update(jnp.int32(1))
    h = t.handles()                 # snapshot refs at "window 1"
    t.update(jnp.int32(1))
    # the held refs still read window 1's values after later updates
    assert [int(v) for v in jax.device_get(list(h))] == [1, 1, 1]
    assert t.poll() == (2, 2, 2)


def _ledger_dir(tmp_path, name, phases):
    d = tmp_path / name
    d.mkdir()
    (d / "manifest.json").write_text('{"schema": 1}\n')
    recs = [{"kind": "phase", "phase": p, "t": t, "step": s}
            for p, t, s in phases]
    recs.append({"kind": "summary", "mfu": 0.1, "goodput": 0.5})
    (d / "metrics.jsonl").write_text(
        "".join(json.dumps(r) + "\n" for r in recs))
    return str(d)


def test_diff_renders_ledger_phase_rows(tmp_path):
    a = _ledger_dir(tmp_path, "a", [
        ("init", 0.0, None), ("compile", 1.0, None), ("step", 11.0, None),
        ("checkpoint", 15.0, 4), ("step", 17.0, 4), ("end", 20.0, 8)])
    b = _ledger_dir(tmp_path, "b", [
        ("init", 0.0, None), ("compile", 1.0, None), ("step", 2.5, None),
        ("checkpoint_async", 6.5, 4), ("step", 6.7, 4), ("end", 10.0, 8)])
    text = "\n".join(obs_metrics.diff_runs(a, b))
    assert "ledger phases (wall s)" in text
    assert "compile" in text and "-85.0%" in text    # 10s -> 1.5s
    assert "checkpoint_async" in text
