"""Llama family: RoPE/RMSNorm/GQA correctness + SP/flash composition."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from tpu_hc_bench.models import create_model
from tpu_hc_bench.models.llama import LlamaLM, apply_rope
from tpu_hc_bench.topology import SEQ_AXIS


def test_rope_preserves_norm_and_relative_phase():
    x = jax.random.normal(jax.random.PRNGKey(0), (1, 8, 2, 16))
    pos = jnp.arange(8)
    y = apply_rope(x, pos)
    # rotation preserves per-pair norms
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(x), axis=-1),
        np.linalg.norm(np.asarray(y), axis=-1), rtol=1e-5)
    # position 0 is the identity rotation
    np.testing.assert_allclose(np.asarray(y[:, 0]), np.asarray(x[:, 0]),
                               rtol=1e-6)
    # relative property: q.k after rope depends only on position delta
    q = jax.random.normal(jax.random.PRNGKey(1), (1, 1, 1, 16))
    k = jax.random.normal(jax.random.PRNGKey(2), (1, 1, 1, 16))
    def dot_at(pq, pk):
        qr = apply_rope(q, jnp.array([pq]))
        kr = apply_rope(k, jnp.array([pk]))
        return float(jnp.sum(qr * kr))
    assert dot_at(5, 3) == pytest.approx(dot_at(9, 7), rel=1e-4)
    assert dot_at(5, 3) != pytest.approx(dot_at(5, 1), rel=1e-3)


def test_llama_tiny_forward_and_param_shapes():
    model, spec = create_model("llama_tiny")
    tokens = jnp.zeros((2, 16), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens, train=False)["params"]
    attn = params["layer_0"]["attn"]
    # GQA: kv projections carry 2 heads vs 8 query heads
    assert attn["wq"]["kernel"].shape == (128, 8, 16)
    assert attn["wk"]["kernel"].shape == (128, 2, 16)
    assert attn["wv"]["kernel"].shape == (128, 2, 16)
    logits = model.apply({"params": params}, tokens, train=False)
    assert logits.shape == (2, 16, 1024)
    assert logits.dtype == jnp.float32


def test_llama_causality():
    """Changing a future token must not change past logits."""
    model, _ = create_model("llama_tiny")
    t1 = jnp.ones((1, 16), jnp.int32)
    t2 = t1.at[0, 10].set(7)
    params = model.init(jax.random.PRNGKey(0), t1, train=False)["params"]
    l1 = model.apply({"params": params}, t1, train=False)
    l2 = model.apply({"params": params}, t2, train=False)
    np.testing.assert_allclose(np.asarray(l1[0, :10]), np.asarray(l2[0, :10]),
                               atol=1e-5)
    assert float(jnp.abs(l1[0, 10:] - l2[0, 10:]).max()) > 1e-3


@pytest.mark.parametrize("impl", ["ring", "ulysses_flash"])
def test_llama_sp_matches_dense(devices, impl):
    """Whole-model SP (RoPE offsets + causal masking across shards) must
    reproduce the unsharded forward."""
    S = 32
    dense = LlamaLM(vocab_size=256, hidden=64, num_layers=2, heads=4,
                    num_kv_heads=2, ffn=128, max_len=S)
    sp = dense.clone(attention_impl=impl, seq_axis=SEQ_AXIS)
    tokens = jax.random.randint(jax.random.PRNGKey(0), (2, S), 0, 256)
    params = dense.init(jax.random.PRNGKey(1), tokens, train=False)["params"]
    ref = dense.apply({"params": params}, tokens, train=False)

    mesh = Mesh(np.array(devices[:2]), (SEQ_AXIS,))
    out = jax.jit(jax.shard_map(
        lambda p, t: sp.apply({"params": p}, t, train=False),
        mesh=mesh, in_specs=(P(), P(None, SEQ_AXIS)),
        out_specs=P(None, SEQ_AXIS), check_vma=False,
    ))(params, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_llama_scan_layers_parity():
    """--scan_layers (stacked [L, ...] params, one compiled body — the
    program-size lever built for llama_1b's remote-compile 500) must
    reproduce the unrolled forward: run the scanned model, slice its
    stacked trunk into layer_i trees, run the unrolled model on them."""
    scanned, _ = create_model("llama_tiny", scan_layers=True)
    unrolled, _ = create_model("llama_tiny")
    tokens = jax.random.randint(jax.random.PRNGKey(0), (2, 16), 0, 1024)
    params = scanned.init(
        jax.random.PRNGKey(1), tokens, train=False)["params"]
    stacked = params["layers"]
    assert jax.tree.leaves(stacked)[0].shape[0] == 4  # [L, ...] trunk
    out_s = scanned.apply({"params": params}, tokens, train=True)
    un = {k: v for k, v in params.items() if k != "layers"}
    for i in range(4):
        un[f"layer_{i}"] = jax.tree.map(lambda x, i=i: x[i], stacked)
    out_u = unrolled.apply({"params": un}, tokens, train=True)
    np.testing.assert_allclose(np.asarray(out_s), np.asarray(out_u),
                               rtol=2e-5, atol=2e-5)


def test_llama_scan_train_step(mesh8):
    """Scanned llama through the shared DP step builder (+ accumulation,
    the combination llama_1b needs): loss finite and decreasing."""
    from tpu_hc_bench import flags
    from tpu_hc_bench.data.synthetic import SyntheticTokens
    from tpu_hc_bench.models import ModelSpec
    from tpu_hc_bench.train import step as step_mod

    cfg = flags.BenchmarkConfig(model="llama_tiny", optimizer="adam",
                                init_learning_rate=1e-3, scan_layers=True,
                                gradient_accumulation_steps=2,
                                accum_dtype="bf16").resolve()
    model, _ = create_model("llama_tiny", scan_layers=True)
    spec = ModelSpec("llama_tiny", None, (16,), 1e6, is_text=True,
                     vocab_size=1024, causal_lm=True)
    batch = SyntheticTokens(16, 16, vocab_size=1024, causal_lm=True).batch()
    state = step_mod.make_train_state(model, cfg, batch)
    state = step_mod.replicate_state(state, mesh8)
    dev_batch = step_mod.shard_batch(batch, mesh8)
    step_fn = step_mod.build_train_step(mesh8, cfg, spec)
    rng = jax.random.PRNGKey(0)
    losses = []
    for _ in range(6):
        state, metrics = step_fn(state, dev_batch, rng)
        losses.append(float(jax.device_get(metrics["loss"])))
    assert losses[-1] < losses[0], losses


def test_llama_train_step(mesh8):
    """Full DP train step through the shared builder; loss decreases."""
    from tpu_hc_bench import flags
    from tpu_hc_bench.data.synthetic import SyntheticTokens
    from tpu_hc_bench.models import ModelSpec
    from tpu_hc_bench.train import step as step_mod

    cfg = flags.BenchmarkConfig(model="llama_tiny", optimizer="adam",
                                init_learning_rate=1e-3).resolve()
    model, _ = create_model("llama_tiny")
    spec = ModelSpec("llama_tiny", None, (16,), 1e6, is_text=True,
                     vocab_size=1024, causal_lm=True)
    batch = SyntheticTokens(16, 16, vocab_size=1024, causal_lm=True).batch()
    state = step_mod.make_train_state(model, cfg, batch)
    state = step_mod.replicate_state(state, mesh8)
    dev_batch = step_mod.shard_batch(batch, mesh8)
    step_fn = step_mod.build_train_step(mesh8, cfg, spec)
    rng = jax.random.PRNGKey(0)
    losses = []
    for _ in range(6):
        state, metrics = step_fn(state, dev_batch, rng)
        losses.append(float(jax.device_get(metrics["loss"])))
    assert losses[-1] < losses[0], losses
