"""Measured device memory (``obs.memory``): report, ledger, forensics.

Six sections, matching the round-15 acceptance contract:

1. Compile-time report: ``memory_analysis_of_compiled`` on a real CPU
   AOT compile, the analytic params/opt/batch table, and the >10%
   argument-byte disagreement tripwire (the MFU cross-check's twin).
2. Runtime ledger: phase attribution and high-water tracking with an
   injected sampler, the pure ``fold_memory_records`` over hand-built
   streams (including the pre-round-15 legacy record shape), rendering.
3. OOM/emergency forensics: error classification, live-buffer
   aggregation, and the best-effort ``memory_dump.json`` writer.
4. ``--hbm_budget``: spec parsing, auto resolution on a backend with no
   allocator stats, verdict lines, flag-time validation.
5. The tune feedback loop: measured HBM anchors beating the seeded
   guess, journal-row joining, ``hbm_source`` provenance in skips, the
   mid-search measured re-check, and ``tune show --journal`` rendering.
6. End-to-end against the SHARED session-scoped ``rewind_run`` driver
   fixture (conftest.py — no new default-lane driver run): memory
   records per sync window, the summary's peak/source fields, the
   unified heartbeat name, summarize/diff/watch rendering.  The
   emergency-save forensics subprocess proof is slow-marked.

Plus the ``memory-probe-in-hot-loop`` analysis lint fixtures.
"""

from __future__ import annotations

import io
import json
import os
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

from tpu_hc_bench import flags
from tpu_hc_bench.analysis import lints
from tpu_hc_bench.obs import fleet, goodput
from tpu_hc_bench.obs import memory as mem
from tpu_hc_bench.obs import metrics as obs_metrics
from tpu_hc_bench.obs import watch as watch_mod
from tpu_hc_bench.obs.__main__ import main as obs_main
from tpu_hc_bench.tune import prune, search, space
from tpu_hc_bench.tune.__main__ import main as tune_main

REPO = Path(__file__).resolve().parent.parent


# ---------------------------------------------------------------------
# 1. compile-time report


def test_memory_analysis_of_compiled_cpu():
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x: (x @ x).sum())
    compiled = f.lower(
        jax.ShapeDtypeStruct((64, 64), jnp.float32)).compile()
    ma = mem.memory_analysis_of_compiled(compiled)
    assert ma is not None
    assert ma["argument_bytes"] == 64 * 64 * 4
    assert ma["output_bytes"] == 4
    # total = args + out + temp + code - aliased, clamped at 0
    assert ma["total_bytes"] == (
        ma["argument_bytes"] + ma["output_bytes"]
        + ma.get("temp_bytes", 0) + ma.get("generated_code_bytes", 0)
        - ma.get("alias_bytes", 0))


def test_memory_analysis_tolerates_absent_backends():
    class Raises:
        def memory_analysis(self):
            raise NotImplementedError

    class NoneShaped:
        def memory_analysis(self):
            return None

    class DictShaped:
        def memory_analysis(self):
            return {"argument_size_in_bytes": 10, "temp_size_in_bytes": 5}

    assert mem.memory_analysis_of_compiled(Raises()) is None
    assert mem.memory_analysis_of_compiled(NoneShaped()) is None
    ma = mem.memory_analysis_of_compiled(DictShaped())
    assert ma == {"argument_bytes": 10, "temp_bytes": 5,
                  "total_bytes": 15}


def test_analytic_memory_table():
    class State:
        params = {"w": np.zeros((4, 4), np.float32)}        # 64 B
        opt_state = [np.zeros(4, np.float32)] * 2           # 32 B

    batch = {"x": np.zeros((2, 8), np.float32)}             # 64 B
    t = mem.analytic_memory_table(State(), batch)
    assert t == {"params_bytes": 64, "opt_bytes": 32,
                 "batch_bytes": 64, "state_bytes": 160}
    # the PP (params, opt_state) tuple shape
    t2 = mem.analytic_memory_table(
        ({"w": np.zeros((4, 4), np.float32)},
         [np.zeros(4, np.float32)]), None)
    assert t2["params_bytes"] == 64 and t2["opt_bytes"] == 16


def test_memory_report_disagreement_tripwire():
    analytic = {"params_bytes": 80, "opt_bytes": 10, "batch_bytes": 10,
                "state_bytes": 100}
    ok = mem.memory_report({"argument_bytes": 105, "total_bytes": 205},
                           analytic)
    assert ok["mem_source"] == "measured" and not ok.get("args_disagree")
    bad = mem.memory_report({"argument_bytes": 150, "total_bytes": 250},
                            analytic)
    assert bad["args_disagree"]
    assert bad["args_disagreement"] == pytest.approx(0.5)
    lines = mem.memory_report_lines(bad)
    assert any("WARNING" in ln and "disagree" in ln for ln in lines)
    # no AOT analysis: the table is still printed, labeled unavailable
    none = mem.memory_report(None, analytic)
    assert none["mem_source"] == "analytic"
    lines = mem.memory_report_lines(none)
    assert "unavailable" in lines[0] and "analytic" in lines[0]


# ---------------------------------------------------------------------
# 2. runtime ledger + the pure fold


def test_memory_ledger_phase_attribution():
    samples = iter([
        {"source": "memory_stats", "bytes_in_use": 50, "peak_bytes": 100,
         "bytes_limit": 1000},
        {"source": "memory_stats", "bytes_in_use": 70, "peak_bytes": 300,
         "bytes_limit": 1000},
        {"source": "memory_stats", "bytes_in_use": 60, "peak_bytes": 300,
         "bytes_limit": 1000},
    ])
    led = mem.MemoryLedger(sample_fn=lambda: next(samples))
    led.sample("compile")
    rec = led.sample("step", step=4)
    assert rec["phase"] == "step" and rec["step"] == 4
    led.sample("checkpoint_async", step=6)
    # the global peak is the allocator's cumulative high water, stamped
    # with the phase during which it ROSE; per-phase maxima come from
    # the sample-point in-use bytes — the cumulative peak (300) must
    # not bleed into checkpoint_async, which was polled after it
    assert led.peak_bytes == 300 and led.peak_phase == "step"
    assert led.per_phase == {"compile": 50, "step": 70,
                             "checkpoint_async": 60}
    fold = led.fold()
    assert fold["bytes_limit"] == 1000
    assert fold["peak_phase"] == "step"


def test_memory_ledger_live_arrays_fallback_carries_high_water():
    vals = iter([40, 90, 30])
    led = mem.MemoryLedger(sample_fn=lambda: {
        "source": "live_arrays", "bytes_in_use": next(vals),
        "peak_bytes": None, "bytes_limit": None})
    led.sample("compile")
    led.sample("step")
    rec = led.sample("step")
    # the stream record carries the ledger's running high water, so the
    # offline fold sees the same peak the in-process ledger does
    assert rec["peak_bytes"] == 90 and led.peak_bytes == 90
    assert led.peak_phase == "step"
    assert led.fold()["source"] == "live_arrays"


def test_ledger_empty_fold_is_none():
    led = mem.MemoryLedger(sample_fn=lambda: {
        "source": "live_arrays", "bytes_in_use": 0, "peak_bytes": None})
    assert led.fold() is None
    led.sample("step")
    assert led.fold() is None


def test_fold_memory_records_phases_and_legacy():
    recs = [
        {"kind": "window", "step": 2},
        {"kind": "memory", "phase": "compile", "bytes_in_use": 10,
         "peak_bytes": 80, "source": "memory_stats", "bytes_limit": 500},
        {"kind": "memory", "phase": "step", "bytes_in_use": 60,
         "peak_bytes": 200, "source": "memory_stats", "bytes_limit": 500},
    ]
    fold = mem.fold_memory_records(recs)
    assert fold["peak_bytes"] == 200 and fold["peak_phase"] == "step"
    # per-phase from the sample-point in-use bytes, not the cumulative
    # allocator peak (MemoryLedger.sample's attribution rule)
    assert fold["per_phase"] == {"compile": 10, "step": 60}
    assert fold["bytes_limit"] == 500
    # the pre-round-15 end-of-run record shape still folds
    legacy = mem.fold_memory_records([
        {"kind": "memory", "supported": True,
         "devices": {"d0": {"peak_bytes_in_use": 123},
                     "d1": {"peak_bytes_in_use": 99}}}])
    assert legacy["peak_bytes"] == 123 and legacy["peak_phase"] is None
    assert mem.fold_memory_records([]) is None
    assert mem.fold_memory_records([{"kind": "memory",
                                     "bytes_in_use": 0}]) is None


def test_memory_lines_render_phase_order():
    fold = {"peak_bytes": 300 << 20, "peak_phase": "step",
            "per_phase": {"checkpoint_async": 10 << 20,
                          "step": 300 << 20, "compile": 200 << 20},
            "source": "memory_stats", "bytes_limit": 1 << 30}
    lines = mem.memory_lines(fold)
    assert "peak 300.0 MiB" in lines[0]
    assert "of 1.0 GiB limit (29%)" in lines[0]
    assert "phase step" in lines[0]
    # per-phase peaks render in ledger phase order (compile before step)
    assert lines[1].index("compile") < lines[1].index("step")
    assert mem.memory_lines(None) == []
    assert goodput.PHASES  # the order source the renderer leans on


# ---------------------------------------------------------------------
# 3. forensics


def test_is_oom_error_spellings():
    assert mem.is_oom_error(RuntimeError(
        "RESOURCE_EXHAUSTED: Out of memory while trying to allocate"))
    assert mem.is_oom_error(RuntimeError("failed to allocate 4.2G"))
    assert not mem.is_oom_error(ValueError("shape mismatch"))


def test_live_buffer_breakdown_aggregates_by_shape():
    import jax.numpy as jnp

    keep = [jnp.ones((17, 23), jnp.float32) for _ in range(3)]
    bd = mem.live_buffer_breakdown(top_k=1000)
    rows = [r for r in bd["top_buffers"]
            if r["shape"] == [17, 23] and r["dtype"] == "float32"]
    assert rows and rows[0]["count"] >= 3
    assert rows[0]["nbytes"] >= 3 * 17 * 23 * 4
    assert bd["total_live_bytes"] >= rows[0]["nbytes"]
    assert bd["buffer_count"] >= 3
    # largest-first ordering
    sizes = [r["nbytes"] for r in bd["top_buffers"]]
    assert sizes == sorted(sizes, reverse=True)
    del keep


def test_dump_forensics_writes_and_never_raises(tmp_path):
    printed: list[str] = []
    path = mem.dump_forensics(str(tmp_path), reason="oom", step=7,
                              error="RESOURCE_EXHAUSTED: boom",
                              print_fn=printed.append)
    assert path and os.path.basename(path) == mem.MEMORY_DUMP_NAME
    payload = json.loads(Path(path).read_text())
    assert payload["reason"] == "oom" and payload["step"] == 7
    assert payload["error"].startswith("RESOURCE_EXHAUSTED")
    assert "top_buffers" in payload and "total_live_bytes" in payload
    assert printed and "memory forensics (oom)" in printed[0]
    # best-effort contract: an unwritable target returns None, no raise
    assert mem.dump_forensics(
        str(tmp_path / "nope" / "nope"), reason="oom") is None


# ---------------------------------------------------------------------
# 4. --hbm_budget


def test_parse_hbm_budget():
    assert mem.parse_hbm_budget(None) is None
    assert mem.parse_hbm_budget("off") is None
    assert mem.parse_hbm_budget("0") is None
    assert mem.parse_hbm_budget("auto") == "auto"
    assert mem.parse_hbm_budget("16GB") == 16 * 2**30
    assert mem.parse_hbm_budget("900mb") == 900 * 2**20
    assert mem.parse_hbm_budget("1.5GiB") == int(1.5 * 2**30)
    assert mem.parse_hbm_budget("12345") == 12345
    with pytest.raises(ValueError, match="hbm_budget"):
        mem.parse_hbm_budget("lots")
    with pytest.raises(ValueError, match="> 0"):
        mem.parse_hbm_budget("-4GB")


def test_resolve_hbm_budget_auto_without_allocator_stats():
    # explicit bytes pass through untouched
    assert mem.resolve_hbm_budget_bytes(123) == (123, None)
    assert mem.resolve_hbm_budget_bytes(None) == (None, None)
    # the CPU backend exposes no bytes_limit: auto degrades to a loud
    # note instead of silently skipping the check
    bytes_, note = mem.resolve_hbm_budget_bytes("auto")
    assert bytes_ is None and "bytes_limit" in note
    assert mem.budget_lines(None, None, note)[0].startswith("WARNING")


def test_budget_lines_verdicts():
    measured = {"argument_bytes": 1 << 30, "temp_bytes": 2 << 30,
                "output_bytes": 0, "total_bytes": 3 << 30}
    over = mem.budget_lines(measured, 2 << 30)
    assert over[0].startswith("WARNING") and "EXCEEDS" in over[0]
    fits = mem.budget_lines(measured, 4 << 30)
    assert "fits the budget" in fits[0] and "75%" in fits[0]
    assert mem.budget_lines(None, 2 << 30)[0].startswith("WARNING")
    assert mem.budget_lines(measured, None) == []


def test_flags_validate_hbm_budget():
    cfg = flags.BenchmarkConfig(hbm_budget="16GB").resolve()
    assert cfg.hbm_budget == "16GB"
    with pytest.raises(ValueError, match="hbm_budget"):
        flags.BenchmarkConfig(hbm_budget="lots").resolve()
    ns = flags.build_parser().parse_args(["--hbm_budget", "auto"])
    assert ns.hbm_budget == "auto"


# ---------------------------------------------------------------------
# 5. the tune feedback loop


def test_hbm_model_from_measurements():
    limit = 1000
    rows = [{"overrides": {"batch_size": 64},
             "peak_hbm_bytes": 500, "hbm_bytes_limit": limit}]
    m = prune.HbmModel.from_measurements(rows, headroom=1.25)
    # 64 * 1000 / (500 * 1.25) = 102 — measured extrapolation, and the
    # anchor IS the estimate: no seeded-guess headroom stacked on top
    assert m.source == "measured" and m.headroom == 1.0
    assert m.max_microbatch == 102
    # an OOM'd row is ground truth the other way: cap strictly below
    rows.append({"overrides": {"batch_size": 96},
                 "error": "RESOURCE_EXHAUSTED: oom"})
    m2 = prune.HbmModel.from_measurements(rows, headroom=1.25)
    assert m2.max_microbatch == 95
    # rows without any measurement yield no model (fall back to seeded)
    assert prune.HbmModel.from_measurements(
        [{"overrides": {"batch_size": 8}}]) is None
    # a peak-only row (no limit) anchors at its own measured microbatch
    m3 = prune.HbmModel.from_measurements(
        [{"overrides": {"batch_size": 32,
                        "gradient_accumulation_steps": 4},
          "peak_hbm_bytes": 10}])
    assert m3.max_microbatch == 8


def test_measured_rows_from_journal_join():
    journal = {
        "model": "trivial",
        "candidates": {
            "batch_size=64": {"overrides": {"batch_size": 64}},
            "batch_size=128": {"overrides": {"batch_size": 128}},
        },
        "measurements": {
            "batch_size=64": {"0": {"peak_hbm_bytes": 500,
                                    "hbm_bytes_limit": 1000}},
            "batch_size=128": {"0": {"per_chip": 5.0}},   # no memory
        },
    }
    rows = prune.measured_rows_from_journal(journal)
    assert len(rows) == 1
    assert rows[0]["overrides"] == {"batch_size": 64}
    assert prune.measured_rows_from_journal(journal, model="lenet") == []


def test_hbm_model_for_prefers_measured():
    rows = [{"overrides": {"batch_size": 64},
             "peak_hbm_bytes": 900, "hbm_bytes_limit": 1000}]
    assert prune.hbm_model_for("trivial", rows).source == "measured"
    assert prune.hbm_model_for("trivial", None).source == "seeded"
    assert prune.hbm_model_for("trivial", [{"overrides": {}}]
                               ).source == "seeded"
    # a member outside the seed table with no measurements: no model
    assert prune.hbm_model_for("not_a_member", None) is None


def test_measured_anchor_keeps_seed_bf16_fact():
    """The f32-accumulator rejection is a state-memory fact from the
    seed; switching the microbatch anchor to measured provenance must
    not drop it."""
    bf16_members = [name for name, seed in prune.SEED_CONFIGS.items()
                    if seed.get("accum_dtype") == "bf16"]
    if not bf16_members:
        pytest.skip("no seed carries accum_dtype=bf16")
    member = bf16_members[0]
    seeded = prune.HbmModel.seeded(member)
    rows = [{"overrides": {"batch_size": 4},
             "peak_hbm_bytes": 100, "hbm_bytes_limit": 1000}]
    m = prune.hbm_model_for(member, rows)
    assert m.source == "measured"
    assert m.needs_bf16_accum_at == seeded.needs_bf16_accum_at
    # OOM rows classify through the ONE spelling list (obs.memory)
    assert prune._row_oomed({"error": "Out of memory: 1 GiB"})
    assert prune._row_oomed({"error": "skipped: hbm-oom"})
    assert not prune._row_oomed({"error": "segfault"})


def test_static_prune_journals_hbm_source():
    big = space.Candidate.make("trivial", {"batch_size": 4096})
    res = prune.static_prune([big])
    skips = [s for s in res.skipped if s.cls == prune.HBM_OOM]
    assert skips and skips[0].hbm_source == "seeded"
    assert skips[0].journal_record()["hbm_source"] == "seeded"
    # with a measured row that says even 64 barely fits, provenance flips
    rows = [{"overrides": {"batch_size": 64},
             "peak_hbm_bytes": 990, "hbm_bytes_limit": 1000}]
    res2 = prune.static_prune(
        [space.Candidate.make("trivial", {"batch_size": 512})],
        measured_rows=rows)
    skips2 = [s for s in res2.skipped if s.cls == prune.HBM_OOM]
    assert skips2 and skips2[0].hbm_source == "measured"
    assert "measured HBM anchor" in skips2[0].reason
    # non-hbm skips carry no provenance field
    assert "hbm_source" not in prune.Skip(
        big, prune.LINT, "x").journal_record()


def test_search_measured_recheck_skips_mid_search(tmp_path):
    """The closed loop: candidate A's measurement journals a peak near
    the device limit, so candidate B (a larger microbatch the SEEDED
    anchor admitted) is skipped without a run, hbm_source=measured."""
    cands = [space.Candidate.make("trivial", {"batch_size": 64}),
             space.Candidate.make("trivial", {"batch_size": 512})]
    calls: list = []

    def stub(c, rung, batches):
        calls.append((c.key, rung))
        return {"per_chip": 100.0, "wall_s": 1.0,
                "peak_hbm_bytes": 950, "hbm_bytes_limit": 1000,
                "mem_source": "memory_stats"}

    j = search.run_search(
        "trivial", str(tmp_path), "cpu-test-w1",
        settings=search.SearchSettings(budget_s=1e9, max_rungs=1),
        runner=stub, space=cands, print_fn=lambda m: None)
    # batch 512 never ran: the measured anchor (~64·1000/950·1.15 ≈ 58,
    # floored at the measured-OK 64) rejected it mid-rung
    assert all(k == "batch_size=64" for k, _ in calls)
    skips = [s for s in j["skipped"]
             if s["class"] == prune.HBM_OOM
             and s.get("hbm_source") == "measured"]
    assert skips and skips[0]["key"] == "batch_size=512"
    assert j["best"]["key"] == "batch_size=64"
    # the journal measurement row carries the memory it recorded
    row = j["measurements"]["batch_size=64"]["0"]
    assert row["peak_hbm_bytes"] == 950
    assert row["hbm_bytes_limit"] == 1000


def test_tune_show_journal_renders_prune_ledger(tmp_path, capsys):
    journal = {
        "model": "trivial", "hardware": "cpu-test-w1",
        "status": "complete", "spent_s": 12.0, "budget_s": 600.0,
        "skipped": [
            {"key": "batch_size=4096", "class": "hbm-oom",
             "hbm_source": "measured",
             "reason": "microbatch 4096 exceeds the measured HBM "
                       "anchor 64 x headroom 1 = 64"},
            {"key": "accum=0", "class": "flag-invalid", "reason": "x"},
        ],
        "candidates": {"batch_size=64": {"overrides":
                                         {"batch_size": 64}}},
        "measurements": {"batch_size=64": {
            "0": {"per_chip": 100.0, "peak_hbm_bytes": 950 << 20,
                  "hbm_bytes_limit": 2 << 30,
                  "mem_source": "memory_stats"}}},
    }
    p = tmp_path / "tune_state.json"
    p.write_text(json.dumps(journal))
    assert tune_main(["show", "--journal", str(p)]) == 0
    out = capsys.readouterr().out
    assert "pruned without a run: 2 (flag-invalid x1, hbm-oom x1)" in out
    assert "[hbm-oom/measured] batch_size=4096" in out
    assert "measured: batch_size=64 rung 0: peak 950.0 MiB" in out
    assert "[memory_stats]" in out


# ---------------------------------------------------------------------
# the memory-probe-in-hot-loop lint


HOT_PROBE_FIXTURE = """\
import jax

def unguarded(n):
    out = []
    for i in range(n):
        out.append(jax.live_arrays())
    return out

def guarded(n, sync_every):
    for i in range(n):
        if i % sync_every == 0:
            jax.live_arrays()

def spelled_guard(mem_ledger, win):
    while True:
        if win.at_sync_boundary:
            mem_ledger.sample("step")

def header_only():
    total = 0
    for a in jax.live_arrays():
        total += a.nbytes
    return total

def nested_def():
    for i in range(3):
        def f():
            return jax.live_arrays()

def profile_loop(n):
    while n:
        jax.profiler.device_memory_profile()
        n -= 1

def ledger_loop(mem_ledger, n):
    for i in range(n):
        mem_ledger.sample("step")
"""


def test_memory_probe_hot_loop_lint():
    fs = [f for f in lints.lint_source_text(HOT_PROBE_FIXTURE, "fx.py")
          if f.lint == lints.HOT_MEMORY]
    assert all(f.severity == "warning" for f in fs)
    flagged = {f.location.rsplit(":", 1)[1] for f in fs}
    # unguarded live_arrays (6), the profiler blob (32), the ledger
    # sample (37) — and nothing else
    assert flagged == {"6", "32", "37"}, [f.render() for f in fs]


def test_memory_probe_lint_in_repo_gate():
    assert lints.HOT_MEMORY in lints.ALL_SOURCE_LINTS


# ---------------------------------------------------------------------
# 6. end-to-end on the shared rewind_run fixture (no new driver run)


def test_driver_memory_records_and_result(rewind_run):
    res = rewind_run["result"]
    # the CPU mesh has no allocator stats: the ledger degraded to the
    # labeled live_arrays byte-sum high water, and said so
    assert res.mem_source == "live_arrays"
    assert res.peak_hbm_bytes and res.peak_hbm_bytes > 0
    assert res.hbm_bytes_limit is None
    # the AOT memory analysis of the actual step program landed
    assert res.memory_analysis and res.memory_analysis["argument_bytes"] > 0
    recs = obs_metrics.read_run(rewind_run["dir"])[1]
    mem_recs = [r for r in recs if r.get("kind") == "memory"]
    # one compile-phase sample + one per sync window + the final sample
    assert {r["phase"] for r in mem_recs} >= {"compile", "step"}
    assert all(r["source"] == "live_arrays" for r in mem_recs)
    rep = [r for r in recs if r.get("kind") == "memory_report"]
    assert rep and rep[-1]["measured"]["argument_bytes"] > 0
    assert rep[-1]["analytic"]["params_bytes"] > 0


def test_driver_prints_memory_lines(rewind_run):
    text = "\n".join(rewind_run["out"])
    assert "memory: peak" in text and "live_arrays" in text
    assert "memory (AOT): args" in text


def test_summarize_memory_section(rewind_run):
    out = io.StringIO()
    assert obs_main(["summarize", rewind_run["dir"]], out=out) == 0
    text = out.getvalue()
    assert "memory: peak" in text
    assert "per-phase peaks (MiB):" in text
    assert "compile" in text and "memory (AOT): args" in text


def test_diff_memory_rows(rewind_run):
    out = io.StringIO()
    assert obs_main(["diff", rewind_run["dir"], rewind_run["dir"]],
                    out=out) == 0
    text = out.getvalue()
    assert "peak HBM MiB" in text
    assert "aot args MiB" in text and "aot temp MiB" in text


def test_heartbeat_carries_unified_mem_peak(rewind_run):
    beats = fleet.read_heartbeats(rewind_run["dir"])
    last = beats[0][-1]
    assert fleet.heartbeat_mem_peak(last) == last["mem_peak_bytes"] > 0
    assert "peak_bytes_in_use" not in last


def test_watch_renders_memory(rewind_run):
    buf = io.StringIO()
    assert watch_mod.watch(rewind_run["dir"], out=buf,
                           interval=0.01) == 0
    text = buf.getvalue()
    assert "memory: peak" in text
    assert "mem peak" in text       # the heartbeat headline field


def test_summary_record_carries_memory_fields(rewind_run):
    recs = obs_metrics.read_run(rewind_run["dir"])[1]
    summary = [r for r in recs if r.get("kind") == "summary"][-1]
    assert summary["peak_hbm_bytes"] > 0
    assert summary["mem_source"] == "live_arrays"
    assert summary["memory_analysis"]["argument_bytes"] > 0


@pytest.mark.slow
def test_emergency_save_writes_memory_dump_subprocess(tmp_path):
    """The forensics proof: an injected preemption exits with the
    preemption code AND leaves ``memory_dump.json`` beside the metrics
    stream, with the dump journaled as a ``memory_dump`` record."""
    from tpu_hc_bench import resilience

    mdir = str(tmp_path / "m")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "tpu_hc_bench", "1", "0", "2", "ici",
         "--model", "trivial", "--num_classes", "10",
         "--num_warmup_batches", "1", "--num_batches", "6",
         "--display_every", "2", "--virtual_devices", "8",
         "--inject_fault", "sigterm@2",
         "--train_dir", str(tmp_path / "ck"), "--metrics_dir", mdir],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=240)
    assert proc.returncode == resilience.EXIT_PREEMPTED, \
        proc.stdout[-2000:] + proc.stderr[-2000:]
    assert "memory forensics (emergency_save)" in proc.stdout
    dump = json.loads(
        (Path(mdir) / mem.MEMORY_DUMP_NAME).read_text())
    assert dump["reason"] == "emergency_save"
    assert dump["total_live_bytes"] > 0 and dump["top_buffers"]
    recs = [json.loads(ln) for ln
            in (Path(mdir) / "metrics.jsonl").read_text().splitlines()
            if ln.strip()]
    drec = [r for r in recs if r.get("kind") == "memory_dump"]
    assert drec and drec[-1]["reason"] == "emergency_save"
    # the emergency path also sampled the ledger under its own phase
    assert any(r.get("kind") == "memory"
               and r.get("phase") == "emergency_save" for r in recs)
    # round 17: the TIME forensics twin lands beside the memory dump —
    # timeline_dump.json with this rank's last-K spans, journaled too
    from tpu_hc_bench.obs import timeline as timeline_mod

    tdump = json.loads(
        (Path(mdir) / timeline_mod.TIMELINE_DUMP_NAME).read_text())
    assert tdump["reason"] == "emergency_save"
    spans0 = tdump["ranks"]["0"]
    assert spans0 and any(s["name"] == "step_dispatch" for s in spans0)
    assert any(r.get("kind") == "timeline_dump"
               and r.get("reason") == "emergency_save" for r in recs)
