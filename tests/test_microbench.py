"""OSU-equivalent microbench smoke tests on the virtual mesh."""

import pytest

from tpu_hc_bench.microbench import osu


@pytest.mark.parametrize("op", osu.OSU_OPS)
def test_sweep_runs_and_reports(op, mesh8):
    results = osu.run_sweep(
        op=op, min_bytes=256, max_bytes=1024, warmup=1, iters=2, mesh=mesh8
    )
    assert len(results) == 3  # 256, 512, 1024
    for r in results:
        assert r.world_size == 8
        assert r.mean_us > 0
        assert r.algbw_gbps > 0
    sizes = [r.message_bytes for r in results]
    assert sizes == sorted(sizes)


def test_busbw_factors():
    assert osu._busbw_factor("allreduce", 8) == pytest.approx(2 * 7 / 8)
    assert osu._busbw_factor("all_gather", 8) == pytest.approx(7 / 8)
    assert osu._busbw_factor("ppermute", 8) == 1.0
    assert osu._busbw_factor("allreduce", 1) == 1.0


def test_format_table(mesh8):
    results = osu.run_sweep(
        op="allreduce", min_bytes=256, max_bytes=256, warmup=1, iters=1,
        mesh=mesh8,
    )
    table = osu.format_table(results)
    assert "allreduce" in table and "busbw" in table
