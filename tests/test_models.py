"""Model-zoo tests: registry dispatch, output shapes, parameter counts.

Parameter counts are checked against the published architecture figures
(ResNet-50 25.6M, VGG-16 138.4M, Inception-v3 23.8M, BERT-base ~110M) —
a strong structural check that the fresh implementations match the
architectures tf_cnn_benchmarks drives.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_hc_bench import models
from tpu_hc_bench.models import bert


def n_params(tree):
    return sum(int(np.prod(x.shape)) for x in jax.tree.leaves(tree))


def init_model(name, image=None, num_classes=1000):
    """Init at the canonical shape, or ``image`` px for global-pool models
    (their param count is input-size independent; small inits keep the CPU
    suite fast)."""
    model, spec = models.create_model(name, num_classes=num_classes)
    if spec.is_text:
        x = jnp.zeros((1, *spec.input_shape), jnp.int32)
    else:
        size = image or spec.default_image_size
        x = jnp.zeros((1, size, size, spec.input_shape[-1]), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    return model, spec, variables, x


def test_registry_lists_reference_models():
    names = models.list_models()
    # resnet50 pinned by the reference (:34); inception3/vgg16/bert from
    # BASELINE.json configs; trivial from tf_cnn_benchmarks
    for required in ("resnet50", "inception3", "vgg16", "bert_base", "trivial"):
        assert required in names


def test_aliases():
    assert models.get_model_spec("bert").name == "bert_base"
    assert models.get_model_spec("inception_v3").name == "inception3"
    with pytest.raises(ValueError):
        models.get_model_spec("alexnet9000")


def test_trivial_forward():
    model, spec, variables, x = init_model("trivial")
    out = model.apply(variables, x, train=False)
    assert out.shape == (1, 1000)


def test_resnet50_params_and_shape():
    model, spec, variables, x = init_model("resnet50", image=64)
    count = n_params(variables["params"])
    assert abs(count - 25.6e6) / 25.6e6 < 0.01, count
    out = model.apply(variables, x, train=False)
    assert out.shape == (1, 1000)
    assert "batch_stats" in variables


def test_resnet18_params():
    _, _, variables, _ = init_model("resnet18", image=64)
    count = n_params(variables["params"])
    assert abs(count - 11.7e6) / 11.7e6 < 0.02, count


@pytest.mark.slow
def test_vgg16_params():
    model, spec, variables, x = init_model("vgg16")
    count = n_params(variables["params"])
    assert abs(count - 138.4e6) / 138.4e6 < 0.01, count


@pytest.mark.slow
def test_inception3_params_and_shape():
    model, spec, variables, x = init_model("inception3", image=96)
    count = n_params(variables["params"])
    # canonical inception_v3 (no aux head) is ~23.8M
    assert abs(count - 23.8e6) / 23.8e6 < 0.03, count
    out = model.apply(variables, x, train=False)
    assert out.shape == (1, 1000)


@pytest.mark.slow
def test_alexnet_params_and_shape():
    model, spec, variables, x = init_model("alexnet")
    count = n_params(variables["params"])
    # single-tower AlexNet ~61M
    assert abs(count - 61e6) / 61e6 < 0.05, count
    out = model.apply(variables, x, train=False)
    assert out.shape == (1, 1000)


@pytest.mark.slow
def test_googlenet_params_and_shape():
    model, spec, variables, x = init_model("googlenet", image=64)
    count = n_params(variables["params"])
    # GoogLeNet ~6.6M (no aux heads)
    assert abs(count - 6.6e6) / 6.6e6 < 0.1, count
    out = model.apply(variables, x, train=False)
    assert out.shape == (1, 1000)


def test_resnet50_v2_params_and_shape():
    model, spec, variables, x = init_model("resnet50_v2", image=64)
    count = n_params(variables["params"])
    # preact v2 carries the same conv stack as v1 (~25.5M)
    assert abs(count - 25.5e6) / 25.5e6 < 0.01, count
    out = model.apply(variables, x, train=False)
    assert out.shape == (1, 1000)


def test_cifar_resnet_params():
    # He 2015 §4.2: 0.27M / 0.85M / 1.7M for depths 20 / 56 / 110
    for name, want in [("resnet20", 0.27e6), ("resnet56", 0.85e6),
                       ("resnet110", 1.7e6)]:
        _, spec, variables, _ = init_model(name, num_classes=10)
        assert spec.name == f"{name}_cifar"
        count = n_params(variables["params"])
        assert abs(count - want) / want < 0.03, (name, count)


@pytest.mark.slow
def test_vgg11_params():
    _, _, variables, _ = init_model("vgg11")
    count = n_params(variables["params"])
    assert abs(count - 132.9e6) / 132.9e6 < 0.01, count


@pytest.mark.slow
def test_inception4_params_and_shape():
    model, spec, variables, x = init_model("inception4", image=160)
    count = n_params(variables["params"])
    # Szegedy 2016: ~42.7M (no aux head)
    assert abs(count - 42.7e6) / 42.7e6 < 0.02, count
    out = model.apply(variables, x, train=False)
    assert out.shape == (1, 1000)


def test_mobilenet_params_and_shape():
    model, spec, variables, x = init_model("mobilenet", image=64)
    count = n_params(variables["params"])
    # MobileNet v1 1.0/224 ~4.2M
    assert abs(count - 4.25e6) / 4.25e6 < 0.03, count
    out = model.apply(variables, x, train=False)
    assert out.shape == (1, 1000)


@pytest.mark.slow
def test_nasnet_mobile_params_and_shape():
    model, spec, variables, x = init_model("nasnet", image=96)
    count = n_params(variables["params"])
    # NASNet-A mobile (4 @ 1056) ~5.3M
    assert abs(count - 5.3e6) / 5.3e6 < 0.02, count
    out = model.apply(variables, x, train=False)
    assert out.shape == (1, 1000)


@pytest.mark.slow
def test_nasnetlarge_params():
    _, _, variables, _ = init_model("nasnetlarge", image=96)
    count = n_params(variables["params"])
    # NASNet-A large (6 @ 4032) ~88.9M
    assert abs(count - 88.9e6) / 88.9e6 < 0.01, count


@pytest.mark.slow
def test_densenet40_params_and_shape():
    model, spec, variables, x = init_model("densenet40_k12", num_classes=10)
    count = n_params(variables["params"])
    # Huang 2017 table 2: DenseNet (k=12) depth 40 ~ 1.0M
    assert abs(count - 1.0e6) / 1.0e6 < 0.1, count
    out = model.apply(variables, x, train=False)
    assert out.shape == (1, 10)


@pytest.mark.parametrize("name", [
    "lenet",
    pytest.param("overfeat", marks=pytest.mark.slow),        # 231px init
    pytest.param("densenet100_k12", marks=pytest.mark.slow), # 100-layer graph
])
def test_small_zoo_forward(name):
    model, spec, variables, x = init_model(
        name, num_classes=10 if "densenet" in name else 1000)
    out = model.apply(variables, x, train=False)
    assert out.shape[0] == 1


def test_space_to_depth_stem_equivalence():
    """The packed 4x4/s1 stem computes exactly the 7x7/s2 SAME conv.

    Maps a 7x7 kernel (zero-padded to 8x8) into the packed layout
    K[r,s,py*2c+px*c+ch,f] = W8[2r+py,2s+px,ch,f] and checks outputs match.
    """
    import jax.lax as lax

    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (2, 16, 16, 3))
    w = jax.random.normal(jax.random.PRNGKey(1), (7, 7, 3, 8))
    ref = lax.conv_general_dilated(
        x, w, window_strides=(2, 2), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))

    n, h, wd, c = x.shape
    xp = x.reshape(n, h // 2, 2, wd // 2, 2, c)
    xp = xp.transpose(0, 1, 3, 2, 4, 5).reshape(n, h // 2, wd // 2, 4 * c)
    w8 = jnp.zeros((8, 8, 3, 8)).at[:7, :7].set(w)
    kp = jnp.zeros((4, 4, 12, 8))
    for py in range(2):
        for px in range(2):
            for ch in range(3):
                kp = kp.at[:, :, py * 6 + px * 3 + ch, :].set(
                    w8[py::2, px::2, ch, :])
    out = lax.conv_general_dilated(
        xp, kp, window_strides=(1, 1), padding=((1, 2), (1, 2)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    np.testing.assert_allclose(out, ref, atol=1e-4)


def test_resnet_s2d_forward():
    model, spec = models.create_model("resnet18", space_to_depth=True)
    x = jnp.zeros((1, 64, 64, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    assert variables["params"]["conv_init_s2d"]["kernel"].shape == (4, 4, 12, 64)
    out = model.apply(variables, x, train=False)
    assert out.shape == (1, 1000)
    with pytest.raises(ValueError):
        models.create_model("mobilenet", space_to_depth=True)


@pytest.mark.slow
def test_bert_base_params():
    model = bert.BertMLM()
    x = jnp.zeros((1, 128), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    count = n_params(variables["params"])
    # BERT-base ~110M (embeddings+encoder+mlm head, tied projection)
    assert 105e6 < count < 115e6, count
    out = model.apply(variables, x, train=False)
    assert out.shape == (1, 128, bert.BERT_BASE_VOCAB)


@pytest.mark.slow
def test_bert_large_params():
    model = bert.bert_large_mlm()
    x = jnp.zeros((1, 16), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    count = n_params(variables["params"])
    # BERT-large ~335M with tied MLM projection
    assert 320e6 < count < 350e6, count


def test_bert_tiny_forward_train_mode():
    model = bert.bert_tiny_mlm()
    x = jnp.zeros((2, 16), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    out = model.apply(
        variables, x, train=True, rngs={"dropout": jax.random.PRNGKey(1)}
    )
    assert out.shape == (2, 16, 1024)


def test_seq_len_override():
    model, spec = models.create_model("bert_tiny", seq_len=256)
    assert spec.input_shape == (256,)
    # linear rescale from the registry's seq-64 figure
    assert spec.flops_per_example == pytest.approx(2 * 4.5e6 * 64 * 4)
    x = jnp.zeros((1, 256), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    out = model.apply(variables, x, train=False)
    assert out.shape == (1, 256, 1024)
    with pytest.raises(ValueError):
        models.create_model("resnet18", seq_len=256)


def test_bf16_compute_keeps_fp32_params_and_logits():
    model, spec = models.create_model("resnet18", dtype=jnp.bfloat16)
    x = jnp.zeros((1, 224, 224, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    for leaf in jax.tree.leaves(variables["params"]):
        assert leaf.dtype == jnp.float32
    out = model.apply(variables, x, train=False)
    assert out.dtype == jnp.float32


@pytest.mark.slow
def test_gpt2_params_and_causality():
    from tpu_hc_bench.models import gpt

    model = gpt.gpt2()
    x = jnp.zeros((1, 16), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    count = n_params(variables["params"])
    # GPT-2 small, tied embeddings: 124.4M
    assert abs(count - 124.4e6) / 124.4e6 < 0.01, count

    # causality: perturbing token t must not change logits at positions < t
    toks = jax.random.randint(jax.random.PRNGKey(1), (1, 16), 1, 1000)
    base = model.apply(variables, toks, train=False)
    toks2 = toks.at[0, 10].set(999)
    pert = model.apply(variables, toks2, train=False)
    np.testing.assert_allclose(base[0, :10], pert[0, :10],
                               rtol=1e-5, atol=1e-5)
    assert not np.allclose(base[0, 10:], pert[0, 10:])


def test_gpt2_registry_and_synthetic_lm():
    from tpu_hc_bench.data.synthetic import SyntheticTokens

    spec = models.get_model_spec("gpt2")
    assert spec.is_text and spec.causal_lm and spec.vocab_size == 50257
    ds = SyntheticTokens(2, 8, vocab_size=100, causal_lm=True)
    toks, targets, weights = ds.batch()
    np.testing.assert_array_equal(targets[:, :-1], toks[:, 1:])
    assert weights[:, -1].sum() == 0 and weights[:, :-1].all()


def test_gradient_checkpointing_matches():
    """Remat changes memory, not math: same loss and grads — including in
    train mode, where the recomputed dropout masks must reuse the forward
    pass's RNG."""
    import optax

    x = jax.random.randint(jax.random.PRNGKey(0), (2, 16), 1, 1000)
    y = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 1, 1000)

    def loss_for(remat):
        model, _ = models.create_model("bert_tiny",
                                       gradient_checkpointing=remat)
        variables = model.init(jax.random.PRNGKey(2), x, train=False)

        def loss_fn(p):
            logits = model.apply(
                {"params": p}, x, train=True,
                rngs={"dropout": jax.random.PRNGKey(3)})
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, y).mean()

        return jax.value_and_grad(loss_fn)(variables["params"])

    (l0, g0), (l1, g1) = loss_for(False), loss_for(True)
    np.testing.assert_allclose(l0, l1, rtol=1e-6)
    assert (jax.tree_util.tree_structure(g0)
            == jax.tree_util.tree_structure(g1))
    for a, b in zip(jax.tree.leaves(g0), jax.tree.leaves(g1)):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6)


@pytest.mark.slow
def test_vit_b16_params():
    from tpu_hc_bench.models import vit

    model = vit.vit_b16()
    x = jnp.zeros((1, 224, 224, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    count = n_params(variables["params"])
    # ViT-B/16 ~86M (patchify + 12 encoder layers + head)
    assert 82e6 < count < 92e6, count
    out = model.apply(variables, x, train=False)
    assert out.shape == (1, 1000)
    assert out.dtype == jnp.float32


def test_vit_tiny_trains_and_flash_matches_dense():
    from tpu_hc_bench.models import vit

    x = jax.random.normal(jax.random.PRNGKey(0), (2, 32, 32, 3))
    dense_model, _ = models.create_model("vit_tiny", num_classes=10)
    flash_model, _ = models.create_model("vit_tiny", num_classes=10,
                                         attention_impl="flash")
    variables = dense_model.init(jax.random.PRNGKey(1), x, train=False)
    ref = dense_model.apply(variables, x, train=False)
    out = flash_model.apply(variables, x, train=False)
    # seq 17 (16 patches + cls): flash pads to its block size; outputs match
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)
    # train mode runs with dropout
    out = dense_model.apply(variables, x, train=True,
                            rngs={"dropout": jax.random.PRNGKey(2)})
    assert out.shape == (2, 10)


def test_vit_remat_accepted():
    model, _ = models.create_model("vit_tiny", num_classes=10,
                                   gradient_checkpointing=True)
    x = jnp.zeros((1, 32, 32, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    assert model.apply(variables, x, train=False).shape == (1, 10)


@pytest.mark.slow
def test_vit_l16_params():
    from tpu_hc_bench.models import vit

    model = vit.vit_l16()
    x = jnp.zeros((1, 224, 224, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    count = n_params(variables["params"])
    # ViT-L/16 ~304M
    assert 295e6 < count < 315e6, count
    assert model.apply(variables, x, train=False).shape == (1, 1000)


def test_ncf_shapes_and_params():
    """NeuMF (tf_cnn's ncf member): head shape + ml-20m parameter count
    (embeddings dominate: (138493+26744)*(64+128) + MLP tower)."""
    import jax
    import jax.numpy as jnp
    from tpu_hc_bench.models import create_model

    model, spec = create_model("ncf_tiny")
    assert spec.integer_input and spec.input_shape == (2,)
    ids = jnp.array([[0, 0], [999, 499]], jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), ids, train=False)
    logits = model.apply(variables, ids, train=False)
    assert logits.shape == (2, 2)

    model, _ = create_model("ncf")
    variables = jax.eval_shape(
        functools.partial(model.init, train=False),
        jax.random.PRNGKey(0), jax.ShapeDtypeStruct((1, 2), jnp.int32))
    n = sum(x.size for x in jax.tree.leaves(variables["params"]))
    # (138493+26744)*64 GMF + (138493+26744)*128 MLP embeds + tower+head
    assert 31_000_000 < n < 33_000_000, n


def test_ncf_through_driver(mesh8):
    from tpu_hc_bench import flags
    from tpu_hc_bench.train import driver
    import numpy as np

    cfg = flags.BenchmarkConfig(
        model="ncf_tiny", batch_size=4, num_warmup_batches=1, num_batches=3,
        display_every=1).resolve()
    out = []
    res = driver.run_benchmark(cfg, print_fn=out.append)
    assert np.isfinite(res.final_loss)
    # eval (binary accuracy via the standard top-1 protocol)
    cfg = flags.BenchmarkConfig(
        model="ncf_tiny", batch_size=4, eval=True, num_batches=2,
        num_warmup_batches=1, display_every=1).resolve()
    out = []
    driver.run_benchmark(cfg, print_fn=out.append)
    assert any("top_1 accuracy" in l for l in out)


def test_deepspeech2_shapes_and_params():
    """DS2 (tf_cnn's speech member): conv frontend shapes, BiGRU stack,
    CTC head, and the ~48M-param count at the paper shape."""
    from tpu_hc_bench.models import create_model

    model, spec = create_model("deepspeech2_tiny")
    assert spec.ctc and spec.input_shape == (64, 32)
    x = jnp.zeros((2, 64, 32), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (2, 16, 29)         # T/4 frames, 29-char vocab

    model, _ = create_model("deepspeech2")
    variables = jax.eval_shape(
        functools.partial(model.init, train=False),
        jax.random.PRNGKey(0),
        jax.ShapeDtypeStruct((1, 300, 161), jnp.float32))
    n = sum(x.size for x in jax.tree.leaves(variables["params"]))
    assert 40_000_000 < n < 55_000_000, n


def test_deepspeech2_through_driver(mesh8):
    """CTC member end to end: SyntheticSpeech batches, optax.ctc_loss
    in the train step, loss decreases-or-finite over a few steps."""
    from tpu_hc_bench import flags
    from tpu_hc_bench.train import driver
    import numpy as np

    cfg = flags.BenchmarkConfig(
        model="deepspeech2_tiny", batch_size=2, num_warmup_batches=1,
        num_batches=3, display_every=1).resolve()
    out = []
    res = driver.run_benchmark(cfg, print_fn=out.append)
    assert np.isfinite(res.final_loss)
    assert any("examples/sec" in l for l in out)
    # eval is out of protocol for CTC
    cfg = flags.BenchmarkConfig(
        model="deepspeech2_tiny", batch_size=2, eval=True,
        num_batches=2).resolve()
    import pytest
    with pytest.raises(ValueError, match="CTC"):
        driver.run_benchmark(cfg, print_fn=lambda _: None)


def test_hoisted_gru_matches_flax_gru():
    """HoistedGRU is flax's GRUCell with the input projections batched
    out of the scan: copying the six flax gate params into the fused
    [I,3H]/[H,3H] layout must reproduce the RNN(GRUCell) output exactly,
    forward and reverse."""
    import flax.linen

    from tpu_hc_bench.models.deepspeech import HoistedGRU

    b, t, i, h = 2, 7, 5, 8
    x = jax.random.normal(jax.random.PRNGKey(3), (b, t, i))
    flax_rnn = flax.linen.RNN(flax.linen.GRUCell(h))
    fv = flax_rnn.init(jax.random.PRNGKey(4), x)
    cell = fv["params"]["cell"]
    fused = {
        "input_gates": {
            "kernel": jnp.concatenate(
                [cell[k]["kernel"] for k in ("ir", "iz", "in")], axis=-1),
            "bias": jnp.concatenate(
                [cell[k]["bias"] for k in ("ir", "iz", "in")], axis=-1),
        },
        "hidden_gates": jnp.concatenate(
            [cell[k]["kernel"] for k in ("hr", "hz", "hn")], axis=-1),
        "candidate_bias": cell["hn"]["bias"],
    }
    want = flax_rnn.apply(fv, x)
    got = HoistedGRU(h).apply({"params": fused}, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)
    # reverse direction == RNN over the time-flipped sequence, flipped back
    want_rev = jnp.flip(flax_rnn.apply(fv, jnp.flip(x, axis=1)), axis=1)
    got_rev = HoistedGRU(h, reverse=True).apply({"params": fused}, x)
    np.testing.assert_allclose(np.asarray(got_rev), np.asarray(want_rev),
                               rtol=1e-5, atol=1e-6)


def test_bidi_gru_matches_hoisted_pair():
    """BiHoistedGRU (both directions in one scan) must reproduce the sum
    of a forward + reverse HoistedGRU pair exactly when the params are
    copied across."""
    from tpu_hc_bench.models.deepspeech import BiHoistedGRU, HoistedGRU

    b, t, i, h = 2, 9, 5, 8
    x = jax.random.normal(jax.random.PRNGKey(5), (b, t, i))
    fwd = HoistedGRU(h)
    bwd = HoistedGRU(h, reverse=True)
    pf = fwd.init(jax.random.PRNGKey(6), x)["params"]
    pb = bwd.init(jax.random.PRNGKey(7), x)["params"]
    want = fwd.apply({"params": pf}, x) + bwd.apply({"params": pb}, x)
    stacked = {
        "fwd_input_gates": pf["input_gates"],
        "bwd_input_gates": pb["input_gates"],
        "fwd_hidden_gates": pf["hidden_gates"],
        "bwd_hidden_gates": pb["hidden_gates"],
        "fwd_candidate_bias": pf["candidate_bias"],
        "bwd_candidate_bias": pb["candidate_bias"],
    }
    got = BiHoistedGRU(h).apply({"params": stacked}, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


def test_deepspeech2_rnn_impl_arms():
    """All rnn_impl arms build and run; hoisted is the default and the
    flax arm stays as the A/B control."""
    from tpu_hc_bench.models import create_model

    x = jnp.zeros((2, 64, 32), jnp.float32)
    for impl in ("hoisted", "bidi", "flax"):
        model, _ = create_model("deepspeech2_tiny")
        model = model.clone(rnn_impl=impl)
        v = model.init(jax.random.PRNGKey(0), x, train=False)
        assert model.apply(v, x, train=False).shape == (2, 16, 29)
