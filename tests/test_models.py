"""Model-zoo tests: registry dispatch, output shapes, parameter counts.

Parameter counts are checked against the published architecture figures
(ResNet-50 25.6M, VGG-16 138.4M, Inception-v3 23.8M, BERT-base ~110M) —
a strong structural check that the fresh implementations match the
architectures tf_cnn_benchmarks drives.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_hc_bench import models
from tpu_hc_bench.models import bert


def n_params(tree):
    return sum(int(np.prod(x.shape)) for x in jax.tree.leaves(tree))


def init_model(name, image=None, num_classes=1000):
    model, spec = models.create_model(name, num_classes=num_classes)
    if spec.is_text:
        x = jnp.zeros((1, *spec.input_shape), jnp.int32)
    else:
        x = jnp.zeros((1, *spec.input_shape), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    return model, spec, variables, x


def test_registry_lists_reference_models():
    names = models.list_models()
    # resnet50 pinned by the reference (:34); inception3/vgg16/bert from
    # BASELINE.json configs; trivial from tf_cnn_benchmarks
    for required in ("resnet50", "inception3", "vgg16", "bert_base", "trivial"):
        assert required in names


def test_aliases():
    assert models.get_model_spec("bert").name == "bert_base"
    assert models.get_model_spec("inception_v3").name == "inception3"
    with pytest.raises(ValueError):
        models.get_model_spec("alexnet9000")


def test_trivial_forward():
    model, spec, variables, x = init_model("trivial")
    out = model.apply(variables, x, train=False)
    assert out.shape == (1, 1000)


def test_resnet50_params_and_shape():
    model, spec, variables, x = init_model("resnet50")
    count = n_params(variables["params"])
    assert abs(count - 25.6e6) / 25.6e6 < 0.01, count
    out = model.apply(variables, x, train=False)
    assert out.shape == (1, 1000)
    assert "batch_stats" in variables


def test_resnet18_params():
    _, _, variables, _ = init_model("resnet18")
    count = n_params(variables["params"])
    assert abs(count - 11.7e6) / 11.7e6 < 0.02, count


def test_vgg16_params():
    model, spec, variables, x = init_model("vgg16")
    count = n_params(variables["params"])
    assert abs(count - 138.4e6) / 138.4e6 < 0.01, count


def test_inception3_params_and_shape():
    model, spec, variables, x = init_model("inception3")
    count = n_params(variables["params"])
    # canonical inception_v3 (no aux head) is ~23.8M
    assert abs(count - 23.8e6) / 23.8e6 < 0.03, count
    out = model.apply(variables, x, train=False)
    assert out.shape == (1, 1000)


def test_alexnet_params_and_shape():
    model, spec, variables, x = init_model("alexnet")
    count = n_params(variables["params"])
    # single-tower AlexNet ~61M
    assert abs(count - 61e6) / 61e6 < 0.05, count
    out = model.apply(variables, x, train=False)
    assert out.shape == (1, 1000)


def test_googlenet_params_and_shape():
    model, spec, variables, x = init_model("googlenet")
    count = n_params(variables["params"])
    # GoogLeNet ~6.6M (no aux heads)
    assert abs(count - 6.6e6) / 6.6e6 < 0.1, count
    out = model.apply(variables, x, train=False)
    assert out.shape == (1, 1000)


def test_mobilenet_params_and_shape():
    model, spec, variables, x = init_model("mobilenet")
    count = n_params(variables["params"])
    # MobileNet v1 1.0/224 ~4.2M
    assert abs(count - 4.25e6) / 4.25e6 < 0.03, count
    out = model.apply(variables, x, train=False)
    assert out.shape == (1, 1000)


def test_densenet40_params_and_shape():
    model, spec, variables, x = init_model("densenet40_k12", num_classes=10)
    count = n_params(variables["params"])
    # Huang 2017 table 2: DenseNet (k=12) depth 40 ~ 1.0M
    assert abs(count - 1.0e6) / 1.0e6 < 0.1, count
    out = model.apply(variables, x, train=False)
    assert out.shape == (1, 10)


@pytest.mark.parametrize("name", ["lenet", "overfeat", "densenet100_k12"])
def test_small_zoo_forward(name):
    model, spec, variables, x = init_model(
        name, num_classes=10 if "densenet" in name else 1000)
    out = model.apply(variables, x, train=False)
    assert out.shape[0] == 1


def test_bert_base_params():
    model = bert.BertMLM()
    x = jnp.zeros((1, 128), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    count = n_params(variables["params"])
    # BERT-base ~110M (embeddings+encoder+mlm head, tied projection)
    assert 105e6 < count < 115e6, count
    out = model.apply(variables, x, train=False)
    assert out.shape == (1, 128, bert.BERT_BASE_VOCAB)


def test_bert_tiny_forward_train_mode():
    model = bert.bert_tiny_mlm()
    x = jnp.zeros((2, 16), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    out = model.apply(
        variables, x, train=True, rngs={"dropout": jax.random.PRNGKey(1)}
    )
    assert out.shape == (2, 16, 1024)


def test_bf16_compute_keeps_fp32_params_and_logits():
    model, spec = models.create_model("resnet18", dtype=jnp.bfloat16)
    x = jnp.zeros((1, 224, 224, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    for leaf in jax.tree.leaves(variables["params"]):
        assert leaf.dtype == jnp.float32
    out = model.apply(variables, x, train=False)
    assert out.dtype == jnp.float32
