"""Mixture-of-Experts routing + expert parallelism on the virtual mesh.

Routing invariants (capacity, gate normalization, aux loss) are checked
directly on ``top_k_routing``; the DP x EP path (expert dim sharded over
the mesh "model" axis, GSPMD all-to-all dispatch) is checked numerically
against the replicated GSPMD step, mirroring test_tensor_parallel.py.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_hc_bench import flags
from tpu_hc_bench._compat import CAPABILITIES
from tpu_hc_bench.data.synthetic import SyntheticTokens
from tpu_hc_bench.models import create_model
from tpu_hc_bench.models.moe import MoEFFN, top_k_routing
from tpu_hc_bench.topology import MODEL_AXIS, build_mesh, compute_layout
from tpu_hc_bench.train import step as step_mod


def test_routing_dispatch_invariants():
    b, s, e = 2, 16, 4
    c = s  # capacity == group size: overflow is impossible
    probs = jax.nn.softmax(
        jax.random.normal(jax.random.PRNGKey(0), (b, s, e)), axis=-1)
    dispatch, combine, aux = top_k_routing(probs, top_k=2, capacity=c)
    assert dispatch.shape == (b, s, e, c)
    # nothing dropped: every token occupies exactly top_k slots with
    # combine weights summing to 1
    np.testing.assert_allclose(dispatch.sum(axis=(2, 3)), 2.0, atol=1e-6)
    np.testing.assert_allclose(combine.sum(axis=(2, 3)), 1.0, atol=1e-6)
    # each expert slot holds at most one token (per group)
    assert float(dispatch.sum(axis=1).max()) <= 1.0 + 1e-6
    # aux loss is ~1 for near-balanced routing, >= 1 in general
    assert 0.5 < float(aux) < 4.0


def test_routing_respects_capacity():
    # all tokens prefer expert 0 -> only `capacity` survive there
    b, s, e, c = 1, 12, 4, 2
    logits = jnp.zeros((b, s, e)).at[..., 0].set(10.0)
    probs = jax.nn.softmax(logits, axis=-1)
    dispatch, combine, _ = top_k_routing(probs, top_k=1, capacity=c)
    assert float(dispatch[..., 0, :].sum()) == pytest.approx(c)
    # dropped tokens have zero combine weight (residual carries them)
    per_token = combine.sum(axis=(2, 3))[0]
    assert float(per_token[:c].min()) > 0.9
    np.testing.assert_allclose(per_token[c:], 0.0, atol=1e-6)


def test_moe_ffn_forward_backward():
    layer = MoEFFN(hidden=16, ffn=32, num_experts=4, top_k=2)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 24, 16))
    params = layer.init(jax.random.PRNGKey(2), x)["params"]

    def loss_fn(p):
        y, updated = layer.apply({"params": p}, x, mutable=["losses"])
        aux = sum(jnp.sum(t) for t in jax.tree.leaves(updated["losses"]))
        return jnp.sum(y ** 2) + 0.01 * aux

    loss, grads = jax.value_and_grad(loss_fn)(params)
    assert np.isfinite(float(loss))
    # router and both expert tensors receive gradient
    for name in ("router", "wi", "wo"):
        leaf = grads[name]["kernel"] if name == "router" else grads[name]
        assert float(jnp.abs(leaf).max()) > 0.0


def _setup(expert_parallel, devices, batch=8):
    layout = compute_layout(num_hosts=1, workers_per_host=len(devices),
                            chips_per_host=len(devices))
    mesh = build_mesh(layout, model_parallel=expert_parallel)
    cfg = flags.BenchmarkConfig(
        model="moe_tiny", batch_size=1, variable_update="replicated",
        expert_parallel=expert_parallel,
    ).resolve()
    model, spec = create_model("moe_tiny")
    raw = SyntheticTokens(batch, 32, vocab_size=1024, seed=0,
                          causal_lm=True).batch()
    state = step_mod.make_train_state(model, cfg, raw)
    if expert_parallel > 1:
        state = step_mod.shard_state_tp(state, mesh, mode="ep")
    else:
        state = step_mod.replicate_state(state, mesh)
    train_step = step_mod.build_train_step(mesh, cfg, spec)
    dev_batch = step_mod.shard_batch(raw, mesh)
    return state, train_step, dev_batch


def test_ep_param_spec_rules():
    spec = step_mod.tp_param_spec("layer_0/moe/wi", 3, mode="ep")
    assert spec[0] == MODEL_AXIS
    spec = step_mod.tp_param_spec("layer_0/moe/wo", 3, mode="ep")
    assert spec[0] == MODEL_AXIS
    # ep mode leaves the dense trunk replicated (unlike tp mode)
    assert (step_mod.tp_param_spec("layer_0/MultiHeadAttention_0/qkv/kernel",
                                   4, mode="ep")
            == jax.sharding.PartitionSpec())


def test_ep_matches_replicated(devices):
    rng = jax.random.PRNGKey(0)
    state_r, step_r, batch_r = _setup(1, devices)
    state_e, step_e, batch_e = _setup(4, devices)

    # expert tensors really are sharded over the model axis
    wi = state_e.params["layer_0"]["moe"]["wi"]
    assert wi.sharding.spec[0] == MODEL_AXIS

    losses = []
    for state, train_step, batch in ((state_r, step_r, batch_r),
                                     (state_e, step_e, batch_e)):
        for _ in range(3):
            state, metrics = train_step(state, batch, rng)
        losses.append(float(jax.device_get(metrics["loss"])))
    # the 0.4.x SPMD partitioner computes the expert-sharded forward with
    # a ~0.7% systematic loss offset vs the replicated arm (from step 0);
    # the modern partitioner is exact to 1e-4 — keep the wiring signal on
    # both stacks at the tolerance each can meet
    rtol = 1e-4 if CAPABILITIES["exact_gspmd_numerics"] else 2e-2
    np.testing.assert_allclose(losses[0], losses[1], rtol=rtol)


def test_ragged_matches_einsum_no_drops():
    """With capacity sized so nothing drops, the ragged (grouped-matmul)
    impl must equal the GShard einsum impl exactly (same routing, same
    gates; only the data movement differs)."""
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 16, 32))
    # capacity_factor = e/top_k makes capacity == s: overflow impossible
    kw = dict(hidden=32, ffn=64, num_experts=4, top_k=2,
              capacity_factor=2.0)
    einsum_layer = MoEFFN(**kw, impl="einsum")
    ragged_layer = MoEFFN(**kw, impl="ragged")
    params = einsum_layer.init(jax.random.PRNGKey(2), x)["params"]

    def run(layer):
        y, upd = layer.apply({"params": params}, x, mutable=["losses"])
        aux = sum(jnp.sum(t) for t in jax.tree.leaves(upd["losses"]))
        return y, aux

    y_e, aux_e = run(einsum_layer)
    y_r, aux_r = run(ragged_layer)
    np.testing.assert_allclose(np.asarray(y_e), np.asarray(y_r),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(float(aux_e), float(aux_r), rtol=1e-6)


def test_ragged_backward_and_no_drops():
    """Ragged impl: gradients flow to router and experts; capacity-free
    dispatch keeps every token (combine weights sum to 1)."""
    layer = MoEFFN(hidden=16, ffn=32, num_experts=4, top_k=2, impl="ragged")
    x = jax.random.normal(jax.random.PRNGKey(3), (2, 24, 16))
    params = layer.init(jax.random.PRNGKey(4), x)["params"]

    def loss_fn(p):
        y, _ = layer.apply({"params": p}, x, mutable=["losses"])
        return jnp.sum(y ** 2)

    loss, grads = jax.value_and_grad(loss_fn)(params)
    assert np.isfinite(float(loss))
    for name in ("router", "wi", "wo"):
        leaf = grads[name]["kernel"] if name == "router" else grads[name]
        assert float(jnp.abs(leaf).max()) > 0.0


def test_ragged_chunked_matches_unchunked():
    """The chunked grouped-matmul path (round 2: bounded VMEM via lax.map
    over sorted chunks) is bitwise-equivalent routing to the one-shot
    ragged_dot — only the matmul tiling differs."""
    x = jax.random.normal(jax.random.PRNGKey(7), (2, 32, 16))
    kw = dict(hidden=16, ffn=32, num_experts=4, top_k=2, impl="ragged")
    one_shot = MoEFFN(**kw, ragged_chunk=1 << 20)
    chunked = MoEFFN(**kw, ragged_chunk=16)     # 2*32*2=128 pairs -> 8 chunks
    params = one_shot.init(jax.random.PRNGKey(8), x)["params"]

    def run(layer, p):
        y, _ = layer.apply({"params": p}, x, mutable=["losses"])
        return y

    y1 = run(one_shot, params)
    y2 = run(chunked, params)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y2),
                               rtol=1e-5, atol=1e-6)
    # gradients flow through the chunked lax.map path too
    g = jax.grad(lambda p: jnp.sum(run(chunked, p) ** 2))(params)
    assert float(jnp.abs(g["wi"]).max()) > 0.0


def test_capacity_factor_plumbs_through():
    """--moe_capacity_factor reaches MoEFFN; lower factor drops tokens."""
    model, _ = create_model("moe_tiny", moe_capacity_factor=0.5)
    assert model.moe_capacity_factor == 0.5
    with pytest.raises(ValueError, match="MoE members"):
        create_model("gpt2", moe_capacity_factor=0.5)
    # behavioral: capacity 0.5 drops tokens that capacity 2.0 keeps
    x = jax.random.normal(jax.random.PRNGKey(9), (1, 32, 16))
    tight = MoEFFN(hidden=16, ffn=32, num_experts=4, top_k=2,
                   capacity_factor=0.25)
    roomy = MoEFFN(hidden=16, ffn=32, num_experts=4, top_k=2,
                   capacity_factor=2.0)
    params = tight.init(jax.random.PRNGKey(10), x)["params"]
    yt, _ = tight.apply({"params": params}, x, mutable=["losses"])
    yr, _ = roomy.apply({"params": params}, x, mutable=["losses"])
    assert not np.allclose(np.asarray(yt), np.asarray(yr))


def test_moe_impl_flag_guards():
    with pytest.raises(ValueError, match="moe_impl=einsum"):
        flags.BenchmarkConfig(expert_parallel=2, moe_impl="ragged").resolve()
    # capacity factor is an einsum-only concept: loud error, not silence
    with pytest.raises(ValueError, match="einsum dispatch only"):
        flags.BenchmarkConfig(moe_impl="ragged",
                              moe_capacity_factor=0.5).resolve()
    # TP also shards the expert tensors (tp_param_spec moe/ rules)
    with pytest.raises(ValueError, match="moe_impl=einsum"):
        flags.BenchmarkConfig(model_parallel=2, moe_impl="ragged").resolve()
    from tpu_hc_bench.models import create_model
    with pytest.raises(ValueError, match="MoE members"):
        create_model("gpt2", moe_impl="ragged")


def test_ep_exclusive_with_tp():
    with pytest.raises(ValueError, match="exclusive"):
        flags.BenchmarkConfig(model_parallel=2, expert_parallel=2).resolve()


def test_moe_impl_auto_translation():
    """--moe_impl=auto picks by the measured crossover (round 3,
    BASELINE.md): einsum short-seq/EP/TP, ragged at long seq."""
    from tpu_hc_bench import flags as fl

    cfg = fl.BenchmarkConfig(model="moe_tiny", moe_impl="auto").resolve()
    assert cfg.moe_impl == "einsum"              # short seq
    assert any("auto->einsum" in l for l in cfg.summary_lines())
    cfg = fl.BenchmarkConfig(model="gpt2_moe", moe_impl="auto",
                             seq_len=4096).resolve()
    assert cfg.moe_impl == "ragged"              # long seq, single-shard
    cfg = fl.BenchmarkConfig(model="gpt2_moe", moe_impl="auto",
                             seq_len=4096, expert_parallel=2).resolve()
    assert cfg.moe_impl == "einsum"              # EP needs GSPMD einsum


def test_ragged_f_chunk_matches_full_width():
    """The F-tiled grouped matmuls (round 4: slicing the [E,H,F]/[E,F,H]
    weights so Mosaic's scoped-VMEM never sees the full contraction) are
    numerically the full-width ragged path: gelu is elementwise over F
    and the second matmul's F-contraction distributes over slices.
    ffn=36 with chunk 8 also exercises the zero-padding tail."""
    x = jax.random.normal(jax.random.PRNGKey(9), (2, 16, 12))
    kw = dict(hidden=12, ffn=36, num_experts=4, top_k=2, impl="ragged")
    full = MoEFFN(**kw, ragged_f_chunk=0)
    tiled = MoEFFN(**kw, ragged_f_chunk=8)
    params = full.init(jax.random.PRNGKey(10), x)["params"]

    def run(layer, p):
        y, _ = layer.apply({"params": p}, x, mutable=["losses"])
        return y

    np.testing.assert_allclose(np.asarray(run(full, params)),
                               np.asarray(run(tiled, params)),
                               rtol=1e-5, atol=1e-6)
    g = jax.grad(lambda p: jnp.sum(run(tiled, p) ** 2))(params)
    assert float(jnp.abs(g["wi"]).max()) > 0.0
    # the tiled path also composes with row-chunking (the lax.map arm)
    both = MoEFFN(**kw, ragged_f_chunk=8, ragged_chunk=16)
    np.testing.assert_allclose(np.asarray(run(full, params)),
                               np.asarray(run(both, params)),
                               rtol=1e-5, atol=1e-6)
