"""True multi-process distributed test: 2 processes x 2 CPU devices.

Exercises the actual multi-host path end to end — the nodeips.txt hostfile
contract (parallel/distributed.py), jax.distributed bring-up, cross-process
mesh construction, and a fused gradient allreduce spanning both processes —
the closest CPU-only analog of a 2-host TPU pod run (SURVEY.md §4's
"multi-process simulation story").
"""

import os
import socket
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

# Deliberately NOT marked slow: this file is the repo's only true
# multi-process evidence (real OS processes, jax.distributed, cross-process
# collectives/checkpoints).  The ~4 min it adds to the default lane is the
# price of the advertised `pytest` command actually exercising the
# distributed path (round-3 verdict, next-round item 8).

from tpu_hc_bench._compat import CAPABILITIES

pytestmark = pytest.mark.skipif(
    not CAPABILITIES["cpu_multiprocess_collectives"],
    reason="this jax's CPU backend cannot execute cross-process "
           "collectives (XLA: 'Multiprocess computations aren't "
           "implemented on the CPU backend')")

REPO = Path(__file__).resolve().parent.parent

WORKER = textwrap.dedent("""
    import os, sys
    import tpu_hc_bench  # noqa: F401  (JAX version shims before config)
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 2)

    from tpu_hc_bench.parallel import distributed
    from tpu_hc_bench.parallel.collectives import fused_psum_tree
    from tpu_hc_bench import topology
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    port = int(sys.argv[1])
    distributed.initialize(coordinator_port=port)  # env-driven hostfile

    assert jax.process_count() == 2, jax.process_count()
    assert jax.device_count() == 4
    layout = topology.discover_layout(workers_per_host=0)
    assert layout.num_hosts == 2 and layout.total_workers == 4, layout
    mesh = topology.build_mesh(layout)

    f = jax.jit(jax.shard_map(
        lambda t: fused_psum_tree(t, threshold_bytes=64, average=True),
        mesh=mesh, in_specs=P(topology.DATA_AXIS),
        out_specs=P(topology.DATA_AXIS), check_vma=False,
    ))
    tree = {"g": jnp.arange(8.0).reshape(4, 2), "b": jnp.ones((4, 3))}
    out = f(tree)
    import numpy as np
    # the global array spans both processes; verify this process's shards
    want_row = np.mean(np.arange(8.0).reshape(4, 2), axis=0)  # [3., 4.]
    for shard in out["g"].addressable_shards:
        np.testing.assert_allclose(np.asarray(shard.data)[0], want_row)
    print(f"MP_OK process={jax.process_index()}", flush=True)
""")


PP_WORKER = textwrap.dedent("""
    import os, sys
    import tpu_hc_bench  # noqa: F401  (JAX version shims before config)
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 2)

    from tpu_hc_bench.parallel import distributed
    from tpu_hc_bench import topology

    port = int(sys.argv[1])
    distributed.initialize(coordinator_port=port)
    assert jax.process_count() == 2 and jax.device_count() == 4

    from tpu_hc_bench import flags
    from tpu_hc_bench.data.synthetic import SyntheticTokens
    from tpu_hc_bench.models.gpt import GPTLM
    from tpu_hc_bench.parallel import pipeline as pp

    layout = topology.discover_layout(workers_per_host=0)
    # minor (pipe) axis = adjacent chips -> intra-host ppermute hops;
    # the data axis crosses the two processes (the DCN analog)
    mesh = topology.build_mesh(layout, pipeline_parallel=2)
    cfg = flags.BenchmarkConfig(model="gpt2", batch_size=2,
                                pipeline_parallel=2).resolve()
    model = GPTLM(vocab_size=64, hidden=32, num_layers=2, heads=4, ffn=64,
                  max_len=16)
    batch = SyntheticTokens(4, 16, vocab_size=64, causal_lm=True).batch()
    params, opt_state = pp.make_pp_state(model, cfg, batch[0], mesh)
    step, _ = pp.build_pp_train_step(mesh, model, cfg, 2, params, opt_state,
                                     deterministic=True)
    params, opt_state, loss = step(params, opt_state, batch)
    loss = float(jax.device_get(loss))
    assert loss == loss, "pp loss is NaN"
    print(f"MP_PP_OK process={jax.process_index()} loss={loss:.4f}",
          flush=True)
""")


TP_WORKER = textwrap.dedent("""
    import os, sys
    import tpu_hc_bench  # noqa: F401  (JAX version shims before config)
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 2)

    from tpu_hc_bench.parallel import distributed
    from tpu_hc_bench import topology

    port = int(sys.argv[1])
    distributed.initialize(coordinator_port=port)
    assert jax.process_count() == 2 and jax.device_count() == 4

    from tpu_hc_bench import flags
    from tpu_hc_bench.data.synthetic import SyntheticTokens
    from tpu_hc_bench.models import create_model
    from tpu_hc_bench.train import step as step_mod

    layout = topology.discover_layout(workers_per_host=0)
    # model axis = adjacent chips (intra-process Megatron all-reduces);
    # the data-axis gradient psum crosses the process boundary (DCN analog)
    mesh = topology.build_mesh(layout, model_parallel=2)
    cfg = flags.BenchmarkConfig(model="bert_tiny", batch_size=1,
                                model_parallel=2).resolve()
    model, spec = create_model("bert_tiny")
    raw = SyntheticTokens(2, 32, vocab_size=1024, seed=0).batch()
    state = step_mod.make_train_state(model, cfg, raw)
    state = step_mod.shard_state_tp(state, mesh)
    qkv = state.params["layer_0"]["MultiHeadAttention_0"]["qkv"]["kernel"]
    assert topology.MODEL_AXIS in qkv.sharding.spec
    train_step = step_mod.build_train_step(mesh, cfg, spec)
    state, metrics = train_step(state, step_mod.shard_batch(raw, mesh),
                                jax.random.PRNGKey(0))
    loss = float(jax.device_get(metrics["loss"]))
    assert loss == loss, "tp loss is NaN"
    print(f"MP_TP_OK process={jax.process_index()} loss={loss:.4f}",
          flush=True)
""")


DCN_WORKER = textwrap.dedent("""
    import os, sys
    import tpu_hc_bench  # noqa: F401  (JAX version shims before config)
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 2)

    from tpu_hc_bench.parallel import distributed
    from tpu_hc_bench import topology

    port = int(sys.argv[1])
    distributed.initialize(coordinator_port=port)
    assert jax.process_count() == 2 and jax.device_count() == 4

    from tpu_hc_bench import flags
    from tpu_hc_bench.data.synthetic import SyntheticImages
    from tpu_hc_bench.models import create_model
    from tpu_hc_bench.train import step as step_mod

    layout = topology.discover_layout(workers_per_host=0)
    # MULTISLICE: each process is one slice; the dcn axis IS the process
    # boundary, the data axis stays inside each process ("slice ICI")
    mesh = topology.build_mesh(layout, num_slices=2)
    assert mesh.axis_names[:2] == (topology.DCN_AXIS, topology.DATA_AXIS)
    assert mesh.shape[topology.DCN_AXIS] == 2
    for dev in mesh.devices[0].ravel():
        assert dev.process_index == 0   # slice 0 == process 0: boundary real
    cfg = flags.BenchmarkConfig(model="trivial", num_classes=10,
                                batch_size=1).resolve()
    model, spec = create_model("trivial", num_classes=10)
    batch = SyntheticImages(4, (8, 8, 3), num_classes=10).batch()
    state = step_mod.make_train_state(model, cfg, batch)
    state = step_mod.replicate_state(state, mesh)
    train_step = step_mod.build_train_step(mesh, cfg, spec)
    state, metrics = train_step(state, step_mod.shard_batch(batch, mesh),
                                jax.random.PRNGKey(0))
    loss = float(jax.device_get(metrics["loss"]))
    assert loss == loss, "multislice loss is NaN"
    print(f"MP_DCN_OK process={jax.process_index()} loss={loss:.4f}",
          flush=True)
""")


CKPT_WORKER = textwrap.dedent("""
    import os, sys
    import tpu_hc_bench  # noqa: F401  (JAX version shims before config)
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 2)

    from tpu_hc_bench.parallel import distributed
    from tpu_hc_bench import flags
    from tpu_hc_bench.train import driver

    port = int(sys.argv[1])
    train_dir = sys.argv[2]      # the shared filesystem (same box)
    distributed.initialize(coordinator_port=port)
    assert jax.process_count() == 2

    def run():
        cfg = flags.BenchmarkConfig(
            model="trivial", num_classes=10, batch_size=1,
            num_warmup_batches=1, num_batches=2, display_every=1,
            train_dir=train_dir).resolve()
        out = []
        driver.run_benchmark(cfg, print_fn=out.append)
        return "\\n".join(out)

    text = run()
    assert "filesystem shared by all hosts" in text
    if jax.process_index() == 0:
        assert "checkpoint saved" in text
    # barrier: process 1 must not start the resume run before process
    # 0's save lands (between-RUNS ordering is the operator's job on a
    # real pod; inside one program we sync explicitly)
    from jax.experimental import multihost_utils
    multihost_utils.sync_global_devices("ckpt_written")
    # second run resumes from the shared checkpoint on BOTH processes
    text = run()
    assert "restored checkpoint step 3" in text, text
    print(f"MP_CKPT_OK process={jax.process_index()}", flush=True)
""")


SHARDED_CKPT_WORKER = textwrap.dedent("""
    import sys
    import tpu_hc_bench  # noqa: F401  (JAX version shims before config)
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 2)
    import numpy as np

    from tpu_hc_bench.parallel import distributed
    from tpu_hc_bench import flags, topology
    from tpu_hc_bench.data.synthetic import SyntheticTokens
    from tpu_hc_bench.models import create_model
    from tpu_hc_bench.train import step as step_mod
    from tpu_hc_bench.utils import checkpoint as ckpt

    port = int(sys.argv[1]); ckpt_dir = sys.argv[2]
    distributed.initialize(coordinator_port=port)
    assert jax.process_count() == 2 and jax.device_count() == 4

    # TP state across 2 processes: params sharded over the model axis,
    # shards NOT addressable from one host — the sharded-save case
    layout = topology.discover_layout(workers_per_host=0)
    mesh = topology.build_mesh(layout, model_parallel=4)
    cfg = flags.BenchmarkConfig(model="bert_tiny", batch_size=1,
                                model_parallel=4).resolve()
    model, spec = create_model("bert_tiny")
    raw = SyntheticTokens(1, 32, vocab_size=1024).batch()
    state = step_mod.make_train_state(model, cfg, raw)
    state = step_mod.shard_state_tp(state, mesh)
    qkv = state.params["layer_0"]["MultiHeadAttention_0"]["qkv"]["kernel"]
    assert not qkv.is_fully_addressable        # the real multi-host case
    state = state.replace(step=jax.numpy.ones((), jax.numpy.int32) * 7)

    ckpt.save(state, ckpt_dir, sharded=True)   # ALL processes call

    # restore into a zeroed placed template with the SAME shardings
    zeros = jax.tree.map(lambda x: jax.device_put(
        np.zeros(x.shape, x.dtype), x.sharding), state.params)
    template = state.replace(params=zeros)
    back = ckpt.restore(template, ckpt_dir, sharded=True)
    assert int(jax.device_get(back.step)) == 7
    got = back.params["layer_0"]["MultiHeadAttention_0"]["qkv"]["kernel"]
    # compare this process's addressable shards
    want = {s.index: np.asarray(s.data) for s in qkv.addressable_shards}
    for s in got.addressable_shards:
        np.testing.assert_allclose(np.asarray(s.data), want[s.index],
                                   rtol=1e-6)
    print(f"MP_SHARDED_CKPT_OK process={jax.process_index()}", flush=True)
""")


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_two_workers(tmp_path, worker_src, ok_marker, extra_args=()):
    hostfile = tmp_path / "nodeips.txt"
    hostfile.write_text("127.0.0.1\n127.0.0.1\n")
    script = tmp_path / "worker.py"
    script.write_text(worker_src)
    port = free_port()

    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.update({
            "TPU_HC_BENCH_HOSTFILE": str(hostfile),
            "TPU_HC_BENCH_PROCESS_ID": str(pid),
            "PYTHONPATH": f"{REPO}:{env.get('PYTHONPATH', '')}",
            "JAX_PLATFORMS": "cpu",
        })
        procs.append(subprocess.Popen(
            [sys.executable, str(script), str(port), *map(str, extra_args)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        ))
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=180)
            outs.append(out)
    except subprocess.TimeoutExpired as e:
        # one worker hanging must not leak its sibling (it would wedge CI);
        # kill everything, then drain ALL pipes — including the partial
        # output attached to the timeout itself and any already-exited
        # sibling not yet communicate()d
        if e.output is not None:
            # TimeoutExpired carries bytes even under text=True
            outs.append(e.output.decode(errors="replace")
                        if isinstance(e.output, bytes) else e.output)
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs[len(outs):]:
            out, _ = p.communicate()
            outs.append(out)
        for p in procs:         # reap the killed timed-out process too
            if p.returncode is None:
                p.wait()
        import pytest
        pytest.fail("worker timed out; captured output:\n" + "\n---\n".join(outs))
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {i} failed:\n{out}"
        assert ok_marker in out
    return outs


def test_two_process_hostfile_allreduce(tmp_path):
    _run_two_workers(tmp_path, WORKER, "MP_OK")


HOST_FABRIC_WORKER = textwrap.dedent("""
    import sys
    import tpu_hc_bench  # noqa: F401  (JAX version shims before config)
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 2)
    import numpy as np

    from tpu_hc_bench.parallel import distributed, fabric as fabric_mod
    from tpu_hc_bench import flags, topology
    from tpu_hc_bench.data.synthetic import SyntheticImages
    from tpu_hc_bench.models import create_model
    from tpu_hc_bench.train import step as step_mod

    port = int(sys.argv[1])
    distributed.initialize(coordinator_port=port)
    assert jax.process_count() == 2 and jax.device_count() == 4

    layout = topology.discover_layout(workers_per_host=0)
    mesh = topology.build_mesh(layout)
    cfg = flags.BenchmarkConfig(model="trivial", num_classes=10,
                                batch_size=1).resolve()
    model, spec = create_model("trivial", num_classes=10)
    batch = SyntheticImages(4, (8, 8, 3), num_classes=10).batch()
    state = step_mod.make_train_state(model, cfg, batch)
    state = step_mod.replicate_state(state, mesh)
    # the sock analog at world > 1: stacked grads span BOTH processes, so
    # host_allreduce must reduce local shards then cross hosts
    train_step = step_mod.build_train_step(mesh, cfg, spec,
                                           fabric_mod.Fabric.HOST)
    state, metrics = train_step(state, step_mod.shard_batch(batch, mesh),
                                jax.random.PRNGKey(0))
    loss = float(jax.device_get(metrics["loss"]))
    assert loss == loss, "host-fabric loss is NaN"
    digest = float(sum(np.abs(np.asarray(jax.device_get(x))).sum()
                       for x in jax.tree.leaves(state.params)))
    print(f"MP_HOST_OK process={jax.process_index()} loss={loss:.6f} "
          f"digest={digest:.6f}", flush=True)
""")


def test_two_process_host_fabric_step(tmp_path):
    """fabric=host (the reference's sock) across 2 real processes: each
    host reduces its addressable shards, partial sums cross hosts via one
    process_allgather, and the post-update params are bit-identical on
    both ranks (same digest) — the slow arm of the scaling table's fabric
    flip, working at world > 1."""
    outs = _run_two_workers(tmp_path, HOST_FABRIC_WORKER, "MP_HOST_OK")
    import re

    digests = sorted(re.search(r"digest=([\d.]+)", o).group(1) for o in outs)
    assert digests[0] == digests[1], digests


def test_two_process_pipeline_step(tmp_path):
    """DP x PP across 2 processes: pipe hops intra-process, the data-axis
    gradient psum crosses the process boundary (the DCN analog)."""
    _run_two_workers(tmp_path, PP_WORKER, "MP_PP_OK")


def test_two_process_checkpoint_roundtrip(tmp_path):
    """--train_dir across 2 real processes: process 0 writes the
    replicated-DP checkpoint, BOTH processes resume from the shared
    filesystem (round 3: the multi-process checkpoint policy)."""
    _run_two_workers(tmp_path, CKPT_WORKER, "MP_CKPT_OK",
                     extra_args=[tmp_path / "shared_ckpt"])


TP_CKPT_WORKER = textwrap.dedent("""
    import sys
    import tpu_hc_bench  # noqa: F401  (JAX version shims before config)
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 2)

    from tpu_hc_bench.parallel import distributed
    from tpu_hc_bench import flags
    from tpu_hc_bench.train import driver

    port = int(sys.argv[1]); train_dir = sys.argv[2]
    distributed.initialize(coordinator_port=port)

    def run():
        cfg = flags.BenchmarkConfig(
            model="bert_tiny", batch_size=1, model_parallel=2,
            num_warmup_batches=1, num_batches=2, display_every=1,
            train_dir=train_dir).resolve()
        out = []
        driver.run_benchmark(cfg, print_fn=out.append)
        return "\\n".join(out)

    text = run()
    assert "sharded Orbax I/O" in text, text
    assert "checkpoint saved" in text
    from jax.experimental import multihost_utils
    multihost_utils.sync_global_devices("tp_ckpt_written")
    text = run()
    assert "restored checkpoint step 3" in text, text
    print(f"MP_TP_CKPT_OK process={jax.process_index()}", flush=True)
""")


SP_CKPT_WORKER = textwrap.dedent("""
    import sys
    import tpu_hc_bench  # noqa: F401  (JAX version shims before config)
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 2)

    from tpu_hc_bench.parallel import distributed
    from tpu_hc_bench import flags
    from tpu_hc_bench.train import driver

    port = int(sys.argv[1]); train_dir = sys.argv[2]
    distributed.initialize(coordinator_port=port)

    def run():
        cfg = flags.BenchmarkConfig(
            model="bert_tiny", batch_size=1, sequence_parallel=2,
            num_warmup_batches=1, num_batches=2, display_every=1,
            train_dir=train_dir).resolve()
        out = []
        driver.run_benchmark(cfg, print_fn=out.append)
        return "\\n".join(out)

    text = run()
    assert "process 0 writes" in text, text    # SP state is REPLICATED
    from jax.experimental import multihost_utils
    multihost_utils.sync_global_devices("sp_ckpt_written")
    text = run()
    assert "restored checkpoint step 3" in text, text
    print(f"MP_SP_CKPT_OK process={jax.process_index()}", flush=True)
""")


def test_two_process_sp_train_dir_roundtrip(tmp_path):
    """--train_dir --sequence_parallel across 2 real processes: SP keeps
    params fully REPLICATED, so the plain process-0-writes path must work
    — this test pins that invariant (a future SP-step change that shards
    params would fail here, not corrupt checkpoints silently)."""
    _run_two_workers(tmp_path, SP_CKPT_WORKER, "MP_SP_CKPT_OK",
                     extra_args=[tmp_path / "sp_ckpt"])


def test_two_process_tp_train_dir_roundtrip(tmp_path):
    """--train_dir --model_parallel across 2 real processes: the driver
    takes the sharded-Orbax path end to end (save during training,
    sharded restore-after-placement on resume)."""
    _run_two_workers(tmp_path, TP_CKPT_WORKER, "MP_TP_CKPT_OK",
                     extra_args=[tmp_path / "tp_ckpt"])


def test_two_process_sharded_checkpoint(tmp_path):
    """Sharded (multi-host TP) checkpointing: live jax.Arrays handed to
    Orbax, each process writing/reading only its addressable shards."""
    _run_two_workers(tmp_path, SHARDED_CKPT_WORKER, "MP_SHARDED_CKPT_OK",
                     extra_args=[tmp_path / "sharded_ckpt"])


def test_two_process_multislice_step(tmp_path):
    """fabric=dcn's layout across 2 REAL processes: the dcn axis is the
    process boundary, gradients reduce hierarchically over (dcn, data)."""
    _run_two_workers(tmp_path, DCN_WORKER, "MP_DCN_OK")


def test_two_process_tensor_parallel_step(tmp_path):
    """DP x TP across 2 processes: Megatron all-reduces intra-process on
    the model axis, the gradient reduction crossing the process boundary —
    multi-host tensor parallelism end to end."""
    _run_two_workers(tmp_path, TP_WORKER, "MP_TP_OK")


PP_NATIVE_CKPT_WORKER = textwrap.dedent("""
    import sys
    import tpu_hc_bench  # noqa: F401  (JAX version shims before config)
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 2)

    from tpu_hc_bench.parallel import distributed
    from tpu_hc_bench import flags
    from tpu_hc_bench.train import driver

    port = int(sys.argv[1]); train_dir = sys.argv[2]
    distributed.initialize(coordinator_port=port)
    assert jax.process_count() == 2 and jax.device_count() == 4

    def run(**kw):
        cfg = flags.BenchmarkConfig(
            model="llama_tiny", batch_size=4, pipeline_parallel=4,
            num_warmup_batches=1, num_batches=2, display_every=1,
            train_dir=train_dir, **kw).resolve()
        out = []
        res = driver.run_benchmark(cfg, print_fn=out.append)
        return "\\n".join(out), res

    # pipe axis spans BOTH processes (4 stages over 2x2 devices): the
    # stacked trunk is NOT fully addressable -> the PP-native sharded path
    text, _ = run()
    assert "PP-native sharded Orbax" in text, text
    assert "checkpoint saved" in text and "(PP-native)" in text
    from jax.experimental import multihost_utils
    multihost_utils.sync_global_devices("pp_native_written")
    text, res = run()
    assert "restored checkpoint step 3" in text, text
    import numpy as np
    assert np.isfinite(res.final_loss)
    # eval restores params-only from the same PP-native checkpoint
    multihost_utils.sync_global_devices("pp_native_resumed")
    text, res = run(eval=True)
    assert "restored checkpoint step" in text, text
    assert "top_1 accuracy" in text
    print(f"MP_PP_CKPT_OK process={jax.process_index()}", flush=True)
""")


def test_two_process_pp_native_train_dir_roundtrip(tmp_path):
    """Round 4 (closes the driver's multi-host-PP --train_dir rejection):
    --train_dir --pipeline_parallel across 2 real processes with the pipe
    axis crossing the process boundary — save_pp writes each process's
    trunk shards, resume restores into the committed shardings, and eval
    restores params-only, all through run_benchmark."""
    _run_two_workers(tmp_path, PP_NATIVE_CKPT_WORKER, "MP_PP_CKPT_OK",
                     extra_args=[tmp_path / "pp_native_ckpt"])


SPTP_CKPT_WORKER = textwrap.dedent("""
    import sys
    import tpu_hc_bench  # noqa: F401  (JAX version shims before config)
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 2)

    from tpu_hc_bench.parallel import distributed
    from tpu_hc_bench import flags
    from tpu_hc_bench.train import driver

    port = int(sys.argv[1]); train_dir = sys.argv[2]
    distributed.initialize(coordinator_port=port)
    assert jax.process_count() == 2 and jax.device_count() == 4

    def run():
        cfg = flags.BenchmarkConfig(
            model="bert_tiny", batch_size=4, sequence_parallel=2,
            model_parallel=2, num_warmup_batches=1, num_batches=2,
            display_every=1, train_dir=train_dir).resolve()
        out = []
        driver.run_benchmark(cfg, print_fn=out.append)
        return "\\n".join(out)

    # DP x SP x TP hybrid: params are model-SHARDED (auto axis) across
    # both processes -> the sharded-Orbax restore-after-placement path
    text = run()
    assert "sharded Orbax I/O" in text, text
    assert "checkpoint saved" in text
    from jax.experimental import multihost_utils
    multihost_utils.sync_global_devices("sptp_ckpt_written")
    text = run()
    assert "restored checkpoint step 3" in text, text
    print(f"MP_SPTP_CKPT_OK process={jax.process_index()}", flush=True)
""")


def test_two_process_sptp_train_dir_roundtrip(tmp_path):
    """Round 4 (closes the multi-host SPxTP --train_dir rejection): the
    hybrid's model-sharded state saves/restores through the same sharded
    Orbax path as plain TP, with restore AFTER placement."""
    _run_two_workers(tmp_path, SPTP_CKPT_WORKER, "MP_SPTP_CKPT_OK",
                     extra_args=[tmp_path / "sptp_ckpt"])
