"""Multislice (fabric=dcn) mechanism: mesh shape, step parity, guards.

Round-3 (VERDICT #6): ``fabric=dcn`` now selects a real layout — a
leading ``dcn`` mesh axis splitting the data dimension — instead of only
printing a different banner.  The cross-PROCESS form lives in
tests/test_multiprocess.py::test_two_process_multislice_step.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_hc_bench import flags, topology
from tpu_hc_bench.train import driver


def test_multislice_mesh_shape(devices):
    layout = topology.compute_layout(1, 8, 8)
    mesh = topology.build_mesh(layout, num_slices=2)
    assert mesh.axis_names[:2] == (topology.DCN_AXIS, topology.DATA_AXIS)
    assert mesh.shape[topology.DCN_AXIS] == 2
    assert mesh.shape[topology.DATA_AXIS] == 4

    with pytest.raises(ValueError, match="num_slices"):
        topology.build_mesh(layout, num_slices=3)   # 8 % 3
    # on a multi-host layout, slices must be contiguous host groups
    with pytest.raises(ValueError, match="does not divide"):
        topology.build_mesh(topology.compute_layout(2, 0, 4), num_slices=3)


def _run(fabric, **kw):
    cfg = flags.BenchmarkConfig(
        model="trivial", num_classes=10, batch_size=2,
        num_warmup_batches=1, num_batches=3, display_every=1, **kw,
    ).resolve()
    out = []
    res = driver.run_benchmark(cfg, fabric_name=fabric, print_fn=out.append)
    return res, "\n".join(out)


def test_dcn_driver_matches_ici(mesh8):
    """fabric=dcn with 2 virtual slices trains and reaches the same loss
    as the plain ICI run (same global batch, same math — the hierarchical
    (dcn, data) reduction must equal the flat data reduction)."""
    res_ici, _ = _run("ici")
    res_dcn, text = _run("dcn", num_slices=2)
    assert "multislice: 2 slices" in text
    assert "dcn(2) x data(4)" in text
    np.testing.assert_allclose(res_dcn.final_loss, res_ici.final_loss,
                               rtol=1e-5)


def test_dcn_gspmd_arm_matches(mesh8):
    """--variable_update=replicated keeps its GSPMD arm under multislice
    (batch sharded over (dcn, data); XLA inserts the hierarchical
    reduction itself)."""
    res_ici, _ = _run("ici", variable_update="replicated")
    res_dcn, text = _run("dcn", num_slices=2, variable_update="replicated")
    assert "multislice: 2 slices" in text
    np.testing.assert_allclose(res_dcn.final_loss, res_ici.final_loss,
                               rtol=1e-5)


def test_dcn_guards(mesh8):
    with pytest.raises(ValueError, match="requires fabric=dcn"):
        _run("ici", num_slices=2)
    with pytest.raises(ValueError, match="data parallelism only"):
        _run("dcn", num_slices=2, model_parallel=2)
    # --eval under multislice is no longer rejected:
    # test_multislice_eval_matches_ici pins its parity with ICI eval


def test_dcn_single_host_degenerates(mesh8):
    """One host => one slice: dcn behaves as before (banner, same mesh)."""
    res, text = _run("dcn")
    assert "multislice" not in text
    assert np.isfinite(res.final_loss)


def test_multislice_eval_matches_ici(mesh8, tmp_path):
    """Round 4: --eval under multislice dcn — the (dcn, data) eval arm
    reports the same accuracy/loss as plain ICI eval of the same
    checkpoint (the hierarchical metric psum must equal the flat one)."""
    train_dir = str(tmp_path / "ms_eval")
    cfg = flags.BenchmarkConfig(
        model="trivial", num_classes=10, batch_size=2,
        num_warmup_batches=1, num_batches=3, display_every=1,
        train_dir=train_dir).resolve()
    driver.run_benchmark(cfg, print_fn=lambda _: None)

    def run_eval(fabric, **kw):
        out = []
        cfg = flags.BenchmarkConfig(
            model="trivial", num_classes=10, batch_size=2, eval=True,
            num_warmup_batches=1, num_batches=2, display_every=1,
            train_dir=train_dir, **kw).resolve()
        res = driver.run_benchmark(cfg, fabric_name=fabric,
                                   print_fn=out.append)
        return res, [l for l in out if "top_1 accuracy" in l][0]

    res_ici, top1_ici = run_eval("ici")
    res_dcn, top1_dcn = run_eval("dcn", num_slices=2)
    assert top1_dcn == top1_ici
    np.testing.assert_allclose(res_dcn.final_loss, res_ici.final_loss,
                               rtol=1e-5)
