"""Native C++ TFRecord scanner tests (builds the .so via make on first use)."""

import numpy as np
import pytest

from tpu_hc_bench import native
from tpu_hc_bench.data import tfrecord

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native build unavailable (no g++/make)"
)


def test_native_crc_matches_python():
    for data in (b"", b"123456789", b"\x00" * 32, bytes(range(256)) * 7):
        assert native.crc32c(data) == tfrecord.crc32c(data)


def test_index_and_read_roundtrip(tmp_path):
    path = tmp_path / "t.tfrecord"
    records = [b"a", b"b" * 100, b"", b"c" * 10000]
    tfrecord.write_records(path, records)
    offsets, lengths = native.index_tfrecord(path)
    assert len(offsets) == 4
    assert list(lengths) == [1, 100, 0, 10000]
    back = native.read_records_native(path)
    assert back == records


def test_native_detects_corruption(tmp_path):
    path = tmp_path / "bad.tfrecord"
    tfrecord.write_records(path, [b"payload-x"])
    raw = bytearray(path.read_bytes())
    raw[-6] ^= 0xFF
    path.write_bytes(bytes(raw))
    with pytest.raises(IOError):
        native.index_tfrecord(path, verify=True)
    # without verification the corrupt record still indexes
    offsets, lengths = native.index_tfrecord(path, verify=False)
    assert len(offsets) == 1


def test_empty_file(tmp_path):
    path = tmp_path / "empty.tfrecord"
    path.write_bytes(b"")
    offsets, lengths = native.index_tfrecord(path)
    assert len(offsets) == 0


def test_large_file_throughput(tmp_path):
    """Native path handles a multi-MB shard and agrees with Python."""
    path = tmp_path / "big.tfrecord"
    rng = np.random.default_rng(0)
    records = [rng.bytes(4096) for _ in range(512)]  # 2 MiB
    tfrecord.write_records(path, records)
    native_recs = native.read_records_native(path)
    py_recs = list(tfrecord.read_records(path, verify_crc=True))
    assert native_recs == py_recs


def test_native_jpeg_matches_pil_pipeline():
    """Native decode+crop+resize draws the same augmentation stream and
    lands within JPEG/bilinear tolerance of the PIL fallback."""
    import io

    import numpy as np
    from PIL import Image

    from tpu_hc_bench import native
    from tpu_hc_bench.data import imagenet

    if not native.jpeg_available():
        import pytest

        pytest.skip("native jpeg decoder unavailable")

    # smooth gradient: resampling-path differences (DCT-scaled decode vs
    # full-res PIL) stay small on natural-image-like content; random noise
    # would amplify them
    yy, xx = np.mgrid[0:280, 0:350]
    img = np.stack([
        (xx * 255 / 350), (yy * 255 / 280), ((xx + yy) * 255 / 630)
    ], axis=-1).astype(np.uint8)
    buf = io.BytesIO()
    Image.fromarray(img).save(buf, "JPEG", quality=95)
    data = buf.getvalue()

    for train in (True, False):
        a = imagenet._decode_and_crop(
            data, 64, np.random.default_rng(7), train, normalize=False)
        b = imagenet._decode_and_crop_pil(
            data, 64, np.random.default_rng(7), train, normalize=False)
        assert a.shape == b.shape == (64, 64, 3)
        assert a.dtype == b.dtype == np.uint8
        diff = np.abs(a.astype(int) - b.astype(int))
        assert diff.mean() < 3.0, diff.mean()
