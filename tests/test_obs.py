"""The runtime-observability subsystem: trace analysis, metrics, CLI.

Four layers, matching the acceptance contract:

1. ``obs.trace`` against a HAND-BUILT synthetic perfetto fixture: step
   reconstruction (step track + envelope fallback), per-step bucket
   attribution (compute/collective/host-transfer/idle-bubble, with the
   hand-computed numbers), and the same-tid containment rule (a long
   leaf overlapping siblings on ANOTHER track must be kept; a real
   container on its OWN track must be dropped).
2. ``obs.metrics`` + the ``python -m tpu_hc_bench.obs`` CLI on fixture
   runs: summarize renders, diff reports per-bucket deltas
   ("collective +40%, compute flat").
3. End-to-end: a real (CPU-mesh) driver run with ``--metrics_dir``
   produces a JSONL + manifest that summarize renders and diff compares;
   ``--profile_steps`` drives the windowed profiler through its single
   stop path.
4. Repo hygiene: no bytecode artifacts are ever tracked (the satellite
   that deleted the stale ``scripts/__pycache__``).
"""

from __future__ import annotations

import gzip
import io
import json
import subprocess
import sys
from pathlib import Path

import pytest

from tpu_hc_bench import flags
from tpu_hc_bench.obs import metrics as obs_metrics
from tpu_hc_bench.obs import trace as obs_trace
from tpu_hc_bench.obs.__main__ import main as obs_main
from tpu_hc_bench.train import driver

REPO = Path(__file__).resolve().parent.parent


# ---------------------------------------------------------------------
# the synthetic perfetto fixture
#
# Device pid 100.  Track (100, 1) = compute stream, track (100, 2) = a
# concurrent DMA-style stream, track (100, 9) = the profiler's "Steps"
# track.  Two steps:
#
#   step 0, span [0, 100):
#     tid 1: fusion.1      [0, 40)    compute
#            all-reduce.2  [45, 75)   collective
#            mult.7        [76, 79)   compute
#            infeed.3      [80, 90)   host-transfer
#     tid 2: copy-done.5   [40, 90)   compute — strictly contains
#            all-reduce.2 and mult.7 on the OTHER track; the same-tid
#            rule must keep it (nothing on its own track is inside it)
#     busy union [0, 90) -> idle-bubble 10
#   step 1, span [120, 220):
#     tid 1: fusion.1      [120, 170) compute
#            all-reduce.2  [175, 215) collective
#     busy union 90 -> idle-bubble 10
#
# Hand totals: compute 93 + 50 = 143, collective 70, host-transfer 10,
# idle 20.

STEP_SPANS = [(0, 100), (120, 220)]
STEP0 = {"compute": 93.0, "collective": 30.0, "host-transfer": 10.0,
         "idle-bubble": 10.0}
STEP1 = {"compute": 50.0, "collective": 40.0, "host-transfer": 0.0,
         "idle-bubble": 10.0}


def _x(pid, tid, name, ts, dur):
    return {"ph": "X", "pid": pid, "tid": tid, "name": name,
            "ts": ts, "dur": dur}


def fixture_events(with_step_track: bool = True) -> list[dict]:
    events = [
        {"ph": "M", "pid": 100, "name": "process_name",
         "args": {"name": "/device:TPU:0 (chip 0)"}},
        {"ph": "M", "pid": 1, "name": "process_name",
         "args": {"name": "python"}},
        # host-side event that must never be attributed
        _x(1, 7, "hostfn", 0, 500),
        # tid 1: compute stream, one jit envelope per step (containers)
        _x(100, 1, "jit_train_step", 0, 100),
        _x(100, 1, "fusion.1", 0, 40),
        _x(100, 1, "all-reduce.2", 45, 30),
        _x(100, 1, "mult.7", 76, 3),
        _x(100, 1, "infeed.3", 80, 10),
        _x(100, 1, "jit_train_step", 120, 100),
        _x(100, 1, "fusion.1", 120, 50),
        _x(100, 1, "all-reduce.2", 175, 40),
        # tid 2: long DMA-stream leaf overlapping two tid-1 ops
        _x(100, 2, "copy-done.5", 40, 50),
    ]
    if with_step_track:
        events += [
            {"ph": "M", "pid": 100, "tid": 9, "name": "thread_name",
             "args": {"name": "Steps"}},
            _x(100, 9, "1", 0, 100),
            _x(100, 9, "2", 120, 100),
        ]
    return events


def write_trace_dir(tmp_path: Path, events, name="run") -> Path:
    d = tmp_path / name / "plugins" / "profile" / "2026_08_02"
    d.mkdir(parents=True)
    with gzip.open(d / "host.trace.json.gz", "wt") as f:
        json.dump({"traceEvents": events}, f)
    return tmp_path / name


# ---------------------------------------------------------------------
# 1. trace analysis


def test_same_tid_containment_keeps_cross_track_leaf():
    ops, counts = obs_trace.leaf_device_ops(fixture_events())
    # the cross-track long op survives (round-6 rule) ...
    assert ops["copy-done.5"] == 50
    # ... while the same-track jit envelopes are dropped as containers
    assert "jit_train_step" not in ops
    assert counts["fusion.1"] == 2 and counts["all-reduce.2"] == 2


def test_host_events_never_attributed():
    ops, _ = obs_trace.leaf_device_ops(fixture_events())
    assert "hostfn" not in ops


def test_no_device_track_is_loud():
    events = [e for e in fixture_events() if e.get("pid") != 100]
    with pytest.raises(RuntimeError, match="no TPU/GPU device track"):
        obs_trace.leaf_device_ops(events)


def test_step_reconstruction_from_step_track():
    spans, source = obs_trace.step_spans(fixture_events())
    assert source == "step-track"
    assert spans == STEP_SPANS


def test_step_reconstruction_envelope_fallback():
    spans, source = obs_trace.step_spans(fixture_events(False))
    assert source == "envelopes"
    assert spans == STEP_SPANS


def test_bucket_attribution_matches_hand_count():
    for with_steps in (True, False):
        s = obs_trace.summarize_trace(fixture_events(with_steps))
        assert len(s.steps) == 2
        assert s.steps[0].buckets == pytest.approx(STEP0)
        assert s.steps[1].buckets == pytest.approx(STEP1)
        assert s.totals == pytest.approx(
            {k: STEP0[k] + STEP1[k] for k in STEP0})


def test_step_track_envelopes_not_counted_as_device_work():
    # the "Steps" envelopes (100 us each, alone on their track) must not
    # inflate any bucket: totals are identical with and without them
    with_track = obs_trace.summarize_trace(fixture_events(True)).totals
    without = obs_trace.summarize_trace(fixture_events(False)).totals
    assert with_track == pytest.approx(without)


def test_device_op_times_excludes_step_track_envelopes(tmp_path):
    # the experiment scripts' entry point: the digit-named step
    # envelopes must not appear as giant "elementwise/other" leaves
    run = write_trace_dir(tmp_path, fixture_events(), "ops")
    ops, counts = obs_trace.device_op_times(str(run))
    assert "1" not in ops and "2" not in ops
    assert ops["copy-done.5"] == 50 and counts["fusion.1"] == 2


def test_summarize_accepts_uncompressed_trace_file(tmp_path):
    # a gunzipped trace (decompressed for inspection) routes to the
    # trace parser, not the metrics jsonl reader
    f = tmp_path / "host.trace.json"
    f.write_text(json.dumps({"traceEvents": fixture_events()}))
    out = io.StringIO()
    assert obs_main(["summarize", str(f)], out=out) == 0
    assert "collective" in out.getvalue()


def test_classify_and_buckets():
    assert obs_trace.classify("all-reduce.1") == "collective"
    assert obs_trace.classify("convert_reduce_fusion") == "reduce/norm"
    assert obs_trace.bucket_of("all-gather.3") == "collective"
    assert obs_trace.bucket_of("infeed.1") == "host-transfer"
    assert obs_trace.bucket_of("loop_fusion.9") == "compute"


def test_trace_cli_summarize_and_diff(tmp_path):
    run_a = write_trace_dir(tmp_path, fixture_events(), "a")
    # run_b: step 1's all-reduce grows 40 -> 50 us (moved to stay a leaf
    # inside its span), total collective 70 -> 80; compute unchanged
    events_b = []
    for e in fixture_events():
        e = dict(e)
        if e.get("name") == "all-reduce.2" and e.get("ts") == 175:
            e["ts"], e["dur"] = 170, 50
        events_b.append(e)
    run_b = write_trace_dir(tmp_path, events_b, "b")
    out = io.StringIO()
    assert obs_main(["summarize", str(run_a)], out=out) == 0
    text = out.getvalue()
    assert "collective" in text and "idle-bubble" in text
    out = io.StringIO()
    assert obs_main(["diff", str(run_a), str(run_b)], out=out) == 0
    text = out.getvalue()
    # 70 -> 80 us collective = +14.3%; compute flat
    assert "+14.3%" in text
    assert "collective" in text


# ---------------------------------------------------------------------
# 2. metrics fixtures + CLI


def write_metrics_run(tmp_path: Path, name: str, rate: float,
                      buckets: dict, config=None) -> Path:
    d = tmp_path / name
    writer = obs_metrics.MetricsWriter(
        str(d), {"schema": 1, "model": "trivial", "fabric": "ici",
                 "jax_version": "0", "jaxlib_version": "0",
                 "git_sha": "f" * 40, "process_count": 1,
                 "device_count": 8, "platform": "cpu",
                 "config": config or {"batch_size": 2}},
        primary=True)
    assert writer.enabled
    for step in (2, 4):
        writer.event("window", step=step, rate=rate,
                     step_ms=1e3 * 16 / rate, loss=4.2 - step / 10)
    writer.event("trace_buckets", buckets=buckets)
    writer.event("summary", total_images_per_sec=rate,
                 images_per_sec_per_chip=rate / 8,
                 mean_step_ms=1e3 * 16 / rate, p50_step_ms=1e3 * 16 / rate,
                 p50_step_granularity=1, mfu=0.01, final_loss=3.8)
    writer.close()
    return d


def test_metrics_summarize_renders_fixture(tmp_path):
    d = write_metrics_run(tmp_path, "a", 100.0,
                          {"compute": 100.0, "collective": 50.0,
                           "host-transfer": 10.0, "idle-bubble": 20.0})
    out = io.StringIO()
    assert obs_main(["summarize", str(d)], out=out) == 0
    text = out.getvalue()
    assert "model=trivial" in text
    assert "git=ffffffffffff" in text
    assert "trace buckets" in text


def test_metrics_diff_reports_bucket_deltas(tmp_path):
    a = write_metrics_run(tmp_path, "a", 100.0,
                          {"compute": 100.0, "collective": 50.0,
                           "host-transfer": 10.0, "idle-bubble": 20.0})
    b = write_metrics_run(tmp_path, "b", 80.0,
                          {"compute": 100.0, "collective": 70.0,
                           "host-transfer": 10.0, "idle-bubble": 20.0})
    out = io.StringIO()
    assert obs_main(["diff", str(a), str(b)], out=out) == 0
    text = out.getvalue()
    # the regression view: collective +40%, compute flat, rate -20%
    assert "+40.0%" in text
    assert "+0.0%" in text
    assert "-20.0%" in text


def test_metrics_diff_flags_config_drift(tmp_path):
    a = write_metrics_run(tmp_path, "a", 100.0, {"compute": 1.0},
                          config={"batch_size": 2})
    b = write_metrics_run(tmp_path, "b", 90.0, {"compute": 1.0},
                          config={"batch_size": 4})
    out = io.StringIO()
    obs_main(["diff", str(a), str(b)], out=out)
    assert "config: batch_size: 2 -> 4" in out.getvalue()


def test_cli_rejects_nonexistent_artifact(tmp_path, capsys):
    # one clear line + exit 2, not a traceback (the CLI meets operators
    # mid-incident; tests/test_goodput.py covers the degraded-dir matrix)
    rc = obs_main(["summarize", str(tmp_path / "nope")], out=io.StringIO())
    assert rc == 2
    assert capsys.readouterr().err.startswith("error:")


def test_writer_disabled_paths(tmp_path):
    w = obs_metrics.MetricsWriter(None)
    assert not w.enabled
    w.event("window", step=1)   # no-ops, no crash
    w.close()
    # non-primary process never writes
    w = obs_metrics.MetricsWriter(str(tmp_path / "np"), {"schema": 1},
                                  primary=False)
    assert not w.enabled and not (tmp_path / "np").exists()


# ---------------------------------------------------------------------
# 3. end-to-end: driver run -> artifact -> summarize/diff


def _tiny_cfg(**kw):
    base = dict(batch_size=2, num_warmup_batches=1, num_batches=4,
                display_every=2, model="trivial", num_classes=10)
    base.update(kw)
    return flags.BenchmarkConfig(**base).resolve()


def _run(tmp_path, name, **kw):
    cfg = _tiny_cfg(metrics_dir=str(tmp_path / name), **kw)
    out: list[str] = []
    res = driver.run_benchmark(cfg, print_fn=out.append)
    return cfg, res, out


def test_driver_run_writes_metrics_and_manifest(tmp_path):
    cfg, res, _ = _run(tmp_path, "run_a")
    run_dir = tmp_path / "run_a"
    manifest = json.loads((run_dir / "manifest.json").read_text())
    assert manifest["model"] == "trivial"
    assert manifest["config"]["num_batches"] == 4
    assert manifest["device_count"] == 8
    assert manifest["mesh_shape"]["data"] == 8   # DP mesh: (data, model=1)
    assert manifest["jax_version"]
    # "unknown" is the documented fallback on non-git checkouts
    assert manifest["git_sha"] == "unknown" or len(manifest["git_sha"]) == 40
    records = [json.loads(line) for line in
               (run_dir / "metrics.jsonl").read_text().splitlines()]
    kinds = [r["kind"] for r in records]
    assert kinds.count("window") == 2      # steps 2 and 4
    assert kinds[-1] == "summary"
    assert "memory" in kinds
    summary = records[-1]
    assert summary["total_images_per_sec"] == pytest.approx(
        res.total_images_per_sec)
    assert summary["p50_step_granularity"] == res.p50_step_granularity
    # CPU mesh completes fetches faster than steps retire: granularity
    # must be honest either way — a positive int no wider than the run
    assert 1 <= res.p50_step_granularity <= 4
    assert res.p50_step_ms > 0


def test_driver_metrics_summarize_and_diff_end_to_end(tmp_path):
    _run(tmp_path, "run_a")
    _run(tmp_path, "run_b", batch_size=4)
    out = io.StringIO()
    assert obs_main(["summarize", str(tmp_path / "run_a")], out=out) == 0
    assert "model=trivial" in out.getvalue()
    out = io.StringIO()
    assert obs_main(["diff", str(tmp_path / "run_a"),
                     str(tmp_path / "run_b")], out=out) == 0
    text = out.getvalue()
    assert "config: batch_size: 2 -> 4" in text
    assert "total ex/s" in text


def test_eval_run_writes_metrics(tmp_path):
    cfg = _tiny_cfg(metrics_dir=str(tmp_path / "ev"), eval=True)
    out: list[str] = []
    driver.run_benchmark(cfg, print_fn=out.append)
    records = [json.loads(line) for line in
               (tmp_path / "ev" / "metrics.jsonl").read_text().splitlines()]
    kinds = [r["kind"] for r in records]
    assert "window" in kinds and kinds[-1] == "summary"
    assert "eval_top_1" in records[-1]


def _profiler_works() -> bool:
    import tempfile

    import jax

    try:
        with tempfile.TemporaryDirectory() as d:
            jax.profiler.start_trace(d)
            jax.profiler.stop_trace()
        return True
    except Exception:
        return False


def test_profile_steps_window_single_stop(tmp_path):
    if not _profiler_works():
        pytest.skip("jax.profiler unavailable on this backend")
    cfg = _tiny_cfg(trace_dir=str(tmp_path / "tr"), profile_steps="2:3",
                    num_batches=4)
    out: list[str] = []
    driver.run_benchmark(cfg, print_fn=out.append)  # double-stop would raise
    text = "\n".join(out)
    assert "profiler trace written" in text
    # CPU profiler writes host tracks only: the post-run summary must
    # degrade loudly-but-gracefully, not kill the run
    assert ("trace summary" in text) or ("bucket" in text)


def test_profile_steps_rejected_under_eval():
    with pytest.raises(ValueError, match="--eval"):
        flags.BenchmarkConfig(profile_steps="1:2", trace_dir="/tmp/x",
                              eval=True).resolve()


def test_profile_window_past_run_end_warns_loudly(tmp_path):
    # window start beyond the run: the profiler never starts, and the
    # run says so instead of silently writing no trace
    cfg = _tiny_cfg(trace_dir=str(tmp_path / "never"),
                    profile_steps="50:60", num_batches=3)
    out: list[str] = []
    driver.run_benchmark(cfg, print_fn=out.append)
    text = "\n".join(out)
    assert "never started" in text
    assert "profiler trace written" not in text


def test_profile_steps_window_past_run_end_stops_once(tmp_path):
    if not _profiler_works():
        pytest.skip("jax.profiler unavailable on this backend")
    # window end beyond num_batches: the post-loop stop is the only stop
    cfg = _tiny_cfg(trace_dir=str(tmp_path / "tr2"), profile_steps="1:99",
                    num_batches=3)
    out: list[str] = []
    driver.run_benchmark(cfg, print_fn=out.append)
    assert sum("profiler trace written" in ln for ln in out) == 1


# ---------------------------------------------------------------------
# 4. repo hygiene: bytecode never tracked (satellite)


def test_no_bytecode_tracked_in_git():
    ls = subprocess.run(["git", "-C", str(REPO), "ls-files"],
                        capture_output=True, text=True, timeout=30)
    if ls.returncode != 0:
        pytest.skip("not a git checkout")
    bad = [f for f in ls.stdout.splitlines()
           if f.endswith((".pyc", ".pyo")) or "__pycache__" in f]
    assert not bad, f"bytecode artifacts tracked: {bad}"
    gitignore = (REPO / ".gitignore").read_text()
    assert "__pycache__/" in gitignore and "*.pyc" in gitignore


def test_exp_scripts_have_no_local_perfetto_parsing():
    """The acceptance check: both trace experiment scripts are thin
    consumers of obs.trace, with no trace-parsing code of their own."""
    for script in ("exp_vit_trace.py", "exp_moe_trace_r05.py"):
        src = (REPO / "scripts" / script).read_text()
        assert "obs.trace import" in src, script
        assert "traceEvents" not in src, script
        assert "trace.json.gz" not in src, script
