"""Pallas blocked cross-entropy tests (interpreter mode on the CPU mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tpu_hc_bench.ops import xent


def make_case(n, v, seed=0):
    k1, k2 = jax.random.split(jax.random.PRNGKey(seed))
    logits = jax.random.normal(k1, (n, v), jnp.float32) * 3.0
    labels = jax.random.randint(k2, (n,), 0, v)
    return logits, labels


@pytest.mark.parametrize("n,v", [
    (128, 512),       # exactly one block
    (256, 1024),      # multiple blocks both dims
    (100, 700),       # ragged: padding in rows and vocab
    (8, 30522),       # BERT vocab width, tiny batch
])
def test_forward_matches_optax(n, v):
    logits, labels = make_case(n, v)
    ours = xent.softmax_xent(logits, labels)
    ref = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
    np.testing.assert_allclose(np.asarray(ours), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_forward_matches_reference_impl():
    logits, labels = make_case(64, 384, seed=3)
    np.testing.assert_allclose(
        np.asarray(xent.softmax_xent(logits, labels)),
        np.asarray(xent.softmax_xent_reference(logits, labels)),
        rtol=1e-5, atol=1e-5,
    )


def test_gradient_matches_autodiff():
    logits, labels = make_case(96, 640, seed=1)
    w = jax.random.uniform(jax.random.PRNGKey(7), (96,))

    def ours(lg):
        return (xent.softmax_xent(lg, labels) * w).sum()

    def ref(lg):
        return (optax.softmax_cross_entropy_with_integer_labels(
            lg, labels) * w).sum()

    g_ours = jax.grad(ours)(logits)
    g_ref = jax.grad(ref)(logits)
    np.testing.assert_allclose(np.asarray(g_ours), np.asarray(g_ref),
                               rtol=1e-4, atol=1e-5)


def test_bf16_logits():
    logits, labels = make_case(128, 512, seed=2)
    ours = xent.softmax_xent(logits.astype(jnp.bfloat16), labels)
    ref = optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.bfloat16).astype(jnp.float32), labels
    )
    np.testing.assert_allclose(np.asarray(ours), np.asarray(ref),
                               rtol=1e-2, atol=1e-2)


def test_extreme_logits_stable():
    logits, labels = make_case(128, 512, seed=4)
    logits = logits * 1e4  # would overflow a naive exp
    ours = xent.softmax_xent(logits, labels)
    ref = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
    assert np.isfinite(np.asarray(ours)).all()
    np.testing.assert_allclose(np.asarray(ours), np.asarray(ref),
                               rtol=1e-4, atol=1e-2)


def test_jit_compatible():
    logits, labels = make_case(128, 512, seed=5)
    f = jax.jit(xent.softmax_xent)
    np.testing.assert_allclose(
        np.asarray(f(logits, labels)),
        np.asarray(xent.softmax_xent(logits, labels)),
        rtol=1e-6,
    )
