"""stack-pins.txt is the single source of truth for every build surface."""

from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
PINS = REPO / "scripts/setup/stack-pins.txt"


def _pins() -> dict[str, str]:
    out = {}
    for line in PINS.read_text().splitlines():
        line = line.split("#")[0].strip()
        if line:
            name, ver = line.split("==")
            out[name] = ver
    return out


def test_pins_cover_the_stack():
    pins = _pins()
    for pkg in ("jax", "flax", "optax", "chex", "einops",
                "orbax-checkpoint", "numpy", "pillow"):
        assert pkg in pins, f"{pkg} missing from stack-pins.txt"
        assert pins[pkg][0].isdigit()


def test_all_build_surfaces_consume_the_pins():
    # host installer, container image, and venv image all read ONE file
    assert "stack-pins.txt" in (REPO / "scripts/setup/install_jax_stack.sh"
                                ).read_text()
    assert "stack-pins.txt" in (REPO / "Dockerfile").read_text()
    assert "stack-pins.txt" in (REPO / "scripts/setup/build-venv-image.sh"
                                ).read_text()
    # no stray hardcoded jax pin left in the Dockerfile
    assert "jax[tpu]==0" not in (REPO / "Dockerfile").read_text()


def test_pins_match_live_env_when_present():
    import importlib.metadata as md

    import pytest

    pins = _pins()
    # The pin file describes the BUILT image (Dockerfile/venv image); a
    # dev/CI sandbox on a different jax generation is a different stack,
    # not drift — the jax version is the image marker.
    if md.version("jax") != pins["jax"]:
        pytest.skip("live stack is not the pinned image "
                    f"(jax {md.version('jax')} != pin {pins['jax']})")
    for name, want in pins.items():
        try:
            have = md.version(name)
        except md.PackageNotFoundError:
            continue
        assert have == want, f"{name}: live {have} != pin {want}"
