"""Pipeline parallelism (GPipe over ppermute) on the virtual mesh.

The gold check: a DP x PP training step on the (data=2, pipe=4) mesh must
match loss AND updated params of the plain unsharded GPTLM trained with
the same SGD — exercising forward equality, the transposed-ppermute
backward schedule, and the per-group gradient psums (trunk over data,
embed/head over data+pipe, tied embedding summing both contributions).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tpu_hc_bench import flags
from tpu_hc_bench.data.synthetic import SyntheticTokens
from tpu_hc_bench.models.gpt import GPTLM
from tpu_hc_bench.parallel import pipeline as pp
from tpu_hc_bench.topology import PIPE_AXIS, build_mesh, compute_layout


def _tiny_model():
    return GPTLM(vocab_size=256, hidden=32, num_layers=4, heads=4, ffn=64,
                 max_len=32)


def _batch(global_batch=8, seq=16):
    return SyntheticTokens(global_batch, seq, vocab_size=256, seed=3,
                           causal_lm=True).batch()


def _reference_step(model, params, batch, cfg):
    """One unsharded momentum-SGD step on the plain GPTLM."""
    tokens, targets, weights = batch
    tx = optax.sgd(cfg.init_learning_rate, momentum=cfg.momentum)
    opt_state = tx.init(params)

    def loss_fn(p):
        logits = model.apply({"params": p}, tokens, train=False)
        losses = optax.softmax_cross_entropy_with_integer_labels(
            logits, targets)
        return (losses * weights).sum() / jnp.maximum(weights.sum(), 1.0)

    loss, grads = jax.value_and_grad(loss_fn)(params)
    updates, opt_state = tx.update(grads, opt_state, params)
    return optax.apply_updates(params, updates), loss


def test_stack_unstack_roundtrip():
    model = _tiny_model()
    tokens = _batch()[0]
    params = model.init(jax.random.PRNGKey(0), tokens[:1],
                        train=False)["params"]
    stacked = pp.stack_layer_params(params, model.num_layers)
    assert stacked["trunk"]["ln1"]["scale"].shape[0] == model.num_layers
    restored = pp.unstack_layer_params(stacked, model.num_layers)
    jax.tree.map(np.testing.assert_array_equal, params, restored)


@pytest.mark.parametrize("num_microbatches", [2, 4])
def test_pp_matches_unsharded(devices, num_microbatches):
    model = _tiny_model()
    cfg = flags.BenchmarkConfig(model="gpt2", batch_size=1,
                                pipeline_parallel=4).resolve()
    batch = _batch()
    tokens = batch[0]
    base_params = model.init(jax.random.PRNGKey(0), tokens[:1],
                             train=False)["params"]

    # reference first: the PP step donates its inputs (which share buffers
    # with base_params)
    ref_params, ref_loss = _reference_step(model, base_params, batch, cfg)

    layout = compute_layout(1, 8, 8)
    mesh = build_mesh(layout, pipeline_parallel=4)
    assert PIPE_AXIS in mesh.axis_names

    params = pp.stack_layer_params(base_params, model.num_layers)
    pspecs = pp.pp_param_specs(params)
    assert pspecs["trunk"]["ln1"]["scale"][0] == PIPE_AXIS
    tx = optax.sgd(cfg.init_learning_rate, momentum=cfg.momentum)
    opt_state = tx.init(params)
    step, _ = pp.build_pp_train_step(mesh, model, cfg, num_microbatches,
                                     params, opt_state, deterministic=True)
    new_params, new_opt, loss = step(params, opt_state, batch)

    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    ref_stacked = pp.stack_layer_params(ref_params, model.num_layers)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-5),
        new_params, ref_stacked,
    )


def _tiny_llama():
    from tpu_hc_bench.models.llama import LlamaLM

    return LlamaLM(vocab_size=256, hidden=32, num_layers=4, heads=4,
                   num_kv_heads=2, ffn=64, max_len=32)


def test_pp_llama_matches_unsharded(devices):
    """The PP step derives the stage forward from the model's PP interface
    — same gold check as the GPT test, on the llama family (RMSNorm +
    RoPE + GQA + SwiGLU, untied head)."""
    model = _tiny_llama()
    cfg = flags.BenchmarkConfig(model="llama_1b", batch_size=1,
                                pipeline_parallel=4).resolve()
    batch = _batch()
    tokens = batch[0]
    base_params = model.init(jax.random.PRNGKey(0), tokens[:1],
                             train=False)["params"]
    ref_params, ref_loss = _reference_step(model, base_params, batch, cfg)

    mesh = build_mesh(compute_layout(1, 8, 8), pipeline_parallel=4)
    params = pp.stack_layer_params(base_params, model.num_layers)
    assert params["trunk"]["attn_norm"]["scale"].shape[0] == model.num_layers
    tx = optax.sgd(cfg.init_learning_rate, momentum=cfg.momentum)
    opt_state = tx.init(params)
    step, _ = pp.build_pp_train_step(mesh, model, cfg, 2, params, opt_state,
                                     deterministic=True)
    new_params, new_opt, loss = step(params, opt_state, batch)

    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    ref_stacked = pp.stack_layer_params(ref_params, model.num_layers)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-5),
        new_params, ref_stacked,
    )


def test_pp_llama_through_driver(devices):
    """--pipeline_parallel --model llama_tiny trains end-to-end."""
    from tpu_hc_bench.train import driver

    cfg = flags.BenchmarkConfig(
        model="llama_tiny", batch_size=4, pipeline_parallel=4,
        num_warmup_batches=1, num_batches=2, display_every=1,
    ).resolve()
    out = []
    res = driver.run_benchmark(cfg, print_fn=out.append)
    assert np.isfinite(res.final_loss)
    assert any("pipeline: 4 stages" in l for l in out)


def test_pp_rejects_non_decoder():
    from tpu_hc_bench.train import driver

    cfg = flags.BenchmarkConfig(
        model="trivial", num_classes=10, batch_size=1, pipeline_parallel=4,
    ).resolve()
    with pytest.raises(ValueError, match="PP interface"):
        driver.run_benchmark(cfg, print_fn=lambda _: None)


def test_pp_state_placement(devices):
    model = _tiny_model()
    cfg = flags.BenchmarkConfig(model="gpt2", batch_size=1,
                                pipeline_parallel=4).resolve()
    layout = compute_layout(1, 8, 8)
    mesh = build_mesh(layout, pipeline_parallel=4)
    params, opt_state = pp.make_pp_state(model, cfg, _batch()[0], mesh)
    spec = params["trunk"]["ln1"]["scale"].sharding.spec
    assert spec[0] == PIPE_AXIS
    assert params["wte"]["embedding"].sharding.spec == \
        jax.sharding.PartitionSpec()


def test_pp_moe_aux_matches_unsharded(devices):
    """PP with MoE layers must include the Switch aux loss exactly as the
    unsharded model does (per-microbatch-mean == batch-mean because
    routing groups are batch rows)."""
    from tpu_hc_bench.models.moe import AUX_LOSS_COEF

    model = GPTLM(vocab_size=256, hidden=32, num_layers=4, heads=4, ffn=64,
                  max_len=32, num_experts=4, top_k=2)
    cfg = flags.BenchmarkConfig(model="gpt2", batch_size=1,
                                pipeline_parallel=4).resolve()
    batch = _batch()
    tokens, targets, weights = batch
    base_params = model.init(jax.random.PRNGKey(0), tokens[:1],
                             train=False)["params"]

    # unsharded reference task loss (attention/loss are per-row, so the
    # full-batch forward matches any grouping)
    logits = model.apply({"params": base_params}, tokens, train=False)
    losses = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
    task_ref = (losses * weights).sum() / jnp.maximum(weights.sum(), 1.0)
    # the Switch aux is a *per-group statistic* (product of two means, not
    # linear), so the reference must use the same 2-row microbatch groups
    # the (data=2, pipe=4, num_mb=2) run produces: mean over groups
    aux_groups = []
    for g in range(0, tokens.shape[0], 2):
        _, upd = model.apply({"params": base_params}, tokens[g:g + 2],
                             train=False, mutable=["losses"])
        aux_groups.append(
            sum(jnp.sum(t) for t in jax.tree.leaves(upd["losses"])))
    aux_ref = float(np.mean([float(a) for a in aux_groups]))
    assert aux_ref > 0.0
    ref = float(task_ref) + AUX_LOSS_COEF * aux_ref

    mesh = build_mesh(compute_layout(1, 8, 8), pipeline_parallel=4)
    params = pp.stack_layer_params(base_params, model.num_layers)
    tx = optax.sgd(cfg.init_learning_rate, momentum=cfg.momentum)
    opt_state = tx.init(params)
    step, _ = pp.build_pp_train_step(mesh, model, cfg, 2, params, opt_state,
                                     deterministic=True)
    _, _, loss = step(params, opt_state, batch)
    np.testing.assert_allclose(float(loss), ref, rtol=1e-5)


def test_pp_dropout_mode_trains(devices):
    """Non-deterministic PP (dropout active) runs and changes params."""
    model = _tiny_model()
    cfg = flags.BenchmarkConfig(model="gpt2", batch_size=1,
                                pipeline_parallel=4).resolve()
    batch = _batch()
    mesh = build_mesh(compute_layout(1, 8, 8), pipeline_parallel=4)
    params, opt_state = pp.make_pp_state(model, cfg, batch[0], mesh)
    before = float(jnp.abs(params["wte"]["embedding"]).sum())
    step, _ = pp.build_pp_train_step(mesh, model, cfg, 2, params, opt_state)
    params, opt_state, loss = step(params, opt_state, batch,
                                   jax.random.PRNGKey(7))
    assert np.isfinite(float(loss))
    assert float(jnp.abs(params["wte"]["embedding"]).sum()) != before


def test_pp_flag_composition():
    # round 2: PP x TP is a supported hybrid (resolves + builds a 3-D
    # mesh); PP x SP remains rejected
    cfg = flags.BenchmarkConfig(pipeline_parallel=2, model_parallel=2
                                ).resolve()
    assert cfg.pipeline_parallel == 2 and cfg.model_parallel == 2
    mesh = build_mesh(compute_layout(1, 8, 8), model_parallel=2,
                      pipeline_parallel=2)
    assert len(mesh.axis_names) == 3
    with pytest.raises(ValueError, match="not a supported composition"):
        flags.BenchmarkConfig(pipeline_parallel=2,
                              sequence_parallel=2).resolve()
