"""Pallas max-pool backward: parity vs XLA's select-and-scatter VJP.

The kernel is a recorded performance NULL (ops/pool_bwd.py docstring —
1.6-4.4x slower than s&s on hardware) kept as measurement apparatus;
these tests pin its numerics so the recorded contest stays reproducible.
Runs in Pallas interpreter mode on CPU (no TPU needed).
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_hc_bench.ops.pool_bwd import _channel_tile, max_pool

CONFIGS = [
    ((2, 17, 17, 8), (3, 3), (2, 2), "SAME"),    # googlenet downsample
    ((2, 16, 16, 8), (3, 3), (2, 2), "VALID"),   # inception downsample
    ((2, 14, 14, 8), (3, 3), (1, 1), "SAME"),    # googlenet branch pool
    ((2, 16, 16, 8), (2, 2), (2, 2), "VALID"),   # vgg/lenet
    ((1, 13, 15, 8), (3, 3), (2, 2), "SAME"),    # odd extents, uneven pad
]


@pytest.mark.parametrize("shape,win,st,pad", CONFIGS)
def test_pool_bwd_matches_xla(shape, win, st, pad):
    """Forward and gradient must match nn.max_pool / XLA's VJP on
    tie-free continuous input (ties: this kernel splits, s&s picks
    first — measure-zero for random floats)."""
    x = jax.random.normal(jax.random.PRNGKey(0), shape, jnp.float32)
    np.testing.assert_allclose(
        np.asarray(max_pool(x, win, st, pad)),
        np.asarray(nn.max_pool(x, win, st, pad)))
    g = jax.grad(lambda v: (max_pool(v, win, st, pad) ** 2).sum())(x)
    g_ref = jax.grad(lambda v: (nn.max_pool(v, win, st, pad) ** 2).sum())(x)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref),
                               rtol=1e-6, atol=1e-6)


def test_pool_bwd_bf16():
    """bf16 path (f32 compare inside — v5e has no bf16 cmp).

    bf16's 8-bit mantissa makes ~1% of windows genuinely TIED, where
    this kernel splits the cotangent to every tied max while s&s picks
    the first — so the reference here is an equality-mask formulation
    with the SAME tie semantics, not nn.max_pool's VJP."""
    import pathlib
    import sys
    sys.path.insert(
        0, str(pathlib.Path(__file__).resolve().parent.parent / "scripts"))
    # same-semantics reference: the experiment script's equality-mask
    # pooling (tie-splitting, parity-pinned vs s&s on tie-free input)
    from exp_pool_bwd_r05 import maxpool_eq

    xv = jax.random.normal(jax.random.PRNGKey(1), (2, 16, 16, 8),
                           jnp.bfloat16)
    g = jax.grad(lambda v: max_pool(
        v, (3, 3), (2, 2), "VALID").astype(jnp.float32).sum())(xv)
    g_ref = jax.grad(lambda v: maxpool_eq(
        v, (3, 3), (2, 2)).astype(jnp.float32).sum())(xv)
    assert g.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(g, np.float32),
                               np.asarray(g_ref, np.float32))


def test_channel_tile_fallback():
    """Shapes whose stack estimate exceeds the VMEM budget must fall
    back (ct=0 -> XLA VJP), and valid tiles are full-C or 128-aligned."""
    assert _channel_tile(224, 224, 64, 9) == 0          # vgg-pool1 class
    ct = _channel_tile(56, 56, 192, 9)
    assert ct == 192                                     # full C
    ct2 = _channel_tile(28, 28, 256, 9)
    assert ct2 in (256, 128) and (ct2 == 256 or ct2 % 128 == 0)


def test_pool_bwd_stride_gt_window_falls_back():
    """stride > window (skipped input rows) routes to the XLA VJP
    instead of the kernel, whose pad algebra assumes window >= stride."""
    x = jax.random.normal(jax.random.PRNGKey(3), (1, 10, 10, 8),
                          jnp.float32)
    g = jax.grad(lambda v: max_pool(v, (2, 2), (3, 3), "VALID").sum())(x)
    g_ref = jax.grad(
        lambda v: nn.max_pool(v, (2, 2), (3, 3), "VALID").sum())(x)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref))


def test_pool_bwd_neg_inf_input_routes_to_xla():
    """An input containing -inf ties with the kernel's -inf pad taps
    (every tied element would get the full cotangent — wrong where the
    "tie" is padding): the runtime -inf scan must route to the XLA VJP,
    whose gradient stays finite and matches s&s exactly."""
    x = jax.random.normal(jax.random.PRNGKey(5), (1, 10, 10, 8),
                          jnp.float32)
    x = x.at[0, :3, :3, :].set(-jnp.inf)
    g = jax.grad(lambda v: max_pool(v, (3, 3), (2, 2), "SAME").sum())(x)
    g_ref = jax.grad(
        lambda v: nn.max_pool(v, (3, 3), (2, 2), "SAME").sum())(x)
    assert np.isfinite(np.asarray(g)).all()
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref))


def test_pool_bwd_int_dtype_routes_to_xla():
    """Non-float dtypes can't encode the kernel's -inf pad identity
    (jnp.asarray(-inf, int32) raises at trace time), so the VJP rule
    must route them to the XLA fallback.  JAX's AD never reaches this
    path through jax.grad (integer primals are rejected upstream), so
    the rule is exercised directly."""
    from tpu_hc_bench.ops.pool_bwd import _pool_bwd, _pool_fwd

    x = jax.random.randint(jax.random.PRNGKey(4), (1, 8, 8, 8),
                           -100, 100, jnp.int32)
    # forward int pooling is real usage and must match nn.max_pool
    np.testing.assert_array_equal(
        np.asarray(max_pool(x, (2, 2), (2, 2), "VALID")),
        np.asarray(nn.max_pool(x, (2, 2), (2, 2), "VALID")))
    y, res = _pool_fwd(x, (2, 2), (2, 2), "VALID")
    (dx,) = _pool_bwd((2, 2), (2, 2), "VALID", res, jnp.ones_like(y))
    assert dx.dtype == x.dtype
    g_ref = jax.grad(lambda v: nn.max_pool(
        v, (2, 2), (2, 2), "VALID").sum())(x.astype(jnp.float32))
    np.testing.assert_allclose(np.asarray(dx), np.asarray(g_ref))


def test_pool_bwd_fallback_path_matches():
    """A budget-rejected shape still computes the right gradient via
    the XLA fallback inside the custom VJP."""
    x = jax.random.normal(jax.random.PRNGKey(2), (1, 224, 224, 64),
                          jnp.float32)
    # 224^2 x 64 exceeds the stack budget at every admissible tile
    g = jax.grad(lambda v: max_pool(v, (2, 2), (2, 2), "VALID").sum())(x)
    g_ref = jax.grad(
        lambda v: nn.max_pool(v, (2, 2), (2, 2), "VALID").sum())(x)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref))
