"""COW shared-prefix KV cache + lazy on-demand page growth (round 25,
``tpu_hc_bench/serve/prefix_cache.py`` + the refcounted allocator).

Default lane rides the ONE warmed session moe engine from conftest in
VirtualClock replays — zero new engine warmups.  The load-bearing pins:

- **refcount discipline**: pages are shared resources; a page rejoins
  the free list only at refcount zero, COW duplications are counted
  apart from pool recycling, and ``bind`` refuses dead pages;
- **trie correctness**: a node's path spells the full token prefix, a
  partial tail page is reusable only under its exact tail tuple, the
  trash page is never cached, and eviction is leaf-first and never
  touches a page a resident still holds;
- **parity**: sharing and lazy growth are allocation tricks — runs
  with the cache on decode token-for-token what the unshared engine
  decodes, with zero post-warmup compiles;
- **lint**: page-table stores and free-list motion outside
  ``PageAllocator`` are flagged at error severity in the serve package.
"""

from __future__ import annotations

import numpy as np
import pytest

from tpu_hc_bench import flags
from tpu_hc_bench.analysis import lints
from tpu_hc_bench.obs import metrics as obs_metrics
from tpu_hc_bench.serve import arrivals
from tpu_hc_bench.serve import engine as engine_mod
from tpu_hc_bench.serve import prefix_cache as pc

from conftest import SERVE_VCOSTS

VCOSTS = dict(SERVE_VCOSTS, page_copy=0.001)


# --- the refcounted allocator -----------------------------------------


def test_allocator_share_free_refcount():
    a = engine_mod.PageAllocator(6)
    pages = a.alloc(2)
    assert pages and all(p != 0 for p in pages)
    assert all(a.refcount(p) == 1 for p in pages)
    a.share(pages)
    assert all(a.refcount(p) == 2 for p in pages)
    free_before = a.free_pages
    a.free(pages)                       # one holder drops: still live
    assert all(a.refcount(p) == 1 for p in pages)
    assert a.free_pages == free_before
    a.free(pages)                       # last holder: back in the pool
    assert all(a.refcount(p) == 0 for p in pages)
    assert a.free_pages == free_before + 2


def test_allocator_cow_counted_apart_from_recycled():
    a = engine_mod.PageAllocator(4)
    first = a.alloc(3)
    a.free(first)
    assert a.recycled == 0              # first hand-out is not a recycle
    again = a.alloc(2)
    assert a.recycled == 2              # genuine churn through alloc
    dst = a.cow_alloc()
    assert dst is not None and a.refcount(dst) == 1
    assert a.cow_copies == 1
    assert a.recycled == 2              # a COW is sharing, not churn
    a.free(again + [dst])


def test_allocator_bind_refuses_dead_page():
    a = engine_mod.PageAllocator(4)
    table = np.zeros(3, np.int32)
    (p,) = a.alloc(1)
    a.bind(table, 1, p)
    assert table[1] == p
    a.free([p])
    with pytest.raises(AssertionError):
        a.bind(table, 2, p)
    with pytest.raises(AssertionError):
        a.share([p])


# --- the prefix trie ---------------------------------------------------


def _cache(num_pages=16, ps=4):
    a = engine_mod.PageAllocator(num_pages)
    return a, pc.PrefixCache(a, page_size=ps)


def test_cache_match_walks_full_chunks():
    a, c = _cache()
    toks = list(range(100, 108))        # two full 4-token chunks
    pages = a.alloc(3)
    assert c.insert(toks, pages, len(toks)) == 2
    # the cache now holds its own ref on each retained page
    assert a.refcount(pages[0]) == 2 and a.refcount(pages[1]) == 2
    assert a.refcount(pages[2]) == 1    # slot past the prompt: private
    m = c.match(toks)
    assert m.pages == pages[:2] and m.tokens_covered == 8
    # a prefix diverging inside chunk 2 shares only chunk 1
    m = c.match(toks[:4] + [999, 998, 997, 996])
    assert m.pages == pages[:1] and m.tokens_covered == 4
    # acquire increfs per shared page for the admitted holder
    got = c.acquire(c.match(toks))
    assert got == pages[:2]
    assert a.refcount(pages[0]) == 3 and a.refcount(pages[1]) == 3


def test_cache_partial_tail_exact_key_only():
    a, c = _cache()
    toks = list(range(200, 206))        # one full chunk + 2-token tail
    pages = a.alloc(2)
    assert c.insert(toks, pages, len(toks)) == 2
    m = c.match(toks)
    assert m.pages == pages and m.partial_key == (204, 205)
    assert m.tokens_covered == 6
    # same chunk, different tail: the partial must NOT be offered
    m = c.match(toks[:4] + [777, 778])
    assert m.pages == pages[:1] and m.partial_key is None


def test_cache_never_retains_trash_page():
    a, c = _cache()
    (p1,) = a.alloc(1)
    # slot 1 routed to trash (a shared slot on the inserting request):
    # the walk stops there and nothing beyond it is cached
    assert c.insert(list(range(12)), [p1, 0, 0], 12) == 1
    assert a.refcount(p1) == 2
    m = c.match(list(range(12)))
    assert m.pages == [p1]


def test_cache_evicts_cold_leaves_never_held_pages():
    a, c = _cache(num_pages=8)
    hot = list(range(300, 308))
    cold = list(range(400, 408))
    hot_pages = a.alloc(2)
    cold_pages = a.alloc(2)
    c.insert(cold, cold_pages, 8)
    c.insert(hot, hot_pages, 8)
    resident = c.acquire(c.match(hot))  # a resident still reads these
    a.free(cold_pages)                  # the inserting requests retire
    a.free(hot_pages)
    # only the cold path is cache-only; the hot pages stay pinned by
    # the resident no matter how many the eviction asks for
    assert c.evict(4) == 2
    assert c.match(cold).pages == []
    assert c.match(hot).pages == hot_pages
    assert a.refcount(cold_pages[0]) == 0
    assert c.evicted_pages == 2
    # the resident retires: leaf first, then its exposed parent
    a.free(resident)
    assert c.evict(4) == 2
    assert c.match(hot).pages == []


# --- closed loops on the warmed session engine ------------------------


def _run(moe_engine, reqs, **policy):
    events = []
    writer = obs_metrics.MetricsWriter(None)
    writer.event = lambda kind, **f: events.append({"kind": kind, **f})
    summary = moe_engine.run(
        reqs, batching="continuous", writer=writer,
        clock=engine_mod.VirtualClock(VCOSTS), **policy)
    gen = {e["id"]: e.get("generated") for e in events
           if e["kind"] == "request"}
    return summary, events, gen


def _shared_prompt_trace(vocab, n, plen, seed=25):
    block = np.random.default_rng((seed, plen)).integers(
        0, vocab, size=plen, dtype=np.int32)
    return [arrivals.Request(rid=i, arrival_s=0.001 * i,
                             prompt=block.copy(), output_len=4)
            for i in range(n)]


def test_shared_prefix_run_matches_unshared_tokens(moe_engine):
    """The satellite-3 parity pin: identical 8-token prompts (two full
    chunks at page 4) decode the same streams with the cache on as off,
    while the ledger proves sharing actually happened."""
    reqs = _shared_prompt_trace(moe_engine.spec.vocab_size, 6, plen=8)
    off, _, gen_off = _run(moe_engine, reqs,
                           kv_reserve="lazy", prefix_cache="off")
    on, _, gen_on = _run(moe_engine, reqs,
                         kv_reserve="lazy", prefix_cache="on")
    assert gen_on == gen_off            # token-for-token
    assert all(v for v in gen_on.values())
    assert off["post_warmup_compiles"] == 0
    assert on["post_warmup_compiles"] == 0
    kvf = on["kv_pool"]
    assert kvf["prefix_lookups"] == 6
    assert kvf["prefix_hits"] >= 1      # everyone after the first
    assert kvf["prefix_pages_shared"] >= 2
    assert on["prefix_hit_frac"] == pytest.approx(
        kvf["prefix_hits"] / 6, abs=1e-4)
    assert on["kv_reserve"] == "lazy" and on["prefix_cache"] == "on"
    # the off arm never consulted a cache: structurally absent, not 0
    assert off["kv_pool"]["prefix_hit_frac"] is None


def test_shared_tail_triggers_cow_copy(moe_engine):
    """A 6-token prompt caches a partially-filled tail page; the
    owner's first decode append into it (refcount 2: owner + cache)
    must copy, not corrupt the cached prefix — and the copy is charged
    to ``cow_copies``, never ``recycled``."""
    reqs = _shared_prompt_trace(moe_engine.spec.vocab_size, 6, plen=6)
    off, _, gen_off = _run(moe_engine, reqs,
                           kv_reserve="lazy", prefix_cache="off")
    on, _, gen_on = _run(moe_engine, reqs,
                         kv_reserve="lazy", prefix_cache="on")
    assert gen_on == gen_off
    assert on["kv_pool"]["cow_copies"] >= 1
    assert on["post_warmup_compiles"] == 0


def test_lazy_reservation_raises_pool_util(moe_engine):
    """Same burst trace, same pool: lazy admission reserves only the
    prompt's pages (+headroom) so written/reserved page-seconds must
    strictly beat the worst-case control's."""
    cfg = flags.BenchmarkConfig(
        model="moe_tiny", workload="serve", arrival_rate=10000.0,
        num_requests=8, max_prompt_len=8, max_output_len=4,
        max_in_flight=2, kv_page_size=4, seed=0).resolve()
    reqs = arrivals.build_requests(cfg, moe_engine.spec.vocab_size)
    worst, _, gen_w = _run(moe_engine, reqs, kv_reserve="worst")
    lazy, _, gen_l = _run(moe_engine, reqs, kv_reserve="lazy")
    assert gen_l == gen_w               # reservation never changes tokens
    assert lazy["kv_pool_util"] > worst["kv_pool_util"]
    assert lazy["kv_req_gap_frac"] < worst["kv_req_gap_frac"]
    assert worst["kv_reserve"] == "worst" and lazy["kv_reserve"] == "lazy"


def test_on_demand_growth_grows_and_accounts(moe_engine):
    """With headroom 0 every page past the prompt's is allocated the
    step its first token lands: the run must grow, stamp per-request
    ``pages_grown``, and still match the worst-case arm's tokens."""
    reqs = _shared_prompt_trace(moe_engine.spec.vocab_size, 4, plen=4)
    worst, _, gen_w = _run(moe_engine, reqs, kv_reserve="worst")
    saved = moe_engine.cfg.kv_growth_headroom
    moe_engine.cfg.kv_growth_headroom = 0
    try:
        lazy, ev, gen_l = _run(moe_engine, reqs, kv_reserve="lazy")
    finally:
        moe_engine.cfg.kv_growth_headroom = saved
    assert gen_l == gen_w
    # plen 4 + output 4 writes 7 tokens = 2 pages; 1 reserved, 1 grown
    assert lazy["kv_pool"]["pages_grown"] == 4
    grown = [e["pages_grown"] for e in ev if e["kind"] == "request"]
    assert grown == [1, 1, 1, 1]
    assert lazy["pages_grown_total"] == 4
    assert lazy["post_warmup_compiles"] == 0


def test_policy_flags_validated_at_run():
    cfg = flags.BenchmarkConfig(model="moe_tiny", workload="serve")
    with pytest.raises(ValueError, match="kv_reserve"):
        flags.BenchmarkConfig(model="moe_tiny", workload="serve",
                              kv_reserve="sometimes").resolve()
    with pytest.raises(ValueError, match="prefix_cache"):
        flags.BenchmarkConfig(model="moe_tiny", workload="serve",
                              prefix_cache="maybe").resolve()
    # sharing requires lazy reservation: with worst-case tables there
    # is nothing for a cache hit to save
    with pytest.raises(ValueError, match="lazy"):
        flags.BenchmarkConfig(model="moe_tiny", workload="serve",
                              prefix_cache="on").resolve()
    assert cfg  # plain defaults resolve elsewhere in the suite


# --- the page-refcount-discipline lint --------------------------------


BAD_TABLE_STORE = """
def admit(fl, page):
    fl.table[0] = page
"""

BAD_FREELIST = """
def retire(self, pages):
    self._free.extend(pages)
    self.free_list.append(pages[0])
"""

ALLOCATOR_INTERNAL = """
class PageAllocator:
    def free(self, pages):
        for p in pages:
            self._free.append(p)
    def bind(self, table, slot, page):
        table[slot] = page
"""

PLURAL_OK = """
def collect(tables, i, fl):
    tables[i] = fl.table
"""


def _lint(src):
    return [f for f in lints.lint_source_text(
        src, filename="tpu_hc_bench/serve/engine.py")
        if f.lint == lints.PAGE_REFCOUNT]


def test_refcount_lint_flags_table_store_and_freelist():
    found = _lint(BAD_TABLE_STORE)
    assert len(found) == 1 and "bind" in found[0].message
    found = _lint(BAD_FREELIST)
    assert len(found) == 2
    assert all("PageAllocator" in f.message for f in found)


def test_refcount_lint_exempts_allocator_and_plurals():
    assert _lint(ALLOCATOR_INTERNAL) == []
    assert _lint(PLURAL_OK) == []
    # outside the serve package: not this lint's business
    assert not [f for f in lints.lint_source_text(
        BAD_TABLE_STORE, filename="tpu_hc_bench/train/driver.py")
        if f.lint == lints.PAGE_REFCOUNT]


def test_refcount_lint_registered_and_suppressable():
    assert lints.PAGE_REFCOUNT in lints.ALL_SOURCE_LINTS
    src = BAD_TABLE_STORE.replace(
        "fl.table[0] = page",
        "fl.table[0] = page  # tpu-hc: disable=page-refcount-discipline")
    assert _lint(src) == []


def test_repo_serve_sources_refcount_clean():
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    serve_dir = os.path.join(repo, "tpu_hc_bench", "serve")
    found = []
    for name in sorted(os.listdir(serve_dir)):
        if name.endswith(".py"):
            found.extend(lints.lint_file(os.path.join(serve_dir, name)))
    found = [f for f in found if f.lint == lints.PAGE_REFCOUNT]
    assert found == [], [f.message for f in found]
