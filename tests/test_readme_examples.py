"""Docs-drift guard: every CLI example in README.md must parse.

Extracts ``python -m tpu_hc_bench ...`` invocations from README code
blocks and runs them through the real positional-arg splitter and flag
parser (no execution) — a README example with a stale flag or model name
fails here instead of on a user's terminal.
"""

import re
from pathlib import Path

import pytest

from tpu_hc_bench import flags, launcher
from tpu_hc_bench.models import get_model_spec

README = Path(__file__).resolve().parent.parent / "README.md"


def _example_argvs():
    text = README.read_text()
    # join backslash-continued lines, then walk fenced code blocks only
    text = re.sub(r"\\\n\s*", " ", text)
    argvs = []
    in_block = False
    for line in text.splitlines():
        if line.strip().startswith("```"):
            in_block = not in_block
            continue
        if not in_block:
            continue
        line = line.split("#")[0].strip()
        m = re.match(r"python -m tpu_hc_bench\s+(.+)", line)
        if m:
            argvs.append(m.group(1).split())
    assert argvs, "no CLI examples found in README"
    return argvs


@pytest.mark.parametrize("argv", _example_argvs(),
                         ids=lambda a: " ".join(a)[:60])
def test_readme_cli_example_parses(argv):
    from tpu_hc_bench.parallel.fabric import resolve_fabric

    if argv and argv[0] == "fleet":
        # the fleet subcommand (round 19) has its own argparse surface
        from tpu_hc_bench.fleet.__main__ import build_parser

        build_parser().parse_args(argv[1:])
        return
    pos, rest = launcher.parse_positionals(argv)
    assert len(pos) in (0, 4), f"positional contract violated: {pos}"
    cfg = flags.parse_flags(rest)
    get_model_spec(cfg.model)          # model name must exist in the zoo
    if pos:
        resolve_fabric(pos[3])         # the launcher's own validator
