"""Request-level tracing & tail-latency attribution (round 20,
``tpu_hc_bench/obs/requests.py`` + serve-lane wiring).

Default lane rides the session serve fixtures from conftest (ONE
warmed moe engine, one classify engine, the shared two-arm ``moe_ab``
closed loop in virtual time) — zero new engine warmups beyond one
extra VirtualClock replay for the SLO-burn path.

The load-bearing pins:

- **conservation invariant**: for every request in every default-lane
  engine run, the five attribution components sum to the measured e2e
  — exactly (float precision) under VirtualClock;
- **back-compat**: pre-round-20 records (no component fields) flow
  through fold/diff/regress normalizing to zero, labeled, never
  KeyError;
- **bounded overhead**: the per-request stamp costs well under the
  round-17 1%-of-step recorder guard;
- span-name-registry lint: typo'd literal span names flag, the repo
  baseline stays clean.
"""

from __future__ import annotations

import json
import os
import time

import pytest

from tpu_hc_bench import flags
from tpu_hc_bench.analysis import lints
from tpu_hc_bench.obs import metrics as obs_metrics
from tpu_hc_bench.obs import regress
from tpu_hc_bench.obs import requests as rq
from tpu_hc_bench.obs import timeline as timeline_mod
from tpu_hc_bench.serve import engine as engine_mod
from tpu_hc_bench.serve import slo

from conftest import SERVE_VCOSTS


def _requests_of(mdir: str) -> list[dict]:
    recs = [json.loads(l) for l in open(os.path.join(mdir,
                                                     "metrics.jsonl"))]
    return [r for r in recs if r.get("kind") == "request"]


# --- the conservation invariant ---------------------------------------


def test_components_conserved_exactly_in_virtual_time(moe_ab):
    """The tentpole pin: every request's components sum to its measured
    e2e — exact under VirtualClock, for BOTH scheduler arms."""
    for arm in ("static", "continuous"):
        reqs = _requests_of(moe_ab[arm]["mdir"])
        assert reqs, arm
        for r in reqs:
            comps = rq.attribution_of(r)
            assert sum(comps.values()) == pytest.approx(
                r["e2e_ms"], abs=1e-6), (arm, r["id"], comps)
            assert all(v >= 0.0 for v in comps.values()), (arm, r)


def test_components_measure_real_work(moe_ab):
    """The decomposition is measurement, not padding: prefill matches
    the modeled prefill cost, multi-token requests accumulate
    decode_active, and the static arm's tail waits in queue_wait."""
    ct = _requests_of(moe_ab["continuous"]["mdir"])
    for r in ct:
        assert r["prefill_ms"] == pytest.approx(
            1e3 * SERVE_VCOSTS["prefill"], abs=1e-6)
        if r["output_len"] > 1:
            assert r["decode_active_ms"] >= 1e3 * SERVE_VCOSTS["decode"]
        else:
            assert r["decode_active_ms"] == 0.0
    st = _requests_of(moe_ab["static"]["mdir"])
    # static batching makes arrivals wait for a full batch: SOME
    # request must see queue_wait the continuous arm's tail doesn't
    assert max(r["queue_ms"] for r in st) > \
        max(r["queue_ms"] for r in ct)


def test_classify_member_components_conserved(trivial_engine):
    from tpu_hc_bench.serve import arrivals

    reqs = arrivals.build_requests(trivial_engine.cfg, None)
    events = []
    writer = obs_metrics.MetricsWriter(None)
    writer.event = lambda kind, **f: events.append({"kind": kind, **f})
    s = trivial_engine.run(reqs,
                           clock=engine_mod.VirtualClock(SERVE_VCOSTS),
                           writer=writer)
    recs = [e for e in events if e["kind"] == "request"]
    assert len(recs) == len(reqs) and s["completed"] == len(reqs)
    for r in recs:
        comps = rq.attribution_of(r)
        assert sum(comps.values()) == pytest.approx(r["e2e_ms"],
                                                    abs=1e-6)
        # classify members have no prompt pass: the resident window is
        # all decode-lane (active + stall), never "prefill"
        assert comps["prefill"] == 0.0
        assert comps["decode_active"] > 0.0


def test_stall_appears_under_batching_interference(moe_engine):
    """A resident request's wall during a batch-mate's prefill is
    decode_stall — the batching-interference component endpoint
    percentiles cannot see.  Everything arrives at once so admissions
    interleave with decode steps."""
    from tpu_hc_bench.serve import arrivals

    cfg = flags.BenchmarkConfig(
        model="moe_tiny", workload="serve", arrival_rate=10000.0,
        num_requests=8, max_prompt_len=8, max_output_len=4,
        max_in_flight=2, kv_page_size=4, seed=0).resolve()
    reqs = arrivals.build_requests(cfg, moe_engine.spec.vocab_size)
    events = []
    writer = obs_metrics.MetricsWriter(None)
    writer.event = lambda kind, **f: events.append({"kind": kind, **f})
    moe_engine.run(reqs, batching="continuous", writer=writer,
                   clock=engine_mod.VirtualClock(SERVE_VCOSTS))
    recs = [e for e in events if e["kind"] == "request"]
    assert any(r["decode_stall_ms"] > 0 for r in recs), recs
    for r in recs:
        assert sum(rq.attribution_of(r).values()) == pytest.approx(
            r["e2e_ms"], abs=1e-6)


# --- the fold ----------------------------------------------------------


def test_fold_attribution_tail_selection():
    recs = [{"e2e_ms": float(10 * (i + 1)), "queue_ms": float(i),
             "prefill_ms": 1.0, "decode_active_ms": 2.0,
             "decode_stall_ms": 0.5, "retire_ms": 0.0}
            for i in range(20)]
    fold = rq.fold_attribution(recs)
    assert fold["n"] == 20 and fold["tail_n"] == 2
    assert fold["tail_cut_ms"] == 190.0
    assert fold["tail_e2e_ms"] == pytest.approx(195.0)
    assert fold["tail_ms"]["queue_wait"] == pytest.approx(18.5)
    assert fold["has_components"]
    flat = rq.flatten_attribution(fold)
    assert flat["tail_queue_wait_frac"] == \
        fold["tail_frac"]["queue_wait"]
    assert rq.fold_attribution([]) is None


def test_fold_normalizes_pre_r20_records_to_zero():
    """The back-compat seam: round-16 records (queue_ms only) fold to
    zero components, labeled — never KeyError."""
    old = [{"e2e_ms": 50.0, "queue_ms": 10.0, "ttft_ms": 20.0}]
    fold = rq.fold_attribution(old)
    assert not fold["has_components"]
    assert fold["tail_ms"]["decode_stall"] == 0.0
    assert fold["tail_ms"]["queue_wait"] == 10.0   # queue_ms predates r20
    lines = rq.attribution_lines(fold, p99_e2e_ms=50.0)
    assert len(lines) == 1 and "pre-round-20" in lines[0]


def test_attribution_lines_name_the_dominant_component(moe_ab):
    fold = moe_ab["continuous"]["summary"]["attribution"]
    lines = rq.attribution_lines(fold, p99_e2e_ms=13.0)
    assert len(lines) == 1
    assert "p99 e2e 13ms" in lines[0]
    assert "decode_active" in lines[0] and "%" in lines[0]


def test_engine_summary_carries_attribution_and_flat_fracs(moe_ab):
    for arm in ("static", "continuous"):
        s = moe_ab[arm]["summary"]
        assert s["attribution"]["n"] == s["completed"]
        assert "tail_queue_wait_frac" in s
        assert "tail_decode_stall_frac" in s
        # fractions of the conserved decomposition live in [0, 1]
        assert all(0.0 <= v <= 1.0
                   for v in s["attribution"]["tail_frac"].values())


# --- bucket utilization ------------------------------------------------


def test_engine_summary_bucket_util(moe_ab):
    bu = moe_ab["continuous"]["summary"]["bucket_util"]
    assert any(k.startswith("decode@") for k in bu)
    assert any(k.startswith("prefill@") for k in bu)
    for k, u in bu.items():
        assert 0.0 <= u["occupancy"] <= 1.0, k
        assert u["rows"] >= u["active_rows"] >= 0
        assert u["steps"] > 0
    lines = rq.bucket_util_lines(bu)
    assert lines and "bucket util" in lines[0]
    assert any("decode@" in ln and "%" in ln for ln in lines[1:])
    assert rq.bucket_util_lines(None) == []


def test_watch_renders_live_bucket_occupancy():
    recs = [{"kind": "serve", "t": 1.0, "queue_depth": 2, "in_flight": 2,
             "tokens": 9, "bucket_occ": {"decode@2": 0.81,
                                         "prefill@8": 0.5}}]
    lines = slo.watch_lines(recs)
    text = "\n".join(lines)
    assert "bucket occ:" in text and "decode@2 81%" in text


# --- summarize / diff / regress surfaces -------------------------------


def test_summarize_renders_attribution_and_buckets(moe_ab):
    text = "\n".join(obs_metrics.summarize_run(
        moe_ab["continuous"]["mdir"]))
    assert "p99 e2e" in text and "queue ms p50" in text
    assert "bucket util" in text
    assert "slowest" in text        # the tail-attribution line


def test_diff_renders_component_deltas(moe_ab):
    lines = obs_metrics.diff_runs(moe_ab["static"]["mdir"],
                                  moe_ab["continuous"]["mdir"])
    text = "\n".join(lines)
    assert "tail attribution" in text
    assert "queue_wait" in text and "pp" in text
    assert "p99 queue ms" in text   # the new DIFF_METRICS row


def test_diff_normalizes_pre_r20_side_to_zero():
    """Satellite pin: a pre-r20 fold (no attribution) against an r20
    fold renders labeled deltas, no KeyError."""
    new = rq.fold_attribution([{
        "e2e_ms": 100.0, "queue_ms": 60.0, "prefill_ms": 10.0,
        "decode_active_ms": 25.0, "decode_stall_ms": 5.0,
        "retire_ms": 0.0}])
    old = rq.fold_attribution([{"e2e_ms": 80.0, "queue_ms": 20.0}])
    lines = rq.attribution_diff_lines(old, new)
    text = "\n".join(lines)
    assert "queue_wait" in text
    assert "predates request attribution" in text
    # both None (two training runs): nothing renders
    assert rq.attribution_diff_lines(None, None) == []
    # one side entirely absent still renders the present side
    assert rq.attribution_diff_lines(None, new)


def test_serve_diff_lines_old_vs_new_streams(moe_ab):
    """obs diff end-to-end back-compat: an r20 fold against a
    synthesized pre-r20 fold (records stripped of component fields)."""
    recs = _requests_of(moe_ab["continuous"]["mdir"])
    old_recs = [{k: v for k, v in r.items()
                 if k not in ("prefill_ms", "decode_active_ms",
                              "decode_stall_ms", "retire_ms")}
                for r in recs]
    fold_new = slo.fold_serve_records(
        [{"kind": "request", **r} for r in recs])
    fold_old = slo.fold_serve_records(
        [{"kind": "request", **r} for r in old_recs])
    lines = slo.serve_diff_lines(fold_old, fold_new)
    text = "\n".join(lines)
    assert "tail attribution" in text
    assert "note: run a predates request attribution" in text


def test_regress_gates_on_attribution_shift(tmp_path):
    """A tail that shifted from compute to waiting flags even when p99
    itself moved little; pre-r20 history (no fields) skips the checks
    instead of KeyError-ing."""
    base = {"metric": "moe_tiny_serve_tokens_per_s", "value": 100.0,
            "unit": "tokens/sec",
            "extra": {"batching": "continuous", "arrival_rate": 16.0,
                      "p99_ms": 100.0, "goodput": 0.5,
                      "tokens_per_s": 100.0,
                      "tail_queue_wait_frac": 0.10,
                      "tail_decode_stall_frac": 0.05}}
    hist = [json.loads(json.dumps(base)) for _ in range(4)]
    fresh = json.loads(json.dumps(base))
    fresh["extra"]["tail_queue_wait_frac"] = 0.60   # tail now waits
    verdict = regress.regress_check(fresh, hist)
    assert any(r["metric"] == "tail queue_wait frac"
               for r in verdict["regressions"])
    # pre-r20 history: the attribution fields are simply absent
    old_hist = []
    for h in hist:
        h = json.loads(json.dumps(h))
        del h["extra"]["tail_queue_wait_frac"]
        del h["extra"]["tail_decode_stall_frac"]
        old_hist.append(h)
    verdict = regress.regress_check(fresh, old_hist)
    assert not any("frac" in r["metric"] for r in verdict["regressions"])
    assert verdict["history_n"] == 4   # still gated on the old metrics


def test_regress_zero_median_fraction_has_absolute_floor():
    """A well-provisioned config's history legitimately sits at
    tail_*_frac == 0.0 — rel_floor*|0| is a zero threshold, so the
    fraction checks carry an absolute floor: sub-floor jitter never
    flags, a real shift still does."""
    base = {"metric": "m", "value": 100.0, "unit": "u",
            "extra": {"tokens_per_s": 100.0,
                      "tail_queue_wait_frac": 0.0,
                      "tail_decode_stall_frac": 0.0}}
    hist = [json.loads(json.dumps(base)) for _ in range(4)]
    jitter = json.loads(json.dumps(base))
    jitter["extra"]["tail_queue_wait_frac"] = 0.003   # one 0.3ms blip
    assert not regress.regress_check(jitter, hist)["regressions"]
    real = json.loads(json.dumps(base))
    real["extra"]["tail_queue_wait_frac"] = 0.30
    assert any(r["metric"] == "tail queue_wait frac"
               for r in regress.regress_check(real, hist)["regressions"])


# --- SLO burn rate -----------------------------------------------------


def test_fold_burn_rate_burst_vs_sustained():
    # transient burst: violations confined to one window
    burst = [{"arrival_s": i * 1.0, "e2e_ms": 500.0 if i == 4 else 10.0}
             for i in range(16)]
    b = slo.fold_burn_rate(burst, 100.0, window_s=2.0)
    assert b["violations"] == 1 and b["max_violation_streak"] == 1
    # sustained overload: every window violates
    over = [{"arrival_s": i * 1.0, "e2e_ms": 500.0} for i in range(16)]
    o = slo.fold_burn_rate(over, 100.0, window_s=2.0)
    assert o["violation_rate"] == 1.0
    assert o["max_violation_streak"] == len(o["windows"])
    # ceil-based bins: the boundary completion clamps into the last
    # FULL window instead of sitting alone in a degenerate ninth one
    assert len(o["windows"]) == 8
    assert all(w["n"] >= 2 for w in o["windows"])
    assert "SUSTAINED" in slo.burn_lines(o)[0]
    assert "SUSTAINED" not in slo.burn_lines(b)[0]
    # off / empty
    assert slo.fold_burn_rate(over, 0.0) is None
    assert slo.fold_burn_rate([], 100.0) is None


def test_slo_flag_wires_burn_into_summary(moe_engine, moe_requests):
    saved = moe_engine.cfg.slo_e2e_ms
    try:
        moe_engine.cfg.slo_e2e_ms = 8.0
        s = moe_engine.run(moe_requests, batching="continuous",
                           clock=engine_mod.VirtualClock(SERVE_VCOSTS))
    finally:
        moe_engine.cfg.slo_e2e_ms = saved
    burn = s["slo"]
    assert burn["slo_e2e_ms"] == 8.0
    assert burn["completed"] == len(moe_requests)
    assert burn["violations"] == sum(
        w["violations"] for w in burn["windows"])
    assert any("slo:" in ln for ln in slo.slo_lines(s))


def test_slo_flag_validation_and_lane():
    with pytest.raises(ValueError, match="slo_e2e_ms"):
        flags.BenchmarkConfig(model="moe_tiny", workload="serve",
                              slo_e2e_ms=-1.0).resolve()
    with pytest.raises(ValueError, match="serving-lane"):
        flags.parse_flags(["--model", "trivial", "--slo_e2e_ms", "50"])
    cfg = flags.parse_flags(["--model", "moe_tiny", "--slo_e2e_ms",
                             "50"], workload="serve")
    assert cfg.slo_e2e_ms == 50.0


# --- timeline request lanes -------------------------------------------


def test_serve_clock_record_on_stream(moe_ab):
    recs = [json.loads(l) for l in open(
        os.path.join(moe_ab["continuous"]["mdir"], "metrics.jsonl"))]
    clocks = [r for r in recs if r.get("kind") == "serve_clock"]
    assert len(clocks) == 1
    assert isinstance(clocks[0]["t_unix"], float)


def test_timeline_merges_request_lanes(moe_ab, serve_cfg):
    """Each request renders as its own Chrome-trace lane (pid
    'requests', tid=rid) with queue_wait/prefill/decode sub-slices
    beside the engine's span view."""
    trace = timeline_mod.merge_chrome_trace(moe_ab["continuous"]["mdir"])
    lanes = [e for e in trace["traceEvents"]
             if e.get("pid") == rq.REQUEST_LANE_PID]
    assert trace["metadata"]["request_lanes"] == serve_cfg.num_requests
    tids = {e["tid"] for e in lanes if e["ph"] == "X"}
    assert len(tids) == serve_cfg.num_requests
    names = {e["name"] for e in lanes}
    assert {"queue_wait", "prefill", "decode",
            "process_name"} <= names
    # decode slices carry the stall/active split for the hover view
    dec = [e for e in lanes if e["name"] == "decode"]
    assert dec and all("active_ms" in e["args"] for e in dec)
    # the engine's own span lane is still there beside the requests
    assert any(e.get("pid") == 0 for e in trace["traceEvents"])


def test_request_lanes_skip_pre_r20_streams():
    # no serve_clock record -> no lanes, never wrongly-placed ones
    assert rq.request_trace_events(
        [{"kind": "request", "e2e_ms": 5.0, "arrival_s": 0.0}]) == []


# --- overhead guard ----------------------------------------------------


def test_attribution_stamp_overhead_bounded(moe_ab):
    """The per-request stamp (components_ms) must cost well under the
    round-17 1%-of-step guard — it runs once per retirement on the
    engine's hot path."""
    step_s = SERVE_VCOSTS["decode"]
    n = 2000
    t0 = time.perf_counter()
    for i in range(n):
        rq.components_ms(0.0, 0.001, 0.005, 0.040, 0.040, 0.030)
    per_call = (time.perf_counter() - t0) / n
    assert per_call < 0.01 * step_s, \
        f"components_ms {per_call * 1e6:.1f}us vs 1% of " \
        f"{step_s * 1e3:.0f}ms step"


# --- span-name-registry lint ------------------------------------------


BAD_SPAN_SRC = """
from tpu_hc_bench.obs import timeline as timeline_mod
def f(t0, t1):
    timeline_mod.record_span("step_dispach", t0, t1)
    timeline_mod.instant("retire")
"""


def test_span_registry_lint_flags_typo():
    found = [f for f in lints.lint_source_text(
        BAD_SPAN_SRC, filename="tpu_hc_bench/train/driver.py")
        if f.lint == lints.SPAN_REGISTRY]
    assert len(found) == 1
    assert "step_dispach" in found[0].message
    assert "KNOWN_SPANS" in found[0].message


def test_span_registry_lint_skips_variables_and_foreign_calls():
    src = """
from tpu_hc_bench.obs import timeline as timeline_mod
def f(kind, t0, t1, thing):
    timeline_mod.record_span(kind, t0, t1)     # variable: caller's contract
    thing.instant("definitely_not_a_span")     # not the recorder's
"""
    found = [f for f in lints.lint_source_text(
        src, filename="tpu_hc_bench/serve/engine.py")
        if f.lint == lints.SPAN_REGISTRY]
    assert found == []


def test_span_registry_lint_suppression():
    src = BAD_SPAN_SRC.replace(
        'timeline_mod.record_span("step_dispach", t0, t1)',
        'timeline_mod.record_span("step_dispach", t0, t1)'
        '  # thb:lint-ok[span-name-registry]')
    found = [f for f in lints.lint_source_text(
        src, filename="tpu_hc_bench/train/driver.py")
        if f.lint == lints.SPAN_REGISTRY]
    assert found == []


def test_repo_span_names_all_registered():
    """The repo baseline stays clean: every literal span name the
    instrumented lanes record is in KNOWN_SPANS."""
    found = [f for f in lints.lint_repo_sources()
             if f.lint == lints.SPAN_REGISTRY]
    assert found == [], [f.message for f in found]


def test_known_spans_cover_engine_kinds():
    # the engine's variable record_span(kind, ...) call records these
    # three — the registry must know them even though the lint can't
    # see through the variable
    assert {"prefill", "decode", "classify", "admit",
            "retire"} <= timeline_mod.KNOWN_SPANS
