"""tpu_hc_bench.resilience: fault injection, guards, preemption,
watchdog, checkpoint hardening.

Every recovery path is exercised by a real injected failure
(``--inject_fault``), per the round-8 acceptance criteria:
``nan_loss@N`` + ``--on_nonfinite=skip`` completes with the bad step
dropped and a ``nonfinite_skip`` metrics record; ``sigterm@N`` +
``--resume=auto`` kill/relaunch resumes from the emergency checkpoint
with bitwise-identical params (fingerprint lines); ``hang@N`` +
``--step_timeout_s`` aborts with a stack dump and the distinct
watchdog exit code instead of hanging.
"""

import json
import os
import subprocess
import sys
import time
from pathlib import Path

import numpy as np
import pytest

from tpu_hc_bench import flags, resilience
from tpu_hc_bench.resilience import (
    guards, inject, preempt, retry as retry_mod, watchdog,
)
from tpu_hc_bench.train import driver

REPO = Path(__file__).resolve().parent.parent


def tiny_cfg(**kw):
    base = dict(
        batch_size=2, num_warmup_batches=1, num_batches=6, display_every=2,
        model="trivial", num_classes=10, init_learning_rate=0.05,
    )
    base.update(kw)
    return flags.BenchmarkConfig(**base).resolve()


def read_metrics(metrics_dir):
    path = os.path.join(metrics_dir, "metrics.jsonl")
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def kinds(records):
    return [r["kind"] for r in records]


# ---------------------------------------------------------------------
# inject: the --inject_fault grammar


def test_parse_plan():
    plan = inject.parse_plan("nan_loss@40,hang@80:30,sigterm@120,"
                             "io_error@ckpt,nan_loss@41")
    assert plan.nan_loss == frozenset({40, 41})
    assert plan.hang == {80: 30.0}
    assert plan.sigterm == frozenset({120})
    assert plan.io_error == {"ckpt"}
    assert inject.parse_plan(None) is None
    assert inject.parse_plan("") is None


@pytest.mark.parametrize("bad", [
    "nan_loss", "nan_loss@", "nan_loss@0", "nan_loss@x", "hang@5",
    "hang@5:-1", "io_error@metrics", "explode@3", "sigterm@1.5",
])
def test_parse_plan_loud(bad):
    with pytest.raises(ValueError, match="malformed|grammar"):
        inject.parse_plan(bad)


def test_flags_validate_inject_and_policies():
    with pytest.raises(ValueError, match="malformed"):
        tiny_cfg(inject_fault="bogus@@")
    with pytest.raises(ValueError, match="rewind"):
        tiny_cfg(on_nonfinite="rewind")            # needs --train_dir
    with pytest.raises(ValueError, match="resume=never"):
        # rewind restores from --train_dir; never-resume contradicts it
        tiny_cfg(on_nonfinite="rewind", train_dir="/tmp/x", resume="never")
    with pytest.raises(ValueError, match="forward-only|--eval"):
        tiny_cfg(on_nonfinite="skip", eval=True)
    with pytest.raises(ValueError, match="GPipe|PP"):
        tiny_cfg(on_nonfinite="skip", model="gpt2_tiny",
                 pipeline_parallel=4)
    with pytest.raises(ValueError, match="step_timeout_s"):
        tiny_cfg(step_timeout_s="soon")
    with pytest.raises(ValueError, match="resume"):
        tiny_cfg(resume="maybe")
    with pytest.raises(ValueError, match="--resume=must"):
        tiny_cfg(resume="must")                    # needs --train_dir
    with pytest.raises(ValueError, match="max_bad_steps"):
        tiny_cfg(on_nonfinite="skip", max_bad_steps=0)


# ---------------------------------------------------------------------
# guards: jit-compatible detection + device-side budget counters


def test_finite_flag_and_select():
    import jax.numpy as jnp

    assert bool(guards.finite_flag(jnp.float32(1.0)))
    assert not bool(guards.finite_flag(jnp.float32(np.nan)))
    assert not bool(guards.finite_flag(
        jnp.float32(1.0), {"w": jnp.array([1.0, np.inf])}))
    new = {"w": jnp.array([2.0]), "n": jnp.int32(5)}
    old = {"w": jnp.array([1.0]), "n": jnp.int32(4)}
    kept = guards.select_state(guards.finite_flag(jnp.float32(np.nan)),
                               new, old)
    assert float(kept["w"][0]) == 1.0 and int(kept["n"]) == 4
    took = guards.select_state(guards.finite_flag(jnp.float32(0.5)),
                               new, old)
    assert float(took["w"][0]) == 2.0 and int(took["n"]) == 5


def test_guard_tracker_streak_resets_on_good_step():
    import jax.numpy as jnp

    t = guards.GuardTracker()
    for bad in (1, 1, 0, 1):
        t.update(jnp.int32(bad))
    streak, total, peak = t.poll()
    # peak remembers the 2-long run even though a good step reset the
    # live streak — the --max_bad_steps budget must not be dodgeable by
    # a streak that ends inside a sync window
    assert (streak, total, peak) == (1, 3, 2)
    t.reset()
    assert t.poll() == (0, 0, 0)


# ---------------------------------------------------------------------
# --on_nonfinite policies through the driver (nan_loss injection)


def test_nonfinite_abort_default(mesh8):
    with pytest.raises(resilience.NonFiniteError, match="abort"):
        driver.run_benchmark(tiny_cfg(inject_fault="nan_loss@2"),
                             print_fn=lambda s: None)


def test_nonfinite_skip_completes(mesh8, tmp_path):
    from tpu_hc_bench.obs import metrics as obs_metrics

    mdir = str(tmp_path / "m")
    out = []
    res = driver.run_benchmark(
        tiny_cfg(on_nonfinite="skip", inject_fault="nan_loss@3",
                 metrics_dir=mdir), print_fn=out.append)
    assert np.isfinite(res.final_loss)
    recs = read_metrics(mdir)
    assert "injected_fault" in kinds(recs)
    skip = [r for r in recs if r["kind"] == "nonfinite_skip"]
    assert skip and skip[0]["new_bad"] == 1
    assert any("dropped 1 update" in l for l in out)
    # ...and `obs summarize` surfaces the resilience events
    text = "\n".join(obs_metrics.summarize_run(mdir))
    assert "resilience:" in text
    assert "nonfinite_skip" in text and "injected_fault" in text


def test_nonfinite_skip_budget_terminates(mesh8):
    cfg = tiny_cfg(on_nonfinite="skip", max_bad_steps=2,
                   inject_fault="nan_loss@1,nan_loss@2,nan_loss@3,"
                                "nan_loss@4,nan_loss@5,nan_loss@6")
    with pytest.raises(resilience.GuardBudgetError, match="consecutive"):
        driver.run_benchmark(cfg, print_fn=lambda s: None)


def test_nonfinite_rewind_restores_and_completes(mesh8, tmp_path):
    # nan at step 1: the double-buffered guard fetch acts one window
    # late (snapshot at window 2, processed at window 4), so the poison
    # must land early enough that clean replay steps remain after the
    # restore
    mdir, ckdir = str(tmp_path / "m"), str(tmp_path / "ck")
    out = []
    res = driver.run_benchmark(
        tiny_cfg(on_nonfinite="rewind", inject_fault="nan_loss@1",
                 train_dir=ckdir, metrics_dir=mdir), print_fn=out.append)
    assert np.isfinite(res.final_loss)
    recs = read_metrics(mdir)
    rewinds = [r for r in recs if r["kind"] == "rewind"]
    assert rewinds and rewinds[0]["skipped_batches"] > 0
    assert any("rewind:" in l for l in out)


def test_rewind_budget_terminates_poisoned_run(mesh8, tmp_path):
    """Every window poisoned: back-to-back rewinds hit --max_bad_steps
    (same consecutive semantics as the skip budget) instead of
    rewind-looping to the end of the run.

    8 timed steps: under the double-buffered guard fetch a rewind wipes
    the following window's counters (the reset), so each rewind needs
    two windows of runway — and the wiped window must NOT pass as
    "observed clean" and break the consecutive-rewind streak (the
    guard_wiped_until accounting this test pins).
    """
    cfg = tiny_cfg(on_nonfinite="rewind", max_bad_steps=2, num_batches=8,
                   train_dir=str(tmp_path / "ck"),
                   inject_fault="nan_loss@1,nan_loss@2,nan_loss@3,"
                                "nan_loss@4,nan_loss@5,nan_loss@6,"
                                "nan_loss@7,nan_loss@8")
    with pytest.raises(resilience.GuardBudgetError, match="rewinds"):
        driver.run_benchmark(cfg, print_fn=lambda s: None)


# ---------------------------------------------------------------------
# preemption: sigterm -> emergency checkpoint -> resume


def test_preempt_emergency_checkpoint_and_resume(mesh8, tmp_path):
    from tpu_hc_bench.utils import checkpoint as ckpt

    ckdir, mdir = str(tmp_path / "ck"), str(tmp_path / "m")
    out = []
    with pytest.raises(resilience.PreemptedError) as ei:
        driver.run_benchmark(
            tiny_cfg(inject_fault="sigterm@2", train_dir=ckdir,
                     metrics_dir=mdir), print_fn=out.append)
    assert ei.value.step == 2 and ei.value.checkpoint_saved
    assert ckpt.latest_step(ckdir) == 3          # 1 warmup + 2 timed
    recs = read_metrics(mdir)
    assert "emergency_ckpt" in kinds(recs) and "preempt" in kinds(recs)
    fp_save = [l for l in out if "params fingerprint" in l]
    assert fp_save

    out2 = []
    res = driver.run_benchmark(tiny_cfg(train_dir=ckdir),
                               print_fn=out2.append)
    assert any("restored checkpoint step 3" in l for l in out2)
    fp_restore = [l for l in out2 if "params fingerprint" in l]
    # bitwise-identical params across the emergency save/restore boundary
    assert fp_restore[0] == fp_save[0]
    assert np.isfinite(res.final_loss)


def test_resume_policies_and_retention(mesh8, tmp_path):
    """One checkpointed run, then the --resume policy matrix against it
    (plus --keep_checkpoints retention through the driver, sharing the
    same run to keep the default lane cheap)."""
    from tpu_hc_bench.utils import checkpoint as ckpt

    ckdir = str(tmp_path / "ck")
    with pytest.raises(FileNotFoundError, match="resume=must"):
        driver.run_benchmark(tiny_cfg(train_dir=ckdir, resume="must"),
                             print_fn=lambda s: None)
    driver.run_benchmark(
        tiny_cfg(train_dir=ckdir, save_model_steps=2, keep_checkpoints=1),
        print_fn=lambda s: None)
    # saves at timed steps 2, 4 and the end (7 = 1 warmup + 6 timed);
    # retention keeps only the newest
    assert ckpt.complete_steps(ckdir) == [7]
    out = []
    driver.run_benchmark(tiny_cfg(train_dir=ckdir, resume="never",
                                  num_batches=2), print_fn=out.append)
    assert not any("restored checkpoint" in l for l in out)
    out = []
    driver.run_benchmark(tiny_cfg(train_dir=ckdir, resume="must",
                                  num_batches=2), print_fn=out.append)
    assert any("restored checkpoint step 7" in l for l in out)


# ---------------------------------------------------------------------
# watchdog


def test_resolve_timeout():
    assert watchdog.resolve_timeout(None) is None
    assert watchdog.resolve_timeout("off") is None
    assert watchdog.resolve_timeout("0") is None
    assert watchdog.resolve_timeout("12.5") == 12.5
    assert watchdog.resolve_timeout("auto") is None     # pre-warmup
    auto = watchdog.resolve_timeout("auto", warmup_step_s=2.0)
    assert auto == max(watchdog.AUTO_TIMEOUT_MIN_S,
                       watchdog.AUTO_TIMEOUT_MULT * 2.0)
    with pytest.raises(ValueError, match="step_timeout_s"):
        watchdog.resolve_timeout("-3")
    with pytest.raises(ValueError, match="step_timeout_s"):
        watchdog.resolve_timeout("soon")


def test_watchdog_fires_without_progress():
    fired = []
    dog = watchdog.Watchdog(
        0.2, progress_fn=lambda: None, print_fn=lambda s: None,
        on_timeout=fired.append, poll_s=0.05).start()
    deadline = time.monotonic() + 5.0
    while not fired and time.monotonic() < deadline:
        time.sleep(0.02)
    dog.stop()
    assert fired and fired[0] > 0.2 and dog.fired


def test_watchdog_quiet_with_progress():
    fired = []
    dog = watchdog.Watchdog(
        0.3, progress_fn=time.perf_counter, print_fn=lambda s: None,
        on_timeout=fired.append, poll_s=0.05).start()
    time.sleep(0.7)
    dog.stop()
    assert not fired and not dog.fired


def test_watchdog_pause_covers_long_checkpoint_saves():
    """A legitimate long stall (checkpoint save to slow storage) must
    not trip the watchdog while paused, and the paused span must not
    count after resume."""
    fired = []
    dog = watchdog.Watchdog(
        0.2, progress_fn=lambda: None, print_fn=lambda s: None,
        on_timeout=fired.append, poll_s=0.05).start()
    dog.pause()
    time.sleep(0.5)              # well past the timeout, but paused
    assert not fired
    dog.resume()
    time.sleep(0.1)              # fresh baseline: still inside timeout
    assert not fired
    deadline = time.monotonic() + 5.0
    while not fired and time.monotonic() < deadline:
        time.sleep(0.02)         # now it must fire
    dog.stop()
    assert fired


# ---------------------------------------------------------------------
# retry + checkpoint/metrics I/O hardening


def test_retry_io_bounded():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("transient")
        return "ok"

    assert retry_mod.retry_io(flaky, "t", base_delay_s=0.001) == "ok"
    with pytest.raises(OSError):
        retry_mod.retry_io(lambda: (_ for _ in ()).throw(OSError("dead")),
                           "t", attempts=2, base_delay_s=0.001)
    # non-OSError propagates immediately (not a transient I/O fault)
    boom = []

    def type_error():
        boom.append(1)
        raise TypeError("bug")

    with pytest.raises(TypeError):
        retry_mod.retry_io(type_error, "t", base_delay_s=0.001)
    assert len(boom) == 1


def test_checkpoint_io_error_injected_retries(mesh8, tmp_path):
    from tpu_hc_bench.utils import checkpoint as ckpt

    ckdir, mdir = str(tmp_path / "ck"), str(tmp_path / "m")
    out = []
    driver.run_benchmark(
        tiny_cfg(inject_fault="io_error@ckpt", train_dir=ckdir,
                 metrics_dir=mdir), print_fn=out.append)
    assert any("retrying" in l for l in out)
    assert "io_retry" in kinds(read_metrics(mdir))
    assert ckpt.latest_step(ckdir) is not None   # save ultimately landed


# ---------------------------------------------------------------------
# checkpoint hardening: atomic commit sentinel, fallback, retention GC


def _save_steps(state, directory, steps):
    import jax.numpy as jnp
    from tpu_hc_bench.utils import checkpoint as ckpt

    for s in steps:
        state = state.replace(step=jnp.asarray(s, jnp.int32))
        ckpt.save(state, directory)
    return state


def _tiny_state():
    from tpu_hc_bench.data.synthetic import SyntheticImages
    from tpu_hc_bench.models import create_model
    from tpu_hc_bench.train import step as step_mod

    cfg = tiny_cfg()
    model, spec = create_model("trivial", num_classes=10)
    batch = SyntheticImages(2, spec.input_shape, num_classes=10,
                            seed=0).batch()
    return step_mod.make_train_state(model, cfg, batch)


def test_read_run_skips_corrupt_lines(tmp_path):
    """A write interrupted mid-flush leaves a terminated fragment; the
    reader skips it instead of crashing summarize/diff on exactly the
    run whose telemetry survived an I/O incident."""
    from tpu_hc_bench.obs import metrics as obs_metrics

    mdir = tmp_path / "m"
    mdir.mkdir()
    (mdir / "metrics.jsonl").write_text(
        '{"kind": "window", "step": 2}\n'
        '{"kind": "window", "st\n'               # the fragment
        '{"kind": "summary", "mfu": 0.5}\n')
    _, records = obs_metrics.read_run(str(mdir))
    assert [r["kind"] for r in records] == ["window", "summary"]


def test_maybe_restore_warns_on_sentinel_less_dirs(mesh8, tmp_path):
    """Sentinel-less step dirs (crashed saves or pre-sentinel-era
    checkpoints) must produce a loud warning, not a silent restart."""
    ckdir = tmp_path / "ck"
    (ckdir / "step_00000005").mkdir(parents=True)
    out = []
    driver.run_benchmark(tiny_cfg(train_dir=str(ckdir), num_batches=2),
                         print_fn=out.append)
    warn = [l for l in out if "WARNING" in l and "sentinel" in l]
    assert warn and "step_00000005" in warn[0]


def test_latest_step_ignores_partial_dirs(tmp_path):
    from tpu_hc_bench.utils import checkpoint as ckpt

    state = _tiny_state()
    _save_steps(state, tmp_path, (1, 2))
    # a crash mid-save leaves a sentinel-less dir and a .tmp dir —
    # neither may be discovered as "latest"
    (tmp_path / "step_00000009").mkdir()
    (tmp_path / "step_00000007.tmp").mkdir()
    assert ckpt.complete_steps(tmp_path) == [1, 2]
    assert ckpt.latest_step(tmp_path) == 2
    restored = ckpt.restore(state, tmp_path)     # newest COMPLETE step
    assert int(np.asarray(restored.step)) == 2
    with pytest.raises(FileNotFoundError, match="incomplete"):
        ckpt.restore(state, tmp_path, step=9)


def test_retention_gc(tmp_path):
    from tpu_hc_bench.utils import checkpoint as ckpt

    state = _tiny_state()
    _save_steps(state, tmp_path, (1, 2, 3, 4))
    (tmp_path / "step_00000002.tmp").mkdir()     # stale partial write
    deleted = ckpt.gc_checkpoints(tmp_path, keep=2)
    assert deleted == [1, 2]
    assert ckpt.complete_steps(tmp_path) == [3, 4]
    assert not (tmp_path / "step_00000002.tmp").exists()
    assert ckpt.gc_checkpoints(tmp_path, keep=0) == []   # 0 = keep all


# ---------------------------------------------------------------------
# fetcher / prefetch error propagation (the "real error, not a hang"
# regression tests)


class _PoisonHandle:
    """jax.device_get(np.asarray) calls __array__ — raise the real error
    there, exactly where a poisoned data iterator's fetch would."""

    def __array__(self, dtype=None):
        raise ValueError("poisoned batch payload")


def test_fetcher_propagates_original_error_not_hang(mesh8):
    timeline = driver._AsyncTimeline(num_batches=4, display_every=2,
                                     global_batch=2)
    with pytest.raises(ValueError, match="poisoned batch payload") as ei:
        timeline.start(_PoisonHandle())
    # the original fetch-thread traceback survives the cross-thread
    # re-raise: the innermost frames are _run/device_get, not check()
    frames = []
    tb = ei.value.__traceback__
    while tb is not None:
        frames.append(tb.tb_frame.f_code.co_name)
        tb = tb.tb_next
    assert "_run" in frames


def test_fetcher_record_surfaces_error(mesh8):
    import jax.numpy as jnp

    timeline = driver._AsyncTimeline(num_batches=8, display_every=2,
                                     global_batch=2)
    timeline.start(jnp.float32(0.0))
    with pytest.raises(ValueError, match="poisoned batch payload"):
        for i in range(1, 9):
            timeline.record(i, _PoisonHandle())
            time.sleep(0.01)


def test_prefetch_propagates_iterator_error():
    def poisoned():
        yield 1
        yield 2
        raise ValueError("poisoned iterator")

    got = []
    with pytest.raises(ValueError, match="poisoned iterator"):
        for x in driver._prefetch(poisoned(), lookahead=2):
            got.append(x)
    assert got == [1]     # lookahead was mid-flight when the poison hit


# ---------------------------------------------------------------------
# exit codes + subprocess end-to-end


def test_exit_codes_distinct_and_documented():
    codes = {resilience.EXIT_OK, resilience.EXIT_ZERO_THROUGHPUT,
             resilience.EXIT_WATCHDOG, resilience.EXIT_PREEMPTED}
    assert len(codes) == 4
    readme = (REPO / "README.md").read_text()
    for code in (resilience.EXIT_WATCHDOG, resilience.EXIT_PREEMPTED):
        assert str(code) in readme


def _launch(tmp_path, *extra, num_batches=6, timeout=240):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    cmd = [sys.executable, "-m", "tpu_hc_bench", "1", "0", "2", "ici",
           "--model", "trivial", "--num_classes", "10",
           "--num_warmup_batches", "1", "--num_batches", str(num_batches),
           "--display_every", "2", "--virtual_devices", "8",
           *extra]
    return subprocess.run(cmd, cwd=REPO, env=env, capture_output=True,
                          text=True, timeout=timeout)


def test_watchdog_aborts_hung_run_subprocess(tmp_path):
    """hang@N + --step_timeout_s: the run aborts with the distinct
    watchdog exit code and a full thread-stack dump, instead of hanging
    until the 60 s injected hang (or a real deadlock) resolves."""
    t0 = time.monotonic()
    proc = _launch(tmp_path, "--inject_fault", "hang@2:60",
                   "--step_timeout_s", "1.0", num_batches=4)
    elapsed = time.monotonic() - t0
    assert proc.returncode == resilience.EXIT_WATCHDOG, proc.stderr[-2000:]
    assert "watchdog: no step completed" in proc.stderr
    assert "Thread" in proc.stderr          # faulthandler stack dump
    assert "fire_step_faults" in proc.stderr  # names the hung frame
    assert elapsed < 55                     # did NOT sit out the hang


@pytest.mark.slow
def test_kill_resume_e2e_subprocess(tmp_path):
    """The full preemption contract: sigterm@N -> exit EXIT_PREEMPTED
    with an emergency checkpoint; relaunch with --resume=auto continues
    from it with bitwise-identical params (fingerprint log lines)."""
    ckdir = str(tmp_path / "ck")
    proc1 = _launch(tmp_path, "--inject_fault", "sigterm@2",
                    "--train_dir", ckdir)
    assert proc1.returncode == resilience.EXIT_PREEMPTED, \
        proc1.stdout[-2000:] + proc1.stderr[-2000:]
    assert "emergency checkpoint saved" in proc1.stdout
    fp1 = [l for l in proc1.stdout.splitlines()
           if "params fingerprint" in l]
    assert fp1

    proc2 = _launch(tmp_path, "--resume", "auto", "--train_dir", ckdir)
    assert proc2.returncode == resilience.EXIT_OK, \
        proc2.stdout[-2000:] + proc2.stderr[-2000:]
    assert "restored checkpoint step 3" in proc2.stdout
    fp2 = [l for l in proc2.stdout.splitlines()
           if "params fingerprint" in l]
    assert fp2[0] == fp1[0]


