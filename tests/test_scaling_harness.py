"""Smoke test for the scaling-table harness (scripts/scaling_table.py).

One real 2-process cell through the literal CLI on a tiny member/protocol
— proves the harness end to end (hostfile + coordinator-port wiring,
rank spawn, throughput parse, table emit) in the default gate, so the
full-protocol table recorded in BASELINE.md stays reproducible.
"""

import json
import subprocess
import sys
from pathlib import Path

import pytest

from tpu_hc_bench._compat import CAPABILITIES

REPO = Path(__file__).resolve().parent.parent


@pytest.mark.skipif(
    not CAPABILITIES["cpu_multiprocess_collectives"],
    reason="this jax's CPU backend cannot execute cross-process "
           "collectives; the surviving rank hangs until the harness "
           "timeout (same gate as tests/test_multiprocess.py)")
def test_scaling_harness_two_process_cell(tmp_path):
    out_dir = tmp_path / "scaling"
    r = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "scaling_table.py"),
         "--worlds", "2", "--fabrics", "ici", "--models", "lenet",
         "--batch", "1", "--warmup", "1", "--batches", "2",
         "--out", str(out_dir), "--timeout", "500"],
        capture_output=True, text=True, timeout=540, cwd=REPO)
    assert r.returncode == 0, r.stdout + r.stderr
    rows = [json.loads(l) for l in
            (out_dir / "scaling.jsonl").read_text().splitlines()]
    assert len(rows) == 1
    row = rows[0]
    assert row["world"] == 2 and row["fabric"] == "ici"
    assert row["total_ex_per_sec"] > 0
    table = (out_dir / "scaling.md").read_text()
    assert "| lenet | ici | 2 |" in table
    # round 7: every cell leaves an obs.metrics artifact — rank 0 of the
    # REAL 2-process run wrote the merged record (worker-0-writes rule)
    cell = out_dir / "obs" / "w2_ici_lenet"
    assert row["metrics_dir"] == str(cell)
    manifest = json.loads((cell / "manifest.json").read_text())
    assert manifest["process_count"] == 2
    records = [json.loads(l) for l in
               (cell / "metrics.jsonl").read_text().splitlines()]
    assert records and records[-1]["kind"] == "summary"
