"""Sequence-parallel attention: ring + Ulysses vs dense reference.

Runs on the 8-virtual-device CPU mesh (conftest).  Each test shard-maps the
sequence-parallel implementation over a seq-sharded mesh and checks the
gathered output against single-device dense attention on the full sequence.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from tpu_hc_bench.parallel import sequence as seq


def _qkv(b=2, s=32, h=4, d=8, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    shape = (b, s, h, d)
    return tuple(jax.random.normal(k, shape, dtype) for k in ks)


def _seq_mesh(devices, n):
    return Mesh(np.array(devices[:n]), (seq.SEQ_AXIS,))


def _run_sharded(fn, mesh, q, k, v):
    spec = P(None, seq.SEQ_AXIS)
    mapped = jax.jit(jax.shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    ))
    return mapped(q, k, v)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("n_shards", [2, 4, 8])
def test_ring_matches_dense(devices, n_shards, causal):
    q, k, v = _qkv()
    ref = seq.dense_attention(q, k, v, causal=causal)
    mesh = _seq_mesh(devices, n_shards)
    out = _run_sharded(
        lambda q, k, v: seq.ring_attention(q, k, v, causal=causal),
        mesh, q, k, v,
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("n_shards", [2, 4])
def test_ulysses_matches_dense(devices, n_shards, causal):
    q, k, v = _qkv()
    ref = seq.dense_attention(q, k, v, causal=causal)
    mesh = _seq_mesh(devices, n_shards)
    out = _run_sharded(
        lambda q, k, v: seq.ulysses_attention(q, k, v, causal=causal),
        mesh, q, k, v,
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_flash_matches_dense(devices, causal):
    """ulysses_flash: all-to-all resharding + Pallas flash local attention
    (interpreter mode on CPU) must match unsharded dense."""
    q, k, v = _qkv()
    ref = seq.dense_attention(q, k, v, causal=causal)
    mesh = _seq_mesh(devices, 2)
    out = _run_sharded(
        lambda q, k, v: seq.local_attention(
            q, k, v, impl="ulysses_flash", axis_name=seq.SEQ_AXIS,
            causal=causal),
        mesh, q, k, v,
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_ulysses_flash_backward(devices):
    """Grads flow through the all-to-all + flash custom-VJP composition.

    Differentiated at the *global* level (shard_map inside the loss), the
    well-defined formulation — per-rank grad seeding inside shard_map
    would double-count through the collectives."""
    q, k, v = _qkv(s=16)
    mesh = _seq_mesh(devices, 2)
    spec = P(None, seq.SEQ_AXIS)
    mapped = jax.shard_map(
        lambda q, k, v: seq.local_attention(
            q, k, v, impl="ulysses_flash", axis_name=seq.SEQ_AXIS,
            causal=True),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )
    grad_fn = jax.jit(jax.grad(
        lambda q, k, v: jnp.sum(mapped(q, k, v) ** 2), argnums=(0, 1, 2)))
    gq, gk, gv = grad_fn(q, k, v)

    ref_gq, ref_gk, ref_gv = jax.grad(
        lambda q, k, v: jnp.sum(
            seq.dense_attention(q, k, v, causal=True) ** 2),
        argnums=(0, 1, 2),
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(gq), np.asarray(ref_gq),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(gk), np.asarray(ref_gk),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(gv), np.asarray(ref_gv),
                               rtol=1e-4, atol=1e-5)


def test_ring_bf16_stable(devices):
    """bf16 inputs accumulate in f32: close to the f32 dense reference."""
    q, k, v = _qkv(dtype=jnp.bfloat16)
    ref = seq.dense_attention(q.astype(jnp.float32), k.astype(jnp.float32),
                              v.astype(jnp.float32))
    mesh = _seq_mesh(devices, 4)
    out = _run_sharded(seq.ring_attention, mesh, q, k, v)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, np.float32), np.asarray(ref),
                               rtol=0.05, atol=0.05)


def test_ulysses_rejects_bad_heads(devices):
    q, k, v = _qkv(h=3)
    mesh = _seq_mesh(devices, 2)
    with pytest.raises(ValueError, match="not divisible"):
        _run_sharded(seq.ulysses_attention, mesh, q, k, v)


def test_ring_composes_with_data_axis(devices):
    """2-D (data, seq) mesh: DP on batch x SP on sequence, one shard_map."""
    q, k, v = _qkv(b=4, s=16)
    ref = seq.dense_attention(q, k, v)
    mesh = Mesh(np.array(devices).reshape(2, 4), ("data", seq.SEQ_AXIS))
    spec = P("data", seq.SEQ_AXIS)
    mapped = jax.jit(jax.shard_map(
        seq.ring_attention, mesh=mesh,
        in_specs=(spec, spec, spec), out_specs=spec, check_vma=False,
    ))
    out = mapped(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_local_attention_dispatch(devices):
    q, k, v = _qkv()
    ref = seq.dense_attention(q, k, v)
    out = seq.local_attention(q, k, v, impl="dense")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref))
    with pytest.raises(ValueError, match="unknown attention impl"):
        seq.local_attention(q, k, v, impl="bogus", axis_name=seq.SEQ_AXIS)
    with pytest.raises(ValueError, match="unknown attention impl"):
        seq.local_attention(q, k, v, impl="bogus")   # even without an axis
    with pytest.raises(ValueError, match="requires axis_name"):
        seq.local_attention(q, k, v, impl="ring")    # sharded impl, no axis


def test_bert_forward_seq_parallel_matches_dense(devices):
    """Whole-model SP: BertMLM shard-mapped over a (data, seq) mesh with
    ring attention reproduces the unsharded dense forward — including the
    per-shard position-embedding offset."""
    from tpu_hc_bench.models.bert import BertMLM

    B, S = 4, 32
    tokens = jax.random.randint(jax.random.PRNGKey(0), (B, S), 0, 64)
    dense = BertMLM(vocab_size=64, hidden=32, num_layers=2, heads=4,
                    ffn=64, max_len=S)
    variables = dense.init(jax.random.PRNGKey(1), tokens, train=False)
    ref = dense.apply(variables, tokens, train=False)

    sharded = BertMLM(vocab_size=64, hidden=32, num_layers=2, heads=4,
                      ffn=64, max_len=S, attention_impl="ring",
                      seq_axis=seq.SEQ_AXIS)
    mesh = Mesh(np.array(devices).reshape(2, 4), ("data", seq.SEQ_AXIS))
    fn = jax.jit(jax.shard_map(
        lambda v, t: sharded.apply(v, t, train=False),
        mesh=mesh, in_specs=(P(), P("data", seq.SEQ_AXIS)),
        out_specs=P("data", seq.SEQ_AXIS), check_vma=False,
    ))
    out = fn(variables, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_gpt_forward_seq_parallel_matches_dense(devices):
    """Causal whole-model SP: GPTLM with ring attention over a seq axis
    reproduces the unsharded causal forward (positions + causal mask)."""
    from tpu_hc_bench.models.gpt import GPTLM

    B, S = 2, 32
    tokens = jax.random.randint(jax.random.PRNGKey(0), (B, S), 1, 64)
    dense = GPTLM(vocab_size=64, hidden=32, num_layers=2, heads=4,
                  ffn=64, max_len=S)
    variables = dense.init(jax.random.PRNGKey(1), tokens, train=False)
    ref = dense.apply(variables, tokens, train=False)

    sharded = GPTLM(vocab_size=64, hidden=32, num_layers=2, heads=4,
                    ffn=64, max_len=S, attention_impl="ring",
                    seq_axis=seq.SEQ_AXIS)
    mesh = Mesh(np.array(devices).reshape(2, 4), ("data", seq.SEQ_AXIS))
    fn = jax.jit(jax.shard_map(
        lambda v, t: sharded.apply(v, t, train=False),
        mesh=mesh, in_specs=(P(), P("data", seq.SEQ_AXIS)),
        out_specs=P("data", seq.SEQ_AXIS), check_vma=False,
    ))
    out = fn(variables, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_degenerate_sp_single_chip(mesh8):
    """Round 3 (VERDICT #9): a seq-sharded attention impl at
    --sequence_parallel=1 runs on a size-1 seq axis — world-1 collectives
    — and must match the plain flash/dense run's loss (same math, so the
    hardware row measures pure SP-machinery overhead).

    Slow lane: three full driver compiles for the degenerate sp=1 row;
    the sp=2/4 tests above pin bitwise attention parity in the default
    lane, and test_degenerate_sp_composes_with_dp_only keeps the
    degenerate-axis wiring checked cheaply."""
    from tpu_hc_bench import flags as fl
    from tpu_hc_bench.train import driver as drv

    def run(impl, sp=1):
        cfg = fl.BenchmarkConfig(
            model="bert_tiny", batch_size=1, num_warmup_batches=1,
            num_batches=2, display_every=1, attention_impl=impl,
            sequence_parallel=sp).resolve()
        out = []
        res = drv.run_benchmark(cfg, print_fn=out.append)
        return res, "\n".join(out)

    res_dense, _ = run("dense")
    res_ring, text = run("ring")
    assert "1 shards x 64 tokens/shard" in text
    # same params/data; the SP step folds dropout keys over the extra
    # (size-1) seq axis so the masks differ — losses agree to ~1%, and the
    # bitwise attention parity is pinned by the sp=2/4 tests above
    np.testing.assert_allclose(res_ring.final_loss, res_dense.final_loss,
                               rtol=5e-2)
    res_uf, _ = run("ulysses_flash")
    np.testing.assert_allclose(res_uf.final_loss, res_dense.final_loss,
                               rtol=5e-2)


def test_degenerate_sp_composes_with_dp_only():
    """The degenerate seq axis is keyed on sequence_parallel>1 nowhere, so
    PP/EP/TP under it would silently misconfigure — rejected at resolve."""
    from tpu_hc_bench import flags as fl

    for kw in (dict(pipeline_parallel=2), dict(expert_parallel=2),
               dict(model_parallel=2)):
        with pytest.raises(ValueError, match="plain data parallelism"):
            fl.BenchmarkConfig(attention_impl="ring", **kw).resolve()
    # host fabric binds no seq axis
    from tpu_hc_bench.train import driver as drv

    cfg = fl.BenchmarkConfig(model="bert_tiny", batch_size=1,
                             attention_impl="ring").resolve()
    with pytest.raises(ValueError, match="device fabric"):
        drv.run_benchmark(cfg, fabric_name="sock", print_fn=lambda _: None)
    # the replicated->psum translation is in the audit trail
    cfg = fl.BenchmarkConfig(attention_impl="ring",
                             variable_update="replicated").resolve()
    assert cfg.variable_update == "psum"
    assert any("replicated->psum" in l for l in cfg.summary_lines())
