"""Serving lane (tpu_hc_bench/serve/, round 16).

Default lane shares ONE session-scoped warmed engine (``moe_engine``,
3 AOT buckets of the tiny MoE member) plus one classify engine on
``trivial`` — zero driver runs, every closed-loop test drives the
scheduler in VIRTUAL time (``VirtualClock``: sleeps are instant, step
costs are modeled), so the whole module costs a few engine warmups.

The load-bearing pins:

- **decode parity**: the engine's incremental paged decode reproduces
  the model's own full-context forward token-for-token (greedy), for
  the MoE/GPT family — the correctness claim under the paged KV cache;
- **zero lowering after warmup**: ``lower_count`` and the compiled
  ladder are frozen across runs, off-ladder shapes raise instead of
  compiling, and the ``serve-bucket-recompile`` lint guards the source;
- **the A/B property**: at the same offered load, continuous batching
  beats the static control on p99 latency and goodput-under-load
  (deterministic in virtual time);
- **request-only obs streams**: ``obs summarize``/``diff``/``watch``
  render a serving run (zero ``step``-keyed records) labeled, with no
  traceback and no empty training table — the pinned regression for
  the step-keyed assumption;
- serve tuner space / ``<model>@serve`` registry rows / staleness lint
  lane checks.

Subprocess e2e (CLI exit codes, bench_serve A/B) and the closed-loop
arrival sweep are slow-marked.
"""

from __future__ import annotations

import io
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from tpu_hc_bench import flags
from tpu_hc_bench.analysis import lints
from tpu_hc_bench.data.tokens import PromptSampler
from tpu_hc_bench.obs import metrics as obs_metrics
from tpu_hc_bench.serve import arrivals, slo
from tpu_hc_bench.serve import engine as engine_mod
from tpu_hc_bench.tune import prune, registry, space

# the session engine fixtures (serve_cfg/moe_engine/moe_requests/
# moe_ab/trivial_engine) live in conftest.py since round 20 — shared
# with test_requests_obs; the shared cost table keeps this module's
# VirtualClock replays deterministic against the moe_ab fixture runs
from conftest import SERVE_VCOSTS as VCOSTS  # noqa: E402


def _quiet(_msg):
    pass


# --- arrivals ---------------------------------------------------------


def test_arrival_processes_deterministic_and_sorted():
    for proc in arrivals.PROCESSES:
        a = arrivals.arrival_times(proc, rate=20.0, n=64, seed=3)
        b = arrivals.arrival_times(proc, rate=20.0, n=64, seed=3)
        np.testing.assert_array_equal(a, b)
        assert (np.diff(a) >= 0).all() and a.shape == (64,)
        c = arrivals.arrival_times(proc, rate=20.0, n=64, seed=4)
        assert not np.array_equal(a, c)


def test_arrival_mean_rate_shared_across_processes():
    # all three shapes hold the same MEAN rate (the A/B axis): n
    # arrivals at rate r span ~n/r seconds
    n, rate = 4096, 50.0
    for proc in arrivals.PROCESSES:
        t = arrivals.arrival_times(proc, rate=rate, n=n, seed=0)
        assert t[-1] == pytest.approx(n / rate, rel=0.25), proc


def test_arrival_validation_loud():
    with pytest.raises(ValueError, match="process"):
        arrivals.arrival_times("uniform", 1.0, 4)
    with pytest.raises(ValueError, match="rate"):
        arrivals.arrival_times("poisson", 0.0, 4)
    with pytest.raises(ValueError, match="arrival"):
        arrivals.arrival_times("poisson", 1.0, 0)


def test_sampled_lengths_in_bounds():
    lens = arrivals.sample_lengths(512, max_len=32, seed=1)
    assert lens.min() >= 1 and lens.max() <= 32
    assert len(np.unique(lens)) > 4     # a distribution, not a constant


def test_build_requests_deterministic(serve_cfg, moe_engine, moe_requests):
    again = arrivals.build_requests(serve_cfg, moe_engine.spec.vocab_size)
    assert len(again) == serve_cfg.num_requests
    for r1, r2 in zip(moe_requests, again):
        assert r1.arrival_s == r2.arrival_s
        assert r1.output_len == r2.output_len
        np.testing.assert_array_equal(r1.prompt, r2.prompt)


def test_build_requests_classify_member(trivial_engine):
    reqs = arrivals.build_requests(trivial_engine.cfg, None)
    assert all(r.prompt is None and r.output_len == 1 for r in reqs)


# --- prompt sampler ---------------------------------------------------


def test_prompt_sampler_synthetic_deterministic():
    s = PromptSampler(vocab_size=64, seed=5)
    a, b = s.sample(3, 10), s.sample(3, 10)
    np.testing.assert_array_equal(a, b)
    assert a.dtype == np.int32 and a.shape == (10,)
    assert a.min() >= 1 and a.max() < 64      # 0 reserved for eod/pad
    assert not np.array_equal(a, s.sample(4, 10))
    with pytest.raises(ValueError, match="length"):
        s.sample(0, 0)


# --- flag surface -----------------------------------------------------


def test_serve_buckets_parsing():
    assert flags.parse_serve_buckets("auto", 8) == (1, 2, 4, 8)
    assert flags.parse_serve_buckets("auto", 6) == (1, 2, 4, 6)
    assert flags.parse_serve_buckets("2,8,4", 8) == (2, 4, 8)
    with pytest.raises(ValueError, match="serve_buckets"):
        flags.parse_serve_buckets("2,x", 8)
    with pytest.raises(ValueError, match="positive"):
        flags.parse_serve_buckets("0,2", 8)
    with pytest.raises(ValueError, match="max_in_flight"):
        flags.parse_serve_buckets("auto", 0)


def test_train_only_flags_rejected_in_serve_lane():
    argv = ["--model", "moe_tiny", "--gradient_accumulation_steps", "8"]
    with pytest.raises(SystemExit):
        # argparse errors exit; the resolve-level rejection needs valid
        # parse first
        flags.parse_flags(["--no_such_flag"], workload="serve")
    with pytest.raises(ValueError, match="training-only"):
        flags.parse_flags(argv, workload="serve")
    # an explicitly typed DEFAULT value still rejects (loudness is
    # about what the operator said, not what changed)
    with pytest.raises(ValueError, match="training-only"):
        flags.parse_flags(
            ["--model", "moe_tiny", "--optimizer", "sgd"],
            workload="serve")


def test_serve_only_flags_rejected_in_train_lane():
    with pytest.raises(ValueError, match="serving-lane"):
        flags.parse_flags(["--model", "trivial", "--arrival_rate", "4"])
    # programmatic construction: non-default serve field on a training
    # config dies too
    with pytest.raises(ValueError, match="serving-lane"):
        flags.BenchmarkConfig(model="trivial", batching="static").resolve()


def test_serve_resolve_validations_loud():
    def cfg(**kw):
        return flags.BenchmarkConfig(
            model="moe_tiny", workload="serve", **kw)

    with pytest.raises(ValueError, match="arrival_rate"):
        cfg(arrival_rate=0.0).resolve()
    with pytest.raises(ValueError, match="num_requests"):
        cfg(num_requests=0).resolve()
    with pytest.raises(ValueError, match="kv_page_size"):
        cfg(kv_page_size=0).resolve()
    with pytest.raises(ValueError, match="batching"):
        cfg(batching="dynamic").resolve()
    c = cfg().resolve()
    assert c.workload == "serve"
    assert "serve" in " ".join(c.summary_lines())


# --- page allocator / bucket ladder -----------------------------------


def test_page_allocator_reserves_trash_page():
    alloc = engine_mod.PageAllocator(5)
    assert alloc.free_pages == 4
    pages = alloc.alloc(4)
    assert 0 not in pages and sorted(pages) == [1, 2, 3, 4]
    assert alloc.alloc(1) is None       # exhausted, never page 0
    alloc.free(pages)
    assert alloc.free_pages == 4
    with pytest.raises(ValueError, match="trash"):
        engine_mod.PageAllocator(1)


def test_pick_bucket_off_ladder_raises():
    assert engine_mod.pick_bucket((1, 2, 4), 3) == 4
    with pytest.raises(ValueError, match="no bucket"):
        engine_mod.pick_bucket((1, 2, 4), 5)


# --- the engine: closed loop in virtual time --------------------------


def test_all_requests_complete_both_arms(moe_ab, serve_cfg):
    for arm in ("static", "continuous"):
        s = moe_ab[arm]["summary"]
        assert s["completed"] == s["requests"] == serve_cfg.num_requests
        assert s["batching"] == arm
        assert s["tokens"] > 0 and s["tokens_per_s"] > 0
        assert 0.0 < s["goodput"] <= 1.0
        assert s["decode_steps"] > 0 and s["prefill_steps"] == 8


def test_continuous_beats_static_in_virtual_time(moe_ab):
    """The headline A/B property, deterministic under VirtualClock: at
    the same offered load, admit/retire-per-step beats run-to-
    completion batching on the latency tail AND on goodput."""
    st = moe_ab["static"]["summary"]
    ct = moe_ab["continuous"]["summary"]
    assert ct["p99_e2e_ms"] < st["p99_e2e_ms"]
    assert ct["goodput"] > st["goodput"]


def test_zero_lowering_after_warmup(moe_engine, moe_requests):
    """The compiled ladder is frozen at construction: replaying traffic
    never lowers a new program or grows the bucket set."""
    before = (moe_engine.lower_count, set(moe_engine.compiled))
    moe_engine.run(moe_requests, batching="continuous",
                   clock=engine_mod.VirtualClock(VCOSTS))
    assert (moe_engine.lower_count, set(moe_engine.compiled)) == before


def test_off_ladder_request_rejected(moe_engine, serve_cfg):
    big = arrivals.Request(
        rid=0, arrival_s=0.0,
        prompt=np.ones(serve_cfg.max_prompt_len + 1, np.int32),
        output_len=1)
    with pytest.raises(ValueError, match="compiled ladder"):
        moe_engine.run([big], clock=engine_mod.VirtualClock(VCOSTS))


def test_engine_run_deterministic(moe_engine, moe_requests, moe_ab):
    """Same trace + same virtual clock -> identical generated tokens
    and step counts (arms share one engine; no hidden state)."""
    replay = moe_engine.run(moe_requests, batching="continuous",
                            clock=engine_mod.VirtualClock(VCOSTS))
    first = moe_ab["continuous"]["summary"]
    for k in ("decode_steps", "prefill_steps", "tokens", "completed"):
        assert replay[k] == first[k], k


def test_classify_member_serves_single_forward(trivial_engine):
    reqs = arrivals.build_requests(trivial_engine.cfg, None)
    s = trivial_engine.run(reqs, clock=engine_mod.VirtualClock(VCOSTS))
    assert s["completed"] == len(reqs)
    assert s["classify_steps"] > 0 and s["decode_steps"] == 0
    assert s["p99_ttft_ms"] == s["p99_e2e_ms"]   # one forward, no decode


def test_non_servable_member_rejected():
    cfg = flags.BenchmarkConfig(
        model="bert_tiny", workload="serve").resolve()
    with pytest.raises(ValueError, match="MLM"):
        engine_mod.ServeEngine(cfg, print_fn=_quiet)


# --- decode parity: incremental paged decode vs full forward ----------


def test_paged_decode_matches_full_forward(moe_engine, moe_ab):
    """Token-for-token greedy parity: for every request, the engine's
    incremental paged decode (per-step KV gather over page tables)
    reproduces the model's own full-context forward.  The engine
    dispatches MoE ragged (zero-drop) for exactly this property."""
    import jax.numpy as jnp

    from tpu_hc_bench.models import create_model

    ref_model, _ = create_model(
        "moe_tiny", dtype=jnp.float32, seq_len=moe_engine.max_ctx,
        moe_impl="ragged")

    recs = [json.loads(l) for l in open(
        os.path.join(moe_ab["continuous"]["mdir"], "metrics.jsonl"))]
    requests = {r.rid: r for r in arrivals.build_requests(
        moe_engine.cfg, moe_engine.spec.vocab_size)}
    checked = 0
    for rec in recs:
        if rec.get("kind") != "request" or checked >= 3:
            continue
        req = requests[rec["id"]]
        seq = list(np.asarray(req.prompt))
        want = rec["generated"]
        got = []
        for _ in range(len(want)):
            toks = np.zeros((1, moe_engine.max_ctx), np.int32)
            toks[0, :len(seq)] = seq
            logits = ref_model.apply(
                moe_engine.variables, jnp.asarray(toks), train=False)
            nxt = int(np.asarray(logits)[0, len(seq) - 1].argmax())
            got.append(nxt)
            seq.append(nxt)
        assert got == want, f"request {rec['id']}: {got} != {want}"
        checked += 1
    assert checked == 3


def test_static_arm_admission_bounded_by_kv_pool(moe_engine):
    """Regression: the static arm sized its batch by max_in_flight
    alone, so a pool smaller than a full batch's worst-case pages
    (legal per resolve(), which only guarantees ONE request, and
    exactly what the tuner's half-pool lever produces) crashed the
    alloc assert at admission.  Page-bounded admission completes the
    trace with smaller batches instead."""
    cfg = flags.BenchmarkConfig(
        model="moe_tiny", workload="serve", arrival_rate=50.0,
        num_requests=6, max_prompt_len=8, max_output_len=4,
        max_in_flight=2, kv_page_size=4, seed=0).resolve()
    reqs = arrivals.build_requests(cfg, moe_engine.spec.vocab_size)
    saved = moe_engine.num_pages
    try:
        # 1 trash page + exactly one request's worst case: a full
        # cap=2 batch can never fit (the warmed KV pool is larger, so
        # page indices stay in range)
        moe_engine.num_pages = 1 + moe_engine.table_width
        s = moe_engine.run(reqs, batching="static",
                           clock=engine_mod.VirtualClock(VCOSTS))
    finally:
        moe_engine.num_pages = saved
    assert s["completed"] == 6


# --- SLO fold + obs stream --------------------------------------------


def test_percentile_matches_numpy_convention():
    vals = [3.0, 1.0, 4.0, 1.5, 9.0, 2.6, 5.3]
    for q in (50, 95, 99):
        assert slo.percentile(vals, q) == pytest.approx(
            float(np.percentile(vals, q)))
    assert slo.percentile([], 99) == 0.0
    assert slo.percentile([7.0], 50) == 7.0


def test_metrics_stream_carries_request_records(moe_ab, serve_cfg):
    recs = [json.loads(l) for l in open(
        os.path.join(moe_ab["continuous"]["mdir"], "metrics.jsonl"))]
    reqs = [r for r in recs if r.get("kind") == "request"]
    assert len(reqs) == serve_cfg.num_requests
    for r in reqs:
        assert r["e2e_ms"] >= r["ttft_ms"] >= 0
        assert r["queue_ms"] >= 0 and r["output_len"] >= 1
    assert sum(1 for r in recs if r.get("kind") == "serve_summary") == 1
    assert not any(r.get("kind") == "window" for r in recs)


def test_fold_serve_records_recomputes_truncated_stream(moe_ab):
    recs = [json.loads(l) for l in open(
        os.path.join(moe_ab["continuous"]["mdir"], "metrics.jsonl"))]
    # a stream truncated before its serve_summary still reports
    # percentiles from the request records
    cut = [r for r in recs if r.get("kind") != "serve_summary"]
    fold = slo.fold_serve_records(cut)
    assert fold is not None and fold["completed"] == 8
    assert "p99_e2e_ms" in fold and fold.get("wall_s") is None
    # training streams cost one scan and fold to None
    assert slo.fold_serve_records(
        [{"kind": "window", "step": 3}]) is None


def test_summarize_labels_request_only_stream(moe_ab):
    """The pinned regression: a stream with request records and ZERO
    step-keyed records renders labeled — no traceback, no empty
    training table."""
    lines = obs_metrics.summarize_run(moe_ab["continuous"]["mdir"])
    text = "\n".join(lines)
    assert "serving run (request-keyed metrics" in text
    assert "serve: 8/8 requests" in text
    assert "ttft ms p50" in text
    assert "ex/sec" not in text          # no empty step table header


def test_diff_renders_serving_delta(moe_ab):
    lines = obs_metrics.diff_runs(moe_ab["static"]["mdir"],
                                  moe_ab["continuous"]["mdir"])
    text = "\n".join(lines)
    assert "serve metrics:" in text
    assert "p99 e2e ms" in text and "serve goodput" in text
    assert "batching arm differs: static -> continuous" in text
    assert "total ex/s" not in text      # no empty training table
    # serving-vs-training diff: serve rows only render when BOTH runs
    # serve; nothing crashes
    assert slo.serve_diff_lines({"p99_e2e_ms": 1.0}, None) == []


def test_watch_renders_and_completes_on_serving_run(moe_ab):
    from tpu_hc_bench.obs import watch as watch_mod

    out = io.StringIO()
    rc = watch_mod.watch(moe_ab["continuous"]["mdir"], out=out,
                         interval=0.01, timeout_s=5.0)
    assert rc == 0                       # serve_summary ends the watch
    text = out.getvalue()
    assert "p99 ttft" in text and "done" in text
    assert "(no progress records yet)" not in text


# --- serve tuner space / registry -------------------------------------


def test_serve_space_seed_first_and_valid():
    sp = space.serve_member_space("moe_tiny")
    assert sp[0] == space.serve_seed_candidate("moe_tiny")
    assert len({c.key for c in sp}) == len(sp) > 4
    assert all(c.workload == "serve" for c in sp)
    # every candidate resolves under the serving validity matrix
    res = prune.static_prune(sp)
    assert [s.journal_record() for s in res.skipped] == []
    assert len(res.survivors) == len(sp)


def test_serve_candidate_lever_validation():
    with pytest.raises(ValueError, match="serve lane"):
        space.Candidate.make("moe_tiny", {"batch_size": 8},
                             workload="serve")
    with pytest.raises(ValueError, match="train lane"):
        space.Candidate.make("moe_tiny", {"max_in_flight": 8})


def test_serve_search_promotes_lane_keyed_row(tmp_path):
    """Regression: promote() keyed a serve-lane search's row under the
    bare member name — unreachable by the serving lane's own
    ``--config=auto`` lookup (which reads ``<model>@serve``) AND
    clobbering the member's training row."""
    from tpu_hc_bench.tune import search

    stub = lambda c, rung, batches: {  # noqa: E731
        "per_chip": 100.0, "goodput": 0.9, "wall_s": 0.1}
    journal = search.run_search(
        "moe_tiny", str(tmp_path / "s"), "cpu-test-w1",
        settings=search.SearchSettings(budget_s=1e9),
        space=space.serve_member_space("moe_tiny"),
        runner=stub, print_fn=_quiet)
    assert journal["workload"] == "serve"
    regdir = tmp_path / "reg"
    registry.promote(journal, registry_dir=regdir)
    rows = registry.load_rows("cpu-test-w1", regdir)
    assert set(rows) == {"moe_tiny@serve"}


def test_serve_hbm_budget_checked_at_warmup(moe_engine):
    """``--hbm_budget`` in the serving lane is a real check, not a
    parsed-then-discarded knob: the warmed ladder's verdict prints
    before traffic and the compile record carries the accounting."""
    lines = []
    saved = moe_engine.cfg.hbm_budget
    try:
        moe_engine.cfg.hbm_budget = "1GB"
        moe_engine._check_hbm_budget(lines.append)
    finally:
        moe_engine.cfg.hbm_budget = saved
    # either a measured verdict against the budget or the loud
    # no-AOT-report warning — never silence
    assert any("budget" in ln for ln in lines)
    rec = moe_engine.compile_record["hbm_budget"]
    assert rec["budget_bytes"] == 2**30


def test_config_auto_resolves_serve_row(tmp_path, monkeypatch):
    hw = "cpu-test-w1"
    monkeypatch.setenv(registry.HW_ENV, hw)
    monkeypatch.setenv(registry.REGISTRY_ENV, str(tmp_path))
    (tmp_path / f"{hw}.json").write_text(json.dumps({
        "hardware": hw, "members": {
            "moe_tiny": {"overrides": {"batch_size": 32}, "score": 1.0},
            "moe_tiny@serve": {"overrides": {
                "max_in_flight": 4,       # applies
                "batch_size": 96,         # train lever: skipped w/ note
                "gone_flag": 1,           # dead: skipped w/ note
            }, "score": 2.0},
        }}))
    cfg = flags.BenchmarkConfig(
        model="moe_tiny", workload="serve", config="auto").resolve()
    assert cfg.max_in_flight == 4
    assert cfg.batch_size == flags.BenchmarkConfig.batch_size
    assert cfg.config_source == "auto"
    note = cfg.translations["config"]
    assert "moe_tiny@serve" in note
    assert "not a serve-lane lever" in note and "unknown flag" in note
    # the training lane never sees the @serve row
    tcfg = flags.BenchmarkConfig(model="moe_tiny", config="auto").resolve()
    assert tcfg.batch_size == 32 and tcfg.max_in_flight == \
        flags.BenchmarkConfig.max_in_flight


def test_config_auto_serve_falls_back_loudly(tmp_path, monkeypatch):
    monkeypatch.setenv(registry.HW_ENV, "cpu-test-w1")
    monkeypatch.setenv(registry.REGISTRY_ENV, str(tmp_path))
    cfg = flags.BenchmarkConfig(
        model="moe_tiny", workload="serve", config="auto").resolve()
    assert cfg.config_source == "baseline"
    assert "moe_tiny@serve" in cfg.translations["config"]


def test_staleness_lint_covers_serving_rows(tmp_path):
    (tmp_path / "hw.json").write_text(json.dumps({
        "hardware": "hw", "members": {
            "moe_tiny@serve": {"overrides": {
                "dead_knob": 1,           # no longer a field
                "batch_size": 8,          # the other lane's lever
                "max_in_flight": 4,       # fine
            }},
            "trivial": {"overrides": {"kv_pages": 9}},   # lane-crossed
        }}))
    found = lints.check_tuned_registry(tmp_path)
    msgs = {f.location.split(":", 1)[1]: f.message for f in found}
    assert "moe_tiny@serve/dead_knob" in msgs
    assert "serving row records the other lane's lever" in \
        msgs["moe_tiny@serve/batch_size"]
    assert "training row records the other lane's lever" in \
        msgs["trivial/kv_pages"]
    assert "moe_tiny@serve/max_in_flight" not in msgs


# --- serve-bucket-recompile lint --------------------------------------


BAD_ENGINE = """
import jax
class E:
    def decode_step(self, x):
        return jax.jit(lambda v: v + 1)(x)
"""

WARM_ENGINE = """
import jax
from tpu_hc_bench.obs import efficiency
class E:
    def __init__(self):
        self._warm()
    def _aot(self, fn, x):
        self.c = efficiency.aot_compile(jax.jit(fn), x)
    def _warm(self):
        self._aot(lambda v: v, 1)
    def decode_step(self, x):
        return self.c(x)
"""


def test_serve_recompile_lint_flags_traffic_path_jit():
    found = lints.lint_source_text(
        BAD_ENGINE, filename="tpu_hc_bench/serve/engine.py")
    assert [f.lint for f in found] == [lints.SERVE_RECOMPILE]
    assert "decode_step" in found[0].message
    # same source outside the serve package: not this lint's business
    assert not [f for f in lints.lint_source_text(
        BAD_ENGINE, filename="tpu_hc_bench/train/driver.py")
        if f.lint == lints.SERVE_RECOMPILE]


def test_serve_recompile_lint_exempts_warmup_namespace():
    found = [f for f in lints.lint_source_text(
        WARM_ENGINE, filename="tpu_hc_bench/serve/engine.py")
        if f.lint == lints.SERVE_RECOMPILE]
    assert found == []


def test_serve_recompile_lint_suppression():
    src = BAD_ENGINE.replace(
        "return jax.jit(lambda v: v + 1)(x)",
        "return jax.jit(lambda v: v + 1)(x)  "
        "# thb:lint-ok[serve-bucket-recompile]")
    found = [f for f in lints.lint_source_text(
        src, filename="tpu_hc_bench/serve/engine.py")
        if f.lint == lints.SERVE_RECOMPILE]
    assert found == []


def test_repo_serve_sources_lint_clean():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    serve_dir = os.path.join(repo, "tpu_hc_bench", "serve")
    found = []
    for name in sorted(os.listdir(serve_dir)):
        if name.endswith(".py"):
            found.extend(lints.lint_file(
                os.path.join(serve_dir, name)))
    found = [f for f in found if f.lint == lints.SERVE_RECOMPILE]
    assert found == [], [f.message for f in found]


# --- slow lane: subprocess e2e + closed-loop sweep --------------------


@pytest.mark.slow
def test_arrival_sweep_latency_monotone(moe_engine):
    """Closed-loop arrival sweep: deeper offered load never IMPROVES
    the p99 tail (virtual time keeps it deterministic), and every rate
    completes all requests with the ladder frozen."""
    p99s = []
    for rate in (10.0, 50.0, 200.0):
        cfg = flags.BenchmarkConfig(
            model="moe_tiny", workload="serve", arrival_rate=rate,
            num_requests=16, max_prompt_len=8, max_output_len=4,
            max_in_flight=2, kv_page_size=4, seed=0).resolve()
        reqs = arrivals.build_requests(cfg, moe_engine.spec.vocab_size)
        s = moe_engine.run(reqs, batching="continuous",
                           clock=engine_mod.VirtualClock(VCOSTS))
        assert s["completed"] == 16
        p99s.append(s["p99_e2e_ms"])
    assert p99s == sorted(p99s), p99s


@pytest.mark.slow
def test_llama_paged_decode_matches_full_forward(tmp_path):
    """Token-for-token greedy parity for the LlamaLM family — the
    RoPE per-row positions, GQA kv-head repeat, and SwiGLU param
    re-walk in serve/decode.py against the model's own full-context
    forward (the gpt/moe twin of this pin runs in the default lane;
    this one pays its own engine warmup, hence slow-marked)."""
    import jax.numpy as jnp

    from tpu_hc_bench.models import create_model

    cfg = flags.BenchmarkConfig(
        model="llama_tiny", workload="serve", arrival_rate=50.0,
        num_requests=3, max_prompt_len=8, max_output_len=4,
        max_in_flight=2, kv_page_size=4, seed=0).resolve()
    eng = engine_mod.ServeEngine(cfg, print_fn=_quiet)
    reqs = arrivals.build_requests(cfg, eng.spec.vocab_size)
    mdir = str(tmp_path / "llama")
    writer = obs_metrics.MetricsWriter(
        mdir, obs_metrics.run_manifest(
            cfg=cfg, extra={"workload": "serve"}))
    try:
        s = eng.run(reqs, batching="continuous", writer=writer,
                    clock=engine_mod.VirtualClock(VCOSTS))
    finally:
        writer.close()
    assert s["completed"] == 3 and s["post_warmup_compiles"] == 0

    ref_model, _ = create_model(
        "llama_tiny", dtype=jnp.float32, seq_len=eng.max_ctx)
    requests = {r.rid: r for r in reqs}
    recs = [json.loads(l) for l in open(
        os.path.join(mdir, "metrics.jsonl"))]
    checked = 0
    for rec in recs:
        if rec.get("kind") != "request":
            continue
        req = requests[rec["id"]]
        seq = list(np.asarray(req.prompt))
        want = rec["generated"]
        got = []
        for _ in range(len(want)):
            toks = np.zeros((1, eng.max_ctx), np.int32)
            toks[0, :len(seq)] = seq
            logits = ref_model.apply(
                eng.variables, jnp.asarray(toks), train=False)
            nxt = int(np.asarray(logits)[0, len(seq) - 1].argmax())
            got.append(nxt)
            seq.append(nxt)
        assert got == want, f"request {rec['id']}: {got} != {want}"
        checked += 1
    assert checked == 3


@pytest.mark.slow
def test_serve_cli_end_to_end(tmp_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    mdir = tmp_path / "run"
    proc = subprocess.run(
        [sys.executable, "-m", "tpu_hc_bench", "serve",
         "--model", "moe_tiny", "--arrival_rate", "50",
         "--num_requests", "8", "--max_prompt_len", "8",
         "--max_output_len", "4", "--max_in_flight", "2",
         "--kv_page_size", "4", "--metrics_dir", str(mdir)],
        capture_output=True, text=True, env=env, timeout=570,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "post-warmup compiles: 0" in proc.stdout
    assert "workload=serve" in proc.stdout
    assert (mdir / "metrics.jsonl").exists()
    # the summarize CLI renders the run labeled, exit 0, no traceback
    proc2 = subprocess.run(
        [sys.executable, "-m", "tpu_hc_bench.obs", "summarize",
         str(mdir)],
        capture_output=True, text=True, env=env, timeout=120)
    assert proc2.returncode == 0, proc2.stdout + proc2.stderr
    assert "serving run" in proc2.stdout
    assert "Traceback" not in proc2.stderr


@pytest.mark.slow
def test_bench_serve_ab_harness(tmp_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               BENCH_ARRIVAL_RATE="40", BENCH_REQUESTS="16",
               BENCH_SERVE_BUCKETS="auto")
    proc = subprocess.run(
        [sys.executable, "scripts/bench_serve.py",
         "--max_prompt_len", "8", "--max_output_len", "4",
         "--max_in_flight", "2", "--kv_page_size", "4",
         "--compile_cache", str(tmp_path / "cc"),
         "--metrics_root", str(tmp_path / "ab")],
        capture_output=True, text=True, env=env, timeout=570,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    rec = json.loads(proc.stdout)
    v = rec["extra"]["verdict"]
    assert v["continuous_beats_static_p99"]
    assert v["continuous_beats_static_goodput"]
    assert v["zero_post_warmup_compiles"]
    assert rec["extra"]["p99_ms"] > 0
