"""Serve-lane overload + failure survival (round 23).

Every degradation path of the serving engine, on the session-scoped
warmed ``moe_engine`` in VIRTUAL time — warmup is the lane's whole
cost, so the policy arms (shed / preempt / quarantine / drain) all
replay traces through the ONE engine, exactly like the faults A/B in
``scripts/bench_serve.py --mode faults``.

The load-bearing pins:

- **the fault grammar is shared**: ``--serve_faults`` parses through
  ``inject.split_entries`` and a malformed entry names BOTH lanes'
  vocabularies — one error message, two grammars;
- **requeue loses nothing**: a preempted-and-requeued request finishes
  with the exact token sequence of its unfaulted run, and its
  component attribution still sums to ``e2e_ms`` across residencies;
- **drain is exactly-once**: SIGTERM journals every unfinished
  request and ``--serve_resume`` serves each journaled rid exactly
  once — no request vanishes, none is served twice;
- **degradation is visible**: causes land in ``obs summarize`` and
  ``slo_lines``, the new spans are registered vocabulary, and
  ``obs regress`` gates ``shed_frac`` direction-aware.

The subprocess SIGTERM-mid-traffic e2e (real signal, real exit code
75, real journal on disk) is slow-marked like the other CLI e2es.
"""

from __future__ import annotations

import dataclasses
import json
import os
import subprocess
import sys

import pytest

from tpu_hc_bench import flags, resilience
from tpu_hc_bench.analysis import lints
from tpu_hc_bench.obs import kv as kv_mod
from tpu_hc_bench.obs import metrics as obs_metrics
from tpu_hc_bench.obs import regress, timeline
from tpu_hc_bench.obs import requests as requests_mod
from tpu_hc_bench.resilience import inject as inject_mod
from tpu_hc_bench.serve import engine as engine_mod
from tpu_hc_bench.serve import faults as faults_mod
from tpu_hc_bench.serve import slo

from conftest import SERVE_VCOSTS as VCOSTS  # noqa: E402


def _quiet(_msg):
    pass


def _burst(requests):
    """The trace with every arrival at t=0 — the only way a 2-slot
    engine ever sees admission pressure in virtual time."""
    return [dataclasses.replace(r, arrival_s=0.0) for r in requests]


def _records(mdir, kinds=("request",)):
    out = []
    with open(os.path.join(mdir, obs_metrics.METRICS_NAME)) as f:
        for line in f:
            rec = json.loads(line)
            if rec.get("kind") in kinds:
                out.append(rec)
    return out


def _writer(mdir, cfg):
    return obs_metrics.MetricsWriter(
        str(mdir), obs_metrics.run_manifest(
            cfg=cfg, extra={"workload": "serve"}))


# --- the shared fault grammar -----------------------------------------


def test_parse_serve_plan_grammar():
    plan = faults_mod.parse_serve_plan(
        "hang@2:0.5,nan_logits@3,sigterm@0.1,"
        "pool_squeeze@0:2,pool_squeeze@0.2:1")
    assert plan.hang == {2: 0.5}
    assert plan.nan_logits == frozenset({3})
    assert plan.sigterm == (0.1,)
    assert plan.pool_squeeze == ((0.0, 2), (0.2, 1))
    assert bool(plan)
    assert faults_mod.parse_serve_plan(None) is None
    assert faults_mod.parse_serve_plan("") is None


@pytest.mark.parametrize("bad", [
    "hang@2",            # hang needs seconds
    "nan_logits@3:1",    # nan_logits takes no arg
    "sigterm@-1",        # negative time
    "pool_squeeze@0:0",  # zero pages squeezes nothing
    "nan_loss@2",        # the TRAIN class, given to the serve lane
    "what@ever:x",
])
def test_parse_serve_plan_loud_names_both_vocabularies(bad):
    with pytest.raises(ValueError, match="malformed") as ei:
        faults_mod.parse_serve_plan(bad)
    # the ONE error message names both lanes' grammars (inject.malformed)
    msg = str(ei.value)
    assert "--inject_fault" in msg and "--serve_faults" in msg
    assert "serve lane" in msg


def test_serve_plan_hooks_are_one_shot():
    plan = faults_mod.parse_serve_plan(
        "hang@2:0.5,nan_logits@3,sigterm@0.1,pool_squeeze@0.2:2")
    assert plan.hang_before_decode(1) == 0.0
    assert plan.hang_before_decode(2) == 0.5
    assert plan.hang_before_decode(2) == 0.0          # consumed
    assert plan.poison_rids([1, 3, 5]) == [3]
    assert plan.poison_rids([1, 3, 5]) == []          # consumed
    assert not plan.sigterm_due(0.05)
    assert plan.sigterm_due(0.2)
    assert not plan.sigterm_due(0.2)                  # consumed
    assert plan.squeezed_pages(0.1) == 0
    assert plan.squeezed_pages(0.3) == 2
    assert plan.squeezed_pages(9.9) == 2              # sticky, not one-shot


def test_split_entries_shared_between_lanes():
    # the serve grammar rides the train lane's splitter — structural
    # malformation is one code path for both vocabularies
    assert inject_mod.split_entries("hang@3:0.5", lane="serve") == \
        [("hang", "3", "0.5", "hang@3:0.5")]
    assert inject_mod.parse_plan("nan_loss@2") is not None  # train intact
    with pytest.raises(ValueError, match="malformed"):
        inject_mod.split_entries("noat", lane="serve")


def test_flags_validate_degradation_knobs():
    base = dict(model="moe_tiny", workload="serve", num_requests=4)
    with pytest.raises(ValueError, match="deadline"):
        flags.BenchmarkConfig(shed="deadline", **base).resolve()
    with pytest.raises(ValueError, match="off|admit|deadline"):
        flags.BenchmarkConfig(shed="yes", deadline_ms=50, **base).resolve()
    with pytest.raises(ValueError, match="malformed"):
        flags.BenchmarkConfig(serve_faults="hang@2", **base).resolve()
    # the knobs are serve-only: the training lane rejects them loudly
    with pytest.raises(ValueError):
        flags.BenchmarkConfig(model="trivial", shed="deadline",
                              deadline_ms=50).resolve()
    # slo_e2e_ms is the documented deadline fallback
    cfg = flags.BenchmarkConfig(shed="deadline", slo_e2e_ms=100.0,
                                **base).resolve()
    assert cfg.shed == "deadline"


# --- quarantine -------------------------------------------------------


def test_nan_quarantine_retires_only_poisoned_request(
        moe_engine, moe_requests, tmp_path):
    w = _writer(tmp_path / "m", moe_engine.cfg)
    try:
        summary = moe_engine.run(
            moe_requests, batching="continuous", writer=w,
            clock=engine_mod.VirtualClock(VCOSTS),
            faults=faults_mod.parse_serve_plan("nan_logits@3"),
            kv_preempt="on")      # arms the logits guard
    finally:
        w.close()
    assert summary["completed"] == len(moe_requests) - 1
    assert summary["degrade"]["quarantined"] == 1
    assert summary["post_warmup_compiles"] == 0
    q = _records(str(tmp_path / "m"), kinds=("quarantine",))
    assert [r["id"] for r in q] == [3]
    assert q[0]["status"] == "quarantined"
    assert q[0]["cause"] == "nonfinite_logits"
    # percentile folds fold kind=="request" only: the poisoned rid
    # must not appear there
    assert 3 not in {r["id"] for r in _records(str(tmp_path / "m"))}


def test_unarmed_control_lets_nan_flow_through(moe_engine, moe_requests):
    # the faults A/B's control arm: both policy knobs off means no
    # host read-back, so the injected NaN decodes through undetected
    summary = moe_engine.run(
        moe_requests, batching="continuous",
        clock=engine_mod.VirtualClock(VCOSTS),
        faults=faults_mod.parse_serve_plan("nan_logits@3"),
        shed="off", kv_preempt="off")
    assert summary["completed"] == len(moe_requests)
    assert summary["degrade"]["quarantined"] == 0


# --- KV-pressure preemption / requeue ---------------------------------


def test_requeue_conserves_tokens_and_components(
        moe_engine, moe_requests, tmp_path):
    burst = _burst(moe_requests)
    # the unfaulted run's tokens, from a metrics stream (summaries
    # carry counts, not records)
    wb = _writer(tmp_path / "base", moe_engine.cfg)
    try:
        moe_engine.run(burst, batching="continuous", writer=wb,
                       clock=engine_mod.VirtualClock(VCOSTS))
    finally:
        wb.close()
    base_tokens = {r["id"]: r["generated"]
                   for r in _records(str(tmp_path / "base"))}
    w = _writer(tmp_path / "m", moe_engine.cfg)
    try:
        summary = moe_engine.run(
            burst, batching="continuous", writer=w,
            clock=engine_mod.VirtualClock(VCOSTS),
            faults=faults_mod.parse_serve_plan("pool_squeeze@0:3"),
            kv_preempt="on")
    finally:
        w.close()
    assert summary["completed"] == len(burst)
    assert summary["degrade"]["preempts"] >= 1
    assert summary["degrade"]["requeues"] >= 1
    assert summary["post_warmup_compiles"] == 0      # requeue re-prefills
    recs = _records(str(tmp_path / "m"))
    requeued = [r for r in recs if r.get("preempts")]
    assert requeued, "squeeze + burst must preempt at least one resident"
    for rec in recs:
        # no token lost across residencies: the prefix carry re-prefills
        # prompt+prefix, so generated output matches the unfaulted run
        assert rec["generated"] == base_tokens[rec["id"]]
        # and the lifecycle attribution still tiles e2e exactly
        parts = requests_mod.attribution_of(rec)
        assert abs(sum(parts.values()) - rec["e2e_ms"]) < 1e-6
    events = _records(str(tmp_path / "m"), kinds=("preempt",))
    assert events and all(e["cause"] == "pool_starved" for e in events)


# --- shedding ---------------------------------------------------------


@pytest.fixture(scope="module")
def shed_run(moe_engine, moe_requests, tmp_path_factory):
    """ONE run under a terminal pool squeeze with ``--shed=deadline``:
    nothing can ever admit, so every request must exit as a shed —
    the would-stall-forever trace the shed path exists for."""
    mdir = str(tmp_path_factory.mktemp("shed") / "m")
    squeeze = moe_engine.num_pages - moe_engine.table_width + 1
    w = _writer(mdir, moe_engine.cfg)
    try:
        summary = moe_engine.run(
            _burst(moe_requests), batching="continuous", writer=w,
            clock=engine_mod.VirtualClock(VCOSTS),
            faults=faults_mod.parse_serve_plan(f"pool_squeeze@0:{squeeze}"),
            shed="deadline", deadline_ms=100.0)
    finally:
        w.close()
    return {"summary": summary, "mdir": mdir}


def test_terminal_squeeze_sheds_instead_of_stalling(
        shed_run, moe_engine, moe_requests):
    summary = shed_run["summary"]
    deg = summary["degrade"]
    n = len(moe_requests)
    assert summary["completed"] + sum(deg["shed"].values()) == n
    assert deg["shed"].get("deadline_expired", 0) >= 1
    assert 0.0 < summary["shed_frac"] <= 1.0
    assert set(deg["shed"]) <= set(kv_mod.SHED_CAUSES)
    recs = _records(shed_run["mdir"], kinds=("shed",))
    assert all(r["status"] == "shed" and r["cause"] in kv_mod.SHED_CAUSES
               for r in recs)
    # the same trace with shedding off is a loud stall, not a hang
    squeeze = moe_engine.num_pages - moe_engine.table_width + 1
    with pytest.raises(RuntimeError, match="stall"):
        moe_engine.run(
            _burst(moe_requests), batching="continuous",
            clock=engine_mod.VirtualClock(VCOSTS),
            faults=faults_mod.parse_serve_plan(f"pool_squeeze@0:{squeeze}"),
            shed="off")


def test_slo_lines_render_degradation(shed_run):
    lines = slo.slo_lines(shed_run["summary"])
    deg_lines = [ln for ln in lines if "degrade:" in ln]
    assert len(deg_lines) == 1
    assert "shed" in deg_lines[0]
    assert "deadline_expired" in deg_lines[0]
    # a clean summary renders no degrade line at all
    clean = dict(shed_run["summary"])
    clean["degrade"] = {"shed": {}, "preempts": 0, "requeues": 0,
                        "quarantined": 0}
    assert not [ln for ln in slo.slo_lines(clean) if "degrade:" in ln]


def test_obs_summarize_shows_shed_causes(shed_run):
    lines = obs_metrics.summarize_run(shed_run["mdir"])
    text = "\n".join(lines)
    assert "shed" in text
    assert "deadline_expired" in text


def test_resilience_kinds_cover_degradation():
    assert {"shed", "quarantine"} <= set(obs_metrics.RESILIENCE_KINDS)


# --- drain / journal / resume ----------------------------------------


class FakeHandler:
    """Poll-a-fake drain trigger: ``requested()`` flips true after N
    scheduler iterations — the in-process stand-in for SIGTERM."""

    def __init__(self, after: int):
        self.after = after
        self.polls = 0

    def requested(self) -> bool:
        self.polls += 1
        return self.polls > self.after


def test_drain_journals_then_resume_serves_exactly_once(
        moe_engine, moe_requests, tmp_path):
    journal = str(tmp_path / "j" / "serve_journal.json")
    w1 = _writer(tmp_path / "m1", moe_engine.cfg)
    try:
        summary = moe_engine.run(
            moe_requests, batching="continuous", writer=w1,
            clock=engine_mod.VirtualClock(VCOSTS),
            drain_handler=FakeHandler(after=2), journal_path=journal)
    finally:
        w1.close()
    drained = summary["drained"]
    assert drained["reason"] == "sigterm"
    assert drained["journal"] == journal
    assert drained["unfinished"] >= 1
    assert summary["completed"] + drained["unfinished"] == len(moe_requests)
    payload = faults_mod.read_journal(journal)
    replay = faults_mod.journal_requests(payload)
    assert len(replay) == drained["unfinished"]
    # the resumed run serves every journaled rid exactly once
    w2 = _writer(tmp_path / "m2", moe_engine.cfg)
    try:
        resumed = moe_engine.run(replay, batching="continuous", writer=w2,
                                 clock=engine_mod.VirtualClock(VCOSTS))
    finally:
        w2.close()
    assert resumed["completed"] == len(replay)
    first = {r["id"] for r in _records(str(tmp_path / "m1"))}
    second = {r["id"] for r in _records(str(tmp_path / "m2"))}
    assert first.isdisjoint(second)
    assert first | second == {r.rid for r in moe_requests}


def test_read_journal_loud_on_wrong_file(tmp_path):
    p = tmp_path / "not_a_journal.json"
    p.write_text('{"kind": "manifest"}\n')
    with pytest.raises(ValueError, match="serve drain journal"):
        faults_mod.read_journal(str(p))
    with pytest.raises(FileNotFoundError):
        faults_mod.read_journal(str(tmp_path / "missing.json"))


# --- scheduler watchdog ----------------------------------------------


def test_watchdog_hook_fires_on_wedged_iteration(moe_engine, moe_requests):
    fired: list = []
    # real clock on purpose: hang@2 is a real 0.8s stall, which the
    # 0.3s watchdog must catch; on_watchdog replaces os._exit so the
    # run survives for the assertion
    summary = moe_engine.run(
        moe_requests, batching="continuous",
        faults=faults_mod.parse_serve_plan("hang@2:0.8"),
        step_timeout_s="0.3",
        on_watchdog=lambda age: fired.append(age))
    assert fired and fired[0] >= 0.3
    assert summary["completed"] == len(moe_requests)


def test_watchdog_quiet_on_healthy_run(moe_engine, moe_requests):
    fired: list = []
    summary = moe_engine.run(
        moe_requests, batching="continuous",
        step_timeout_s="30",
        on_watchdog=lambda age: fired.append(age))
    assert not fired
    assert summary["completed"] == len(moe_requests)


# --- obs vocabulary + regress gate ------------------------------------


def test_degradation_spans_are_registered_vocabulary():
    assert {"shed", "preempt", "requeue", "quarantine", "drain"} \
        <= set(timeline.KNOWN_SPANS)


def test_regress_gates_shed_frac_direction_aware():
    assert (("extra", "shed_frac"), "lower", "shed frac") in regress.CHECKS
    assert regress.ABS_FLOORS["shed frac"] == 0.05


# --- retire-without-status lint ---------------------------------------


BAD_RETIRE = """
class E:
    def run(self):
        self.finish(fl, t)
        shed_queued(req, t)
"""

GOOD_RETIRE = """
class E:
    def run(self):
        self.finish(fl, t, status="ok")
        finish(fl, t, status="shed", cause="resident_expired")
        shed_queued(req, "deadline_expired", t)
"""


def test_retire_status_lint_flags_statusless_terminals():
    found = [f for f in lints.lint_source_text(
        BAD_RETIRE, filename="tpu_hc_bench/serve/engine.py")
        if f.lint == lints.RETIRE_STATUS]
    assert len(found) == 2
    assert all(f.severity == "error" for f in found)
    # not this lint's business outside the serve package
    assert not [f for f in lints.lint_source_text(
        BAD_RETIRE, filename="tpu_hc_bench/train/driver.py")
        if f.lint == lints.RETIRE_STATUS]


def test_retire_status_lint_passes_disposed_terminals():
    assert not [f for f in lints.lint_source_text(
        GOOD_RETIRE, filename="tpu_hc_bench/serve/engine.py")
        if f.lint == lints.RETIRE_STATUS]


def test_retire_status_lint_registered():
    from tpu_hc_bench.analysis import registry
    assert lints.RETIRE_STATUS in {row[0] for row in registry.pass_index()}
    assert registry.default_severity(lints.RETIRE_STATUS) == "error"


# --- subprocess e2e: SIGTERM mid-traffic, exit 75, resume -------------


@pytest.mark.slow
def test_serve_sigterm_drain_resume_subprocess(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    journal = str(tmp_path / "serve_journal.json")
    base = [sys.executable, "-m", "tpu_hc_bench", "serve",
            "--model", "moe_tiny", "--arrival_rate", "50",
            "--num_requests", "8", "--max_prompt_len", "8",
            "--max_output_len", "4", "--max_in_flight", "2",
            "--kv_page_size", "4"]
    m1, m2 = str(tmp_path / "m1"), str(tmp_path / "m2")
    first = subprocess.run(
        base + ["--metrics_dir", m1, "--serve_journal", journal,
                "--serve_faults", "sigterm@0.05"],
        capture_output=True, text=True, env=env, cwd=repo, timeout=570)
    assert first.returncode == resilience.EXIT_PREEMPTED, \
        f"stdout:\n{first.stdout}\nstderr:\n{first.stderr}"
    assert "drain" in first.stdout
    assert os.path.exists(journal)
    payload = faults_mod.read_journal(journal)
    assert payload["unfinished"] >= 1
    second = subprocess.run(
        base + ["--metrics_dir", m2, "--serve_resume", journal],
        capture_output=True, text=True, env=env, cwd=repo, timeout=570)
    assert second.returncode == 0, \
        f"stdout:\n{second.stdout}\nstderr:\n{second.stderr}"
    assert "resume" in second.stdout
    done1 = {r["id"] for r in _records(m1)}
    done2 = {r["id"] for r in _records(m2)}
    # exactly-once across the SIGTERM boundary: the two runs partition
    # the trace, and the resumed records still attribute cleanly
    assert done1.isdisjoint(done2)
    assert done1 | done2 == set(range(8))
    for rec in _records(m2):
        parts = requests_mod.attribution_of(rec)
        assert abs(sum(parts.values()) - rec["e2e_ms"]) < 1e-6
