"""Mergeable quantile sketches + the streaming health-signal engine
(round 24, ``tpu_hc_bench/obs/sketch.py`` + ``obs/signals.py`` + the
serve/driver/fleet wiring).

Default lane is host-only — the sketch and signal engines are pure
record processing, and every closed-loop assertion rides the session
serve fixtures from conftest (the ONE warmed moe engine and the shared
``moe_ab`` two-arm loop in virtual time) — zero new engine warmups and
zero driver runs.

The load-bearing pins:

- **merge algebra**: bucket-wise merge is associative and commutative
  — the merged sketch answers exactly what the sketch of the
  concatenated stream answers, which averaged per-host p99s do not;
- **relative-error bound**: every quantile lands inside the exact
  order-statistic bracket widened by alpha, on adversarial
  distributions (heavy tail, two-point, constant);
- **hysteresis**: a one-window spike never fires; a sustained breach
  fires after ``fire_windows``; clearing debounces across the dead
  band; a no-evidence window holds every streak;
- **bounded retention**: the engine's raw-sample ring is capped while
  the sketch keeps run-lifetime percentiles — the week-long-serve
  memory leak the sketch exists to close;
- **registry**: signal-name literals lint against ``KNOWN_SIGNALS``
  (the span-name-registry pattern), and the repo baseline stays clean.
"""

from __future__ import annotations

import json
import math
import os
import pathlib

import pytest

from tpu_hc_bench.obs import metrics as obs_metrics
from tpu_hc_bench.obs import regress
from tpu_hc_bench.obs import signals as signals_mod
from tpu_hc_bench.obs import sketch as sketch_mod
from tpu_hc_bench.obs.sketch import QuantileSketch
from tpu_hc_bench.serve import slo

from conftest import SERVE_VCOSTS


def _records_of(mdir: str) -> list[dict]:
    return [json.loads(l) for l in open(os.path.join(mdir,
                                                     "metrics.jsonl"))]


def _exact_bracket(values: list[float], q: float) -> tuple[float, float]:
    """The order-statistic bracket the sketch's answer must land in
    (rank convention matches slo.percentile / sketch.quantile)."""
    vs = sorted(values)
    rank = q / 100.0 * (len(vs) - 1)
    return vs[int(rank)], vs[min(int(rank) + 1, len(vs) - 1)]


def _assert_within(sk: QuantileSketch, values: list[float],
                   qs=(0, 10, 50, 90, 95, 99, 100)) -> None:
    for q in qs:
        lo, hi = _exact_bracket(values, q)
        got = sk.quantile(q)
        assert lo * (1 - sk.alpha) - 1e-12 <= got \
            <= hi * (1 + sk.alpha) + 1e-12, \
            f"q{q}: {got} outside [{lo}, {hi}] +/- alpha"


# --- sketch: algebra, error bound, edges ------------------------------

def test_sketch_error_bound_adversarial():
    # heavy tail spanning 6 decades, a two-point bimodal, a constant
    # stream, and near-zero values against the zero bucket
    heavy = [1.0001 ** i * 0.1 for i in range(0, 6000, 7)]
    two_point = [1.0] * 99 + [5000.0]
    const = [42.0] * 257
    # exact zeros ride the zero bucket; positives keep the alpha bound
    zeros = [0.0, 0.0, 0.0, 1e-6, 0.5, 1.0]
    for values in (heavy, two_point, const, zeros):
        _assert_within(sketch_mod.sketch_of(values), values)


def test_sketch_merge_associative_commutative():
    a = [0.5 * i for i in range(1, 40)]
    b = [100.0 + 3.0 * i for i in range(30)]
    c = [0.001, 0.01, 7000.0, 12.5]
    sks = {k: sketch_mod.sketch_of(v) for k, v in
           (("a", a), ("b", b), ("c", c))}

    def fresh(name):
        return QuantileSketch().merge(sks[name])

    ab_c = fresh("a").merge(fresh("b")).merge(fresh("c"))
    a_bc = fresh("a").merge(fresh("b").merge(fresh("c")))
    cba = fresh("c").merge(fresh("b")).merge(fresh("a"))
    direct = sketch_mod.sketch_of(a + b + c)
    for q in (0, 25, 50, 75, 90, 99, 100):
        assert ab_c.quantile(q) == a_bc.quantile(q) == cba.quantile(q) \
            == direct.quantile(q)
    assert ab_c.count == direct.count == len(a) + len(b) + len(c)
    _assert_within(ab_c, a + b + c)


def test_sketch_merge_alpha_mismatch_raises():
    with pytest.raises(ValueError):
        QuantileSketch(alpha=0.01).merge(QuantileSketch(alpha=0.02))


def test_sketch_empty_and_single():
    sk = QuantileSketch()
    assert sk.count == 0 and sk.quantile(50) == 0.0 and sk.mean() == 0.0
    sk.add(17.25)
    for q in (0, 50, 100):
        assert sk.quantile(q) == 17.25
    # merging an empty sketch is the identity
    merged = QuantileSketch().merge(sk)
    assert merged.quantile(99) == 17.25 and merged.count == 1
    # negative jitter clamps, never raises
    sk2 = QuantileSketch()
    sk2.add(-0.0)
    sk2.add(-5.0)
    assert sk2.quantile(100) == 0.0 and sk2.count == 2


def test_sketch_record_roundtrip_and_merge_records():
    values = [0.3 * i for i in range(1, 200)]
    halves = [values[:100], values[100:]]
    recs = [sketch_mod.sketch_of(h).to_record() for h in halves]
    # the jsonl trip must preserve the answers exactly
    recs = json.loads(json.dumps(recs))
    merged = sketch_mod.merge_records(recs)
    direct = sketch_mod.sketch_of(values)
    for q in (0, 50, 95, 99, 100):
        assert merged.quantile(q) == direct.quantile(q)
    # absent history folds to absent, never a KeyError
    assert sketch_mod.merge_records([]) is None
    assert sketch_mod.merge_records([None, "x"]) is None


def test_sketch_collapse_bounds_memory_keeps_tail():
    sk = QuantileSketch(max_buckets=32)
    values = [1.002 ** i for i in range(4000)]   # ~3.5 decades
    for v in values:
        sk.add(v)
    assert len(sk.buckets) <= 32
    assert sk.count == len(values)
    # collapse folds the LOW end: the SLO tail stays within bound (the
    # 32 surviving buckets cover the top few percent of this range),
    # and the collapsed low quantiles only ever bias UPWARD — a capped
    # sketch never understates a latency
    for q in (95, 99, 100):
        lo, hi = _exact_bracket(values, q)
        assert lo * (1 - sk.alpha) <= sk.quantile(q) <= hi * (1 + sk.alpha)
    lo50, _ = _exact_bracket(values, 50)
    assert sk.quantile(50) >= lo50 * (1 - sk.alpha)


def test_sketch_from_counts_matches_service_histogram():
    hist = [0, 5, 0, 3, 9, 0, 0, 2]     # counts[v] = occurrences of v
    sk = QuantileSketch.from_counts(hist)
    values = [float(v) for v, n in enumerate(hist) for _ in range(n)]
    assert sk.count == len(values)
    # small ints resolve exactly at alpha=1%
    for q in (0, 50, 90, 100):
        assert round(sk.quantile(q)) in values


# --- signal engine: hysteresis ----------------------------------------

def test_signal_one_window_spike_never_fires():
    eng = signals_mod.SignalEngine()
    eng.observe(1.0, {"SUSTAINED_OVERLOAD": 0.9})
    eng.observe(2.0, {"SUSTAINED_OVERLOAD": 0.0})
    eng.observe(3.0, {"SUSTAINED_OVERLOAD": 0.9})
    eng.observe(4.0, {"SUSTAINED_OVERLOAD": 0.0})
    assert eng.events == [] and eng.active == {} and eng.fired == {}


def test_signal_sustained_fires_then_debounced_clear():
    eng = signals_mod.SignalEngine()
    assert eng.observe(1.0, {"KV_PRESSURE": 0.8}) == []
    evs = eng.observe(2.0, {"KV_PRESSURE": 0.7},
                      causes={"KV_PRESSURE": {"pool_starved_s": 1.2}})
    assert len(evs) == 1 and evs[0]["state"] == "fire"
    assert evs[0]["signal"] == "KV_PRESSURE" and evs[0]["t"] == 2.0
    assert evs[0]["cause"] == {"pool_starved_s": 1.2}
    assert "KV_PRESSURE" in eng.active
    # 0.3 is under fire (0.5) but NOT under clear (0.25): holds active
    assert eng.observe(3.0, {"KV_PRESSURE": 0.3}) == []
    # one recovered window is not enough (clear_windows=2)
    assert eng.observe(4.0, {"KV_PRESSURE": 0.1}) == []
    evs = eng.observe(5.0, {"KV_PRESSURE": 0.1})
    assert len(evs) == 1 and evs[0]["state"] == "clear"
    assert evs[0]["since"] == 2.0
    assert eng.active == {}
    assert signals_mod.fired_count(eng.events, "KV_PRESSURE") == 1


def test_signal_none_holds_streaks_and_active_state():
    eng = signals_mod.SignalEngine()
    eng.observe(1.0, {"SUSTAINED_OVERLOAD": 0.9})
    # silence is not health: the breach streak survives the gap
    eng.observe(2.0, {"SUSTAINED_OVERLOAD": None})
    evs = eng.observe(3.0, {"SUSTAINED_OVERLOAD": 0.9})
    assert [e["state"] for e in evs] == ["fire"]
    # and an active signal never clears on no-evidence windows
    eng.observe(4.0, {})
    eng.observe(5.0, {"SUSTAINED_OVERLOAD": None})
    assert "SUSTAINED_OVERLOAD" in eng.active


def test_signal_direction_below_goodput_collapse():
    eng = signals_mod.SignalEngine()
    for t in (1.0, 2.0):
        eng.observe(t, {"GOODPUT_COLLAPSE": 0.01})
    assert eng.events == []       # fire_windows=3
    evs = eng.observe(3.0, {"GOODPUT_COLLAPSE": 0.01})
    assert [e["state"] for e in evs] == ["fire"]
    # 0.1 is above fire (0.05) but below clear (0.15): holds active
    eng.observe(4.0, {"GOODPUT_COLLAPSE": 0.10})
    eng.observe(5.0, {"GOODPUT_COLLAPSE": 0.30})
    evs = eng.observe(6.0, {"GOODPUT_COLLAPSE": 0.30})
    assert [e["state"] for e in evs] == ["clear"]


def test_signal_registry_surface():
    for name in signals_mod.KNOWN_SIGNALS:
        spec = signals_mod.spec_of(name)
        assert spec.name == name
        assert signals_mod.advice_for(name)
        if spec.direction == "above":
            assert spec.clear_threshold < spec.fire_threshold
        else:
            assert spec.clear_threshold > spec.fire_threshold
    bogus = "NOT_" + "A_SIGNAL"   # built, not literal: the lint's out
    with pytest.raises(ValueError, match="unknown signal"):
        signals_mod.spec_of(bogus)
    with pytest.raises(ValueError):
        signals_mod.fired_count([], bogus)


def test_signal_events_roundtrip_and_folds(tmp_path):
    eng = signals_mod.SignalEngine()
    for t in (1.0, 2.0):
        eng.observe(t, {"KV_PRESSURE": 0.9, "SUSTAINED_OVERLOAD": 0.9})
    path = signals_mod.signals_path(str(tmp_path))
    signals_mod.append_events(path, eng.events)
    signals_mod.append_events(path, [])      # no-op, never truncates
    back = signals_mod.read_signals(str(tmp_path))
    assert back == eng.events
    assert set(signals_mod.active_of(back)) == {"KV_PRESSURE",
                                                "SUSTAINED_OVERLOAD"}
    assert signals_mod.fired_counts(back) == {"KV_PRESSURE": 1,
                                              "SUSTAINED_OVERLOAD": 1}
    lines = signals_mod.signal_lines(back)
    assert any("still active" in ln for ln in lines)
    watch = signals_mod.watch_lines(str(tmp_path))
    assert len(watch) == 1 and "KV_PRESSURE" in watch[0]
    # a run that never signalled renders nothing (no file, no noise)
    assert signals_mod.read_signals(str(tmp_path / "nowhere")) == []
    assert signals_mod.watch_lines(str(tmp_path / "nowhere")) == []


# --- serve-lane wiring (rides the session moe_ab fixture) -------------

def test_summary_carries_sketch_fields(moe_ab):
    for arm in ("static", "continuous"):
        s = moe_ab[arm]["summary"]
        assert s["latency_source"] == "sketch"
        assert s["sketch_windows"] >= 1
        assert s["latency_sample_cap"] >= 1
        # single host: the run sketch IS the merge of its windows
        assert s["p99_merged_ms"] == pytest.approx(s["p99_e2e_ms"])


def test_stream_carries_window_sketches_merged_matches_exact(moe_ab):
    for arm in ("static", "continuous"):
        records = _records_of(moe_ab[arm]["mdir"])
        wins = [r for r in records if r.get("kind") == slo.SKETCH_KIND]
        assert wins, "no latency_sketch records in the stream"
        assert all("window" in r and isinstance(r.get("fields"), dict)
                   for r in wins)
        merged = sketch_mod.merge_records(
            (r["fields"].get("e2e_ms") for r in wins))
        e2e = [float(r["e2e_ms"]) for r in records
               if r.get("kind") == "request"]
        assert merged.count == len(e2e)
        _assert_within(merged, e2e)
        # the offline fold agrees with the engine's own summary
        fold = slo.fold_window_sketches(records)
        assert fold["latency_source"] == "sketch"
        assert fold["sketch_windows"] == len(wins)
        assert fold["p99_merged_ms"] == pytest.approx(
            moe_ab[arm]["summary"]["p99_merged_ms"], abs=1e-3)


def test_fold_window_sketches_absent_on_pre_r24_streams():
    # pre-round-24 stream: no latency_sketch records -> {} (absent and
    # labeled downstream, never a KeyError)
    assert slo.fold_window_sketches(
        [{"kind": "request", "e2e_ms": 5.0}]) == {}
    lines = slo.slo_lines(slo.fold_requests(
        [{"kind": "request", "ttft_ms": 1.0, "e2e_ms": 2.0,
          "queue_ms": 0.5}]))
    assert not any("merged" in ln for ln in lines)


def test_summarize_renders_merged_sketch_line(moe_ab):
    lines = obs_metrics.summarize_run(moe_ab["continuous"]["mdir"])
    assert any("[sketch" in ln and "p99" in ln for ln in lines)


def test_obs_signals_cli(moe_ab, tmp_path, capsys):
    from tpu_hc_bench.obs.__main__ import main as obs_main

    mdir = moe_ab["continuous"]["mdir"]
    rc = obs_main(["signals", mdir])
    rep_out = capsys.readouterr().out
    assert "offline re-evaluation" in rep_out
    # rc contract: 1 iff anything fired (live or offline), 2 when the
    # path is unusable
    fired = signals_mod.fired_counts(
        signals_mod.read_signals(mdir)) or signals_mod.fired_counts(
        signals_mod.evaluate_records(_records_of(mdir), run_dir=mdir))
    assert rc == (1 if fired else 0)
    assert obs_main(["signals", str(tmp_path / "missing")]) == 2
    rc = obs_main(["signals", mdir, "--json"])
    payload = json.loads(capsys.readouterr().out)
    assert set(payload) == {"recorded", "evaluated", "fired"}


def test_bounded_retention_long_trace(moe_engine, monkeypatch):
    """The round-24 memory pin: a long VirtualClock trace through the
    warmed engine with the raw ring pinned tiny — completion counting,
    percentiles, and the burn fold must all keep working off the
    run-lifetime sketches while raw retention stays at the cap."""
    from tpu_hc_bench import flags
    from tpu_hc_bench.serve import arrivals
    from tpu_hc_bench.serve import engine as engine_mod

    monkeypatch.setattr(engine_mod, "_DONE_SAMPLE_CAP", 6)
    cfg = flags.BenchmarkConfig(
        model="moe_tiny", workload="serve", arrival_rate=200.0,
        num_requests=24, max_prompt_len=8, max_output_len=4,
        max_in_flight=2, kv_page_size=4, seed=3).resolve()
    reqs = arrivals.build_requests(cfg, moe_engine.spec.vocab_size)
    summary = moe_engine.run(
        reqs, batching="continuous",
        clock=engine_mod.VirtualClock(SERVE_VCOSTS))
    # every completion counted, even though only 6 raw records survive
    assert summary["completed"] == 24
    assert summary["latency_sample_cap"] == 6
    # the sketch percentiles cover the WHOLE run, not the ring
    assert summary["p99_merged_ms"] == pytest.approx(
        summary["p99_e2e_ms"])
    assert summary["p99_e2e_ms"] >= summary["p50_e2e_ms"] > 0
    assert summary["sketch_windows"] >= 1


def test_engine_emits_signals_on_sustained_overload(moe_engine,
                                                    tmp_path):
    """A deliberately-impossible e2e target over a burst trace: the
    live engine must fire SUSTAINED_OVERLOAD (hysteresis-gated, so
    only after consecutive breached windows) and journal it into
    signals.jsonl beside the stream."""
    from tpu_hc_bench import flags
    from tpu_hc_bench.serve import arrivals
    from tpu_hc_bench.serve import engine as engine_mod

    cfg = flags.BenchmarkConfig(
        model="moe_tiny", workload="serve", arrival_rate=5000.0,
        num_requests=24, max_prompt_len=8, max_output_len=4,
        max_in_flight=2, kv_page_size=4, seed=1).resolve()
    reqs = arrivals.build_requests(cfg, moe_engine.spec.vocab_size)
    mdir = str(tmp_path / "overload")
    writer = obs_metrics.MetricsWriter(
        mdir, obs_metrics.run_manifest(cfg=moe_engine.cfg,
                                       extra={"workload": "serve"}))
    try:
        summary = moe_engine.run(
            reqs, batching="continuous", writer=writer,
            clock=engine_mod.VirtualClock(SERVE_VCOSTS),
            deadline_ms=1.0, shed="off", kv_preempt="off")
    finally:
        writer.close()
    assert summary["signals_fired"].get("SUSTAINED_OVERLOAD", 0) >= 1
    assert summary["signals_fired_total"] >= 1
    events = signals_mod.read_signals(mdir)
    fires = [e for e in events if e.get("state") == "fire"
             and e.get("signal") == "SUSTAINED_OVERLOAD"]
    assert fires and fires[0].get("cause", {}).get("target_ms") == 1.0
    # hysteresis: the fire credits >= fire_windows consecutive windows
    assert fires[0]["windows"] >= signals_mod.spec_of(
        "SUSTAINED_OVERLOAD").fire_windows
    # the live column renders it
    assert any("SUSTAINED_OVERLOAD" in ln
               for ln in signals_mod.watch_lines(mdir))
    # and a clean run fires nothing: the moe_ab arms carry no target
    # (no deadline/slo), so the engine holds "no evidence" forever


def test_clean_run_fires_nothing(moe_ab):
    for arm in ("static", "continuous"):
        s = moe_ab[arm]["summary"]
        assert s["signals_fired"] == {}
        assert s["signals_fired_total"] == 0
        assert signals_mod.read_signals(moe_ab[arm]["mdir"]) == []


# --- fleet supervisor: advisory journaling ----------------------------

def test_supervisor_journals_signals_log_only(tmp_path):
    from tpu_hc_bench.fleet.pool import DevicePool, JobSpec
    from tpu_hc_bench.fleet.supervisor import RUNNING, FleetController

    out = str(tmp_path / "fleet")
    ctl = FleetController(DevicePool(4), [], out,
                          print_fn=lambda s: None)
    st = ctl.supervisor.add(JobSpec(
        name="j0", model="trivial", batch_size=2,
        world_pref=2, world_min=2))
    st.status = RUNNING
    st.run_dir = str(tmp_path / "j0")
    mdir = os.path.join(st.run_dir, "m")
    os.makedirs(mdir)
    sig_path = signals_mod.signals_path(mdir)
    fire = {"kind": "signal", "t": 3.25, "signal": "KV_PRESSURE",
            "state": "fire", "measure": 0.9, "threshold": 0.5,
            "windows": 2}
    with open(sig_path, "w") as f:
        f.write(json.dumps(fire) + "\n")
        f.write('{"kind": "signal", "t": 4.0, "sig')   # mid-write tail
    ctl._scan_signals()
    events = [json.loads(l)
              for l in open(os.path.join(out, "fleet_events.jsonl"))]
    sigs = [e for e in events if e["kind"] == "signal"]
    advs = [e for e in events if e["kind"] == "signal_advice"]
    assert len(sigs) == 1 and sigs[0]["signal"] == "KV_PRESSURE"
    assert sigs[0]["t_sig"] == 3.25 and sigs[0]["job"] == "j0"
    # actuation is ADVISORY by contract: journaled advice, no lever
    assert len(advs) == 1 and advs[0]["actuation"] == "log-only"
    assert advs[0]["advice"] == signals_mod.advice_for("KV_PRESSURE")
    assert st.status == RUNNING
    # the partial line was NOT consumed; completing it lands it once
    with open(sig_path, "a") as f:
        f.write('nal": "STRAGGLER", "state": "clear"}\n')
    ctl._scan_signals()
    ctl._scan_signals()     # idempotent: offsets advance past consumed
    events = [json.loads(l)
              for l in open(os.path.join(out, "fleet_events.jsonl"))]
    sigs = [e for e in events if e["kind"] == "signal"]
    assert len(sigs) == 2 and sigs[1]["signal"] == "STRAGGLER"
    assert len([e for e in events
                if e["kind"] == "signal_advice"]) == 1


# --- lint + regress satellites ----------------------------------------

def test_lint_signal_name_registry():
    from tpu_hc_bench.analysis import lints

    bad = [f for f in lints.lint_source_text(
        'from tpu_hc_bench.obs import signals as signals_mod\n'
        'n = signals_mod.fired_count([], "KV_PRESURE")\n',
        filename="x.py") if f.lint == lints.SIGNAL_REGISTRY]
    assert len(bad) == 1 and "KV_PRESURE" in bad[0].message
    ok = [f for f in lints.lint_source_text(
        'from tpu_hc_bench.obs.signals import spec_of\n'
        'spec_of("SUSTAINED_OVERLOAD")\n'
        'def g(events, name):\n'
        '    return spec_of(name)\n',
        filename="x.py") if f.lint == lints.SIGNAL_REGISTRY]
    assert ok == []
    # suppression spelling works for this pass too
    sup = [f for f in lints.lint_source_text(
        'from tpu_hc_bench.obs.signals import spec_of\n'
        'spec_of("LEGACY")  # tpu-hc: disable=signal-name-registry\n',
        filename="x.py") if f.lint == lints.SIGNAL_REGISTRY]
    assert sup == []
    assert lints.SIGNAL_REGISTRY in lints.ALL_SOURCE_LINTS


def test_lint_repo_baseline_clean_of_signal_findings():
    # the full-tree gate (test_analysis's repo source gate) already runs
    # every registered pass including this one; here we lint only the
    # files that can trigger it — anything naming a registry callee —
    # so the check stays honest without re-paying the repo-scope passes
    from tpu_hc_bench.analysis import lints

    root = pathlib.Path(lints.__file__).resolve().parents[2]
    callees = tuple(lints._FileLinter._SIGNAL_NAME_CALLEES)
    findings = []
    for sub in ("tpu_hc_bench", "scripts"):
        for path in sorted((root / sub).rglob("*.py")):
            text = path.read_text()
            if not any(c in text for c in callees):
                continue
            findings += [f for f in lints.lint_source_text(
                             text, str(path.relative_to(root)))
                         if f.lint == lints.SIGNAL_REGISTRY]
    assert findings == [], findings


def test_regress_gates_merged_p99_direction_aware():
    base = {"metric": "m", "value": 1.0, "unit": "u",
            "extra": {"p99_merged_ms": 50.0, "signals_fired_total": 0}}
    hist = [json.loads(json.dumps(base)) for _ in range(4)]
    # pre-r24 history lacks the fields entirely: structural skip
    old = {"metric": "m", "value": 1.0, "unit": "u", "extra": {}}
    verdict = regress.regress_check(base, [old] * 4)
    assert not any(c["metric"] == "p99 merged ms"
                   for c in verdict["checked"])
    # a big rise regresses; a drop never does
    worse = json.loads(json.dumps(base))
    worse["extra"]["p99_merged_ms"] = 80.0
    verdict = regress.regress_check(worse, hist)
    assert any(r["metric"] == "p99 merged ms"
               for r in verdict["regressions"])
    better = json.loads(json.dumps(base))
    better["extra"]["p99_merged_ms"] = 30.0
    assert regress.regress_check(better, hist)["regressions"] == []
    # ONE fire on a clean-history config flags (abs floor = 1 fire)
    fired = json.loads(json.dumps(base))
    fired["extra"]["signals_fired_total"] = 1
    verdict = regress.regress_check(fired, hist)
    assert any(r["metric"] == "signals fired"
               for r in verdict["regressions"])


def test_driver_step_sketch_weighted():
    from tpu_hc_bench.train import driver as driver_mod

    # __new__ skips the fetcher thread: only the timed intervals matter
    tl = driver_mod._AsyncTimeline.__new__(driver_mod._AsyncTimeline)
    tl.per_step_times = [(0.010, 1), (0.010, 1), (0.010, 1), (0.070, 1)]
    sk = tl.step_sketch()
    assert sk is not None and sk.count == 4
    # three 10ms intervals and one 70ms straggler: the p50 is 10ms
    # within the sketch's relative error
    assert tl.p50_step_ms() == pytest.approx(10.0, rel=0.02)
    # a coalesced-over stretch weights as the steps it spans, not one
    tl.per_step_times = [(0.010, 9), (0.070, 1)]
    assert tl.p50_step_ms() == pytest.approx(10.0, rel=0.02)
    tl.per_step_times = []
    assert tl.step_sketch() is None
    assert math.isnan(tl.p50_step_ms())
