"""Tensor parallelism (GSPMD, Megatron-style shardings) on the virtual mesh.

DP x TP runs on the 8-device CPU mesh: params sharded per
``step.tp_param_spec``, batch over the data axis, XLA inserting the TP
collectives.  Checked against the replicated GSPMD step numerically.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_hc_bench import flags
from tpu_hc_bench._compat import CAPABILITIES

# the 0.4.x SPMD partitioner computes the TP-sharded forward with a
# systematic loss offset vs the replicated arm (~0.9% for bert, ~6% for
# vit; same mechanism as the EP arm in test_moe); the modern partitioner
# is exact to 1e-4 — keep the wiring signal on both stacks at the
# tolerance each can meet (a band that still catches NaN/garbage)
TP_RTOL = 1e-4 if CAPABILITIES["exact_gspmd_numerics"] else 2e-2
VIT_TP_RTOL = 1e-4 if CAPABILITIES["exact_gspmd_numerics"] else 1.5e-1
from tpu_hc_bench.data.synthetic import SyntheticTokens
from tpu_hc_bench.models import create_model
from tpu_hc_bench.topology import MODEL_AXIS, build_mesh, compute_layout
from tpu_hc_bench.train import step as step_mod


def _setup(model_parallel, devices, batch=8, model_name="bert_tiny",
           num_classes=1000, make_batch=None):
    layout = compute_layout(num_hosts=1, workers_per_host=len(devices),
                            chips_per_host=len(devices))
    mesh = build_mesh(layout, model_parallel=model_parallel)
    cfg = flags.BenchmarkConfig(
        model=model_name, batch_size=1, variable_update="replicated",
        model_parallel=model_parallel, num_classes=num_classes,
    ).resolve()
    model, spec = create_model(model_name, num_classes=num_classes)
    raw = (make_batch(batch) if make_batch is not None
           else SyntheticTokens(batch, 32, vocab_size=1024, seed=0).batch())
    state = step_mod.make_train_state(model, cfg, raw)
    if model_parallel > 1:
        state = step_mod.shard_state_tp(state, mesh)
    else:
        state = step_mod.replicate_state(state, mesh)
    train_step = step_mod.build_train_step(mesh, cfg, spec)
    dev_batch = step_mod.shard_batch(raw, mesh)
    return state, train_step, dev_batch


def test_tp_param_spec_rules():
    spec = step_mod.tp_param_spec("layer_0/MultiHeadAttention_0/qkv/kernel", 4)
    assert MODEL_AXIS in spec
    assert step_mod.tp_param_spec("layer_0/Dense_0/kernel", 2)[1] == MODEL_AXIS
    assert step_mod.tp_param_spec("layer_0/Dense_1/kernel", 2)[0] == MODEL_AXIS
    # unmatched and CNN params replicate
    assert step_mod.tp_param_spec("conv_init/kernel", 4) == jax.sharding.PartitionSpec()


def test_tp_matches_replicated(devices):
    rng = jax.random.PRNGKey(0)
    state_r, step_r, batch_r = _setup(1, devices)
    state_t, step_t, batch_t = _setup(2, devices)

    # qkv kernels really are sharded over the model axis
    qkv = state_t.params["layer_0"]["MultiHeadAttention_0"]["qkv"]["kernel"]
    assert MODEL_AXIS in qkv.sharding.spec

    losses = []
    for state, train_step, batch in ((state_r, step_r, batch_r),
                                     (state_t, step_t, batch_t)):
        for _ in range(3):
            state, metrics = train_step(state, batch, rng)
        losses.append(float(jax.device_get(metrics["loss"])))
    np.testing.assert_allclose(losses[0], losses[1], rtol=TP_RTOL)


def test_vit_tp_matches_replicated(devices):
    """ViT is tensor-parallel for free: its encoder block shares the
    qkv/out/fc/proj param names the Megatron TP rules match."""
    from tpu_hc_bench.data.synthetic import SyntheticImages

    def images(batch):
        return SyntheticImages(batch, (32, 32, 3), num_classes=10).batch()

    rng = jax.random.PRNGKey(0)
    losses = []
    for mp in (1, 2):
        state, train_step, batch = _setup(
            mp, devices, model_name="vit_tiny", num_classes=10,
            make_batch=images)
        if mp > 1:
            qkv = state.params["layer_0"]["MultiHeadAttention_0"]["qkv"][
                "kernel"]
            assert MODEL_AXIS in qkv.sharding.spec
        for _ in range(2):
            state, metrics = train_step(state, batch, rng)
        losses.append(float(jax.device_get(metrics["loss"])))
    np.testing.assert_allclose(losses[0], losses[1], rtol=VIT_TP_RTOL)


def test_llama_tp_matches_replicated(devices):
    """llama's wq/wk/wv/wo + gate/up/down names have their own TP rules;
    before them, --model_parallel on llama silently degraded to DP."""
    rng = jax.random.PRNGKey(0)
    losses = []
    for mp in (1, 2):
        state, train_step, batch = _setup(mp, devices,
                                          model_name="llama_tiny")
        if mp > 1:
            wq = state.params["layer_0"]["attn"]["wq"]["kernel"]
            gate = state.params["layer_0"]["gate"]["kernel"]
            assert MODEL_AXIS in wq.sharding.spec
            assert MODEL_AXIS in gate.sharding.spec
        for _ in range(3):
            state, metrics = train_step(state, batch, rng)
        losses.append(float(jax.device_get(metrics["loss"])))
    np.testing.assert_allclose(losses[0], losses[1], rtol=1e-4)


def test_tp_rejects_unmatched_model(devices):
    """Non-transformer params match no TP rule -> loud error, not silent
    DP degradation (ADVICE r1 medium)."""
    from tpu_hc_bench.data.synthetic import SyntheticImages

    def images(batch):
        return SyntheticImages(batch, (28, 28, 3), num_classes=10).batch()

    with pytest.raises(ValueError, match="no param matched"):
        _setup(2, devices, model_name="lenet", num_classes=10,
               make_batch=images)


def test_tp_rejects_bad_degree(devices):
    layout = compute_layout(num_hosts=1, workers_per_host=len(devices),
                            chips_per_host=len(devices))
    with pytest.raises(ValueError, match="divisible"):
        build_mesh(layout, model_parallel=3)
