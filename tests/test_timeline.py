"""Flight recorder (``obs.timeline``) + its satellites.

Seven sections, matching the round-17 acceptance contract:

1. Ring mechanics: bounded preallocated ring, drop accounting, the span
   context manager, instants, the coarse phase lane
   (``transition``/``current_phase``).
2. Persistence: flush/append/read round-trip, corrupt-line tolerance,
   never-fatal I/O.
3. Cross-rank merge: clock alignment through heartbeat ``(t_mono,
   t_unix)`` pairs AND the spans files' own ``clock`` records, the
   >= 2-rank aligned Chrome-trace export, summarize's
   straggler/bubble attribution lines.
4. Forensics: ``dump_timeline`` (live ring + other ranks' flushed
   files) and the watchdog wiring (in-process fire with an injected
   ``on_timeout`` — the subprocess e2e proof rides the slow-marked
   emergency-save test in test_memory_obs, which now asserts
   ``timeline_dump.json`` too).
5. ``obs regress``: the noise-aware gate flags an injected 10%
   throughput regression, passes an unchanged rerun, respects
   fingerprints and per-metric direction; the CLI exit codes.
6. The ``span-in-compiled-fn`` analysis lint (positive + negative
   fixtures; the repo baseline stays clean via test_analysis).
7. End-to-end against the SHARED session-scoped ``rewind_run`` driver
   fixture (conftest.py — no new default-lane driver runs): on-by-
   default spans.<k>.jsonl, recorder span names, heartbeat
   phase/incarnation/t_mono fields, `obs timeline` CLI, summarize and
   watch rendering, FleetWriter append-across-incarnations, and the
   bounded-overhead guard (<1% of the measured steady-state step).
"""

from __future__ import annotations

import io
import json
import os
import time
from pathlib import Path

import pytest

from tpu_hc_bench.analysis import lints
from tpu_hc_bench.obs import fleet
from tpu_hc_bench.obs import regress
from tpu_hc_bench.obs import timeline as tl
from tpu_hc_bench.obs.__main__ import main as obs_main

REPO = Path(__file__).resolve().parent.parent


# ---------------------------------------------------------------------
# 1. ring mechanics


def test_ring_is_bounded_and_counts_drops(tmp_path):
    rec = tl.SpanRecorder(capacity=8)
    rec.attach(str(tmp_path), rank=0)
    for i in range(20):
        rec.record("s", float(i), float(i) + 0.5, step=i)
    # nothing flushed yet: 20 recorded, only the newest 8 live
    rec.flush()
    assert rec.dropped == 12
    spans = tl.read_spans(str(tmp_path))[0]
    assert len(spans) == 8
    assert [s["step"] for s in spans] == list(range(12, 20))
    rec.detach()


def test_span_context_manager_and_instant():
    rec = tl.SpanRecorder(capacity=16)
    with rec.span("work", step=3, detail="x"):
        pass
    rec.instant("mark", step=4)
    spans = rec.tail()
    assert spans[0]["name"] == "work" and spans[0]["step"] == 3
    assert spans[0]["detail"] == "x"
    assert spans[0]["t1"] >= spans[0]["t0"]
    assert spans[1]["name"] == "mark" and spans[1]["t0"] == spans[1]["t1"]


def test_phase_lane_transitions_and_current_phase():
    rec = tl.SpanRecorder(capacity=16)
    rec.transition("init")
    assert rec.current_phase() == "init"
    rec.transition("step", step=1)
    # the closed init phase landed as a span
    assert rec.tail()[-1]["name"] == "init"
    assert rec.current_phase() == "step"
    rec.transition("end", step=5)
    # lane closed: current_phase falls back to the newest span
    assert rec.current_phase() == "step"


def test_disabled_recorder_is_a_noop():
    rec = tl.SpanRecorder(capacity=4)
    rec.enabled = False
    rec.record("s", 0.0, 1.0)
    assert rec.tail() == []


# ---------------------------------------------------------------------
# 2. persistence


def test_flush_appends_and_reader_skips_corrupt_lines(tmp_path):
    rec = tl.SpanRecorder(capacity=32)
    rec.attach(str(tmp_path), rank=2)
    rec.record("a", 1.0, 2.0)
    assert rec.flush() == 1
    rec.record("b", 2.0, 3.0)
    assert rec.flush() == 1
    # a flush interrupted by the death it documents: garbage tail
    path = tmp_path / "spans.2.jsonl"
    with open(path, "a") as f:
        f.write('{"name": "tru')
    spans = tl.read_spans(str(tmp_path))
    assert [s["name"] for s in spans[2]] == ["a", "b"]
    rec.detach()


def test_flush_without_run_dir_is_free():
    rec = tl.SpanRecorder(capacity=4)
    rec.record("a", 0.0, 1.0)
    assert rec.flush() == 0        # nowhere to persist, no error


def test_persistence_failure_never_raises(tmp_path):
    rec = tl.SpanRecorder(capacity=4)
    # attach to a path that cannot be a directory
    blocker = tmp_path / "f"
    blocker.write_text("x")
    rec.attach(str(blocker / "sub"), rank=0)
    rec.record("a", 0.0, 1.0)
    assert rec.flush() == 0        # disabled itself, run unharmed
    assert rec.enabled             # RING keeps recording for forensics


# ---------------------------------------------------------------------
# 3. cross-rank merge + clock alignment


def _write_spans(run_dir, rank, spans, clock=None):
    with open(os.path.join(run_dir, f"spans.{rank}.jsonl"), "w") as f:
        if clock is not None:
            f.write(json.dumps({"clock": clock}) + "\n")
        for s in spans:
            f.write(json.dumps(s) + "\n")


def _write_heartbeats(run_dir, rank, pairs):
    with open(os.path.join(run_dir, f"metrics.{rank}.jsonl"), "w") as f:
        for t_mono, t_unix in pairs:
            f.write(json.dumps({"kind": "heartbeat", "host": rank,
                                "step": 1, "step_ewma_ms": 1.0,
                                "t_mono": t_mono, "t_unix": t_unix}) + "\n")


def test_merge_aligns_two_ranks_via_heartbeats(tmp_path):
    """The acceptance merge: two ranks whose monotonic epochs differ by
    4000s but whose spans happened at the SAME wall instant land at the
    same aligned timestamp in one Chrome-trace file."""
    d = str(tmp_path)
    wall = 1.7e9
    _write_spans(d, 0, [{"name": "step_dispatch", "t0": 1000.5,
                         "t1": 1000.6, "step": 1}])
    _write_spans(d, 1, [{"name": "step_dispatch", "t0": 5000.5,
                         "t1": 5000.6, "step": 1}])
    _write_heartbeats(d, 0, [(1000.0, wall)])
    _write_heartbeats(d, 1, [(5000.0, wall)])
    trace = tl.merge_chrome_trace(d)
    xs = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    assert sorted(e["pid"] for e in xs) == [0, 1]
    assert xs[0]["ts"] == xs[1]["ts"]      # aligned despite epoch skew
    assert trace["metadata"]["aligned_ranks"] == [0, 1]


def test_merge_falls_back_to_spans_clock_records(tmp_path):
    d = str(tmp_path)
    wall = 1.7e9
    _write_spans(d, 0, [{"name": "a", "t0": 10.0, "t1": 11.0}],
                 clock={"t_mono": 10.0, "t_unix": wall})
    _write_spans(d, 1, [{"name": "a", "t0": 90.0, "t1": 91.0}],
                 clock={"t_mono": 90.0, "t_unix": wall})
    trace = tl.merge_chrome_trace(d)
    xs = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    assert xs[0]["ts"] == xs[1]["ts"]


def test_merge_without_spans_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        tl.merge_chrome_trace(str(tmp_path))


def test_merge_mixed_aligned_and_clockless_rank_is_loud(tmp_path):
    """Round-20 fallback hardening: a rank whose spans file has no
    ``clock`` records AND whose dir has no heartbeats merges with the
    identity offset and ONE loud warning — it is never silently
    dropped, and the aligned ranks stay aligned."""
    d = str(tmp_path)
    wall = 1.7e9
    _write_spans(d, 0, [{"name": "step_dispatch", "t0": 1000.5,
                         "t1": 1000.6}])
    _write_spans(d, 1, [{"name": "step_dispatch", "t0": 5000.5,
                         "t1": 5000.6}])
    # rank 2: NO clock record in its spans file, NO heartbeat file
    _write_spans(d, 2, [{"name": "ring_get", "t0": 77.0, "t1": 78.0}])
    _write_heartbeats(d, 0, [(1000.0, wall)])
    _write_heartbeats(d, 1, [(5000.0, wall)])
    trace = tl.merge_chrome_trace(d)
    xs = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    assert sorted({e["pid"] for e in xs}) == [0, 1, 2]   # nobody dropped
    a = {e["pid"]: e["ts"] for e in xs}
    assert a[0] == a[1]                 # aligned pair still aligned
    assert trace["metadata"]["aligned_ranks"] == [0, 1]
    warns = trace["metadata"]["warnings"]
    assert len(warns) == 1 and "rank2" in warns[0]
    assert "IDENTITY offset" in warns[0]
    # the clockless rank's process lane is marked in the trace itself
    marks = [e for e in trace["traceEvents"]
             if e.get("ph") == "M" and e["pid"] == 2]
    assert any("unaligned clock" in e["args"]["name"] for e in marks)
    # the CLI surfaces it: WARNING on stderr, degraded exit code 1
    import io as _io

    from tpu_hc_bench.obs.__main__ import main as obs_main_fn

    buf = _io.StringIO()
    import contextlib
    import sys as _sys

    err = _io.StringIO()
    with contextlib.redirect_stderr(err):
        rc = obs_main_fn(["timeline", d], out=buf)
    assert rc == 1
    assert "WARNING" in err.getvalue() and "rank2" in err.getvalue()
    # all-aligned dirs keep exiting 0 (pin for the existing contract)
    for f in os.listdir(d):
        if f.startswith("spans.2."):
            os.unlink(os.path.join(d, f))
    with contextlib.redirect_stderr(_io.StringIO()):
        assert obs_main_fn(["timeline", d], out=_io.StringIO()) == 0


def test_alignment_survives_a_rebooted_incarnation(tmp_path):
    """Elastic resume on a REBOOTED host restarts CLOCK_MONOTONIC: one
    rank's spans file then carries two lives with wildly different
    mono->unix offsets.  Alignment must be per-sample (nearest clock
    pair), not one pooled median — the minority life's spans would
    otherwise land hours off, confidently."""
    d = str(tmp_path)
    wall = 1.7e9
    # life 0: mono epoch ~90000 (long-lived host); life 1 after reboot:
    # mono epoch ~100 (fresh boot), 50 wall-seconds later
    _write_heartbeats(d, 0, [(90000.0, wall), (90010.0, wall + 10.0),
                             (100.0, wall + 50.0), (110.0, wall + 60.0)])
    _write_spans(d, 0, [
        {"name": "step_dispatch", "t0": 90005.0, "t1": 90006.0},
        {"name": "step_dispatch", "t0": 105.0, "t1": 106.0},
    ])
    # reference rank with one life, for the shared t_base
    _write_heartbeats(d, 1, [(500.0, wall)])
    _write_spans(d, 1, [{"name": "step_dispatch", "t0": 505.0,
                         "t1": 506.0}])
    trace = tl.merge_chrome_trace(d)
    xs = sorted((e for e in trace["traceEvents"] if e["ph"] == "X"
                 and e["pid"] == 0), key=lambda e: e["ts"])
    # life 0's span at wall+5, life 1's at wall+55: 50s apart aligned,
    # NOT ~90000s apart (raw mono) or half-pooled-median garbage
    assert xs[1]["ts"] - xs[0]["ts"] == pytest.approx(50.0 * 1e6, rel=1e-3)


def test_offsets_use_median_not_mean(tmp_path):
    d = str(tmp_path)
    # one paused-VM outlier pair must not skew the rank's offset
    _write_heartbeats(d, 0, [(10.0, 110.0), (11.0, 111.0),
                             (12.0, 112.0), (13.0, 9999.0)])
    _write_spans(d, 0, [{"name": "a", "t0": 10.0, "t1": 11.0}])
    assert tl.rank_clock_offsets(d)[0] == pytest.approx(100.0)


def test_timeline_lines_bubble_attribution(tmp_path):
    d = str(tmp_path)
    wall = 1.7e9
    _write_spans(d, 0, [{"name": "step_dispatch", "t0": 100.0,
                         "t1": 110.0}],
                 clock={"t_mono": 100.0, "t_unix": wall})
    _write_spans(d, 1, [{"name": "ring_get", "t0": 200.0, "t1": 207.0}],
                 clock={"t_mono": 200.0, "t_unix": wall})
    lines = tl.timeline_lines(d)
    text = "\n".join(lines)
    assert "2 rank(s)" in text
    # rank1's aligned end is 3s before rank0's, stuck in ring_get
    assert "bubble: rank1" in text and "3.00s" in text
    assert "ring_get" in text


# ---------------------------------------------------------------------
# 4. forensics


def test_dump_timeline_merges_live_ring_and_flushed_ranks(tmp_path):
    d = str(tmp_path)
    _write_spans(d, 1, [{"name": "ring_get", "t0": 1.0, "t1": 2.0}])
    tl.configure(enabled=True, run_dir=None, rank=0)
    tl.record_span("step_dispatch", 0.0, 1.0, step=7)
    try:
        path = tl.dump_timeline(d, reason="watchdog", step=7)
        assert path is not None
        dump = json.loads(Path(path).read_text())
        assert dump["reason"] == "watchdog" and dump["step"] == 7
        assert any(s["name"] == "step_dispatch"
                   for s in dump["ranks"]["0"])
        assert any(s["name"] == "ring_get" for s in dump["ranks"]["1"])
        # summarize's attribution renders the dump line
        assert any("timeline dump" in ln for ln in tl.timeline_lines(d))
    finally:
        tl.configure(enabled=True, run_dir=None, rank=0)


def test_dump_timeline_is_best_effort():
    assert tl.dump_timeline(None, reason="oom") is None
    assert tl.dump_timeline("/nonexistent/nope/x", reason="oom") is None


def test_watchdog_fire_drops_timeline_dump(tmp_path):
    """The driver wires ``dump_timeline`` into the watchdog's
    ``forensics_fn``; an in-process fire (injected ``on_timeout``)
    must leave timeline_dump.json behind — the hang forensics."""
    from tpu_hc_bench.resilience import watchdog as watchdog_mod

    d = str(tmp_path)
    tl.configure(enabled=True, run_dir=None, rank=0)
    tl.record_span("device_step", 0.0, 1.0, step=3)
    fired = []
    dog = watchdog_mod.Watchdog(
        0.15, lambda: None, print_fn=lambda s: None,
        on_timeout=lambda age: fired.append(age), poll_s=0.05,
        forensics_fn=lambda: tl.dump_timeline(d, reason="watchdog"))
    dog.start()
    deadline = time.monotonic() + 5.0
    while not fired and time.monotonic() < deadline:
        time.sleep(0.05)
    dog.stop()
    assert fired
    dump = json.loads((tmp_path / tl.TIMELINE_DUMP_NAME).read_text())
    assert dump["reason"] == "watchdog"
    assert any(s["name"] == "device_step" for s in dump["ranks"]["0"])


# ---------------------------------------------------------------------
# 5. obs regress


def _bench_rec(value=2700.0, **extra_over):
    extra = {"global_batch": 128, "chips": 1, "dtype": "bfloat16",
             "peak_hbm_bytes": 1_000_000, "goodput": 0.5}
    extra.update(extra_over)
    return {"metric": "resnet50_synthetic_images_per_sec_per_chip",
            "value": value, "unit": "images/sec/chip", "extra": extra,
            "manifest": {"device_kind": "cpu", "process_count": 1}}


@pytest.fixture()
def bench_history(tmp_path):
    for i in range(5):
        (tmp_path / f"BENCH_r{i:02d}.json").write_text(
            json.dumps({"parsed": _bench_rec(2700.0 + i)}))
    return tmp_path


def test_regress_flags_injected_ten_percent_drop(bench_history):
    out = io.StringIO()
    rc = regress.run_regress(_bench_rec(2700.0 * 0.9),
                             [str(bench_history / "BENCH_*.json")],
                             out=out)
    assert rc == 1
    assert "REGRESSION" in out.getvalue()
    assert "headline" in out.getvalue()


def test_regress_passes_unchanged_rerun(bench_history):
    rc = regress.run_regress(_bench_rec(2702.0),
                             [str(bench_history / "BENCH_*.json")],
                             out=io.StringIO())
    assert rc == 0


def test_regress_improvement_never_flags(bench_history):
    rc = regress.run_regress(_bench_rec(2700.0 * 1.5),
                             [str(bench_history / "BENCH_*.json")],
                             out=io.StringIO())
    assert rc == 0


def test_regress_lower_better_direction(bench_history):
    # HBM peak DOUBLING is a regression even with throughput flat
    out = io.StringIO()
    rc = regress.run_regress(_bench_rec(2702.0, peak_hbm_bytes=2_000_000),
                             [str(bench_history / "BENCH_*.json")],
                             out=out)
    assert rc == 1 and "peak HBM" in out.getvalue()


def test_regress_fingerprint_mismatch_is_no_history(bench_history):
    rec = _bench_rec(1.0, global_batch=256)       # different config
    out = io.StringIO()
    rc = regress.run_regress(rec, [str(bench_history / "BENCH_*.json")],
                             out=out)
    assert rc == 0 and "no history" in out.getvalue()


def test_regress_mad_adapts_to_noisy_history(tmp_path):
    # noisy history (+-10%): a 10% drop is WITHIN the noise band and
    # must not flag — the fixed-threshold failure mode this gate avoids
    for i, v in enumerate([2400, 2700, 3000, 2500, 2900]):
        (tmp_path / f"BENCH_r{i:02d}.json").write_text(
            json.dumps({"parsed": _bench_rec(float(v))}))
    rc = regress.run_regress(_bench_rec(2700.0 * 0.9),
                             [str(tmp_path / "BENCH_*.json")],
                             out=io.StringIO())
    assert rc == 0


def test_regress_parses_repo_bench_wrapper():
    rec = regress.load_bench_record(str(REPO / "BENCH_r05.json"))
    assert rec is not None and rec["value"] > 0
    assert regress.fingerprint(rec)[0].startswith("resnet50")


def test_regress_cli_exit_codes(bench_history, capsys):
    fresh = bench_history / "fresh.json"
    fresh.write_text(json.dumps(_bench_rec(2700.0 * 0.9)))
    rc = obs_main(["regress", str(fresh), "--history",
                   str(bench_history / "BENCH_*.json")],
                  out=io.StringIO())
    assert rc == 1
    # the gate never compares a file against itself: the fresh path is
    # excluded even when the history glob matches it
    fresh2 = bench_history / "BENCH_fresh.json"
    fresh2.write_text(json.dumps(_bench_rec(2700.0 * 0.9)))
    rc = obs_main(["regress", str(fresh2), "--history",
                   str(bench_history / "BENCH_*.json")],
                  out=io.StringIO())
    assert rc == 1
    assert obs_main(["regress", str(bench_history / "nope.json")],
                    out=io.StringIO()) == 2


# ---------------------------------------------------------------------
# 6. span-in-compiled-fn lint


_LINT_BAD = """
import jax
from tpu_hc_bench.obs import timeline

@jax.jit
def step(x):
    timeline.record_span("step", 0.0, 1.0)
    return x * 2
"""

_LINT_BAD_NESTED = """
import jax
from tpu_hc_bench.obs import timeline as timeline_mod


def build(mesh):
    def step(x):
        timeline_mod.instant("mark")
        return x + 1
    return jax.jit(step)
"""

_LINT_GOOD = """
import jax, time
from tpu_hc_bench.obs import timeline


def run(step_fn, x):
    t0 = time.monotonic()
    y = step_fn(x)
    timeline.record_span("step_dispatch", t0, time.monotonic())
    return y
"""

_LINT_GOOD_OTHER_SPAN = """
import jax

@jax.jit
def step(tracer):
    return tracer.span(3)      # somebody else's .span — not the recorder
"""


def test_lint_flags_recorder_call_in_jit():
    f = [x for x in lints.lint_source_text(_LINT_BAD)
         if x.lint == lints.SPAN_IN_JIT]
    assert len(f) == 1 and f[0].severity == "error"
    assert "record_span" in f[0].message


def test_lint_flags_nested_traced_fn():
    f = [x for x in lints.lint_source_text(_LINT_BAD_NESTED)
         if x.lint == lints.SPAN_IN_JIT]
    assert len(f) == 1


_LINT_BAD_BARE_IMPORT = """
import jax
from tpu_hc_bench.obs.timeline import transition

@jax.jit
def step(x):
    transition("step")
    return x * 2
"""


def test_lint_flags_bare_imported_recorder_call():
    # `from ...timeline import transition` leaves no dotted prefix to
    # recognize — the import binding itself marks the call
    f = [x for x in lints.lint_source_text(_LINT_BAD_BARE_IMPORT)
         if x.lint == lints.SPAN_IN_JIT]
    assert len(f) == 1


def test_lint_allows_host_side_recording():
    assert not [x for x in lints.lint_source_text(_LINT_GOOD)
                if x.lint == lints.SPAN_IN_JIT]


def test_lint_ignores_unrelated_span_methods():
    assert not [x for x in lints.lint_source_text(_LINT_GOOD_OTHER_SPAN)
                if x.lint == lints.SPAN_IN_JIT]


def test_lint_suppression_token():
    src = _LINT_BAD.replace(
        'timeline.record_span("step", 0.0, 1.0)',
        'timeline.record_span("step", 0.0, 1.0)  '
        '# thb:lint-ok[span-in-compiled-fn]')
    assert not [x for x in lints.lint_source_text(src)
                if x.lint == lints.SPAN_IN_JIT]


# ---------------------------------------------------------------------
# 7. e2e against the shared rewind_run fixture + fleet satellites


def test_rewind_run_persists_spans_by_default(rewind_run):
    """On-by-default: the fixture sets no --flight_recorder flag, yet
    its run dir carries rank 0's span file with every driver lane."""
    spans = tl.read_spans(rewind_run["dir"])
    assert 0 in spans and spans[0]
    names = {s["name"] for s in spans[0]}
    # fine driver spans + the coarse goodput lane + checkpoint spans
    assert {"input_wait", "step_dispatch", "device_step",
            "compile", "ckpt_write"} <= names
    # rewind fault injected at step 1: the restore span is on the tape
    assert "ckpt_restore" in names


def test_rewind_run_chrome_trace_cli(rewind_run, tmp_path):
    out_path = str(tmp_path / "t.trace.json")
    buf = io.StringIO()
    assert obs_main(["timeline", rewind_run["dir"], "-o", out_path],
                    out=buf) == 0
    trace = json.loads(Path(out_path).read_text())
    assert any(e.get("name") == "device_step"
               for e in trace["traceEvents"])
    assert trace["metadata"]["aligned_ranks"] == [0]
    assert "chrome trace written" in buf.getvalue()


def test_rewind_run_summarize_renders_timeline(rewind_run):
    buf = io.StringIO()
    assert obs_main(["summarize", rewind_run["dir"]], out=buf) == 0
    text = buf.getvalue()
    assert "timeline: 1 rank(s)" in text


def test_rewind_run_heartbeat_phase_and_incarnation(rewind_run):
    recs = fleet.read_heartbeats(rewind_run["dir"])[0]
    assert recs
    for r in recs:
        assert r["incarnation"] == 0
        assert isinstance(r["t_mono"], float)
    assert any(r.get("phase") for r in recs)


def test_rewind_run_watch_renders_phase_column(rewind_run):
    from tpu_hc_bench.obs import metrics as obs_metrics
    from tpu_hc_bench.obs import watch as watch_mod

    manifest, records = obs_metrics.read_run(rewind_run["dir"])
    lines = watch_mod.render(rewind_run["dir"], manifest, records)
    row = [ln for ln in lines if ln.strip().startswith("rank0:")]
    assert row and "phase" in row[0]


def test_fleet_writer_appends_across_incarnations(tmp_path):
    """The round-17 fix: an elastic resume into the same run dir used
    to TRUNCATE the prior life's heartbeats; now it appends, tagged."""
    w1 = fleet.FleetWriter(str(tmp_path), process_index=0)
    assert w1.incarnation == 0
    w1.heartbeat(step=5, step_ewma_ms=1.0)
    w1.close()
    w2 = fleet.FleetWriter(str(tmp_path), process_index=0)
    assert w2.incarnation == 1
    w2.heartbeat(step=1, step_ewma_ms=2.0)
    w2.close()
    recs = fleet.read_heartbeats(str(tmp_path))[0]
    assert [r["step"] for r in recs] == [5, 1]     # both lives survive
    assert [r["incarnation"] for r in recs] == [0, 1]


def test_flight_recorder_off_flag(tmp_path):
    from tpu_hc_bench import flags

    cfg = flags.BenchmarkConfig(flight_recorder="off").resolve()
    assert cfg.flight_recorder == "off"
    with pytest.raises(ValueError, match="flight_recorder"):
        flags.BenchmarkConfig(flight_recorder="maybe").resolve()
    # the off switch stops the ring cold
    rec = tl.SpanRecorder()
    rec.enabled = False
    rec.record("x", 0.0, 1.0)
    assert rec.tail() == []


def test_recorder_overhead_under_one_percent(rewind_run):
    """The bounded-overhead guard: the driver records <= 4 spans per
    step (input_wait, step_dispatch, one fetch-thread device_step, an
    amortized share of the sync-window flush); 4x the measured per-span
    cost must stay under 1% of the fixture's measured steady-state
    step time."""
    rec = tl.SpanRecorder(capacity=1024)
    n = 20_000
    t0 = time.perf_counter()
    for i in range(n):
        rec.record("overhead_probe", 0.0, 1.0, step=i)
    per_span_s = (time.perf_counter() - t0) / n
    step_s = rewind_run["result"].mean_step_ms / 1e3
    assert step_s > 0
    assert 4 * per_span_s < 0.01 * step_s, (
        f"recorder overhead {4 * per_span_s * 1e6:.1f}us/step vs 1% of "
        f"step {0.01 * step_s * 1e6:.1f}us")


def test_serve_engine_records_spans(tmp_path):
    """Serving lane instrumentation without a new engine warmup: the
    span call sites live in ``_timed``/admit/retire, pinned here by
    source inspection (a full engine run is test_serve's job)."""
    import inspect

    from tpu_hc_bench.serve import engine as engine_mod

    src = inspect.getsource(engine_mod.ServeEngine)
    assert "timeline_mod.record_span(kind" in src
    assert 'timeline_mod.instant("retire"' in src
    assert 'timeline_mod.instant("admit"' in src


_MERGE_WORKER = """
import sys
import tpu_hc_bench  # noqa: F401  (JAX version shims before config)
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 2)

from tpu_hc_bench.parallel import distributed
from tpu_hc_bench import flags
from tpu_hc_bench.train import driver

port, mdir = int(sys.argv[1]), sys.argv[2]
distributed.initialize(coordinator_port=port)
assert jax.process_count() == 2 and jax.device_count() == 4
cfg = flags.BenchmarkConfig(
    model="trivial", num_classes=10, batch_size=1,
    num_warmup_batches=1, num_batches=4, display_every=2,
    metrics_dir=mdir).resolve()
res = driver.run_benchmark(cfg, print_fn=lambda s: None)
print(f"TL_MERGE_OK process={jax.process_index()} "
      f"rate={res.total_images_per_sec:.1f}", flush=True)
"""


@pytest.mark.slow
def test_two_rank_run_merges_one_trace(tmp_path):
    """The acceptance merge on REAL processes: a 2-process driver run
    leaves spans.0.jsonl AND spans.1.jsonl in the shared run dir, and
    `obs timeline` merges them into one aligned Chrome-trace file."""
    import socket
    import subprocess
    import sys as _sys
    import textwrap

    from tpu_hc_bench._compat import CAPABILITIES

    if not CAPABILITIES["cpu_multiprocess_collectives"]:
        pytest.skip("CPU backend lacks cross-process collectives")
    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent(_MERGE_WORKER))
    hostfile = tmp_path / "nodeips.txt"
    hostfile.write_text("127.0.0.1\n127.0.0.1\n")
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    mdir = tmp_path / "m"
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.update({
            "TPU_HC_BENCH_HOSTFILE": str(hostfile),
            "TPU_HC_BENCH_PROCESS_ID": str(pid),
            "PYTHONPATH": f"{REPO}:{env.get('PYTHONPATH', '')}",
            "JAX_PLATFORMS": "cpu",
        })
        procs.append(subprocess.Popen(
            [_sys.executable, str(script), str(port), str(mdir)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True))
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=180)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {i} failed:\n{out}"
        assert "TL_MERGE_OK" in out
    spans = tl.read_spans(str(mdir))
    assert sorted(spans) == [0, 1] and all(spans.values())
    buf = io.StringIO()
    assert obs_main(["timeline", str(mdir)], out=buf) == 0
    trace = json.loads((mdir / "timeline.trace.json").read_text())
    xs = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    assert sorted({e["pid"] for e in xs}) == [0, 1]
    assert trace["metadata"]["aligned_ranks"] == [0, 1]
    assert "2 rank(s)" in buf.getvalue()


@pytest.mark.slow
def test_input_service_spans_e2e(tmp_path):
    """The data-service lanes (svc_decode / ring_put / ring_get) land on
    the recorder when a service streams batches."""
    import numpy as np

    from tpu_hc_bench.data import service as service_mod

    tl.configure(enabled=True, run_dir=None, rank=0)
    layout = service_mod.BatchLayout(
        [service_mod.ArraySpec("x", (4, 8), "float32")])

    def make_stream(w):
        def gen():
            for i in range(3):
                yield (np.full((4, 8), i, np.float32),)
        return gen()

    svc = service_mod.InputService(
        f"thbtl{os.getpid() % 100000}", layout, num_workers=1,
        make_stream=make_stream, depth=2).start()
    client = service_mod.ServiceClient(svc.name, layout, worker=0,
                                       depth=2, copy=True)
    got = list(client)
    client.close()
    svc.stop()
    assert len(got) == 3
    names = {s["name"] for s in tl.get_recorder().tail(256)}
    assert {"svc_decode", "ring_put", "ring_get"} <= names
