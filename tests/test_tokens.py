"""Memory-mapped token-corpus loader (data/tokens.py) + driver wiring."""

import numpy as np
import pytest

from tpu_hc_bench import flags
from tpu_hc_bench.data import tokens
from tpu_hc_bench.train import driver


def _corpus(tmp_path, n=5000, vocab=1024, split="train", seed=0):
    rng = np.random.default_rng(seed)
    toks = rng.integers(1, vocab, size=(n,))
    tokens.write_token_file(tmp_path / f"{split}.bin", toks, vocab)
    return toks


def test_wire_format_roundtrip(tmp_path):
    toks = _corpus(tmp_path, vocab=1024)
    path = tmp_path / "train.bin"
    assert path.stat().st_size == 5000 * 2          # uint16 wire
    back = np.fromfile(path, np.uint16)
    np.testing.assert_array_equal(back, toks)
    # vocab > 65536 widens the wire
    tokens.write_token_file(tmp_path / "wide.bin", np.array([70000]), 70001)
    assert (tmp_path / "wide.bin").stat().st_size == 4


def test_causal_batches_deterministic(tmp_path):
    toks = _corpus(tmp_path)
    ds = tokens.TokenDataset(tmp_path, global_batch=4, seq_len=16,
                             causal_lm=True, seed=7)
    t1, y1, w1 = ds.batch(step=3)
    t2, y2, w2 = tokens.TokenDataset(
        tmp_path, global_batch=4, seq_len=16, causal_lm=True,
        seed=7).batch(step=3)
    np.testing.assert_array_equal(t1, t2)           # keyed rng: reproducible
    assert not np.array_equal(t1, ds.batch(step=4)[0])
    # next-token alignment: targets are the stream shifted by one
    np.testing.assert_array_equal(t1[:, 1:], y1[:, :-1])
    assert w1.shape == t1.shape and w1.min() == 1.0
    # windows really come from the corpus
    flat = toks.astype(np.int32)
    row = t1[0]
    starts = np.flatnonzero(flat[: len(flat) - 17] == row[0])
    assert any(np.array_equal(flat[s:s + 16], row) for s in starts)


def test_mlm_batches(tmp_path):
    _corpus(tmp_path)
    ds = tokens.TokenDataset(tmp_path, global_batch=8, seq_len=32,
                             causal_lm=False, seed=1)
    t, y, w = ds.batch()
    assert ((t == 0) == (w > 0)).all()              # masked inputs
    rate = float(w.mean())
    assert 0.05 < rate < 0.3                        # ~15% BERT masking
    np.testing.assert_array_equal(np.where(w > 0, y, t), y)


def test_worker_sharding_disjoint(tmp_path):
    _corpus(tmp_path, n=4000)
    a = tokens.TokenDataset(tmp_path, 2, 8, worker=0, num_workers=2)
    b = tokens.TokenDataset(tmp_path, 2, 8, worker=1, num_workers=2)
    assert len(a._data) == len(b._data) == 2000
    assert not np.array_equal(np.asarray(a._data[:100]),
                              np.asarray(b._data[:100]))


def test_guards(tmp_path):
    _corpus(tmp_path, n=100, vocab=1024)
    with pytest.raises(FileNotFoundError, match="token file"):
        tokens.TokenDataset(tmp_path, 2, 8, split="validation")
    with pytest.raises(ValueError, match="vocab"):
        tokens.TokenDataset(tmp_path, 2, 8, vocab_size=500)
    with pytest.raises(ValueError, match="too small"):
        tokens.TokenDataset(tmp_path, 2, 64, num_workers=4)


def test_text_driver_real_corpus(mesh8, tmp_path):
    """bert_tiny (MLM) and llama_tiny (causal) train from a real token
    file through the full driver — the text real-data axis end to end."""
    _corpus(tmp_path, n=20000, vocab=1024)
    for model in ("bert_tiny", "llama_tiny"):
        cfg = flags.BenchmarkConfig(
            model=model, batch_size=1, num_warmup_batches=1, num_batches=2,
            display_every=1, data_dir=str(tmp_path),
        ).resolve()
        out = []
        res = driver.run_benchmark(cfg, print_fn=out.append)
        assert np.isfinite(res.final_loss), model


def test_tokens_cli(tmp_path, capsys):
    from tpu_hc_bench.data import tokens as tok_mod

    tok_mod.main([str(tmp_path / "rand"), "--num_tokens", "1000",
                  "--vocab_size", "512"])
    ds = tokens.TokenDataset(tmp_path / "rand", 2, 8)
    assert ds.batch()[0].max() < 512

    (tmp_path / "c.txt").write_text("hello corpus " * 100)
    tok_mod.main([str(tmp_path / "text"), "--from_text",
                  str(tmp_path / "c.txt")])
    ds = tokens.TokenDataset(tmp_path / "text", 2, 8, vocab_size=256)
    t, y, w = ds.batch()
    assert t.max() < 256
