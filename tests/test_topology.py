"""Layout-math and mesh tests (reference math: run-tf-sing-ucx-openmpi.sh:37-50)."""

import pytest

from tpu_hc_bench import topology


def test_whole_host_mode():
    # WORKERS_PER_SOCKET=0 -> whole-machine mode (:40-46): all chips
    lay = topology.compute_layout(num_hosts=4, workers_per_host=0, chips_per_host=8)
    assert lay.workers_per_host == 8
    assert lay.total_workers == 32


def test_explicit_workers():
    lay = topology.compute_layout(num_hosts=2, workers_per_host=2, chips_per_host=4)
    assert lay.total_workers == 4
    assert lay.global_batch(64) == 256  # per-worker batch semantics


def test_layout_validation():
    with pytest.raises(ValueError):
        topology.compute_layout(0, 1, 4)
    with pytest.raises(ValueError):
        topology.compute_layout(1, 5, 4)  # more workers than chips
    with pytest.raises(ValueError):
        topology.compute_layout(1, -1, 4)


def test_discover_layout_virtual_devices(devices):
    lay = topology.discover_layout()
    assert lay.chips_per_host == 8
    assert lay.total_workers == 8


def test_build_mesh_dp(mesh8):
    assert mesh8.axis_names == (topology.DATA_AXIS, topology.MODEL_AXIS)
    assert mesh8.shape[topology.DATA_AXIS] == 8
    assert mesh8.shape[topology.MODEL_AXIS] == 1


def test_build_mesh_hybrid(devices):
    lay = topology.discover_layout()
    mesh = topology.build_mesh(lay, model_parallel=2)
    assert mesh.shape[topology.DATA_AXIS] == 4
    assert mesh.shape[topology.MODEL_AXIS] == 2


def test_select_devices_partial(devices):
    lay = topology.compute_layout(num_hosts=1, workers_per_host=4, chips_per_host=8)
    picked = topology.select_devices(lay)
    assert len(picked) == 4
    ids = [d.id for d in picked]
    assert ids == sorted(ids)  # deterministic contiguous pinning


def test_summary_banner():
    lay = topology.compute_layout(4, 1, 8)
    text = "\n".join(lay.summary_lines(fabric="ici"))
    assert "num_hosts=4" in text and "total_workers=4" in text
