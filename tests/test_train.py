"""Train-step + driver tests on the 8-device virtual mesh.

Uses small models/batches (CPU mesh) but exercises the full protocol:
DP psum path, GSPMD replicated path, host (sock-analog) path, BN-stat sync,
forward_only, and the driver's warmup/timed/display loop.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_hc_bench import flags
from tpu_hc_bench.data.synthetic import SyntheticImages, SyntheticTokens
from tpu_hc_bench.models import ModelSpec, TrivialModel, create_model
from tpu_hc_bench.parallel import fabric as fabric_mod
from tpu_hc_bench.topology import compute_layout
from tpu_hc_bench.train import driver, step as step_mod


def tiny_cfg(**kw):
    base = dict(
        batch_size=2, num_warmup_batches=1, num_batches=4, display_every=2,
        model="trivial", num_classes=10, init_learning_rate=0.05,
    )
    base.update(kw)
    return flags.BenchmarkConfig(**base).resolve()


def tiny_image_setup(mesh8, cfg, shape=(8, 8, 3)):
    spec = ModelSpec("trivial", TrivialModel, shape, 1e6)
    model = TrivialModel(num_classes=cfg.num_classes)
    ds = SyntheticImages(16, shape, num_classes=cfg.num_classes)
    batch = ds.batch()
    state = step_mod.make_train_state(model, cfg, batch)
    state = step_mod.replicate_state(state, mesh8)
    dev_batch = step_mod.shard_batch(batch, mesh8)
    return model, spec, state, batch, dev_batch


def run_steps(step_fn, state, batch, n=3):
    rng = jax.random.PRNGKey(0)
    losses = []
    for _ in range(n):
        state, metrics = step_fn(state, batch, rng)
        losses.append(float(jax.device_get(metrics["loss"])))
    return state, losses


def test_psum_path_loss_decreases(mesh8):
    cfg = tiny_cfg()
    model, spec, state, batch, dev_batch = tiny_image_setup(mesh8, cfg)
    step_fn = step_mod.build_train_step(mesh8, cfg, spec)
    state, losses = run_steps(step_fn, state, dev_batch, n=8)
    assert losses[-1] < losses[0], losses


def test_host_path_matches_ici_path(mesh8):
    """The sock-analog slow path must produce the same update as ICI psum."""
    cfg = tiny_cfg()
    # two independent (deterministically identical) states: the ICI step
    # donates its input buffers, so states can't be shared across paths
    model, spec, state_a, batch, dev_batch = tiny_image_setup(mesh8, cfg)
    _, _, state_b, _, _ = tiny_image_setup(mesh8, cfg)
    ici = step_mod.build_train_step(mesh8, cfg, spec, fabric_mod.Fabric.ICI)
    host = step_mod.build_train_step(mesh8, cfg, spec, fabric_mod.Fabric.HOST)
    rng = jax.random.PRNGKey(0)
    s_ici, _ = ici(state_a, dev_batch, rng)
    s_host, _ = host(state_b, dev_batch, rng)
    for a, b in zip(
        jax.tree.leaves(s_ici.params), jax.tree.leaves(s_host.params)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-5, atol=2e-6
        )


def test_resnet18_small_images_bn_sync(mesh8):
    """BN model: batch_stats stay replicated-identical after the step."""
    cfg = tiny_cfg(model="resnet18", num_classes=10, batch_size=1)
    model, spec = create_model("resnet18", num_classes=10)
    spec = ModelSpec("resnet18", None, (32, 32, 3), 1e8)
    ds = SyntheticImages(8, (32, 32, 3), num_classes=10)
    batch = ds.batch()
    state = step_mod.make_train_state(model, cfg, batch)
    state = step_mod.replicate_state(state, mesh8)
    dev_batch = step_mod.shard_batch(batch, mesh8)
    step_fn = step_mod.build_train_step(mesh8, cfg, spec)
    state, losses = run_steps(step_fn, state, dev_batch, n=2)
    assert state.batch_stats, "resnet must carry batch_stats"
    assert np.isfinite(losses).all()


def test_gspmd_path_matches_psum_path(mesh8):
    """--variable_update=replicated (GSPMD) must match the explicit-psum
    update on a BN-free model (identical math, different collective
    insertion)."""
    cfg_psum = tiny_cfg(variable_update="psum")
    cfg_gspmd = tiny_cfg(variable_update="replicated")
    model, spec, state_a, batch, dev_batch = tiny_image_setup(mesh8, cfg_psum)
    _, _, state_b, _, _ = tiny_image_setup(mesh8, cfg_gspmd)
    psum_step = step_mod.build_train_step(mesh8, cfg_psum, spec)
    gspmd_step = step_mod.build_train_step(mesh8, cfg_gspmd, spec)
    rng = jax.random.PRNGKey(0)
    s_p, m_p = psum_step(state_a, dev_batch, rng)
    s_g, m_g = gspmd_step(state_b, dev_batch, rng)
    assert float(m_p["loss"]) == pytest.approx(float(m_g["loss"]), rel=1e-5)
    for a, b in zip(jax.tree.leaves(s_p.params), jax.tree.leaves(s_g.params)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-5, atol=2e-6
        )


def test_grad_accumulation_matches_full_batch(mesh8):
    """--gradient_accumulation_steps=2 must produce the identical update
    to the one-shot step on a BN-free, dropout-free model (microbatch
    means average exactly to the full-batch mean for uniform weights)."""
    cfg_full = tiny_cfg()
    cfg_acc = tiny_cfg(gradient_accumulation_steps=2)
    model, spec, state_a, batch, dev_batch = tiny_image_setup(mesh8, cfg_full)
    _, _, state_b, _, _ = tiny_image_setup(mesh8, cfg_acc)
    full = step_mod.build_train_step(mesh8, cfg_full, spec)
    acc = step_mod.build_train_step(mesh8, cfg_acc, spec)
    rng = jax.random.PRNGKey(0)
    s_f, m_f = full(state_a, dev_batch, rng)
    s_a, m_a = acc(state_b, dev_batch, rng)
    assert float(m_f["loss"]) == pytest.approx(float(m_a["loss"]), rel=1e-5)
    for a, b in zip(jax.tree.leaves(s_f.params), jax.tree.leaves(s_a.params)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-5, atol=2e-6
        )


def test_grad_accumulation_bf16_matches_f32(mesh8):
    """--accum_dtype=bf16 (bf16 accumulator tree, kept bf16 through the
    allreduce and optimizer — the HBM/wire lever for param-bound members)
    must track the f32 arm's update to bf16 gradient precision."""
    cfg_f32 = tiny_cfg(gradient_accumulation_steps=2)
    cfg_b16 = tiny_cfg(gradient_accumulation_steps=2, accum_dtype="bf16")
    model, spec, state_a, batch, dev_batch = tiny_image_setup(mesh8, cfg_f32)
    _, _, state_b, _, _ = tiny_image_setup(mesh8, cfg_b16)
    p0 = jax.tree.map(np.asarray, jax.device_get(state_a.params))
    f32_step = step_mod.build_train_step(mesh8, cfg_f32, spec)
    b16_step = step_mod.build_train_step(mesh8, cfg_b16, spec)
    rng = jax.random.PRNGKey(0)
    s_f, m_f = f32_step(state_a, dev_batch, rng)
    s_b, m_b = b16_step(state_b, dev_batch, rng)
    assert float(m_f["loss"]) == pytest.approx(float(m_b["loss"]), rel=1e-5)
    # compare the param DELTAS (lr * grad): bf16 grads carry ~3
    # significant digits, so the update agrees to ~1% relative with a
    # small absolute floor for near-zero entries
    for a, b, p in zip(jax.tree.leaves(s_f.params),
                       jax.tree.leaves(s_b.params), jax.tree.leaves(p0)):
        da, db = np.asarray(a) - p, np.asarray(b) - p
        np.testing.assert_allclose(da, db, rtol=2e-2,
                                   atol=2e-2 * np.abs(da).max() + 1e-8)
    # params/updates themselves must stay in the param dtype (f32)
    assert all(x.dtype == np.float32
               for x in jax.tree.leaves(jax.device_get(s_b.params)))


def test_grad_accumulation_bf16_matches_f32_high_accum(mesh8):
    """The accum=32 arm of the bf16-vs-f32 delta (ADVICE r5): the bf16
    accumulator's error is a random walk over microbatch additions,
    growing ~sqrt(N)*2^-9 with the accumulation count — so the sweep's
    accum=64 configs see ~3%, not the ~0.4% the accum=2 test tolerates.
    Same protocol as accum=2 above, with the tolerance loosened by the
    sqrt(32/2) = 4x the scaling predicts."""
    accum = 32
    cfg_f32 = tiny_cfg(batch_size=accum,
                       gradient_accumulation_steps=accum)
    cfg_b16 = tiny_cfg(batch_size=accum,
                       gradient_accumulation_steps=accum,
                       accum_dtype="bf16")
    shape = (8, 8, 3)
    spec = ModelSpec("trivial", TrivialModel, shape, 1e6)
    model = TrivialModel(num_classes=cfg_f32.num_classes)
    # local per-device batch must be divisible by accum: 8 devices x 32
    batch = SyntheticImages(8 * accum, shape,
                            num_classes=cfg_f32.num_classes).batch()
    state_a = step_mod.replicate_state(
        step_mod.make_train_state(model, cfg_f32, batch), mesh8)
    state_b = step_mod.replicate_state(
        step_mod.make_train_state(model, cfg_b16, batch), mesh8)
    dev_batch = step_mod.shard_batch(batch, mesh8)
    p0 = jax.tree.map(np.asarray, jax.device_get(state_a.params))
    rng = jax.random.PRNGKey(0)
    s_f, m_f = step_mod.build_train_step(mesh8, cfg_f32, spec)(
        state_a, dev_batch, rng)
    s_b, m_b = step_mod.build_train_step(mesh8, cfg_b16, spec)(
        state_b, dev_batch, rng)
    assert float(m_f["loss"]) == pytest.approx(float(m_b["loss"]), rel=1e-4)
    for a, b, p in zip(jax.tree.leaves(s_f.params),
                       jax.tree.leaves(s_b.params), jax.tree.leaves(p0)):
        da, db = np.asarray(a) - p, np.asarray(b) - p
        np.testing.assert_allclose(da, db, rtol=8e-2,
                                   atol=8e-2 * np.abs(da).max() + 1e-8)


def test_accum_dtype_rejected_without_accumulation():
    with pytest.raises(ValueError, match="accum_dtype"):
        tiny_cfg(accum_dtype="bf16")
    with pytest.raises(ValueError, match="accum_dtype"):
        tiny_cfg(gradient_accumulation_steps=2, accum_dtype="f16")


def test_grad_accumulation_bn_model_trains(mesh8):
    """BN member under accumulation: stats stay replicated, loss finite.
    No exact-parity claim: BN normalizes per-microbatch batch stats, and
    the running-stat EMA advances one decay per optimizer step (toward
    the microbatch-mean statistics — see _accumulated_grads docstring)."""
    cfg = tiny_cfg(model="resnet18", num_classes=10, batch_size=1,
                   gradient_accumulation_steps=2)
    model, spec = create_model("resnet18", num_classes=10)
    spec = ModelSpec("resnet18", None, (32, 32, 3), 1e8)
    ds = SyntheticImages(16, (32, 32, 3), num_classes=10)
    batch = ds.batch()
    state = step_mod.make_train_state(model, cfg, batch)
    state = step_mod.replicate_state(state, mesh8)
    dev_batch = step_mod.shard_batch(batch, mesh8)
    step_fn = step_mod.build_train_step(mesh8, cfg, spec)
    state, losses = run_steps(step_fn, state, dev_batch, n=2)
    assert state.batch_stats, "resnet must carry batch_stats"
    assert np.isfinite(losses).all()


def test_grad_accumulation_driver_and_rejections(mesh8):
    """CLI end-to-end (banner + finite loss) and the loud-rejection
    matrix for arms that would silently ignore the flag."""
    cfg = tiny_cfg(batch_size=2, gradient_accumulation_steps=2,
                   num_batches=3)
    out = []
    res = driver.run_benchmark(cfg, print_fn=out.append)
    assert res.total_images_per_sec > 0
    assert np.isfinite(res.final_loss)
    assert "gradient_accumulation_steps=2" in "\n".join(out)

    for combo in (dict(pipeline_parallel=2),
                  dict(model_parallel=2),
                  dict(variable_update="replicated"),
                  dict(forward_only=True)):
        with pytest.raises(ValueError,
                           match="gradient_accumulation_steps"):
            tiny_cfg(gradient_accumulation_steps=2, **combo)
    # host fabric is only known at step-build time
    cfg_h = tiny_cfg(gradient_accumulation_steps=2)
    _, spec, *_ = tiny_image_setup(mesh8, cfg_h)
    with pytest.raises(ValueError, match="host"):
        step_mod.build_train_step(mesh8, cfg_h, spec,
                                  fabric_mod.Fabric.HOST)
    # DP x SP composes — including via the SP replicated->psum
    # translation, which must not be pre-empted by the accum rejection
    cfg_sp = tiny_cfg(gradient_accumulation_steps=2, sequence_parallel=2,
                      variable_update="replicated")
    assert cfg_sp.variable_update == "psum"
    # ...and the degenerate seq-1 axis (ring attention at SP=1), which
    # translates replicated->psum through the other SP block
    cfg_deg = tiny_cfg(model="bert_tiny", gradient_accumulation_steps=2,
                       attention_impl="ring",
                       variable_update="replicated")
    assert cfg_deg.variable_update == "psum"


def test_forward_only(mesh8):
    cfg = tiny_cfg(forward_only=True)
    model, spec, state, batch, dev_batch = tiny_image_setup(mesh8, cfg)
    # snapshot params to host before the (donating) step invalidates buffers
    orig = jax.device_get(state.params)
    step_fn = step_mod.build_train_step(mesh8, cfg, spec)
    s1, losses = run_steps(step_fn, state, dev_batch, n=3)
    # params unchanged in forward_only mode
    for a, b in zip(jax.tree.leaves(orig), jax.tree.leaves(s1.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert losses[0] == pytest.approx(losses[-1])


def test_bert_tiny_mlm_step(mesh8):
    from tpu_hc_bench.models import bert

    cfg = tiny_cfg(model="bert_base", optimizer="adam",
                   init_learning_rate=1e-3)
    model = bert.bert_tiny_mlm()
    spec = ModelSpec("bert_tiny", None, (16,), 1e6, is_text=True)
    ds = SyntheticTokens(16, 16, vocab_size=1024)
    batch = ds.batch()
    state = step_mod.make_train_state(model, cfg, batch)
    state = step_mod.replicate_state(state, mesh8)
    dev_batch = step_mod.shard_batch(batch, mesh8)
    step_fn = step_mod.build_train_step(mesh8, cfg, spec)
    state, losses = run_steps(step_fn, state, dev_batch, n=6)
    assert losses[-1] < losses[0], losses


def test_bert_fused_xent_matches_unfused(mesh8):
    """--fused_xent (Pallas blocked CE) must match the optax loss path."""
    from tpu_hc_bench.models import bert

    losses = {}
    for fused in (False, True):
        cfg = tiny_cfg(model="bert_base", optimizer="adam",
                       init_learning_rate=1e-3, fused_xent=fused)
        model = bert.bert_tiny_mlm()
        spec = ModelSpec("bert_tiny", None, (16,), 1e6, is_text=True)
        ds = SyntheticTokens(16, 16, vocab_size=1024)
        batch = ds.batch()
        state = step_mod.make_train_state(model, cfg, batch)
        state = step_mod.replicate_state(state, mesh8)
        dev_batch = step_mod.shard_batch(batch, mesh8)
        step_fn = step_mod.build_train_step(mesh8, cfg, spec)
        _, ls = run_steps(step_fn, state, dev_batch, n=2)
        losses[fused] = ls
    np.testing.assert_allclose(losses[False], losses[True], rtol=1e-4)


def test_driver_end_to_end(mesh8):
    cfg = tiny_cfg(model="trivial", num_classes=100)
    out = []
    res = driver.run_benchmark(cfg, print_fn=out.append)
    text = "\n".join(out)
    assert "total images/sec:" in text
    assert "warmup done" in text
    assert res.total_images_per_sec > 0
    assert res.total_workers == 8
    assert res.global_batch == 16
    assert np.isfinite(res.final_loss)


def test_driver_host_fabric(mesh8):
    cfg = tiny_cfg(model="trivial", num_classes=100, num_batches=2)
    out = []
    res = driver.run_benchmark(cfg, fabric_name="sock", print_fn=out.append)
    assert res.fabric == "host"
    assert res.total_images_per_sec > 0


def test_driver_real_tfrecord_data(mesh8, tmp_path):
    """End-to-end with the real-data path: TFRecord shards -> train loop."""
    from tpu_hc_bench.data import imagenet

    imagenet.make_synthetic_shards(
        tmp_path, num_shards=2, examples_per_shard=16, image_size=32,
        num_classes=100,
    )
    cfg = tiny_cfg(
        model="trivial", num_classes=100, data_dir=str(tmp_path),
        num_warmup_batches=1, num_batches=2,
    )
    out = []
    res = driver.run_benchmark(cfg, print_fn=out.append)
    assert res.total_images_per_sec > 0
    assert np.isfinite(res.final_loss)
    assert any("real" in l or str(tmp_path) in l for l in out)


def test_driver_repeat_cached_sample(mesh8, tmp_path):
    """--datasets_repeat_cached_sample: real batches decoded once, cycled.

    The tf_cnn_benchmarks flag for isolating the device-side real-data
    step cost from the host decode/transfer wall.  With only 8 examples
    in the dataset the uncached path would exhaust the (repeating)
    stream anyway; the point here is the banner line and that more
    timed batches than decoded batches still run (proof of cycling).
    """
    from tpu_hc_bench.data import imagenet

    imagenet.make_synthetic_shards(
        tmp_path, num_shards=1, examples_per_shard=8, image_size=32,
        num_classes=100,
    )
    cfg = tiny_cfg(
        model="trivial", num_classes=100, data_dir=str(tmp_path),
        datasets_repeat_cached_sample=True,
        num_warmup_batches=1, num_batches=12,
    )
    out = []
    res = driver.run_benchmark(cfg, print_fn=out.append)
    assert res.total_images_per_sec > 0
    assert np.isfinite(res.final_loss)
    text = "\n".join(out)
    # the driver's own line, not the config banner (which prints whenever
    # the flag is set) — this is what proves the cached path actually ran
    assert "decoded once, device-resident" in text


def test_driver_repeat_cached_sample_needs_real_images(mesh8):
    """The flag without a real image dataset is a loud error, not a
    banner silently claiming an isolation that never ran."""
    import pytest

    cfg = tiny_cfg(model="trivial", num_classes=10,
                   datasets_repeat_cached_sample=True, num_batches=2)
    with pytest.raises(ValueError, match="real image dataset"):
        driver.run_benchmark(cfg, print_fn=lambda *_: None)


def test_driver_repeat_cached_sample_rejects_epoch_and_eval(mesh8, tmp_path):
    """Cycling 8 batches can define neither an epoch nor a split-wide
    eval metric — both combos are loud errors, not lying banners."""
    import pytest

    from tpu_hc_bench.data import imagenet

    imagenet.make_synthetic_shards(
        tmp_path, num_shards=1, examples_per_shard=8, image_size=32,
        num_classes=100,
    )
    for combo in ({"num_epochs": 1.0, "num_batches": None},
                  {"eval": True, "num_batches": 2}):
        cfg = tiny_cfg(model="trivial", num_classes=100,
                       data_dir=str(tmp_path),
                       datasets_repeat_cached_sample=True, **combo)
        with pytest.raises(ValueError, match="throughput-isolation"):
            driver.run_benchmark(cfg, print_fn=lambda *_: None)


def test_driver_eval_mode(mesh8):
    """--eval: forward-only protocol reporting top-1 accuracy."""
    cfg = tiny_cfg(model="trivial", num_classes=10, eval=True, num_batches=3)
    out = []
    res = driver.run_benchmark(cfg, print_fn=out.append)
    text = "\n".join(out)
    assert "eval top_1 accuracy:" in text
    assert res.total_images_per_sec > 0


def test_driver_expert_parallel(mesh8):
    """--expert_parallel end-to-end through run_benchmark (DP x EP)."""
    cfg = tiny_cfg(model="moe_tiny", expert_parallel=2, batch_size=2,
                   num_batches=2)
    out = []
    res = driver.run_benchmark(cfg, print_fn=out.append)
    assert "expert_parallel=2" in "\n".join(out)
    assert res.total_images_per_sec > 0
    assert np.isfinite(res.final_loss)


def test_driver_pipeline_parallel(mesh8):
    """--pipeline_parallel end-to-end through run_benchmark (DP x PP)."""
    cfg = tiny_cfg(model="moe_tiny", pipeline_parallel=4, batch_size=4,
                   num_batches=2)
    out = []
    res = driver.run_benchmark(cfg, print_fn=out.append)
    assert "pipeline: 4 stages" in "\n".join(out)
    assert res.total_images_per_sec > 0
    assert np.isfinite(res.final_loss)


def test_driver_sequence_parallel(mesh8):
    """--sequence_parallel end-to-end through run_benchmark (DP x SP)."""
    cfg = tiny_cfg(model="bert_tiny", sequence_parallel=2, batch_size=2,
                   num_batches=2)
    out = []
    res = driver.run_benchmark(cfg, print_fn=out.append)
    text = "\n".join(out)
    assert "sequence parallel: 2 shards" in text
    assert "dense->ring" in text
    assert res.total_images_per_sec > 0
    assert np.isfinite(res.final_loss)


def test_sp_flag_translation_and_guards():
    cfg = flags.BenchmarkConfig(sequence_parallel=2,
                                attention_impl="flash").resolve()
    assert cfg.attention_impl == "ulysses_flash"
    # round 3: a seq-sharded impl at sequence_parallel=1 is the DEGENERATE
    # SP mode (size-1 seq axis), allowed for plain DP and recorded in the
    # translation audit trail
    cfg = flags.BenchmarkConfig(attention_impl="ring").resolve()
    assert any("degenerate seq axis" in l for l in cfg.summary_lines())
    with pytest.raises(ValueError, match="not a supported composition"):
        flags.BenchmarkConfig(sequence_parallel=2,
                              pipeline_parallel=2).resolve()


def test_num_epochs_duration(mesh8, tmp_path):
    """tf_cnn's --num_epochs: duration derived from the ACTUAL dataset's
    example count and the resolved global batch (2x16=32 examples / gb 16
    -> 2 timed steps per epoch, x1.5 epochs -> 3)."""
    from tpu_hc_bench.data import imagenet

    imagenet.make_synthetic_shards(
        tmp_path, num_shards=2, examples_per_shard=16, image_size=32,
        num_classes=10,
    )
    cfg = flags.BenchmarkConfig(
        batch_size=2, num_warmup_batches=1, display_every=2,
        model="trivial", num_classes=10, num_epochs=1.5,
        data_dir=str(tmp_path),
    ).resolve()
    out = []
    driver.run_benchmark(cfg, print_fn=out.append)
    text = "\n".join(out)
    assert "(32 examples) -> num_batches=3" in text
    assert cfg.num_epochs == 0.0          # cleared: cfg re-resolvable
    cfg.resolve()                          # does not raise

    # synthetic/text streams have no epoch size: reject, don't assume
    cfg2 = flags.BenchmarkConfig(
        batch_size=2, model="trivial", num_classes=10, num_epochs=1.0,
    ).resolve()
    with pytest.raises(ValueError, match="real image dataset"):
        driver.run_benchmark(cfg2, print_fn=lambda _: None)

    # an EXPLICIT --num_batches conflicts even at the default value
    with pytest.raises(ValueError, match="cannot both be set"):
        flags.BenchmarkConfig(num_batches=100, num_epochs=1.0).resolve()


def test_log_name_convention():
    # reference: tfmn-<n>n-<b>b-<data>-<fabric>-r<run>.log (:9-12)
    assert driver.log_name(4, 64, "synthetic", "ici", 1) == \
        "tpubench-4n-64b-synthetic-ici-r1.log"


def test_launcher_positional_parse():
    from tpu_hc_bench import launcher

    pos, rest = launcher.parse_positionals(
        ["4", "1", "64", "ib", "--model", "resnet50"]
    )
    assert pos == ["4", "1", "64", "ib"]
    assert rest == ["--model", "resnet50"]
    pos, rest = launcher.parse_positionals(["--model", "vgg16"])
    assert pos == [] and rest == ["--model", "vgg16"]
