"""Budgeted autotuner (tpu_hc_bench/tune/, round 14).

Default lane is pure host-side work — space enumeration, the static
pruner, successive halving over a STUBBED runner with a deterministic
synthetic throughput surface, journal resume, registry round-trip, and
``--config=auto`` resolution.  No subprocess training runs (tier-1 sits
~805s of the 870s budget); the one real end-to-end micro-search on
``trivial`` plus its follow-up ``--config=auto`` bench run is
slow-marked.

The load-bearing pins:
- a stub-surface search recovers the known-best (seeded) config for two
  members whose surfaces peak there — the closed-loop claim;
- the pruner's three skip classes (flag-invalid / lint / hbm-oom) each
  reject without a run and land in the journal;
- a killed search resumed with the same --out never re-measures a
  journaled (candidate, rung) pair;
- ``--config=auto`` applies a tuned row to default fields only, falls
  back LOUDLY when no row exists, and survives a stale row;
- the tuned-config-staleness lint flags rows spelling dead flag names.
"""

from __future__ import annotations

import dataclasses
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from tpu_hc_bench import flags
from tpu_hc_bench.analysis import lints
from tpu_hc_bench.tune import prune, registry, runner, search, space

HW = "cpu-test-w1"


def make_stub(peak_overrides: dict, calls: list | None = None,
              wall_s: float = 1.0):
    """A deterministic synthetic throughput surface: score decays with
    distance from ``peak_overrides`` in (log2 batch, log2 accum, dtype,
    toggles) space, so the seeded config is the argmax iff the peak sits
    there.  Longer rungs keep the ordering (rung-invariant surface)."""

    def stub(c: space.Candidate, rung: int, batches: int) -> dict:
        if calls is not None:
            calls.append((c.key, rung))
        d = dict(c.overrides)
        peak = dict(peak_overrides)
        dist = 0.0
        b = d.get("batch_size", 64)
        pb = peak.get("batch_size", 64)
        dist += abs(np.log2(b) - np.log2(pb))
        a = d.get("gradient_accumulation_steps", 1)
        pa = peak.get("gradient_accumulation_steps", 1)
        dist += abs(np.log2(a) - np.log2(pa))
        for k in ("accum_dtype", "gradient_checkpointing", "scan_layers",
                  "fusion_threshold_bytes", "variable_update"):
            if d.get(k) != peak.get(k):
                dist += 1.0
        return {"per_chip": round(1000.0 * 0.8 ** dist, 3),
                "goodput": 0.9, "wall_s": wall_s}

    return stub


# --------------------------------------------------------------- space


def test_member_space_seed_first_and_valid():
    sp = space.member_space("trivial")
    assert sp[0] == space.seed_candidate("trivial")
    keys = [c.key for c in sp]
    assert len(keys) == len(set(keys)), "duplicate candidates"
    for c in sp:
        d = dict(c.overrides)
        b = d.get("batch_size", 64)
        a = d.get("gradient_accumulation_steps", 1)
        assert b % a == 0 and b // a >= 1, c.key
        if d.get("accum_dtype", "f32") != "f32":
            assert a > 1, f"dtype lever without accum: {c.key}"


def test_member_space_covers_the_manual_levers():
    sp = space.member_space("trivial")
    keys = [c.key for c in sp]
    # batch ladder around the seeded 512
    for b in (128, 256, 1024, 2048):
        assert any(f"batch_size={b}" in k for k in keys)
    # accum ladder and the zero1 arm toggle
    assert any("gradient_accumulation_steps=8" in k for k in keys)
    assert any("variable_update=zero1" in k for k in keys)
    # the fusion-threshold axis
    assert any("fusion_threshold_bytes" in k for k in keys)


def test_member_space_decoder_levers():
    sp = space.member_space("gpt2_moe")
    seed = sp[0]
    assert dict(seed.base).get("attention_impl") == "flash"
    assert dict(seed.overrides)["accum_dtype"] == "bf16"
    keys = [c.key for c in sp]
    # decoders get the remat/scan toggles and the dtype flip back to f32
    # (the flip's key drops the default accum_dtype)
    assert any("scan_layers=True" in k for k in keys)
    assert any("gradient_checkpointing=True" in k for k in keys)
    assert "batch_size=512,gradient_accumulation_steps=64" in keys


def test_grid_mode_crosses_batch_accum_dtype():
    axes = space.member_space("gpt2_moe", mode="axes")
    grid = space.member_space("gpt2_moe", mode="grid")
    assert len(grid) > len(axes)
    # the grid contains a cross point no axis pass generates: off-seed
    # batch AND off-seed accum together
    assert any(
        dict(c.overrides).get("batch_size") == 256
        and dict(c.overrides).get("gradient_accumulation_steps") == 32
        for c in grid)


def test_seed_matrix_matches_the_zoo_table():
    m = dict(space.seed_matrix())
    assert len(m) == 36
    assert m["trivial"] == 512 and m["ncf"] == 1048576
    # the old EXTRA_FLAGS knowledge, now derived from SEED_CONFIGS
    assert space.seed_extra_flags("trivial") == []
    assert space.seed_extra_flags("bert_large") == [
        "--gradient_accumulation_steps=32"]
    assert set(space.seed_extra_flags("gpt2_moe")) == {
        "--accum_dtype=bf16", "--attention_impl=flash",
        "--gradient_accumulation_steps=64"}


def test_candidate_rejects_non_lever_overrides():
    with pytest.raises(ValueError, match="not a tunable lever"):
        space.Candidate.make("trivial", {"learning_rate": 0.1})


# --------------------------------------------------------------- prune


def test_prune_hbm_model_rejects_known_oom():
    # trivial seed: batch 512, accum 1 -> microbatch anchor 512,
    # headroom 2 -> the batch-2048 one-shot candidate is a known OOM
    res = prune.static_prune(space.member_space("trivial"))
    oom = [s for s in res.skipped if s.cls == prune.HBM_OOM]
    assert any("batch_size=2048" == s.candidate.key for s in oom)
    assert all("batch_size=2048" != c.key for c in res.survivors)


def test_prune_bf16_seed_rejects_f32_accumulator():
    # gpt2_moe's seed NEEDED accum_dtype=bf16 at batch 512 (the f32
    # grad tree is what OOMed, BASELINE.md round 5) -> an f32-accum
    # candidate at that batch is a free skip
    hbm = prune.HbmModel.seeded("gpt2_moe")
    assert hbm.needs_bf16_accum_at == 512
    c = space.Candidate.make(
        "gpt2_moe",
        {"batch_size": 512, "gradient_accumulation_steps": 64},
        {"attention_impl": "flash"})
    assert hbm.check(c) is not None
    # the seeded bf16 point itself survives
    assert hbm.check(space.seed_candidate("gpt2_moe")) is None


def test_prune_flag_invalid_via_resolve():
    # accum_dtype without accumulation is a flag-time ValueError; the
    # space never generates it, but a hand-built candidate hits the
    # resolve() wall and classifies as flag-invalid
    bad = space.Candidate(
        "trivial", overrides=(("accum_dtype", "bf16"),))
    res = prune.static_prune([bad])
    assert not res.survivors
    assert res.skipped[0].cls == prune.FLAG_INVALID
    assert "accum_dtype" in res.skipped[0].reason


def test_prune_lint_class_skips_the_member():
    cands = space.member_space("trivial")
    res = prune.static_prune(
        cands, lint_fn=lambda m: ("host-sync-in-jit at foo.py:1",))
    assert not res.survivors
    assert {s.cls for s in res.skipped} == {prune.LINT}
    assert len(res.skipped) == len(cands)


# -------------------------------------------------------------- search


def test_search_recovers_seed_for_two_members(tmp_path):
    """The closed-loop claim: with a surface peaked at the seeded
    best-known config, the budgeted search returns exactly that config
    for two different members (acceptance criterion)."""
    for model in ("trivial", "gpt2_moe"):
        seed = space.seed_candidate(model)
        j = search.run_search(
            model, str(tmp_path / model), HW,
            settings=search.SearchSettings(budget_s=1e9),
            runner=make_stub(dict(seed.overrides)),
            print_fn=lambda m: None)
        assert j["status"] == "complete"
        assert j["best"]["key"] == seed.key, model


def test_search_halving_bookkeeping(tmp_path):
    calls: list = []
    j = search.run_search(
        "trivial", str(tmp_path), HW,
        settings=search.SearchSettings(budget_s=1e9, rung0_batches=4,
                                       growth=2, max_rungs=3),
        runner=make_stub({"batch_size": 512}, calls),
        print_fn=lambda m: None)
    rungs = j["rungs"]
    assert [r["batches"] for r in rungs] == [4, 8, 16][:len(rungs)]
    # each rung keeps ~half, never fewer than one
    for r in rungs:
        assert len(r["kept"]) == max(1, int(len(r["measured"]) * 0.5))
    # no (candidate, rung) pair measured twice
    assert len(calls) == len(set(calls))
    # journal measurements mirror the calls exactly
    journaled = {(k, int(rg)) for k, m in j["measurements"].items()
                 for rg in m}
    assert journaled == set(calls)
    # pruning is journaled alongside (hbm-oom from the seeded model)
    assert any(s["class"] == prune.HBM_OOM for s in j["skipped"])


def test_search_budget_exhaustion_and_resume(tmp_path):
    out = str(tmp_path)
    # each measurement bills 100s against a 250s budget -> exhausts
    # after 3 runs, mid-rung
    j = search.run_search(
        "trivial", out, HW,
        settings=search.SearchSettings(budget_s=250.0),
        runner=make_stub({"batch_size": 512}, wall_s=100.0),
        print_fn=lambda m: None)
    assert j["status"] == "budget-exhausted"
    assert j["spent_s"] == pytest.approx(300.0)
    done = {(k, int(r)) for k, m in j["measurements"].items() for r in m}
    assert len(done) == 3
    # resumed with a bigger budget: the journaled measurements are
    # never re-run
    calls: list = []
    j2 = search.run_search(
        "trivial", out, HW,
        settings=search.SearchSettings(budget_s=1e9),
        runner=make_stub({"batch_size": 512}, calls),
        print_fn=lambda m: None)
    assert j2["status"] == "complete"
    assert not (done & set(calls)), "re-measured a journaled pair"
    assert j2["best"]["key"] == "batch_size=512"


def test_search_resume_after_kill(tmp_path):
    """A search killed mid-run (journal committed after every
    measurement) resumes without repeating completed work."""
    out = str(tmp_path)
    base = make_stub({"batch_size": 512})
    n = 0

    def dying(c, rung, batches):
        nonlocal n
        n += 1
        if n > 4:
            raise KeyboardInterrupt("killed")
        return base(c, rung, batches)

    with pytest.raises(KeyboardInterrupt):
        search.run_search("trivial", out, HW,
                          settings=search.SearchSettings(budget_s=1e9),
                          runner=dying, print_fn=lambda m: None)
    j = search.load_journal(out)
    assert j is not None and j["status"] == "running"
    done = {(k, int(r)) for k, m in j["measurements"].items() for r in m}
    assert len(done) == 4
    calls: list = []
    j2 = search.run_search(
        "trivial", out, HW,
        settings=search.SearchSettings(budget_s=1e9),
        runner=make_stub({"batch_size": 512}, calls),
        print_fn=lambda m: None)
    assert j2["status"] == "complete"
    assert not (done & set(calls))


def test_search_rerun_of_finished_journal_is_a_noop(tmp_path):
    # a FINISHED search re-run with the same --out must not burn budget
    # on a fresh measurement past the halving's stopping point
    out = str(tmp_path)
    j = search.run_search("trivial", out, HW,
                          settings=search.SearchSettings(budget_s=1e9),
                          runner=make_stub({"batch_size": 512}),
                          print_fn=lambda m: None)
    assert j["status"] == "complete"
    calls: list = []
    j2 = search.run_search("trivial", out, HW,
                           settings=search.SearchSettings(budget_s=1e9),
                           runner=make_stub({"batch_size": 512}, calls),
                           print_fn=lambda m: None)
    assert not calls
    assert j2["status"] == "complete"
    assert j2["best"]["key"] == j["best"]["key"]


def test_search_best_prefers_the_deepest_rung(tmp_path):
    # a candidate eliminated at rung 0 with a noisy high score must not
    # beat the halving's steady-state winner; the promoted record's
    # measured_batches is the winner's OWN rung length
    cands = [space.Candidate.make("trivial", {"batch_size": b})
             for b in (128, 256, 512, 1024)]
    r0 = {"batch_size=128": 100.0, "batch_size=256": 99.0,
          "batch_size=512": 70.0, "batch_size=1024": 40.0}
    r1 = {"batch_size=128": 60.0, "batch_size=256": 59.0}

    def stub(c, rung, batches):
        return {"per_chip": (r0 if rung == 0 else r1)[c.key],
                "wall_s": 1.0}

    j = search.run_search(
        "trivial", str(tmp_path), HW,
        settings=search.SearchSettings(budget_s=1e9, rung0_batches=8,
                                       max_rungs=2),
        runner=stub, space=cands, print_fn=lambda m: None)
    # rung 0 cut batch 512 at score 70; the rung-1 winner scores 60 —
    # deepest-rung-first selection picks it anyway
    assert j["best"]["key"] == "batch_size=128"
    assert j["best"]["score"] == pytest.approx(60.0)
    assert j["best"]["record"]["measured_batches"] == 16


def test_search_journal_guards_model_and_hardware(tmp_path):
    out = str(tmp_path)
    search.run_search("trivial", out, HW,
                      settings=search.SearchSettings(budget_s=1e9),
                      runner=make_stub({"batch_size": 512}),
                      print_fn=lambda m: None)
    with pytest.raises(ValueError, match="is for model"):
        search.run_search("lenet", out, HW,
                          runner=make_stub({}), print_fn=lambda m: None)
    with pytest.raises(ValueError, match="per-hardware"):
        search.run_search("trivial", out, "v5e-16gb-w4",
                          runner=make_stub({}), print_fn=lambda m: None)


def test_search_max_candidates_truncation_is_journaled(tmp_path):
    j = search.run_search(
        "trivial", str(tmp_path), HW,
        settings=search.SearchSettings(budget_s=1e9, max_candidates=3),
        runner=make_stub({"batch_size": 512}),
        print_fn=lambda m: None)
    assert j["truncated"] > 0
    assert len(j["rungs"][0]["measured"]) == 3
    # the seed (enumerated first) survives truncation
    assert space.seed_candidate("trivial").key in j["rungs"][0]["measured"]


def test_search_all_failed(tmp_path):
    j = search.run_search(
        "trivial", str(tmp_path), HW,
        settings=search.SearchSettings(budget_s=1e9, max_candidates=2),
        runner=lambda c, r, b: {"error": "exit-1", "wall_s": 1.0},
        print_fn=lambda m: None)
    assert j["status"] == "all-failed"
    assert j["best"] is None


def test_commit_json_never_leaves_a_truncated_journal(tmp_path):
    path = str(tmp_path / "tune_state.json")
    search.commit_json(path, {"ok": 1})
    assert json.load(open(path)) == {"ok": 1}
    assert not os.path.exists(path + ".tmp")


# -------------------------------------------------------------- runner


def test_runner_stdout_parse_and_score():
    rec = runner.parse_stdout_metrics(
        "images/sec/chip: 2687.1  step: 47.6ms (p50 47.1ms)  MFU: 33.3%")
    assert rec["per_chip"] == pytest.approx(2687.1)
    assert rec["step_ms"] == pytest.approx(47.6)
    assert rec["mfu_pct"] == pytest.approx(33.3)
    # goodput-adjusted objective; NaN/absent goodput falls back to raw
    assert runner.score({"per_chip": 100.0, "goodput": 0.5}) == 50.0
    assert runner.score({"per_chip": 100.0}) == 100.0
    assert runner.score({"per_chip": 100.0, "error": "timeout"}) == 0.0
    # the launcher exit-code contract classes
    assert runner.EXIT_CLASSES[70] == "watchdog-timeout"
    assert runner.EXIT_CLASSES[75] == "preempted"


# ------------------------------------------------------------ registry


def _searched_journal(tmp_path, model="trivial"):
    seed = space.seed_candidate(model)
    return search.run_search(
        model, str(tmp_path / f"search-{model}"), HW,
        settings=search.SearchSettings(budget_s=1e9),
        runner=make_stub(dict(seed.overrides)), print_fn=lambda m: None)


def test_registry_round_trip(tmp_path, monkeypatch):
    j = _searched_journal(tmp_path)
    regdir = tmp_path / "reg"
    path, row = registry.promote(j, registry_dir=regdir)
    assert path == regdir / f"{HW}.json"
    assert registry.lookup("trivial", HW, regdir) == row
    assert row["overrides"] == {"batch_size": 512}
    assert row["search_status"] == "complete"
    # provenance: the winner's own deepest-rung length (default
    # settings: rung0 8 steps, growth 2 -> rung 2 measures 32)
    assert row["measured_batches"] == 32
    # promote merges: a second member lands in the same hardware file
    j2 = _searched_journal(tmp_path, "gpt2_moe")
    registry.promote(j2, registry_dir=regdir)
    rows = registry.load_rows(HW, regdir)
    assert set(rows) == {"trivial", "gpt2_moe"}


def test_promote_refuses_a_bestless_journal(tmp_path):
    with pytest.raises(ValueError, match="no successful measurement"):
        registry.promote({"model": "trivial", "hardware": HW,
                          "status": "all-failed", "best": None})


def test_config_auto_applies_tuned_row(tmp_path, monkeypatch):
    j = _searched_journal(tmp_path)
    regdir = tmp_path / "reg"
    registry.promote(j, registry_dir=regdir)
    monkeypatch.setenv(registry.REGISTRY_ENV, str(regdir))
    monkeypatch.setenv(registry.HW_ENV, HW)
    cfg = flags.BenchmarkConfig(model="trivial", config="auto").resolve()
    assert cfg.config_source == "auto"
    assert cfg.batch_size == 512
    assert cfg.tuned_config["hardware"] == HW
    assert "config" in cfg.translations


def test_config_auto_explicit_flag_wins(tmp_path, monkeypatch):
    j = _searched_journal(tmp_path)
    regdir = tmp_path / "reg"
    registry.promote(j, registry_dir=regdir)
    monkeypatch.setenv(registry.REGISTRY_ENV, str(regdir))
    monkeypatch.setenv(registry.HW_ENV, HW)
    cfg = flags.BenchmarkConfig(model="trivial", config="auto",
                                batch_size=64 * 3).resolve()
    assert cfg.config_source == "auto"
    assert cfg.batch_size == 64 * 3          # the operator's choice
    assert "explicit flag wins" in cfg.translations["config"]


def test_config_auto_explicit_default_value_pins(tmp_path, monkeypatch):
    # through parse_flags, a typed --batch_size=64 (the dataclass
    # default value) still pins against the tuned row — explicitness
    # is what the operator wrote, not a default-value compare
    j = _searched_journal(tmp_path)
    regdir = tmp_path / "reg"
    registry.promote(j, registry_dir=regdir)
    monkeypatch.setenv(registry.REGISTRY_ENV, str(regdir))
    monkeypatch.setenv(registry.HW_ENV, HW)
    cfg = flags.parse_flags(["--model=trivial", "--config=auto",
                             "--batch_size=64"])
    assert cfg.explicit_flags == ("batch_size", "config", "model")
    assert cfg.batch_size == 64
    assert "explicit flag wins" in cfg.translations["config"]
    # untyped fields still receive the row
    cfg = flags.parse_flags(["--model=trivial", "--config=auto"])
    assert cfg.batch_size == 512


def test_config_auto_falls_back_loudly_without_a_row(tmp_path,
                                                     monkeypatch):
    monkeypatch.setenv(registry.REGISTRY_ENV, str(tmp_path / "empty"))
    monkeypatch.setenv(registry.HW_ENV, HW)
    cfg = flags.BenchmarkConfig(model="trivial", config="auto").resolve()
    assert cfg.config_source == "baseline"
    assert cfg.tuned_config is None
    assert cfg.batch_size == 64              # untouched defaults
    note = cfg.translations["config"]
    assert "no tuned row" in note and "tune search" in note


def test_config_auto_survives_a_stale_row(tmp_path, monkeypatch):
    regdir = tmp_path / "reg"
    regdir.mkdir()
    (regdir / f"{HW}.json").write_text(json.dumps({
        "hardware": HW,
        "members": {"trivial": {"overrides": {"batch_size": 512,
                                              "dead_flag": 1},
                                "base": {}, "score": 1.0}}}))
    monkeypatch.setenv(registry.REGISTRY_ENV, str(regdir))
    monkeypatch.setenv(registry.HW_ENV, HW)
    cfg = flags.BenchmarkConfig(model="trivial", config="auto").resolve()
    assert cfg.config_source == "auto"
    assert cfg.batch_size == 512             # the live flag applied
    assert "dead_flag (unknown flag)" in cfg.translations["config"]


def test_config_manual_is_the_default_and_validated():
    cfg = flags.BenchmarkConfig(model="trivial").resolve()
    assert cfg.config_source == "manual" and cfg.tuned_config is None
    with pytest.raises(ValueError, match="manual|auto"):
        flags.BenchmarkConfig(model="trivial", config="bogus").resolve()


def test_hardware_key_env_pin(monkeypatch):
    monkeypatch.setenv(registry.HW_ENV, "v5e-16gb-w4")
    assert registry.hardware_key() == "v5e-16gb-w4"


# ----------------------------------------------------- staleness lint


def test_tuned_config_staleness_lint(tmp_path):
    regdir = tmp_path / "tuned"
    regdir.mkdir()
    (regdir / "cpu-w1.json").write_text(json.dumps({
        "hardware": "cpu-w1",
        "members": {
            "trivial": {"overrides": {"batch_size": 512}},
            "lenet": {"overrides": {"microbatch_ladder": 4},
                      "base": {"dead_base_flag": True}},
        }}))
    fs = lints.check_tuned_registry(regdir)
    assert {f.lint for f in fs} == {lints.TUNED_STALENESS}
    assert {f.model for f in fs} == {"lenet"}
    assert {f.location.split("/")[-1] for f in fs} == {
        "microbatch_ladder", "dead_base_flag"}
    assert all(f.severity == "warning" for f in fs)


def test_tuned_config_staleness_flags_unreadable_file(tmp_path):
    regdir = tmp_path / "tuned"
    regdir.mkdir()
    (regdir / "broken.json").write_text("{ not json")
    fs = lints.check_tuned_registry(regdir)
    assert len(fs) == 1 and "unreadable" in fs[0].message


def test_repo_registry_is_lint_clean():
    # the acceptance bar: whatever artifacts/tuned/ the repo ships lints
    # clean (missing dir included)
    assert lints.check_tuned_registry() == []


def test_sweep_from_registry_skips_stale_rows(tmp_path, monkeypatch,
                                              capsys):
    # one stale row must not block re-validating the other members
    # (and with only stale rows the sweep makes no subprocess runs)
    import importlib.util

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "sweep_zoo_under_test", os.path.join(root, "scripts",
                                             "sweep_zoo.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    regdir = tmp_path / "reg"
    regdir.mkdir()
    (regdir / "hw-x.json").write_text(json.dumps({
        "hardware": "hw-x",
        "members": {"trivial": {"overrides": {"dead_lever": 1}}}}))
    monkeypatch.setenv(registry.REGISTRY_ENV, str(regdir))
    out = tmp_path / "sweep.jsonl"
    monkeypatch.setattr(sys, "argv",
                        ["sweep_zoo.py", "--from_registry",
                         "--hardware", "hw-x", "--out", str(out)])
    mod.main()
    err = capsys.readouterr().err
    assert "skipping trivial" in err and "not a tunable lever" in err
    assert out.read_text() == ""


# ----------------------------------------------- sliced-batch satellite


def test_full_batch_identity_flag_parses():
    p = flags.build_parser()
    ns = p.parse_args(["--full_batch_identity=True", "--config=auto"])
    assert ns.full_batch_identity is True
    assert ns.config == "auto"
    ns = p.parse_args([])
    assert ns.full_batch_identity is False
    assert ns.config == "manual"


def test_shard_batch_local_identity_at_world_one(mesh8):
    # world=1: the local rows ARE the global batch, so the sliced path
    # must place bitwise-identical arrays to the device_put path
    from tpu_hc_bench._compat import CAPABILITIES
    from tpu_hc_bench.train import step as step_mod

    if not CAPABILITIES["process_local_arrays"]:
        pytest.skip("jax lacks make_array_from_process_local_data")
    mesh = mesh8
    rng = np.random.default_rng(0)
    batch = (rng.standard_normal((16, 4, 4, 3)).astype(np.float32),
             rng.integers(0, 10, size=(16,)).astype(np.int32))
    a = step_mod.shard_batch(batch, mesh)
    b = step_mod.shard_batch_local(batch, mesh)
    for x, y in zip(a, b):
        assert x.sharding.is_equivalent_to(y.sharding, x.ndim)
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# ------------------------------------------------------------ CLI + e2e


def test_cli_show_and_promote(tmp_path, monkeypatch, capsys):
    from tpu_hc_bench.tune.__main__ import main as tune_main

    j = _searched_journal(tmp_path)
    journal_path = tmp_path / "search-trivial" / "tune_state.json"
    regdir = tmp_path / "reg"
    rc = tune_main(["promote", "--journal", str(journal_path),
                    "--registry", str(regdir)])
    assert rc == 0
    out = capsys.readouterr().out
    assert "promoted: trivial" in out
    rc = tune_main(["show", "--hardware", HW,
                    "--registry", str(regdir)])
    assert rc == 0
    assert "batch_size=512" in capsys.readouterr().out
    # show on an empty registry: loud, nonzero
    rc = tune_main(["show", "--hardware", "no-such-hw",
                    "--registry", str(regdir)])
    assert rc == 1


@pytest.mark.slow
def test_real_micro_search_promote_and_config_auto(tmp_path):
    """The end-to-end acceptance loop, real subprocess runs: a budgeted
    micro-search on ``trivial`` completes within budget, journals >= 1
    pruner skip, emits a registry row, and a follow-up BENCH_CONFIG=auto
    bench run resolves it (config_source=auto in the BENCH json)."""
    from tpu_hc_bench.tune import prune as prune_mod

    out = str(tmp_path / "search")
    regdir = tmp_path / "reg"
    env_hw = "cpu-micro-w1"
    os.environ[registry.HW_ENV] = env_hw          # subprocesses inherit
    os.environ[registry.REGISTRY_ENV] = str(regdir)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    try:
        j = search.run_search(
            "trivial", out, env_hw,
            settings=search.SearchSettings(
                budget_s=600.0, rung0_batches=2, warmup=1, max_rungs=2,
                timeout_s=240.0, max_candidates=2),
            lint_fn=prune_mod.baseline_lint_classes)
        assert j["status"] in ("complete", "budget-exhausted")
        assert j["best"] is not None
        assert j["spent_s"] <= j["budget_s"]
        # static pruning was load-bearing: the hbm-oom class skipped
        # without a run (trivial's batch-2048 one-shot candidate)
        assert any(s["class"] == prune_mod.HBM_OOM for s in j["skipped"])
        path, row = registry.promote(j, registry_dir=regdir)
        assert path.exists()

        bench_env = dict(os.environ)
        bench_env.update(BENCH_FORCE_CPU="1", BENCH_MODEL="trivial",
                         BENCH_WARMUP="1", BENCH_BATCHES="2",
                         BENCH_CONFIG="auto")
        bench_env.pop("BENCH_BATCH_SIZE", None)
        proc = subprocess.run(
            [sys.executable, "bench.py"], capture_output=True, text=True,
            timeout=600,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            env=bench_env)
        assert proc.returncode == 0, proc.stderr[-2000:]
        rec = json.loads(proc.stdout.strip().splitlines()[-1])
        assert rec["extra"]["config_source"] == "auto"
        assert rec["extra"]["tuned_config"]["overrides"] == \
            row["overrides"]
    finally:
        os.environ.pop(registry.HW_ENV, None)
        os.environ.pop(registry.REGISTRY_ENV, None)


@pytest.mark.slow
def test_sweep_zoo_from_registry_smoke(tmp_path):
    """--from_registry sweeps the tuned rows (subprocess, one member)."""
    regdir = tmp_path / "reg"
    regdir.mkdir()
    (regdir / "cpu-sweep-w1.json").write_text(json.dumps({
        "hardware": "cpu-sweep-w1",
        "members": {"trivial": {"overrides": {"batch_size": 64},
                                "base": {}, "score": 1.0}}}))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env[registry.REGISTRY_ENV] = str(regdir)
    out = tmp_path / "sweep.jsonl"
    proc = subprocess.run(
        [sys.executable, "scripts/sweep_zoo.py", "--from_registry",
         "--hardware", "cpu-sweep-w1", "--out", str(out),
         "--warmup", "1", "--batches", "2"],
        capture_output=True, text=True, timeout=600,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    recs = [json.loads(line) for line in out.read_text().splitlines()]
    assert len(recs) == 1
    assert recs[0]["model"] == "trivial"
    assert recs[0]["config_source"] == "registry"
    assert recs[0].get("per_chip", 0) > 0, recs[0]
