"""Checkpoint, sanity-report, hostfile, and hw-table tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_hc_bench import flags
from tpu_hc_bench.data.synthetic import SyntheticImages
from tpu_hc_bench.models import TrivialModel
from tpu_hc_bench.parallel import distributed
from tpu_hc_bench.train import step as step_mod
from tpu_hc_bench.utils import checkpoint, hw, sanity


def make_state(lr=0.05):
    cfg = flags.BenchmarkConfig(
        batch_size=2, model="trivial", num_classes=10,
        init_learning_rate=lr,
    ).resolve()
    model = TrivialModel(num_classes=10)
    batch = SyntheticImages(8, (8, 8, 3), num_classes=10).batch()
    return step_mod.make_train_state(model, cfg, batch), batch


def test_checkpoint_roundtrip(tmp_path):
    state, _ = make_state()
    state = state.replace(step=jnp.asarray(7, jnp.int32))
    checkpoint.save(state, tmp_path)
    assert checkpoint.latest_step(tmp_path) == 7

    fresh, _ = make_state()
    restored = checkpoint.restore(fresh, tmp_path)
    assert int(restored.step) == 7
    for a, b in zip(jax.tree.leaves(state.params),
                    jax.tree.leaves(restored.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_checkpoint_multiple_steps(tmp_path):
    state, _ = make_state()
    for s in (1, 5, 3):
        checkpoint.save(state.replace(step=jnp.asarray(s, jnp.int32)), tmp_path)
    assert checkpoint.latest_step(tmp_path) == 5
    restored = checkpoint.restore(make_state()[0], tmp_path, step=3)
    assert int(restored.step) == 3


def test_restore_missing_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        checkpoint.restore(make_state()[0], tmp_path / "nope")


def test_sanity_report_passes_on_cpu_mesh(devices):
    lines, failures = sanity.collect_report()
    assert failures == [], failures
    text = "\n".join(lines)
    assert "jax:" in text and "matmul smoke test: ok" in text
    assert "psum smoke test: ok over 8 device(s)" in text


def test_hostfile_parsing(tmp_path):
    p = tmp_path / "nodeips.txt"
    p.write_text("# head node first\n10.0.0.1\n10.0.0.2\n\n10.0.0.3\n")
    hosts = distributed.read_hostfile(p)
    assert hosts == ["10.0.0.1", "10.0.0.2", "10.0.0.3"]
    (tmp_path / "empty.txt").write_text("\n# nothing\n")
    with pytest.raises(ValueError):
        distributed.read_hostfile(tmp_path / "empty.txt")


def test_peak_flops_table():
    # CPU test devices fall into the nominal row
    assert hw.peak_flops(dtype="bfloat16") > 0
    assert hw.peak_flops(dtype="float32") > 0


def test_ici_topology_lines():
    # CPU mesh: no coords -> graceful virtual-mesh line
    lines = hw.ici_topology_lines()
    assert lines and lines[0].startswith("ici:")
    assert "virtual/CPU mesh" in lines[0]

    # TPU-shaped fakes: coords -> slice shape + per-host chip map
    class FakeDev:
        def __init__(self, i, coords):
            self.id = i
            self.coords = coords
            self.process_index = 0
            self.core_on_chip = 0
            self.device_kind = "TPU v5 lite"

    devs = [FakeDev(i, (i % 2, i // 2, 0)) for i in range(4)]
    lines = hw.ici_topology_lines(devs)
    assert "slice_shape=2x2x1" in lines[0]
    assert "chips=4" in lines[0]
    assert "d0@0,0,0" in lines[1]
