"""ZeRO-1 arm (--variable_update=zero1) + --overlap_grad_comm.

Budget-conscious layout (tier-1 sits near the 870s ceiling): ONE
module-scoped fixture runs the psum and zero1 steps side by side and
every equivalence/memory assertion reads from it; the driver e2e is a
single kill/resume pair on the trivial member, which doubles as the
sharded-opt-state checkpoint proof.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_hc_bench import flags, resilience
from tpu_hc_bench.data.synthetic import SyntheticImages
from tpu_hc_bench.models import ModelSpec, TrivialModel
from tpu_hc_bench.train import driver, step as step_mod
from tpu_hc_bench.utils import checkpoint as ckpt


def tiny_cfg(**kw):
    base = dict(
        batch_size=2, num_warmup_batches=1, num_batches=4, display_every=2,
        model="trivial", num_classes=10, init_learning_rate=0.05,
    )
    base.update(kw)
    return flags.BenchmarkConfig(**base).resolve()


@pytest.fixture(scope="module")
def arm_states(mesh8):
    """psum and zero1 arms advanced 3 steps from identical init, with a
    small threshold so the gradient tree spans several buckets."""
    shape = (8, 8, 3)
    spec = ModelSpec("trivial", TrivialModel, shape, 1e6)
    model = TrivialModel(num_classes=10)
    batch = SyntheticImages(16, shape, num_classes=10).batch()
    dev_batch = step_mod.shard_batch(batch, mesh8)
    cfg_p = tiny_cfg(variable_update="psum", fusion_threshold_bytes=256)
    cfg_z = tiny_cfg(variable_update="zero1", fusion_threshold_bytes=256)
    state_p = step_mod.replicate_state(
        step_mod.make_train_state(model, cfg_p, batch), mesh8)
    state_z = step_mod.place_zero1_state(
        step_mod.make_zero1_state(model, cfg_z, batch, 8), mesh8)
    param_bytes = sum(l.size * l.dtype.itemsize
                      for l in jax.tree.leaves(state_z.params))
    sp = step_mod.build_train_step(mesh8, cfg_p, spec)
    sz = step_mod.build_train_step(mesh8, cfg_z, spec)
    rng = jax.random.PRNGKey(0)
    losses_p, losses_z = [], []
    for _ in range(3):
        state_p, mp = sp(state_p, dev_batch, rng)
        state_z, mz = sz(state_z, dev_batch, rng)
        losses_p.append(float(mp["loss"]))
        losses_z.append(float(mz["loss"]))
    return {"model": model, "spec": spec, "batch": batch,
            "dev_batch": dev_batch, "mesh": mesh8,
            "state_p": state_p, "state_z": state_z,
            "losses_p": losses_p, "losses_z": losses_z,
            "param_bytes": param_bytes}


def test_zero1_matches_psum_bitwise(arm_states):
    """Acceptance: the zero1 arm proves numerical equivalence to psum —
    bitwise-identical f32 params after K steps (the scatter/shard-
    update/gather pipeline is elementwise-identical math; only the
    cross-device summation differs, and psum and psum_scatter reduce in
    the same order)."""
    assert arm_states["losses_p"] == arm_states["losses_z"]
    fp_p = ckpt.fingerprint(arm_states["state_p"].params)
    fp_z = ckpt.fingerprint(arm_states["state_z"].params)
    assert fp_p == fp_z


def test_zero1_opt_state_bytes_one_over_n(arm_states):
    """Acceptance: per-device optimizer-state bytes drop ~1/N, asserted
    by live-array inspection (each sharded leaf's per-device shard)."""
    state_z = arm_states["state_z"]
    local = 0
    sharded_leaves = 0
    for leaf in jax.tree.leaves(state_z.opt_state):
        if not isinstance(leaf, jax.Array) or leaf.ndim < 2:
            continue
        shard_shape = leaf.sharding.shard_shape(leaf.shape)
        assert shard_shape[0] == leaf.shape[0] // 8  # data-axis sharded
        local += int(np.prod(shard_shape)) * leaf.dtype.itemsize
        sharded_leaves += 1
    assert sharded_leaves > 0
    # momentum trace mirrors the param tree: per-device bytes within
    # padding slack of param_bytes / 8
    assert local <= arm_states["param_bytes"] / 8 * 1.1
    assert local >= arm_states["param_bytes"] / 8 * 0.9


def test_zero1_overlap_off_same_values(arm_states):
    """--overlap_grad_comm=off (full-tree barrier, forward-order
    buckets) changes only the schedule, never the update."""
    mesh8 = arm_states["mesh"]
    cfg = tiny_cfg(variable_update="zero1", fusion_threshold_bytes=256,
                   overlap_grad_comm="off")
    state = step_mod.place_zero1_state(
        step_mod.make_zero1_state(arm_states["model"], cfg,
                                  arm_states["batch"], 8), mesh8)
    step = step_mod.build_train_step(mesh8, cfg, arm_states["spec"])
    rng = jax.random.PRNGKey(0)
    for _ in range(3):
        state, _ = step(state, arm_states["dev_batch"], rng)
    assert ckpt.fingerprint(state.params) == ckpt.fingerprint(
        arm_states["state_z"].params)


def test_zero1_checkpoint_roundtrip(arm_states, tmp_path):
    """Gather-on-save + restore into a fresh zero1 template is bitwise
    (params AND the sharded optimizer state)."""
    state_z = arm_states["state_z"]
    path = ckpt.save(state_z, tmp_path)
    assert path.exists()
    fresh = step_mod.make_zero1_state(
        arm_states["model"],
        tiny_cfg(variable_update="zero1", fusion_threshold_bytes=256),
        arm_states["batch"], 8)
    restored = ckpt.restore(fresh, tmp_path)
    assert ckpt.fingerprint(restored.params) == ckpt.fingerprint(
        state_z.params)
    assert ckpt.fingerprint(restored.opt_state) == ckpt.fingerprint(
        state_z.opt_state)


def test_zero1_flag_rules():
    """Every unsupported composition dies at flag time."""
    with pytest.raises(ValueError, match="plain data parallelism"):
        tiny_cfg(variable_update="zero1", model_parallel=2)
    with pytest.raises(ValueError, match="plain data parallelism"):
        tiny_cfg(variable_update="zero1", expert_parallel=2)
    with pytest.raises(ValueError, match="pipeline"):
        tiny_cfg(variable_update="zero1", pipeline_parallel=2)
    with pytest.raises(ValueError, match="data-axis only"):
        tiny_cfg(variable_update="zero1", sequence_parallel=2)
    with pytest.raises(ValueError, match="data-axis only"):
        tiny_cfg(variable_update="zero1", attention_impl="ring")
    with pytest.raises(ValueError, match="forward-only"):
        tiny_cfg(variable_update="zero1", forward_only=True)
    with pytest.raises(ValueError, match="overlap_grad_comm"):
        tiny_cfg(overlap_grad_comm="maybe")
    # accum composes (the scan's mean grads feed the reduce-scatter)
    cfg = tiny_cfg(variable_update="zero1",
                   gradient_accumulation_steps=2)
    assert cfg.variable_update == "zero1"
    # the GSPMD arm records the flag as n/a instead of silently eating it
    cfg = tiny_cfg(variable_update="replicated", overlap_grad_comm="off")
    assert "overlap_grad_comm" in cfg.translations
    # banner carries the arm + overlap setting
    assert any("overlap_grad_comm=on" in ln
               for ln in tiny_cfg(variable_update="zero1").summary_lines())


def test_zero1_step_rejects_host_fabric(arm_states):
    from tpu_hc_bench.parallel import fabric as fabric_mod

    cfg = tiny_cfg(variable_update="zero1")
    with pytest.raises(ValueError, match="device fabric"):
        step_mod.build_train_step(arm_states["mesh"], cfg,
                                  arm_states["spec"],
                                  fabric_mod.Fabric.HOST)


def test_zero1_driver_kill_resume_fingerprint(mesh8, tmp_path):
    """Acceptance: the kill/resume fingerprint proof passes with the
    SHARDED optimizer state — emergency save at sigterm, resume
    restores bitwise-identical params, manifest notes gather-on-save."""
    import json
    import os

    ck = str(tmp_path / "ck")
    md = str(tmp_path / "m")
    base = dict(batch_size=2, num_warmup_batches=1, num_batches=4,
                display_every=2, model="trivial", num_classes=10,
                init_learning_rate=0.05, variable_update="zero1",
                train_dir=ck, metrics_dir=md)
    out: list[str] = []
    with pytest.raises(resilience.PreemptedError):
        driver.run_benchmark(
            flags.BenchmarkConfig(**base, inject_fault="sigterm@2"
                                  ).resolve(),
            print_fn=out.append)
    fp_save = [l for l in out if "params fingerprint" in l]
    assert fp_save, out
    out2: list[str] = []
    res = driver.run_benchmark(
        flags.BenchmarkConfig(**base, resume="must").resolve(),
        print_fn=out2.append)
    fp_restore = [l for l in out2 if "params fingerprint" in l]
    assert fp_restore and fp_restore[0] == fp_save[-1]
    assert any("restored checkpoint" in l for l in out2)
    assert any("zero1: optimizer state sharded 8-way" in l for l in out2)
    assert np.isfinite(res.final_loss)
    manifest = json.load(open(os.path.join(md, "manifest.json")))
    assert manifest["zero1"] == {"opt_state_sharded": True,
                                 "opt_shards": 8,
                                 "checkpoint": "gather-on-save"}
    assert manifest["config"]["overlap_grad_comm"] == "on"
