"""Decode kernels & quantized serving arms (round 18).

Layers under test, cheapest first:

- **ops**: ``paged_decode_attention`` (Pallas flash-decode through the
  page tables, interpret mode on the CPU mesh) pinned against the
  dense-gather ``_softmax_attend`` reference — f32 exact-ish, GQA,
  multi-page blocks, int8-with-scales, the lse fresh-token merge;
  ``fused_residual_norm`` pinned against the Flax modules it replaces.
- **programs**: ``serve.decode``'s prefill/decode builders on
  hand-built two-layer GPT and Llama minis — the paged program's
  logits match the gather reference to f32 tolerance, the int8 arms
  to stated bounds (the zero1-fingerprint style of proof).
- **engine**: ONE session-scoped warmed paged engine on ``moe_tiny``
  (the test_serve discipline: every closed loop in virtual time, no
  driver runs) — token-for-token greedy parity against the model's
  own full-context forward, zero lowering after warmup — plus one
  int8_kv engine for the quantized closed loop.
- **flags / tune space / staleness / dequantize-in-hot-loop lint /
  tune-show journal rendering**: the wiring around the kernels.

Anything paying its own fresh engine on a bigger family (llama parity,
the bench_serve decode-A/B subprocess) is slow-marked.
"""

from __future__ import annotations

import functools
import io
import json
import os
import subprocess
import sys
from contextlib import redirect_stdout

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_hc_bench import flags
from tpu_hc_bench.analysis import lints
from tpu_hc_bench.models import gpt as gpt_mod
from tpu_hc_bench.models import llama as llama_mod
from tpu_hc_bench.ops.fused_residual_ln import fused_residual_norm
from tpu_hc_bench.ops.paged_attention import paged_decode_attention
from tpu_hc_bench.serve import arrivals
from tpu_hc_bench.serve import decode as decode_mod
from tpu_hc_bench.serve import engine as engine_mod
from tpu_hc_bench.serve import slo
from tpu_hc_bench.tune import prune, space

VCOSTS = {"prefill": 0.004, "decode": 0.003, "classify": 0.002}


def _quiet(_msg):
    pass


def _gather_reference(q, k_pages, v_pages, tables, lengths):
    """Dense-gather reference in serve.decode._softmax_attend's exact
    convention (page gather -> GQA repeat -> masked f32 softmax)."""
    b, heads, d = q.shape
    pages, ps, kvh, _ = k_pages.shape
    w = tables.shape[1]
    group = heads // kvh
    kc = k_pages[tables].reshape(b, w * ps, kvh, d)
    vc = v_pages[tables].reshape(b, w * ps, kvh, d)
    if group > 1:
        kc = np.repeat(kc, group, axis=2)
        vc = np.repeat(vc, group, axis=2)
    mask = np.arange(w * ps)[None, :] < lengths[:, None]
    out = decode_mod._softmax_attend(
        jnp.asarray(q)[:, None], jnp.asarray(kc), jnp.asarray(vc),
        jnp.asarray(mask))
    return np.asarray(out)[:, 0]


# --- ops: the paged flash-decode kernel -------------------------------


@pytest.mark.parametrize("b,heads,kvh,d,pages,ps,w,ppb", [
    (3, 4, 4, 16, 10, 4, 3, 1),      # MHA, one page per block
    (2, 8, 2, 32, 12, 8, 4, 2),      # GQA group 4, two pages per block
    (1, 2, 2, 8, 6, 4, 5, 4),        # width not divisible by the block
])
def test_paged_kernel_matches_gather_reference(b, heads, kvh, d, pages,
                                               ps, w, ppb):
    rng = np.random.default_rng(b * 100 + ppb)
    q = rng.standard_normal((b, heads, d)).astype(np.float32)
    kp = rng.standard_normal((pages, ps, kvh, d)).astype(np.float32)
    vp = rng.standard_normal((pages, ps, kvh, d)).astype(np.float32)
    tables = rng.integers(0, pages, (b, w)).astype(np.int32)
    lengths = rng.integers(1, w * ps + 1, (b,)).astype(np.int32)
    out = paged_decode_attention(
        jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
        jnp.asarray(tables), jnp.asarray(lengths), pages_per_block=ppb)
    want = _gather_reference(q, kp, vp, tables, lengths)
    np.testing.assert_allclose(np.asarray(out), want, atol=2e-5)


def test_paged_kernel_lse_merges_fresh_token():
    """softmax over [cache, fresh] == the kernel's output mixed with
    the fresh value through sigmoid(s_new - lse) — the identity the
    paged decode program's scatter-after-attend ordering rests on."""
    rng = np.random.default_rng(7)
    b, heads, d, pages, ps, w = 2, 4, 16, 8, 4, 3
    q = rng.standard_normal((b, heads, d)).astype(np.float32)
    kp = rng.standard_normal((pages, ps, heads, d)).astype(np.float32)
    vp = rng.standard_normal((pages, ps, heads, d)).astype(np.float32)
    tables = rng.integers(0, pages, (b, w)).astype(np.int32)
    lengths = rng.integers(1, w * ps, (b,)).astype(np.int32)
    kf = rng.standard_normal((b, heads, d)).astype(np.float32)
    vf = rng.standard_normal((b, heads, d)).astype(np.float32)

    out, lse = paged_decode_attention(
        jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
        jnp.asarray(tables), jnp.asarray(lengths), return_lse=True)
    s_new = np.einsum("bhd,bhd->bh", q, kf) / d ** 0.5
    w_new = np.asarray(jax.nn.sigmoid(jnp.asarray(
        s_new - np.asarray(lse))))
    got = (np.asarray(out) * (1 - w_new)[..., None]
           + vf * w_new[..., None])

    # reference: dense softmax over the cache rows PLUS the fresh token
    kc = kp[tables].reshape(b, w * ps, heads, d)
    vc = vp[tables].reshape(b, w * ps, heads, d)
    mask = np.arange(w * ps)[None, :] < lengths[:, None]
    s = np.einsum("bhd,bkhd->bhk", q, kc) / d ** 0.5
    s = np.where(mask[:, None, :], s, -1e30)
    s_full = np.concatenate([s, s_new[:, :, None]], axis=-1)
    p = np.asarray(jax.nn.softmax(jnp.asarray(s_full), axis=-1))
    v_full = np.concatenate([vc, vf[:, None]], axis=1)
    want = np.einsum("bhk,bkhd->bhd", p, v_full)
    np.testing.assert_allclose(got, want, atol=2e-5)


def test_paged_kernel_int8_within_tolerance():
    """int8 pages + per-page scales dequantized inside the kernel stay
    within quantization tolerance of the f32 reference (and are exact
    against the explicitly dequantized pool)."""
    rng = np.random.default_rng(3)
    L, pages, ps, kvh, d, b, heads, w = 3, 8, 4, 2, 16, 2, 4, 3
    kf = rng.standard_normal((L, pages, ps, kvh, d)).astype(np.float32)
    vf = rng.standard_normal((L, pages, ps, kvh, d)).astype(np.float32)
    ks = np.maximum(np.abs(kf).reshape(L, pages, -1).max(-1) / 127, 1e-8)
    vs = np.maximum(np.abs(vf).reshape(L, pages, -1).max(-1) / 127, 1e-8)
    kq = np.round(kf / ks[..., None, None, None]).astype(np.int8)
    vq = np.round(vf / vs[..., None, None, None]).astype(np.int8)
    q = rng.standard_normal((b, heads, d)).astype(np.float32)
    tables = rng.integers(0, pages, (b, w)).astype(np.int32)
    lengths = rng.integers(1, w * ps + 1, (b,)).astype(np.int32)
    out = paged_decode_attention(
        jnp.asarray(q), jnp.asarray(kq), jnp.asarray(vq),
        jnp.asarray(tables), jnp.asarray(lengths), layer=1,
        k_scales=jnp.asarray(ks.astype(np.float32)),
        v_scales=jnp.asarray(vs.astype(np.float32)),
        pages_per_block=2)
    # exact against the dequantized pool...
    kdq = kq[1].astype(np.float32) * ks[1][:, None, None, None]
    vdq = vq[1].astype(np.float32) * vs[1][:, None, None, None]
    np.testing.assert_allclose(
        np.asarray(out), _gather_reference(q, kdq, vdq, tables, lengths),
        atol=2e-5)
    # ...and within int8 tolerance of the unquantized truth
    want = _gather_reference(q, kf[1], vf[1], tables, lengths)
    assert np.abs(np.asarray(out) - want).max() < 0.1


def test_paged_kernel_validation_loud():
    z = jnp.zeros
    with pytest.raises(ValueError, match="kv_heads"):
        paged_decode_attention(
            z((1, 3, 8)), z((4, 4, 2, 8)), z((4, 4, 2, 8)),
            z((1, 2), jnp.int32), z((1,), jnp.int32))
    with pytest.raises(ValueError, match="scales"):
        paged_decode_attention(
            z((1, 2, 8)), z((4, 4, 2, 8), jnp.int8),
            z((4, 4, 2, 8), jnp.int8),
            z((1, 2), jnp.int32), z((1,), jnp.int32))


# --- ops: fused residual + norm ---------------------------------------


def test_fused_residual_layernorm_matches_flax():
    import flax.linen as nn

    rng = np.random.default_rng(1)
    res = rng.standard_normal((3, 5, 64)).astype(np.float32)
    x = rng.standard_normal((3, 5, 64)).astype(np.float32)
    gamma = rng.standard_normal(64).astype(np.float32)
    beta = rng.standard_normal(64).astype(np.float32)
    y, o = fused_residual_norm(
        jnp.asarray(res), jnp.asarray(x), jnp.asarray(gamma),
        jnp.asarray(beta))
    want_y = res + x
    want_o = nn.LayerNorm().apply(
        {"params": {"scale": gamma, "bias": beta}}, jnp.asarray(want_y))
    np.testing.assert_allclose(np.asarray(y), want_y, atol=1e-6)
    np.testing.assert_allclose(np.asarray(o), np.asarray(want_o),
                               atol=1e-5)


def test_fused_residual_rmsnorm_matches_llama():
    rng = np.random.default_rng(2)
    res = rng.standard_normal((4, 32)).astype(np.float32)
    x = rng.standard_normal((4, 32)).astype(np.float32)
    gamma = rng.standard_normal(32).astype(np.float32)
    y, o = fused_residual_norm(
        jnp.asarray(res), jnp.asarray(x), jnp.asarray(gamma),
        kind="rmsnorm")
    want_o = llama_mod.RMSNorm().apply(
        {"params": {"scale": gamma}}, jnp.asarray(res + x))
    np.testing.assert_allclose(np.asarray(o), np.asarray(want_o),
                               atol=1e-5)
    with pytest.raises(ValueError, match="beta"):
        fused_residual_norm(jnp.asarray(res), jnp.asarray(x),
                            jnp.asarray(gamma), kind="layernorm")
    with pytest.raises(ValueError, match="kind"):
        fused_residual_norm(jnp.asarray(res), jnp.asarray(x),
                            jnp.asarray(gamma), kind="batchnorm")


# --- programs: mini-family prefill/decode parity ----------------------


def _mini_model(kind: str):
    if kind == "gpt":
        # dense FFN: the GPTLM branch moe_tiny (MoE) never covers
        return gpt_mod.GPTLM(vocab_size=64, hidden=32, num_layers=2,
                             heads=2, ffn=64, max_len=32)
    return llama_mod.LlamaLM(vocab_size=64, hidden=32, num_layers=2,
                             heads=4, num_kv_heads=2, ffn=64, max_len=32)


@functools.lru_cache(maxsize=None)
def _decode_logits(kind: str, attention: str, quant: str,
                   block_pages: int = 0, steps: int = 2):
    """Prefill two prompts then run ``steps`` decode steps feeding a
    FIXED token stream (not argmax, so arms stay aligned bit-for-bit on
    inputs); returns the stacked per-step logits [steps, b, vocab].
    Cached: three tolerance tests per family share one gather/off
    reference run (tier-1 wall budget)."""
    model = _mini_model(kind)
    family = decode_mod.build_family(model, quant=quant)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32),
                        train=False)["params"]
    exec_params = (decode_mod.quantize_weights(family, params)
                   if quant == "int8_w" else params)
    page_size, w, b = 4, 4, 2
    kv = decode_mod.init_kv_state(family, 1 + b * w, page_size,
                                  jnp.float32, quant=quant)
    # jit: one compile per arm instead of an eager retrace per call
    # (the module's wall rides the tier-1 budget)
    prefill = jax.jit(decode_mod.build_prefill_fn(
        family, page_size, w, quant=quant))
    decode = jax.jit(decode_mod.build_decode_fn(
        family, page_size, w, attention=attention, quant=quant,
        block_pages=block_pages))
    rng = np.random.default_rng(11)
    prompts = [rng.integers(1, 64, n).astype(np.int32) for n in (5, 3)]
    tables = np.arange(1, 1 + b * w, dtype=np.int32).reshape(b, w)
    lengths = np.zeros((b,), np.int32)
    last = np.zeros((b,), np.int32)
    for i, prompt in enumerate(prompts):
        toks = np.zeros((1, 8), np.int32)
        toks[0, :len(prompt)] = prompt
        tok, _, kv = prefill(exec_params, kv, toks,
                             np.int32(len(prompt)), tables[i])
        lengths[i] = len(prompt)
        last[i] = int(np.asarray(tok)[0])
    feed = rng.integers(1, 64, (steps, b)).astype(np.int32)
    out = []
    for t in range(steps):
        _, logits, kv = decode(exec_params, kv, feed[t], tables,
                               lengths, np.ones((b,), bool))
        out.append(np.asarray(logits))
        lengths += 1
    return np.stack(out)


# the llama mini rides the slow lane like test_serve's llama engine
# parity: the default lane keeps one family (dense-GPT — the branch
# moe_tiny's engine pin never covers) per the tier-1 wall budget, and
# the llama program twins run under --runslow
_FAMILY_KINDS = ["gpt", pytest.param("llama", marks=pytest.mark.slow)]


@pytest.mark.parametrize("kind", _FAMILY_KINDS)
def test_paged_program_matches_gather_program(kind):
    """The paged decode program (kernel attention + lse fresh-token
    merge + fused residual norms) reproduces the gather reference's
    logits to f32 tolerance, greedy argmax identical — for BOTH
    families, dense-GPT (layernorm) and Llama (rmsnorm/GQA/RoPE).
    (Multi-page blocks are pinned at the kernel level above; re-running
    the whole program per block size would re-buy the same coverage
    against the tier-1 wall budget.)"""
    ref = _decode_logits(kind, "gather", "off")
    got = _decode_logits(kind, "paged", "off")
    np.testing.assert_allclose(got, ref, atol=2e-4)
    assert (got.argmax(-1) == ref.argmax(-1)).all()


@pytest.mark.parametrize("kind", _FAMILY_KINDS)
def test_int8_kv_program_within_tolerance(kind):
    """int8 KV pool (per-page scales written at prefill/append,
    consumed inside the kernel): logits within the stated bound of the
    f32 reference — |diff| <= 5% of the reference's logit range."""
    ref = _decode_logits(kind, "gather", "off")
    got = _decode_logits(kind, "paged", "int8_kv")
    bound = 0.05 * (ref.max() - ref.min())
    assert np.abs(got - ref).max() <= bound, (
        np.abs(got - ref).max(), bound)


@pytest.mark.parametrize("kind", _FAMILY_KINDS)
def test_int8_w_program_within_tolerance(kind):
    """Per-channel int8 weights dequantized at the matmul: same 5%%-of-
    range bound.  The gather arm suffices — the scale-fused einsum
    path is attention-kernel-independent by construction."""
    ref = _decode_logits(kind, "gather", "off")
    got = _decode_logits(kind, "gather", "int8_w")
    bound = 0.05 * (ref.max() - ref.min())
    assert np.abs(got - ref).max() <= bound, (
        np.abs(got - ref).max(), bound)


def test_int8_append_ignores_recycled_page_garbage():
    """Regression: the allocator never scrubs freed pages, so a page
    recycled from a retired request still holds the previous occupant's
    int8 rows and scale.  The append's requantize amax must only see
    THIS request's own rows (positions <= the append offset) — stale
    rows would otherwise inflate the fresh token's quantization scale
    arbitrarily (reads stay masked; precision is what's at stake)."""
    L, pages, ps, kvh, d = 1, 3, 4, 1, 4
    pages_q = jnp.zeros((L, pages, ps, kvh, d), jnp.int8)
    # page 2: previous occupant left full-range int8 rows at a scale
    # 1000x the new request's values
    pages_q = pages_q.at[0, 2].set(127)
    scales = jnp.ones((L, pages), jnp.float32).at[0, 2].set(100.0)
    new = jnp.full((L, 1, kvh, d), 0.125, jnp.float32)  # tiny fresh K
    out_q, out_sc = decode_mod._append_quantized(
        pages_q, scales, jnp.array([2], jnp.int32),
        jnp.array([0], jnp.int32), new)
    # scale reflects ONLY the fresh row, not the 12700.0 stale garbage
    assert float(out_sc[0, 2]) == pytest.approx(0.125 / 127.0)
    got = np.asarray(out_q[0, 2, 0], np.float32) * float(out_sc[0, 2])
    np.testing.assert_allclose(got, 0.125, rtol=0.02)
    # stale rows were zeroed, not requantized garbage
    assert (np.asarray(out_q[0, 2, 1:]) == 0).all()


def test_regress_fingerprint_back_compat_with_pre_r18_history():
    """Regression: adding decode_attention/quant to the fingerprint
    must not orphan pre-round-18 serve history — records without the
    keys normalize to the arms those runs effectively ran (gather/off),
    so a fresh default-arm run still compares against them while a
    paged run gets its own bucket."""
    from tpu_hc_bench.obs import regress

    old = {"metric": "m", "unit": "u", "extra": {"arrival_rate": 16.0}}
    fresh = {"metric": "m", "unit": "u",
             "extra": {"arrival_rate": 16.0,
                       "decode_attention": "gather", "quant": "off"}}
    paged = {"metric": "m", "unit": "u",
             "extra": {"arrival_rate": 16.0,
                       "decode_attention": "paged", "quant": "off"}}
    assert regress.fingerprint(old) == regress.fingerprint(fresh)
    assert regress.fingerprint(paged) != regress.fingerprint(fresh)


def test_quantize_weights_structure_and_roundtrip():
    model = _mini_model("gpt")
    family = decode_mod.build_family(model, quant="int8_w")
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32),
                        train=False)["params"]
    qp = decode_mod.quantize_weights(family, params)
    leaf = qp["layer_0"]["MultiHeadAttention_0"]["qkv"]["kernel"]
    assert set(leaf) == {"q", "scale"} and leaf["q"].dtype == jnp.int8
    # per-output-channel scale: one per (3, heads, d) output element
    assert leaf["scale"].shape == leaf["q"].shape[1:]
    # dequantized weight within half-step of the original everywhere
    w = params["layer_0"]["MultiHeadAttention_0"]["qkv"]["kernel"]
    deq = leaf["q"].astype(jnp.float32) * leaf["scale"]
    step = np.asarray(leaf["scale"])[None]
    assert (np.abs(np.asarray(deq) - np.asarray(w))
            <= 0.5 * step + 1e-8).all()
    # untouched leaves are the SAME objects (shared, not copied)
    assert qp["wte"]["embedding"] is params["wte"]["embedding"]
    assert (qp["layer_0"]["ln1"]["scale"]
            is params["layer_0"]["ln1"]["scale"])


# --- engine: the warmed paged arms ------------------------------------


@pytest.fixture(scope="session")
def paged_cfg():
    return flags.BenchmarkConfig(
        model="moe_tiny", workload="serve",
        arrival_rate=50.0, num_requests=8,
        max_prompt_len=8, max_output_len=4,
        max_in_flight=2, kv_page_size=4, seed=0,
        decode_attention="paged").resolve()


@pytest.fixture(scope="session")
def paged_engine(paged_cfg):
    return engine_mod.ServeEngine(paged_cfg, print_fn=_quiet)


class _TokenTap:
    """Minimal writer capturing request records' generated tokens."""

    enabled = False

    def __init__(self):
        self.tokens = {}

    def event(self, kind, **kw):
        if kind == "request":
            self.tokens[kw["id"]] = kw["generated"]

    def close(self):
        pass


@pytest.fixture(scope="session")
def paged_run(paged_cfg, paged_engine):
    reqs = arrivals.build_requests(paged_cfg,
                                   paged_engine.spec.vocab_size)
    tap = _TokenTap()
    summary = paged_engine.run(reqs, batching="continuous", writer=tap,
                               clock=engine_mod.VirtualClock(VCOSTS))
    return {"summary": summary, "tokens": tap.tokens, "requests": reqs}


def test_paged_engine_completes_with_frozen_ladder(paged_engine,
                                                   paged_run):
    s = paged_run["summary"]
    assert s["completed"] == s["requests"] == 8
    assert s["decode_attention"] == "paged" and s["quant"] == "off"
    assert s["decode_block_pages"] == 1      # paged arm reports blocks
    assert s["post_warmup_compiles"] == 0
    assert s["decode_steps"] > 0
    before = (paged_engine.lower_count, set(paged_engine.compiled))
    paged_engine.run(paged_run["requests"], batching="continuous",
                     clock=engine_mod.VirtualClock(VCOSTS))
    assert (paged_engine.lower_count, set(paged_engine.compiled)) \
        == before


def test_paged_engine_matches_full_forward(paged_engine, paged_run):
    """Token-for-token greedy parity: the paged Pallas decode (kernel
    attention + int32 page-table reads + fused norms) reproduces the
    model's own full-context forward — the moe/gpt family's pin; the
    llama twin is slow-marked below."""
    from tpu_hc_bench.models import create_model

    ref_model, _ = create_model(
        "moe_tiny", dtype=jnp.float32, seq_len=paged_engine.max_ctx,
        moe_impl="ragged")
    fwd = jax.jit(lambda v, t: ref_model.apply(v, t, train=False))
    requests = {r.rid: r for r in paged_run["requests"]}
    checked = 0
    for rid, want in paged_run["tokens"].items():
        if checked >= 3:
            break
        seq = list(np.asarray(requests[rid].prompt))
        got = []
        for _ in range(len(want)):
            toks = np.zeros((1, paged_engine.max_ctx), np.int32)
            toks[0, :len(seq)] = seq
            logits = fwd(paged_engine.variables, jnp.asarray(toks))
            nxt = int(np.asarray(logits)[0, len(seq) - 1].argmax())
            got.append(nxt)
            seq.append(nxt)
        assert got == want, f"request {rid}: {got} != {want}"
        checked += 1
    assert checked == 3


@pytest.mark.slow
def test_int8_kv_engine_closed_loop(paged_cfg):
    """The quantized closed loop: int8 pool + per-page scales through
    prefill/append/kernel-read, every request completes and the ladder
    stays frozen.  Slow-marked: it pays a fresh engine warmup, and the
    int8_kv numerics are already pinned in the default lane at program
    level (prefill + append + kernel read, both families)."""
    cfg = flags.BenchmarkConfig(
        **{**paged_cfg.__dict__, "translations": {},
           "explicit_flags": None, "tuned_config": None,
           "quant": "int8_kv"})
    eng = engine_mod.ServeEngine(cfg, print_fn=_quiet)
    assert eng.compile_record["quant"] == "int8_kv"
    reqs = arrivals.build_requests(cfg, eng.spec.vocab_size)
    s = eng.run(reqs, batching="continuous",
                clock=engine_mod.VirtualClock(VCOSTS))
    assert s["completed"] == 8 and s["post_warmup_compiles"] == 0
    assert s["quant"] == "int8_kv"
    # int8 pool state: pages int8, scales per (layer, page)
    kp, vp, ks, vs = eng._kv
    assert kp.dtype == jnp.int8 and vp.dtype == jnp.int8
    assert ks.shape == (eng.family.num_layers, eng.num_pages)


def test_classify_member_rejects_decode_knobs():
    cfg = flags.BenchmarkConfig(
        model="trivial", workload="serve",
        decode_attention="paged").resolve()
    with pytest.raises(ValueError, match="classify"):
        engine_mod.ServeEngine(cfg, print_fn=_quiet)


# --- flags ------------------------------------------------------------


def test_decode_flag_validity_matrix():
    def cfg(**kw):
        return flags.BenchmarkConfig(model="moe_tiny",
                                     workload="serve", **kw)

    with pytest.raises(ValueError, match="decode_attention"):
        cfg(decode_attention="dense").resolve()
    with pytest.raises(ValueError, match="quant"):
        cfg(quant="fp8").resolve()
    with pytest.raises(ValueError, match="paged"):
        cfg(quant="int8_kv").resolve()                # gather + int8_kv
    with pytest.raises(ValueError, match="decode_block_pages"):
        cfg(decode_block_pages=2).resolve()           # gather + blocks
    with pytest.raises(ValueError, match="decode_block_pages"):
        cfg(decode_attention="paged", decode_block_pages=-1).resolve()
    ok = cfg(decode_attention="paged", quant="int8_kv",
             decode_block_pages=2).resolve()
    assert "decode_attention=paged" in " ".join(ok.summary_lines())


def test_decode_flags_rejected_in_train_lane():
    with pytest.raises(ValueError, match="serving-lane"):
        flags.parse_flags(["--model", "trivial", "--quant", "int8_w"])
    with pytest.raises(ValueError, match="serving-lane"):
        flags.BenchmarkConfig(model="trivial",
                              decode_attention="paged").resolve()


# --- tune space / registry staleness / journal rendering --------------


def test_serve_levers_grow_kernel_arms():
    for lever in ("decode_attention", "quant", "decode_block_pages"):
        assert lever in space.SERVE_LEVERS
    sp = space.serve_member_space("moe_tiny")
    keys = {c.key for c in sp}
    assert "decode_attention=paged,max_in_flight=8" in keys
    assert ("decode_attention=paged,max_in_flight=8,quant=int8_kv"
            in keys)
    assert ("decode_attention=paged,decode_block_pages=2,"
            "max_in_flight=8" in keys)
    assert "max_in_flight=8,quant=int8_w" in keys
    # every generated combination survives flag-time resolve (int8_kv
    # and block pages only ever ride the paged arm)
    res = prune.static_prune(sp)
    assert [s.journal_record() for s in res.skipped] == []
    # classify members get no decode-kernel levers
    assert not any("decode_attention" in c.key or "quant" in c.key
                   for c in space.serve_member_space("trivial"))


def test_staleness_lint_flags_lane_crossed_kernel_levers(tmp_path):
    (tmp_path / "hw.json").write_text(json.dumps({
        "hardware": "hw", "members": {
            # training row spelling a serve kernel lever: lane-crossed
            "trivial": {"overrides": {"decode_attention": "paged"}},
            # @serve row with the kernel levers: legitimate
            "moe_tiny@serve": {"overrides": {
                "decode_attention": "paged", "quant": "int8_kv",
                "decode_block_pages": 2}},
        }}))
    found = lints.check_tuned_registry(tmp_path)
    locs = {f.location.split(":", 1)[1] for f in found}
    assert "trivial/decode_attention" in locs
    assert not any(loc.startswith("moe_tiny@serve") for loc in locs)


def test_tune_show_renders_kernel_levers_in_journal_rows():
    from tpu_hc_bench.tune.__main__ import _render_journal

    journal = {
        "model": "moe_tiny", "hardware": "cpu-test-w1",
        "status": "FINISHED", "spent_s": 10.0, "budget_s": 60.0,
        "skipped": [],
        "measurements": {
            "decode_attention=paged,decode_block_pages=2": {
                "0": {"score": 123.4, "wall_s": 1.0}},
            "quant=int8_kv,decode_attention=paged": {
                "0": {"score": 150.0, "peak_hbm_bytes": 2 ** 20}},
        },
    }
    buf = io.StringIO()
    with redirect_stdout(buf):
        _render_journal(journal)
    text = buf.getvalue()
    assert "decode_attention=paged,decode_block_pages=2" in text
    assert "score 123.4" in text
    assert "quant=int8_kv" in text and "peak 1.0 MiB" in text


# --- the dequantize-in-hot-loop lint ----------------------------------


DEQUANT_BAD = """
def decode(k_pages_q, scales, tables, x):
    for l in range(4):
        kc = k_pages_q[l][tables].astype(jnp.float32) * scales[l]
        x = x @ kc
    return x
"""

DEQUANT_SCALE_FUSED = """
def decode(params, x):
    for l in range(4):
        w = params[l]
        x = jnp.einsum("bh,hf->bf", x,
                       w["q"].astype(jnp.float32)) * w["scale"]
    return x
"""

DEQUANT_SCAN_BAD = """
def step(carry, w_int8):
    y = carry @ (w_int8.astype(jnp.float32) * 0.5)
    return y, y

out = jax.lax.scan(step, x0, ws)
"""


def test_dequant_lint_flags_dense_dequant_in_loop():
    found = lints.lint_source_text(DEQUANT_BAD, filename="x.py")
    assert [f.lint for f in found] == [lints.DEQUANT_HOT]
    assert found[0].severity == "error"
    assert "scale-fused" in found[0].message


def test_dequant_lint_accepts_scale_fused_matmul():
    found = [f for f in lints.lint_source_text(
        DEQUANT_SCALE_FUSED, filename="x.py")
        if f.lint == lints.DEQUANT_HOT]
    assert found == []


def test_dequant_lint_covers_scan_bodies():
    found = [f for f in lints.lint_source_text(
        DEQUANT_SCAN_BAD, filename="x.py")
        if f.lint == lints.DEQUANT_HOT]
    assert len(found) == 1
    # the same expression OUTSIDE any loop body never flags
    free = DEQUANT_SCAN_BAD.replace("out = jax.lax.scan(step, x0, ws)",
                                    "")
    assert not [f for f in lints.lint_source_text(free, filename="x.py")
                if f.lint == lints.DEQUANT_HOT]


def test_dequant_lint_suppression_and_query_name_exempt():
    sup = DEQUANT_BAD.replace(
        "* scales[l]",
        "* scales[l]  # thb:lint-ok[dequantize-in-hot-loop]")
    assert not [f for f in lints.lint_source_text(sup, filename="x.py")
                if f.lint == lints.DEQUANT_HOT]
    # a bare `q` is the attention query convention, not a quantized
    # buffer — the paged decode program's own s_new math must not flag
    query = """
def f(q, kf):
    for l in range(2):
        s = q.astype(jnp.float32) * kf.astype(jnp.float32)
    return s
"""
    assert not [f for f in lints.lint_source_text(query,
                                                  filename="x.py")
                if f.lint == lints.DEQUANT_HOT]


def test_repo_sources_dequant_clean():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    found = []
    for sub in ("tpu_hc_bench/ops", "tpu_hc_bench/serve"):
        base = os.path.join(repo, sub)
        for name in sorted(os.listdir(base)):
            if name.endswith(".py"):
                found.extend(lints.lint_file(os.path.join(base, name)))
    found = [f for f in found if f.lint == lints.DEQUANT_HOT]
    assert found == [], [f.message for f in found]


# --- obs: serve diff / slo rendering ----------------------------------


def test_serve_diff_notes_kernel_arm_changes():
    a = {"p99_e2e_ms": 10.0, "decode_attention": "gather",
         "quant": "off", "aot_decode_temp_bytes": 800000}
    b = {"p99_e2e_ms": 9.0, "decode_attention": "paged",
         "quant": "int8_kv", "aot_decode_temp_bytes": 700000}
    text = "\n".join(slo.serve_diff_lines(a, b))
    assert "decode-attention arm differs: gather -> paged" in text
    assert "quant arm differs: off -> int8_kv" in text
    assert "aot dec temp B" in text


def test_slo_lines_render_decode_arm():
    fold = {"completed": 8, "requests": 8, "batching": "continuous",
            "arrival": "poisson", "arrival_rate": 8.0,
            "decode_attention": "paged", "quant": "int8_kv",
            "decode_block_pages": 2,
            "aot_decode_temp_bytes": 2 ** 20}
    text = "\n".join(slo.slo_lines(fold))
    assert "attention=paged quant=int8_kv block_pages=2" in text
    assert "AOT temp 1.0 MiB" in text


# --- slow lane --------------------------------------------------------


@pytest.mark.slow
def test_llama_paged_engine_matches_full_forward():
    """The llama twin of the default-lane moe parity pin: RoPE per-row
    positions, GQA through the kernel's grouped grid, SwiGLU, rmsnorm
    fusion — token-for-token against the full-context forward (pays
    its own engine warmup, hence slow)."""
    from tpu_hc_bench.models import create_model

    cfg = flags.BenchmarkConfig(
        model="llama_tiny", workload="serve", arrival_rate=50.0,
        num_requests=3, max_prompt_len=8, max_output_len=4,
        max_in_flight=2, kv_page_size=4, seed=0,
        decode_attention="paged").resolve()
    eng = engine_mod.ServeEngine(cfg, print_fn=_quiet)
    reqs = arrivals.build_requests(cfg, eng.spec.vocab_size)
    tap = _TokenTap()
    s = eng.run(reqs, batching="continuous", writer=tap,
                clock=engine_mod.VirtualClock(VCOSTS))
    assert s["completed"] == 3 and s["post_warmup_compiles"] == 0

    ref_model, _ = create_model(
        "llama_tiny", dtype=jnp.float32, seq_len=eng.max_ctx)
    requests = {r.rid: r for r in reqs}
    for rid, want in tap.tokens.items():
        seq = list(np.asarray(requests[rid].prompt))
        got = []
        for _ in range(len(want)):
            toks = np.zeros((1, eng.max_ctx), np.int32)
            toks[0, :len(seq)] = seq
            logits = ref_model.apply(
                eng.variables, jnp.asarray(toks), train=False)
            nxt = int(np.asarray(logits)[0, len(seq) - 1].argmax())
            got.append(nxt)
            seq.append(nxt)
        assert got == want, f"request {rid}: {got} != {want}"
    assert len(tap.tokens) == 3


@pytest.mark.slow
def test_bench_serve_decode_ab_harness(tmp_path):
    """The decode-kernel A/B subprocess e2e at a scale where the dense
    gather's temporaries dominate: paged temp bytes down, token
    parity, zero post-warmup compiles on every arm (the r18
    acceptance shape; the committed artifact is
    artifacts/bench_decode_ab_r18.json)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "scripts/bench_serve.py", "--mode", "decode",
         "--max_prompt_len", "64", "--max_output_len", "32",
         "--max_in_flight", "16", "--kv_page_size", "16",
         "--num_requests", "12", "--arrival_rate", "30",
         "--metrics_root", str(tmp_path / "ab")],
        capture_output=True, text=True, env=env, timeout=570,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    rec = json.loads(proc.stdout)
    v = rec["extra"]["verdict"]
    assert v["paged_temp_lt_gather"]
    assert v["paged_token_parity"]
    assert v["zero_post_warmup_compiles"] and v["all_completed"]
    assert rec["extra"]["arms"]["paged+int8_kv"]["aot_decode_args_bytes"] \
        < rec["extra"]["arms"]["gather+off"]["aot_decode_args_bytes"]
