"""tpu-hc-bench: a TPU-native distributed-training benchmark harness.

A brand-new framework with the capabilities of the reference repo
``md-k-sarker/azure-hc-intel-tf`` (an Azure HC-series InfiniBand cluster
bring-up + Intel-TF/Horovod CNN benchmark harness), re-designed TPU-first:

- Horovod/MPI allreduce over InfiniBand  ->  XLA collectives over the ICI mesh
  (``jax.lax.psum`` under ``jax.shard_map``/``jit``).
- lscpu socket/core layout math           ->  TPU device-topology mesh layout.
- tf_cnn_benchmarks flag surface + models ->  Flax model zoo driven by a
  compatible flag surface (``tpu_hc_bench.flags``).
- OSU MPI micro-benchmarks                ->  ICI collective latency/bandwidth
  sweeps (``tpu_hc_bench.microbench``).
- Singularity image + setenv registry     ->  TPU-VM setup scripts + generated
  env registry (``tpu_hc_bench.envfile``).

See SURVEY.md at the repo root for the full structural mapping with
file:line citations into the reference.
"""

from tpu_hc_bench import _compat  # noqa: F401  (installs JAX version shims)

__version__ = "0.1.0"
