from tpu_hc_bench.launcher import main

raise SystemExit(main())
