"""JAX cross-version compatibility shims.

The framework is written against the current jax API (``jax.shard_map``
with ``check_vma``, the ``jax_num_cpu_devices`` config option,
``pallas.tpu.CompilerParams``), but the pinned container stacks range
back to jax 0.4.x where those names either do not exist or are spelled
differently.  Importing :mod:`tpu_hc_bench` installs the shims below so
the SAME source runs on both ends of the pin range:

- ``jax.shard_map``: on old jax, wraps
  ``jax.experimental.shard_map.shard_map``, translating ``check_vma`` ->
  ``check_rep`` and the partial-manual ``axis_names=...`` selector into
  the old ``auto=<complement>`` spelling.
- ``jax.config.update("jax_num_cpu_devices", n)``: the option landed
  after 0.4.x; on stacks without it the call is rerouted to
  ``XLA_FLAGS=--xla_force_host_platform_device_count=n``, which must
  (same contract as the real option) be issued before backend init —
  after init it degrades to an assertion that the count already matches.
- ``jax.experimental.pallas.tpu.CompilerParams``: aliased to the old
  ``TPUCompilerParams`` dataclass when only that name exists.

Standalone scripts that configure device counts before importing the
package must ``import tpu_hc_bench`` (or this module) first so the
config reroute is installed.
"""

from __future__ import annotations

import os

import jax

__all__ = ["install", "CAPABILITIES"]

_INSTALLED = False

_JAX_VERSION = tuple(int(p) for p in jax.__version__.split(".")[:2])

#: Stack capabilities the shims can NOT paper over — true on the pinned
#: modern stack, false on the 0.4.x end of the container range.  The
#: test suite consumes these by name (skipif) so the version knowledge
#: lives here, next to the shims, instead of scattered per test file.
CAPABILITIES = {
    # cross-process collectives on the CPU backend: 0.4.x raises
    # "Multiprocess computations aren't implemented on the CPU backend"
    # inside the compiled program, so the true multi-process suite
    # cannot run CPU-only there
    "cpu_multiprocess_collectives": _JAX_VERSION >= (0, 5),
    # partial-manual shard_map (manual data/seq axes composed with an
    # auto/GSPMD model axis — the SP x TP / PP x TP hybrids and the SP
    # eval arm): the 0.4.x CPU SPMD partitioner rejects the lowered
    # program with "PartitionId instruction is not supported for SPMD
    # partitioning"
    "partial_auto_shard_map": _JAX_VERSION >= (0, 5),
    # GSPMD-partitioned numerics (expert-sharded MoE dispatch, Megatron
    # TP on bert/vit): on 0.4.x the partitioned forward computes a
    # ~0.7-0.9% different loss than the replicated arm from step 0, so
    # sharded-vs-replicated equivalence only holds to rtol ~1e-2 there,
    # not the 1e-4 the modern partitioner delivers
    "exact_gspmd_numerics": _JAX_VERSION >= (0, 5),
    # executing a persistent-cache-deserialized CPU executable on 0.4.x
    # jaxlib corrupts the heap (glibc "corrupted double-linked list"
    # abort) — tests/conftest.py gates the compile cache on this
    "persistent_compilation_cache": _JAX_VERSION >= (0, 5),
    # jax.make_array_from_process_local_data: each process contributes
    # ONLY its local batch rows and jax assembles the global array — the
    # driver's sliced input mode (round 14).  hasattr, not a version
    # compare: the API landed mid-0.4.x and a backport shim would be
    # worse than the full-batch fallback the driver keeps
    # (--full_batch_identity)
    "process_local_arrays": hasattr(jax, "make_array_from_process_local_data"),
}


def _backend_initialized() -> bool:
    try:
        from jax._src import xla_bridge as xb

        return bool(getattr(xb, "_backends", {}))
    except Exception:  # pragma: no cover - defensive
        return True


def _set_host_device_count(n: int) -> None:
    """``jax_num_cpu_devices`` fallback: the legacy XLA flag, pre-init."""
    if _backend_initialized():
        have = len(jax.devices())
        if have != n:
            raise RuntimeError(
                f"jax_num_cpu_devices={n} requested after backend init "
                f"with {have} devices; set it before first device use")
        return
    flags = os.environ.get("XLA_FLAGS", "")
    kept = [f for f in flags.split()
            if not f.startswith("--xla_force_host_platform_device_count")]
    kept.append(f"--xla_force_host_platform_device_count={n}")
    os.environ["XLA_FLAGS"] = " ".join(kept)


def _install_config_shim() -> None:
    try:
        jax.config.update("jax_num_cpu_devices",
                          jax.config.jax_num_cpu_devices)
        return  # native option exists
    except Exception:
        pass
    orig_update = jax.config.update

    def update(name: str, value):
        if name == "jax_num_cpu_devices":
            return _set_host_device_count(int(value))
        return orig_update(name, value)

    jax.config.update = update


def _install_shard_map_shim() -> None:
    if hasattr(jax, "shard_map"):
        return
    from jax.experimental.shard_map import shard_map as legacy_shard_map

    def shard_map(f, mesh, in_specs, out_specs, check_vma=None,
                  check_rep=None, axis_names=None):
        kwargs = {}
        if axis_names is not None:
            # new API: axis_names = the MANUAL axes; old API: auto = the
            # axes left automatic (GSPMD) — complement within the mesh
            kwargs["auto"] = (frozenset(mesh.axis_names)
                              - frozenset(axis_names))
        check = check_vma if check_vma is not None else check_rep
        return legacy_shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=True if check is None else bool(check), **kwargs)

    jax.shard_map = shard_map


def _install_lax_shims() -> None:
    from jax import lax

    if not hasattr(lax, "axis_size"):
        from jax import core

        def axis_size(axis_name):
            """Static size of (a tuple of) bound mesh axes — the old
            spelling is ``core.axis_frame(name)``, which returns the
            size directly on this stack."""
            if isinstance(axis_name, (tuple, list)):
                n = 1
                for a in axis_name:
                    n *= core.axis_frame(a)
                return n
            return core.axis_frame(axis_name)

        lax.axis_size = axis_size
    if not hasattr(lax, "pcast"):
        # varying-manual-axes casts don't exist before the vma type
        # system; without check_vma there is nothing to cast — identity
        def pcast(x, axis_name=None, *, to=None):
            del axis_name, to
            return x

        lax.pcast = pcast


def _install_pallas_shim() -> None:
    try:
        from jax.experimental.pallas import tpu as pltpu
    except Exception:  # pragma: no cover - pallas-free stacks
        return
    if not hasattr(pltpu, "CompilerParams") and hasattr(
            pltpu, "TPUCompilerParams"):
        pltpu.CompilerParams = pltpu.TPUCompilerParams


def install() -> None:
    """Install all shims (idempotent; called on package import)."""
    global _INSTALLED
    if _INSTALLED:
        return
    _INSTALLED = True
    _install_config_shim()
    _install_shard_map_shim()
    _install_lax_shims()
    _install_pallas_shim()


install()
