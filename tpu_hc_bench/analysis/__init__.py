"""Static analysis of compiled programs + lint passes over the zoo.

The reference harness validates its fabric *operationally* (OSU
microbenchmarks over InfiniBand, run-*.sh); the TPU-native counterpart
of that validation is *static*: inspect the compiled XLA program and the
traced jaxpr and assert structural properties — how many collectives
cross the mesh per step, whether a host sync hides inside a jitted
region, whether a sharding annotation is inconsistent across a pjit
boundary.  Before round 6 those checks lived in fragile per-experiment
regexes (ADVICE.md round 5 flagged three independent miscounting bugs);
this package is the one reusable home:

- :mod:`tpu_hc_bench.analysis.hlo` — a definition-site parser for HLO
  text.  Counts ops by parsing ``%name = <shape> opcode(...)`` definition
  lines only (operand references never match), folds ``-start``/``-done``
  async pairs into one op, and attributes fused computations through
  their HLO ``metadata op_name`` paths instead of event-name substrings.
- :mod:`tpu_hc_bench.analysis.lints` — jaxpr/AST lint passes runnable
  against every model in the zoo: host-sync-inside-jit, recompilation
  hazards, donated-buffer misuse, sharding-annotation consistency.
- :mod:`tpu_hc_bench.analysis.registry` — the pass registry: every
  check registers name/severity/scope/docs once; the run order, the
  ``_emit`` default severity, and the README lint table all derive
  from it.
- :mod:`tpu_hc_bench.analysis.dataflow` — distributed-correctness
  passes: an intraprocedural rank-taint engine flagging collectives
  under rank-divergent control flow, and dict/set-ordered
  collective-issuing loops.
- :mod:`tpu_hc_bench.analysis.contracts` — the stream-schema contract
  checker: keys the obs folds read vs keys the writers materialize,
  gated by a committed allowlist of documented seams.
- :mod:`tpu_hc_bench.analysis.report` — findings, JSON reports, and the
  checked-in baseline the CI gate (``tests/test_analysis.py`` +
  ``python -m tpu_hc_bench.analysis``) fails against on regression.

CLI::

    python -m tpu_hc_bench.analysis --model resnet50   # lints + HLO counts
    python -m tpu_hc_bench.analysis --all --json out.json
    python -m tpu_hc_bench.analysis --all --changed-only
    python -m tpu_hc_bench.analysis baseline            # dry-run diff
    python -m tpu_hc_bench.analysis baseline --update   # atomic rewrite
"""

from tpu_hc_bench.analysis.hlo import (  # noqa: F401
    COLLECTIVE_OPCODES,
    HloComputation,
    HloInstruction,
    HloModule,
    collective_counts,
    fusion_ops,
    parse_hlo,
)
from tpu_hc_bench.analysis.registry import (  # noqa: F401
    PassInfo,
    all_passes,
    pass_index,
    register_pass,
)
from tpu_hc_bench.analysis.report import (  # noqa: F401
    Finding,
    compare_to_baseline,
    load_baseline,
)
