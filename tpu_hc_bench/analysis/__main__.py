"""CLI: ``python -m tpu_hc_bench.analysis``.

Runs the lint passes (and, per model, the world=2 compiled-HLO
collective count) and compares the findings against the checked-in
baseline; exits non-zero on any finding the baseline does not accept —
the CI lint gate.

Examples::

    # one member: lints + definition-site collective counts
    JAX_PLATFORMS=cpu python -m tpu_hc_bench.analysis --model resnet50

    # the whole zoo's lints + the repo source passes, JSON to a file
    JAX_PLATFORMS=cpu python -m tpu_hc_bench.analysis --all --json out.json

    # per-file passes restricted to sources `git diff` names (repo-scope
    # passes still see the whole tree) — the cheap pre-push loop
    JAX_PLATFORMS=cpu python -m tpu_hc_bench.analysis --all --changed-only

    # show what accepting the current tree WOULD change (exit 1 if
    # anything), then actually rewrite it (atomic tmp->fsync->rename)
    JAX_PLATFORMS=cpu python -m tpu_hc_bench.analysis baseline
    JAX_PLATFORMS=cpu python -m tpu_hc_bench.analysis baseline --update

The collective count lowers the member's real world=2 train step on a
2-virtual-device CPU mesh (identical program to a two-process run; see
``hlo.lower_world_step_hlo``), so ``--collectives`` runs want
``JAX_PLATFORMS=cpu`` and take compile time; ``--no-collectives`` skips
them for lint-only runs.
"""

from __future__ import annotations

import argparse
import os
import sys


def _configure_cpu(world: int) -> None:
    # must precede any jax device use; the compat shim reroutes the
    # option to XLA_FLAGS on old stacks
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", world)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tpu_hc_bench.analysis",
        description="static analysis + lint gate over the model zoo")
    ap.add_argument("command", nargs="?", choices=["baseline"],
                    help="subcommand: `baseline` diffs this run's "
                         "findings against the committed baseline "
                         "(exit 1 on any change); `baseline --update` "
                         "rewrites it atomically")
    ap.add_argument("--update", action="store_true",
                    help="(baseline) actually rewrite the baseline "
                         "file instead of dry-running the diff")
    ap.add_argument("--changed-only", action="store_true",
                    help="restrict per-file passes to python sources "
                         "changed vs HEAD (plus untracked); repo-scope "
                         "passes still see the whole tree")
    ap.add_argument("--model", action="append", default=[],
                    help="zoo member to analyze (repeatable)")
    ap.add_argument("--all", action="store_true",
                    help="analyze every zoo member + repo sources")
    ap.add_argument("--batch", type=int, default=2,
                    help="per-device batch for the lowered step "
                         "(collective counts are batch-invariant)")
    ap.add_argument("--world", type=int, default=2,
                    help="virtual device count for the lowered step")
    ap.add_argument("--collectives", dest="collectives",
                    action="store_true", default=None,
                    help="count collectives in the compiled world=N HLO "
                         "(default: on for --model, off for --all)")
    ap.add_argument("--no-collectives", dest="collectives",
                    action="store_false")
    ap.add_argument("--json", metavar="PATH",
                    help="write the full JSON report here ('-' = stdout)")
    ap.add_argument("--baseline", metavar="PATH",
                    help="baseline findings file (default: checked-in)")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline from this run's findings")
    args = ap.parse_args(argv)

    from tpu_hc_bench.models import list_models

    models = list(args.model)
    if args.all:
        models = list_models()
    if not models and not args.all and args.command != "baseline":
        ap.error("pass --model NAME (repeatable), --all, or the "
                 "`baseline` subcommand")
    if args.update and args.command != "baseline":
        ap.error("--update belongs to the `baseline` subcommand")
    count_collectives = args.collectives
    if count_collectives is None:
        count_collectives = bool(args.model) and not args.all

    if count_collectives:
        _configure_cpu(args.world)

    import collections
    import time

    from tpu_hc_bench.analysis import hlo, lints, registry, report

    t0 = time.monotonic()
    files = None
    if args.changed_only:
        root = __import__("pathlib").Path(__file__).resolve().parents[2]
        files = registry.changed_python_files(root)
        if files is None:
            print("--changed-only: git unavailable, falling back to "
                  "the full tree", file=sys.stderr)
        else:
            print(f"--changed-only: {len(files)} changed python "
                  f"source(s)", file=sys.stderr)

    findings = []
    collectives: dict[str, dict[str, int]] = {}
    suppressed: collections.Counter = collections.Counter()
    findings.extend(lints.lint_repo_sources(files=files,
                                            counters=suppressed))
    for name in models:
        print(f"-- {name}", file=sys.stderr)
        findings.extend(lints.lint_model(name))
        if count_collectives:
            text = hlo.lower_world_step_hlo(name, batch=args.batch,
                                            world=args.world)
            collectives[name] = hlo.collective_counts(text)
    wall_s = time.monotonic() - t0

    rep = report.Report(findings=findings, collectives=collectives,
                        suppressed=dict(suppressed), wall_s=wall_s)
    if args.json == "-":
        sys.stdout.write(rep.to_json())
    elif args.json:
        with open(args.json, "w") as f:
            f.write(rep.to_json())

    # human summary: stderr when stdout is the JSON stream
    out = sys.stderr if args.json == "-" else sys.stdout
    for name, counts in sorted(collectives.items()):
        total = sum(counts.values())
        print(f"{name} world={args.world} optimized-HLO collectives "
              f"(definition sites, async pairs folded): {total}  {counts}",
              file=out)

    if args.command == "baseline":
        path = args.baseline or report.BASELINE_PATH
        # a partial (--model) run only ADDS keys; erasing other models'
        # accepted findings requires the full --all picture
        merge = set() if args.all else report.load_baseline(path)
        gating = {f.key for f in findings
                  if f.severity in ("error", "warning")} | merge
        before = report.load_baseline(path)
        added, removed = sorted(gating - before), sorted(before - gating)
        for k in added:
            print(f"+ {k}", file=out)
        for k in removed:
            print(f"- {k}", file=out)
        if not args.update:
            if added or removed:
                print(f"baseline DIFF: +{len(added)} -{len(removed)} "
                      f"key(s); rerun with `baseline --update` to "
                      f"accept", file=out)
                return 1
            print(f"baseline up to date: {path} "
                  f"({len(before)} accepted keys)", file=out)
            return 0
        gating_findings = [f for f in findings
                           if f.severity in ("error", "warning")]
        report.save_baseline(gating_findings, path, merge=merge)
        print(f"baseline updated: {path} (+{len(added)} "
              f"-{len(removed)}, {len(gating)} accepted keys)", file=out)
        return 0

    if args.update_baseline:
        path = args.baseline or report.BASELINE_PATH
        merge = set() if args.all else report.load_baseline(path)
        added, removed = report.save_baseline(findings, path, merge=merge)
        for k in added:
            print(f"+ {k}", file=out)
        for k in removed:
            print(f"- {k}", file=out)
        print(f"baseline updated: {path} "
              f"({len({f.key for f in findings} | merge)} accepted keys)",
              file=out)
        return 0

    baseline = report.load_baseline(args.baseline or report.BASELINE_PATH)
    regressions = report.compare_to_baseline(findings, baseline)
    for f in regressions:
        print(f.render(), file=sys.stderr)
    if regressions:
        print(f"{len(regressions)} finding(s) not in baseline "
              f"(accept with `baseline --update` or suppress with "
              f"`# tpu-hc: disable=<lint>`)", file=sys.stderr)
        return 1
    n_info = sum(1 for f in findings if f.severity == "info")
    n_sup = sum(suppressed.values())
    print(f"analysis clean: {len(findings)} finding(s), all accepted "
          f"({n_info} info, {n_sup} suppressed) in {wall_s:.1f}s",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    rc = main()
    # hard-exit: 0.4.x jaxlib can segfault in interpreter teardown after
    # a lowering (model-dependent; `trivial` reproduces it), which would
    # overwrite the gate's verdict with 139 — flush and skip teardown so
    # the exit code is always the comparison result
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(rc)
