"""Stream-schema contract checker: obs readers vs metrics writers.

The metrics stream is a JSONL contract with no schema: writers emit
``MetricsWriter.event(kind, **fields)`` records (plus kind-tagged dict
literals for heartbeats/spans), and the obs folds — summarize, diff,
watch, regress, timeline, fleet — consume keys by string literal.
Nothing ties the two sides together, so the failure mode is *silent*:
a reader key that no writer emits folds to zero (PR 10 fixed exactly
this by hand for ``mem_peak_bytes``), and a writer field no reader
consumes is dead weight nobody notices.  This pass extracts both sides
statically and reports the asymmetric difference:

- **stream-contract-orphan-read** (warning, gates): a key consumed by
  one of the reader folds that NO code in the tree materializes — not
  as a dict-literal key, a ``rec["key"] = ...`` store, an
  ``event(...)`` kwarg, or a ``dict(key=...)`` kwarg.  The write
  universe is deliberately BROAD (any materialization anywhere counts)
  so a hit means "this spelling exists nowhere": a typo or a reader
  that drifted from its writer.
- **stream-contract-orphan-write** (info, never gates): a field
  emitted at a stream writer site — ``event()`` kwargs and dict
  literals carrying a literal ``"kind"`` entry, the ISSUE's
  emit-anchored definition — that no obs module reads.  Info because
  write-side slack is intentional (records carry forensics fields for
  humans); the report keeps it visible without gating.

Reads are extracted from literal ``.get("k")`` / ``rec["k"]`` sites,
``_of_kind``/``_last`` kind arguments, ``rec.get("kind") == ...``
comparisons, and module-level key-path tables (the requests
``COMPONENTS`` pairs, the regress ``CHECKS``/``FINGERPRINT_KEYS``
paths) — table-driven reads are real reads even though no string
literal appears at the ``.get`` site.

Known intentional seams live in ``contract_allowlist.json`` next to
this module, each with a reason.  Allowlisted orphans are still
REPORTED (info) so the seam stays visible — the round-20
zero-component normalizer (``obs/requests.py`` reads the component
keys through the ``COMPONENTS`` table and normalizes absent ones to
0.0 by design) is the canonical entry.  The allowlist is the contract
baseline: tightening the contract means deleting an entry and fixing
the orphan, not editing findings JSON by hand.
"""

from __future__ import annotations

import ast
import json
import re
from pathlib import Path

from tpu_hc_bench.analysis.registry import register_pass
from tpu_hc_bench.analysis.report import Finding

__all__ = [
    "ORPHAN_READ", "ORPHAN_WRITE", "READER_MODULES",
    "extract_reads", "extract_writes", "load_allowlist",
    "check_stream_contracts", "ALLOWLIST_PATH",
]

ORPHAN_READ = "stream-contract-orphan-read"
ORPHAN_WRITE = "stream-contract-orphan-write"

ALLOWLIST_PATH = Path(__file__).parent / "contract_allowlist.json"

#: the seven obs reader folds whose consumed keys define the read side
#: of the contract (narrow on purpose: these are the modules that fold
#: the stream back into human-facing reports, where a missing key
#: renders as a silent zero)
READER_MODULES = (
    "obs/metrics.py",       # summarize_run / diff_runs
    "obs/watch.py",
    "obs/regress.py",
    "obs/timeline.py",
    "obs/fleet.py",
    "obs/requests.py",
    "obs/kv.py",            # round 22: the KV-pool utilization ledger
)

#: helpers whose second positional argument is a record KIND
_KIND_SELECTORS = frozenset({"_of_kind", "of_kind", "_last"})

#: keys must look like snake_case record fields; uppercase (env vars),
#: dunder and one-letter strings are out of contract scope
_KEY_RE = re.compile(r"^[a-z][a-z0-9_]{1,63}$")


def _const_str(node: ast.AST) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _record(keys: dict[str, str], key: str | None, where: str) -> None:
    if key is not None and _KEY_RE.match(key):
        keys.setdefault(key, where)


def _loc(rel: str, node: ast.AST) -> str:
    return f"{rel}:{getattr(node, 'lineno', 0)}"


# ---------------------------------------------------------------------
# read side


def _table_strings(value: ast.AST) -> list[str]:
    """String constants inside a module-level key-path table: a
    tuple/list of rows where each row is (or contains) tuples of
    string constants.  Captures the requests ``COMPONENTS`` pairs and
    the regress ``CHECKS``/``FINGERPRINT_KEYS`` record paths."""
    if not isinstance(value, (ast.Tuple, ast.List)):
        return []
    out = []
    for row in value.elts:
        if not isinstance(row, (ast.Tuple, ast.List)):
            return []    # not a table of rows
        inner = [n for n in ast.walk(row)
                 if isinstance(n, ast.Tuple) and n is not row]
        # rows with inner key-path tuples (the regress CHECKS shape)
        # contribute only the path keys, not the direction/label
        # strings riding alongside them
        pools = inner or [row]
        for pool in pools:
            for elt in getattr(pool, "elts", []):
                s = _const_str(elt)
                if s is not None:
                    out.append(s)
    return out


def extract_reads(root: Path,
                  modules=READER_MODULES) -> tuple[dict, dict]:
    """(field_keys, kind_keys) consumed by the reader folds — each a
    ``{key: first-site}`` dict."""
    fields: dict[str, str] = {}
    kinds: dict[str, str] = {}
    for rel in modules:
        path = root / "tpu_hc_bench" / rel
        if not path.is_file():
            continue
        tree = ast.parse(path.read_text(), filename=str(path))
        for node in ast.walk(tree):
            # rec.get("k") / rec.get("kind") == "x" comparisons
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "get" and node.args:
                _record(fields, _const_str(node.args[0]), _loc(rel, node))
            # rec["k"] loads
            elif isinstance(node, ast.Subscript) \
                    and isinstance(node.ctx, ast.Load):
                _record(fields, _const_str(node.slice), _loc(rel, node))
            # _of_kind(records, "step") / _last(records, "summary")
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Name) \
                    and node.func.id in _KIND_SELECTORS \
                    and len(node.args) >= 2:
                _record(kinds, _const_str(node.args[1]), _loc(rel, node))
            # rec.get("kind") == "x" / in ("x", "y")
            elif isinstance(node, ast.Compare):
                if not _reads_kind(node.left):
                    continue
                for comp in node.comparators:
                    for elt in ([comp] if not isinstance(
                            comp, (ast.Tuple, ast.List, ast.Set))
                            else comp.elts):
                        _record(kinds, _const_str(elt), _loc(rel, node))
        # module-level key tables (COMPONENTS, CHECKS, FINGERPRINT_KEYS,
        # RESILIENCE_KINDS-style string collections)
        for stmt in tree.body:
            if not isinstance(stmt, ast.Assign):
                continue
            name = stmt.targets[0].id \
                if isinstance(stmt.targets[0], ast.Name) else ""
            for s in _table_strings(stmt.value):
                _record(fields, s, f"{rel}:{stmt.lineno}")
            if "KIND" in name and isinstance(
                    stmt.value, (ast.Tuple, ast.List, ast.Set)):
                for elt in stmt.value.elts:
                    _record(kinds, _const_str(elt),
                            f"{rel}:{stmt.lineno}")
    return fields, kinds


def _reads_kind(node: ast.AST) -> bool:
    if isinstance(node, ast.Call) \
            and isinstance(node.func, ast.Attribute) \
            and node.func.attr == "get" and node.args:
        return _const_str(node.args[0]) == "kind"
    if isinstance(node, ast.Subscript):
        return _const_str(node.slice) == "kind"
    # `kind = rec.get("kind")` then `kind == "phase"` — the goodput
    # fold's shape; matching the variable NAME is lexical but cheap
    return isinstance(node, ast.Name) and node.id == "kind"


# ---------------------------------------------------------------------
# write side


def extract_writes(root: Path) -> tuple[dict, dict, dict]:
    """(broad_fields, stream_fields, stream_kinds) over the package.

    ``broad_fields``: ANY materialization of a snake_case string key —
    dict-literal keys, ``x["k"] = ...`` stores, keyword arguments of
    any call (records are routinely built through dataclass/event
    constructors), class-body attribute names (``dataclasses.asdict``
    turns field names into record keys), and module-level all-string
    tuple/set registries (``PHASES``/``KNOWN_SPANS``-style name
    tables).  The universe the orphan-READ check tests against:
    absence here means the spelling exists nowhere in the tree.

    ``stream_fields``/``stream_kinds``: the emit-anchored subset —
    ``event(kind, **fields)``/``heartbeat()`` call sites and dict
    literals carrying a literal ``"kind"`` entry — that the
    orphan-WRITE check audits.
    """
    broad: dict[str, str] = {}
    stream: dict[str, str] = {}
    kinds: dict[str, str] = {}
    paths: list[Path] = []
    for sub in ("tpu_hc_bench", "scripts"):
        base = root / sub
        if base.is_dir():
            paths.extend(sorted(base.rglob("*.py")))
    for path in paths:
        rel = path.relative_to(root).as_posix()
        if "/analysis/" in f"/{rel}":
            continue                 # the checker itself is not a writer
        try:
            tree = ast.parse(path.read_text(), filename=str(path))
        except SyntaxError:
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.Dict):
                keys = [_const_str(k) for k in node.keys
                        if k is not None]
                tagged = "kind" in keys
                if tagged:
                    _record(kinds, _const_str(
                        node.values[keys.index("kind")]),
                        _loc(rel, node))
                for k in keys:
                    _record(broad, k, _loc(rel, node))
                    if tagged and k != "kind":
                        _record(stream, k, _loc(rel, node))
            elif isinstance(node, ast.Subscript) \
                    and isinstance(node.ctx, ast.Store):
                _record(broad, _const_str(node.slice), _loc(rel, node))
            elif isinstance(node, ast.ClassDef):
                for stmt in node.body:
                    tgt = stmt.target if isinstance(
                        stmt, ast.AnnAssign) else (
                        stmt.targets[0] if isinstance(stmt, ast.Assign)
                        and stmt.targets else None)
                    if isinstance(tgt, ast.Name):
                        _record(broad, tgt.id, _loc(rel, stmt))
            elif isinstance(node, ast.Call):
                callee = None
                if isinstance(node.func, ast.Attribute):
                    callee = node.func.attr
                elif isinstance(node.func, ast.Name):
                    callee = node.func.id
                if callee == "event" and node.args:
                    kv = _const_str(node.args[0])
                    _record(kinds, kv, _loc(rel, node))
                    _record(broad, kv, _loc(rel, node))
                for kw in node.keywords:
                    if kw.arg:
                        _record(broad, kw.arg, _loc(rel, node))
                        if callee in ("event", "heartbeat"):
                            _record(stream, kw.arg, _loc(rel, node))
        # module-level name registries: a flat tuple/list/set of string
        # constants IS the materialization site for its names
        for stmt in tree.body:
            if isinstance(stmt, ast.Assign) and isinstance(
                    stmt.value, (ast.Tuple, ast.List, ast.Set)):
                elts = stmt.value.elts
                if elts and all(_const_str(e) is not None for e in elts):
                    for e in elts:
                        _record(broad, _const_str(e), _loc(rel, stmt))
    return broad, stream, kinds


# ---------------------------------------------------------------------
# the check


def load_allowlist(path: Path | None = None) -> dict:
    """``{"reads": {key: reason}, "writes": {key: reason}}`` — the
    committed contract baseline of intentional seams."""
    p = ALLOWLIST_PATH if path is None else Path(path)
    if not p.is_file():
        return {"reads": {}, "writes": {}}
    data = json.loads(p.read_text())
    return {"reads": dict(data.get("reads", {})),
            "writes": dict(data.get("writes", {}))}


@register_pass(
    ORPHAN_READ, "warning", "repo",
    doc="an obs fold consumes a record key no code in the tree "
        "materializes — the reader renders silent zeros (the PR-10 "
        "mem_peak_bytes bug class)",
    example="obs/watch.py reads `.get(\"mem_peak_byte\")` but every "
            "writer spells it `mem_peak_bytes` — liveness rows show "
            "no memory forever")
@register_pass(
    ORPHAN_WRITE, "info", "repo",
    doc="a field emitted at a stream writer site (event kwargs, "
        "kind-tagged dict literals) that no obs module reads — dead "
        "weight in every record",
    example="`writer.event(\"step\", grad_norm_sq=...)` emitted every "
            "step, consumed by no fold")
def check_stream_contracts(root: str | Path | None = None,
                           allowlist_path: Path | None = None
                           ) -> list[Finding]:
    """Run both contract checks over the repo; returns findings."""
    if root is None:
        root = Path(__file__).resolve().parents[2]
    root = Path(root)
    reads, kind_reads = extract_reads(root)
    broad, stream, kind_writes = extract_writes(root)
    allow = load_allowlist(allowlist_path)
    findings: list[Finding] = []

    # read side: consumed but materialized nowhere
    for key in sorted(reads):
        if key in broad:
            continue
        site = reads[key]
        module = site.rsplit(":", 1)[0]
        if key in allow["reads"]:
            findings.append(Finding(
                ORPHAN_READ, "info", "repo",
                f"{module}::{key}",
                f"allowlisted contract seam `{key}` (read at {site}, "
                f"no literal writer): {allow['reads'][key]}"))
            continue
        findings.append(Finding(
            ORPHAN_READ, "warning", "repo", f"{module}::{key}",
            f"reader consumes `{key}` (at {site}) but no writer, dict "
            f"literal, store, or kwarg in the tree materializes that "
            f"key — the fold renders a silent zero/None; fix the "
            f"spelling or allowlist the seam in "
            f"contract_allowlist.json with a reason"))
    for kind in sorted(kind_reads):
        if kind in kind_writes or kind in allow["reads"]:
            continue
        site = kind_reads[kind]
        module = site.rsplit(":", 1)[0]
        findings.append(Finding(
            ORPHAN_READ, "warning", "repo", f"{module}::kind={kind}",
            f"reader selects records of kind `{kind}` (at {site}) but "
            f"no writer emits that kind — the selection is always "
            f"empty"))

    # write side: emitted at stream sites but read by no stream fold —
    # the read universe here is every obs module plus the serve SLO
    # fold (the one stream consumer living outside obs/)
    consumer_modules = tuple(
        p.relative_to(root / "tpu_hc_bench").as_posix()
        for p in sorted((root / "tpu_hc_bench" / "obs").glob("*.py"))
    ) + ("serve/slo.py",)
    obs_reads, obs_kind_reads = extract_reads(
        root, modules=consumer_modules)
    dead = [k for k in sorted(stream)
            if k not in obs_reads and k not in allow["writes"]]
    if dead:
        shown = ", ".join(dead[:12]) + (
            f", … +{len(dead) - 12} more" if len(dead) > 12 else "")
        findings.append(Finding(
            ORPHAN_WRITE, "info", "repo", "stream-writers",
            f"{len(dead)} stream field(s) emitted but consumed by no "
            f"obs/slo fold: {shown} — forensics-only fields are fine; "
            f"prune or allowlist intentional ones"))
    dead_kinds = [k for k in sorted(kind_writes)
                  if k not in obs_kind_reads
                  and k not in allow["writes"]]
    if dead_kinds:
        findings.append(Finding(
            ORPHAN_WRITE, "info", "repo", "stream-writers::kinds",
            f"{len(dead_kinds)} record kind(s) emitted but selected by "
            f"no obs reader: {', '.join(dead_kinds[:12])}"))
    return findings
