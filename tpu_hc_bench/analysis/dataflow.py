"""Distributed-correctness dataflow passes: rank taint -> collectives.

The costliest multi-node failure mode this harness has is the *silent
hang*: every rank must issue the same collectives in the same order, and
a collective guarded by rank-dependent control flow (or ordered by a
rank-divergent dict walk) deadlocks the fabric with no error on any
rank — the runtime watchdog (``resilience/watchdog.py``) catches it only
AFTER burning a pod-slice.  These passes catch the two shapes at lint
time:

- **rank-divergent-collective** (error): an intraprocedural AST taint
  engine.  Values derived from ``jax.process_index()`` (any dotted
  spelling), from parameters/attributes named ``process_index`` /
  ``rank`` / ``host_id``, or transitively through assignments and
  comparisons, *taint* the expressions they flow into.  A collective or
  cross-process sync call (``psum``/``all_gather``/``reduce_scatter``
  family, ``all_processes_any``, ``process_allgather``,
  ``broadcast_one_to_all``, barriers) is flagged when it is reachable
  under a tainted branch **without a matching collective on the other
  side**: inside a tainted ``if`` whose other arm does not issue the
  same collectives, inside a tainted ``while`` (divergent trip counts),
  or after a tainted early-exit (``if rank != 0: return`` followed by a
  collective every rank must reach).  Rank-gated *host work* (worker-0
  logging, checkpoint commits) is the normal idiom and stays silent —
  only collectives under the divergence flag.

- **nondeterministic-collective-order** (error): a loop that issues
  collectives and draws its iteration order from a dict or set
  (``.items()``/``.keys()``/``.values()``, ``set(...)``, set
  literals/comprehensions).  Dict order is insertion order — per
  process — and set order is hash order; if any rank built the mapping
  differently (a racing arrival, a per-host file listing), the ranks
  issue the same collectives in different orders and the fabric
  deadlocks.  Wrapping the iterable in ``sorted(...)`` canonicalizes
  the order and passes.

**Scope — what the taint model provably cannot see** (keep claims
honest; ARCHITECTURE repeats this): the engine is *intraprocedural* and
*lexical*.  It does not follow taint through function calls (a helper
returning ``process_index() == 0`` launders the taint), through
closures, containers, or object attributes assigned elsewhere; it
cannot know a variable holds a dict when the iteration spells a bare
name; and it cannot prove two ranks' dicts actually diverge — it flags
the *shape* that makes divergence possible.  A clean report is
necessary, not sufficient.  Suppress deliberate sites with
``# tpu-hc: disable=<lint-name>`` (counted in the findings JSON) or
accept them into the baseline.
"""

from __future__ import annotations

import ast

from tpu_hc_bench.analysis.registry import register_pass

__all__ = [
    "RANK_DIVERGENT", "NONDET_ORDER", "COLLECTIVE_CALLEES",
    "TAINT_CALL_NAMES", "TAINT_NAMES", "FunctionTaint",
    "check_rank_divergence", "check_collective_order",
]

RANK_DIVERGENT = "rank-divergent-collective"
NONDET_ORDER = "nondeterministic-collective-order"

#: call basenames that are collectives / cross-process sync points —
#: every rank must execute these the same number of times in the same
#: order (the ``parallel/collectives.py`` wrappers, the raw lax/
#: multihost primitives they wrap, and the repo's host-level sync)
COLLECTIVE_CALLEES = frozenset({
    # parallel/collectives.py wrappers + bucketed trees
    "psum", "pmean", "all_gather", "reduce_scatter", "ppermute_ring",
    "fused_psum_tree", "allreduce_gradients", "reduce_scatter_tree",
    "all_gather_tree",
    # raw lax primitives
    "psum_scatter", "ppermute", "all_to_all", "pmax", "pmin",
    # host-level cross-process sync (utils.sync, multihost_utils)
    "all_processes_any", "process_allgather", "broadcast_one_to_all",
    "sync_global_devices", "barrier",
})

#: calls whose RESULT is rank-dependent (any dotted spelling:
#: ``jax.process_index()``, ``distributed.process_index()``)
TAINT_CALL_NAMES = frozenset({"process_index"})

#: parameter / attribute / variable names that carry per-host identity
TAINT_NAMES = frozenset({
    "process_index", "process_idx", "rank", "host_id", "host_index",
})

#: fixpoint bound for assignment propagation (chains longer than this
#: do not occur in honest code; the bound keeps the pass O(n))
_MAX_ROUNDS = 10


def _dotted(node: ast.AST) -> str:
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


def _own_walk(node: ast.AST):
    """Walk ``node``'s subtree WITHOUT descending into nested function/
    class scopes (their bodies run on call, not here)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop(0)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda, ast.ClassDef)):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


def _collective_calls(stmts) -> list[ast.Call]:
    """Collective call sites among ``stmts``' own nodes (document
    order), nested scopes excluded."""
    out = []
    for stmt in stmts:
        nodes = [stmt] if isinstance(stmt, ast.Call) else []
        nodes += list(_own_walk(stmt))
        for n in nodes:
            if isinstance(n, ast.Call):
                name = _dotted(n.func)
                if name.rsplit(".", 1)[-1] in COLLECTIVE_CALLEES:
                    out.append(n)
    return out


def _names_of(calls: list[ast.Call]) -> list[str]:
    return sorted(_dotted(c.func).rsplit(".", 1)[-1] for c in calls)


class FunctionTaint:
    """Intraprocedural taint for ONE function scope (or the module
    top level): seed from rank-identity sources, propagate through the
    scope's own assignments to a fixpoint."""

    def __init__(self, scope: ast.AST):
        self.scope = scope
        self.tainted: set[str] = set()
        self._seed_params()
        self._propagate()

    def _seed_params(self) -> None:
        args = getattr(self.scope, "args", None)
        if args is None:
            return
        for a in (list(args.posonlyargs) + list(args.args)
                  + list(args.kwonlyargs)):
            if a.arg in TAINT_NAMES:
                self.tainted.add(a.arg)

    def expr_tainted(self, node: ast.AST) -> bool:
        """An expression is tainted when any part of it reads a rank
        source or a tainted local."""
        for n in ast.walk(node):
            if isinstance(n, ast.Name) and n.id in self.tainted:
                return True
            if isinstance(n, ast.Call):
                base = _dotted(n.func).rsplit(".", 1)[-1]
                if base in TAINT_CALL_NAMES:
                    return True
            if isinstance(n, ast.Attribute) and n.attr in TAINT_NAMES:
                return True
        return False

    @staticmethod
    def _target_names(target: ast.AST) -> set[str]:
        return {n.id for n in ast.walk(target) if isinstance(n, ast.Name)}

    def _propagate(self) -> None:
        stmts = list(_own_walk(self.scope))
        for _ in range(_MAX_ROUNDS):
            before = len(self.tainted)
            for n in stmts:
                if isinstance(n, ast.Assign) and self.expr_tainted(n.value):
                    for t in n.targets:
                        self.tainted |= self._target_names(t)
                elif isinstance(n, (ast.AugAssign, ast.AnnAssign)) \
                        and n.value is not None \
                        and self.expr_tainted(n.value):
                    self.tainted |= self._target_names(n.target)
                elif isinstance(n, ast.NamedExpr) \
                        and self.expr_tainted(n.value):
                    self.tainted |= self._target_names(n.target)
                elif isinstance(n, ast.For) \
                        and self.expr_tainted(n.iter):
                    self.tainted |= self._target_names(n.target)
            if len(self.tainted) == before:
                return


def _scopes(tree: ast.Module):
    """Every analysis scope: the module body + each function def."""
    yield tree
    for n in ast.walk(tree):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield n


def _exits_control_flow(stmts) -> bool:
    """A branch arm diverges ranks' CONTROL FLOW when it returns/breaks/
    continues/raises — ranks taking it never reach the code after the
    branch."""
    for stmt in stmts:
        for n in [stmt] + list(_own_walk(stmt)):
            if isinstance(n, (ast.Return, ast.Break, ast.Continue,
                              ast.Raise)):
                return True
    return False


def _subtree_end(node: ast.AST) -> int:
    return max((getattr(n, "lineno", 0) for n in ast.walk(node)),
               default=getattr(node, "lineno", 0))


# ---------------------------------------------------------------------
# pass: rank-divergent collectives


@register_pass(
    RANK_DIVERGENT, "error", "file",
    doc="collective/cross-process sync reachable under rank-dependent "
        "control flow without a matching partner on the other arm — "
        "the silent multi-host deadlock",
    example="`all_processes_any(...)` at driver.py:412 executes only "
            "where `jax.process_index() == 0` holds; other ranks never "
            "enter the collective and the fabric hangs")
def check_rank_divergence(linter) -> None:
    """Per scope: seed+propagate taint, then audit every tainted branch
    for unbalanced collectives.  ``linter`` is the ``_FileLinter``
    running this file (duck-typed: ``.tree``, ``._emit``)."""
    for scope in _scopes(linter.tree):
        taint = FunctionTaint(scope)
        stmts = [n for n in _own_walk(scope) if isinstance(n, ast.stmt)]
        for node in stmts:
            if isinstance(node, ast.If) and taint.expr_tainted(node.test):
                _audit_tainted_if(linter, taint, node, stmts)
            elif isinstance(node, ast.While) \
                    and taint.expr_tainted(node.test):
                for call in _collective_calls(node.body):
                    _emit_divergent(
                        linter, call,
                        f"inside a while-loop whose condition "
                        f"(line {node.lineno}) is rank-dependent — "
                        f"ranks run different trip counts and issue "
                        f"different collective sequences")


def _audit_tainted_if(linter, taint: FunctionTaint, node: ast.If,
                      scope_stmts: list[ast.stmt]) -> None:
    body_calls = _collective_calls(node.body)
    else_calls = _collective_calls(node.orelse)
    body_names = _names_of(body_calls)
    else_names = _names_of(else_calls)
    if body_names != else_names:
        # flag the arm(s) whose collectives lack a partner opposite
        surplus = _unmatched(body_calls, else_names) \
            + _unmatched(else_calls, body_names)
        for call in surplus:
            _emit_divergent(
                linter, call,
                f"under a rank-dependent branch (line {node.lineno}) "
                f"with no matching collective on the other arm — only "
                f"some ranks enter it")
    # early-exit divergence: one arm leaves the scope (return/raise/
    # break/continue), so ranks taking it never reach collectives
    # issued after the branch
    body_exits = _exits_control_flow(node.body)
    else_exits = bool(node.orelse) and _exits_control_flow(node.orelse)
    if not (body_exits or else_exits):
        return
    if body_calls or else_calls:
        return      # already audited above; the arms' own collectives
                    # carry the verdict
    end = _subtree_end(node)
    after = [s for s in scope_stmts if s.lineno > end]
    for call in _collective_calls(after):
        _emit_divergent(
            linter, call,
            f"after a rank-dependent early exit (line {node.lineno}): "
            f"ranks taking the exit never reach this collective while "
            f"the rest block in it")


def _unmatched(calls: list[ast.Call], other_names: list[str]
               ) -> list[ast.Call]:
    """Calls whose basename has no remaining partner in the other arm's
    (multiset) name list."""
    remaining = list(other_names)
    out = []
    for c in calls:
        base = _dotted(c.func).rsplit(".", 1)[-1]
        if base in remaining:
            remaining.remove(base)
        else:
            out.append(c)
    return out


def _emit_divergent(linter, call: ast.Call, why: str) -> None:
    name = _dotted(call.func) or "<collective>"
    linter._emit(
        RANK_DIVERGENT, call,
        f"collective `{name}(...)` {why}; every rank must issue the "
        f"same collectives in the same order or the fabric deadlocks "
        f"silently — hoist the collective out of the branch, or make "
        f"both arms issue it")


# ---------------------------------------------------------------------
# pass: nondeterministic collective order


def _nondet_iter(iter_expr: ast.AST) -> str | None:
    """Why this loop's iteration order can diverge across ranks, or
    None when it cannot (lexically).  ``sorted(...)`` at the top
    canonicalizes everything under it."""
    if isinstance(iter_expr, ast.Call) \
            and _dotted(iter_expr.func).rsplit(".", 1)[-1] == "sorted":
        return None
    for n in ast.walk(iter_expr):
        if isinstance(n, ast.Call):
            base = _dotted(n.func).rsplit(".", 1)[-1]
            if isinstance(n.func, ast.Attribute) \
                    and n.func.attr in ("items", "keys", "values"):
                return (f"`.{n.func.attr}()` iterates in dict insertion "
                        f"order, which diverges when ranks built the "
                        f"dict differently")
            if base in ("set", "frozenset"):
                return "`set(...)` iterates in hash order"
        if isinstance(n, (ast.Set, ast.SetComp)):
            return "a set literal/comprehension iterates in hash order"
    return None


@register_pass(
    NONDET_ORDER, "error", "file",
    doc="a collective-issuing loop ordered by dict/set iteration — "
        "insertion/hash-order divergence across ranks reorders the "
        "collective sequence and deadlocks the fabric",
    example="`for name, g in grads.items(): psum(g)` at step.py:88 — "
            "two ranks that populated `grads` differently psum "
            "different tensors against each other")
def check_collective_order(linter) -> None:
    for node in ast.walk(linter.tree):
        if not isinstance(node, (ast.For, ast.AsyncFor)):
            continue
        why = _nondet_iter(node.iter)
        if why is None:
            continue
        calls = _collective_calls(node.body)
        if not calls:
            continue
        first = _dotted(calls[0].func) or "<collective>"
        linter._emit(
            NONDET_ORDER, node,
            f"loop order feeds collective `{first}(...)` but {why}; "
            f"ranks disagreeing on the order issue the same "
            f"collectives in different sequences — a silent deadlock; "
            f"iterate `sorted(...)` (or a list with one canonical "
            f"order) instead")
