"""Definition-site HLO text parser.

Why a parser instead of a regex over the whole module text (the round-5
approach of ``scripts/exp_hlo_collectives_r05.py``): in HLO text every
*consumer* of an instruction repeats its name —

    %all-reduce.1 = f32[2,2] all-reduce(%dot.1), ...
    ROOT %fusion = f32[2,2] fusion(f32[2,2] %all-reduce.1), ...

so a bare substring match counts the all-reduce twice (once at its
definition, once per operand reference), and async pairs
(``all-reduce-start`` + ``all-reduce-done``) count a third time.  This
parser recognizes only *definition sites* — lines of the shape
``[ROOT] %name = <shape> opcode(operands), attrs`` — so each executed op
is seen exactly once, and ``-done``/``-update`` halves of async pairs
are folded into their ``-start``.

The parse is deliberately line-based and tolerant: XLA's text format is
stable at the granularity we consume (one instruction per line inside a
computation body; computations delimited by ``name (params) -> type {``
and ``}``), and anything unrecognized is simply skipped rather than an
error, so new attribute syntax can't break the counters.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

__all__ = [
    "COLLECTIVE_OPCODES",
    "HloInstruction",
    "HloComputation",
    "HloModule",
    "parse_hlo",
    "collective_counts",
    "fusion_ops",
    "op_attribution",
]

# Cross-device collective opcodes (sync spellings; async spellings are
# these + "-start"/"-done").  collective-permute appears for ppermute
# pipelines, all-to-all for expert parallelism.
COLLECTIVE_OPCODES = frozenset({
    "all-reduce", "all-gather", "reduce-scatter", "all-to-all",
    "collective-permute", "collective-broadcast", "ragged-all-to-all",
})

# Async-pair suffix folding: the "-start" half carries the op, the
# "-done" (and copy/collective "-update") half is the wait.
_START_SUFFIX = "-start"
_DONE_SUFFIXES = ("-done", "-update")

# `[ROOT] %name = <rest>`; names may be %-less in some dump flavors.
_DEF_RE = re.compile(
    r"^\s*(?P<root>ROOT\s+)?%?(?P<name>[\w.\-]+)\s*=\s*(?P<rest>\S.*)$")
# first identifier immediately followed by "(" in <rest> is the opcode:
# shape tokens (f32[2,8]{1,0}, (f32[2], u32[]) tuples, pred[], token[])
# are never an identifier directly followed by "(".
_OPCODE_RE = re.compile(r"\b(?P<op>[a-zA-Z][\w\-]*)\(")
_METADATA_OP_NAME_RE = re.compile(r'metadata=\{[^}]*?op_name="(?P<n>[^"]*)"')
_SOURCE_RE = re.compile(
    r'source_file="(?P<f>[^"]*)"(?:\s+source_line=(?P<l>\d+))?')
# called-computation attributes: fusion calls=, reduce to_apply=, while
# body=/condition=, conditional branch_computations={...}
_CALLS_RE = re.compile(
    r"\b(?:calls|to_apply|body|condition)=%?(?P<c>[\w.\-]+)")
_BRANCHES_RE = re.compile(r"branch_computations=\{(?P<cs>[^}]*)\}")
# `[ENTRY] %name (params...) -> type {`
_COMP_RE = re.compile(
    r"^(?P<entry>ENTRY\s+)?%?(?P<name>[\w.\-]+)\s*\(.*\)\s*->\s*.*\{\s*$")


@dataclass
class HloInstruction:
    name: str                       # without the leading %
    opcode: str                     # e.g. "all-reduce-start", "fusion"
    is_root: bool = False
    op_name: str = ""               # metadata={op_name="..."} (jax path)
    source: str = ""                # metadata source_file:source_line
    called: tuple[str, ...] = ()    # computations this op calls
    text: str = ""                  # the raw definition line

    @property
    def base_opcode(self) -> str:
        """Opcode with the async ``-start`` suffix stripped."""
        if self.opcode.endswith(_START_SUFFIX):
            return self.opcode[:-len(_START_SUFFIX)]
        return self.opcode

    @property
    def is_async_done(self) -> bool:
        return self.opcode.endswith(_DONE_SUFFIXES)


@dataclass
class HloComputation:
    name: str
    is_entry: bool = False
    instructions: list[HloInstruction] = field(default_factory=list)


@dataclass
class HloModule:
    name: str = ""
    computations: dict[str, HloComputation] = field(default_factory=dict)

    @property
    def entry(self) -> HloComputation:
        for c in self.computations.values():
            if c.is_entry:
                return c
        raise ValueError(f"module {self.name!r} has no ENTRY computation")

    def find(self, instr_name: str) -> HloInstruction | None:
        """Look up a definition by name across all computations."""
        want = instr_name.lstrip("%")
        for comp in self.computations.values():
            for ins in comp.instructions:
                if ins.name == want:
                    return ins
        return None


def _parse_instruction(line: str) -> HloInstruction | None:
    m = _DEF_RE.match(line)
    if not m:
        return None
    rest = m.group("rest")
    om = _OPCODE_RE.search(rest)
    if not om:
        return None
    meta = _METADATA_OP_NAME_RE.search(rest)
    src = _SOURCE_RE.search(rest)
    called = tuple(_CALLS_RE.findall(rest))
    bm = _BRANCHES_RE.search(rest)
    if bm:
        called += tuple(
            c.strip().lstrip("%") for c in bm.group("cs").split(",")
            if c.strip())
    return HloInstruction(
        name=m.group("name"),
        opcode=om.group("op"),
        is_root=bool(m.group("root")),
        op_name=meta.group("n") if meta else "",
        source=(f"{src.group('f')}:{src.group('l') or '?'}" if src else ""),
        called=called,
        text=line.strip(),
    )


def parse_hlo(text: str) -> HloModule:
    """Parse HLO text into computations of definition-site instructions."""
    module = HloModule()
    current: HloComputation | None = None
    for line in text.splitlines():
        stripped = line.strip()
        if not stripped:
            continue
        if stripped.startswith("HloModule"):
            parts = stripped.split(None, 2)
            module.name = parts[1].rstrip(",") if len(parts) > 1 else ""
            continue
        cm = _COMP_RE.match(stripped)
        if cm and "=" not in stripped.split("(", 1)[0]:
            current = HloComputation(
                name=cm.group("name"), is_entry=bool(cm.group("entry")))
            module.computations[current.name] = current
            continue
        if stripped == "}":
            current = None
            continue
        if current is None:
            continue
        ins = _parse_instruction(stripped)
        if ins is not None:
            current.instructions.append(ins)
    return module


def _iter_instructions(module: HloModule):
    for comp in module.computations.values():
        yield from comp.instructions


def collective_counts(module: HloModule | str,
                      fold_async: bool = True) -> dict[str, int]:
    """Count collective-op *definitions* per base opcode.

    Operand references never count (only definitions are parsed); with
    ``fold_async`` (default) an ``all-reduce-start``/``all-reduce-done``
    pair counts as ONE ``all-reduce`` (the ``-done`` half is skipped and
    the ``-start`` spelling is normalized to the sync name).
    """
    if isinstance(module, str):
        module = parse_hlo(module)
    counts: dict[str, int] = {}
    for ins in _iter_instructions(module):
        if fold_async and ins.is_async_done:
            continue
        op = ins.base_opcode if fold_async else ins.opcode
        # membership is tested on the async-suffix-free family so the
        # unfolded spellings ("all-reduce-start"/"-done") still count
        family = op
        for suf in (_START_SUFFIX, *_DONE_SUFFIXES):
            if family.endswith(suf):
                family = family[:-len(suf)]
                break
        if family in COLLECTIVE_OPCODES:
            counts[op] = counts.get(op, 0) + 1
    return counts


def fusion_ops(module: HloModule,
               instr: HloInstruction | str) -> list[HloInstruction]:
    """The leaf ops a (fusion) instruction actually executes.

    For a ``fusion`` op, the instructions of its fused computation
    (recursively through nested calls); for anything else, the
    instruction itself.  This is what makes trace/HLO attribution honest:
    a device event named ``fusion.123`` says nothing, but its fused
    computation's ``dot``s and their ``metadata op_name`` paths say
    exactly which model layer the time belongs to.
    """
    if isinstance(instr, str):
        found = module.find(instr)
        if found is None:
            return []
        instr = found
    if not instr.called:
        return [instr]
    out: list[HloInstruction] = []
    seen: set[str] = set()

    def walk(comp_name: str):
        if comp_name in seen:
            return
        seen.add(comp_name)
        comp = module.computations.get(comp_name)
        if comp is None:
            return
        for ins in comp.instructions:
            out.append(ins)
            for c in ins.called:
                walk(c)

    for c in instr.called:
        walk(c)
    return out


def op_attribution(module: HloModule, opcodes: tuple[str, ...] = ("dot",),
                   entry_only: bool = True) -> dict[str, list[str]]:
    """Map each instruction -> ``metadata op_name`` paths of the
    matching leaf opcodes it executes (through fusions).

    E.g. ``op_attribution(m, ("dot",))["loop_fusion.12"]`` lists the jax
    op paths (``.../moe/expert_mm/dot_general``...) of every dot that
    fusion computes — the substring-free way to decide whether a traced
    fusion is expert matmul, attention, or router work.

    ``entry_only=False`` indexes every computation's instructions, not
    just the entry's: trace events name the ops executed inside while
    loops / conditionals (e.g. a ``lax.map``-chunked MoE dispatch), and
    those are defined in body computations the entry never lists.
    """
    instructions = (module.entry.instructions if entry_only
                    else list(_iter_instructions(module)))
    attribution: dict[str, list[str]] = {}
    for ins in instructions:
        leaves = fusion_ops(module, ins)
        names = [l.op_name for l in leaves
                 if l.base_opcode in opcodes and not l.is_async_done]
        if names:
            attribution[ins.name] = names
    return attribution


def lower_world_step_hlo(model_name: str, batch: int = 2,
                         world: int = 2, attention_impl: str = "dense",
                         moe_impl: str = "einsum", optimize: bool = True,
                         **config_overrides) -> str:
    """Optimized-HLO text of the zoo member's compiled world=N train step.

    ``optimize=False`` returns the pre-optimization (StableHLO) text of
    the lowered step instead — needed for program properties the CPU
    backend erases during optimization (e.g. the ``optimization_barrier``
    the ``--overlap_grad_comm=off`` arm pins across the gradient tree:
    the TPU pipeline schedules around it, the CPU pipeline deletes it),
    and cheaper when no compile is needed.

    A ``world``-virtual-device single-process data mesh compiles the
    identical program a ``world``-process run executes (same mesh shape,
    same partitioner input), so collective counts need no hardware — the
    round-5 insight of ``scripts/exp_hlo_collectives_r05.py``, now
    reusable for any member.  Must run under ``JAX_PLATFORMS=cpu`` with
    the device count set before backend init (the CLI does both).

    Extra ``config_overrides`` pass through to ``BenchmarkConfig``, so
    step variants are lowerable too (e.g. ``fusion_threshold_bytes=1``
    compiles the per-tensor-crossing step the fusion buckets replace).
    """
    import jax
    import jax.numpy as jnp

    from tpu_hc_bench import flags
    from tpu_hc_bench.data.synthetic import SyntheticImages, SyntheticTokens
    from tpu_hc_bench.models import create_model, get_model_spec
    from tpu_hc_bench.topology import build_mesh, compute_layout
    from tpu_hc_bench.train import step as step_mod

    cfg = flags.BenchmarkConfig(model=model_name, batch_size=batch,
                                attention_impl=attention_impl,
                                moe_impl=moe_impl,
                                **config_overrides).resolve()
    layout = compute_layout(num_hosts=1, workers_per_host=world,
                            chips_per_host=world)
    mesh = build_mesh(layout)
    spec = get_model_spec(model_name)
    kwargs = {}
    if spec.attention or spec.is_text:
        kwargs["attention_impl"] = attention_impl
    if spec.moe:
        kwargs["moe_impl"] = moe_impl
    model, spec = create_model(model_name, dtype=jnp.bfloat16, **kwargs)
    if spec.is_text:
        raw = SyntheticTokens(batch * world, spec.input_shape[0],
                              vocab_size=spec.vocab_size,
                              causal_lm=spec.causal_lm).batch()
    else:
        raw = SyntheticImages(batch * world, spec.input_shape,
                              num_classes=cfg.num_classes).batch()
    if cfg.variable_update == "zero1":
        # zero1 states carry stacked [world, k] optimizer leaves sharded
        # over the data axis — the layout the step's in_specs name
        state = step_mod.make_zero1_state(model, cfg, raw, world)
        state = step_mod.place_zero1_state(state, mesh)
    else:
        state = step_mod.make_train_state(model, cfg, raw)
        state = step_mod.replicate_state(state, mesh)
    dev_batch = step_mod.shard_batch(raw, mesh)
    step_fn = step_mod.build_train_step(mesh, cfg, spec)
    # the builder returns a wrapper around its jitted shard_map; jitting
    # the wrapper inlines it, giving a lowerable handle on the SAME program
    lowered = jax.jit(step_fn).lower(state, dev_batch, jax.random.PRNGKey(0))
    if not optimize:
        return lowered.as_text()
    return lowered.compile().as_text()
